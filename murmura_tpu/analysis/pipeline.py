"""Pipelined-rounds contracts (MUR1200-1203) — part of the default
package check (docs/PERFORMANCE.md "Pipelined rounds").

The pipeline stage (core/pipeline.py) threads a double buffer through
the compiled round program: round r's production (train + attack +
sentinels + codec + stale fold) writes the buffer that round r+1's
delayed aggregation consumes, while round r+1's training runs with no
data dependence on that aggregation.  Each link carries an invariant
that must stay machine-checked or the overlap story silently rots:

- **MUR1200 — pipeline-state registry bijection.**
  ``PIPELINE_STATE_KEYS`` must be registered in the MUR900 snapshot
  registry under its defining module, its keys distinct and
  ``pipe_``-prefixed, ``init_pipeline_state`` must emit exactly the
  ``pipeline_state_keys(stale)`` subset with the shapes the scan carry,
  gang vmap, mesh placement (node-leading ``pipe_adj``) and durability
  snapshot rely on, the buffer must start INVALID (``pipe_valid`` 0 —
  warm-up exactness), and with staleness armed ``pipe_bcast`` must be
  absent (the buffer-reuse bijection with the stale cache).
- **MUR1201 — recompile-free pipelining.**  The buffer is carried state;
  a pipelined round program compiles once and every buffer swap — churn
  varying the buffered adjacency round to round — is value-only
  (:class:`~murmura_tpu.analysis.sanitizers.CompileTracker`).  The probe
  also requires the pipeline to actually report a valid buffer after
  warm-up (``agg_pipe_valid``), so a silently-dead pipeline cannot pass
  vacuously.
- **MUR1202 — collective-inventory parity.**  The delayed aggregation
  runs the same rule kernels once per round on buffered values; the
  pipelined round program's traced collective inventory must equal the
  serialized program's, per rule x dense/sparse — overlapping the
  exchange must not add communication.
- **MUR1203 — delayed-step influence bounds + the lagging-verdict
  discipline.**  Run the taint interpreter (analysis/flow.py) over the
  composed produce -> buffer -> delayed-aggregate -> combine step:
  bounded rules (krum/median/trimmed/ubar) must keep their declared
  MUR800 per-coordinate influence cardinality when the aggregation
  consumes BUFFERED rows (a delayed row is still ONE neighbor), a
  sender scrubbed at production time must never enter the buffer, and a
  sender whose scrub verdict zeroed its buffered edges must not reach
  the delayed output through its cached payload — the scrub verdicts
  lag one round behind the aggregation, so containment must ride the
  buffer write, not the aggregation (the MUR1103 replay-hole
  discipline applied to the pipeline).

Like ``check_staleness``, MUR1201 compiles and runs tiny programs, so
the family is memoized per process and runs by default only for the
package check; tests gate representative cells per tier-1 run
(tests/test_pipeline.py) and negatives prove each probe can fire.
"""

from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from murmura_tpu.analysis.lint import Finding

# Registry of check families in this module: name -> callable, scanned by
# analysis/ir.py's check_coverage so an unwired family is a MUR205
# finding (the flow.py/durability.py/staleness.py twin pattern).
PIPELINE_CHECK_FAMILIES: Dict[str, Callable[[], List[Finding]]] = {}


def _family(fn):
    PIPELINE_CHECK_FAMILIES[fn.__name__] = fn
    return fn


_PKG = Path(__file__).resolve().parent.parent
_PIPE_PATH = str(_PKG / "core" / "pipeline.py")

# The trace-level collective vocabulary — IMPORTED from the MUR1002
# check so the parity checks cannot drift on what counts as
# communication (the staleness.py convention).
from murmura_tpu.analysis.adaptive import _COLLECTIVE_PRIMS  # noqa: E402

# The exchange layouts the pipeline grids sweep: the dense [N, N]
# adjacency and the sparse [k, N] edge-mask engine (the pipeline buffers
# whatever adjacency values the round consumed, so every per-round graph
# composes — dense and sparse cover both storage layouts of the buffer).
PIPELINE_MODES: Tuple[str, ...] = ("dense", "sparse")


def _rule_anchor(rule: str) -> Tuple[str, int]:
    from murmura_tpu.analysis.ir import _rule_anchor as anchor

    return anchor(rule)


# --------------------------------------------------------------------------
# MUR1200 — pipeline-state registry bijection
# --------------------------------------------------------------------------


@_family
def check_pipeline_state_registry() -> List[Finding]:
    """MUR1200: PIPELINE_STATE_KEYS <-> init_pipeline_state <-> MUR900
    snapshot registry, all bijective and shape-sound, including the
    staleness buffer-reuse subset."""
    findings: List[Finding] = []
    try:
        from murmura_tpu.core.pipeline import (
            ADJ_KEY,
            BCAST_KEY,
            PIPELINE_STATE_KEYS,
            VALID_KEY,
            init_pipeline_state,
            pipeline_state_keys,
        )
        from murmura_tpu.durability.snapshot import (
            RESERVED_AGG_STATE_KEY_GROUPS,
        )
    except Exception as e:  # noqa: BLE001 — the import failure IS the finding
        return [Finding(
            "MUR1200", _PIPE_PATH, 1,
            f"the pipeline module failed to import "
            f"({type(e).__name__}: {e}) — the MUR1200 bijection cannot "
            "be checked",
        )]

    keys = tuple(PIPELINE_STATE_KEYS)
    if len(set(keys)) != len(keys) or any(
        not k.startswith("pipe_") for k in keys
    ):
        findings.append(Finding(
            "MUR1200", _PIPE_PATH, 1,
            f"PIPELINE_STATE_KEYS must be distinct 'pipe_'-prefixed "
            f"agg_state keys, got {keys} — the prefix is how telemetry "
            "and report consumers recognize pipeline state",
        ))
    reg = RESERVED_AGG_STATE_KEY_GROUPS.get("PIPELINE_STATE_KEYS")
    if reg != "murmura_tpu.core.pipeline":
        findings.append(Finding(
            "MUR1200", _PIPE_PATH, 1,
            "PIPELINE_STATE_KEYS is not registered in durability."
            f"snapshot.RESERVED_AGG_STATE_KEY_GROUPS under its defining "
            f"module (got {reg!r}) — the double buffer would be "
            "invisible to the MUR900 snapshot-completeness contract and "
            "a SIGKILL at a buffer-populated round boundary would "
            "silently resume with the in-flight exchange discarded",
        ))
    stale_keys = pipeline_state_keys(stale=True)
    if BCAST_KEY in stale_keys or set(stale_keys) != set(keys) - {BCAST_KEY}:
        findings.append(Finding(
            "MUR1200", _PIPE_PATH, 1,
            f"pipeline_state_keys(stale=True) returned {stale_keys} — "
            "with bounded staleness armed the broadcast buffer must be "
            "the stale cache (buffer reuse) and exactly pipe_bcast must "
            "be dropped from the carried set",
        ))
    if tuple(pipeline_state_keys(stale=False)) != keys:
        findings.append(Finding(
            "MUR1200", _PIPE_PATH, 1,
            "pipeline_state_keys(stale=False) must return the full "
            "PIPELINE_STATE_KEYS reservation",
        ))
    for n, p, offsets, stale in (
        (5, 7, (), False), (8, 3, (1, 2, 4), False), (6, 4, (), True),
    ):
        init = init_pipeline_state(
            n, p, np.float32, sparse_offsets=offsets, stale=stale,
        )
        want = set(pipeline_state_keys(stale))
        if set(init) != want:
            findings.append(Finding(
                "MUR1200", _PIPE_PATH, 1,
                f"init_pipeline_state keys {sorted(init)} != "
                f"pipeline_state_keys({stale}) {sorted(want)} — the "
                "round program seeds agg_state from the reservation",
            ))
            continue
        adj = np.asarray(init[ADJ_KEY])
        want_adj = (n, len(offsets)) if offsets else (n, n)
        if adj.shape != want_adj:
            findings.append(Finding(
                "MUR1200", _PIPE_PATH, 1,
                f"init pipe_adj is shape {adj.shape}, not {want_adj} — "
                "the buffered adjacency must be node-LEADING ([N, N] "
                "dense / [N, k] sparse) so the mesh's leading-axis "
                "sharding places it on the node axis",
            ))
        if not offsets and np.diagonal(adj).any():
            findings.append(Finding(
                "MUR1200", _PIPE_PATH, 1,
                "init pipe_adj has a non-zero diagonal — the warm-up "
                "placeholder graph must respect MUR301 (no self-loops)",
            ))
        valid = np.asarray(init[VALID_KEY])
        if valid.shape != () or valid.item() != 0.0:
            findings.append(Finding(
                "MUR1200", _PIPE_PATH, 1,
                f"init pipe_valid is {valid!r}, not a scalar 0.0 — the "
                "buffer must start invalid so round 0's placeholder "
                "aggregation is where-discarded (warm-up exactness: "
                "P_1 = Q_0)",
            ))
    return findings


# --------------------------------------------------------------------------
# MUR1201 — recompile-free pipelining (executable)
# --------------------------------------------------------------------------


def _cell_config(rule: str, mode: str, pipeline: bool = True):
    """One (rule, mode) pipeline cell's tiny-but-real config — the
    durability grid's cell plus a fault schedule (so the buffered
    adjacency varies round to round) and the exchange block."""
    from murmura_tpu.analysis.ir import AGG_CASES
    from murmura_tpu.config import Config

    raw: Dict[str, Any] = {
        "experiment": {"name": f"pipe-{rule}-{mode}", "seed": 7,
                       "rounds": 5},
        "topology": {"type": "ring", "num_nodes": 5},
        "aggregation": {"algorithm": rule,
                        "params": dict(AGG_CASES.get(rule, {}))},
        "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.05},
        "data": {"adapter": "synthetic",
                 "params": {"num_samples": 40, "input_shape": [6],
                            "num_classes": 3}},
        "model": {"factory": "mlp",
                  "params": {"input_dim": 6, "hidden_dims": [8],
                             "num_classes": 3}},
        "backend": "simulation",
        "faults": {"enabled": True, "straggler_prob": 0.4,
                   "link_drop_prob": 0.2, "seed": 11},
    }
    if pipeline:
        raw["exchange"] = {"pipeline": True}
    if mode == "sparse":
        raw["topology"] = {"type": "exponential", "num_nodes": 8}
    elif mode != "dense":
        raise ValueError(f"unknown pipeline mode {mode!r}")
    return Config.model_validate(raw)


def recompile_cell_findings(rule: str, mode: str = "dense") -> List[Finding]:
    """Run ONE (rule, mode) MUR1201 cell: 2 warmup rounds (the compile),
    then 3 more under CompileTracker — the buffer fills, churn varies
    the buffered adjacency, and none of it may recompile.  The cell must
    also report a valid buffer after warm-up (``agg_pipe_valid`` > 0),
    so a dead pipeline cannot pass vacuously.  Exposed per-cell so tests
    gate a subset (tests/test_pipeline.py)."""
    from murmura_tpu.analysis.sanitizers import track_compiles
    from murmura_tpu.utils.factories import build_network_from_config

    path, line = _rule_anchor(rule)
    net = build_network_from_config(_cell_config(rule, mode))
    net.train(rounds=2, verbose=False)
    with track_compiles() as tracker:
        net.train(rounds=3, verbose=False)
    findings: List[Finding] = []
    if tracker.total:
        findings.append(Finding(
            "MUR1201", path, line,
            f"[{rule}/{mode}] 3 pipelined rounds after warmup compiled "
            f"{tracker.total} program(s) — the double buffer is carried "
            "state and the fault masks input values, so pipelining must "
            "be value-only over one compiled round program",
        ))
    valid = net.history.get("agg_pipe_valid") or []
    if not any(v > 0 for v in valid):
        findings.append(Finding(
            "MUR1201", path, line,
            f"[{rule}/{mode}] agg_pipe_valid never reported a valid "
            "buffer across 5 pipelined rounds — the recompile check is "
            "vacuous (the pipeline stage is not actually wired into "
            "this rule's round program; check core/rounds.py)",
        ))
    return findings


@_family
def check_pipeline_recompile() -> List[Finding]:
    """MUR1201 over ``AGGREGATORS x PIPELINE_MODES`` (compiles and runs
    tiny programs — the check_durability cost profile)."""
    from murmura_tpu.aggregation import AGGREGATORS

    findings: List[Finding] = []
    for rule in sorted(AGGREGATORS):
        for mode in PIPELINE_MODES:
            try:
                findings.extend(recompile_cell_findings(rule, mode))
            except Exception as e:  # noqa: BLE001 — a crash IS the finding
                path, line = _rule_anchor(rule)
                findings.append(Finding(
                    "MUR1201", path, line,
                    f"[{rule}/{mode}] pipeline recompile probe crashed: "
                    f"{type(e).__name__}: {e}",
                ))
    return findings


# --------------------------------------------------------------------------
# MUR1202 — collective-inventory parity (trace-level, per rule x mode)
# --------------------------------------------------------------------------


def _build_pipeline_programs(rule: str, mode: str):
    """(serialized program, pipelined program) for one (rule, mode) cell
    — identical in every respect except the pipeline flag."""
    import jax
    from jax.flatten_util import ravel_pytree

    from murmura_tpu.aggregation import build_aggregator
    from murmura_tpu.analysis.ir import AGG_CASES, canonical_offsets
    from murmura_tpu.attacks.gaussian import make_gaussian_attack
    from murmura_tpu.core.rounds import build_round_program
    from murmura_tpu.data.base import FederatedArrays
    from murmura_tpu.models import make_mlp

    n, s = 8, 16
    rng = np.random.default_rng(0)
    data = FederatedArrays(
        x=rng.normal(size=(n, s, 6)).astype(np.float32),
        y=rng.integers(0, 3, size=(n, s)).astype(np.int32),
        mask=np.ones((n, s), np.float32),
        num_samples=np.full((n,), s),
        num_classes=3,
    )
    model = make_mlp(
        input_dim=6, hidden_dims=(8,), num_classes=3,
        evidential=(rule == "evidential_trust"),
    )
    flat0, _ = ravel_pytree(model.init(jax.random.PRNGKey(0)))
    case = dict(AGG_CASES.get(rule, {}))
    if mode == "sparse":
        offsets = tuple(canonical_offsets(n))
        case["exchange_offsets"] = list(offsets)
        case["sparse_exchange"] = True
        sparse_offsets: Optional[Tuple[int, ...]] = offsets
    elif mode == "dense":
        sparse_offsets = None
    else:
        raise ValueError(f"unknown pipeline mode {mode!r}")
    agg = build_aggregator(
        rule, case, model_dim=int(flat0.size), total_rounds=4
    )
    attack = make_gaussian_attack(
        n, attack_percentage=0.3, noise_std=5.0, seed=7
    )
    common = dict(
        local_epochs=1, batch_size=8, lr=0.05, total_rounds=4, seed=7,
        attack=attack, sparse_offsets=sparse_offsets,
    )
    plain = build_round_program(model, agg, data, **common)
    piped = build_round_program(model, agg, data, pipeline=True, **common)
    return plain, piped


def _trace_collectives(prog) -> frozenset:
    """Collective primitive names in an (unfaulted) round program's
    traced jaxpr."""
    import jax
    import jax.numpy as jnp

    from murmura_tpu.analysis.ir import iter_eqns

    n = prog.num_nodes
    if prog.sparse:
        adj = jnp.ones((len(prog.sparse_offsets), n), jnp.float32)
    else:
        adj = jnp.asarray(
            np.ones((n, n), np.float32) - np.eye(n, dtype=np.float32)
        )
    closed = jax.make_jaxpr(prog.train_step)(
        prog.init_params,
        {k: jnp.asarray(v) for k, v in prog.init_agg_state.items()},
        jax.random.PRNGKey(0),
        adj,
        jnp.zeros((n,), jnp.float32),
        jnp.asarray(0.0, jnp.float32),
        {k: jnp.asarray(v) for k, v in prog.data_arrays.items()},
    )
    return frozenset(
        e.primitive.name for e in iter_eqns(closed)
        if e.primitive.name in _COLLECTIVE_PRIMS
    )


def collective_cell_findings(rule: str, mode: str) -> List[Finding]:
    """One (rule, mode) MUR1202 cell: the pipelined round program's
    traced collective inventory vs the serialized program's — hiding the
    exchange must not add communication."""
    path, line = _rule_anchor(rule)
    plain, piped = _build_pipeline_programs(rule, mode)
    stray = _trace_collectives(piped) - _trace_collectives(plain)
    if stray:
        return [Finding(
            "MUR1202", path, line,
            f"[{rule}/{mode}] the pipelined round program traces "
            f"collective(s) {sorted(stray)} absent from the serialized "
            "program — the delayed aggregation must run the same rule "
            "kernels on buffered values, adding no communication",
        )]
    return []


@_family
def check_pipeline_collectives() -> List[Finding]:
    """MUR1202 over ``AGGREGATORS x PIPELINE_MODES`` (trace-only: nothing
    compiles)."""
    from murmura_tpu.aggregation import AGGREGATORS

    findings: List[Finding] = []
    for rule in sorted(AGGREGATORS):
        for mode in PIPELINE_MODES:
            try:
                findings.extend(collective_cell_findings(rule, mode))
            except Exception as e:  # noqa: BLE001 — a crash IS the finding
                path, line = _rule_anchor(rule)
                findings.append(Finding(
                    "MUR1202", path, line,
                    f"[{rule}/{mode}] pipeline collective-inventory "
                    f"probe crashed: {type(e).__name__}: {e}",
                ))
    return findings


# --------------------------------------------------------------------------
# MUR1203 — delayed-step influence bounds + lagging-verdict discipline
# --------------------------------------------------------------------------

# The probe's cast over the canonical flow cell's graph: one sender
# scrubbed at THIS round's production (its row must never enter the next
# buffer), and one sender whose LAST-round scrub verdict zeroed its
# buffered edges (its buffered payload must never reach the delayed
# output — the lagging-verdict containment).
_SCRUBBED_NOW = 2
_SCRUBBED_PREV = 3

# Rules exempt from the probe-C buffered-taint check, with the reason —
# the same documented value-dataflow limitation as MUR802/MUR1103:
# geometric_median's dense Weiszfeld distances run through the Gram
# centering mean, which couples all rows in value dataflow while
# cancelling exactly in every distance.
_DELAYED_TAINT_EXEMPT: Dict[str, str] = {
    "geometric_median": "Weiszfeld distances run through the dense "
    "Gram centering mean, which couples all rows in value dataflow "
    "while cancelling exactly in every distance",
}


# Default-path memos: the composed cell build (make_jaxpr) and each
# taint evaluation are deterministic and pure, and the non-vacuity guard
# plus probes A and C would otherwise repeat identical sweeps — the
# memos keep the package check to one build + two taint runs per rule.
# Negative tests pass a combine_factory and bypass both memos.
_DEFAULT_CELL_MEMO: Dict[str, Any] = {}
_DEFAULT_TAINT_MEMO: Dict[Tuple[str, bool, bool], Any] = {}


def _delayed_cell(rule: str, combine_factory=None):
    """The composed produce-scrub -> buffer -> delayed-aggregate ->
    combine step over the canonical dense flow cell, plus the concrete
    seed values the probes share.  ``combine_factory`` overrides the
    combine/buffer-write wiring so negative tests can drive the probes
    with a broken pipeline (tests/test_pipeline.py): it receives
    ``(bcast_raw, own_now, scrub_ok, buf_bcast)`` and returns
    ``(next_buffer, delayed_bcast)`` — the default stores the scrubbed
    broadcast and serves the buffer.  Default-path results are memoized
    per rule (pure build; the probes and the non-vacuity guard share
    one trace).
    """
    if combine_factory is None and rule in _DEFAULT_CELL_MEMO:
        return _DEFAULT_CELL_MEMO[rule]
    import jax
    import jax.numpy as jnp

    from murmura_tpu.analysis.flow import _quiet_tracing, build_flow_cell

    cell = build_flow_cell(rule, "dense")
    n = cell.n
    own, bcast, adj0 = cell.args[0], cell.args[1], cell.args[2]
    base = np.asarray(adj0, np.float32)

    # This round's production verdicts: sender _SCRUBBED_NOW caught.
    scrub_np = np.ones((n,), np.float32)
    scrub_np[_SCRUBBED_NOW] = 0.0
    scrub_ok = jnp.asarray(scrub_np)
    # The BUFFERED adjacency: last round's folds already zeroed sender
    # _SCRUBBED_PREV's edges (its verdict was enforced at production
    # time, one round before this aggregation runs).
    buf_adj_np = base.copy()
    buf_adj_np[:, _SCRUBBED_PREV] = 0.0
    rng = np.random.default_rng(1)
    buf_own_np = np.asarray(rng.normal(size=bcast.shape) * 0.1, np.float32)
    buf_bcast_np = np.asarray(rng.normal(size=bcast.shape) * 0.1, np.float32)

    cell_fn = cell.fn
    rest = tuple(cell.args[3:])

    def default_combine(bcast_raw, own_now, scrub, buf_bcast):
        # The production sentinel substitution (rounds.py): a caught
        # row's broadcast is replaced by its own state before the
        # buffer write — the lagging verdict is enforced HERE.
        next_buffer = jnp.where(scrub[:, None] > 0, bcast_raw, own_now)
        return next_buffer, buf_bcast

    combine = combine_factory or default_combine

    def fn(own_now, bcast_raw, buf_own, buf_bcast, buf_adj, *rest_a):  # murmura: traced
        next_buffer, delayed_bcast = combine(
            bcast_raw, own_now, scrub_ok, buf_bcast
        )
        agg_out, _state, _stats = cell_fn(
            buf_own, delayed_bcast, buf_adj, *rest_a
        )
        disp = agg_out - buf_own
        out = own_now + disp
        return out, next_buffer

    args = (
        own, bcast, jnp.asarray(buf_own_np), jnp.asarray(buf_bcast_np),
        jnp.asarray(buf_adj_np),
    ) + rest
    with _quiet_tracing():
        closed = jax.make_jaxpr(fn)(*args)
    pack = (cell, closed, args, buf_adj_np, base)
    if combine_factory is None:
        _DEFAULT_CELL_MEMO[rule] = pack
    return pack


def _taint_run(closed, args, n, seed_bcast: bool, seed_buffer: bool):
    """Evaluate the composed step with row labels on the raw broadcast
    and/or buffered broadcast leaves; returns
    ``(out_taint [L, N, P], buffer_taint [L, N, P])``."""
    import jax

    from murmura_tpu.analysis.flow import TaintEval, _quiet_tracing, _tz

    flat_args, _ = jax.tree_util.tree_flatten(args)
    arg_leaf_pos: List[int] = []
    for i, a in enumerate(args):
        arg_leaf_pos.extend([i] * len(jax.tree_util.tree_leaves(a)))
    pairs = []
    for leaf, pos in zip(flat_args, arg_leaf_pos):
        v = np.asarray(leaf)
        t = _tz(n, v.shape)
        if (pos == 1 and seed_bcast) or (pos == 3 and seed_buffer):
            for lbl in range(n):
                t[lbl, lbl] = True
        pairs.append((v, t))
    ev = TaintEval(n)
    with _quiet_tracing():
        outs = ev.eval_closed(closed, pairs)
    return outs[0][1], outs[1][1]


def delayed_influence_findings(rule: str, combine_factory=None) -> List[Finding]:
    """One rule's MUR1203 probes over the composed delayed step.

    Probe A (buffer seeded): bounded rules keep their declared
    per-coordinate influence cardinality when the aggregation consumes
    buffered rows.
    Probe B (bcast seeded): a sender scrubbed at THIS round's production
    never reaches the next buffer; every clean sender's broadcast does.
    Probe C (buffer seeded): a sender whose lagging verdict zeroed its
    buffered edges never reaches the delayed output via its buffered
    payload.
    """
    path, line = _rule_anchor(rule)
    cell, closed, args, buf_adj, base = _delayed_cell(rule, combine_factory)
    n = cell.n
    findings: List[Finding] = []

    def taint(seed_bcast: bool, seed_buffer: bool):
        key = (rule, seed_bcast, seed_buffer)
        if combine_factory is None and key in _DEFAULT_TAINT_MEMO:
            return _DEFAULT_TAINT_MEMO[key]
        res = _taint_run(closed, args, n, seed_bcast, seed_buffer)
        if combine_factory is None:
            _DEFAULT_TAINT_MEMO[key] = res
        return res

    # -- Probe A: influence cardinality over buffered rows --------------
    # (the buffer-seeded evaluation; probe C reads the same result)
    out_t, _buf_t = taint(seed_bcast=False, seed_buffer=True)
    influence = cell.agg.influence
    if influence is not None and influence.kind == "bounded":
        eff = buf_adj > 0
        per_coord = out_t.sum(axis=0)  # [N, P] distinct-label counts
        self_t = out_t[np.arange(n), np.arange(n)]  # [N, P]
        card_i = (per_coord - self_t).max(axis=1)  # [N]
        for i in range(n):
            bound = influence.bound(int(eff[i].sum()))
            if int(card_i[i]) > bound:
                findings.append(Finding(
                    "MUR1203", path, line,
                    f"[{rule}] the composed delayed step mixes "
                    f"{int(card_i[i])} buffered neighbors into receiver "
                    f"{i}'s output coordinate but the rule declares a "
                    f"bound of {bound} at its buffered degree "
                    f"{int(eff[i].sum())} — delaying the aggregation "
                    "widened the rule's per-coordinate influence",
                ))

    # -- Probe B: a production-scrubbed row must never enter the buffer -
    _out_b, buf_t = taint(seed_bcast=True, seed_buffer=False)
    s = _SCRUBBED_NOW
    if buf_t[s].any():
        findings.append(Finding(
            "MUR1203", path, line,
            f"[{rule}] sender {s}'s scrubbed broadcast taints the next "
            "pipeline buffer — the sentinel verdict must be enforced at "
            "the buffer write (production time), because the delayed "
            "aggregation runs one round after the verdict",
        ))
    clean = [j for j in range(n) if j not in (_SCRUBBED_NOW,)]
    if clean and not buf_t[clean[0], clean[0]].any():
        findings.append(Finding(
            "MUR1203", path, line,
            f"[{rule}] clean sender {clean[0]}'s broadcast does not "
            "reach its own buffer row — the buffer write is not wired "
            "and the lagging-verdict probes are vacuous",
        ))

    # -- Probe C: a lag-scrubbed BUFFERED row must not be aggregated ----
    # (same seeding as probe A — one evaluation serves both)
    if rule in _DELAYED_TAINT_EXEMPT:
        return findings
    out_c = out_t
    if out_c[_SCRUBBED_PREV].any():
        findings.append(Finding(
            "MUR1203", path, line,
            f"[{rule}] sender {_SCRUBBED_PREV}'s BUFFERED payload "
            "taints the delayed output although its scrub verdict "
            "zeroed its buffered edges — a caught row survives one "
            "round late through the pipeline buffer",
        ))
    return findings


@_family
def check_pipeline_influence() -> List[Finding]:
    """MUR1203 over every registered rule (trace-only), plus the
    non-vacuity guard: on fedavg — declared-unbounded, every neighbor
    admitted — a live buffered sender's payload MUST reach some
    receiver's output, proving the probes exercise a live delayed path
    rather than an edgeless one."""
    from murmura_tpu.aggregation import AGGREGATORS

    findings: List[Finding] = []
    for rule in sorted(AGGREGATORS):
        try:
            findings.extend(delayed_influence_findings(rule))
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            path, line = _rule_anchor(rule)
            findings.append(Finding(
                "MUR1203", path, line,
                f"[{rule}] delayed influence probe crashed: "
                f"{type(e).__name__}: {e}",
            ))
    try:
        # The memoized default-path cell + buffer-seeded taint run: the
        # fedavg probes above already computed both, so the guard costs
        # nothing extra.
        cell, closed, args, buf_adj, base = _delayed_cell("fedavg")
        memo = _DEFAULT_TAINT_MEMO.get(("fedavg", False, True))
        out_c, _ = memo if memo is not None else _taint_run(
            closed, args, cell.n, seed_bcast=False, seed_buffer=True
        )
        live = next(
            j for j in range(cell.n)
            if j not in (_SCRUBBED_NOW, _SCRUBBED_PREV)
        )
        receivers = np.nonzero(buf_adj[:, live] > 0)[0]
        served = any(out_c[live, r].any() for r in receivers)
        if not served:
            path, line = _rule_anchor("fedavg")
            findings.append(Finding(
                "MUR1203", path, line,
                "[fedavg] a live buffered sender's payload reaches NO "
                "receiver through the delayed aggregation — the "
                "delayed path is dead and every MUR1203 containment "
                "verdict above is vacuous",
            ))
    except Exception as e:  # noqa: BLE001 — a crash IS the finding
        findings.append(Finding(
            "MUR1203", _PIPE_PATH, 1,
            f"the MUR1203 non-vacuity guard crashed: "
            f"{type(e).__name__}: {e}",
        ))
    return findings


# --------------------------------------------------------------------------
# Entry point
# --------------------------------------------------------------------------

_PIPELINE_MEMO: Optional[List[Finding]] = None


def check_pipeline(force: bool = False) -> List[Finding]:
    """Run MUR1200-1203; returns findings (empty = every pipelined-
    rounds contract holds).  Memoized per process — the CLI, the battery
    pre-flight and the slow test gate share one sweep.  MUR1201 compiles
    and runs tiny programs (the check_durability cost profile), which is
    why the family runs only for the package-level check."""
    global _PIPELINE_MEMO
    if _PIPELINE_MEMO is not None and not force:
        return list(_PIPELINE_MEMO)

    from murmura_tpu.analysis.ir import _apply_suppressions

    findings: List[Finding] = []
    for fam_name, fam in PIPELINE_CHECK_FAMILIES.items():
        try:
            findings.extend(fam())
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            findings.append(Finding(
                "MUR1200", str(Path(__file__).resolve()), 1,
                f"pipeline check family '{fam_name}' crashed: "
                f"{type(e).__name__}: {e}",
            ))
    findings = _apply_suppressions(list(dict.fromkeys(findings)))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    _PIPELINE_MEMO = list(findings)
    return findings

"""Adaptive-adversary contracts (MUR1000-1003) — part of the default
package check (docs/ROBUSTNESS.md "Adaptive adversaries & the frontier").

The closed-loop attacks (attacks/adaptive.py) thread a feedback path
through the compiled round program: acceptance taps -> adaptation state
(``ATTACK_STATE_KEYS`` in ``agg_state``) -> next round's broadcast.  Each
link carries an invariant that must stay machine-checked or the frontier's
claims (docs/ROBUSTNESS.md) silently rot:

- **MUR1000 — attack-state registry bijection.**  Every adaptive attack's
  carried state keys must be drawn from — and jointly cover —
  :data:`~murmura_tpu.attacks.adaptive.ATTACK_STATE_KEYS`, every factory
  must populate the full adaptation interface with ``[N] float32`` rows,
  and the tuple itself must be registered in the MUR900 snapshot registry
  (durability/snapshot.py) so SIGKILL/``--resume`` carries a
  mid-bisection attacker byte-identically.
- **MUR1001 — recompile-free adaptation.**  Strength lives in carried
  state and the round index is a traced input, so an adaptive round
  program compiles once and every strength/round variation is value-only
  (:class:`~murmura_tpu.analysis.sanitizers.CompileTracker`); the gang's
  ``reset_run`` re-aim between frontier stages must be equally free.
- **MUR1002 — collective-inventory parity.**  The feedback path is
  elementwise over node-local rows; the adaptive round program's traced
  collective inventory must equal the static-attack *tapped* program's,
  per rule (observing-and-reacting must not add communication, the
  MUR400 promise extended through the loop).
- **MUR1003 — feedback taint containment.**  Run the taint interpreter
  (analysis/flow.py) over the feedback path and the composed
  aggregate+feedback step: acceptance-signal taint may reach the
  *attacker's* broadcast/state rows only, and the composed step must
  still satisfy each bounded rule's declared MUR800 influence bound.
  (The interpreter deliberately excludes selection influence — a
  predicate's taint is dropped, the MUR800 semantics — so what this
  proves is that the acceptance signal never flows *as values* into
  honest rows or the aggregated output.)

Like ``check_durability``, the full grid compiles and runs tiny programs,
so it is memoized per process and runs by default only for the package
check; tests gate representative cells per tier-1 run
(tests/test_adaptive.py) and the full grid under ``-m slow``.
"""

from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from murmura_tpu.analysis.lint import Finding

# The adaptive-attack kinds the grids sweep: adaptive ALIE (the
# variance-quantile z walk), the generic scale bisection wrapped around
# the gaussian attack (the pair `murmura frontier` charts), and adaptive
# IPM (the epsilon walk on the paper's own mean-negation axis — the
# ISSUE-13 follow-up).
ADAPTIVE_ATTACK_KINDS: Tuple[str, ...] = ("alie", "gaussian", "ipm")

# Registry of check families in this module: name -> callable, scanned by
# analysis/ir.py's check_coverage so an unwired family is a MUR205
# finding (the flow.py/durability.py twin pattern).
ADAPTIVE_CHECK_FAMILIES: Dict[str, Callable[[], List[Finding]]] = {}


def _family(fn):
    ADAPTIVE_CHECK_FAMILIES[fn.__name__] = fn
    return fn


_PKG = Path(__file__).resolve().parent.parent
_ATK_PATH = str(_PKG / "attacks" / "adaptive.py")
_ROUNDS_PATH = str(_PKG / "core" / "rounds.py")

# Collective jaxpr primitives (the MUR1002 inventory subject) — the traced
# names, not HLO op names (analysis/ir.py's _HLO_COLLECTIVES covers the
# lowered side for the canonical cells under MUR400).
_COLLECTIVE_PRIMS = frozenset({
    "ppermute", "pbroadcast", "psum", "psum_scatter", "pmax", "pmin",
    "all_gather", "all_to_all", "reduce_scatter", "pgather", "axis_index",
})


def _build_adaptive(kind: str, n: int, pct: float = 0.3, seed: int = 7):
    """One adaptive attack of ``kind`` at size ``n`` (the grid cells')."""
    from murmura_tpu.attacks.adaptive import (
        make_adaptive_alie_attack,
        make_adaptive_ipm_attack,
        make_bisection_attack,
    )
    from murmura_tpu.attacks.gaussian import make_gaussian_attack

    if kind == "alie":
        return make_adaptive_alie_attack(n, attack_percentage=pct, seed=seed)
    if kind == "ipm":
        return make_adaptive_ipm_attack(n, attack_percentage=pct, seed=seed)
    if kind == "gaussian":
        return make_bisection_attack(
            make_gaussian_attack(
                n, attack_percentage=pct, noise_std=5.0, seed=seed
            )
        )
    raise ValueError(f"unknown adaptive attack kind {kind!r}")


# --------------------------------------------------------------------------
# MUR1000 — attack-state registry bijection
# --------------------------------------------------------------------------


@_family
def check_attack_state_registry() -> List[Finding]:
    """MUR1000: ATTACK_STATE_KEYS <-> adaptive-attack factories <-> MUR900
    snapshot registry, all bijective and shape-sound."""
    findings: List[Finding] = []
    try:
        from murmura_tpu.attacks.adaptive import (
            ADAPTIVE_ATTACKS,
            ATTACK_STATE_KEYS,
            AdaptiveAttack,
        )
        from murmura_tpu.durability.snapshot import (
            RESERVED_AGG_STATE_KEY_GROUPS,
        )
    except Exception as e:  # noqa: BLE001 — the import failure IS the finding
        return [Finding(
            "MUR1000", _ATK_PATH, 1,
            f"the adaptive-attack registries failed to import "
            f"({type(e).__name__}: {e}) — the MUR1000 bijection cannot "
            "be checked",
        )]

    keys = tuple(ATTACK_STATE_KEYS)
    if len(set(keys)) != len(keys) or any(
        not k.startswith("atk_") for k in keys
    ):
        findings.append(Finding(
            "MUR1000", _ATK_PATH, 1,
            f"ATTACK_STATE_KEYS must be distinct 'atk_'-prefixed agg_state "
            f"keys, got {keys} — the prefix is how telemetry/frontier "
            "consumers recognize adaptation state",
        ))
    reg = RESERVED_AGG_STATE_KEY_GROUPS.get("ATTACK_STATE_KEYS")
    if reg != "murmura_tpu.attacks.adaptive":
        findings.append(Finding(
            "MUR1000", _ATK_PATH, 1,
            "ATTACK_STATE_KEYS is not registered in durability.snapshot."
            f"RESERVED_AGG_STATE_KEY_GROUPS under its defining module "
            f"(got {reg!r}) — the attacker's bracket/EMA state would be "
            "invisible to the MUR900 snapshot-completeness contract and "
            "a resumed attacker would silently restart cold",
        ))

    covered: set = set()
    for name, factory in sorted(ADAPTIVE_ATTACKS.items()):
        try:
            atk = factory()
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            findings.append(Finding(
                "MUR1000", _ATK_PATH, 1,
                f"adaptive attack factory '{name}' crashed: "
                f"{type(e).__name__}: {e}",
            ))
            continue
        if not isinstance(atk, AdaptiveAttack):
            findings.append(Finding(
                "MUR1000", _ATK_PATH, 1,
                f"ADAPTIVE_ATTACKS['{name}'] built a "
                f"{type(atk).__name__}, not an AdaptiveAttack",
            ))
            continue
        for hook in ("init_attack_state", "apply_adaptive",
                     "update_attack_state", "strength_stats"):
            if getattr(atk, hook) is None:
                findings.append(Finding(
                    "MUR1000", _ATK_PATH, 1,
                    f"adaptive attack '{name}' does not populate "
                    f"'{hook}' — the round program (core/rounds.py) "
                    "calls every adaptation hook unconditionally",
                ))
        stray = set(atk.state_keys) - set(keys)
        if stray:
            findings.append(Finding(
                "MUR1000", _ATK_PATH, 1,
                f"adaptive attack '{name}' carries state keys "
                f"{sorted(stray)} not reserved in ATTACK_STATE_KEYS — "
                "unreserved carried state collides with rule state and "
                "escapes the MUR900 snapshot bijection",
            ))
        covered |= set(atk.state_keys)
        if atk.init_attack_state is None:
            continue
        for n in (4, 9):
            try:
                init = atk.init_attack_state(n)
            except Exception as e:  # noqa: BLE001 — a crash IS the finding
                findings.append(Finding(
                    "MUR1000", _ATK_PATH, 1,
                    f"adaptive attack '{name}' init_attack_state({n}) "
                    f"crashed: {type(e).__name__}: {e}",
                ))
                continue
            if set(init) != set(atk.state_keys):
                findings.append(Finding(
                    "MUR1000", _ATK_PATH, 1,
                    f"adaptive attack '{name}' init_attack_state keys "
                    f"{sorted(init)} != declared state_keys "
                    f"{sorted(atk.state_keys)} — the round program seeds "
                    "agg_state from the declaration",
                ))
                continue
            for k, v in init.items():
                arr = np.asarray(v)
                if arr.shape != (n,) or arr.dtype != np.float32:
                    findings.append(Finding(
                        "MUR1000", _ATK_PATH, 1,
                        f"adaptive attack '{name}' state key '{k}' is "
                        f"{arr.dtype}{arr.shape}, not float32 ({n},) — "
                        "adaptation state must be per-node [N] float32 "
                        "rows so gang vmap and the durability snapshot "
                        "treat it like any node-indexed carried state",
                    ))
    orphans = set(keys) - covered
    if orphans:
        findings.append(Finding(
            "MUR1000", _ATK_PATH, 1,
            f"ATTACK_STATE_KEYS entries {sorted(orphans)} are carried by "
            "no registered adaptive attack — remove the stale "
            "reservation or register the attack in ADAPTIVE_ATTACKS",
        ))
    return findings


# --------------------------------------------------------------------------
# MUR1001 — recompile-free adaptation (executable, per rule x attack)
# --------------------------------------------------------------------------


def _cell_config(rule: str, kind: str):
    """One (rule, adaptive attack) cell's tiny-but-real config — the
    durability grid's cell (analysis/durability.py) plus the adaptive
    attack block, so the two executable grids stay one inventory."""
    from murmura_tpu.analysis.ir import AGG_CASES
    from murmura_tpu.config import Config

    raw: Dict[str, Any] = {
        "experiment": {"name": f"adaptive-{rule}-{kind}", "seed": 7,
                       "rounds": 4},
        "topology": {"type": "ring", "num_nodes": 5},
        "aggregation": {"algorithm": rule,
                        "params": dict(AGG_CASES.get(rule, {}))},
        "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.05},
        "data": {"adapter": "synthetic",
                 "params": {"num_samples": 40, "input_shape": [6],
                            "num_classes": 3}},
        "model": {"factory": "mlp",
                  "params": {"input_dim": 6, "hidden_dims": [8],
                             "num_classes": 3}},
        "backend": "simulation",
        "attack": {"enabled": True, "type": kind, "percentage": 0.3,
                   "params": ({"noise_std": 5.0} if kind == "gaussian"
                              else {}),
                   "adaptive": {"enabled": True}},
    }
    return Config.model_validate(raw)


def recompile_cell_findings(rule: str, kind: str) -> List[Finding]:
    """Run ONE (rule, adaptive attack) MUR1001 cell: 2 warmup rounds (the
    compile), then 2 more under CompileTracker — the adaptation state
    evolves (the bisection moves its probe, the ALIE z walks) and the
    round index advances, and none of it may recompile.  Exposed per-cell
    so tests gate a subset (tests/test_adaptive.py)."""
    from murmura_tpu.analysis.ir import _rule_anchor
    from murmura_tpu.analysis.sanitizers import track_compiles
    from murmura_tpu.utils.factories import build_network_from_config

    path, line = _rule_anchor(rule)
    net = build_network_from_config(_cell_config(rule, kind))
    net.train(rounds=2, verbose=False)
    state_before = {
        k: np.asarray(v) for k, v in net.agg_state.items()
        if k.startswith("atk_")
    }
    with track_compiles() as tracker:
        net.train(rounds=2, verbose=False)
    findings: List[Finding] = []
    if tracker.total:
        findings.append(Finding(
            "MUR1001", path, line,
            f"[{rule}/{kind}] 2 adaptive rounds after warmup compiled "
            f"{tracker.total} program(s) — attack strength is carried "
            "state and the round index a traced input, so adaptation "
            "must be value-only over one compiled round program",
        ))
    comp = np.asarray(net.compromised) > 0
    moved = any(
        not np.array_equal(
            state_before[k][comp], np.asarray(net.agg_state[k])[comp]
        )
        for k in state_before
    )
    if state_before and comp.any() and not moved:
        findings.append(Finding(
            "MUR1001", path, line,
            f"[{rule}/{kind}] the adaptation state did not move across 2 "
            "rounds — the recompile check is vacuous (the feedback loop "
            "is not actually running; check the acceptance wiring in "
            "core/rounds.py)",
        ))
    return findings


@_family
def check_adaptive_recompile() -> List[Finding]:
    """MUR1001 over ``AGGREGATORS x ADAPTIVE_ATTACK_KINDS``, plus the
    frontier's gang re-aim: ``reset_run`` to a new strength grid over the
    warm bucket must cost zero compiles (the `murmura frontier` stage
    loop's contract)."""
    from murmura_tpu.aggregation import AGGREGATORS
    from murmura_tpu.analysis.ir import _rule_anchor

    findings: List[Finding] = []
    for rule in sorted(AGGREGATORS):
        for kind in ADAPTIVE_ATTACK_KINDS:
            try:
                findings.extend(recompile_cell_findings(rule, kind))
            except Exception as e:  # noqa: BLE001 — a crash IS the finding
                path, line = _rule_anchor(rule)
                findings.append(Finding(
                    "MUR1001", path, line,
                    f"[{rule}/{kind}] adaptive recompile probe crashed: "
                    f"{type(e).__name__}: {e}",
                ))
    try:
        findings.extend(gang_reset_findings())
    except Exception as e:  # noqa: BLE001 — a crash IS the finding
        findings.append(Finding(
            "MUR1001", str(_PKG / "core" / "gang.py"), 1,
            f"the gang reset_run recompile probe crashed: "
            f"{type(e).__name__}: {e}",
        ))
    return findings


def gang_reset_findings() -> List[Finding]:
    """The frontier stage loop's contract: a strength-grid re-aim via
    ``GangNetwork.reset_run`` over the warm bucket costs zero compiles."""
    from murmura_tpu.analysis.sanitizers import track_compiles
    from murmura_tpu.config import Config
    from murmura_tpu.core.gang import GangMember
    from murmura_tpu.utils.factories import build_gang_from_config

    raw = _cell_config("krum", "gaussian").model_dump()
    raw["sweep"] = {"members": [
        {"seed": 7, "attack_scale": 0.0},
        {"seed": 7, "attack_scale": 1.0},
    ]}
    gang = build_gang_from_config(
        Config.model_validate(raw), retain_init=True
    )
    gang.train(rounds=2, eval_every=2)
    with track_compiles() as tracker:
        gang.reset_run([
            GangMember(seed=7, attack_scale=0.0),
            GangMember(seed=7, attack_scale=2.5),
        ])
        gang.train(rounds=2, eval_every=2)
    if tracker.total:
        return [Finding(
            "MUR1001", str(_PKG / "core" / "gang.py"), 1,
            f"reset_run + retrain over the warm gang bucket compiled "
            f"{tracker.total} program(s) — the frontier's successive-"
            "halving stages must be value-only resets (strengths are "
            "traced hp inputs; the bucket shape is unchanged)",
        )]
    return []


# --------------------------------------------------------------------------
# MUR1002 — collective-inventory parity (trace-level, per rule x attack)
# --------------------------------------------------------------------------


def _trace_collectives(prog) -> frozenset:
    """Collective primitive names in the round program's traced jaxpr."""
    import jax
    import jax.numpy as jnp

    from murmura_tpu.analysis.ir import iter_eqns

    n = prog.num_nodes
    adj = jnp.asarray(
        np.ones((n, n), np.float32) - np.eye(n, dtype=np.float32)
    )
    closed = jax.make_jaxpr(prog.train_step)(
        prog.init_params,
        {k: jnp.asarray(v) for k, v in prog.init_agg_state.items()},
        jax.random.PRNGKey(0),
        adj,
        jnp.zeros((n,), jnp.float32),
        jnp.asarray(0.0, jnp.float32),
        {k: jnp.asarray(v) for k, v in prog.data_arrays.items()},
    )
    return frozenset(
        e.primitive.name for e in iter_eqns(closed)
        if e.primitive.name in _COLLECTIVE_PRIMS
    )


def collective_cell_findings(rule: str, kind: str) -> List[Finding]:
    """One (rule, adaptive attack) MUR1002 cell: the adaptive round
    program's traced collective inventory vs the static-attack *tapped*
    program's — the feedback path must not add communication."""
    import jax
    from jax.flatten_util import ravel_pytree

    from murmura_tpu.aggregation import build_aggregator
    from murmura_tpu.analysis.ir import AGG_CASES, _rule_anchor
    from murmura_tpu.attacks.alie import make_alie_attack
    from murmura_tpu.attacks.gaussian import make_gaussian_attack
    from murmura_tpu.core.rounds import build_round_program
    from murmura_tpu.data.base import FederatedArrays
    from murmura_tpu.models import make_mlp

    path, line = _rule_anchor(rule)
    n, s = 5, 16
    rng = np.random.default_rng(0)
    data = FederatedArrays(
        x=rng.normal(size=(n, s, 6)).astype(np.float32),
        y=rng.integers(0, 3, size=(n, s)).astype(np.int32),
        mask=np.ones((n, s), np.float32),
        num_samples=np.full((n,), s),
        num_classes=3,
    )
    model = make_mlp(
        input_dim=6, hidden_dims=(8,), num_classes=3,
        evidential=(rule == "evidential_trust"),
    )
    flat0, _ = ravel_pytree(model.init(jax.random.PRNGKey(0)))
    agg = build_aggregator(
        rule, dict(AGG_CASES.get(rule, {})), model_dim=int(flat0.size),
        total_rounds=4,
    )
    if kind == "alie":
        static = make_alie_attack(n, attack_percentage=0.3, seed=7)
    elif kind == "ipm":
        from murmura_tpu.attacks.ipm import make_ipm_attack

        static = make_ipm_attack(n, attack_percentage=0.3, seed=7)
    else:
        static = make_gaussian_attack(
            n, attack_percentage=0.3, noise_std=5.0, seed=7
        )
    adaptive = _build_adaptive(kind, n)
    common = dict(
        local_epochs=1, batch_size=8, lr=0.05, total_rounds=4, seed=7
    )
    inv_static = _trace_collectives(build_round_program(
        model, agg, data, attack=static, audit_taps=True, **common
    ))
    inv_adaptive = _trace_collectives(build_round_program(
        model, agg, data, attack=adaptive, **common
    ))
    stray = inv_adaptive - inv_static
    if stray:
        return [Finding(
            "MUR1002", path, line,
            f"[{rule}/{kind}] the adaptive round program traces "
            f"collective(s) {sorted(stray)} absent from the static-attack "
            "tapped program — the acceptance feedback must stay "
            "elementwise over node-local rows (closing the loop must not "
            "add communication)",
        )]
    return []


@_family
def check_adaptive_collectives() -> List[Finding]:
    """MUR1002 over ``AGGREGATORS x ADAPTIVE_ATTACK_KINDS`` (trace-only:
    nothing compiles)."""
    from murmura_tpu.aggregation import AGGREGATORS
    from murmura_tpu.analysis.ir import _rule_anchor

    findings: List[Finding] = []
    for rule in sorted(AGGREGATORS):
        for kind in ADAPTIVE_ATTACK_KINDS:
            try:
                findings.extend(collective_cell_findings(rule, kind))
            except Exception as e:  # noqa: BLE001 — a crash IS the finding
                path, line = _rule_anchor(rule)
                findings.append(Finding(
                    "MUR1002", path, line,
                    f"[{rule}/{kind}] adaptive collective-inventory probe "
                    f"crashed: {type(e).__name__}: {e}",
                ))
    return findings


# --------------------------------------------------------------------------
# MUR1003 — feedback taint containment (trace-only)
# --------------------------------------------------------------------------


def containment_findings(name: str, attack) -> List[Finding]:
    """Taint the acceptance signal, run the feedback update + the next
    apply, and require every tainted broadcast/state row to be the
    attacker's own: accept-label j may reach row i only when ``i == j``
    and i is compromised.  Factored out so tests can drive it with a
    leaky fake attack (tests/test_adaptive.py)."""
    import jax
    import jax.numpy as jnp

    from murmura_tpu.analysis.flow import TaintEval, _quiet_tracing, _tz

    n, dim = 8, 6
    comp = jnp.asarray(attack.compromised.astype(np.float32))
    comp_np = np.asarray(attack.compromised) > 0
    keys = tuple(sorted(attack.state_keys))
    state0 = attack.init_attack_state(n)
    rng_np = np.random.default_rng(0)
    flat0 = jnp.asarray(rng_np.normal(size=(n, dim)) * 0.1, jnp.float32)
    prng = jax.random.PRNGKey(0)

    def fn(flat, accept, *state_vals):  # murmura: traced
        state = dict(zip(keys, state_vals))
        new_state = attack.update_attack_state(
            state, accept, jnp.ones(n, jnp.float32), comp
        )
        out = attack.apply_adaptive(
            flat, comp, prng, jnp.asarray(0.0, jnp.float32), new_state
        )
        return (out,) + tuple(new_state[k] for k in keys)

    args = (flat0, jnp.full((n,), 0.5, jnp.float32)) + tuple(
        jnp.asarray(state0[k]) for k in keys
    )
    with _quiet_tracing():
        closed = jax.make_jaxpr(fn)(*args)
    ev = TaintEval(n)
    pairs = []
    for i, a in enumerate(args):
        v = np.asarray(a)
        t = _tz(n, v.shape)
        if i == 1:  # the acceptance signal: row labels
            for lbl in range(n):
                t[lbl, lbl] = True
        pairs.append((v, t))
    with _quiet_tracing():
        outs = ev.eval_closed(closed, pairs)

    findings: List[Finding] = []
    subjects = [("broadcast", outs[0][1])] + [
        (f"state '{k}'", outs[1 + i][1]) for i, k in enumerate(keys)
    ]
    for label, t in subjects:
        # t is [L, N, ...]: label j present anywhere in row i.
        rows = t.reshape(n, n, -1).any(axis=2)  # [label, row]
        for j in range(n):
            for i in range(n):
                if not rows[j, i]:
                    continue
                if i != j or not comp_np[i]:
                    who = (
                        "an honest row" if not comp_np[i]
                        else "another compromised node's row"
                    )
                    findings.append(Finding(
                        "MUR1003", _ATK_PATH, 1,
                        f"adaptive attack '{name}': acceptance-signal "
                        f"taint about node {j} reaches {label} row {i} "
                        f"({who}) — the feedback loop may only tune the "
                        "attacker's own rows",
                    ))
    return findings


def adaptive_influence_findings(rule: str, kind: str) -> List[Finding]:
    """One (rule, adaptive attack) composed-step cell: aggregate with
    taps on, feed the acceptance signal into the attack-state update, and
    analyze the whole step with broadcast rows taint-seeded.  The
    aggregated output must still satisfy the rule's declared MUR800
    bound, and the updated attack state may be tainted at compromised
    rows only."""
    import jax
    import jax.numpy as jnp

    from murmura_tpu.analysis.flow import (
        TaintEval,
        _quiet_tracing,
        _rule_anchor,
        _tz,
        build_flow_cell,
    )
    from murmura_tpu.attacks.adaptive import acceptance_feedback

    path, line = _rule_anchor(rule)
    cell = build_flow_cell(rule, "dense", audit=True)
    n = cell.n
    attack = _build_adaptive(kind, n)
    comp = jnp.asarray(attack.compromised.astype(np.float32))
    comp_np = np.asarray(attack.compromised) > 0
    keys = tuple(sorted(attack.state_keys))
    atk0 = attack.init_attack_state(n)
    cell_fn, bcast_args = cell.fn, cell.bcast_args

    def fn(*all_args):  # murmura: traced
        cell_args = all_args[: len(cell.args)]
        state_vals = all_args[len(cell.args):]
        new_flat, _rule_state, agg_stats = cell_fn(*cell_args)
        adj = cell_args[2]  # dense cells: (own, bcast, adj, ridx, ...)
        accept, observed = acceptance_feedback(
            agg_stats, {}, adj.sum(axis=1), None
        )
        atk_state = dict(zip(keys, state_vals))
        new_atk = attack.update_attack_state(
            atk_state, accept, observed, comp
        )
        return (new_flat,) + tuple(new_atk[k] for k in keys)

    args = tuple(cell.args) + tuple(jnp.asarray(atk0[k]) for k in keys)
    with _quiet_tracing():
        closed = jax.make_jaxpr(fn)(*args)
    ev = TaintEval(n)
    flat_args, _ = jax.tree_util.tree_flatten(args)
    arg_leaf_pos: List[int] = []
    for i, a in enumerate(args):
        arg_leaf_pos.extend([i] * len(jax.tree_util.tree_leaves(a)))
    pairs = []
    for leaf, pos in zip(flat_args, arg_leaf_pos):
        v = np.asarray(leaf)
        t = _tz(n, v.shape)
        if pos in bcast_args:  # the exchanged payload: row labels
            for lbl in range(n):
                t[lbl, lbl] = True
        pairs.append((v, t))
    with _quiet_tracing():
        outs = ev.eval_closed(closed, pairs)

    findings: List[Finding] = []
    out_t = outs[0][1]  # [L, N, P]
    self_t = out_t[np.arange(n), np.arange(n)]
    card = int((out_t.sum(axis=0) - self_t).max())
    influence = cell.agg.influence
    if influence is not None and influence.kind == "bounded":
        k_deg = int(np.asarray(cell.args[2]).sum(axis=1).max())
        bound = influence.bound(k_deg)
        if card > bound:
            findings.append(Finding(
                "MUR1003", path, line,
                f"[{rule}/{kind}] the composed aggregate+feedback step "
                f"mixes {card} neighbors into an output coordinate but "
                f"the rule declares a bound of {bound} — the adaptive "
                "feedback loop widened the rule's per-coordinate "
                "influence",
            ))
    for i, key in enumerate(keys):
        t = outs[1 + i][1]  # [L, N]
        tainted_rows = np.nonzero(t.any(axis=0))[0]
        bad = [int(r) for r in tainted_rows if not comp_np[r]]
        if bad:
            findings.append(Finding(
                "MUR1003", path, line,
                f"[{rule}/{kind}] updated attack state '{key}' carries "
                f"exchange taint at honest row(s) {bad} — the feedback "
                "update must be gated to the attacker's own rows",
            ))
    return findings


@_family
def check_adaptive_influence() -> List[Finding]:
    """MUR1003: feedback containment per adaptive attack, plus the
    composed aggregate+feedback influence sweep over
    ``AGGREGATORS x ADAPTIVE_ATTACK_KINDS`` (trace-only)."""
    from murmura_tpu.aggregation import AGGREGATORS
    from murmura_tpu.attacks.adaptive import ADAPTIVE_ATTACKS

    findings: List[Finding] = []
    kind_of = {
        "adaptive_alie": "alie",
        "adaptive_ipm": "ipm",
        "bisection": "gaussian",
    }
    for name in sorted(ADAPTIVE_ATTACKS):
        try:
            atk = _build_adaptive(kind_of.get(name, "gaussian"), 8)
            findings.extend(containment_findings(name, atk))
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            findings.append(Finding(
                "MUR1003", _ATK_PATH, 1,
                f"adaptive attack '{name}' crashed the containment "
                f"probe: {type(e).__name__}: {e}",
            ))
    for rule in sorted(AGGREGATORS):
        for kind in ADAPTIVE_ATTACK_KINDS:
            try:
                findings.extend(adaptive_influence_findings(rule, kind))
            except Exception as e:  # noqa: BLE001 — a crash IS the finding
                from murmura_tpu.analysis.flow import _rule_anchor

                path, line = _rule_anchor(rule)
                findings.append(Finding(
                    "MUR1003", path, line,
                    f"[{rule}/{kind}] adaptive influence probe crashed: "
                    f"{type(e).__name__}: {e}",
                ))
    return findings


# --------------------------------------------------------------------------
# Entry point
# --------------------------------------------------------------------------

_ADAPTIVE_MEMO: Optional[List[Finding]] = None


def check_adaptive(force: bool = False) -> List[Finding]:
    """Run MUR1000-1003; returns findings (empty = every adaptive-attack
    contract holds).  Memoized per process — the CLI, the battery
    pre-flight and the slow test gate share one sweep.  MUR1001 compiles
    and runs tiny programs (the check_durability cost profile), which is
    why the family runs only for the package-level check."""
    global _ADAPTIVE_MEMO
    if _ADAPTIVE_MEMO is not None and not force:
        return list(_ADAPTIVE_MEMO)

    from murmura_tpu.analysis.ir import _apply_suppressions

    findings: List[Finding] = []
    for fam_name, fam in ADAPTIVE_CHECK_FAMILIES.items():
        try:
            findings.extend(fam())
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            findings.append(Finding(
                "MUR1000", str(Path(__file__).resolve()), 1,
                f"adaptive check family '{fam_name}' crashed: "
                f"{type(e).__name__}: {e}",
            ))
    findings = _apply_suppressions(list(dict.fromkeys(findings)))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    _ADAPTIVE_MEMO = list(findings)
    return findings

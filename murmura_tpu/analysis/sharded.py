"""Param-axis sharding contracts (MUR1300-1303) — part of the default
package check (docs/PERFORMANCE.md "Param-axis sharding").

The ``"param"`` mesh axis (parallel/mesh.py) splits the flattened
parameter vector so every [N, P] round tensor is resident at
``N x P/shards`` per device.  Each link of that story carries an
invariant that must stay machine-checked or the memory-scaling claim
silently rots:

- **MUR1300 — sharded-P collective inventory.**  Compile each rule's
  canonical circulant/sparse cell on a ("seed", "nodes", "param") mesh
  with the [N, P] operands column-sharded: the lowered program's
  collectives must stay within the rule's DECLARED inventory for the
  mode plus at most ``all_reduce`` — the one new collective param
  sharding is allowed to add is the small scalar ``psum`` over the param
  groups (distance partials, norm partials).  Every all-reduce in the
  optimized HLO must be strictly smaller than the [N, P] class: a
  full-width gathered or reduced [N, P] tensor is exactly the resident
  copy the axis exists to eliminate.
- **MUR1301 — recompile-free sharded rounds.**  A param-sharded run
  (backend tpu, ``tpu.param_shards`` > 1 over the forced-host mesh)
  compiles once and every subsequent round is value-only
  (:class:`~murmura_tpu.analysis.sanitizers.CompileTracker`) — shard
  layout is program structure, round data is values.
- **MUR1302 — shards=1 bit-parity.**  ``build_round_program(...,
  param_shards=1)`` must be byte-identical to the default build: same
  traced jaxpr signature, ``flat_dim == model_dim`` (no pad), identical
  initial carried state.  The sharded code path may not perturb the
  unsharded program in any way.
- **MUR1303 — sharded execution parity.**  The MUR1300 cell's sharded
  program must produce the same aggregation output as the unsharded
  single-device cell to float-reassociation tolerance (the shard-local
  partial reductions regroup f32 sums; they must not change the math).

Probe-based rules (ubar, evidential_trust) are exempt from
MUR1300/MUR1303 with a documented reason (the MUR802-style limitation
pattern): their probe sweeps unravel every broadcast row into a full
model for the forward pass, so their sharded-P program necessarily
re-gathers rows — correct, but not psum-only, and not the regime param
sharding targets (a 50M-param model is not probe-evaluated N x N times
per round).

MUR1301 compiles and runs tiny programs (the check_durability cost
profile), so the family is memoized per process and runs by default only
for the package check; tests gate representative cells per tier-1 run
(tests/test_param_sharding.py) and negatives prove each probe can fire.
"""

import re
import warnings
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from murmura_tpu.analysis.lint import Finding

# Registry of check families in this module: name -> callable, scanned by
# analysis/ir.py's check_coverage so an unwired family is a MUR205
# finding (the flow.py/pipeline.py twin pattern).
SHARDED_CHECK_FAMILIES: Dict[str, Callable[[], List[Finding]]] = {}


def _family(fn):
    SHARDED_CHECK_FAMILIES[fn.__name__] = fn
    return fn


_PKG = Path(__file__).resolve().parent.parent
_MESH_PATH = str(_PKG / "parallel" / "mesh.py")
_ROUNDS_PATH = str(_PKG / "core" / "rounds.py")

# The exchange modes whose declared inventories the sharded-P contract
# extends: circulant (tpu.exchange: ppermute) and the sparse [k, N]
# edge-mask engine.  Dense mode already declares all_gather/all_reduce,
# so "ppermute-only on nodes" is not its contract to keep.
SHARDED_MODES: Tuple[str, ...] = ("circulant", "sparse")

# The canonical param-axis layout the probes compile on: 8 forced host
# devices as ("seed", "nodes", "param") = (1, 2, 4).
_SHARDS = 4
_NODE_AX = 2

# Probe-rule exemption (see module docstring).
SHARDED_EXEMPT: Dict[str, str] = {
    "ubar": "the probe sweep unravels each broadcast row into a full "
    "model per forward pass — the sharded-P program re-gathers rows by "
    "construction",
    "evidential_trust": "the trust probe sweep unravels each broadcast "
    "row into a full model per forward pass — the sharded-P program "
    "re-gathers rows by construction",
}


def _rule_anchor(rule: str) -> Tuple[str, int]:
    from murmura_tpu.analysis.ir import _rule_anchor as anchor

    return anchor(rule)


def _param_mesh():
    """The (1, 2, 4) check mesh, or None when the platform cannot give
    8 devices (the inventory is then unobservable — degrade with a
    warning, the MUR202 convention)."""
    import jax
    from jax.sharding import Mesh

    from murmura_tpu.analysis.ir import _ensure_host_devices

    _ensure_host_devices(8)
    devices = jax.devices()
    if len(devices) < _NODE_AX * _SHARDS:
        return None
    sel = np.array(devices[: _NODE_AX * _SHARDS])
    return Mesh(
        sel.reshape(1, _NODE_AX, _SHARDS), ("seed", "nodes", "param")
    )


# --------------------------------------------------------------------------
# MUR1300 + MUR1303 — sharded-P collective inventory and execution parity
# --------------------------------------------------------------------------

# LHS shapes of an HLO all-reduce (covers tuple-shaped variants): capture
# everything between "= " and " all-reduce(" and pull each "[dims]" out.
_AR_LINE_RE = re.compile(r"= (.{0,200}?) all-reduce(?:-start)?\(")
_DIMS_RE = re.compile(r"\[([0-9,]*)\]")


def oversized_all_reduces(hlo_text: str, max_elements: int) -> List[int]:
    """Element counts of all-reduce outputs exceeding ``max_elements`` —
    the "small scalar psum" half of the MUR1300 contract."""
    bad: List[int] = []
    for m in _AR_LINE_RE.finditer(hlo_text):
        for dims in _DIMS_RE.findall(m.group(1)):
            n = 1
            for d in filter(None, dims.split(",")):
                n *= int(d)
            if n > max_elements:
                bad.append(n)
    return bad


def _sharded_cell(rule: str, mode: str, mesh):
    """(jitted sharded fn, canonical cell) for one (rule, mode) cell on
    the param mesh: [N, dim] operands and state column-sharded, the cell
    traced under the param-axis scope so the chunk-alignment and pallas
    consumers see the layout (parallel/mesh.param_axis_scope)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    from murmura_tpu.analysis.ir import build_canonical
    from murmura_tpu.parallel.mesh import param_axis_scope

    prog = build_canonical(
        rule, 8, circulant=(mode == "circulant"), node_axis_sharded=True,
        sparse=(mode == "sparse"),
    )
    if prog.dim % _SHARDS:
        raise ValueError(
            f"canonical dim {prog.dim} not divisible by {_SHARDS} shards"
        )
    node_s = NamedSharding(mesh, P("nodes"))
    repl = NamedSharding(mesh, P())
    edge_s = NamedSharding(mesh, P(None, "nodes"))
    flat_s = NamedSharding(mesh, P("nodes", "param"))

    base = prog.arg_shardings(node_s, repl, edge_s)

    def flatten_spec(arg, spec):
        # [N, dim] leaves gain the param axis; everything else keeps the
        # canonical node-leading layout.
        def leaf_spec(a, s):
            if (
                hasattr(a, "ndim") and a.ndim == 2
                and a.shape[-1] == prog.dim
            ):
                return flat_s
            return s
        if isinstance(arg, dict):
            return {
                k: leaf_spec(arg[k], spec[k] if isinstance(spec, dict) else spec)
                for k in arg
            }
        return leaf_spec(arg, spec)

    in_s = tuple(
        flatten_spec(arg, spec) for arg, spec in zip(prog.args, base)
    )

    def scoped(*args):  # murmura: traced
        with param_axis_scope(mesh, prog.dim):
            return prog.fn(*args)

    return jax.jit(scoped, in_shardings=in_s), prog


def inventory_cell_findings(rule: str, mode: str, mesh=None) -> List[Finding]:
    """One (rule, mode) MUR1300 + MUR1303 cell (exposed per-cell so tests
    gate a subset — tests/test_param_sharding.py)."""
    import jax

    from murmura_tpu.analysis.ir import _HLO_COLLECTIVES, _COLL_RE

    path, line = _rule_anchor(rule)
    if mesh is None:
        mesh = _param_mesh()
    if mesh is None:
        warnings.warn(
            "MUR1300 sharded-P collective inventory is unobservable on "
            "this platform (needs >= 8 devices; set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8)",
            stacklevel=2,
        )
        return []
    jitted, prog = _sharded_cell(rule, mode, mesh)
    lowered = jitted.lower(*prog.args)
    txt = lowered.compile().as_text()
    findings: List[Finding] = []

    inventory = frozenset(
        _HLO_COLLECTIVES[m] for m in _COLL_RE.findall(txt)
    )
    declared = prog.agg.declared_collectives(mode)
    if declared is not None:
        allowed = frozenset(declared) | {"all_reduce"}
        stray = inventory - allowed
        if stray:
            findings.append(Finding(
                "MUR1300", path, line,
                f"[{rule}/{mode}] the param-sharded lowering contains "
                f"collective(s) {sorted(stray)} outside the declared "
                f"{sorted(declared)} + the all_reduce psum — param "
                "sharding may add ONLY the small scalar reduction over "
                "the param groups",
            ))
    limit = (prog.n * prog.dim) // 2
    big = oversized_all_reduces(txt, limit)
    if big:
        findings.append(Finding(
            "MUR1300", path, line,
            f"[{rule}/{mode}] the param-sharded lowering all-reduces "
            f"tensor(s) of {sorted(set(big), reverse=True)} elements "
            f"(limit {limit}, strictly below the [N, P] class) — a "
            "full-width reduction re-materializes exactly the resident "
            "copy the param axis exists to eliminate",
        ))

    # -- MUR1303: execution parity vs the unsharded single-device cell --
    out_sh = jax.device_get(jitted(*prog.args)[0])
    out_ref = jax.device_get(jax.jit(prog.fn)(*prog.args)[0])
    if not np.allclose(
        np.asarray(out_sh, np.float32), np.asarray(out_ref, np.float32),
        rtol=5e-5, atol=5e-6,
    ):
        err = float(np.max(np.abs(
            np.asarray(out_sh, np.float32) - np.asarray(out_ref, np.float32)
        )))
        findings.append(Finding(
            "MUR1303", path, line,
            f"[{rule}/{mode}] the param-sharded aggregation diverges "
            f"from the single-device program by {err:.2e} — shard-local "
            "partial reductions may regroup f32 sums but must not "
            "change the math",
        ))
    return findings


@_family
def check_sharded_inventory() -> List[Finding]:
    """MUR1300/MUR1303 over ``AGGREGATORS x SHARDED_MODES`` (compiles one
    sharded cell per pair; probe rules exempt with reason)."""
    from murmura_tpu.aggregation import AGGREGATORS

    mesh = _param_mesh()
    if mesh is None:
        warnings.warn(
            "MUR1300/MUR1303 are unobservable on this platform (needs "
            ">= 8 devices)", stacklevel=2,
        )
        return []
    findings: List[Finding] = []
    for rule in sorted(AGGREGATORS):
        if rule in SHARDED_EXEMPT:
            continue
        for mode in SHARDED_MODES:
            try:
                findings.extend(inventory_cell_findings(rule, mode, mesh))
            except Exception as e:  # noqa: BLE001 — a crash IS the finding
                path, line = _rule_anchor(rule)
                findings.append(Finding(
                    "MUR1300", path, line,
                    f"[{rule}/{mode}] sharded-P inventory probe crashed: "
                    f"{type(e).__name__}: {e}",
                ))
    return findings


# --------------------------------------------------------------------------
# MUR1301 — recompile-free sharded rounds (executable)
# --------------------------------------------------------------------------

# Representative cells (rule, topology mode): the full rule sweep is the
# MUR1300 trace pass; the executable recompile probe needs only one cell
# per storage layout of the adjacency input.
MUR1301_CELLS: Tuple[Tuple[str, str], ...] = (
    ("fedavg", "dense"),
    ("krum", "dense"),
    ("median", "sparse"),
)


def _cell_config(rule: str, mode: str, param_shards: int = _SHARDS):
    from murmura_tpu.analysis.ir import AGG_CASES
    from murmura_tpu.config import Config

    raw: Dict[str, Any] = {
        "experiment": {"name": f"sharded-{rule}-{mode}", "seed": 7,
                       "rounds": 5},
        "topology": {"type": "ring", "num_nodes": 8},
        "aggregation": {"algorithm": rule,
                        "params": dict(AGG_CASES.get(rule, {}))},
        "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.05},
        "data": {"adapter": "synthetic",
                 "params": {"num_samples": 40, "input_shape": [6],
                            "num_classes": 3}},
        "model": {"factory": "mlp",
                  "params": {"input_dim": 6, "hidden_dims": [8],
                             "num_classes": 3}},
        "backend": "tpu",
        "tpu": {"param_shards": param_shards, "param_dtype": "float32"},
    }
    if mode == "sparse":
        raw["topology"] = {"type": "exponential", "num_nodes": 8}
    elif mode != "dense":
        raise ValueError(f"unknown sharded mode {mode!r}")
    return Config.model_validate(raw)


def recompile_cell_findings(rule: str, mode: str = "dense") -> List[Finding]:
    """Run ONE (rule, mode) MUR1301 cell: 2 warmup rounds (the compile),
    then 3 more under CompileTracker — shard layout is program structure,
    round data is values, so nothing may recompile."""
    import jax

    from murmura_tpu.analysis.ir import _ensure_host_devices
    from murmura_tpu.analysis.sanitizers import track_compiles
    from murmura_tpu.utils.factories import build_network_from_config

    _ensure_host_devices(8)
    path, line = _rule_anchor(rule)
    if len(jax.devices()) < 2:
        warnings.warn(
            "MUR1301 is unobservable on this platform (needs >= 2 "
            "devices)", stacklevel=2,
        )
        return []
    net = build_network_from_config(_cell_config(rule, mode))
    net.train(rounds=2, verbose=False)
    with track_compiles() as tracker:
        net.train(rounds=3, verbose=False)
    if tracker.total:
        return [Finding(
            "MUR1301", path, line,
            f"[{rule}/{mode}] 3 param-sharded rounds after warmup "
            f"compiled {tracker.total} program(s) — the shard layout is "
            "program structure and round data is values, so sharded "
            "rounds must be value-only over one compiled program",
        )]
    return []


@_family
def check_sharded_recompile() -> List[Finding]:
    """MUR1301 over the representative cells (compiles and runs tiny
    sharded programs — the check_durability cost profile)."""
    findings: List[Finding] = []
    for rule, mode in MUR1301_CELLS:
        try:
            findings.extend(recompile_cell_findings(rule, mode))
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            path, line = _rule_anchor(rule)
            findings.append(Finding(
                "MUR1301", path, line,
                f"[{rule}/{mode}] sharded recompile probe crashed: "
                f"{type(e).__name__}: {e}",
            ))
    return findings


# --------------------------------------------------------------------------
# MUR1302 — shards=1 bit-parity (trace-only)
# --------------------------------------------------------------------------


def _tiny_programs(rule: str):
    """(default build, param_shards=1 build) of one rule's tiny round
    program — identical in every argument except the explicit shards."""
    import jax
    from jax.flatten_util import ravel_pytree

    from murmura_tpu.aggregation import build_aggregator
    from murmura_tpu.analysis.ir import AGG_CASES
    from murmura_tpu.core.rounds import build_round_program
    from murmura_tpu.data.base import FederatedArrays
    from murmura_tpu.models import make_mlp

    n, s = 5, 12
    rng = np.random.default_rng(0)
    data = FederatedArrays(
        x=rng.normal(size=(n, s, 6)).astype(np.float32),
        y=rng.integers(0, 3, size=(n, s)).astype(np.int32),
        mask=np.ones((n, s), np.float32),
        num_samples=np.full((n,), s),
        num_classes=3,
    )
    model = make_mlp(
        input_dim=6, hidden_dims=(8,), num_classes=3,
        evidential=(rule == "evidential_trust"),
    )
    flat0, _ = ravel_pytree(model.init(jax.random.PRNGKey(0)))
    agg = build_aggregator(
        rule, dict(AGG_CASES.get(rule, {})), model_dim=int(flat0.size),
        total_rounds=4,
    )
    common = dict(
        local_epochs=1, batch_size=8, lr=0.05, total_rounds=4, seed=7,
    )
    default = build_round_program(model, agg, data, **common)
    explicit = build_round_program(
        model, agg, data, param_shards=1, **common
    )
    return default, explicit


def bit_parity_findings(rule: str) -> List[Finding]:
    """One rule's MUR1302 probes: flat_dim == model_dim, identical
    initial carried state, identical traced jaxpr signature."""
    import jax
    import jax.numpy as jnp

    from murmura_tpu.analysis.ir import jaxpr_signature

    path, line = _rule_anchor(rule)
    default, explicit = _tiny_programs(rule)
    findings: List[Finding] = []
    if (
        explicit.flat_dim != explicit.model_dim
        or explicit.flat_dim != default.flat_dim
    ):
        findings.append(Finding(
            "MUR1302", _ROUNDS_PATH, 1,
            f"[{rule}] param_shards=1 padded the flat width "
            f"({explicit.flat_dim} vs model_dim {explicit.model_dim}) — "
            "the unsharded program must carry no pad",
        ))
    for k in set(default.init_agg_state) | set(explicit.init_agg_state):
        a = default.init_agg_state.get(k)
        b = explicit.init_agg_state.get(k)
        if a is None or b is None or not np.array_equal(
            np.asarray(a), np.asarray(b), equal_nan=True
        ):
            findings.append(Finding(
                "MUR1302", _ROUNDS_PATH, 1,
                f"[{rule}] initial carried state key '{k}' differs "
                "between the default and param_shards=1 builds",
            ))

    def trace(prog):
        n = prog.num_nodes
        adj = jnp.asarray(
            np.ones((n, n), np.float32) - np.eye(n, dtype=np.float32)
        )
        return jax.make_jaxpr(prog.train_step)(
            prog.init_params,
            {k: jnp.asarray(v) for k, v in prog.init_agg_state.items()},
            jax.random.PRNGKey(0),
            adj,
            jnp.zeros((n,), jnp.float32),
            jnp.asarray(0.0, jnp.float32),
            {k: jnp.asarray(v) for k, v in prog.data_arrays.items()},
        )

    if jaxpr_signature(trace(default)) != jaxpr_signature(trace(explicit)):
        findings.append(Finding(
            "MUR1302", _ROUNDS_PATH, 1,
            f"[{rule}] the param_shards=1 build traces a different "
            "program than the default build — the sharded code path must "
            "be byte-invisible at shards=1",
        ))
    return findings


@_family
def check_sharded_bit_parity() -> List[Finding]:
    """MUR1302 over every registered rule (trace-only: nothing
    compiles)."""
    from murmura_tpu.aggregation import AGGREGATORS

    findings: List[Finding] = []
    for rule in sorted(AGGREGATORS):
        try:
            findings.extend(bit_parity_findings(rule))
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            path, line = _rule_anchor(rule)
            findings.append(Finding(
                "MUR1302", path, line,
                f"[{rule}] shards=1 bit-parity probe crashed: "
                f"{type(e).__name__}: {e}",
            ))
    return findings


# --------------------------------------------------------------------------
# Entry point
# --------------------------------------------------------------------------

_SHARDED_MEMO: Optional[List[Finding]] = None


def check_sharded(force: bool = False) -> List[Finding]:
    """Run MUR1300-1303; returns findings (empty = every param-axis
    sharding contract holds).  Memoized per process — the CLI, the
    battery pre-flight and the test gate share one sweep."""
    global _SHARDED_MEMO
    if _SHARDED_MEMO is not None and not force:
        return list(_SHARDED_MEMO)

    from murmura_tpu.analysis.ir import _apply_suppressions

    findings: List[Finding] = []
    for fam_name, fam in SHARDED_CHECK_FAMILIES.items():
        try:
            findings.extend(fam())
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            findings.append(Finding(
                "MUR1300", str(Path(__file__).resolve()), 1,
                f"sharded check family '{fam_name}' crashed: "
                f"{type(e).__name__}: {e}",
            ))
    findings = _apply_suppressions(list(dict.fromkeys(findings)))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    _SHARDED_MEMO = list(findings)
    return findings

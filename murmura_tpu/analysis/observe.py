"""MUR1700-1703: the observability contracts (`murmura check --observe`;
docs/OBSERVABILITY.md "The fleet observability plane").

The observability plane (ISSUE 19) is only trustworthy if it is both
*honest* (a scrape never shows numbers the durable artifacts cannot
reproduce) and *inert* (watching a daemon cannot perturb its tenants).
Four executable probes on tiny-but-real cells:

- **MUR1700 — metrics↔ledger parity.**  Scrape an in-process daemon
  after a drained generation and independently replay the durable
  state (ledger records re-read from disk, event streams re-counted
  line by line): every scraped counter must equal the replay.
  Negative-tested by dropping an event after the scrape
  (tests/test_observability.py).
- **MUR1701 — scrape non-interference.**  Run a warm bucket's second
  generation under :class:`CompileTracker` while a polling thread
  hammers the read ops (metrics/ping/list): zero compiles, and every
  tenant history byte-identical to an unscraped reference daemon's —
  the MUR1602 pattern applied to observation instead of eviction.
- **MUR1702 — span well-formedness.**  Build trace spans from a real
  drained tenant stream: every span closed and parented, per-lane
  non-overlap, and the round spans summing to the stream's
  ``phase_times`` total within tolerance (telemetry/spans.py
  :func:`validate_spans` is the shared predicate the tests negative-
  test with doctored spans).
- **MUR1703 — schema discipline.**  The v2 event additions (``t``
  timestamps, ``serve`` lifecycle events) bumped
  ``MANIFEST_SCHEMA_VERSION`` with the MUR401-required migration note,
  AND a hand-built v1 stream (no ``t``) still renders through the
  report, the span builder, and the metrics fold.

Executable and compile-bearing (like check_serve), so the sweep is
memoized per process and runs by default only for the package-level
check.
"""

import json
import shutil
import tempfile
import threading
from pathlib import Path
from typing import Callable, Dict, List, Optional, Tuple

from murmura_tpu.analysis.durability import history_equal
from murmura_tpu.analysis.lint import Finding

# Registry of check families in this module: name -> callable, scanned by
# analysis/ir.py's check_coverage so an unwired family is a MUR205
# finding (the serve.py twin pattern).
OBSERVE_CHECK_FAMILIES: Dict[str, Callable[[], List[Finding]]] = {}


def _family(fn):
    OBSERVE_CHECK_FAMILIES[fn.__name__] = fn
    return fn


def _anchor(rel_path: str, needle: str) -> Tuple[str, int]:
    """Finding anchor: the line defining the machinery under contract."""
    path = Path(__file__).resolve().parents[1] / rel_path
    try:
        for i, line in enumerate(
            path.read_text(encoding="utf-8").splitlines(), 1
        ):
            if needle in line:
                return str(path), i
    except OSError:
        pass
    return str(path), 1


def _daemon(state_dir, capacity: int = 2, checkpoint_every: int = 1):
    from murmura_tpu.analysis.serve import _tenant_raw
    from murmura_tpu.config import Config
    from murmura_tpu.serve.daemon import ServeDaemon

    cfg = Config.model_validate({
        **_tenant_raw(seed=0, rounds=3),
        "serve": {"state_dir": str(state_dir), "capacity": capacity,
                  "checkpoint_every": checkpoint_every},
    })
    return ServeDaemon(cfg)


# --------------------------------------------------------------------------
# MUR1700 — metrics <-> ledger parity
# --------------------------------------------------------------------------


def metrics_ledger_parity(daemon, text: Optional[str] = None) -> List[str]:
    """Compare a scrape against an INDEPENDENT replay of durable state;
    returns human-readable discrepancies (empty = parity).

    The replay deliberately bypasses the registry fold: ledger records
    are re-read from disk and event streams re-counted line by line, so
    a fold bug, a dropped event, or a doctored counter all surface.
    ``text`` lets callers check a scrape taken earlier (the dropped-
    event negative test scrapes, mutates the stream, then re-checks)."""
    from murmura_tpu.telemetry.metrics import (
        parse_openmetrics,
        render_openmetrics,
    )

    if text is None:
        text = render_openmetrics(daemon.metrics_registry())
    parsed = parse_openmetrics(text)
    problems: List[str] = []

    def scraped(name: str, **labels) -> Optional[float]:
        key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        return parsed.get((name, key))

    # Ledger replay: records re-read from disk, not the in-memory dict.
    records = []
    for path in sorted((daemon.state_dir / "submissions").glob("*.json")):
        records.append(json.loads(path.read_text(encoding="utf-8")))
    got = scraped("murmura_serve_lifetime_total", counter="admissions")
    if got != len(records):
        problems.append(
            f"scraped admissions={got} but the durable ledger holds "
            f"{len(records)} submission records"
        )
    states: Dict[str, int] = {}
    for rec in records:
        states[rec["state"]] = states.get(rec["state"], 0) + 1
    for state, count in sorted(states.items()):
        got = scraped("murmura_serve_submissions", state=state)
        if got != count:
            problems.append(
                f"scraped submissions{{state={state}}}={got} but the "
                f"ledger replay counts {count}"
            )
    # Event-stream replay: raw line counts per tenant, no shared reader.
    for rec in records:
        run_dir = daemon.state_dir / "telemetry" / rec["id"]
        events_path = run_dir / "events.jsonl"
        if not events_path.exists():
            continue
        rounds = 0
        lifecycle: Dict[str, int] = {}
        for line in events_path.read_text(encoding="utf-8").splitlines():
            line = line.strip()
            if not line:
                continue
            try:
                event = json.loads(line)
            except json.JSONDecodeError:
                break  # torn tail: the valid prefix is the stream
            if event.get("type") == "round":
                rounds += 1
            elif event.get("type") == "serve":
                name = str(event.get("event"))
                lifecycle[name] = lifecycle.get(name, 0) + 1
        got = scraped("murmura_rounds_total", tenant=rec["id"])
        if (got or 0) != rounds:
            problems.append(
                f"scraped rounds_total{{tenant={rec['id']}}}={got} but the "
                f"stream replay counts {rounds} round events"
            )
        for name, count in sorted(lifecycle.items()):
            got = scraped(
                "murmura_serve_events_total", tenant=rec["id"], event=name,
            )
            if (got or 0) != count:
                problems.append(
                    f"scraped serve_events{{tenant={rec['id']}, "
                    f"event={name}}}={got} but the stream replay counts "
                    f"{count}"
                )
    return problems


@_family
def check_metrics_parity() -> List[Finding]:
    """MUR1700: a drained daemon's scrape equals the durable replay."""
    from murmura_tpu.analysis.serve import _tenant_raw

    path, line = _anchor("serve/daemon.py", "def metrics_registry")
    findings: List[Finding] = []
    tmp = Path(tempfile.mkdtemp(prefix="murmura-observe-1700-"))
    try:
        daemon = _daemon(tmp / "state")
        daemon.submit_config(_tenant_raw(seed=5))
        daemon.submit_config(_tenant_raw(seed=6))
        daemon.drain()
        for problem in metrics_ledger_parity(daemon):
            findings.append(Finding(
                "MUR1700", path, line,
                f"metrics scrape disagrees with the durable replay: "
                f"{problem} — a scraped counter must be reconstructible "
                "from the ledger + event streams alone",
            ))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return findings


# --------------------------------------------------------------------------
# MUR1701 — scrape non-interference
# --------------------------------------------------------------------------


def interference_problems(
    compiles: int,
    history_pairs: List[Tuple[str, dict, dict]],
) -> List[str]:
    """The MUR1701 verdict: ``compiles`` observed during the scraped
    generation and (sub_id, scraped_history, reference_history) pairs.
    Shared with the negative tests."""
    problems: List[str] = []
    if compiles:
        problems.append(
            f"{compiles} XLA compilation(s) during the scraped "
            "generation — the read ops must not touch compiled state"
        )
    for sub_id, scraped_hist, ref_hist in history_pairs:
        if not history_equal(scraped_hist, ref_hist):
            problems.append(
                f"tenant {sub_id}'s history diverges from the unscraped "
                "reference — observation perturbed the computation"
            )
    return problems


@_family
def check_scrape_noninterference() -> List[Finding]:
    """MUR1701: a metrics/ping/list polling loop against a running
    daemon causes zero recompiles and leaves tenant histories
    byte-identical to an unscraped reference."""
    from murmura_tpu.analysis.sanitizers import track_compiles
    from murmura_tpu.analysis.serve import _tenant_raw

    path, line = _anchor("serve/daemon.py", "def handle_request")
    findings: List[Finding] = []
    tmp = Path(tempfile.mkdtemp(prefix="murmura-observe-1701-"))
    try:
        def run(state: Path, scrape: bool) -> dict:
            daemon = _daemon(state)
            daemon.submit_config(_tenant_raw(seed=5))
            daemon.submit_config(_tenant_raw(seed=6))
            daemon.drain()  # generation 1 warms the bucket's one compile
            gen2_ids = [
                daemon.submit_config(_tenant_raw(seed=7))["id"],
                daemon.submit_config(_tenant_raw(seed=8))["id"],
            ]
            stop = threading.Event()

            def poll():
                while not stop.is_set():
                    daemon.handle_request({"op": "metrics"})
                    daemon.handle_request({"op": "ping"})
                    daemon.handle_request({"op": "list"})

            poller = None
            if scrape:
                poller = threading.Thread(target=poll, daemon=True)
                poller.start()
            try:
                with track_compiles() as tracker:
                    daemon.drain()  # generation 2: must stay warm
            finally:
                stop.set()
                if poller is not None:
                    poller.join(timeout=10.0)
            return {
                "compiles": tracker.total,
                "ledger": {i: daemon._ledger[i] for i in gen2_ids},
            }

        ref = run(tmp / "ref", scrape=False)
        scraped = run(tmp / "scraped", scrape=True)
        pairs = [
            (i,
             scraped["ledger"][i].get("history"),
             ref["ledger"][i].get("history"))
            for i in sorted(ref["ledger"])
        ]
        for problem in interference_problems(scraped["compiles"], pairs):
            findings.append(Finding(
                "MUR1701", path, line,
                f"scrape non-interference violated: {problem} (polling "
                "metrics/ping/list mid-generation must be invisible to "
                "tenants — the MUR1602 pattern for observation)",
            ))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return findings


# --------------------------------------------------------------------------
# MUR1702 — span well-formedness
# --------------------------------------------------------------------------


@_family
def check_span_wellformedness() -> List[Finding]:
    """MUR1702: spans built from a real drained tenant stream are
    closed, parented, per-lane non-overlapping, and their round lane
    sums to the stream's phase_times total."""
    from murmura_tpu.analysis.serve import _tenant_raw
    from murmura_tpu.telemetry.spans import (
        LANE_ROUNDS,
        build_spans,
        validate_spans,
    )
    from murmura_tpu.telemetry.writer import events_of_type

    path, line = _anchor("telemetry/spans.py", "def build_spans")
    findings: List[Finding] = []
    tmp = Path(tempfile.mkdtemp(prefix="murmura-observe-1702-"))
    try:
        daemon = _daemon(tmp / "state")
        daemon.submit_config(_tenant_raw(seed=5))
        daemon.submit_config(_tenant_raw(seed=6))
        daemon.drain()
        for sub_id in sorted(daemon._ledger):
            run_dir = daemon.state_dir / "telemetry" / sub_id
            spans = build_spans(run_dir)
            phase_events = events_of_type(run_dir, "phase_times")
            phase_total = sum(float(e.get("wall_s", 0.0))
                              for e in phase_events)
            for problem in validate_spans(spans, phase_total=phase_total):
                findings.append(Finding(
                    "MUR1702", path, line,
                    f"tenant {sub_id}: {problem}",
                ))
            round_spans = [s for s in spans if s["tid"] == LANE_ROUNDS]
            if len(round_spans) != len(phase_events):
                findings.append(Finding(
                    "MUR1702", path, line,
                    f"tenant {sub_id}: {len(round_spans)} round spans for "
                    f"{len(phase_events)} phase_times events — every "
                    "accounted round must appear in the trace",
                ))
            names = {s["name"] for s in spans}
            for required in ("run", "queued", "generation"):
                if required not in names:
                    findings.append(Finding(
                        "MUR1702", path, line,
                        f"tenant {sub_id}: no {required!r} span — the "
                        "serve lifecycle must be visible in the trace",
                    ))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return findings


# --------------------------------------------------------------------------
# MUR1703 — schema discipline
# --------------------------------------------------------------------------


def schema_discipline_problems(version: int, docs_text: str) -> List[str]:
    """The static half of MUR1703, shared with the negative tests."""
    problems: List[str] = []
    if version < 2:
        problems.append(
            f"MANIFEST_SCHEMA_VERSION is {version} but the v2 event "
            "additions (per-event `t`, `serve` lifecycle events) are in "
            "the stream — new event types require a schema bump"
        )
    if f"### v{version}" not in docs_text:
        problems.append(
            f"docs/OBSERVABILITY.md has no '### v{version}' migration "
            "note for the current schema (the MUR401 discipline)"
        )
    return problems


@_family
def check_schema_discipline() -> List[Finding]:
    """MUR1703: the v2 bump carries its migration note AND a v1 stream
    (no per-event ``t``) still renders through the report, the span
    builder, and the metrics fold."""
    from murmura_tpu.telemetry.metrics import MetricsRegistry, fold_run_events
    from murmura_tpu.telemetry.report import build_report
    from murmura_tpu.telemetry.schema import MANIFEST_SCHEMA_VERSION
    from murmura_tpu.telemetry.spans import build_spans, validate_spans

    path, line = _anchor("telemetry/schema.py", "MANIFEST_SCHEMA_VERSION =")
    findings: List[Finding] = []
    docs = Path(__file__).resolve().parents[2] / "docs" / "OBSERVABILITY.md"
    try:
        docs_text = docs.read_text(encoding="utf-8")
    except OSError:
        docs_text = ""
    for problem in schema_discipline_problems(
        MANIFEST_SCHEMA_VERSION, docs_text,
    ):
        findings.append(Finding("MUR1703", path, line, problem))

    # Old streams still render: a hand-built v1 run (no `t` anywhere).
    tmp = Path(tempfile.mkdtemp(prefix="murmura-observe-1703-"))
    try:
        run_dir = tmp / "v1run"
        run_dir.mkdir(parents=True)
        (run_dir / "manifest.json").write_text(json.dumps({
            "schema_version": 1, "kind": "run", "run_id": "v1-probe",
            "created_unix": 1000.0, "finalized": True,
            "finalized_unix": 1004.0, "counters": {},
            "history": {"round": [1, 2], "mean_accuracy": [0.5, 0.6],
                        "mean_loss": [1.0, 0.9]},
        }))
        v1_events = [
            {"type": "run", "seq": 0, "status": "started"},
            {"type": "round", "seq": 1, "round": 1,
             "metrics": {"accuracy": [0.5]}},
            {"type": "phase_times", "seq": 2, "round": 0,
             "mode": "per_round", "wall_s": 0.5},
            {"type": "round", "seq": 3, "round": 2,
             "metrics": {"accuracy": [0.6]}},
            {"type": "phase_times", "seq": 4, "round": 1,
             "mode": "per_round", "wall_s": 0.5},
        ]
        (run_dir / "events.jsonl").write_text(
            "".join(json.dumps(e) + "\n" for e in v1_events)
        )
        try:
            build_report(run_dir)
            spans = build_spans(run_dir)
            problems = validate_spans(spans, phase_total=1.0)
            if problems:
                findings.append(Finding(
                    "MUR1703", path, line,
                    "a v1 stream (no per-event `t`) builds malformed "
                    f"spans: {problems[0]} — old streams must still "
                    "render after the v2 bump",
                ))
            reg = MetricsRegistry()
            fold_run_events(reg, run_dir)
            if reg.value("murmura_rounds") != 2:
                findings.append(Finding(
                    "MUR1703", path, line,
                    "the metrics fold miscounts a v1 stream "
                    f"({reg.value('murmura_rounds')} rounds for 2 round "
                    "events) — old streams must still fold",
                ))
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            findings.append(Finding(
                "MUR1703", path, line,
                f"rendering a v1 stream crashed ({type(e).__name__}: {e}) "
                "— the v2 readers must tolerate v1 artifacts",
            ))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return findings


# --------------------------------------------------------------------------
# Entry point
# --------------------------------------------------------------------------

_OBSERVE_MEMO: Optional[List[Finding]] = None


def check_observe(force: bool = False) -> List[Finding]:
    """Run MUR1700-1703; returns findings (empty = scrapes are honest
    replays of durable state, observation is invisible to tenants,
    traces are well-formed and reconcile with phase accounting, and the
    schema bump is disciplined).  Memoized per process; compile-bearing,
    so it runs by default only for the package-level check (like
    check_serve)."""
    global _OBSERVE_MEMO
    if _OBSERVE_MEMO is not None and not force:
        return list(_OBSERVE_MEMO)

    from murmura_tpu.analysis.ir import _apply_suppressions

    findings: List[Finding] = []
    for fam_name, fam in OBSERVE_CHECK_FAMILIES.items():
        try:
            findings.extend(fam())
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            findings.append(Finding(
                "MUR1700", str(Path(__file__).resolve()), 1,
                f"observe check family '{fam_name}' crashed: "
                f"{type(e).__name__}: {e}",
            ))
    findings = _apply_suppressions(list(dict.fromkeys(findings)))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    _OBSERVE_MEMO = list(findings)
    return findings

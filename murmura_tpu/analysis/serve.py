"""MUR1600-1603: the serving contract (`murmura check --serve`;
docs/ROBUSTNESS.md "Serving").

The serve layer's whole pitch is that multiplexing experiments through
shared compiled buckets changes NOTHING about their numbers.  Four
executable probes, each on a tiny-but-real cell (5 nodes, an 83-param
MLP, 2-3 rounds):

- **MUR1600 — bucket-key soundness.**  Plan a small grid and re-derive
  every cell's jaxpr skeleton INDEPENDENTLY (its own single-member
  program, its own trace).  Two cells share a bucket ⇔ their skeletons
  are structurally equal: every cell's trace must equal its bucket's,
  and no two buckets may share a skeleton.  (The planner refuses
  colliding classes loud — scheduler.plan_grid — so the ⇔ holds on
  every grid that actually runs; this probe verifies the half the
  refusal cannot: that the per-class representative trace speaks for
  every member cell.)
- **MUR1601 — zero recompiles across admissions.**  Run a warm bucket
  through generation 1, then admit a NEW tenant set
  (``reset_run(member_programs=...)``) and run generation 2 under
  :class:`~murmura_tpu.analysis.sanitizers.CompileTracker`.  One compile
  paid at bucket birth, zero forever after — a recompiling admission
  would stall every co-tenant behind XLA.
- **MUR1602 — frozen-lane non-interference.**  Freeze one member of a
  two-member gang mid-run (the daemon's eviction); the survivor's
  history must be byte-identical to a reference gang that never had the
  neighbor at all (same compiled batch via ``min_batch``).  A vmap lane
  can no more perturb its neighbor than a padding lane can — this probe
  keeps that true as the lane machinery evolves.
- **MUR1603 — resume completeness.**  Submit two tenants to an
  in-process daemon, kill it mid-generation (after the first cadence
  snapshot), rebuild a fresh daemon over the same ``state_dir``,
  ``recover()``: every submission must reach a terminal state with a
  history byte-identical to an uninterrupted reference daemon's.

Executable and compile-bearing (like check_durability), so the sweep is
memoized per process and runs by default only for the package-level
check; tests gate representatives per tier-1 run
(tests/test_serve_daemon.py) with negatives for each rule.
"""

import shutil
import tempfile
from pathlib import Path
from typing import Callable, Dict, List, Optional, Tuple

from murmura_tpu.analysis.durability import history_equal
from murmura_tpu.analysis.lint import Finding

# Registry of check families in this module: name -> callable, scanned by
# analysis/ir.py's check_coverage so an unwired family is a MUR205
# finding (the durability.py twin pattern).
SERVE_CHECK_FAMILIES: Dict[str, Callable[[], List[Finding]]] = {}


def _family(fn):
    SERVE_CHECK_FAMILIES[fn.__name__] = fn
    return fn


def _anchor(rel_path: str, needle: str) -> Tuple[str, int]:
    """Finding anchor: the line defining the machinery under contract."""
    path = Path(__file__).resolve().parents[1] / rel_path
    try:
        for i, line in enumerate(
            path.read_text(encoding="utf-8").splitlines(), 1
        ):
            if needle in line:
                return str(path), i
    except OSError:
        pass
    return str(path), 1


def _tenant_raw(seed: int, rounds: int = 3, rule: str = "fedavg") -> dict:
    """One tenant/cell config dict — the durability._cell_config tiny
    cell, parameterized by seed so daemon probes can submit several."""
    return {
        "experiment": {"name": f"serve-probe-{seed}", "seed": seed,
                       "rounds": rounds},
        "topology": {"type": "ring", "num_nodes": 5},
        "aggregation": {"algorithm": rule},
        "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.05},
        "data": {"adapter": "synthetic",
                 "params": {"num_samples": 40, "input_shape": [6],
                            "num_classes": 3}},
        "model": {"factory": "mlp",
                  "params": {"input_dim": 6, "hidden_dims": [8],
                             "num_classes": 3}},
        "backend": "simulation",
    }


def _grid_config():
    from murmura_tpu.config import Config

    return Config.model_validate({
        **_tenant_raw(seed=7, rounds=2),
        "grid": {"rules": ["fedavg", "median"], "attacks": ["gaussian"],
                 "topologies": ["dense"], "strengths": [0.0, 1.0],
                 "seeds": [7]},
    })


@_family
def check_bucket_key_soundness() -> List[Finding]:
    """MUR1600: same bucket ⇔ structurally equal skeletons, verified by
    re-tracing every cell independently of the planner's representative."""
    from murmura_tpu.config.schema import GridConfig
    from murmura_tpu.serve.scheduler import cell_skeleton, plan_grid

    path, line = _anchor("serve/scheduler.py", "def plan_grid")
    config = _grid_config()
    g = config.grid or GridConfig()
    buckets = plan_grid(config, g)
    findings: List[Finding] = []
    seen: Dict[Tuple[str, ...], str] = {}
    for bucket in buckets:
        prior = seen.get(bucket.skeleton)
        if prior is not None:
            findings.append(Finding(
                "MUR1600", path, line,
                f"buckets {prior} and {bucket.key} carry structurally "
                "equal skeletons — cells in different buckets must have "
                "unequal skeletons (the planner's collision refusal is "
                "broken)",
            ))
        seen[bucket.skeleton] = bucket.key
        for cell in bucket.cells:
            independent = cell_skeleton(config, g, cell)
            if independent != bucket.skeleton:
                findings.append(Finding(
                    "MUR1600", path, line,
                    f"cell {cell.cell_id} traces a skeleton different "
                    f"from its bucket {bucket.key}'s — the per-class "
                    "representative does not speak for this cell, so the "
                    "bucket would hide a recompile (or worse, run the "
                    "wrong math)",
                ))
    return findings


@_family
def check_admission_recompile() -> List[Finding]:
    """MUR1601: generation 2 admitted into a warm bucket compiles
    nothing."""
    from murmura_tpu.analysis.sanitizers import track_compiles
    from murmura_tpu.config import Config
    from murmura_tpu.core.gang import GangMember
    from murmura_tpu.utils.factories import (
        build_gang_from_config,
        build_gang_member_programs,
    )

    path, line = _anchor("core/gang.py", "def _admit_members")
    raw = _tenant_raw(seed=7, rounds=2)
    raw["sweep"] = {"members": [{"seed": 7, "lr": 0.05}]}
    template = Config.model_validate(raw)
    gang = build_gang_from_config(template, min_batch=4)
    gang.train(rounds=2, eval_every=1)  # generation 1: pays the compile

    findings: List[Finding] = []
    gen2 = [GangMember(seed=21, lr=0.05), GangMember(seed=22, lr=0.02)]
    progs = []
    for m in gen2:
        t_raw = _tenant_raw(seed=m.seed, rounds=2)
        t_raw["training"]["lr"] = m.lr
        progs.append(build_gang_member_programs(
            Config.model_validate(t_raw), [m]
        )[0])
    with track_compiles() as tracker:
        gang.reset_run(gen2, member_programs=progs)
        gang.train(rounds=2, eval_every=1)
    if tracker.total:
        findings.append(Finding(
            "MUR1601", path, line,
            f"admitting generation 2 into a warm bucket compiled "
            f"{tracker.total} program(s) — admission must be a value-only "
            "splice into the frozen lanes (fixed [B, ...] shapes via "
            "min_batch); a recompiling admission stalls every co-tenant",
        ))
    return findings


@_family
def check_frozen_lane_interference() -> List[Finding]:
    """MUR1602: freezing a lane mid-run leaves the survivor's history
    byte-identical to a gang that never had the neighbor."""
    from murmura_tpu.config import Config
    from murmura_tpu.utils.factories import build_gang_from_config

    path, line = _anchor("core/gang.py", "def freeze_member")
    raw = _tenant_raw(seed=7, rounds=3)
    raw["sweep"] = {"members": [{"seed": 7, "lr": 0.05},
                                {"seed": 8, "lr": 0.05}]}
    pair = build_gang_from_config(Config.model_validate(raw))
    pair.train(rounds=1, eval_every=1)
    pair.freeze_member(1, "mur1602-probe")
    pair.train(rounds=2, eval_every=1)

    solo_raw = _tenant_raw(seed=7, rounds=3)
    solo_raw["sweep"] = {"members": [{"seed": 7, "lr": 0.05}]}
    # min_batch matches the pair gang's compiled batch, so the survivor
    # and the reference run the SAME program shape — lane count is the
    # only difference under test.
    solo = build_gang_from_config(
        Config.model_validate(solo_raw), min_batch=2,
    )
    solo.train(rounds=3, eval_every=1)

    findings: List[Finding] = []
    if not history_equal(pair.histories[0], solo.histories[0]):
        diverged = sorted(
            k for k in set(pair.histories[0]) | set(solo.histories[0])
            if not history_equal(
                pair.histories[0].get(k), solo.histories[0].get(k)
            )
        )
        findings.append(Finding(
            "MUR1602", path, line,
            f"survivor history diverges from the unadmitted reference in "
            f"{diverged} after freezing the neighbor lane — eviction must "
            "not perturb co-tenants (a frozen lane is a padding lane)",
        ))
    frozen_len = len(pair.histories[1].get("round", []))
    if frozen_len > 1:
        findings.append(Finding(
            "MUR1602", path, line,
            f"frozen lane kept recording ({frozen_len} rounds after a "
            "freeze at round 1) — freeze_member must stop the lane's "
            "history at the freeze round",
        ))
    return findings


@_family
def check_resume_completeness() -> List[Finding]:
    """MUR1603: kill the daemon mid-generation, recover a fresh one from
    the same state_dir — every submission terminal, histories
    byte-identical to an uninterrupted reference daemon."""
    import murmura_tpu.core.gang as gang_mod
    from murmura_tpu.config import Config
    from murmura_tpu.serve.daemon import TERMINAL_STATES, ServeDaemon

    path, line = _anchor("serve/daemon.py", "def recover")
    findings: List[Finding] = []
    tmp = Path(tempfile.mkdtemp(prefix="murmura-serve-check-"))
    try:
        def daemon(state: Path) -> ServeDaemon:
            cfg = Config.model_validate({
                **_tenant_raw(seed=0, rounds=3),
                "serve": {"state_dir": str(state), "capacity": 2,
                          "checkpoint_every": 1},
            })
            return ServeDaemon(cfg)

        ref = daemon(tmp / "ref")
        ref.submit_config(_tenant_raw(seed=5))
        ref.submit_config(_tenant_raw(seed=6))
        ref.drain()

        victim = daemon(tmp / "crash")
        victim.submit_config(_tenant_raw(seed=5))
        victim.submit_config(_tenant_raw(seed=6))

        class _Kill(BaseException):
            """Out-of-band like a real SIGKILL: no handler catches it."""

        orig_train = gang_mod.GangNetwork.train
        def dying_train(self, rounds, **kw):
            orig_train(self, rounds=1, **kw)  # round 1 + cadence snapshot
            raise _Kill()
        gang_mod.GangNetwork.train = dying_train
        try:
            victim.drain()
        except _Kill:
            pass
        finally:
            gang_mod.GangNetwork.train = orig_train
        del victim  # the process is gone; only state_dir survives

        revived = daemon(tmp / "crash")
        revived.recover()
        revived.drain()

        for (rid, ref_rec), (vid, rec) in zip(
            sorted(ref._ledger.items()), sorted(revived._ledger.items())
        ):
            if rec["state"] not in TERMINAL_STATES:
                findings.append(Finding(
                    "MUR1603", path, line,
                    f"submission {vid} is still '{rec['state']}' after "
                    "daemon kill + recover + drain — every submitted run "
                    "must reach a terminal state",
                ))
                continue
            if rec["state"] != "done":
                findings.append(Finding(
                    "MUR1603", path, line,
                    f"submission {vid} recovered to '{rec['state']}' "
                    f"({rec.get('error')}) — the interrupted generation "
                    "did not resume",
                ))
                continue
            if not history_equal(rec.get("history"), ref_rec.get("history")):
                findings.append(Finding(
                    "MUR1603", path, line,
                    f"submission {vid} resumed to a history diverging "
                    f"from the uninterrupted reference {rid} — the "
                    "recovered generation is not crash-equivalent "
                    "(MUR901 machinery broken on the serve path)",
                ))
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return findings


# --------------------------------------------------------------------------
# Entry point
# --------------------------------------------------------------------------

_SERVE_MEMO: Optional[List[Finding]] = None


def check_serve(force: bool = False) -> List[Finding]:
    """Run MUR1600-1603; returns findings (empty = bucketing is sound,
    admissions never recompile, eviction never perturbs survivors, and a
    killed daemon completes everything it accepted).  Memoized per
    process; compile-bearing, so it runs by default only for the
    package-level check (like check_durability)."""
    global _SERVE_MEMO
    if _SERVE_MEMO is not None and not force:
        return list(_SERVE_MEMO)

    from murmura_tpu.analysis.ir import _apply_suppressions

    findings: List[Finding] = []
    for fam_name, fam in SERVE_CHECK_FAMILIES.items():
        try:
            findings.extend(fam())
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            findings.append(Finding(
                "MUR1600", str(Path(__file__).resolve()), 1,
                f"serve check family '{fam_name}' crashed: "
                f"{type(e).__name__}: {e}",
            ))
    findings = _apply_suppressions(list(dict.fromkeys(findings)))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    _SERVE_MEMO = list(findings)
    return findings

"""Cross-layer contract checks (MUR101-103).

The framework's component wiring spans three layers that must stay in
bijection but have no shared source of truth: the runtime registries
(``aggregation.AGGREGATORS`` / ``attacks.ATTACKS`` /
``topology.generators.TOPOLOGY_TYPES``), the config schema's ``Literal``
enums (config/schema.py — what a YAML file may name), and the test suite
(which names must each have at least one test referencing them).  A rule
added to the registry but not the schema is unreachable from configs; a
schema value without a registry entry is a guaranteed runtime failure; a
name in both with no test is a rule whose semantics nothing pins.

MUR103 executes every topology generator on small instances and verifies
the emitted adjacency has a zero diagonal — the non-local invariant the
aggregation rules' neighbor masks historically leaned on (round-5 verdict;
robust_stats.py now also zeroes locally as the first line of defense).

These checks import the live modules rather than parsing their ASTs: the
contract is between the actual runtime artifacts, and an import failure is
itself a finding.
"""

import typing
from pathlib import Path
from typing import Iterable, List, Optional, Set

from murmura_tpu.analysis.lint import Finding

# Topology instances MUR103 builds: every TOPOLOGY_TYPES entry must appear
# (check_contracts emits a MUR103 finding for any entry missing here) at
# more than one size, including sizes that exercise the generators' edge
# handling (odd-k bump, k >= n degeneration to fully connected, ER
# isolated-node fixup).
_TOPOLOGY_CASES = {
    "ring": [{"num_nodes": 2}, {"num_nodes": 9}],
    "fully": [{"num_nodes": 2}, {"num_nodes": 8}],
    "erdos": [
        {"num_nodes": 8, "p": 0.05, "seed": 7},
        {"num_nodes": 12, "p": 0.9, "seed": 3},
    ],
    "k-regular": [
        {"num_nodes": 10, "k": 3},  # odd k bumped to 4
        {"num_nodes": 4, "k": 6},  # k >= n: fully-connected degeneration
    ],
    # Sparse offset-list families (topology/sparse.py): non-power-of-two
    # sizes exercise the exponential-offset dedupe/degenerate handling.
    "exponential": [{"num_nodes": 2}, {"num_nodes": 9}, {"num_nodes": 12}],
    "one_peer": [{"num_nodes": 5}, {"num_nodes": 8}],
}


def _literal_values(annotation) -> Set[str]:
    """String values of a (possibly Optional-wrapped) ``Literal`` annotation."""
    values: Set[str] = set()
    for arg in typing.get_args(annotation):
        if isinstance(arg, str):
            values.add(arg)
        elif arg is not type(None):
            values |= _literal_values(arg)
    return values


def _schema_enum(field_name: str, model) -> Set[str]:
    return _literal_values(model.model_fields[field_name].annotation)


def _sync_findings(
    kind: str,
    registry_names: Set[str],
    schema_names: Set[str],
    registry_path: str,
    schema_path: str,
) -> Iterable[Finding]:
    """MUR101: registry and schema enum must name the same components."""
    for name in sorted(registry_names - schema_names):
        yield Finding(
            "MUR101", registry_path, 1,
            f"{kind} '{name}' is in the runtime registry but missing from "
            "the config schema enum (config/schema.py) — it is unreachable "
            "from any config file",
        )
    for name in sorted(schema_names - registry_names):
        yield Finding(
            "MUR101", schema_path, 1,
            f"{kind} '{name}' is in the config schema enum but has no "
            "runtime registry entry — any config naming it fails at build "
            "time",
        )


def _coverage_findings(
    kind: str, names: Set[str], tests_src: str, registry_path: str
) -> Iterable[Finding]:
    """MUR102: every registered component name must appear as a string in
    the test suite — the cheapest machine-checkable proxy for 'this rule
    has at least one test pinning its semantics'."""
    if not tests_src:
        return
    for name in sorted(names):
        if f'"{name}"' not in tests_src and f"'{name}'" not in tests_src:
            yield Finding(
                "MUR102", registry_path, 1,
                f"{kind} '{name}' never appears as a string literal in "
                "tests/ — add a test exercising it by its registry name",
            )


def _tests_dir() -> Optional[Path]:
    """The repo's tests/ directory, if running from a source checkout."""
    pkg_root = Path(__file__).resolve().parent.parent
    tests = pkg_root.parent / "tests"
    return tests if tests.is_dir() else None


def check_contracts(tests_dir: Optional[Path] = None) -> List[Finding]:
    """Run MUR101/102/103; returns findings (empty = all contracts hold)."""
    import numpy as np

    pkg = Path(__file__).resolve().parent.parent
    try:
        from murmura_tpu.aggregation import AGGREGATORS
        from murmura_tpu.attacks import ATTACKS
        from murmura_tpu.config import schema
        from murmura_tpu.topology import generators
    except Exception as e:  # noqa: BLE001 — the import failure IS the finding
        return [Finding(
            "MUR100", str(pkg), 1,
            "contract checks could not import the runtime registries "
            f"({type(e).__name__}: {e}) — the package is broken at a level "
            "below the cross-layer contracts",
        )]

    findings: List[Finding] = []
    schema_path = str(pkg / "config" / "schema.py")
    agg_path = str(pkg / "aggregation" / "__init__.py")
    atk_path = str(pkg / "attacks" / "__init__.py")
    topo_path = str(pkg / "topology" / "generators.py")

    # -- MUR101: registry <-> schema enum bijection -------------------------
    findings += _sync_findings(
        "aggregation rule", set(AGGREGATORS),
        _schema_enum("algorithm", schema.AggregationConfig),
        agg_path, schema_path,
    )
    findings += _sync_findings(
        "attack", set(ATTACKS),
        _schema_enum("type", schema.AttackConfig),
        atk_path, schema_path,
    )
    findings += _sync_findings(
        "topology", set(generators.TOPOLOGY_TYPES),
        _schema_enum("type", schema.TopologyConfig),
        topo_path, schema_path,
    )

    # -- MUR102: per-component test coverage --------------------------------
    tests = tests_dir if tests_dir is not None else _tests_dir()
    tests_src = ""
    if tests is not None:
        tests_src = "\n".join(
            f.read_text() for f in sorted(tests.rglob("*.py"))
        )
    for kind, names, path in (
        ("aggregation rule", set(AGGREGATORS), agg_path),
        ("attack", set(ATTACKS), atk_path),
        ("topology", set(generators.TOPOLOGY_TYPES), topo_path),
    ):
        findings += _coverage_findings(kind, names, tests_src, path)

    # -- MUR103: every generator emits a zero-diagonal adjacency ------------
    # A registered type with no cases would make this check vacuous for it,
    # so the case-table sync is itself a finding (not just a test assert).
    for topo_type in sorted(set(generators.TOPOLOGY_TYPES) - set(_TOPOLOGY_CASES)):
        findings.append(Finding(
            "MUR103", topo_path, 1,
            f"topology '{topo_type}' has no _TOPOLOGY_CASES entry "
            "(analysis/contracts.py) — its zero-diagonal invariant is never "
            "executed; add small-instance cases",
        ))
    for topo_type, cases in _TOPOLOGY_CASES.items():
        for kwargs in cases:
            try:
                topo = generators.create_topology(topo_type, **kwargs)
            except Exception as e:  # noqa: BLE001 — a crash IS the finding
                findings.append(Finding(
                    "MUR103", topo_path, 1,
                    f"topology generator '{topo_type}' raised on {kwargs}: "
                    f"{type(e).__name__}: {e}",
                ))
                continue
            raw = np.asarray(topo.adjacency)
            if raw.diagonal().any():
                findings.append(Finding(
                    "MUR103", topo_path, 1,
                    f"topology '{topo_type}' with {kwargs} emitted self-"
                    "edges (non-zero adjacency diagonal) — aggregation "
                    "neighbor masks assume a zero diagonal",
                ))
    # The mobility model's per-round G^t carries the same invariant.
    dyn_path = str(pkg / "topology" / "dynamic.py")
    try:
        from murmura_tpu.topology.dynamic import MobilityModel
    except Exception as e:  # noqa: BLE001 — the import failure IS the finding
        findings.append(Finding(
            "MUR100", dyn_path, 1,
            f"topology.dynamic failed to import ({type(e).__name__}: {e}) — "
            "the MobilityModel zero-diagonal contract cannot be checked",
        ))
        return findings
    mob = MobilityModel(num_nodes=6, area_size=50.0, comm_range=60.0,
                        max_speed=5.0, seed=0)
    for r in (0, 3):
        if np.asarray(mob.adjacency_at(r)).diagonal().any():
            findings.append(Finding(
                "MUR103", dyn_path, 1,
                f"MobilityModel.adjacency_at({r}) emitted self-edges — "
                "the dynamic G^t must keep the zero-diagonal invariant",
            ))

    # -- MUR300/301: fault-masked adjacency stays a valid neighbor mask -----
    # The fault schedule composes multiplicatively into every adjacency
    # source (static topology, mobility G^t); it may only REMOVE edges and
    # must re-assert the zero diagonal the aggregation rules lean on.
    sched_path = str(pkg / "faults" / "schedule.py")
    try:
        from murmura_tpu.faults.schedule import FaultSchedule
    except Exception as e:  # noqa: BLE001 — the import failure IS the finding
        findings.append(Finding(
            "MUR300", sched_path, 1,
            f"faults.schedule failed to import ({type(e).__name__}: {e}) — "
            "the fault-mask contracts cannot be checked",
        ))
        return findings
    sched = FaultSchedule(
        6, crash_prob=0.35, recovery_prob=0.3, link_drop_prob=0.3,
        straggler_prob=0.3, seed=0,
    )
    sources = [("mobility G^t", np.asarray(mob.adjacency_at(3), np.float32))]
    try:
        sources.append(
            ("ring topology",
             generators.create_topology("ring", num_nodes=6).mask()),
        )
    except Exception:  # noqa: BLE001 — already a MUR103 finding above
        pass
    for label, adj in sources:
        for r in (0, 2, 7):
            masked = sched.masked_adjacency(adj, r)
            if np.asarray(masked).diagonal().any():
                findings.append(Finding(
                    "MUR301", sched_path, 1,
                    f"FaultSchedule.masked_adjacency over the {label} "
                    f"emitted self-edges at round {r} — the fault-masked "
                    "adjacency must keep the zero-diagonal invariant",
                ))
            if (np.asarray(masked) > np.asarray(adj, dtype=np.float32)).any():
                findings.append(Finding(
                    "MUR301", sched_path, 1,
                    f"FaultSchedule.masked_adjacency over the {label} "
                    f"ADDED edge weight at round {r} — fault masking may "
                    "only remove edges, never create or amplify them",
                ))

    # -- MUR602: sparse-topology + population-sampler bijections ------------
    # The sparse families and cohort samplers span the same three layers as
    # MUR101's registries: the runtime registry (SPARSE_TOPOLOGY_TYPES /
    # population.sampler.SAMPLERS), the config schema enums, and the
    # executable generator contract (a sparse type must actually return a
    # SparseTopology with valid nonzero deduped offsets).
    sparse_path = str(pkg / "topology" / "sparse.py")
    sparse_imports_ok = True
    try:
        from murmura_tpu.population.sampler import SAMPLERS
        from murmura_tpu.topology.generators import SPARSE_TOPOLOGY_TYPES
        from murmura_tpu.topology.sparse import SparseTopology
    except Exception as e:  # noqa: BLE001 — the import failure IS the finding
        findings.append(Finding(
            "MUR602", sparse_path, 1,
            f"the population/sparse registries failed to import "
            f"({type(e).__name__}: {e}) — the MUR602 bijections cannot "
            "be checked",
        ))
        # No early return: the MUR401 telemetry contract below is
        # unrelated and must still run.
        sparse_imports_ok = False
        SAMPLERS, SPARSE_TOPOLOGY_TYPES, SparseTopology = {}, (), None
    sampler_path = str(pkg / "population" / "sampler.py")
    if sparse_imports_ok:
        findings += _sync_findings(
            "population sampler", set(SAMPLERS),
            _schema_enum("sampler", schema.PopulationConfig),
            sampler_path, schema_path,
        )
    for name in sorted(set(SPARSE_TOPOLOGY_TYPES) - set(generators.TOPOLOGY_TYPES)):
        findings.append(Finding(
            "MUR602", topo_path, 1,
            f"sparse topology '{name}' is not in TOPOLOGY_TYPES — the "
            "MUR101/MUR103 contracts never see it",
        ))
    for name in SPARSE_TOPOLOGY_TYPES:
        for nn in (6, 8):
            try:
                topo = generators.create_topology(name, num_nodes=nn)
            except Exception as e:  # noqa: BLE001 — a crash IS the finding
                findings.append(Finding(
                    "MUR602", topo_path, 1,
                    f"sparse topology '{name}' raised at num_nodes={nn}: "
                    f"{type(e).__name__}: {e}",
                ))
                continue
            if not isinstance(topo, SparseTopology):
                findings.append(Finding(
                    "MUR602", topo_path, 1,
                    f"sparse topology '{name}' returned a "
                    f"{type(topo).__name__} — sparse families must return "
                    "SparseTopology (the [k, N] edge-mask engine's input "
                    "contract)",
                ))
                continue
            offs = list(topo.offsets)
            if (
                not offs
                or any(not 0 < o < nn for o in offs)
                or len(set(offs)) != len(offs)
            ):
                findings.append(Finding(
                    "MUR602", sparse_path, 1,
                    f"sparse topology '{name}' at num_nodes={nn} emitted "
                    f"invalid offsets {offs} — offsets must be nonzero mod "
                    "N, in-range, and deduped (self-loops/double-counting "
                    "break every weighted circulant kernel)",
                ))

    # -- MUR401: telemetry schema version carries a migration note ----------
    # The manifest schema is a cross-process, cross-release contract (old
    # monitors read new node events; `murmura report` reads any past run
    # dir).  A version bump without a written migration note strands every
    # existing run directory, so the note is machine-required: bumping
    # MANIFEST_SCHEMA_VERSION without adding "### v<N>" to the "Schema
    # versions" section of docs/OBSERVABILITY.md fails `murmura check`.
    tel_path = str(pkg / "telemetry" / "schema.py")
    try:
        from murmura_tpu.telemetry.schema import MANIFEST_SCHEMA_VERSION
    except Exception as e:  # noqa: BLE001 — the import failure IS the finding
        findings.append(Finding(
            "MUR401", tel_path, 1,
            f"telemetry.schema failed to import ({type(e).__name__}: {e}) "
            "— the manifest schema-version contract cannot be checked",
        ))
        return findings
    obs_doc = pkg.parent / "docs" / "OBSERVABILITY.md"
    if obs_doc.is_file():  # source checkout only, like the MUR102 tests scan
        text = obs_doc.read_text()
        if f"### v{MANIFEST_SCHEMA_VERSION}" not in text:
            findings.append(Finding(
                "MUR401", tel_path, 1,
                f"MANIFEST_SCHEMA_VERSION is {MANIFEST_SCHEMA_VERSION} but "
                f"docs/OBSERVABILITY.md has no '### v"
                f"{MANIFEST_SCHEMA_VERSION}' migration note under 'Schema "
                "versions' — a schema bump must document how existing run "
                "directories migrate",
            ))

    # -- MUR900: snapshot completeness bijection ----------------------------
    # The durability snapshot (durability/snapshot.py) promises to carry
    # EVERY piece of state the run carries across rounds.  Two halves keep
    # that promise machine-checked: (a) every reserved ``*_STATE_KEYS``
    # tuple in the package must be registered with the snapshot module (an
    # unregistered group is carried state the completeness contract cannot
    # see), and (b) a payload containing every reserved key must survive
    # the save→restore roundtrip byte-for-byte.
    dur_path = str(pkg / "durability" / "snapshot.py")
    try:
        from murmura_tpu.durability import snapshot as dsnap
    except Exception as e:  # noqa: BLE001 — the import failure IS the finding
        findings.append(Finding(
            "MUR900", dur_path, 1,
            f"durability.snapshot failed to import ({type(e).__name__}: "
            f"{e}) — the snapshot completeness contract cannot be checked",
        ))
        return findings
    findings += _mur900_registry_findings(
        dsnap.discover_state_key_groups(pkg),
        dsnap.RESERVED_AGG_STATE_KEY_GROUPS,
        dur_path,
    )
    findings += _mur900_roundtrip_findings(dur_path)
    return findings


def _mur900_registry_findings(
    discovered, registry, dur_path: str
) -> List[Finding]:
    """MUR900 half (a): the discovered ``*_STATE_KEYS`` assignments and
    the durability registry must name the same groups, in the same
    modules.  Split out for negative-testability (the _sync_findings
    pattern)."""
    findings: List[Finding] = []
    for name, module in sorted(discovered.items()):
        reg = registry.get(name)
        if reg is None:
            findings.append(Finding(
                "MUR900", dur_path, 1,
                f"reserved carried-state key group '{name}' ({module}) is "
                "not registered in durability.snapshot."
                "RESERVED_AGG_STATE_KEY_GROUPS — state it reserves would "
                "be invisible to the snapshot completeness contract; "
                "register it",
            ))
        elif reg != module:
            findings.append(Finding(
                "MUR900", dur_path, 1,
                f"carried-state key group '{name}' is registered under "
                f"module '{reg}' but defined in '{module}' — fix the "
                "registry entry",
            ))
    for name in sorted(set(registry) - set(discovered)):
        findings.append(Finding(
            "MUR900", dur_path, 1,
            f"RESERVED_AGG_STATE_KEY_GROUPS entry '{name}' names no "
            "module-level *_STATE_KEYS assignment in the package — remove "
            "the stale registry entry",
        ))
    return findings


def _mur900_roundtrip_findings(dur_path: str) -> List[Finding]:
    """MUR900 half (b): an assembled payload carrying every base section
    and every reserved agg_state key must survive the snapshot
    save→restore roundtrip byte-for-byte."""
    import tempfile

    import numpy as np

    from murmura_tpu.durability import snapshot as dsnap

    findings: List[Finding] = []
    try:
        groups = dsnap.resolve_reserved_agg_state_keys()
    except Exception as e:  # noqa: BLE001 — a stale entry IS the finding
        return [Finding(
            "MUR900", dur_path, 1,
            f"RESERVED_AGG_STATE_KEY_GROUPS failed to resolve "
            f"({type(e).__name__}: {e}) — registry entries must import to "
            "non-empty tuples of agg_state key strings",
        )]
    rng = np.random.default_rng(0)
    agg_state = {"ordinary_stat": rng.normal(size=(4,)).astype(np.float32)}
    for keys in groups.values():
        for k in keys:
            agg_state[k] = rng.normal(size=(4, 3)).astype(np.float32)
    payload = {
        "params": {"w": rng.normal(size=(4, 2)).astype(np.float32)},
        "agg_state": agg_state,
        "rng": np.zeros(2, np.uint32),
        "round": 3,
        "history": {"round": [1, 2, 3]},
        "round_times": [0.1, 0.2, 0.3],
    }
    try:
        with tempfile.TemporaryDirectory() as d:
            missing, corrupted = dsnap.snapshot_roundtrip_missing_sections(
                d, payload
            )
    except Exception as e:  # noqa: BLE001 — a broken writer IS the finding
        return [Finding(
            "MUR900", dur_path, 1,
            f"the snapshot roundtrip probe crashed ({type(e).__name__}: "
            f"{e}) — the save/restore path cannot carry the reserved "
            "state",
        )]
    for section in missing:
        findings.append(Finding(
            "MUR900", dur_path, 1,
            f"snapshot base section '{section}' did not survive the "
            "save→restore roundtrip — the snapshot payload is incomplete",
        ))
    for key in corrupted:
        findings.append(Finding(
            "MUR900", dur_path, 1,
            f"reserved carried-state key '{key}' was lost or corrupted by "
            "the snapshot roundtrip — a resumed run would silently drop "
            "this subsystem's carried state",
        ))
    return findings

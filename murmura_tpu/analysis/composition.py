"""Cross-feature composition contracts (MUR1400-1403) — part of the
default package check (docs/ANALYSIS.md "Composition grid").

The framework's orthogonal levers (murmura_tpu/levers.py) historically
interacted through hand-written ``ConfigError`` refusals scattered over
``config/schema.py`` and ``utils/factories.py``: nothing checked that a
refusal was still justified, that a declared-compatible pair still
composed, or that a new lever declared anything at all.  Each
:class:`~murmura_tpu.levers.LeverManifest` now declares its lever's
composition surface exactly once, and this module closes the loop both
ways:

- **MUR1400 — manifest <-> guard bijection.**  The ``LEVER_MODULES``
  registry, an AST scan for module-level ``LEVER_MANIFEST`` assignments
  (the MUR900 ``*_STATE_KEYS`` discovery pattern), the reserved
  state-key-group registry and the ``STAGE_ORDER`` labels must agree;
  every ``refusal_reason(...)`` guard site in schema/factories must
  resolve to a declared verdict; every declared refusal must have a
  live guard that FIRES (the executable census arms each refused
  combination and requires the declared reason verbatim in the raised
  error); and no refusal-shaped literal may bypass the manifest — a
  guard string containing "does not compose" outside ``refusal_reason``
  is an undeclared refusal.  The committed census
  (analysis/COMPOSITION.json) pins the refusal count so lifting a pair
  (or quietly refusing a new one) is a reviewed diff, not drift.
- **MUR1401 — the generated pairwise grid.**  Every declared-compatible
  pair's composed round program must actually build from config, train
  recompile-free after warmup
  (:class:`~murmura_tpu.analysis.sanitizers.CompileTracker`), produce
  finite metrics, and keep collective-inventory parity: the composed
  trace's collectives stay within the union of the two
  individually-armed programs' (a composed build that grows a new
  collective is a new distributed algorithm, not a composition).  The
  lifted ``sharding x sweep`` cell additionally pins the
  ("seed", "nodes", "param") gang mesh and rebuild determinism.
- **MUR1402 — composed carried state + stage order.**  The reserved
  ``*_STATE_KEYS`` groups are pairwise disjoint; a composed program's
  ``agg_state`` carries the union of the two single-lever programs'
  keys; and the composed trace's ``murmura.*`` named_scope stage labels
  first-occur in ``STAGE_ORDER`` order, with each armed lever's
  declared stage hook actually present (core/rounds.py is the single
  ordering authority the manifests must match).
- **MUR1403 — flow-taint preservation on composed cells.**  Bounded
  rules keep their MUR800-declared per-coordinate influence when two
  levers touch the same exchange: the compressed+stale cell (int8
  round-trip feeding the stale fold) and the sparse+stale cell ([k, N]
  edge masks through the re-add layer) re-run the staleness Probe-A
  taint run (analysis/staleness.py) over the composed step.

MUR1401 compiles and runs one tiny program per compatible pair (the
check_durability cost profile at grid scale), so the family is memoized
per process and runs by default only for the package check; tests gate
representative cells per tier-1 run (tests/test_composition.py) and
negatives prove each probe can fire.
"""

import ast
import copy
import json
import warnings
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from murmura_tpu.analysis.lint import Finding
from murmura_tpu.levers import (
    LEVER_MODULES,
    STAGE_ORDER,
    compatible_pairs,
    declared_refusals,
    discover_lever_manifests,
    lever_manifests,
    pair_verdict,
    refusal_reason,
)

# Registry of check families in this module: name -> callable, scanned by
# analysis/ir.py's check_coverage so an unwired family is a MUR205
# finding (the flow.py/sharded.py twin pattern).
COMPOSE_CHECK_FAMILIES: Dict[str, Callable[[], List[Finding]]] = {}


def _family(fn):
    COMPOSE_CHECK_FAMILIES[fn.__name__] = fn
    return fn


_PKG = Path(__file__).resolve().parent.parent
_LEVERS_PATH = str(_PKG / "levers.py")
_SCHEMA_PATH = str(_PKG / "config" / "schema.py")
_FACTORIES_PATH = str(_PKG / "utils" / "factories.py")

# The committed refusal census: lifting a pair (or adding a refusal)
# must move this file in the same diff (the BUDGETS.json convention).
COMPOSITION_JSON = Path(__file__).resolve().parent / "COMPOSITION.json"

# Levers whose arming changes the traced round program (the others —
# mobility, population, sweep — act at the orchestrator layer and leave
# the per-round trace alone, so collective parity is not their contract).
_PROGRAM_LEVERS = frozenset((
    "adaptive", "compression", "dmtt", "faults", "pipeline", "sharding",
    "sparse", "staleness",
))

# Stage labels a lever's arming reliably emits into the composed trace
# (core/rounds.py wraps exactly these code paths in jax.named_scope).
# dmtt/sparse declare the exchange stage they ride but do not open their
# own bracket, so presence is only required for this subset.
_SCOPED_STAGES: Dict[str, str] = {
    "adaptive": "murmura.exchange",
    "compression": "murmura.compress",
    "staleness": "murmura.stale",
    "pipeline": "murmura.pipeline",
}


def _manifest_anchor(lever: str) -> Tuple[str, int]:
    """(path, line) of a lever's LEVER_MANIFEST declaration."""
    import importlib

    mod = importlib.import_module(LEVER_MODULES[lever])
    path = str(Path(mod.__file__).resolve())
    try:
        for i, text in enumerate(Path(path).read_text().splitlines(), 1):
            if text.startswith("LEVER_MANIFEST"):
                return path, i
    except OSError:
        pass
    return path, 1


def _pair_anchor(a: str, b: str) -> Tuple[str, int]:
    """Findings about a pair anchor at the later lever's manifest — the
    declaration that owns the verdict."""
    return _manifest_anchor(max(a, b))


def _deep_merge(base: Dict[str, Any], over: Dict[str, Any]) -> Dict[str, Any]:
    out = dict(base)
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(out.get(k), dict):
            out[k] = _deep_merge(out[k], v)
        else:
            out[k] = v
    return out


# --------------------------------------------------------------------------
# The canonical grid cell: one tiny experiment + one armer per lever
# --------------------------------------------------------------------------

# Ring of 8 nodes, tiny MLP (flat dim 99 -> padded 100 over 2 shards),
# synthetic data, 4 trained rounds per cell (2 warmup + 2 tracked).
_BASE_RAW: Dict[str, Any] = {
    "experiment": {"name": "compose-cell", "seed": 7, "rounds": 6},
    "topology": {"type": "ring", "num_nodes": 8},
    "aggregation": {"algorithm": "balance", "params": {}},
    "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.05},
    "data": {"adapter": "synthetic",
             "params": {"num_samples": 40, "input_shape": [6],
                        "num_classes": 3}},
    "model": {"factory": "mlp",
              "params": {"input_dim": 6, "hidden_dims": [8],
                         "num_classes": 3}},
}

# One canonical arming per lever — the raw-config override that turns
# the lever ON in a grid cell.  Constrained pairs arm OUTSIDE their
# refused sub-configuration (see _PAIR_OVERRIDES): int8 block 10
# divides the 50-wide shard-local flat width, the sparse armer has 3
# offsets (not one_peer), the staleness armer carries the fault model it
# requires, and the dmtt armer sets allow_static so cells without
# mobility stay wirable.
LEVER_ARMERS: Dict[str, Dict[str, Any]] = {
    "adaptive": {"attack": {"enabled": True, "type": "gaussian",
                            "percentage": 0.25,
                            "adaptive": {"enabled": True},
                            "params": {"noise_std": 5.0, "seed": 7}}},
    "compression": {"compression": {"algorithm": "int8",
                                    "error_feedback": True, "block": 10}},
    "dmtt": {"dmtt": {"budget_B": 3, "rho": 0.1, "lambda_forget": 0.9,
                      "w_a": 0.7, "tau_U": 0.3, "eta": 5.0,
                      "allow_static": True}},
    "faults": {"faults": {"enabled": True, "seed": 777,
                          "straggler_prob": 0.3, "link_drop_prob": 0.2}},
    "mobility": {"mobility": {"area_size": 100.0, "comm_range": 60.0,
                              "max_speed": 5.0, "seed": 42,
                              "ensure_connected": True}},
    "pipeline": {"exchange": {"pipeline": True}},
    "population": {"population": {"enabled": True, "virtual_size": 32,
                                  "sampler": "stratified", "seed": 3,
                                  "rounds_per_cohort": 1}},
    "sharding": {"backend": "tpu", "tpu": {"param_shards": 2}},
    "sparse": {"topology": {"type": "exponential", "num_nodes": 8}},
    "staleness": {"exchange": {"max_staleness": 2,
                               "staleness_discount": 0.7},
                  "faults": {"enabled": True, "seed": 777,
                             "straggler_prob": 0.3}},
    "sweep": {"sweep": {"num_seeds": 2}},
}

# Pair-specific adjustments that keep a CONSTRAINED pair outside its
# refused sub-configuration when the plain armer union would hit it.
_PAIR_OVERRIDES: Dict[Tuple[str, str], Dict[str, Any]] = {
    # carried_state: error feedback is per-slot carried state; the
    # population cell arms the stateless int8 codec.
    ("compression", "population"): {"compression": {"error_feedback": False}},
}


def pair_raw(a: str, b: str) -> Dict[str, Any]:
    """The raw config of the (a, b) grid cell: base + both armers."""
    raw = copy.deepcopy(_BASE_RAW)
    earlier, later = sorted((a, b))
    raw = _deep_merge(raw, LEVER_ARMERS[earlier])
    raw = _deep_merge(raw, LEVER_ARMERS[later])
    raw = _deep_merge(raw, _PAIR_OVERRIDES.get((earlier, later), {}))
    return raw


def _validate(raw: Dict[str, Any]):
    from murmura_tpu.config import Config

    return Config.model_validate(raw)


def _build_cell(cfg):
    """(driver, is_gang) for one validated cell config."""
    from murmura_tpu.utils.factories import (
        build_gang_from_config,
        build_network_from_config,
    )

    if cfg.sweep is not None:
        return build_gang_from_config(cfg), True
    return build_network_from_config(cfg), False


def _histories(driver, is_gang) -> List[Dict[str, List[Any]]]:
    return list(driver.histories) if is_gang else [driver.history]


# --------------------------------------------------------------------------
# Trace helpers (shared by MUR1401 parity and MUR1402 stage order)
# --------------------------------------------------------------------------


def _trace_program(prog):
    """Closed jaxpr of one round program's ``train_step`` over canonical
    inputs (dense or [k, N] sparse adjacency; the faulted signature
    carries the extra alive mask)."""
    import jax
    import jax.numpy as jnp

    n = prog.num_nodes
    if prog.sparse:
        adj = jnp.ones((len(prog.sparse_offsets), n), jnp.float32)
    else:
        adj = jnp.asarray(
            np.ones((n, n), np.float32) - np.eye(n, dtype=np.float32)
        )
    args = [
        prog.init_params,
        {k: jnp.asarray(v) for k, v in prog.init_agg_state.items()},
        jax.random.PRNGKey(0),
        adj,
        jnp.zeros((n,), jnp.float32),
    ]
    if prog.faulted:
        args.append(jnp.ones((n,), jnp.float32))
    args.append(jnp.asarray(0.0, jnp.float32))
    args.append({k: jnp.asarray(v) for k, v in prog.data_arrays.items()})
    return jax.make_jaxpr(prog.train_step)(*args)


def _trace_collectives(closed) -> frozenset:
    from murmura_tpu.analysis.adaptive import _COLLECTIVE_PRIMS
    from murmura_tpu.analysis.ir import iter_eqns

    return frozenset(
        e.primitive.name for e in iter_eqns(closed)
        if e.primitive.name in _COLLECTIVE_PRIMS
    )


def _trace_stages(closed) -> List[str]:
    """First-occurrence order of ``murmura.*`` named_scope labels in one
    traced program (core/rounds.py stage brackets)."""
    from murmura_tpu.analysis.ir import iter_eqns

    seen: List[str] = []
    for e in iter_eqns(closed):
        stack = getattr(e.source_info, "name_stack", None)
        if stack is None:
            continue
        for part in str(stack).split("/"):
            if part.startswith("murmura.") and part not in seen:
                seen.append(part)
    return seen


_SINGLE_MEMO: Dict[str, Any] = {}


def _single_program(lever: str, override: Optional[Dict[str, Any]] = None):
    """The single-lever round program (memoized), or None for levers
    whose arming never reaches the traced program.  ``override`` is the
    pair's constrained-arming patch (_PAIR_OVERRIDES) so the baseline
    matches the composed cell's sub-configuration."""
    if lever not in _PROGRAM_LEVERS:
        return None
    key = lever if not override else (
        lever + "|" + json.dumps(override, sort_keys=True)
    )
    if key not in _SINGLE_MEMO:
        raw = _deep_merge(copy.deepcopy(_BASE_RAW), LEVER_ARMERS[lever])
        if override:
            raw = _deep_merge(raw, override)
        net, _ = _build_cell(_validate(raw))
        _SINGLE_MEMO[key] = net.program
    return _SINGLE_MEMO[key]


_BASE_MEMO: Dict[str, Any] = {}


def _base_program():
    if "base" not in _BASE_MEMO:
        net, _ = _build_cell(_validate(copy.deepcopy(_BASE_RAW)))
        _BASE_MEMO["base"] = net.program
    return _BASE_MEMO["base"]


# --------------------------------------------------------------------------
# MUR1400 — manifest <-> schema/guard bijection
# --------------------------------------------------------------------------


@_family
def check_manifest_bijection() -> List[Finding]:
    """MUR1400 (structural): the LEVER_MODULES registry, the AST-scan
    discovery, the reserved state-key-group registry, the stage labels
    and the mesh-axis names must agree with the loaded manifests."""
    from murmura_tpu.durability.snapshot import (
        RESERVED_AGG_STATE_KEY_GROUPS,
        resolve_reserved_agg_state_keys,
    )

    findings: List[Finding] = []
    manifests = lever_manifests()

    found = discover_lever_manifests(_PKG)
    declared_mods = set(LEVER_MODULES.values())
    for mod in sorted(declared_mods - set(found)):
        findings.append(Finding(
            "MUR1400", _LEVERS_PATH, 1,
            f"LEVER_MODULES names {mod} but no module-level "
            "LEVER_MANIFEST assignment was discovered there — the "
            "registry row is stale",
        ))
    for mod in sorted(set(found) - declared_mods):
        findings.append(Finding(
            "MUR1400", found[mod], 1,
            f"module {mod} declares a LEVER_MANIFEST that is not in the "
            "levers.LEVER_MODULES registry — register the lever so the "
            "composition grid covers it",
        ))

    reserved = resolve_reserved_agg_state_keys()
    claimed = {
        m.state_keys_group: name for name, m in manifests.items()
        if m.state_keys_group is not None
    }
    for group in sorted(set(claimed) - set(reserved)):
        path, line = _manifest_anchor(claimed[group])
        findings.append(Finding(
            "MUR1400", path, line,
            f"lever '{claimed[group]}' claims state-key group "
            f"'{group}' which RESERVED_AGG_STATE_KEY_GROUPS does not "
            "register (durability/snapshot.py)",
        ))
    for group in sorted(set(reserved) - set(claimed)):
        findings.append(Finding(
            "MUR1400", _LEVERS_PATH, 1,
            f"reserved state-key group '{group}' "
            f"({RESERVED_AGG_STATE_KEY_GROUPS[group]}) is claimed by no "
            "lever manifest — carried state with no composition owner",
        ))

    for name, m in sorted(manifests.items()):
        path, line = _manifest_anchor(name)
        if m.stage is not None and m.stage not in STAGE_ORDER:
            findings.append(Finding(
                "MUR1400", path, line,
                f"lever '{name}' declares stage {m.stage!r} which is "
                "not a STAGE_ORDER label (levers.py)",
            ))
        bad_axes = [ax for ax in m.mesh_axes
                    if ax not in ("seed", "nodes", "param")]
        if bad_axes:
            findings.append(Finding(
                "MUR1400", path, line,
                f"lever '{name}' declares mesh axes {bad_axes} outside "
                "the (seed, nodes, param) mesh vocabulary "
                "(parallel/mesh.py)",
            ))
    return findings


# Phrases that mark a hand-written refusal message.  A guard literal
# containing one of these OUTSIDE a refusal_reason(...) citation is an
# undeclared refusal — the bypass MUR1400 exists to catch.
_REFUSAL_PHRASES: Tuple[str, ...] = (
    "does not compose", "do not compose", "not gang-batchable",
)


def _cited_refusals(src: str, path: str):
    """(citations, findings) from one guard module's source: every
    ``refusal_reason(...)`` call with literal arguments resolved to its
    (earlier, later, constraint|None) key, plus findings for dynamic
    citations and for refusal-phrase literals outside any citation."""
    findings: List[Finding] = []
    cited: List[Tuple[str, str, Optional[str]]] = []
    tree = ast.parse(src, filename=path)
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call):
            continue
        fn = node.func
        fname = fn.id if isinstance(fn, ast.Name) else (
            fn.attr if isinstance(fn, ast.Attribute) else None
        )
        if fname != "refusal_reason":
            continue
        lits = [
            a.value for a in node.args
            if isinstance(a, ast.Constant) and isinstance(a.value, str)
        ]
        if len(lits) != len(node.args) or len(lits) not in (2, 3):
            findings.append(Finding(
                "MUR1400", path, node.lineno,
                "refusal_reason(...) cited with non-literal arguments — "
                "the manifest bijection cannot be verified statically; "
                "cite lever names as string literals",
            ))
            continue
        a, b = sorted(lits[:2])
        key = (a, b, lits[2] if len(lits) == 3 else None)
        cited.append(key)
        if key not in set(declared_refusals()):
            findings.append(Finding(
                "MUR1400", path, node.lineno,
                f"guard cites refusal_reason{tuple(lits)} but the "
                "manifests declare no such refusal — an undeclared "
                "refusal (or a stale citation after a lift)",
            ))
    for node in ast.walk(tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            low = node.value.lower()
            if any(p in low for p in _REFUSAL_PHRASES):
                findings.append(Finding(
                    "MUR1400", path, node.lineno,
                    "refusal-shaped literal (contains "
                    f"{[p for p in _REFUSAL_PHRASES if p in low]!r}) is "
                    "not routed through refusal_reason(...) — an "
                    "undeclared cross-feature refusal bypassing the "
                    "manifest grid",
                ))
    return cited, findings


def refusal_guard_findings(
    schema_src: Optional[str] = None,
    factories_src: Optional[str] = None,
) -> List[Finding]:
    """MUR1400 (guard sites): every citation resolves to a declared
    verdict; every declared refusal is cited by at least one guard.
    ``schema_src``/``factories_src`` are injectable so negative tests
    drive the probes with doctored sources (tests/test_composition.py)."""
    if schema_src is None:
        schema_src = Path(_SCHEMA_PATH).read_text()
    if factories_src is None:
        factories_src = Path(_FACTORIES_PATH).read_text()
    findings: List[Finding] = []
    cited: List[Tuple[str, str, Optional[str]]] = []
    for src, path in (
        (schema_src, _SCHEMA_PATH), (factories_src, _FACTORIES_PATH),
    ):
        c, f = _cited_refusals(src, path)
        cited.extend(c)
        findings.extend(f)
    for key in sorted(set(declared_refusals()) - set(cited),
                      key=lambda k: (k[0], k[1], k[2] or "")):
        a, b, tag = key
        path, line = _pair_anchor(a, b)
        findings.append(Finding(
            "MUR1400", path, line,
            f"manifest declares refusal ({a}, {b}"
            + (f", {tag!r})" if tag else ")")
            + " but no guard site in config/schema.py or "
            "utils/factories.py cites it — a stale declaration (lift "
            "the verdict) or a missing guard (users hit the refused "
            "combination at runtime instead of validation)",
        ))
    return findings


# The executable refusal census: for every declared refusal, a raw
# config that arms exactly the refused combination ("arm" pulls lever
# armers, "extra" patches on top) and the layer whose guard must fire.
# MUR1400 runs each and requires the declared reason verbatim in the
# raised error — the message a user sees IS the manifest's verdict.
REFUSAL_CONFIGS: Dict[Tuple[str, str, Optional[str]], Dict[str, Any]] = {
    ("adaptive", "dmtt", None): {"via": "schema",
                                 "arm": ("adaptive", "dmtt")},
    ("adaptive", "pipeline", None): {"via": "schema",
                                     "arm": ("adaptive", "pipeline")},
    ("compression", "dmtt", None): {"via": "schema",
                                    "arm": ("compression", "dmtt")},
    ("compression", "population", "carried_state"): {
        "via": "schema", "arm": ("compression", "population"),
        "extra": {"compression": {"error_feedback": True}},
    },
    ("compression", "sharding", "topk"): {
        "via": "schema", "arm": ("compression", "sharding"),
        "extra": {"compression": {"algorithm": "topk",
                                  "topk_ratio": 0.1}},
    },
    ("compression", "sharding", "int8_block"): {
        # Block 48 does not divide the 50-wide shard-local flat width —
        # the guard lives where the model dim is known
        # (utils/factories.py).
        "via": "network", "arm": ("compression", "sharding"),
        "extra": {"compression": {"block": 48}},
    },
    ("dmtt", "mobility", "requires_mobility"): {
        "via": "schema", "arm": ("dmtt",),
        "extra": {"dmtt": {"allow_static": False}},
    },
    ("dmtt", "pipeline", None): {"via": "schema",
                                 "arm": ("dmtt", "pipeline")},
    ("dmtt", "population", None): {"via": "schema",
                                   "arm": ("dmtt", "population")},
    ("dmtt", "sharding", None): {"via": "schema",
                                 "arm": ("dmtt", "sharding")},
    ("dmtt", "sparse", None): {"via": "schema",
                               "arm": ("dmtt", "sparse")},
    ("dmtt", "staleness", None): {"via": "schema",
                                  "arm": ("dmtt", "staleness")},
    ("faults", "staleness", "requires_faults"): {
        "via": "schema", "arm": (),
        "extra": {"exchange": {"max_staleness": 2,
                               "staleness_discount": 0.7}},
    },
    ("mobility", "sparse", None): {"via": "schema",
                                   "arm": ("mobility", "sparse")},
    ("mobility", "staleness", None): {"via": "schema",
                                      "arm": ("mobility", "staleness")},
    ("pipeline", "population", None): {"via": "schema",
                                       "arm": ("pipeline", "population")},
    ("population", "sharding", None): {"via": "schema",
                                       "arm": ("population", "sharding")},
    ("population", "staleness", None): {"via": "schema",
                                        "arm": ("population", "staleness")},
    ("population", "sweep", None): {"via": "schema",
                                    "arm": ("population", "sweep")},
    ("sparse", "staleness", "one_peer"): {
        "via": "schema", "arm": ("staleness",),
        "extra": {"topology": {"type": "one_peer", "num_nodes": 8}},
    },
    ("sparse", "sweep", "tpu_backend"): {
        "via": "gang", "arm": ("sparse", "sweep"),
        "extra": {"backend": "tpu"},
    },
}


def _census_raw(entry: Dict[str, Any]) -> Dict[str, Any]:
    raw = copy.deepcopy(_BASE_RAW)
    for lever in entry.get("arm", ()):
        raw = _deep_merge(raw, LEVER_ARMERS[lever])
    return _deep_merge(raw, entry.get("extra", {}))


def census_cell_findings(
    key: Tuple[str, str, Optional[str]], entry: Dict[str, Any],
) -> List[Finding]:
    """Arm ONE declared refusal's combination and require its guard to
    fire with the manifest's reason verbatim."""
    a, b, tag = key
    path, line = _pair_anchor(a, b)
    reason = refusal_reason(a, b, tag)
    raw = _census_raw(entry)
    try:
        cfg = _validate(raw)
        if entry["via"] == "network":
            from murmura_tpu.utils.factories import build_network_from_config

            build_network_from_config(cfg)
        elif entry["via"] == "gang":
            from murmura_tpu.utils.factories import build_gang_from_config

            build_gang_from_config(cfg)
        elif entry["via"] != "schema":
            raise ValueError(f"unknown census layer {entry['via']!r}")
    except Exception as e:  # noqa: BLE001 — the raise IS the contract
        if reason not in str(e):
            return [Finding(
                "MUR1400", path, line,
                f"census ({a}, {b}" + (f", {tag!r})" if tag else ")")
                + f" raised via {entry['via']} but the error does not "
                "carry the manifest's declared reason verbatim — the "
                "guard message and the declaration have diverged: "
                f"{type(e).__name__}: {str(e)[:300]}",
            )]
        return []
    return [Finding(
        "MUR1400", path, line,
        f"census ({a}, {b}" + (f", {tag!r})" if tag else ")")
        + f" armed the refused combination via {entry['via']} and no "
        "guard fired — a stale refusal declaration (lift it) or a "
        "fail-loud guard that silently degraded",
    )]


@_family
def check_refusal_census() -> List[Finding]:
    """MUR1400 (executable): the census covers every declared refusal,
    every entry's guard fires with the declared reason, and the
    committed COMPOSITION.json matches the live grid."""
    findings: List[Finding] = list(refusal_guard_findings())
    declared = set(declared_refusals())
    census = set(REFUSAL_CONFIGS)
    for a, b, tag in sorted(
        declared - census, key=lambda k: (k[0], k[1], k[2] or "")
    ):
        path, line = _pair_anchor(a, b)
        findings.append(Finding(
            "MUR1400", path, line,
            f"declared refusal ({a}, {b}"
            + (f", {tag!r})" if tag else ")")
            + " has no REFUSAL_CONFIGS census entry — add the arming "
            "raw config so the guard is executed, not just grepped",
        ))
    for a, b, tag in sorted(
        census - declared, key=lambda k: (k[0], k[1], k[2] or "")
    ):
        findings.append(Finding(
            "MUR1400", str(Path(__file__).resolve()), 1,
            f"census entry ({a}, {b}" + (f", {tag!r})" if tag else ")")
            + " matches no declared refusal — remove it (the pair was "
            "lifted) or declare the verdict",
        ))
    for key in sorted(census & declared,
                      key=lambda k: (k[0], k[1], k[2] or "")):
        try:
            findings.extend(census_cell_findings(key, REFUSAL_CONFIGS[key]))
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            a, b, tag = key
            path, line = _pair_anchor(a, b)
            findings.append(Finding(
                "MUR1400", path, line,
                f"census ({a}, {b}" + (f", {tag!r})" if tag else ")")
                + f" probe crashed: {type(e).__name__}: {e}",
            ))
    findings.extend(_census_drift_findings())
    return findings


def census_snapshot() -> Dict[str, Any]:
    """The live census in COMPOSITION.json's committed shape."""
    refusals = [
        [a, b] for a, b, tag in declared_refusals() if tag is None
    ]
    constrained = [
        [a, b, tag] for a, b, tag in declared_refusals() if tag is not None
    ]
    return {
        "refusals": refusals,
        "constrained": constrained,
        "compatible_pairs": [[a, b] for a, b in compatible_pairs()],
    }


def _census_drift_findings() -> List[Finding]:
    path = str(COMPOSITION_JSON)
    if not COMPOSITION_JSON.exists():
        return [Finding(
            "MUR1400", path, 1,
            "analysis/COMPOSITION.json is missing — commit the refusal "
            "census (murmura check --compose regenerates the snapshot)",
        )]
    committed = json.loads(COMPOSITION_JSON.read_text())
    live = census_snapshot()
    findings: List[Finding] = []
    for field in ("refusals", "constrained", "compatible_pairs"):
        if committed.get(field) != live[field]:
            findings.append(Finding(
                "MUR1400", path, 1,
                f"COMPOSITION.json '{field}' "
                f"({len(committed.get(field, []))} entries) diverges "
                f"from the live manifests ({len(live[field])}) — "
                "lifting or refusing a pair must move the committed "
                "census in the same diff",
            ))
    return findings


# --------------------------------------------------------------------------
# MUR1401 + MUR1402 — the generated pairwise grid
# --------------------------------------------------------------------------

# The lifted pair whose cell pins the 3-axis gang mesh (ISSUE 16).
LIFTED_PAIRS: Tuple[Tuple[str, str], ...] = (("sharding", "sweep"),)

_COMPOSE_SUMMARIES: List[Dict[str, Any]] = []


def compose_summaries() -> List[Dict[str, Any]]:
    """Machine-readable grid rows for ``check --json`` (one
    ``{"kind": "compose_summary", ...}`` per pair, refusals included) —
    the flow_summaries() twin.  Populated by check_composition_grid."""
    return list(_COMPOSE_SUMMARIES)


def _lifted_cell_findings(gang, raw) -> List[Finding]:
    """Extra probes for the sharding x sweep cell: the gang mesh carries
    all three axes with a real param extent, and the cell is
    rebuild-deterministic (the sharded lowering's RNG placement makes
    cross-mesh bit-parity meaningless; determinism of the SAME composed
    build is the parity contract that remains)."""
    from murmura_tpu.utils.factories import build_gang_from_config

    path, line = _pair_anchor("sharding", "sweep")
    findings: List[Finding] = []
    mesh = gang.mesh
    if tuple(mesh.axis_names) != ("seed", "nodes", "param"):
        findings.append(Finding(
            "MUR1401", path, line,
            f"[sharding x sweep] the lifted gang mesh carries axes "
            f"{tuple(mesh.axis_names)} instead of "
            "('seed', 'nodes', 'param') — the composed cell did not "
            "take the 3-axis layout",
        ))
        return findings
    if dict(mesh.shape).get("param", 1) <= 1:
        findings.append(Finding(
            "MUR1401", path, line,
            "[sharding x sweep] the lifted gang mesh has a degenerate "
            "param axis — the cell must actually shard the flat width "
            "(needs >= 8 host devices; set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8)",
        ))
        return findings
    losses = []
    for _ in range(2):
        g = build_gang_from_config(_validate(copy.deepcopy(raw)))
        g.train(rounds=2, verbose=False)
        losses.append(np.asarray(
            [h["mean_loss"][-1] for h in g.histories], np.float64
        ))
    if not np.array_equal(losses[0], losses[1]):
        findings.append(Finding(
            "MUR1401", path, line,
            "[sharding x sweep] two identical builds of the lifted "
            "cell diverge after 2 rounds "
            f"({losses[0].tolist()} vs {losses[1].tolist()}) — the "
            "composed sharded sweep is not rebuild-deterministic",
        ))
    return findings


def grid_cell_findings(a: str, b: str) -> List[Finding]:
    """One declared-compatible pair's composed cell: builds from config,
    trains recompile-free with finite metrics (MUR1401), keeps
    collective-inventory parity with the single-armed programs
    (MUR1401), and carries the union of their state keys with stage
    labels in STAGE_ORDER order (MUR1402).  Exposed per-cell so tests
    gate a subset (tests/test_composition.py)."""
    from murmura_tpu.analysis.sanitizers import track_compiles

    a, b = sorted((a, b))
    path, line = _pair_anchor(a, b)
    findings: List[Finding] = []
    raw = pair_raw(a, b)
    try:
        cfg = _validate(raw)
    except Exception as e:  # noqa: BLE001 — the refusal IS the finding
        return [Finding(
            "MUR1401", path, line,
            f"[{a} x {b}] declared composes() but the composed config "
            f"refuses at validation — a stale composes() declaration: "
            f"{type(e).__name__}: {str(e)[:300]}",
        )]
    try:
        driver, is_gang = _build_cell(cfg)
    except Exception as e:  # noqa: BLE001
        return [Finding(
            "MUR1401", path, line,
            f"[{a} x {b}] declared composes() but the composed build "
            f"crashed: {type(e).__name__}: {str(e)[:300]}",
        )]

    driver.train(rounds=2, verbose=False)
    with track_compiles() as tracker:
        driver.train(rounds=2, verbose=False)
    if tracker.total:
        findings.append(Finding(
            "MUR1401", path, line,
            f"[{a} x {b}] 2 composed rounds after warmup compiled "
            f"{tracker.total} program(s) — arming two levers together "
            "must stay value-only over one compiled program",
        ))
    for h in _histories(driver, is_gang):
        tail = h.get("mean_loss", [])
        if not tail or not np.isfinite(np.asarray(tail, np.float64)).all():
            findings.append(Finding(
                "MUR1401", path, line,
                f"[{a} x {b}] the composed cell's mean_loss history is "
                f"missing or non-finite ({tail[-3:] if tail else []}) — "
                "the pair composes structurally but not numerically",
            ))
            break

    prog = getattr(driver, "program", None)
    if prog is not None and not is_gang:
        closed = _trace_program(prog)
        override = _PAIR_OVERRIDES.get((a, b))
        # -- MUR1401: collective-inventory parity --------------------
        allowed = _trace_collectives(_trace_program(_base_program()))
        for lever in (a, b):
            single = _single_program(lever, override)
            if single is not None:
                allowed = allowed | _trace_collectives(
                    _trace_program(single)
                )
        stray = _trace_collectives(closed) - allowed
        if stray:
            findings.append(Finding(
                "MUR1401", path, line,
                f"[{a} x {b}] the composed trace contains "
                f"collective(s) {sorted(stray)} that neither "
                "single-armed program lowers — composition grew a new "
                "distributed algorithm",
            ))
        # -- MUR1402: composed state is the union of the singles -----
        composed_keys = set(prog.init_agg_state)
        # Declared buffer reuse (core/pipeline.pipeline_state_keys):
        # with bounded staleness armed the pipeline's broadcast buffer
        # IS the stale fold's payload cache, so pipe_bcast is dropped
        # by contract rather than silently disarmed.
        reused: set = set()
        if getattr(prog, "pipelined", False) and prog.stale:
            from murmura_tpu.core.pipeline import (
                PIPELINE_STATE_KEYS,
                pipeline_state_keys,
            )

            reused = set(PIPELINE_STATE_KEYS) - set(
                pipeline_state_keys(stale=True)
            )
        for lever in (a, b):
            single = _single_program(lever, override)
            if single is None:
                continue
            missing = set(single.init_agg_state) - composed_keys - reused
            if missing:
                findings.append(Finding(
                    "MUR1402", path, line,
                    f"[{a} x {b}] the composed agg_state drops "
                    f"{sorted(missing)} that the '{lever}'-only "
                    "program carries — arming a second lever silently "
                    "disarmed the first",
                ))
        # -- MUR1402: stage hooks present and in STAGE_ORDER order ----
        stages = _trace_stages(closed)
        order = {s: i for i, s in enumerate(STAGE_ORDER)}
        checked = stages
        if getattr(prog, "pipelined", False) \
                and checked[:1] == ["murmura.aggregate"]:
            # A pipelined program drains round r-1's delayed aggregation
            # at the top of round r — the double-buffer rotation IS the
            # lever's contract (core/pipeline.py); the rest of the round
            # must still follow STAGE_ORDER.
            checked = checked[1:]
        idx = [order[s] for s in checked if s in order]
        if idx != sorted(idx):
            findings.append(Finding(
                "MUR1402", path, line,
                f"[{a} x {b}] the composed trace's stage labels "
                f"first-occur as {stages} — out of the declared "
                f"STAGE_ORDER; core/rounds.py and levers.py disagree "
                "about hook ordering",
            ))
        for lever in (a, b):
            want = _SCOPED_STAGES.get(lever)
            if want is not None and want not in stages:
                findings.append(Finding(
                    "MUR1402", path, line,
                    f"[{a} x {b}] lever '{lever}' declares stage "
                    f"{want!r} but the composed trace opens no such "
                    "bracket — the hook is disarmed or the manifest "
                    "stage is stale",
                ))

    _COMPOSE_SUMMARIES.append({
        "kind": "compose_summary",
        "pair": [a, b],
        "verdict": "composes",
        "constraints": [t for t, _ in pair_verdict(a, b).constraints],
        "cell": "gang" if is_gang else "network",
        "recompiles": int(tracker.total),
        "clean": not findings,
    })
    return findings


@_family
def check_composition_grid() -> List[Finding]:
    """MUR1401/MUR1402 over every declared-compatible pair (compiles and
    runs one tiny composed program per pair — the check_durability cost
    profile at grid scale)."""
    from murmura_tpu.analysis.ir import _ensure_host_devices

    _ensure_host_devices(8)
    _COMPOSE_SUMMARIES.clear()
    for a, b, tag in declared_refusals():
        if tag is None:
            _COMPOSE_SUMMARIES.append({
                "kind": "compose_summary", "pair": [a, b],
                "verdict": "refuses", "reason": refusal_reason(a, b),
            })
    findings: List[Finding] = []
    for a, b in compatible_pairs():
        try:
            findings.extend(grid_cell_findings(a, b))
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            path, line = _pair_anchor(a, b)
            findings.append(Finding(
                "MUR1401", path, line,
                f"[{a} x {b}] composed grid cell crashed: "
                f"{type(e).__name__}: {e}",
            ))
    for a, b in LIFTED_PAIRS:
        try:
            raw = pair_raw(a, b)
            gang, _ = _build_cell(_validate(raw))
            findings.extend(_lifted_cell_findings(gang, raw))
        except Exception as e:  # noqa: BLE001
            path, line = _pair_anchor(a, b)
            findings.append(Finding(
                "MUR1401", path, line,
                f"[{a} x {b}] lifted-cell probe crashed: "
                f"{type(e).__name__}: {e}",
            ))
    return findings


@_family
def check_composed_state() -> List[Finding]:
    """MUR1402 (global): every pair of reserved state-key groups is
    disjoint — two levers riding the same agg_state key cannot compose
    under any verdict."""
    from murmura_tpu.durability.snapshot import (
        resolve_reserved_agg_state_keys,
    )

    resolved = resolve_reserved_agg_state_keys()
    findings: List[Finding] = []
    groups = sorted(resolved)
    for i, g1 in enumerate(groups):
        for g2 in groups[i + 1:]:
            clash = set(resolved[g1]) & set(resolved[g2])
            if clash:
                findings.append(Finding(
                    "MUR1402", _LEVERS_PATH, 1,
                    f"reserved state-key groups {g1} and {g2} both "
                    f"claim {sorted(clash)} — composed programs would "
                    "overwrite one lever's carried state with the "
                    "other's",
                ))
    return findings


# --------------------------------------------------------------------------
# MUR1403 — flow-taint preservation on composed cells
# --------------------------------------------------------------------------

# (mode, rule) composed taint cells.  The compressed+stale cell runs
# both bounded archetypes; the sparse+stale cell runs krum, whose
# declared bound is degree-invariant — the [k, N] fault surgery changes
# per-receiver degrees in a direction-dependent way the probe does not
# reconstruct, and a constant bound makes that reconstruction moot.
COMPOSED_TAINT_CELLS: Tuple[Tuple[str, str], ...] = (
    ("compressed_stale", "krum"),
    ("compressed_stale", "median"),
    ("sparse_stale", "krum"),
)


def _composed_stale_cell(rule: str, mode: str, fold_factory=None):
    """The staleness Probe cell with a second lever in the loop:
    ``compressed_stale`` round-trips the broadcast through the int8
    codec before the stale fold (the core/rounds.py compress->stale
    ordering); ``sparse_stale`` runs the [k, N] sparse cell through the
    sparse-mode fold."""
    import jax
    import jax.numpy as jnp

    from murmura_tpu.analysis.flow import (
        FLOW_BLOCK,
        _flow_offsets,
        _quiet_tracing,
        build_flow_cell,
    )
    from murmura_tpu.analysis.staleness import (
        _EXPIRED_SENDER,
        _SCRUBBED_SENDER,
        _STALE_SENDER,
    )
    from murmura_tpu.core.stale import (
        AGE_KEY,
        CACHE_KEY,
        StalenessSpec,
        make_stale_fold,
    )
    from murmura_tpu.ops.compress import quantize_int8

    cell = build_flow_cell(rule, "sparse" if mode == "sparse_stale"
                           else "dense")
    n = cell.n
    own, bcast, adj0 = cell.args[0], cell.args[1], cell.args[2]
    base = np.asarray(adj0, np.float32)
    spec = StalenessSpec(max_staleness=2, discount=0.5, base_mask=base)
    offsets = _flow_offsets(n) if mode == "sparse_stale" else ()
    fold = (fold_factory or make_stale_fold)(spec, sparse_offsets=offsets)

    adj_f = base.copy()
    for s in (_STALE_SENDER, _SCRUBBED_SENDER, _EXPIRED_SENDER):
        adj_f[:, s] = 0.0  # dense rows or [k, N] offsets: same surgery
    scrub_np = np.ones((n,), np.float32)
    scrub_np[_SCRUBBED_SENDER] = 0.0
    age_np = np.zeros((n,), np.float32)
    age_np[_EXPIRED_SENDER] = spec.age_cap
    rng = np.random.default_rng(1)
    cache_np = np.asarray(rng.normal(size=bcast.shape) * 0.1, np.float32)
    alive = jnp.ones((n,), jnp.float32)
    scrub_ok = jnp.asarray(scrub_np)

    cell_fn = cell.fn
    rest = tuple(cell.args[3:])
    compressed = mode == "compressed_stale"

    def fn(own_a, bcast_a, adj_a, cache_a, age_a, *rest_a):  # murmura: traced
        if compressed:
            bcast_a = quantize_int8(bcast_a, FLOW_BLOCK).dequantize()
        bcast_eff, adj_eff, updates, _stats = fold(
            bcast_a, adj_a,
            {CACHE_KEY: cache_a, AGE_KEY: age_a},
            alive, scrub_ok,
        )
        new_flat, _state, _stats2 = cell_fn(
            own_a, bcast_eff, adj_eff, *rest_a
        )
        return new_flat, updates[CACHE_KEY]

    args = (
        own, bcast, jnp.asarray(adj_f),
        jnp.asarray(cache_np), jnp.asarray(age_np),
    ) + rest
    with _quiet_tracing():
        closed = jax.make_jaxpr(fn)(*args)
    return cell, closed, args, adj_f, base


def composed_taint_findings(
    mode: str, rule: str, fold_factory=None,
) -> List[Finding]:
    """Probe A over one composed cell: with the broadcast AND cache
    seeded, bounded rules keep their MUR800-declared per-coordinate
    influence cardinality although a second lever (codec or [k, N]
    masks) stands between exchange and rule math."""
    from murmura_tpu.analysis.ir import _rule_anchor
    from murmura_tpu.analysis.staleness import _STALE_SENDER, _taint_run

    path, line = _rule_anchor(rule)
    cell, closed, args, adj_f, base = _composed_stale_cell(
        rule, mode, fold_factory
    )
    n = cell.n
    out_t, _cache_t = _taint_run(
        closed, args, n, seed_bcast=True, seed_cache=True
    )
    influence = cell.agg.influence
    if influence is None or influence.kind != "bounded":
        return [Finding(
            "MUR1403", path, line,
            f"[{rule}/{mode}] composed taint cell ran on a rule "
            "without a bounded influence declaration — the probe is "
            "vacuous; pick a bounded rule for COMPOSED_TAINT_CELLS",
        )]
    findings: List[Finding] = []
    per_coord = out_t.sum(axis=0)  # [N, P] distinct-label counts
    self_t = out_t[np.arange(n), np.arange(n)]
    card_i = (per_coord - self_t).max(axis=1)  # [N]
    if mode == "sparse_stale":
        # [k, N] masks: per-receiver degree is offset-direction
        # dependent; restrict to degree-invariant bounds (see
        # COMPOSED_TAINT_CELLS) and use the full-degree bound.
        bounds = {influence.bound(d) for d in range(1, n)}
        if len(bounds) != 1:
            return [Finding(
                "MUR1403", path, line,
                f"[{rule}/{mode}] the sparse composed cell needs a "
                "degree-invariant influence bound but "
                f"'{rule}' declares {sorted(bounds)} over degrees "
                "1..n-1 — move the rule to the compressed cell",
            )]
        bound = bounds.pop()
        for i in range(n):
            if int(card_i[i]) > bound:
                findings.append(Finding(
                    "MUR1403", path, line,
                    f"[{rule}/{mode}] the composed sparse+stale step "
                    f"mixes {int(card_i[i])} neighbors into receiver "
                    f"{i}'s output coordinate but the rule declares a "
                    f"degree-invariant bound of {bound} — the second "
                    "lever widened the rule's per-coordinate influence",
                ))
        return findings
    eff = adj_f > 0
    eff[:, _STALE_SENDER] |= base[:, _STALE_SENDER] > 0
    for i in range(n):
        bound = influence.bound(int(eff[i].sum()))
        if int(card_i[i]) > bound:
            findings.append(Finding(
                "MUR1403", path, line,
                f"[{rule}/{mode}] the composed compress+stale step "
                f"mixes {int(card_i[i])} neighbors into receiver "
                f"{i}'s output coordinate but the rule declares a "
                f"bound of {bound} at its effective degree "
                f"{int(eff[i].sum())} — the codec round-trip widened "
                "the rule's per-coordinate influence",
            ))
    return findings


@_family
def check_composed_taint() -> List[Finding]:
    """MUR1403 over the composed taint cells (trace-only)."""
    findings: List[Finding] = []
    for mode, rule in COMPOSED_TAINT_CELLS:
        try:
            findings.extend(composed_taint_findings(mode, rule))
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            from murmura_tpu.analysis.ir import _rule_anchor

            path, line = _rule_anchor(rule)
            findings.append(Finding(
                "MUR1403", path, line,
                f"[{rule}/{mode}] composed taint probe crashed: "
                f"{type(e).__name__}: {e}",
            ))
    return findings


# --------------------------------------------------------------------------
# Entry point
# --------------------------------------------------------------------------

_COMPOSITION_MEMO: Optional[List[Finding]] = None


def check_composition(force: bool = False) -> List[Finding]:
    """Run MUR1400-1403; returns findings (empty = the declared grid and
    the shipped code agree everywhere).  Memoized per process — the CLI,
    the battery pre-flight and the test gate share one sweep."""
    global _COMPOSITION_MEMO
    if _COMPOSITION_MEMO is not None and not force:
        return list(_COMPOSITION_MEMO)

    from murmura_tpu.analysis.ir import _apply_suppressions

    findings: List[Finding] = []
    for fam_name, fam in COMPOSE_CHECK_FAMILIES.items():
        try:
            findings.extend(fam())
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            findings.append(Finding(
                "MUR1400", str(Path(__file__).resolve()), 1,
                f"composition check family '{fam_name}' crashed: "
                f"{type(e).__name__}: {e}",
            ))
    findings = _apply_suppressions(list(dict.fromkeys(findings)))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    _COMPOSITION_MEMO = list(findings)
    return findings

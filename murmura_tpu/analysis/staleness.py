"""Bounded-staleness contracts (MUR1100-1103) — part of the default
package check (docs/ROBUSTNESS.md "Bounded staleness").

The stale exchange layer (core/stale.py) threads a payload cache through
the compiled round program: folded adjacency -> delivery inference ->
cache/age update -> re-added discounted edges -> rule math.  Each link
carries an invariant that must stay machine-checked or the robustness
story silently rots:

- **MUR1100 — stale-state registry bijection.**  ``STALE_STATE_KEYS``
  must be registered in the MUR900 snapshot registry under its defining
  module, its keys distinct and ``stale_``-prefixed, and
  ``init_stale_state`` must emit exactly those keys with the [N, P]
  cache / [N] float32 age shapes the scan carry, gang vmap and
  durability snapshot rely on.
- **MUR1101 — recompile-free staleness.**  The cache, ages and the
  per-round stale/fresh split are carried state and input values; a
  stale-enabled round program compiles once and every staleness
  variation — churn filling and draining the cache round to round — is
  value-only (:class:`~murmura_tpu.analysis.sanitizers.CompileTracker`).
  The probe also requires the cache to actually serve edges, so a
  silently-dead stale layer cannot pass vacuously.
- **MUR1102 — collective-inventory parity.**  The stale fold is
  elementwise math plus adjacency column sums (dense) or rolls of [N]
  rows (sparse); the stale round program's traced collective inventory
  must equal the drop-sync faulted program's, per rule x dense/sparse —
  tolerating staleness must not add communication.
- **MUR1103 — staleness influence bounds + the replay hole.**  Run the
  taint interpreter (analysis/flow.py) over the composed stale-fold +
  aggregation step with broadcast AND cache rows label-seeded: bounded
  rules (krum/median/trimmed/ubar) must keep their declared MUR800
  per-coordinate influence cardinality when stale rows enter rule math
  (a cached row is still ONE neighbor), a scrubbed sender's current
  broadcast must never reach the cache, and a scrubbed/expired sender's
  CACHED copy must never reach the aggregated output — the replay hole
  an adaptive attacker (alternating loud rounds with quiet cache
  replays) would otherwise exploit.

Like ``check_adaptive``, MUR1101 compiles and runs tiny programs, so the
family is memoized per process and runs by default only for the package
check; tests gate representative cells per tier-1 run
(tests/test_staleness.py) and negatives prove each probe can fire.
"""

from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from murmura_tpu.analysis.lint import Finding

# Registry of check families in this module: name -> callable, scanned by
# analysis/ir.py's check_coverage so an unwired family is a MUR205
# finding (the flow.py/durability.py/adaptive.py twin pattern).
STALE_CHECK_FAMILIES: Dict[str, Callable[[], List[Finding]]] = {}


def _family(fn):
    STALE_CHECK_FAMILIES[fn.__name__] = fn
    return fn


_PKG = Path(__file__).resolve().parent.parent
_STALE_PATH = str(_PKG / "core" / "stale.py")

# The trace-level collective vocabulary — IMPORTED from the MUR1002
# check so the two parity checks cannot drift on what counts as
# communication.
from murmura_tpu.analysis.adaptive import _COLLECTIVE_PRIMS  # noqa: E402

# The exchange layouts the staleness grids sweep: the dense [N, N]
# adjacency fold and the sparse [k, N] edge-mask fold (one_peer has no
# static base mask and mobility no static graph — both are rejected at
# schema validation, so there is nothing to sweep there).
STALE_MODES: Tuple[str, ...] = ("dense", "sparse")


def _rule_anchor(rule: str) -> Tuple[str, int]:
    from murmura_tpu.analysis.ir import _rule_anchor as anchor

    return anchor(rule)


# --------------------------------------------------------------------------
# MUR1100 — stale-state registry bijection
# --------------------------------------------------------------------------


@_family
def check_stale_state_registry() -> List[Finding]:
    """MUR1100: STALE_STATE_KEYS <-> init_stale_state <-> MUR900 snapshot
    registry, all bijective and shape-sound."""
    findings: List[Finding] = []
    try:
        from murmura_tpu.core.stale import (
            STALE_STATE_KEYS,
            StalenessSpec,
            init_stale_state,
        )
        from murmura_tpu.durability.snapshot import (
            RESERVED_AGG_STATE_KEY_GROUPS,
        )
    except Exception as e:  # noqa: BLE001 — the import failure IS the finding
        return [Finding(
            "MUR1100", _STALE_PATH, 1,
            f"the staleness module failed to import "
            f"({type(e).__name__}: {e}) — the MUR1100 bijection cannot "
            "be checked",
        )]

    keys = tuple(STALE_STATE_KEYS)
    if len(set(keys)) != len(keys) or any(
        not k.startswith("stale_") for k in keys
    ):
        findings.append(Finding(
            "MUR1100", _STALE_PATH, 1,
            f"STALE_STATE_KEYS must be distinct 'stale_'-prefixed "
            f"agg_state keys, got {keys} — the prefix is how telemetry "
            "and report consumers recognize staleness state",
        ))
    reg = RESERVED_AGG_STATE_KEY_GROUPS.get("STALE_STATE_KEYS")
    if reg != "murmura_tpu.core.stale":
        findings.append(Finding(
            "MUR1100", _STALE_PATH, 1,
            "STALE_STATE_KEYS is not registered in durability.snapshot."
            f"RESERVED_AGG_STATE_KEY_GROUPS under its defining module "
            f"(got {reg!r}) — the payload cache would be invisible to "
            "the MUR900 snapshot-completeness contract and a SIGKILL "
            "mid-round would silently resume with a cold cache",
        ))
    try:
        spec = StalenessSpec(max_staleness=2, discount=0.5)
    except Exception as e:  # noqa: BLE001 — a crash IS the finding
        findings.append(Finding(
            "MUR1100", _STALE_PATH, 1,
            f"StalenessSpec(2, 0.5) crashed: {type(e).__name__}: {e}",
        ))
        return findings
    for n, p in ((4, 7), (9, 3)):
        init = init_stale_state(spec, n, p, np.float32)
        if set(init) != set(keys):
            findings.append(Finding(
                "MUR1100", _STALE_PATH, 1,
                f"init_stale_state keys {sorted(init)} != "
                f"STALE_STATE_KEYS {sorted(keys)} — the round program "
                "seeds agg_state from the reservation",
            ))
            continue
        cache = np.asarray(init["stale_cache"])
        age = np.asarray(init["stale_age"])
        if cache.shape != (n, p):
            findings.append(Finding(
                "MUR1100", _STALE_PATH, 1,
                f"init stale_cache is shape {cache.shape}, not "
                f"({n}, {p}) — the cache must mirror the exchanged "
                "[N, P] tensor so donation aliases and gang vmap hold",
            ))
        if age.shape != (n,) or age.dtype != np.float32:
            findings.append(Finding(
                "MUR1100", _STALE_PATH, 1,
                f"init stale_age is {age.dtype}{age.shape}, not float32 "
                f"({n},) — ages are per-sender [N] float32 rows",
            ))
        elif not (age > spec.max_staleness).all():
            findings.append(Finding(
                "MUR1100", _STALE_PATH, 1,
                "init stale_age starts within the staleness bound — a "
                "round-0 disruption would serve the all-zeros cache as "
                "a real payload instead of degrading to drop-the-edge",
            ))
    for bad in ({"max_staleness": 0}, {"max_staleness": 2, "discount": 0.0}):
        try:
            StalenessSpec(**bad)
        except ValueError:
            pass
        else:
            findings.append(Finding(
                "MUR1100", _STALE_PATH, 1,
                f"StalenessSpec accepted invalid parameters {bad} — the "
                "spec must refuse configurations the schema layer "
                "already rejects, so direct library use cannot build a "
                "silently-dead stale layer",
            ))
    return findings


# --------------------------------------------------------------------------
# MUR1101 — recompile-free staleness (executable)
# --------------------------------------------------------------------------


def _cell_config(rule: str, mode: str, max_staleness: int = 2):
    """One (rule, mode) staleness cell's tiny-but-real config — the
    durability grid's cell (analysis/durability.py) plus the fault
    schedule and the exchange block, so the executable grids stay one
    inventory."""
    from murmura_tpu.analysis.ir import AGG_CASES
    from murmura_tpu.config import Config

    raw: Dict[str, Any] = {
        "experiment": {"name": f"stale-{rule}-{mode}", "seed": 7,
                       "rounds": 5},
        "topology": {"type": "ring", "num_nodes": 5},
        "aggregation": {"algorithm": rule,
                        "params": dict(AGG_CASES.get(rule, {}))},
        "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.05},
        "data": {"adapter": "synthetic",
                 "params": {"num_samples": 40, "input_shape": [6],
                            "num_classes": 3}},
        "model": {"factory": "mlp",
                  "params": {"input_dim": 6, "hidden_dims": [8],
                             "num_classes": 3}},
        "backend": "simulation",
        "faults": {"enabled": True, "straggler_prob": 0.4,
                   "link_drop_prob": 0.2, "seed": 11},
        "exchange": {"max_staleness": max_staleness,
                     "staleness_discount": 0.5},
    }
    if mode == "sparse":
        raw["topology"] = {"type": "exponential", "num_nodes": 8}
    elif mode != "dense":
        raise ValueError(f"unknown staleness mode {mode!r}")
    return Config.model_validate(raw)


def recompile_cell_findings(rule: str, mode: str = "dense") -> List[Finding]:
    """Run ONE (rule, mode) MUR1101 cell: 2 warmup rounds (the compile),
    then 3 more under CompileTracker — churn fills and drains the cache,
    ages walk their whole range, and none of it may recompile.  The cell
    must also actually serve stale edges (``agg_stale_used`` > 0), so a
    dead stale layer cannot pass vacuously.  Exposed per-cell so tests
    gate a subset (tests/test_staleness.py)."""
    from murmura_tpu.analysis.sanitizers import track_compiles
    from murmura_tpu.utils.factories import build_network_from_config

    path, line = _rule_anchor(rule)
    net = build_network_from_config(_cell_config(rule, mode))
    net.train(rounds=2, verbose=False)
    with track_compiles() as tracker:
        net.train(rounds=3, verbose=False)
    findings: List[Finding] = []
    if tracker.total:
        findings.append(Finding(
            "MUR1101", path, line,
            f"[{rule}/{mode}] 3 stale-enabled rounds after warmup "
            f"compiled {tracker.total} program(s) — the cache and ages "
            "are carried state and the fault masks input values, so "
            "staleness variation must be value-only over one compiled "
            "round program",
        ))
    used = net.history.get("agg_stale_used") or []
    if not any(u > 0 for u in used):
        findings.append(Finding(
            "MUR1101", path, line,
            f"[{rule}/{mode}] a 40% straggler / 20% link-drop schedule "
            "served zero stale edges across 5 rounds — the recompile "
            "check is vacuous (the stale fold is not actually wired "
            "into this rule's round program; check core/rounds.py)",
        ))
    return findings


@_family
def check_stale_recompile() -> List[Finding]:
    """MUR1101 over ``AGGREGATORS x STALE_MODES`` (compiles and runs tiny
    programs — the check_durability cost profile)."""
    from murmura_tpu.aggregation import AGGREGATORS

    findings: List[Finding] = []
    for rule in sorted(AGGREGATORS):
        for mode in STALE_MODES:
            try:
                findings.extend(recompile_cell_findings(rule, mode))
            except Exception as e:  # noqa: BLE001 — a crash IS the finding
                path, line = _rule_anchor(rule)
                findings.append(Finding(
                    "MUR1101", path, line,
                    f"[{rule}/{mode}] stale recompile probe crashed: "
                    f"{type(e).__name__}: {e}",
                ))
    return findings


# --------------------------------------------------------------------------
# MUR1102 — collective-inventory parity (trace-level, per rule x mode)
# --------------------------------------------------------------------------


def _build_stale_programs(rule: str, mode: str):
    """(drop-sync program, stale program) for one (rule, mode) cell —
    identical in every respect except the staleness spec."""
    import jax
    from jax.flatten_util import ravel_pytree

    from murmura_tpu.aggregation import build_aggregator
    from murmura_tpu.analysis.ir import AGG_CASES, canonical_offsets
    from murmura_tpu.attacks.gaussian import make_gaussian_attack
    from murmura_tpu.core.rounds import build_round_program
    from murmura_tpu.core.stale import StalenessSpec
    from murmura_tpu.data.base import FederatedArrays
    from murmura_tpu.faults.schedule import FaultSpec
    from murmura_tpu.models import make_mlp

    n, s = 8, 16
    rng = np.random.default_rng(0)
    data = FederatedArrays(
        x=rng.normal(size=(n, s, 6)).astype(np.float32),
        y=rng.integers(0, 3, size=(n, s)).astype(np.int32),
        mask=np.ones((n, s), np.float32),
        num_samples=np.full((n,), s),
        num_classes=3,
    )
    model = make_mlp(
        input_dim=6, hidden_dims=(8,), num_classes=3,
        evidential=(rule == "evidential_trust"),
    )
    flat0, _ = ravel_pytree(model.init(jax.random.PRNGKey(0)))
    case = dict(AGG_CASES.get(rule, {}))
    offsets = tuple(canonical_offsets(n))
    if mode == "sparse":
        case["exchange_offsets"] = list(offsets)
        case["sparse_exchange"] = True
        sparse_offsets: Optional[Tuple[int, ...]] = offsets
        base = np.ones((len(offsets), n), np.float32)
    else:
        from murmura_tpu.analysis.ir import _canonical_adj

        sparse_offsets = None
        base = np.asarray(_canonical_adj(n, circulant=True), np.float32)
    agg = build_aggregator(
        rule, case, model_dim=int(flat0.size), total_rounds=4
    )
    attack = make_gaussian_attack(
        n, attack_percentage=0.3, noise_std=5.0, seed=7
    )
    common = dict(
        local_epochs=1, batch_size=8, lr=0.05, total_rounds=4, seed=7,
        attack=attack, faults=FaultSpec(), sparse_offsets=sparse_offsets,
    )
    plain = build_round_program(model, agg, data, **common)
    stale = build_round_program(
        model, agg, data,
        staleness=StalenessSpec(
            max_staleness=2, discount=0.5, base_mask=base
        ),
        **common,
    )
    return plain, stale


def _trace_collectives(prog) -> frozenset:
    """Collective primitive names in a FAULTED round program's traced
    jaxpr (the program takes the extra [N] alive input)."""
    import jax
    import jax.numpy as jnp

    from murmura_tpu.analysis.ir import iter_eqns

    n = prog.num_nodes
    if prog.sparse:
        adj = jnp.ones((len(prog.sparse_offsets), n), jnp.float32)
    else:
        adj = jnp.asarray(
            np.ones((n, n), np.float32) - np.eye(n, dtype=np.float32)
        )
    closed = jax.make_jaxpr(prog.train_step)(
        prog.init_params,
        {k: jnp.asarray(v) for k, v in prog.init_agg_state.items()},
        jax.random.PRNGKey(0),
        adj,
        jnp.zeros((n,), jnp.float32),
        jnp.ones((n,), jnp.float32),
        jnp.asarray(0.0, jnp.float32),
        {k: jnp.asarray(v) for k, v in prog.data_arrays.items()},
    )
    return frozenset(
        e.primitive.name for e in iter_eqns(closed)
        if e.primitive.name in _COLLECTIVE_PRIMS
    )


def collective_cell_findings(rule: str, mode: str) -> List[Finding]:
    """One (rule, mode) MUR1102 cell: the stale round program's traced
    collective inventory vs the drop-sync faulted program's — tolerating
    staleness must not add communication."""
    path, line = _rule_anchor(rule)
    plain, stale = _build_stale_programs(rule, mode)
    stray = _trace_collectives(stale) - _trace_collectives(plain)
    if stray:
        return [Finding(
            "MUR1102", path, line,
            f"[{rule}/{mode}] the stale round program traces "
            f"collective(s) {sorted(stray)} absent from the drop-sync "
            "faulted program — the stale fold must stay elementwise "
            "math, adjacency column sums, and rolls of [N] rows",
        )]
    return []


@_family
def check_stale_collectives() -> List[Finding]:
    """MUR1102 over ``AGGREGATORS x STALE_MODES`` (trace-only: nothing
    compiles)."""
    from murmura_tpu.aggregation import AGGREGATORS

    findings: List[Finding] = []
    for rule in sorted(AGGREGATORS):
        for mode in STALE_MODES:
            try:
                findings.extend(collective_cell_findings(rule, mode))
            except Exception as e:  # noqa: BLE001 — a crash IS the finding
                path, line = _rule_anchor(rule)
                findings.append(Finding(
                    "MUR1102", path, line,
                    f"[{rule}/{mode}] stale collective-inventory probe "
                    f"crashed: {type(e).__name__}: {e}",
                ))
    return findings


# --------------------------------------------------------------------------
# MUR1103 — staleness influence bounds + the replay hole (trace-only)
# --------------------------------------------------------------------------

# The probe's cast of senders over the canonical flow cell's graph:
# one usable stale sender (column down, age within bound, clean), one
# scrubbed sender (column down, age within bound, sentinel-caught this
# round), one expired sender (column down, age past the bound).
_STALE_SENDER = 1
_SCRUBBED_SENDER = 2
_EXPIRED_SENDER = 3

# Rules exempt from the probe-C replay-hole taint check, with the reason.
# geometric_median's dense path computes its Weiszfeld distances through
# ``pairwise_l2_distances``, which centers every row on the mean of the
# WHOLE broadcast tensor before the Gram identity — the centering cancels
# exactly in every distance (the dark rows mathematically cannot move the
# result, and their cached values are finite by construction, so no
# 0*inf hazard either), but a value-dataflow taint cannot see the
# cancellation, so every label reaches every weight.  This is the same
# documented analysis limitation that exempts unbounded rules from the
# MUR802 cross-mode parity (analysis/flow.py).  The probe-B cache-write
# contract still applies to these rules in full.
_REPLAY_TAINT_EXEMPT: Dict[str, str] = {
    "geometric_median": "Weiszfeld distances run through the dense "
    "Gram centering mean, which couples all rows in value dataflow "
    "while cancelling exactly in every distance",
}


def _stale_cell(rule: str, fold_factory=None):
    """The composed stale-fold + aggregation step over the canonical
    dense flow cell, plus the concrete seed values the probes share.
    ``fold_factory`` overrides :func:`murmura_tpu.core.stale.
    make_stale_fold` so negative tests can drive the probes with a
    broken fold (tests/test_staleness.py)."""
    import jax
    import jax.numpy as jnp

    from murmura_tpu.analysis.flow import _quiet_tracing, build_flow_cell
    from murmura_tpu.core.stale import (
        AGE_KEY,
        CACHE_KEY,
        StalenessSpec,
        make_stale_fold,
    )

    cell = build_flow_cell(rule, "dense")
    n = cell.n
    own, bcast, adj0 = cell.args[0], cell.args[1], cell.args[2]
    base = np.asarray(adj0, np.float32)
    spec = StalenessSpec(max_staleness=2, discount=0.5, base_mask=base)
    fold = (fold_factory or make_stale_fold)(spec)

    # Fault the adjacency: the three probe senders' columns go dark.
    adj_f = base.copy()
    for s in (_STALE_SENDER, _SCRUBBED_SENDER, _EXPIRED_SENDER):
        adj_f[:, s] = 0.0
    scrub_np = np.ones((n,), np.float32)
    scrub_np[_SCRUBBED_SENDER] = 0.0
    age_np = np.zeros((n,), np.float32)
    age_np[_EXPIRED_SENDER] = spec.age_cap  # saturated: long-dark sender
    rng = np.random.default_rng(1)
    cache_np = np.asarray(rng.normal(size=bcast.shape) * 0.1, np.float32)
    alive = jnp.ones((n,), jnp.float32)
    scrub_ok = jnp.asarray(scrub_np)

    cell_fn = cell.fn
    rest = tuple(cell.args[3:])

    def fn(own_a, bcast_a, adj_a, cache_a, age_a, *rest_a):  # murmura: traced
        bcast_eff, adj_eff, updates, _stats = fold(
            bcast_a, adj_a,
            {CACHE_KEY: cache_a, AGE_KEY: age_a},
            alive, scrub_ok,
        )
        new_flat, _state, _stats2 = cell_fn(
            own_a, bcast_eff, adj_eff, *rest_a
        )
        return new_flat, updates[CACHE_KEY]

    args = (
        own, bcast, jnp.asarray(adj_f),
        jnp.asarray(cache_np), jnp.asarray(age_np),
    ) + rest
    with _quiet_tracing():
        closed = jax.make_jaxpr(fn)(*args)
    return cell, closed, args, adj_f, base


def _taint_run(closed, args, n, seed_bcast: bool, seed_cache: bool):
    """Evaluate the composed step with row labels on the broadcast and/or
    cache leaves; returns (out_taint [L, N, P], cache_taint [L, N, P])."""
    import jax

    from murmura_tpu.analysis.flow import TaintEval, _quiet_tracing, _tz

    flat_args, _ = jax.tree_util.tree_flatten(args)
    arg_leaf_pos: List[int] = []
    for i, a in enumerate(args):
        arg_leaf_pos.extend([i] * len(jax.tree_util.tree_leaves(a)))
    pairs = []
    for leaf, pos in zip(flat_args, arg_leaf_pos):
        v = np.asarray(leaf)
        t = _tz(n, v.shape)
        if (pos == 1 and seed_bcast) or (pos == 3 and seed_cache):
            for lbl in range(n):
                t[lbl, lbl] = True
        pairs.append((v, t))
    ev = TaintEval(n)
    with _quiet_tracing():
        outs = ev.eval_closed(closed, pairs)
    return outs[0][1], outs[1][1]


def stale_influence_findings(rule: str, fold_factory=None) -> List[Finding]:
    """One rule's MUR1103 probes over the composed stale+aggregate step.

    Probe A (bcast + cache seeded): bounded rules keep their declared
    per-coordinate influence cardinality with a stale row in rule math.
    Probe B (bcast seeded): the scrubbed sender's current broadcast never
    reaches the cache; every delivering sender's does.
    Probe C (cache seeded): the scrubbed and expired senders' cached
    copies never reach the aggregated output — the replay hole.
    """
    path, line = _rule_anchor(rule)
    cell, closed, args, adj_f, base = _stale_cell(rule, fold_factory)
    n = cell.n
    findings: List[Finding] = []

    # -- Probe A: influence cardinality with stale rows in rule math ----
    out_t, _cache_t = _taint_run(
        closed, args, n, seed_bcast=True, seed_cache=True
    )
    influence = cell.agg.influence
    if influence is not None and influence.kind == "bounded":
        # Per-RECEIVER comparison: the effective graph is ragged (live
        # edges plus the one usable re-added stale edge; the scrubbed
        # and expired senders stay dark), and bounds like the median's
        # depend on stack parity — bound(k) is not monotone in k, so a
        # single worst-case degree would miss (or fabricate) violations.
        eff = adj_f > 0
        eff[:, _STALE_SENDER] |= base[:, _STALE_SENDER] > 0
        per_coord = out_t.sum(axis=0)  # [N, P] distinct-label counts
        self_t = out_t[np.arange(n), np.arange(n)]  # [N, P]
        card_i = (per_coord - self_t).max(axis=1)  # [N]
        for i in range(n):
            bound = influence.bound(int(eff[i].sum()))
            if int(card_i[i]) > bound:
                findings.append(Finding(
                    "MUR1103", path, line,
                    f"[{rule}] the composed stale+aggregate step mixes "
                    f"{int(card_i[i])} neighbors into receiver {i}'s "
                    f"output coordinate but the rule declares a bound "
                    f"of {bound} at its effective degree "
                    f"{int(eff[i].sum())} — stale rows entering rule "
                    "math widened the rule's per-coordinate influence",
                ))

    # -- Probe B: a scrubbed row must never enter the cache -------------
    _out_b, cache_t = _taint_run(
        closed, args, n, seed_bcast=True, seed_cache=False
    )
    s = _SCRUBBED_SENDER
    if cache_t[s].any():
        findings.append(Finding(
            "MUR1103", path, line,
            f"[{rule}] the scrubbed sender {s}'s current broadcast "
            "taints the updated stale cache — a sentinel-caught row "
            "must never be stored for replay",
        ))
    fresh = [
        j for j in range(n)
        if j not in (_STALE_SENDER, _SCRUBBED_SENDER, _EXPIRED_SENDER)
    ]
    if fresh and not cache_t[fresh[0], fresh[0]].any():
        findings.append(Finding(
            "MUR1103", path, line,
            f"[{rule}] delivering sender {fresh[0]}'s broadcast does "
            "not reach its own cache row — the cache update is not "
            "wired and the replay-hole probes are vacuous",
        ))

    # -- Probe C: scrubbed/expired CACHED copies must not be served -----
    if rule in _REPLAY_TAINT_EXEMPT:
        return findings
    out_c, _ = _taint_run(closed, args, n, seed_bcast=False, seed_cache=True)
    for bad, why in (
        (_SCRUBBED_SENDER, "was scrubbed/quarantined this round"),
        (_EXPIRED_SENDER, "aged past max_staleness"),
    ):
        if out_c[bad].any():
            findings.append(Finding(
                "MUR1103", path, line,
                f"[{rule}] sender {bad}'s CACHED payload taints the "
                f"aggregated output although it {why} — the replay "
                "hole: a caught or expired row survives via its cached "
                "copy",
            ))
    return findings


@_family
def check_stale_influence() -> List[Finding]:
    """MUR1103 over every registered rule (trace-only), plus the
    non-vacuity guard: on fedavg — declared-unbounded, every neighbor
    admitted — the usable stale sender's cached row MUST reach some
    honest receiver's output, proving the probes exercise a live stale
    path rather than an edgeless one."""
    from murmura_tpu.aggregation import AGGREGATORS

    findings: List[Finding] = []
    for rule in sorted(AGGREGATORS):
        try:
            findings.extend(stale_influence_findings(rule))
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            path, line = _rule_anchor(rule)
            findings.append(Finding(
                "MUR1103", path, line,
                f"[{rule}] stale influence probe crashed: "
                f"{type(e).__name__}: {e}",
            ))
    try:
        cell, closed, args, adj_f, base = _stale_cell("fedavg")
        out_c, _ = _taint_run(
            closed, args, cell.n, seed_bcast=False, seed_cache=True
        )
        receivers = np.nonzero(base[:, _STALE_SENDER] > 0)[0]
        served = any(
            out_c[_STALE_SENDER, r].any() for r in receivers
        )
        if not served:
            path, line = _rule_anchor("fedavg")
            findings.append(Finding(
                "MUR1103", path, line,
                "[fedavg] the usable stale sender's cached payload "
                "reaches NO base-graph receiver — the stale path is "
                "dead and every MUR1103 containment verdict above is "
                "vacuous",
            ))
    except Exception as e:  # noqa: BLE001 — a crash IS the finding
        findings.append(Finding(
            "MUR1103", _STALE_PATH, 1,
            f"the MUR1103 non-vacuity guard crashed: "
            f"{type(e).__name__}: {e}",
        ))
    return findings


# --------------------------------------------------------------------------
# Entry point
# --------------------------------------------------------------------------

_STALE_MEMO: Optional[List[Finding]] = None


def check_staleness(force: bool = False) -> List[Finding]:
    """Run MUR1100-1103; returns findings (empty = every bounded-
    staleness contract holds).  Memoized per process — the CLI, the
    battery pre-flight and the slow test gate share one sweep.  MUR1101
    compiles and runs tiny programs (the check_durability cost profile),
    which is why the family runs only for the package-level check."""
    global _STALE_MEMO
    if _STALE_MEMO is not None and not force:
        return list(_STALE_MEMO)

    from murmura_tpu.analysis.ir import _apply_suppressions

    findings: List[Finding] = []
    for fam_name, fam in STALE_CHECK_FAMILIES.items():
        try:
            findings.extend(fam())
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            findings.append(Finding(
                "MUR1100", str(Path(__file__).resolve()), 1,
                f"staleness check family '{fam_name}' crashed: "
                f"{type(e).__name__}: {e}",
            ))
    findings = _apply_suppressions(list(dict.fromkeys(findings)))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    _STALE_MEMO = list(findings)
    return findings

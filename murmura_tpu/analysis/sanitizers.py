"""Opt-in runtime sanitizers for the round hot path.

Two guards, each behind a config flag (``tpu.recompile_guard`` /
``tpu.transfer_guard``) and wired around the Network round loop
(core/network.py):

- **Recompile sanitizer** (:func:`track_compiles`): counts XLA backend
  compiles via the jax.monitoring ``/jax/core/compile`` duration events —
  zero-overhead when quiet, fires exactly once per real compile and never
  on cache hits.  The orchestrator brackets each round with
  ``tracker.begin``/``tracker.end``; a compile in a round after the
  program's warmup execution raises :class:`RecompileError` instead of
  silently degrading a 60ms round into a multi-second XLA build (the
  dominant silent-regression class for the rounds/sec headline).

- **Transfer sanitizer** (:func:`transfer_sanitizer`):
  ``jax.transfer_guard("disallow")`` around the round loop.  The loop's
  deliberate transfers are all *explicit* (``jnp.asarray`` of the per-round
  adjacency, ``jax.device_get`` of recorded metrics) and pass the guard;
  what it catches is *implicit* traffic — a numpy array slipped directly
  into the jitted step, a tracer forced to host mid-trace — each a
  serializing device sync the profiler only shows after the fact.
"""

import contextlib
import threading
from typing import Iterator, List, Optional, Tuple

import jax

# One backend_compile event fires per XLA compilation; trace/lowering
# events also exist but re-tracing without re-compiling is cheap enough
# not to guard.
_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

_lock = threading.Lock()
_listener_installed = False
_compile_count = 0


def _on_event_duration(event: str, duration: float, **_kwargs) -> None:
    global _compile_count
    if event == _COMPILE_EVENT:
        with _lock:
            _compile_count += 1


def _install_listener() -> None:
    # jax.monitoring has no unregister API, so the listener installs once
    # per process and trackers snapshot the monotonic counter instead.
    global _listener_installed
    with _lock:
        if not _listener_installed:
            jax.monitoring.register_event_duration_secs_listener(
                _on_event_duration
            )
            _listener_installed = True


def compile_count() -> int:
    """Process-wide XLA compilations observed since the listener installed."""
    return _compile_count


class RecompileError(RuntimeError):
    """An XLA compilation happened in a round after warmup.

    Post-warmup compiles mean the round program's signature is unstable —
    a shape/dtype drifting between rounds, a non-hashable static arg, a
    fresh ``jax.jit`` per iteration — and each one stalls the device for
    the full XLA build.  See docs/ANALYSIS.md (recompile sanitizer).
    """


class CompileTracker:
    """Per-run compile counter with round bracketing.

    ``begin(label)`` snapshots the counter; ``end(allow=...)`` records the
    round's compile delta and raises :class:`RecompileError` when a
    non-warmup round compiled.  ``per_round`` keeps (label, compiles) pairs
    for diagnostics.

    The underlying counter is process-wide (jax.monitoring has no
    per-callsite events), so *any* compile that lands inside a bracket
    counts — including a first-time eager op in user callback code or a
    second guarded Network in the same process.  That is deliberate for a
    sanitizer (every compile inside the round window stalls the device,
    whoever triggered it), but it means the blamed round program is not
    necessarily the unstable one; the error message says so.
    """

    def __init__(self) -> None:
        _install_listener()
        self._baseline = compile_count()
        self._round_start: Optional[int] = None
        self._sub_start = 0
        self._label = ""
        self.per_round: List[Tuple[str, int]] = []

    @property
    def total(self) -> int:
        """Compiles since this tracker was created."""
        return compile_count() - self._baseline

    def begin(self, label: str) -> None:
        self._round_start = compile_count()
        self._sub_start = self._round_start
        self._label = label

    def mark(self, allow: bool = False) -> int:
        """Close a sub-phase inside the current bracket; returns its count.

        Lets a bracket spanning two programs with different warmup states
        (the per-round step + eval pair) check each phase independently —
        otherwise one program's warmup round would whitelist a post-warmup
        recompile of the other.  The per_round report still gets one entry
        for the whole bracket at ``end``.
        """
        if self._round_start is None:
            raise RuntimeError("CompileTracker.mark() without begin()")
        delta = compile_count() - self._sub_start
        self._sub_start = compile_count()
        if delta and not allow:
            # Record the full-bracket delta, same unit end() reports, so
            # last_compile_report stays comparable across rounds.
            self.per_round.append(
                (self._label, compile_count() - self._round_start)
            )
            self._round_start = None
            raise RecompileError(self._violation(delta))
        return delta

    def end(self, allow: bool = False) -> int:
        """Close the current bracket; returns its total compile count.

        Args:
            allow: True for warmup phases (a program's first execution
                legitimately compiles); False raises on any compile since
                the last ``mark`` (or ``begin``).
        """
        if self._round_start is None:
            raise RuntimeError("CompileTracker.end() without begin()")
        delta = compile_count() - self._round_start
        sub_delta = compile_count() - self._sub_start
        self.per_round.append((self._label, delta))
        self._round_start = None
        if sub_delta and not allow:
            raise RecompileError(self._violation(sub_delta))
        return delta

    def _violation(self, delta: int) -> str:
        return (
            f"{delta} XLA compilation(s) during {self._label!r} after "
            "warmup — a program signature is unstable (shape/dtype "
            "drift or non-static argument), or other code compiled "
            "inside the round window (the counter is process-wide); "
            f"history: {self.per_round}"
        )


@contextlib.contextmanager
def track_compiles() -> Iterator[CompileTracker]:
    """Context manager yielding a fresh :class:`CompileTracker`."""
    yield CompileTracker()


@contextlib.contextmanager
def transfer_sanitizer() -> Iterator[None]:
    """``jax.transfer_guard("disallow")`` scope for the round loop.

    Explicit transfers (``jnp.asarray``, ``jax.device_put``,
    ``jax.device_get``) pass; implicit host↔device traffic raises inside
    the scope.
    """
    with jax.transfer_guard("disallow"):
        yield

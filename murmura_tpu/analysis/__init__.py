"""Static analysis + runtime sanitizers for the murmura_tpu codebase.

``python -m murmura_tpu check [path]`` — a JAX-aware lint pass (see
:mod:`murmura_tpu.analysis.lint`) plus cross-layer contract checks
(:mod:`murmura_tpu.analysis.contracts`).  The runtime sanitizers
(:mod:`murmura_tpu.analysis.sanitizers`) are opt-in guards wired into the
round loop behind ``tpu.recompile_guard`` / ``tpu.transfer_guard``.

Rationale (round-5 verdict): the framework's correctness rests on
non-local invariants the type system cannot see — zero-diagonal adjacency,
registry/schema/test sync, no host syncs or recompiles inside the round
hot path.  ``check`` turns each into a machine-checked contract.  See
docs/ANALYSIS.md for the rule catalogue and suppression syntax.
"""

from murmura_tpu.analysis.lint import Finding, lint_file, lint_paths
from murmura_tpu.analysis.contracts import check_contracts
from murmura_tpu.analysis.sanitizers import (
    CompileTracker,
    RecompileError,
    track_compiles,
    transfer_sanitizer,
)

from pathlib import Path
from typing import Iterable, List, Optional, Sequence


def run_check(
    paths: Optional[Sequence] = None, contracts: bool = True
) -> List[Finding]:
    """Run the full static pass: AST lint over ``paths`` (default: the
    installed murmura_tpu package) plus the cross-layer contract checks.

    Returns all findings sorted by (path, line); empty means clean.
    """
    if not paths:
        paths = [Path(__file__).resolve().parent.parent]
    findings = list(lint_paths(paths))
    if contracts:
        findings.extend(check_contracts())
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def format_findings(findings: Iterable[Finding]) -> str:
    """One greppable line per finding: ``path:line: RULE [name] message``."""
    return "\n".join(
        f"{f.path}:{f.line}: {f.rule} [{f.name}] {f.message}" for f in findings
    )


__all__ = [
    "Finding",
    "lint_file",
    "lint_paths",
    "check_contracts",
    "run_check",
    "format_findings",
    "CompileTracker",
    "RecompileError",
    "track_compiles",
    "transfer_sanitizer",
]

"""Static analysis + runtime sanitizers for the murmura_tpu codebase.

``python -m murmura_tpu check [path]`` — a JAX-aware lint pass (see
:mod:`murmura_tpu.analysis.lint`), cross-layer contract checks
(:mod:`murmura_tpu.analysis.contracts`), and — for the package check — the
jaxpr/HLO-level IR contracts (:mod:`murmura_tpu.analysis.ir`, MUR200-205)
plus committed AOT cost budgets (:mod:`murmura_tpu.analysis.budgets`,
MUR206).  The runtime sanitizers (:mod:`murmura_tpu.analysis.sanitizers`)
are opt-in guards wired into the round loop behind ``tpu.recompile_guard``
/ ``tpu.transfer_guard``.

Rationale (round-5 verdict + ISSUE 2): the framework's correctness rests
on non-local invariants the type system cannot see — zero-diagonal
adjacency, registry/schema/test sync, no host syncs or recompiles inside
the round hot path — and its *performance* rests on IR-level invariants
the AST can only approximate: collective inventory, dtype discipline
through the dataflow, donation, shape-stable programs, and each
aggregator's FLOPs/bytes envelope.  ``check`` turns each into a
machine-checked contract.  See docs/ANALYSIS.md for the rule catalogue and
suppression syntax.
"""

from murmura_tpu.analysis.lint import Finding, lint_file, lint_paths
from murmura_tpu.analysis.contracts import check_contracts
from murmura_tpu.analysis.sanitizers import (
    CompileTracker,
    RecompileError,
    track_compiles,
    transfer_sanitizer,
)

import json
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple


def run_check_detailed(
    paths: Optional[Sequence] = None,
    contracts: bool = True,
    ir: Optional[bool] = None,
    budget_path=None,
) -> Tuple[List[Finding], List[Dict[str, Any]]]:
    """Run the full static pass and return ``(findings, budget_deltas)``.

    The pass layers: AST lint over ``paths`` (default: the installed
    murmura_tpu package), the cross-layer contract checks, and — when
    ``ir`` is enabled — the jaxpr/HLO IR contracts (analysis/ir.py,
    MUR200-205) plus the AOT cost-budget sweep (analysis/budgets.py,
    MUR206).  ``ir=None`` means "on for the package check, off for
    explicit paths" (the IR pass is package-global: it traces the live
    registry, not the files named on the command line).

    ``budget_deltas`` carries one record per budget grid cell (measured vs
    committed flops/bytes, including in-tolerance cells) and is empty when
    the IR pass does not run.
    """
    run_ir = ir if ir is not None else not paths
    if not paths:
        paths = [Path(__file__).resolve().parent.parent]
    findings = list(lint_paths(paths))
    if contracts:
        findings.extend(check_contracts())
    deltas: List[Dict[str, Any]] = []
    if run_ir:
        from murmura_tpu.analysis import budgets as budgets_mod
        from murmura_tpu.analysis import ir as ir_mod

        findings.extend(ir_mod.check_ir())
        budget_findings, deltas = budgets_mod.check_budgets(budget_path)
        findings.extend(budget_findings)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, deltas


def run_check(
    paths: Optional[Sequence] = None,
    contracts: bool = True,
    ir: Optional[bool] = None,
) -> List[Finding]:
    """Findings-only wrapper of :func:`run_check_detailed` (the historical
    API; empty result means clean)."""
    return run_check_detailed(paths, contracts=contracts, ir=ir)[0]


def format_findings(findings: Iterable[Finding]) -> str:
    """One greppable line per finding: ``path:line: RULE [name] message``."""
    return "\n".join(
        f"{f.path}:{f.line}: {f.rule} [{f.name}] {f.message}" for f in findings
    )


def format_findings_json(
    findings: Iterable[Finding],
    budget_deltas: Optional[Iterable[Dict[str, Any]]] = None,
) -> str:
    """JSON-lines rendering for editors/CI (``check --json``): one
    ``{"kind": "finding", ...}`` object per finding followed by one
    ``{"kind": "budget_delta", ...}`` object per budget grid cell."""
    lines = [
        json.dumps(
            {
                "kind": "finding",
                "rule": f.rule,
                "name": f.name,
                "path": f.path,
                "line": f.line,
                "message": f.message,
                **({"data": f.data} if f.data else {}),
            }
        )
        for f in findings
    ]
    for rec in budget_deltas or ():
        lines.append(json.dumps({"kind": "budget_delta", **rec}))
    return "\n".join(lines)


__all__ = [
    "Finding",
    "lint_file",
    "lint_paths",
    "check_contracts",
    "run_check",
    "run_check_detailed",
    "format_findings",
    "format_findings_json",
    "CompileTracker",
    "RecompileError",
    "track_compiles",
    "transfer_sanitizer",
]

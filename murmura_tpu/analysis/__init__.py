"""Static analysis + runtime sanitizers for the murmura_tpu codebase.

``python -m murmura_tpu check [path]`` — a JAX-aware lint pass (see
:mod:`murmura_tpu.analysis.lint`), cross-layer contract checks
(:mod:`murmura_tpu.analysis.contracts`), and — for the package check — the
jaxpr/HLO-level IR contracts (:mod:`murmura_tpu.analysis.ir`, MUR200-205)
plus committed AOT cost budgets (:mod:`murmura_tpu.analysis.budgets`,
MUR206).  The runtime sanitizers (:mod:`murmura_tpu.analysis.sanitizers`)
are opt-in guards wired into the round loop behind ``tpu.recompile_guard``
/ ``tpu.transfer_guard``.

Rationale (round-5 verdict + ISSUE 2): the framework's correctness rests
on non-local invariants the type system cannot see — zero-diagonal
adjacency, registry/schema/test sync, no host syncs or recompiles inside
the round hot path — and its *performance* rests on IR-level invariants
the AST can only approximate: collective inventory, dtype discipline
through the dataflow, donation, shape-stable programs, and each
aggregator's FLOPs/bytes envelope.  ``check`` turns each into a
machine-checked contract.  See docs/ANALYSIS.md for the rule catalogue and
suppression syntax.
"""

from murmura_tpu.analysis.lint import Finding, lint_file, lint_paths
from murmura_tpu.analysis.contracts import check_contracts
from murmura_tpu.analysis.sanitizers import (
    CompileTracker,
    RecompileError,
    track_compiles,
    transfer_sanitizer,
)

import json
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple


def run_check_detailed(
    paths: Optional[Sequence] = None,
    contracts: bool = True,
    ir: Optional[bool] = None,
    budget_path=None,
    flow: Optional[bool] = None,
    durability: Optional[bool] = None,
    adaptive: Optional[bool] = None,
    staleness: Optional[bool] = None,
    pipeline: Optional[bool] = None,
    sharded: Optional[bool] = None,
    compose: Optional[bool] = None,
    memory: Optional[bool] = None,
    serve: Optional[bool] = None,
    observe: Optional[bool] = None,
) -> Tuple[List[Finding], List[Dict[str, Any]]]:
    """Run the full static pass and return ``(findings, records)``.

    The pass layers: AST lint over ``paths`` (default: the installed
    murmura_tpu package), the cross-layer contract checks, when ``ir`` is
    enabled the jaxpr/HLO IR contracts (analysis/ir.py, MUR200-205) plus
    the AOT cost-budget sweep (analysis/budgets.py, MUR206), when ``flow``
    is enabled the jaxpr dataflow contracts (analysis/flow.py,
    MUR800-804), and when ``durability`` is enabled the executable
    resume-determinism contract (analysis/durability.py, MUR901/902:
    save→restore→replay byte-equality + zero-recompile restore per
    rule x exchange mode), and when ``adaptive`` is enabled the
    adaptive-adversary contracts (analysis/adaptive.py, MUR1000-1003:
    attack-state registry bijection, recompile-free adaptation,
    collective-inventory parity, feedback taint containment), and when
    ``staleness`` is enabled the bounded-staleness contracts
    (analysis/staleness.py, MUR1100-1103: stale-state registry
    bijection, zero recompiles across staleness variation,
    collective-inventory parity with the drop-sync program, and the
    influence-bound/replay-hole taint runs over the staleness path),
    and when ``pipeline`` is enabled the pipelined-rounds contracts
    (analysis/pipeline.py, MUR1200-1203: pipeline-state registry
    bijection, zero recompiles across buffer swaps,
    collective-inventory parity with the serialized program, and the
    delayed-step influence/lagging-verdict taint runs), and when
    ``sharded`` is enabled the param-axis sharding contracts
    (analysis/sharded.py, MUR1300-1303: sharded-P collective
    inventory — ppermute-only on "nodes", one small psum over "param"
    — zero recompiles across sharded rounds, shards=1 bit-parity with
    the unsharded program, and sharded execution parity), and when
    ``compose`` is enabled the cross-feature composition grid
    (analysis/composition.py, MUR1400-1403: lever-manifest/guard
    bijection with the executable refusal census, the generated
    pairwise grid over every declared-compatible pair — recompile-free
    composed builds with collective-inventory parity — composed
    carried-state/stage-order parity, and flow-taint preservation on
    composed cells), and when ``memory`` is enabled the static memory
    contracts (analysis/memory.py, MUR1500-1503: committed
    ``memory_analysis()`` budgets per (rule x topology x feature) grid
    cell against analysis/MEMORY.json, per-device peak shrinking
    ~P/shards across shards {1, 2, 4} on the param mesh, donation
    completeness per carried leaf against the MUR900 key-group
    registry, and the overlap-dependence proof that the pipelined
    program's buffered aggregation has no def-use path from the round's
    training subgraph), and when ``serve`` is enabled the serving
    contracts (analysis/serve.py, MUR1600-1603: bucket-key soundness —
    two cells share a scheduler bucket ⇔ their independently-traced
    jaxpr skeletons are structurally equal — zero recompiles across
    warm-bucket admissions, frozen-lane non-interference under
    eviction, and daemon kill+recover resume completeness with
    byte-identical histories), and when ``observe`` is enabled the
    observability contracts (analysis/observe.py, MUR1700-1703:
    metrics↔ledger parity — a daemon scrape equals an independent
    replay of the durable ledger + event streams — scrape
    non-interference (polling metrics/ping/list mid-generation causes
    zero recompiles and byte-identical tenant histories), trace-span
    well-formedness with phase_times reconciliation, and schema
    discipline — the v2 event additions carry their migration note and
    v1 streams still render).
    ``ir=None``/``flow=None``/``durability=None``/``adaptive=None``/
    ``staleness=None``/``pipeline=None``/``sharded=None``/
    ``compose=None``/``memory=None``/``serve=None``/``observe=None``
    mean "on for the package check, off for explicit paths" (all eleven
    passes are package-global: they exercise the live registry, not the
    files named on the command line).

    ``records`` carries machine-readable non-finding rows for
    ``check --json``: one ``{"kind": "budget_delta", ...}`` per budget
    grid cell (measured vs committed flops/bytes, including in-tolerance
    cells) and one ``{"kind": "flow_summary", ...}`` per (rule, exchange
    mode) flow cell with its per-node taint-set payload, plus one
    ``{"kind": "compose_summary", ...}`` per composition-grid pair with
    its verdict, cell kind and recompile count, and one
    ``{"kind": "memory_summary", ...}`` per memory grid cell (measured
    vs committed temp/argument/output/generated/peak bytes, including
    in-tolerance cells).
    """
    run_ir = ir if ir is not None else not paths
    run_flow = flow if flow is not None else not paths
    run_durability = durability if durability is not None else not paths
    run_adaptive = adaptive if adaptive is not None else not paths
    run_staleness = staleness if staleness is not None else not paths
    run_pipeline = pipeline if pipeline is not None else not paths
    run_sharded = sharded if sharded is not None else not paths
    run_compose = compose if compose is not None else not paths
    run_memory = memory if memory is not None else not paths
    run_serve = serve if serve is not None else not paths
    run_observe = observe if observe is not None else not paths
    if not paths:
        paths = [Path(__file__).resolve().parent.parent]
    findings = list(lint_paths(paths))
    if contracts:
        findings.extend(check_contracts())
    records: List[Dict[str, Any]] = []
    if run_ir:
        from murmura_tpu.analysis import budgets as budgets_mod
        from murmura_tpu.analysis import ir as ir_mod

        findings.extend(ir_mod.check_ir())
        budget_findings, deltas = budgets_mod.check_budgets(budget_path)
        findings.extend(budget_findings)
        records.extend({"kind": "budget_delta", **d} for d in deltas)
    if run_flow:
        from murmura_tpu.analysis import flow as flow_mod

        findings.extend(flow_mod.check_flow())
        records.extend(flow_mod.flow_summaries())
    if run_durability:
        from murmura_tpu.analysis import durability as durability_mod

        findings.extend(durability_mod.check_durability())
    if run_adaptive:
        from murmura_tpu.analysis import adaptive as adaptive_mod

        findings.extend(adaptive_mod.check_adaptive())
    if run_staleness:
        from murmura_tpu.analysis import staleness as staleness_mod

        findings.extend(staleness_mod.check_staleness())
    if run_pipeline:
        from murmura_tpu.analysis import pipeline as pipeline_mod

        findings.extend(pipeline_mod.check_pipeline())
    if run_sharded:
        from murmura_tpu.analysis import sharded as sharded_mod

        findings.extend(sharded_mod.check_sharded())
    if run_compose:
        from murmura_tpu.analysis import composition as composition_mod

        findings.extend(composition_mod.check_composition())
        records.extend(composition_mod.compose_summaries())
    if run_memory:
        from murmura_tpu.analysis import memory as memory_mod

        findings.extend(memory_mod.check_memory())
        records.extend(memory_mod.memory_summaries())
    if run_serve:
        from murmura_tpu.analysis import serve as serve_mod

        findings.extend(serve_mod.check_serve())
    if run_observe:
        from murmura_tpu.analysis import observe as observe_mod

        findings.extend(observe_mod.check_observe())
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings, records


def run_check(
    paths: Optional[Sequence] = None,
    contracts: bool = True,
    ir: Optional[bool] = None,
    flow: Optional[bool] = None,
    durability: Optional[bool] = None,
    adaptive: Optional[bool] = None,
    staleness: Optional[bool] = None,
    pipeline: Optional[bool] = None,
    sharded: Optional[bool] = None,
    compose: Optional[bool] = None,
    memory: Optional[bool] = None,
    serve: Optional[bool] = None,
    observe: Optional[bool] = None,
) -> List[Finding]:
    """Findings-only wrapper of :func:`run_check_detailed` (the historical
    API; empty result means clean)."""
    return run_check_detailed(
        paths, contracts=contracts, ir=ir, flow=flow, durability=durability,
        adaptive=adaptive, staleness=staleness, pipeline=pipeline,
        sharded=sharded, compose=compose, memory=memory, serve=serve,
        observe=observe,
    )[0]


def format_findings(findings: Iterable[Finding]) -> str:
    """One greppable line per finding: ``path:line: RULE [name] message``."""
    return "\n".join(
        f"{f.path}:{f.line}: {f.rule} [{f.name}] {f.message}" for f in findings
    )


def format_findings_json(
    findings: Iterable[Finding],
    records: Optional[Iterable[Dict[str, Any]]] = None,
) -> str:
    """JSON-lines rendering for editors/CI (``check --json``): one
    ``{"kind": "finding", ...}`` object per finding followed by the
    non-finding records — ``budget_delta`` rows per cost grid cell,
    ``flow_summary`` rows per (rule, exchange mode) flow cell (their
    per-rule taint-set payloads ride ``data``/``taint_sets``) and
    ``compose_summary`` rows per composition-grid pair.  Legacy
    callers may still pass bare budget-delta dicts; they default to
    ``kind: budget_delta``."""
    lines = [
        json.dumps(
            {
                "kind": "finding",
                "rule": f.rule,
                "name": f.name,
                "path": f.path,
                "line": f.line,
                "message": f.message,
                **({"data": f.data} if f.data else {}),
            }
        )
        for f in findings
    ]
    for rec in records or ():
        lines.append(json.dumps({"kind": "budget_delta", **rec}))
    return "\n".join(lines)


__all__ = [
    "Finding",
    "lint_file",
    "lint_paths",
    "check_contracts",
    "run_check",
    "run_check_detailed",
    "format_findings",
    "format_findings_json",
    "CompileTracker",
    "RecompileError",
    "track_compiles",
    "transfer_sanitizer",
]

"""jaxpr dataflow contracts (MUR800-804) — ``murmura check --flow``.

The third layer of the analysis subsystem, and the first that reasons about
*values* rather than program shape: two composable abstract domains over
the lowered jaxprs of every registered aggregation rule.

**Domain 1 — per-neighbor taint/influence (MUR800-802).**  Each exchanged
broadcast row is seeded with a distinct taint label and propagated through
the rule's jaxpr by a concrete taint interpreter: every equation is
evaluated on the canonical inputs while a boolean label tensor rides along.
The semantics track *value* dataflow — selection dataflow is excluded by
construction:

- comparison outputs carry no taint (they decide WHICH values are chosen,
  not what they are);
- ``sort`` permutes taints by the concrete sort permutation (each output
  element IS one input element);
- ``gather``/``dynamic_slice``/``top_k`` move the gathered elements' taints
  and ignore the index operands';
- ``select_n`` follows the concretely chosen case and drops the predicate;
- multiplication by an exact zero kills the other operand's taint (a
  0-weighted neighbor contributes nothing — sound because the MUR803
  scrub-dominance check separately proves rule math only sees finite
  values, so 0 * x == 0).

The result is, per output coordinate, the set of neighbors whose broadcast
VALUES can enter it — Krum analyzes to its single winner, the trimmed mean
to its kept interior, fedavg to the whole neighborhood.  MUR800 checks the
cardinality against the rule's declared ``AggregatorDef.influence``
contract; MUR801 requires every registered rule to declare one; MUR802
pins the analyzed per-node cardinality's parity across the
dense/circulant/sparse/compressed exchange modes of the same rule (all
built over the SAME canonical k-regular graph so the numbers are
comparable).

**Domain 2 — interval/finiteness (MUR803-804).**  A classic abstract
interpreter: whole-array [lo, hi] intervals plus a finiteness-contamination
flag propagated from the exchange inputs.  The contamination flag tracks
non-finiteness *originating from data* (diverged training math, attack
noise, bit-cast RNG output) — deliberate ``inf`` literals (sort padding)
stay clean, and arithmetic semantics are real-valued (float overflow is
out of scope; the runtime sentinel owns it).  The ``isfinite`` guard
pattern is recognized relationally: a predicate derived from
``isfinite(x)`` (through ``all``/``&``/``~``/broadcasts) discharges x's
contamination on the branch it implies finite, so the rounds.py sentinel
scrubs — ``where(isfinite(update).all(1)[:, None], update, snapshot)`` —
provably dominate.

- MUR803 runs the interpreter over full *faulted* round programs
  (attack + NaN sentinel armed) with divergence-capable seeds and fails if
  contamination can reach the output parameters or carried aggregation
  state — the static retirement of the ``0 * inf`` class PR 3's runtime
  sentinel handles dynamically.  A mask applied by multiplication instead
  of ``where``-replacement leaves the contamination flag set (0 * nan is
  nan), so the exact bug class PR 3 fixed by hand cannot come back
  silently.
- MUR804 scans every rule cell (all exchange modes) and the compression
  codec for division/rsqrt equations whose denominator interval contains
  zero given the post-scrub seeds (inputs finite but arbitrary, adjacency
  in [0, 1], the codec's symmetric-scale invariants) — the Weiszfeld
  ``1/max(d, nu)`` guards and compress.py's guarded scale division
  verify clean; an unguarded denominator is a finding anchored at its
  source line.

Suppression: MUR800-802 anchor to the rule factory ``def`` line (the IR
pass's convention); MUR803 anchors to core/rounds.py; MUR804 anchors to
the offending source line (falling back to the factory line), where the
ordinary ``# murmura: ignore[MUR804]`` applies.
"""

import contextlib
import dataclasses
import math
import warnings
from pathlib import Path
from typing import (
    Any,
    Callable,
    Dict,
    FrozenSet,
    List,
    Optional,
    Sequence,
    Set,
    Tuple,
)

import numpy as np

from murmura_tpu.analysis.lint import Finding

# --------------------------------------------------------------------------
# Canonical flow grid
# --------------------------------------------------------------------------

FLOW_N = 8  # nodes == taint labels; the canonical k-regular(4) graph
FLOW_DIM = 100  # non-probe flat dimension (pads to 4 blocks of 32)
FLOW_BLOCK = 32  # compressed-cell quant block (exercises padding: 100 % 32)
_PROBE_IN = 8
_PROBE_BATCH = 8
_PROBE_CLASSES = 4

# Exchange modes the influence analysis sweeps.  ``compressed`` applies to
# quantized_exchange rules only (the others receive the receiver-side
# dequantized tensor, which is taint-identical to the dense float path).
FLOW_MODES: Tuple[str, ...] = ("dense", "circulant", "sparse", "compressed")

# Check families this module registers (the ir.check_coverage registry
# sweep asserts every module-level ``check_*`` is wired through here).
FLOW_CHECK_FAMILIES: Dict[str, Callable[[], List[Finding]]] = {}


def _family(fn):
    FLOW_CHECK_FAMILIES[fn.__name__] = fn
    return fn


# --------------------------------------------------------------------------
# Shared jaxpr walking
# --------------------------------------------------------------------------


@contextlib.contextmanager
def _quiet_tracing():
    """Tracing/eager-binding rule cells constant-folds over deliberate inf
    padding; numpy's 'invalid value encountered in cast' warnings there
    are expected and non-actionable."""
    with warnings.catch_warnings():
        warnings.simplefilter("ignore", RuntimeWarning)
        yield


def _closed(sub) -> Any:
    """Normalize an eqn param that holds a jaxpr into a ClosedJaxpr."""
    import jax

    if isinstance(sub, jax.core.ClosedJaxpr):
        return sub
    return jax.core.ClosedJaxpr(sub, ())


def _sub_jaxpr(eqn):
    """The callee ClosedJaxpr of a call-like primitive, else None."""
    for key in ("jaxpr", "call_jaxpr", "fun_jaxpr"):
        sub = eqn.params.get(key)
        if sub is not None and (hasattr(sub, "eqns") or hasattr(sub, "jaxpr")):
            return _closed(sub)
    return None


def eqn_source(eqn) -> Optional[Tuple[str, int]]:
    """(path, line) of the user frame that created this equation, if the
    traceback survived tracing (it does for normal python-traced code)."""
    try:
        from jax._src import source_info_util

        frame = source_info_util.user_frame(eqn.source_info)
        if frame is None:
            return None
        return str(frame.file_name), int(frame.start_line)
    except Exception:  # noqa: BLE001 — source info is best-effort
        return None


# --------------------------------------------------------------------------
# Domain 1: concrete taint interpreter
# --------------------------------------------------------------------------

# Elementwise value maps: output taint is the broadcast-OR of operand
# taints (selection exclusion happens at comparisons, not here).
_ELEMENTWISE = frozenset({
    "add", "add_any", "sub", "pow", "integer_pow", "exp", "exp2", "log",
    "log2",
    "log1p", "expm1", "sqrt", "rsqrt", "cbrt", "abs", "sign", "neg",
    "floor", "ceil", "round", "tanh", "sin", "cos", "tan", "asin", "acos",
    "atan", "atan2", "sinh", "cosh", "asinh", "acosh", "atanh", "erf",
    "erfc", "erf_inv", "logistic", "lgamma", "digamma", "rem", "nextafter",
    "real", "imag", "square", "clamp", "shift_left",
    "shift_right_logical", "shift_right_arithmetic", "population_count",
    "clz", "reduce_precision", "copy", "convert_element_type",
    "bitcast_convert_type", "stop_gradient",
})

# Predicate producers: output carries NO taint (selection dataflow).
# and/or/not/xor join this set only for BOOLEAN operands — on integers the
# same primitives are bitwise VALUE ops (payload bit-twiddling, PRNG lanes)
# and must carry taint like any other arithmetic (see TaintEval._eqn).
_PREDICATES = frozenset({
    "eq", "ne", "lt", "le", "gt", "ge", "is_finite",
})
_BOOL_OR_BITWISE = frozenset({"and", "or", "not", "xor"})


def _tz(L: int, shape) -> np.ndarray:
    return np.zeros((L,) + tuple(shape), bool)


def _bt(t: np.ndarray, L: int, shape) -> np.ndarray:
    """Broadcast a taint tensor to (L,) + shape (rank-aligning trailing
    dims, the numpy rule — lax elementwise operands share ranks)."""
    target = (L,) + tuple(shape)
    if t.shape == target:
        return t
    # Align trailing dims: insert axes after the label axis as needed.
    extra = len(target) - t.ndim
    if extra > 0:
        t = t.reshape(t.shape[:1] + (1,) * extra + t.shape[1:])
    return np.broadcast_to(t, target)


class TaintEval:
    """Concrete evaluator with per-label boolean taint riding each value."""

    def __init__(self, num_labels: int):
        self.L = num_labels
        self.unknown: Set[str] = set()

    # -- entry ------------------------------------------------------------

    def eval_closed(self, closed, pairs: Sequence[Tuple[Any, np.ndarray]]):
        jaxpr = closed.jaxpr
        env: Dict[Any, Tuple[Any, np.ndarray]] = {}

        def write(var, pair):
            env[var] = pair

        def read(atom):
            import jax

            if isinstance(atom, jax.core.Literal):
                v = np.asarray(atom.val)
                return v, _tz(self.L, v.shape)
            return env[atom]

        for var, const in zip(jaxpr.constvars, closed.consts):
            c = np.asarray(const)
            write(var, (c, _tz(self.L, c.shape)))
        if len(jaxpr.invars) != len(pairs):
            raise ValueError(
                f"taint eval got {len(pairs)} inputs for "
                f"{len(jaxpr.invars)} invars"
            )
        for var, pair in zip(jaxpr.invars, pairs):
            write(var, pair)

        for eqn in jaxpr.eqns:
            in_pairs = [read(a) for a in eqn.invars]
            outs = self._eqn(eqn, in_pairs)
            for var, pair in zip(eqn.outvars, outs):
                write(var, pair)
        return [read(a) for a in jaxpr.outvars]

    # -- helpers ----------------------------------------------------------

    def _concrete(self, eqn, vals) -> List[Any]:
        import warnings

        with warnings.catch_warnings():
            # Eager binds on inf-padded literals emit numpy cast warnings
            # (jax's own constant folding path) — expected, not actionable.
            warnings.simplefilter("ignore", RuntimeWarning)
            out = eqn.primitive.bind(*vals, **eqn.params)
        return list(out) if eqn.primitive.multiple_results else [out]

    def _coarse(self, eqn, pairs) -> List[Tuple[Any, np.ndarray]]:
        """Sound fallback: every output fully tainted by the join of all
        operand taints (any label set anywhere contaminates everything)."""
        vals = [p[0] for p in pairs]
        outs = self._concrete(eqn, vals)
        joined = np.zeros((self.L,), bool)
        for _, t in pairs:
            joined |= t.reshape(self.L, -1).any(axis=1)
        return [
            (
                o,
                np.broadcast_to(
                    joined.reshape((self.L,) + (1,) * np.ndim(o)),
                    (self.L,) + np.shape(o),
                ).copy(),
            )
            for o in outs
        ]

    # -- dispatch ---------------------------------------------------------

    def _eqn(self, eqn, pairs) -> List[Tuple[Any, np.ndarray]]:
        name = eqn.primitive.name.replace("-", "_")
        handler = getattr(self, f"_t_{name}", None)
        if handler is not None:
            return handler(eqn, pairs)
        if name in _BOOL_OR_BITWISE:
            dt = getattr(eqn.invars[0].aval, "dtype", None)
            if dt == np.bool_:
                name = "__predicate__"
            else:
                name = "__elementwise__"
        if name in _PREDICATES or name == "__predicate__":
            outs = self._concrete(eqn, [p[0] for p in pairs])
            return [(o, _tz(self.L, np.shape(o))) for o in outs]
        if name in _ELEMENTWISE or name == "__elementwise__":
            outs = self._concrete(eqn, [p[0] for p in pairs])
            out = outs[0]
            t = _tz(self.L, np.shape(out))
            for _, ti in pairs:
                t = t | _bt(ti, self.L, np.shape(out))
            return [(out, t)]
        sub = _sub_jaxpr(eqn)
        if sub is not None:
            return self.eval_closed(sub, pairs)
        self.unknown.add(name)
        return self._coarse(eqn, pairs)

    # -- structural primitives -------------------------------------------

    def _t_broadcast_in_dim(self, eqn, pairs):
        (v, t), = pairs
        outs = self._concrete(eqn, [v])
        target = tuple(eqn.params["shape"])
        bdims = tuple(eqn.params["broadcast_dimensions"])
        new_shape = [1] * len(target)
        for i, d in enumerate(bdims):
            new_shape[d] = np.shape(v)[i]
        t_out = np.broadcast_to(
            t.reshape((self.L,) + tuple(new_shape)), (self.L,) + target
        ).copy()
        return [(outs[0], t_out)]

    def _t_reshape(self, eqn, pairs):
        (v, t), = pairs
        outs = self._concrete(eqn, [v])
        dims = eqn.params.get("dimensions")
        if dims is not None:
            t = np.transpose(t, (0,) + tuple(d + 1 for d in dims))
        t_out = t.reshape((self.L,) + tuple(eqn.params["new_sizes"]))
        return [(outs[0], t_out)]

    def _t_transpose(self, eqn, pairs):
        (v, t), = pairs
        outs = self._concrete(eqn, [v])
        perm = tuple(eqn.params["permutation"])
        return [(outs[0], np.transpose(t, (0,) + tuple(p + 1 for p in perm)))]

    def _t_squeeze(self, eqn, pairs):
        (v, t), = pairs
        outs = self._concrete(eqn, [v])
        dims = tuple(d + 1 for d in eqn.params["dimensions"])
        return [(outs[0], np.squeeze(t, axis=dims))]

    def _t_rev(self, eqn, pairs):
        (v, t), = pairs
        outs = self._concrete(eqn, [v])
        dims = tuple(d + 1 for d in eqn.params["dimensions"])
        return [(outs[0], np.flip(t, axis=dims))]

    def _t_slice(self, eqn, pairs):
        (v, t), = pairs
        outs = self._concrete(eqn, [v])
        starts = eqn.params["start_indices"]
        limits = eqn.params["limit_indices"]
        strides = eqn.params["strides"] or (1,) * len(starts)
        sl = (slice(None),) + tuple(
            slice(s, l, st) for s, l, st in zip(starts, limits, strides)
        )
        return [(outs[0], t[sl])]

    def _t_concatenate(self, eqn, pairs):
        outs = self._concrete(eqn, [p[0] for p in pairs])
        dim = eqn.params["dimension"] + 1
        return [(outs[0], np.concatenate([p[1] for p in pairs], axis=dim))]

    def _t_pad(self, eqn, pairs):
        import jax

        (v, t), (pv, pt) = pairs
        outs = self._concrete(eqn, [v, pv])
        cfg = eqn.params["padding_config"]
        t_rows = [
            np.asarray(jax.lax.pad(
                t[l].astype(np.int8), np.int8(pt[l].any()), cfg
            )) > 0
            for l in range(self.L)
        ]
        return [(outs[0], np.stack(t_rows))]

    def _t_iota(self, eqn, pairs):
        outs = self._concrete(eqn, [])
        return [(outs[0], _tz(self.L, np.shape(outs[0])))]

    # -- data movement with index operands --------------------------------

    # scatter variants join every operand's labels over the whole output —
    # deliberately coarse (no ``unknown`` mark): the rules only scatter
    # predicate-derived masks and carried state, never selection payloads,
    # so precision is irrelevant while soundness is preserved.
    def _t_scatter(self, eqn, pairs):
        return self._coarse(eqn, pairs)

    _t_scatter_add = _t_scatter
    _t_scatter_mul = _t_scatter
    _t_scatter_min = _t_scatter
    _t_scatter_max = _t_scatter

    def _t_gather(self, eqn, pairs):
        (op, t_op), (idx, t_idx) = pairs
        outs = self._concrete(eqn, [op, idx])
        del t_idx  # selection influence: index taint excluded
        try:
            t_rows = [
                np.asarray(
                    eqn.primitive.bind(
                        np.asarray(t_op[l], np.int8), idx, **eqn.params
                    )
                ) > 0
                for l in range(self.L)
            ]
        except Exception:  # noqa: BLE001 — params may be dtype-entangled
            return self._coarse(eqn, pairs)
        return [(outs[0], np.stack(t_rows))]

    def _t_dynamic_slice(self, eqn, pairs):
        op, t_op = pairs[0]
        idx_vals = [p[0] for p in pairs[1:]]
        outs = self._concrete(eqn, [op] + idx_vals)
        t_rows = [
            np.asarray(
                eqn.primitive.bind(
                    np.asarray(t_op[l], np.int8), *idx_vals, **eqn.params
                )
            ) > 0
            for l in range(self.L)
        ]
        return [(outs[0], np.stack(t_rows))]

    def _t_dynamic_update_slice(self, eqn, pairs):
        (op, t_op), (up, t_up) = pairs[0], pairs[1]
        idx_vals = [p[0] for p in pairs[2:]]
        outs = self._concrete(eqn, [op, up] + idx_vals)
        t_rows = [
            np.asarray(
                eqn.primitive.bind(
                    np.asarray(t_op[l], np.int8),
                    np.asarray(t_up[l], np.int8),
                    *idx_vals,
                    **eqn.params,
                )
            ) > 0
            for l in range(self.L)
        ]
        return [(outs[0], np.stack(t_rows))]

    # -- selection / ordering ---------------------------------------------

    def _t_select_n(self, eqn, pairs):
        (pred, _t_pred) = pairs[0]
        cases = pairs[1:]
        outs = self._concrete(eqn, [pred] + [c[0] for c in cases])
        pred_np = np.asarray(pred)
        shape = np.shape(outs[0])
        t = _bt(cases[0][1], self.L, shape).copy()
        for i, (cv, ct) in enumerate(cases):
            if i == 0:
                continue
            sel = np.broadcast_to(pred_np == i, shape)
            t = np.where(sel[None], _bt(ct, self.L, shape), t)
        return [(outs[0], t)]

    def _t_sort(self, eqn, pairs):
        import jax

        dim = eqn.params["dimension"]
        num_keys = eqn.params["num_keys"]
        vals = [p[0] for p in pairs]
        shape = np.shape(vals[0])
        iota = np.broadcast_to(
            np.arange(shape[dim]).reshape(
                (1,) * dim + (shape[dim],) + (1,) * (len(shape) - dim - 1)
            ),
            shape,
        ).astype(np.int32)
        sorted_all = jax.lax.sort_p.bind(
            *vals, iota, dimension=dim, is_stable=True, num_keys=num_keys
        )
        perm = np.asarray(sorted_all[-1])
        outs = [np.take_along_axis(np.asarray(v), perm, axis=dim) for v in vals]
        t_outs = [
            np.take_along_axis(p[1], perm[None], axis=dim + 1) for p in pairs
        ]
        return list(zip(outs, t_outs))

    def _t_top_k(self, eqn, pairs):
        (v, t), = pairs
        outs = self._concrete(eqn, [v])
        idx = np.asarray(outs[1])
        t_vals = np.take_along_axis(t, idx[None], axis=t.ndim - 1)
        return [(outs[0], t_vals), (outs[1], _tz(self.L, idx.shape))]

    def _t_argmax(self, eqn, pairs):
        outs = self._concrete(eqn, [pairs[0][0]])
        return [(outs[0], _tz(self.L, np.shape(outs[0])))]

    _t_argmin = _t_argmax

    # -- elementwise with kill rules --------------------------------------

    def _t_mul(self, eqn, pairs):
        (a, ta), (b, tb) = pairs
        outs = self._concrete(eqn, [a, b])
        shape = np.shape(outs[0])
        a_nz = np.broadcast_to(np.asarray(a) != 0, shape)
        b_nz = np.broadcast_to(np.asarray(b) != 0, shape)
        t = (_bt(ta, self.L, shape) & b_nz[None]) | (
            _bt(tb, self.L, shape) & a_nz[None]
        )
        return [(outs[0], t)]

    def _t_div(self, eqn, pairs):
        (a, ta), (b, tb) = pairs
        outs = self._concrete(eqn, [a, b])
        shape = np.shape(outs[0])
        a_nz = np.broadcast_to(np.asarray(a) != 0, shape)
        t = _bt(ta, self.L, shape) | (_bt(tb, self.L, shape) & a_nz[None])
        return [(outs[0], t)]

    def _winner(self, eqn, pairs, pick_first):
        (a, ta), (b, tb) = pairs
        outs = self._concrete(eqn, [a, b])
        shape = np.shape(outs[0])
        first = np.broadcast_to(pick_first(np.asarray(a), np.asarray(b)), shape)
        t = np.where(
            first[None], _bt(ta, self.L, shape), _bt(tb, self.L, shape)
        )
        return [(outs[0], t)]

    def _t_max(self, eqn, pairs):
        return self._winner(eqn, pairs, lambda a, b: a >= b)

    def _t_min(self, eqn, pairs):
        return self._winner(eqn, pairs, lambda a, b: a <= b)

    # -- reductions --------------------------------------------------------

    def _reduce_or(self, eqn, pairs):
        (v, t), = pairs
        outs = self._concrete(eqn, [v])
        axes = tuple(a + 1 for a in eqn.params["axes"])
        return [(outs[0], t.any(axis=axes))]

    _t_reduce_sum = _reduce_or
    _t_reduce_prod = _reduce_or
    _t_reduce_and = _reduce_or
    _t_reduce_or = _reduce_or
    _t_reduce_xor = _reduce_or

    def _reduce_winner(self, eqn, pairs, argfn):
        (v, t), = pairs
        outs = self._concrete(eqn, [v])
        axes = tuple(eqn.params["axes"])
        vv = np.asarray(v)
        kept = [d for d in range(vv.ndim) if d not in axes]
        perm = kept + list(axes)
        red = int(np.prod([vv.shape[d] for d in axes])) if axes else 1
        vt = np.transpose(vv, perm).reshape(
            tuple(vv.shape[d] for d in kept) + (red,)
        )
        tt = np.transpose(t, (0,) + tuple(p + 1 for p in perm)).reshape(
            (self.L,) + tuple(vv.shape[d] for d in kept) + (red,)
        )
        w = argfn(vt, axis=-1)
        t_out = np.take_along_axis(tt, w[None, ..., None], axis=-1)[..., 0]
        return [(outs[0], t_out)]

    def _t_reduce_max(self, eqn, pairs):
        return self._reduce_winner(eqn, pairs, np.argmax)

    def _t_reduce_min(self, eqn, pairs):
        return self._reduce_winner(eqn, pairs, np.argmin)

    def _cumulative(self, eqn, pairs):
        (v, t), = pairs
        outs = self._concrete(eqn, [v])
        axis = eqn.params["axis"] + 1
        rev = eqn.params.get("reverse", False)
        tt = np.flip(t, axis=axis) if rev else t
        acc = np.logical_or.accumulate(tt, axis=axis)
        if rev:
            acc = np.flip(acc, axis=axis)
        return [(outs[0], acc)]

    _t_cumsum = _cumulative
    _t_cumprod = _cumulative
    _t_cummax = _cumulative
    _t_cummin = _cumulative
    _t_cumlogsumexp = _cumulative

    # -- linear algebra ----------------------------------------------------

    def _t_dot_general(self, eqn, pairs):
        import jax

        (a, ta), (b, tb) = pairs
        outs = self._concrete(eqn, [a, b])
        dims = eqn.params["dimension_numbers"]
        a_nz = (np.asarray(a) != 0).astype(np.float32)
        b_nz = (np.asarray(b) != 0).astype(np.float32)
        rows = []
        for l in range(self.L):
            from_a = np.asarray(jax.lax.dot_general(
                ta[l].astype(np.float32), b_nz, dims
            )) > 0
            from_b = np.asarray(jax.lax.dot_general(
                a_nz, tb[l].astype(np.float32), dims
            )) > 0
            rows.append(from_a | from_b)
        return [(outs[0], np.stack(rows))]

    # -- identity-ish ------------------------------------------------------

    def _t_optimization_barrier(self, eqn, pairs):
        outs = self._concrete(eqn, [p[0] for p in pairs])
        return [(o, p[1]) for o, p in zip(outs, pairs)]

    def _t_device_put(self, eqn, pairs):
        outs = self._concrete(eqn, [p[0] for p in pairs])
        return [(o, p[1]) for o, p in zip(outs, pairs)]

    # -- control flow ------------------------------------------------------

    def _t_pjit(self, eqn, pairs):
        return self.eval_closed(_closed(eqn.params["jaxpr"]), pairs)

    def _t_custom_jvp_call(self, eqn, pairs):
        return self.eval_closed(_closed(eqn.params["call_jaxpr"]), pairs)

    def _t_custom_vjp_call(self, eqn, pairs):
        sub = _sub_jaxpr(eqn)
        return self.eval_closed(sub, pairs)

    _t_custom_vjp_call_jaxpr = _t_custom_vjp_call
    _t_remat2 = _t_pjit
    _t_checkpoint = _t_pjit
    _t_closed_call = _t_pjit

    def _t_cond(self, eqn, pairs):
        idx = int(np.asarray(pairs[0][0]))
        branch = _closed(eqn.params["branches"][idx])
        return self.eval_closed(branch, pairs[1:])

    def _t_while(self, eqn, pairs):
        p = eqn.params
        cn, bn = p["cond_nconsts"], p["body_nconsts"]
        cond_j, body_j = _closed(p["cond_jaxpr"]), _closed(p["body_jaxpr"])
        cc, bc, carry = pairs[:cn], pairs[cn:cn + bn], list(pairs[cn + bn:])
        for _ in range(1_000_000):
            pred = self.eval_closed(cond_j, list(cc) + carry)[0][0]
            if not bool(np.asarray(pred)):
                break
            carry = self.eval_closed(body_j, list(bc) + carry)
        else:
            raise RuntimeError("taint eval: while loop iteration cap hit")
        return carry

    def _t_scan(self, eqn, pairs):
        p = eqn.params
        nc, ncarry = p["num_consts"], p["num_carry"]
        length, reverse = p["length"], p["reverse"]
        body = _closed(p["jaxpr"])
        consts = list(pairs[:nc])
        carry = list(pairs[nc:nc + ncarry])
        xs = pairs[nc + ncarry:]
        ys_slots: List[Optional[List[Tuple[Any, np.ndarray]]]] = [
            None
        ] * length
        order = range(length - 1, -1, -1) if reverse else range(length)
        num_ys = len(eqn.outvars) - ncarry
        for i in order:
            sliced = [
                (np.asarray(v)[i], t[:, i]) for v, t in xs
            ]
            outs = self.eval_closed(body, consts + carry + sliced)
            carry = list(outs[:ncarry])
            ys_slots[i] = list(outs[ncarry:])
        ys: List[Tuple[Any, np.ndarray]] = []
        for j in range(num_ys):
            if length == 0:
                outs_shapes = eqn.outvars[ncarry + j].aval
                ys.append((
                    np.zeros(outs_shapes.shape, outs_shapes.dtype),
                    _tz(self.L, outs_shapes.shape),
                ))
                continue
            vals = np.stack(
                [np.asarray(ys_slots[i][j][0]) for i in range(length)]
            )
            ts = np.stack(
                [ys_slots[i][j][1] for i in range(length)], axis=1
            )
            ys.append((vals, ts))
        return carry + ys


# --------------------------------------------------------------------------
# Domain 2: interval / finiteness abstract interpreter
# --------------------------------------------------------------------------

_INF = float("inf")


@dataclasses.dataclass(frozen=True)
class IVal:
    """Whole-array abstract value: [lo, hi] bounds over every element, a
    finiteness-contamination flag (``nf`` — may carry NaN/inf originating
    from the contaminated seeds), elementwise-copy identity (``ids``) and,
    for predicates, the sets of value-ids whose finiteness their truth
    (``tif``) or falsity (``fif``) implies."""

    lo: float
    hi: float
    nf: bool = False
    ids: FrozenSet[int] = frozenset()
    tif: FrozenSet[int] = frozenset()
    fif: FrozenSet[int] = frozenset()

    def widen_to(self, other: "IVal") -> "IVal":
        return IVal(
            min(self.lo, other.lo), max(self.hi, other.hi),
            self.nf or other.nf,
        )

    def same_bounds(self, other: "IVal") -> bool:
        return (
            self.lo == other.lo
            and self.hi == other.hi
            and self.nf == other.nf
        )


def _iv(lo, hi, nf=False, **kw) -> IVal:
    lo = float(lo) if not math.isnan(float(lo)) else -_INF
    hi = float(hi) if not math.isnan(float(hi)) else _INF
    return IVal(lo, hi, nf, **kw)


TOP_F = _iv(-_INF, _INF)
BOOL_IV = _iv(0.0, 1.0)


def _contains_zero(v: IVal) -> bool:
    return v.lo <= 0.0 <= v.hi


def _mul_bounds(a: IVal, b: IVal) -> Tuple[float, float]:
    with np.errstate(invalid="ignore"):
        cands = np.array(
            [a.lo * b.lo, a.lo * b.hi, a.hi * b.lo, a.hi * b.hi], np.float64
        )
    cands = np.where(np.isnan(cands), 0.0, cands)  # 0 * inf -> 0 (reals)
    return float(cands.min()), float(cands.max())


class IntervalEval:
    """Abstract interpreter over whole-array intervals + contamination."""

    WIDEN_AFTER = 4
    MAX_FIX = 24

    def __init__(self):
        self.events: List[Dict[str, Any]] = []
        self.unknown: Set[str] = set()
        self.record_denominators = True

    def _event(self, kind: str, eqn, detail: str):
        src = eqn_source(eqn)
        self.events.append({
            "kind": kind,
            "prim": eqn.primitive.name,
            "path": src[0] if src else None,
            "line": src[1] if src else None,
            "detail": detail,
        })

    # -- entry ------------------------------------------------------------

    def eval_closed(self, closed, ivals: Sequence[IVal]) -> List[IVal]:
        jaxpr = closed.jaxpr
        env: Dict[Any, IVal] = {}

        def write(var, v: IVal):
            env[var] = dataclasses.replace(v, ids=v.ids | {id(var)})

        def read(atom) -> IVal:
            import jax

            if isinstance(atom, jax.core.Literal):
                a = np.asarray(atom.val)
                if a.size == 0:
                    return _iv(0.0, 0.0)
                if a.dtype == bool:
                    return _iv(float(a.min()), float(a.max()))
                lo = float(np.min(a.astype(np.float64)))
                hi = float(np.max(a.astype(np.float64)))
                # Deliberate literal inf (sort padding) is CLEAN: nf tracks
                # contamination from the seeded inputs only.
                return _iv(lo, hi)
            return env[atom]

        for var, const in zip(jaxpr.constvars, closed.consts):
            write(var, read_const(const))
        for var, v in zip(jaxpr.invars, ivals):
            write(var, v)
        for eqn in jaxpr.eqns:
            ins = [read(a) for a in eqn.invars]
            outs = self._eqn(eqn, ins)
            for var, v in zip(eqn.outvars, outs):
                write(var, v)
        return [read(a) for a in jaxpr.outvars]

    # -- dispatch ---------------------------------------------------------

    def _eqn(self, eqn, ins: Sequence[IVal]) -> List[IVal]:
        name = eqn.primitive.name.replace("-", "_")
        handler = getattr(self, f"_i_{name}", None)
        if handler is not None:
            return handler(eqn, ins)
        if name in _IV_TABLE:
            return [_IV_TABLE[name](self, eqn, ins)]
        sub = _sub_jaxpr(eqn)
        if sub is not None:
            return self.eval_closed(sub, ins)
        # Unknown primitive: sound defaults by output dtype.  Float outputs
        # become contaminated TOP (the safe direction for MUR803); the prim
        # name is surfaced so coverage gaps are debuggable, not silent.
        self.unknown.add(name)
        outs = []
        for var in eqn.outvars:
            dt = getattr(var.aval, "dtype", None)
            if dt is not None and np.issubdtype(dt, np.floating):
                outs.append(_iv(-_INF, _INF, nf=True))
            else:
                outs.append(TOP_F)
        return outs

    # -- explicit handlers -------------------------------------------------

    def _join(self, ins: Sequence[IVal]) -> IVal:
        lo = min((v.lo for v in ins), default=0.0)
        hi = max((v.hi for v in ins), default=0.0)
        return _iv(lo, hi, any(v.nf for v in ins))

    @staticmethod
    def _same_operand(eqn) -> bool:
        """Both invars are literally the same jaxpr Var — the only safe
        notion of elementwise self-application.  (The ``ids`` copy-chains
        survive value-CHANGING ops like reduce_max/floor, so using them
        here would constant-fold ``x == max(x)``-style data-dependent
        masks — verified unsound.)"""
        import jax

        return (
            len(eqn.invars) == 2
            and not isinstance(eqn.invars[0], jax.core.Literal)
            and eqn.invars[0] is eqn.invars[1]
        )

    def _i_mul(self, eqn, ins):
        a, b = ins
        lo, hi = _mul_bounds(a, b)
        if self._same_operand(eqn):
            # x * x (the jnp.square/variance idiom): the product of a value
            # with itself is nonnegative — the refinement that proves
            # layernorm's sqrt(var + eps) denominator positive.
            lo = max(lo, 0.0)
        if (a.nf and _contains_zero(b)) or (b.nf and _contains_zero(a)):
            self._event(
                "mask-mul", eqn,
                "possibly-non-finite operand multiplied by a value that "
                "can be exactly 0 (0*inf == nan) — masks over possibly "
                "non-finite data must be where-style replacements",
            )
        return [_iv(lo, hi, a.nf or b.nf)]

    def _i_ne(self, eqn, ins):
        a, b = ins
        if self._same_operand(eqn) and not (a.nf or b.nf):
            # x != x is isnan(x); a value that cannot be NaN (real-valued
            # semantics, uncontaminated) makes it constantly False — which
            # is what keeps logaddexp/softplus's NaN-repair branch from
            # joining an unbounded interval into every softplus output.
            return [_iv(0.0, 0.0)]
        return [BOOL_IV]

    def _i_eq(self, eqn, ins):
        a, b = ins
        if self._same_operand(eqn) and not (a.nf or b.nf):
            return [_iv(1.0, 1.0)]
        return [BOOL_IV]

    # Order comparisons resolve to constants when the intervals are
    # disjoint (and the operands provably non-NaN) — which is what lets
    # jnp.var's ``where(count > 0, var, nan)`` repair branch drop its NaN
    # literal instead of joining it into every layernorm denominator.
    def _cmp(self, ins, true_when, false_when):
        a, b = ins
        if not (a.nf or b.nf):
            if true_when(a, b):
                return [_iv(1.0, 1.0)]
            if false_when(a, b):
                return [_iv(0.0, 0.0)]
        return [BOOL_IV]

    def _i_gt(self, eqn, ins):
        return self._cmp(
            ins, lambda a, b: a.lo > b.hi, lambda a, b: a.hi <= b.lo
        )

    def _i_ge(self, eqn, ins):
        return self._cmp(
            ins, lambda a, b: a.lo >= b.hi, lambda a, b: a.hi < b.lo
        )

    def _i_lt(self, eqn, ins):
        return self._cmp(
            ins, lambda a, b: a.hi < b.lo, lambda a, b: a.lo >= b.hi
        )

    def _i_le(self, eqn, ins):
        return self._cmp(
            ins, lambda a, b: a.hi <= b.lo, lambda a, b: a.lo > b.hi
        )

    def _i_dot_general(self, eqn, ins):
        a, b = ins
        lo, hi = _mul_bounds(a, b)
        dims = eqn.params["dimension_numbers"]
        lhs_contract = dims[0][0]
        shape = eqn.invars[0].aval.shape
        c = 1
        for d in lhs_contract:
            c *= int(shape[d])
        c = max(c, 1)
        if (a.nf and _contains_zero(b)) or (b.nf and _contains_zero(a)):
            self._event(
                "mask-mul", eqn,
                "possibly-non-finite matmul operand against a value that "
                "can be exactly 0",
            )
        return [_iv(c * lo if lo != 0 else 0.0, c * hi if hi != 0 else 0.0,
                    a.nf or b.nf)]

    def _i_div(self, eqn, ins):
        a, b = ins
        nf = a.nf or b.nf
        if _contains_zero(b):
            if self.record_denominators:
                self._event(
                    "zero-denominator", eqn,
                    f"denominator interval [{b.lo:g}, {b.hi:g}] contains 0 "
                    "— guard with jnp.maximum(x, eps) or a where()",
                )
            return [_iv(-_INF, _INF, True)]
        with np.errstate(invalid="ignore", divide="ignore"):
            cands = np.array(
                [a.lo / b.lo, a.lo / b.hi, a.hi / b.lo, a.hi / b.hi],
                np.float64,
            )
        cands = np.where(np.isnan(cands), 0.0, cands)
        return [_iv(float(cands.min()), float(cands.max()), nf)]

    def _i_rsqrt(self, eqn, ins):
        (x,) = ins
        nf = x.nf
        if x.lo <= 0.0 <= x.hi or (x.lo < 0):
            if self.record_denominators and x.hi >= 0.0 >= x.lo:
                self._event(
                    "zero-denominator", eqn,
                    f"rsqrt operand interval [{x.lo:g}, {x.hi:g}] reaches 0 "
                    "— 1/sqrt(0) is inf; floor the operand first",
                )
            nf = True
        return [_iv(0.0, _INF, nf)]

    def _i_integer_pow(self, eqn, ins):
        (x,) = ins
        y = eqn.params["y"]
        if y < 0 and _contains_zero(x):
            if self.record_denominators:
                self._event(
                    "zero-denominator", eqn,
                    f"x**{y} with base interval [{x.lo:g}, {x.hi:g}] "
                    "containing 0",
                )
            return [_iv(-_INF, _INF, True)]
        if y >= 0 and y % 2 == 0:
            m = max(abs(x.lo), abs(x.hi))
            return [_iv(0.0, m ** y if math.isfinite(m) else _INF, x.nf)]
        try:
            lo, hi = x.lo ** y, x.hi ** y
        except (OverflowError, ZeroDivisionError):
            lo, hi = -_INF, _INF
        return [_iv(min(lo, hi), max(lo, hi), x.nf)]

    def _i_is_finite(self, eqn, ins):
        (x,) = ins
        return [dataclasses.replace(BOOL_IV, tif=x.ids)]

    def _is_bool_op(self, eqn) -> bool:
        # and/or/not/xor on integers are bitwise VALUE ops, not predicate
        # algebra — no [0, 1] bounds, no finiteness implications.
        return getattr(eqn.invars[0].aval, "dtype", None) == np.bool_

    def _i_and(self, eqn, ins):
        if not self._is_bool_op(eqn):
            return [TOP_F]
        a, b = ins
        return [dataclasses.replace(BOOL_IV, tif=a.tif | b.tif)]

    def _i_or(self, eqn, ins):
        if not self._is_bool_op(eqn):
            return [TOP_F]
        a, b = ins
        return [dataclasses.replace(BOOL_IV, fif=a.fif | b.fif)]

    def _i_not(self, eqn, ins):
        if not self._is_bool_op(eqn):
            return [TOP_F]
        (a,) = ins
        return [dataclasses.replace(BOOL_IV, tif=a.fif, fif=a.tif)]

    def _i_xor(self, eqn, ins):
        return [BOOL_IV if self._is_bool_op(eqn) else TOP_F]

    def _i_reduce_and(self, eqn, ins):
        (a,) = ins
        return [dataclasses.replace(BOOL_IV, tif=a.tif)]

    def _i_reduce_or(self, eqn, ins):
        (a,) = ins
        return [dataclasses.replace(BOOL_IV, fif=a.fif)]

    def _i_reduce_min(self, eqn, ins):
        # all(x) over bools lowers to reduce_min on some paths: min true
        # => ALL true, so tif survives; min false only means SOME element
        # is false, so fif must NOT (the reduce_and asymmetry, mirrored).
        (a,) = ins
        return [dataclasses.replace(a, ids=frozenset(), fif=frozenset())]

    def _i_select_n(self, eqn, ins):
        pred, cases = ins[0], list(ins[1:])
        if pred.hi <= 0.0 and not pred.nf:
            return [cases[0]]  # predicate constantly false
        if pred.lo >= len(cases) - 1 and not pred.nf:
            return [cases[-1]]  # predicate constantly picks the last case
        lo = min(c.lo for c in cases)
        hi = max(c.hi for c in cases)
        nf = False
        for i, c in enumerate(cases):
            c_nf = c.nf
            if c_nf and i == len(cases) - 1 and pred.tif & c.ids:
                c_nf = False  # chosen when pred true => proven finite
            if c_nf and i == 0 and pred.fif & c.ids:
                c_nf = False  # chosen when pred false => proven finite
            nf = nf or c_nf
        return [_iv(lo, hi, nf)]

    def _i_select(self, eqn, ins):  # legacy select
        return self._i_select_n(eqn, ins)

    def _i_reduce_sum(self, eqn, ins):
        (a,) = ins
        shape = eqn.invars[0].aval.shape
        n = 1
        for d in eqn.params["axes"]:
            n *= int(shape[d])
        n = max(n, 1)
        return [_iv(
            a.lo * n if a.lo < 0 else a.lo,
            a.hi * n if a.hi > 0 else a.hi,
            a.nf,
        )]

    def _i_convert_element_type(self, eqn, ins):
        (a,) = ins
        dt = eqn.params["new_dtype"]
        if np.issubdtype(dt, np.bool_):
            return [BOOL_IV]
        # keep ids: elementwise value-preserving (up to rounding) — the
        # isfinite-pattern matching tolerates it (finite stays finite).
        return [dataclasses.replace(a, tif=frozenset(), fif=frozenset())]

    def _i_bitcast_convert_type(self, eqn, ins):
        dt = eqn.params["new_dtype"]
        if np.issubdtype(dt, np.floating):
            # Arbitrary bit patterns include NaN/inf encodings: RNG-derived
            # floats count as contaminated until a guard proves otherwise.
            return [_iv(-_INF, _INF, True)]
        return [TOP_F]

    def _i_iota(self, eqn, ins):
        shape = eqn.params["shape"]
        dim = eqn.params["dimension"]
        n = int(shape[dim]) if shape else 1
        return [_iv(0.0, max(0, n - 1))]

    def _i_clamp(self, eqn, ins):
        # Both bounds must land inside [mn.lo, mx.hi] or the interval
        # inverts when x lies entirely outside the clamp window (e.g.
        # clip(d, 0, cap) with d in [5, 6] and cap == 0 is exactly 0) —
        # and an inverted interval vacuously "excludes" zero.
        mn, x, mx = ins
        lo = min(max(x.lo, mn.lo), mx.hi)
        hi = max(min(x.hi, mx.hi), mn.lo)
        return [_iv(lo, hi, x.nf or mn.nf or mx.nf)]

    def _i_pad(self, eqn, ins):
        return [self._join(ins)]

    def _i_concatenate(self, eqn, ins):
        return [self._join(ins)]

    def _i_dynamic_update_slice(self, eqn, ins):
        return [self._join(ins[:2])]

    def _i_gather(self, eqn, ins):
        op = ins[0]
        return [dataclasses.replace(op, ids=frozenset(),
                                    tif=frozenset(), fif=frozenset())]

    def _i_dynamic_slice(self, eqn, ins):
        return self._i_gather(eqn, ins)

    def _i_sort(self, eqn, ins):
        return [dataclasses.replace(v, ids=frozenset(), tif=frozenset(),
                                    fif=frozenset()) for v in ins]

    def _i_top_k(self, eqn, ins):
        (x,) = ins
        k_extent = 0
        shape = eqn.invars[0].aval.shape
        if shape:
            k_extent = max(0, int(shape[-1]) - 1)
        return [dataclasses.replace(x, ids=frozenset()), _iv(0.0, k_extent)]

    def _i_optimization_barrier(self, eqn, ins):
        return list(ins)

    def _i_stop_gradient(self, eqn, ins):
        return [ins[0]]

    # -- control flow ------------------------------------------------------

    def _i_pjit(self, eqn, ins):
        return self.eval_closed(_closed(eqn.params["jaxpr"]), ins)

    def _i_custom_jvp_call(self, eqn, ins):
        return self.eval_closed(_closed(eqn.params["call_jaxpr"]), ins)

    def _i_custom_vjp_call(self, eqn, ins):
        return self.eval_closed(_sub_jaxpr(eqn), ins)

    _i_custom_vjp_call_jaxpr = _i_custom_vjp_call
    _i_remat2 = _i_pjit
    _i_checkpoint = _i_pjit
    _i_closed_call = _i_pjit

    def _i_cond(self, eqn, ins):
        branches = [
            self.eval_closed(_closed(b), list(ins[1:]))
            for b in eqn.params["branches"]
        ]
        out = []
        for outs in zip(*branches):
            v = outs[0]
            for o in outs[1:]:
                v = v.widen_to(o)
            out.append(v)
        return out

    def _fixpoint(self, body, consts, carry, xs):
        carry = [dataclasses.replace(c, ids=frozenset(), tif=frozenset(),
                                     fif=frozenset()) for c in carry]
        outs = None
        for it in range(self.MAX_FIX):
            outs = self.eval_closed(body, consts + carry + xs)
            new_carry = [
                c.widen_to(o) for c, o in zip(carry, outs[:len(carry)])
            ]
            if all(c.same_bounds(n) for c, n in zip(carry, new_carry)):
                return new_carry, outs
            if it >= self.WIDEN_AFTER:
                new_carry = [
                    n if c.same_bounds(n)
                    else _iv(-_INF, _INF, c.nf or n.nf)
                    for c, n in zip(carry, new_carry)
                ]
            carry = new_carry
        return carry, outs

    def _i_scan(self, eqn, ins):
        p = eqn.params
        nc, ncarry = p["num_consts"], p["num_carry"]
        body = _closed(p["jaxpr"])
        consts = list(ins[:nc])
        carry = list(ins[nc:nc + ncarry])
        xs = list(ins[nc + ncarry:])
        if p["length"] == 0:
            num_ys = len(eqn.outvars) - ncarry
            return carry + [_iv(0.0, 0.0)] * num_ys
        carry, outs = self._fixpoint(body, consts, carry, xs)
        ys = [
            dataclasses.replace(y, ids=frozenset(), tif=frozenset(),
                                fif=frozenset())
            for y in outs[ncarry:]
        ]
        return carry + ys

    def _i_while(self, eqn, ins):
        p = eqn.params
        cn, bn = p["cond_nconsts"], p["body_nconsts"]
        body = _closed(p["body_jaxpr"])
        bc = list(ins[cn:cn + bn])
        carry = list(ins[cn + bn:])
        fixed, _ = self._fixpoint(body, bc, carry, [])
        # The loop may execute zero times: join with the initial carry.
        return [c.widen_to(f) for c, f in zip(carry, fixed)]


def read_const(const) -> IVal:
    a = np.asarray(const)
    if a.size == 0:
        return _iv(0.0, 0.0)
    if a.dtype == bool:
        return _iv(float(a.min()), float(a.max()))
    if not np.issubdtype(a.dtype, np.number):
        return TOP_F
    af = a.astype(np.float64)
    finite = af[np.isfinite(af)]
    lo = float(finite.min()) if finite.size else 0.0
    hi = float(finite.max()) if finite.size else 0.0
    if not np.isfinite(af).all():
        # Literal inf padding is deliberate and CLEAN (nf stays False);
        # bounds still record the infinities.
        lo = -_INF if (af == -_INF).any() else lo
        hi = _INF if (af == _INF).any() else hi
    return _iv(lo, hi)


def _mk_unary(fn) -> Callable:
    def h(self, eqn, ins):
        return fn(self, ins[0])

    return h


def _monotone(f, lo_clip=None, hi_clip=None):
    def t(self, x: IVal) -> IVal:
        try:
            lo = f(x.lo)
        except (ValueError, OverflowError):
            lo = -_INF
        try:
            hi = f(x.hi)
        except (ValueError, OverflowError):
            hi = _INF
        if lo_clip is not None:
            lo, hi = max(lo, lo_clip), max(hi, lo_clip)
        if hi_clip is not None:
            lo, hi = min(lo, hi_clip), min(hi, hi_clip)
        return _iv(lo, hi, x.nf)

    return t


def _iv_add(self, eqn, ins):
    a, b = ins
    return _iv(a.lo + b.lo if not math.isnan(a.lo + b.lo) else -_INF,
               a.hi + b.hi if not math.isnan(a.hi + b.hi) else _INF,
               a.nf or b.nf)


def _iv_sub(self, eqn, ins):
    a, b = ins
    lo = a.lo - b.hi
    hi = a.hi - b.lo
    return _iv(lo if not math.isnan(lo) else -_INF,
               hi if not math.isnan(hi) else _INF, a.nf or b.nf)


def _iv_max(self, eqn, ins):
    a, b = ins
    return _iv(max(a.lo, b.lo), max(a.hi, b.hi), a.nf or b.nf)


def _iv_min(self, eqn, ins):
    a, b = ins
    return _iv(min(a.lo, b.lo), min(a.hi, b.hi), a.nf or b.nf)


def _iv_abs(self, eqn, ins):
    (x,) = ins
    if x.lo >= 0:
        return _iv(x.lo, x.hi, x.nf)
    if x.hi <= 0:
        return _iv(-x.hi, -x.lo, x.nf)
    return _iv(0.0, max(-x.lo, x.hi), x.nf)


def _iv_neg(self, eqn, ins):
    (x,) = ins
    return _iv(-x.hi, -x.lo, x.nf)


def _iv_square(self, eqn, ins):
    v = _iv_abs(self, eqn, ins)
    lo, hi = _mul_bounds(v, v)
    return _iv(max(lo, 0.0), hi, ins[0].nf)


def _iv_sqrt(self, eqn, ins):
    (x,) = ins
    nf = x.nf or x.lo < 0
    return _iv(math.sqrt(max(x.lo, 0.0)) if math.isfinite(x.lo) else 0.0,
               math.sqrt(x.hi) if math.isfinite(x.hi) and x.hi >= 0 else _INF,
               nf)


def _iv_log(self, eqn, ins):
    (x,) = ins
    nf = x.nf or x.lo <= 0
    hi = math.log(x.hi) if math.isfinite(x.hi) and x.hi > 0 else _INF
    lo = math.log(x.lo) if x.lo > 0 and math.isfinite(x.lo) else -_INF
    return _iv(lo, hi, nf)


def _iv_log2(self, eqn, ins):
    (x,) = ins
    nf = x.nf or x.lo <= 0
    hi = math.log2(x.hi) if math.isfinite(x.hi) and x.hi > 0 else _INF
    lo = math.log2(x.lo) if x.lo > 0 and math.isfinite(x.lo) else -_INF
    return _iv(lo, hi, nf)


def _iv_log1p(self, eqn, ins):
    (x,) = ins
    nf = x.nf or x.lo <= -1.0
    return _iv(
        math.log1p(x.lo) if x.lo > -1.0 and math.isfinite(x.lo) else -_INF,
        math.log1p(x.hi) if math.isfinite(x.hi) else _INF,
        nf,
    )


def _iv_poles_nonpos(self, eqn, ins):
    (x,) = ins
    return _iv(-_INF, _INF, x.nf or x.lo <= 0)


def _iv_domain_pm1(self, eqn, ins):
    (x,) = ins
    return _iv(-_INF, _INF, x.nf or x.lo <= -1.0 or x.hi >= 1.0)


def _iv_bool_out(self, eqn, ins):
    return BOOL_IV


def _iv_passthrough(self, eqn, ins):
    x = ins[0]
    return dataclasses.replace(x, tif=frozenset(), fif=frozenset())


def _iv_view(self, eqn, ins):
    """Shape-only view of one operand: bounds, contamination, identity AND
    predicate implications all survive — the sentinel pattern broadcasts
    its row predicate (``ok[:, None]``) before the select, and reshapes/
    slices keep elementwise correspondence for the reduce_and-based
    implications (pred true => the whole reduced group is finite, which
    implies any subset)."""
    return ins[0]


def _iv_join_all(self, eqn, ins):
    return self._join(ins)


def _iv_int_top(self, eqn, ins):
    return TOP_F


def _iv_rem(self, eqn, ins):
    a, b = ins
    if _contains_zero(b):
        if self.record_denominators:
            self._event(
                "zero-denominator", eqn,
                f"rem divisor interval [{b.lo:g}, {b.hi:g}] contains 0",
            )
        return _iv(-_INF, _INF, True)
    m = max(abs(b.lo), abs(b.hi))
    return _iv(-m, m, a.nf or b.nf)


def _iv_cumulative(self, eqn, ins):
    (a,) = ins
    shape = eqn.invars[0].aval.shape
    axis = eqn.params.get("axis", 0)
    n = int(shape[axis]) if shape else 1
    n = max(n, 1)
    return _iv(a.lo * n if a.lo < 0 else a.lo,
               a.hi * n if a.hi > 0 else a.hi, a.nf)


_IV_TABLE: Dict[str, Callable] = {
    "add": _iv_add,
    "add_any": _iv_add,  # the AD transpose's accumulating add
    "sub": _iv_sub,
    "max": _iv_max,
    "min": _iv_min,
    "abs": _iv_abs,
    "neg": _iv_neg,
    "sign": _mk_unary(lambda self, x: _iv(-1.0, 1.0, x.nf)),
    "square": _iv_square,
    "sqrt": _iv_sqrt,
    "cbrt": _mk_unary(lambda self, x: _iv(-_INF, _INF, x.nf)),
    "exp": _mk_unary(_monotone(math.exp, lo_clip=0.0)),
    "exp2": _mk_unary(_monotone(lambda v: 2.0 ** v, lo_clip=0.0)),
    "expm1": _mk_unary(_monotone(math.expm1, lo_clip=-1.0)),
    "log": _iv_log,
    "log1p": _iv_log1p,
    "log2": _iv_log2,
    "lgamma": _iv_poles_nonpos,
    "digamma": _iv_poles_nonpos,
    "logistic": _mk_unary(lambda self, x: _iv(0.0, 1.0, x.nf)),
    "tanh": _mk_unary(lambda self, x: _iv(-1.0, 1.0, x.nf)),
    "erf": _mk_unary(lambda self, x: _iv(-1.0, 1.0, x.nf)),
    "erfc": _mk_unary(lambda self, x: _iv(0.0, 2.0, x.nf)),
    "erf_inv": _iv_domain_pm1,
    "atanh": _iv_domain_pm1,
    "sin": _mk_unary(lambda self, x: _iv(-1.0, 1.0, x.nf)),
    "cos": _mk_unary(lambda self, x: _iv(-1.0, 1.0, x.nf)),
    "tan": _mk_unary(lambda self, x: _iv(-_INF, _INF, x.nf)),
    "asin": _iv_domain_pm1,
    "acos": _iv_domain_pm1,
    "atan": _mk_unary(lambda self, x: _iv(-2.0, 2.0, x.nf)),
    "atan2": _iv_join_all,
    "sinh": _mk_unary(lambda self, x: _iv(-_INF, _INF, x.nf)),
    "cosh": _mk_unary(lambda self, x: _iv(1.0, _INF, x.nf)),
    "asinh": _mk_unary(lambda self, x: _iv(-_INF, _INF, x.nf)),
    "acosh": _mk_unary(lambda self, x: _iv(0.0, _INF, x.nf or x.lo < 1.0)),
    # floor/ceil/round are monotone but move values off the input bounds
    # (floor(0.6) == 0 < 0.6): transfer through the function itself so
    # 1/floor(x) with x in [0.5, 2] correctly flags a zero denominator.
    "floor": _mk_unary(_monotone(math.floor)),
    "ceil": _mk_unary(_monotone(math.ceil)),
    "round": _mk_unary(_monotone(lambda v: float(round(v)))),
    "nextafter": _iv_join_all,
    "rem": _iv_rem,
    "pow": _iv_join_all,
    "eq": _iv_bool_out,
    "ne": _iv_bool_out,
    "lt": _iv_bool_out,
    "le": _iv_bool_out,
    "gt": _iv_bool_out,
    "ge": _iv_bool_out,
    "reduce_max": _iv_passthrough,
    "reduce_prod": _mk_unary(
        lambda self, x: _iv(0.0 if x.lo >= 0 else -_INF, _INF, x.nf)
    ),
    "reduce_xor": _iv_bool_out,
    "broadcast_in_dim": _iv_view,
    "reshape": _iv_view,
    "transpose": _iv_view,
    "squeeze": _iv_view,
    "expand_dims": _iv_view,
    "rev": _iv_view,
    "slice": _iv_view,
    "copy": _iv_view,
    "real": _iv_passthrough,
    "imag": _iv_passthrough,
    "reduce_precision": _iv_view,
    "scatter": _iv_join_all,
    "scatter-add": _iv_join_all,
    "scatter_add": _iv_join_all,
    "scatter_max": _iv_join_all,
    "scatter_min": _iv_join_all,
    "scatter_mul": _iv_join_all,
    "argmax": _iv_int_top,
    "argmin": _iv_int_top,
    "cumsum": _iv_cumulative,
    "cumlogsumexp": _iv_cumulative,
    "cumprod": _mk_unary(lambda self, x: _iv(-_INF, _INF, x.nf)),
    "cummax": _iv_passthrough,
    "cummin": _iv_passthrough,
    "threefry2x32": _iv_int_top,
    "random_seed": _iv_int_top,
    "random_wrap": _iv_int_top,
    "random_unwrap": _iv_int_top,
    "random_fold_in": _iv_int_top,
    "random_bits": _iv_int_top,
    "random_split": _iv_int_top,
    "random_clone": _iv_int_top,
    "random_gamma": _mk_unary(lambda self, x: _iv(0.0, _INF, True)),
    "shift_left": _iv_int_top,
    "shift_right_logical": _iv_int_top,
    "shift_right_arithmetic": _iv_int_top,
    "population_count": _iv_int_top,
    "clz": _iv_int_top,
    "device_put": _iv_passthrough,
}


# --------------------------------------------------------------------------
# Canonical flow cells
# --------------------------------------------------------------------------


_FLOW_PROBE_MEMO: Dict[bool, Tuple[Any, Any, int]] = {}


def _flow_probe_model(evidential: bool):
    """(apply_fn, unravel, dim) of the flow pass's probe model.  Unlike the
    IR pass's single plain-MLP probe, evidential rules get the evidential
    head here: the interval domain then SEES the softplus+1 alpha floor
    (alphas >= 1 => Dirichlet strength >= K), which is what proves the
    vacuity/entropy divisions in evidential_trust_metric zero-free — the
    paper-faithful configuration of that rule."""
    if evidential in _FLOW_PROBE_MEMO:
        return _FLOW_PROBE_MEMO[evidential]
    import jax
    from jax.flatten_util import ravel_pytree

    from murmura_tpu.models import make_mlp

    model = make_mlp(
        input_dim=_PROBE_IN, hidden_dims=(16,), num_classes=_PROBE_CLASSES,
        evidential=evidential,
    )
    flat0, unravel = ravel_pytree(model.init(jax.random.PRNGKey(0)))
    _FLOW_PROBE_MEMO[evidential] = (model.apply, unravel, int(flat0.size))
    return _FLOW_PROBE_MEMO[evidential]


_PROBE_RULES = frozenset({"ubar", "evidential_trust"})
_EVIDENTIAL_RULES = frozenset({"evidential_trust"})


@dataclasses.dataclass
class FlowCell:
    """One (rule, exchange mode) cell of the flow grid: a traceable
    ``fn(*args)`` plus which argument positions carry the per-neighbor
    exchange payload (taint-seeded along their leading node axis)."""

    name: str
    mode: str  # dense | circulant | sparse | compressed
    n: int
    fn: Callable
    args: Tuple
    bcast_args: Tuple[int, ...]  # arg indices seeded with row labels
    agg: Any
    _closed: Any = None

    def traced(self):
        """Memoized ClosedJaxpr of the cell — both flow domains (taint
        influence and interval denominators) analyze the same trace, so
        one sweep pays the jax.make_jaxpr cost."""
        if self._closed is None:
            import jax

            with _quiet_tracing():
                self._closed = jax.make_jaxpr(self.fn)(*self.args)
        return self._closed


# Default cells memoized per (rule, mode): check_influence and
# check_denominators sweep the same grid in one check_flow run, and the
# battery pre-flight runs under a hard timeout — building each aggregator
# and probe model once is the difference between one trace per cell and
# two.
_CELL_MEMO: Dict[Tuple[str, str], "FlowCell"] = {}


def _flow_offsets(n: int) -> List[int]:
    from murmura_tpu.analysis.ir import canonical_offsets

    return canonical_offsets(n)


def build_flow_cell(
    name: str,
    mode: str,
    n: int = FLOW_N,
    agg_override: Any = None,
    params: Optional[Dict[str, Any]] = None,
    audit: bool = False,
) -> FlowCell:
    """Instantiate one rule over one flow-grid cell.

    Every mode is built over the SAME canonical k-regular(4) circulant
    graph (the dense mode takes its [N, N] matrix, the circulant/sparse/
    compressed modes its offsets), so the analyzed influence cardinality
    is comparable across modes — the MUR802 parity subject.

    ``audit`` builds the cell with ``ctx.audit`` on so the rule emits its
    per-node ``tap_*`` stats — the MUR1003 adaptive-feedback cells
    (analysis/adaptive.py) analyze the acceptance signal those taps feed.
    """
    import dataclasses as dc

    import jax.numpy as jnp

    from murmura_tpu.aggregation import build_aggregator
    from murmura_tpu.aggregation.base import AggContext
    from murmura_tpu.analysis.ir import AGG_CASES, _canonical_adj
    from murmura_tpu.ops.compress import Int8Blocks, quantize_int8

    if mode not in FLOW_MODES:
        raise ValueError(f"unknown flow mode {mode!r}")
    default_cell = (
        agg_override is None and params is None and n == FLOW_N
        and not audit
    )
    if default_cell and (name, mode) in _CELL_MEMO:
        return _CELL_MEMO[(name, mode)]
    offsets = _flow_offsets(n)
    k = len(offsets)
    evidential = name in _EVIDENTIAL_RULES
    if name in _PROBE_RULES:
        apply_fn, unravel, dim = _flow_probe_model(evidential)
    else:
        apply_fn = unravel = None
        dim = FLOW_DIM

    case = dict(AGG_CASES.get(name, {}) if params is None else params)
    if mode != "dense":
        case["exchange_offsets"] = list(offsets)
    if mode == "sparse":
        case["sparse_exchange"] = True
    if agg_override is not None:
        agg = agg_override
    else:
        agg = build_aggregator(name, case, model_dim=dim, total_rounds=10)

    rng = np.random.default_rng(0)
    own = jnp.asarray(rng.normal(size=(n, dim)) * 0.1, jnp.float32)
    bcast_f = jnp.asarray(rng.normal(size=(n, dim)) * 0.1, jnp.float32)
    if mode == "sparse":
        adj = jnp.ones((k, n), jnp.float32)
    else:
        # Dense mode takes the SAME circulant graph's [N, N] matrix.
        adj = jnp.asarray(_canonical_adj(n, circulant=True))
    ridx = jnp.asarray(0.0, jnp.float32)
    state = {k2: jnp.asarray(v) for k2, v in agg.init_state(n).items()}

    ctx = AggContext(
        apply_fn=apply_fn,
        unravel=unravel,
        evidential=evidential,
        num_classes=_PROBE_CLASSES,
        total_rounds=10,
        audit=audit,
    )
    if name in _PROBE_RULES:
        probe = {
            "x": jnp.asarray(
                rng.normal(size=(n, _PROBE_BATCH, _PROBE_IN)), jnp.float32
            ),
            "y": jnp.asarray(
                rng.integers(0, _PROBE_CLASSES, size=(n, _PROBE_BATCH)),
                jnp.int32,
            ),
            "mask": jnp.ones((n, _PROBE_BATCH), jnp.float32),
        }
        ctx = dc.replace(
            ctx, probe_x=probe["x"], probe_y=probe["y"],
            probe_mask=probe["mask"],
        )

    if mode == "compressed":
        if not agg.quantized_exchange:
            raise ValueError(
                f"rule '{name}' has no quantized exchange path — the "
                "compressed flow mode applies to quantized_exchange rules"
            )
        qb = quantize_int8(bcast_f, FLOW_BLOCK)

        def fn(own, q, scale, adj, ridx, state):  # murmura: traced
            payload = Int8Blocks(q, scale, FLOW_BLOCK, dim, jnp.float32)
            return agg.aggregate(own, payload, adj, ridx, state, ctx)

        args = (own, qb.q, qb.scale, adj, ridx, state)
        bcast_args = (1, 2)
    else:

        def fn(own, bcast, adj, ridx, state):  # murmura: traced
            return agg.aggregate(own, bcast, adj, ridx, state, ctx)

        args = (own, bcast_f, adj, ridx, state)
        bcast_args = (1,)

    cell = FlowCell(
        name=name, mode=mode, n=n, fn=fn, args=args, bcast_args=bcast_args,
        agg=agg,
    )
    if default_cell:
        _CELL_MEMO[(name, mode)] = cell
    return cell


def rule_flow_modes(name: str, agg=None) -> Tuple[str, ...]:
    """Exchange modes the flow grid sweeps for one rule.  ``compressed``
    only where the circulant kernels take the int8 payload itself —
    other rules consume the receiver-side dequantized tensor, which is
    taint-identical to their dense/circulant float path."""
    if agg is None:
        from murmura_tpu.aggregation import build_aggregator
        from murmura_tpu.analysis.ir import AGG_CASES

        case = dict(AGG_CASES.get(name, {}))
        case["exchange_offsets"] = _flow_offsets(FLOW_N)
        agg = build_aggregator(name, case, model_dim=FLOW_DIM, total_rounds=10)
    modes = ["dense", "circulant", "sparse"]
    if agg.quantized_exchange:
        modes.append("compressed")
    return tuple(modes)


# --------------------------------------------------------------------------
# Influence analysis (Domain 1 drivers)
# --------------------------------------------------------------------------


def analyze_cell_influence(cell: FlowCell) -> Dict[str, Any]:
    """Run the taint interpreter over one cell and summarize the output
    [N, P] tensor's per-neighbor influence.

    Returns ``{"per_node": tuple[int], "max": int, "sets": [[labels]],
    "unknown_prims": [...]}`` where ``per_node[i]`` is the maximum number
    of distinct NON-SELF labels any single coordinate of output row i
    carries, and ``sets[i]`` the union of labels across row i's
    coordinates."""
    import jax

    closed = cell.traced()
    flat_args, _ = jax.tree_util.tree_flatten(cell.args)
    n = cell.n
    ev = TaintEval(n)
    pairs = []
    # Map flattened invars back to arg positions to seed the payload rows.
    # tree_flatten of the args tuple matches jaxpr invars order.
    arg_leaf_pos: List[int] = []
    for i, a in enumerate(cell.args):
        leaves = jax.tree_util.tree_leaves(a)
        arg_leaf_pos.extend([i] * len(leaves))
    assert len(arg_leaf_pos) == len(flat_args)
    for leaf, pos in zip(flat_args, arg_leaf_pos):
        v = np.asarray(leaf)
        t = _tz(n, v.shape)
        if pos in cell.bcast_args:
            if v.ndim == 0 or v.shape[0] != n:
                raise ValueError(
                    f"payload arg {pos} of cell {cell.name}/{cell.mode} has "
                    f"no leading node axis: {v.shape}"
                )
            for lbl in range(n):
                t[lbl, lbl] = True
        pairs.append((v, t))
    with _quiet_tracing():
        outs = ev.eval_closed(closed, pairs)
    out_val, out_t = outs[0]  # (new_flat, state, stats) flattens new_flat first
    if np.shape(out_val)[0] != n:
        raise AssertionError(
            f"cell {cell.name}/{cell.mode}: first output is not [N, P]"
        )
    self_t = out_t[np.arange(n), np.arange(n)]  # [N, P] self-label bits
    card = out_t.sum(axis=0) - self_t  # [N, P] non-self labels per coord
    per_node = card.max(axis=1).astype(int)
    sets = [
        sorted(int(l) for l in np.nonzero(out_t[:, i, :].any(axis=1))[0])
        for i in range(n)
    ]
    return {
        "per_node": tuple(int(c) for c in per_node),
        "max": int(per_node.max()),
        "sets": sets,
        "unknown_prims": sorted(ev.unknown),
    }


def rule_influence_summary(
    name: str,
    agg_overrides: Optional[Dict[str, Any]] = None,
    n: int = FLOW_N,
    modes: Optional[Sequence[str]] = None,
) -> Dict[str, Dict[str, Any]]:
    """Per-mode influence summaries for one rule (the `flow_summary` JSON
    payload and the MUR800/802 subject).  ``agg_overrides`` maps mode ->
    prebuilt AggregatorDef (tests inject leaky fakes this way)."""
    agg_overrides = agg_overrides or {}
    if modes is None:
        modes = rule_flow_modes(name, agg=agg_overrides.get("circulant"))
    out = {}
    for mode in modes:
        cell = build_flow_cell(
            name, mode, n=n, agg_override=agg_overrides.get(mode)
        )
        out[mode] = analyze_cell_influence(cell)
    return out


def _rule_anchor(name: str) -> Tuple[str, int]:
    from murmura_tpu.analysis.ir import _rule_anchor as ir_anchor

    return ir_anchor(name)


def influence_findings(
    name: str,
    summaries: Dict[str, Dict[str, Any]],
    influence,
    k: int,
    anchor: Optional[Tuple[str, int]] = None,
) -> List[Finding]:
    """MUR800 (bound) + MUR802 (mode parity) over one rule's analyzed
    summaries — factored out so tests drive it with fake rules."""
    path, line = anchor if anchor is not None else _rule_anchor(name)
    findings: List[Finding] = []
    for mode, s in summaries.items():
        if s.get("unknown_prims"):
            findings.append(Finding(
                "MUR800", path, line,
                f"aggregator '{name}' ({mode}) hit jaxpr primitives the "
                f"taint interpreter does not model: {s['unknown_prims']} — "
                "their coarse fallback taints everything, so the influence "
                "result is vacuous; teach analysis/flow.py the primitive",
                data={"rule": name, "mode": mode,
                      "unknown_prims": s["unknown_prims"]},
            ))
    if influence is None:
        findings.append(Finding(
            "MUR801", path, line,
            f"aggregator '{name}' declares no influence contract — set "
            "AggregatorDef.influence (aggregation/base.py InfluenceDecl) "
            "so the bounded-influence claim is machine-checked (MUR800) "
            "instead of folklore",
            data={"rule": name},
        ))
    elif influence.kind == "bounded":
        bound = influence.bound(k)
        for mode, s in summaries.items():
            if s["max"] > bound:
                findings.append(Finding(
                    "MUR800", path, line,
                    f"aggregator '{name}' ({mode}) leaks influence: some "
                    f"output coordinate mixes values from {s['max']} "
                    f"neighbors but the rule declares a bound of {bound} "
                    f"(degree {k}) — either the rule regressed or its "
                    "InfluenceDecl is wrong",
                    data={
                        "rule": name, "mode": mode, "analyzed": s["max"],
                        "declared_bound": bound, "degree": k,
                        "per_node": list(s["per_node"]),
                        "taint_sets": s["sets"],
                    },
                ))
    # MUR802: per-node cardinality parity across every supported mode —
    # for BOUNDED rules, where the cardinality IS the contract (krum must
    # stay 1 in compressed mode too).  Unbounded rules' benign-input
    # cardinality is data/precision-dependent: the dense Gram path centers
    # on the mean of ALL rows (a cancellation — ||(a-c)-(b-c)|| == ||a-b||
    # — the taint domain cannot see), so e.g. the dense geometric median
    # analyzes to "every row" while its circulant direct-norm twin
    # analyzes to the true neighborhood.  Their summaries are still
    # emitted for `check --json`.
    if influence is not None and influence.kind == "bounded":
        vectors = {m: s["per_node"] for m, s in summaries.items()}
    else:
        vectors = {}
    if len(set(vectors.values())) > 1:
        findings.append(Finding(
            "MUR802", path, line,
            f"aggregator '{name}' analyzes to different per-node influence "
            f"across exchange modes: "
            + "; ".join(f"{m}={list(v)}" for m, v in sorted(vectors.items()))
            + " — the same rule's math must bound influence identically in "
            "every mode (dense/circulant/sparse/compressed parity)",
            data={"rule": name,
                  "per_node": {m: list(v) for m, v in vectors.items()}},
        ))
    return findings


# Most recent flow sweep's per-rule/mode summaries, as `check --json`
# records ({"kind": "flow_summary", ...}); populated by check_influence.
_FLOW_SUMMARIES: List[Dict[str, Any]] = []


def flow_summaries() -> List[Dict[str, Any]]:
    return list(_FLOW_SUMMARIES)


@_family
def check_influence() -> List[Finding]:
    """MUR800/801/802: analyzed per-neighbor influence vs the declared
    contract, declaration coverage, and cross-mode parity."""
    from murmura_tpu.aggregation import AGGREGATORS

    from murmura_tpu.aggregation import build_aggregator
    from murmura_tpu.analysis.ir import AGG_CASES

    findings: List[Finding] = []
    _FLOW_SUMMARIES.clear()
    offsets = _flow_offsets(FLOW_N)
    k = len(offsets)
    for name in sorted(AGGREGATORS):
        path, line = _rule_anchor(name)
        try:
            # One circulant build answers both "which modes" and "what is
            # declared" — the per-mode cells are built by the summary sweep.
            case = dict(AGG_CASES.get(name, {}))
            case["exchange_offsets"] = list(offsets)
            agg_circ = build_aggregator(
                name, case, model_dim=FLOW_DIM, total_rounds=10
            )
            modes = rule_flow_modes(name, agg=agg_circ)
            summaries = rule_influence_summary(name, modes=modes)
            influence = agg_circ.influence
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            findings.append(Finding(
                "MUR800", path, line,
                f"aggregator '{name}' crashed the influence sweep: "
                f"{type(e).__name__}: {e}",
            ))
            continue
        findings.extend(
            influence_findings(name, summaries, influence, k)
        )
        declared = (
            None if influence is None
            else {"kind": influence.kind,
                  "bound": (influence.bound(k)
                            if influence.kind == "bounded" else None),
                  "note": influence.note}
        )
        for mode, s in summaries.items():
            _FLOW_SUMMARIES.append({
                "kind": "flow_summary",
                "rule": name,
                "mode": mode,
                "degree": k,
                "max_influence": s["max"],
                "per_node": list(s["per_node"]),
                "taint_sets": s["sets"],
                "declared": declared,
            })
    return findings


# --------------------------------------------------------------------------
# Scrub dominance (MUR803) and denominators (MUR804)
# --------------------------------------------------------------------------


def _seed_round_ivals(
    args_tree, overrides: Optional[Dict[int, IVal]] = None
) -> List[IVal]:
    """Abstract seeds for a round program's flattened inputs: everything
    finite but arbitrary, ``overrides`` pinning specific top-level arg
    positions (adjacency/compromised/alive masks to [0, 1]), mask-named
    data leaves in [0, 1], integers bounded — contamination must be
    CREATED by the program's own math (diverging training, attack noise)
    and contained by its scrubs."""
    import jax

    overrides = overrides or {}
    paths = jax.tree_util.tree_flatten_with_path(args_tree)[0]
    ivals = []
    for (path, leaf) in paths:
        top = getattr(path[0], "idx", None) if path else None
        key = jax.tree_util.keystr(path)
        a = np.asarray(leaf)
        if top is not None and top in overrides:
            ivals.append(overrides[top])
        elif a.dtype == bool:
            ivals.append(BOOL_IV)
        elif np.issubdtype(a.dtype, np.integer) or np.issubdtype(
            a.dtype, np.unsignedinteger
        ):
            ivals.append(TOP_F)
        elif "mask" in key:
            ivals.append(_iv(0.0, 1.0))
        else:
            ivals.append(_iv(-_INF, _INF))
    return ivals


def scrub_dominance_report(
    fn,
    args_tree,
    check_leading: int = 2,
    seed_overrides: Optional[Dict[int, IVal]] = None,
):
    """Interval-analyze ``fn(*args_tree)`` with divergence-capable seeds;
    returns ``(contaminated_paths, events, unknown)`` where
    ``contaminated_paths`` are the output leaves among the first
    ``check_leading`` top-level outputs (params', agg_state') whose
    abstract value may carry input-originated non-finiteness.  The core of
    MUR803, factored out so tests drive it on hand-built programs."""
    import jax

    with _quiet_tracing():
        closed, out_shape = jax.make_jaxpr(fn, return_shape=True)(*args_tree)
    ev = IntervalEval()
    ev.record_denominators = False  # MUR804's job, over rule cells
    outs = ev.eval_closed(
        closed, _seed_round_ivals(args_tree, seed_overrides)
    )
    flat_paths = jax.tree_util.tree_flatten_with_path(out_shape)[0]
    assert len(flat_paths) == len(outs)
    contaminated = []
    for (path, _shape), iv_out in zip(flat_paths, outs):
        keys = jax.tree_util.keystr(path)
        idx = getattr(path[0], "idx", None) if path else None
        if idx is not None and idx >= check_leading:
            continue  # metrics/stats may carry loss-derived non-finites
        if iv_out.nf:
            contaminated.append(keys)
    return contaminated, ev.events, sorted(ev.unknown)


# The rule set the scrub-dominance contract is traced over.  The sentinel
# lives in core/rounds.py UPSTREAM of every rule, so one representative per
# rule family keeps the sweep fast while still proving each rule's own math
# cannot resurrect contamination the scrub removed.
SCRUB_RULES: Tuple[str, ...] = (
    "fedavg", "krum", "median", "trimmed_mean", "geometric_median",
    "balance", "sketchguard", "ubar", "evidential_trust",
)


@_family
def check_scrub_dominance() -> List[Finding]:
    """MUR803: the NaN/attack scrub dominates all rule math.

    Each SCRUB_RULES faulted round program (NaN sentinel + gaussian attack
    armed — the configuration whose contract is 'non-finite data cannot
    reach parameters') is interval-analyzed with divergence-capable seeds:
    training math may abstractly diverge (log/exp/grad chains), the attack
    perturbation is contaminated by construction (RNG bitcasts), and the
    check fails if any output PARAMETER or carried aggregation-state leaf
    can still be non-finite — i.e. the where-style sentinel replacements
    in core/rounds.py no longer dominate every path to the output.  A mask
    applied multiplicatively (0 * nan == nan) keeps the contamination flag
    set, so the exact regression class PR 3 fixed by hand fails here
    statically."""
    import jax
    import jax.numpy as jnp

    from murmura_tpu.aggregation import build_aggregator
    from murmura_tpu.analysis.ir import AGG_CASES, _canonical_adj
    from murmura_tpu.attacks.gaussian import make_gaussian_attack
    from murmura_tpu.core.rounds import build_round_program
    from murmura_tpu.data.base import FederatedArrays
    from murmura_tpu.faults.schedule import FaultSpec
    from murmura_tpu.models import make_mlp

    pkg = Path(__file__).resolve().parent.parent
    anchor = str(pkg / "core" / "rounds.py")
    findings: List[Finding] = []

    n, s = 4, 16
    rng = np.random.default_rng(0)
    data = FederatedArrays(
        x=rng.normal(size=(n, s, _PROBE_IN)).astype(np.float32),
        y=rng.integers(0, _PROBE_CLASSES, size=(n, s)).astype(np.int32),
        mask=np.ones((n, s), np.float32),
        num_samples=np.full((n,), s),
        num_classes=_PROBE_CLASSES,
    )
    model = make_mlp(
        input_dim=_PROBE_IN, hidden_dims=(16,), num_classes=_PROBE_CLASSES
    )
    dim = _flow_probe_model(False)[2]
    attack = make_gaussian_attack(n, attack_percentage=0.25, noise_std=10.0)

    for rule in SCRUB_RULES:
        try:
            agg = build_aggregator(
                rule, dict(AGG_CASES.get(rule, {})), model_dim=dim,
                total_rounds=5,
            )
            prog = build_round_program(
                model, agg, data, total_rounds=5, batch_size=8,
                faults=FaultSpec(), attack=attack,
            )
            args = (
                prog.init_params,
                {k: jnp.asarray(v) for k, v in prog.init_agg_state.items()},
                jax.random.PRNGKey(0),
                jnp.asarray(_canonical_adj(n, circulant=False)),
                jnp.asarray(attack.compromised, jnp.float32),
                jnp.ones((n,), jnp.float32),
                jnp.asarray(0.0, jnp.float32),
                {k: jnp.asarray(v) for k, v in prog.data_arrays.items()},
            )
            # Positions 3/4/5 of the faulted signature are the adjacency /
            # compromised / alive masks — [0, 1] by contract (the host-side
            # folds), which is what proves degree-style denominators like
            # fedavg's 1/(1+degree) nonzero.
            contaminated, events, unknown = scrub_dominance_report(
                prog.train_step, args,
                seed_overrides={
                    3: _iv(0.0, 1.0), 4: _iv(0.0, 1.0), 5: _iv(0.0, 1.0),
                },
            )
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            findings.append(Finding(
                "MUR803", anchor, 1,
                f"the scrub-dominance sweep crashed for rule '{rule}': "
                f"{type(e).__name__}: {e}",
            ))
            continue
        if contaminated:
            entry_points = [
                e for e in events if e["kind"] in ("mask-mul",)
            ][:6]
            findings.append(Finding(
                "MUR803", anchor, 1,
                f"rule '{rule}': input-originated non-finiteness can reach "
                f"the round output at {contaminated[:6]} — the NaN/attack "
                "scrub (where-style replacement in core/rounds.py) no "
                "longer dominates every path; suspect multiplicative "
                "masking (0*inf == nan) or a bypassed sentinel"
                + (f"; mask-multiply sites: {entry_points}"
                   if entry_points else ""),
                data={"rule": rule, "contaminated": contaminated,
                      "mask_mul_events": entry_points,
                      "unknown_prims": unknown},
            ))
        elif unknown:
            findings.append(Finding(
                "MUR803", anchor, 1,
                f"rule '{rule}': the interval interpreter hit unmodeled "
                f"primitives {unknown} — their contaminated fallback makes "
                "the scrub-dominance verdict vacuous; teach "
                "analysis/flow.py the primitive",
                data={"rule": rule, "unknown_prims": unknown},
            ))
    return findings


def denominator_events(
    fn, args, seed_fn=None, closed=None
) -> List[Dict[str, Any]]:
    """Interval-analyze ``fn(*args)`` with post-scrub seeds and return the
    zero-denominator events (div/rsqrt/x**-k/rem whose denominator
    interval contains 0).  The MUR804 core, factored out for tests."""
    import jax

    if closed is None:
        with _quiet_tracing():
            closed = jax.make_jaxpr(fn)(*args)
    leaves = jax.tree_util.tree_leaves(args)
    ev = IntervalEval()
    if seed_fn is None:
        ivals = []
        for leaf in leaves:
            a = np.asarray(leaf)
            if a.dtype == bool:
                ivals.append(BOOL_IV)
            elif not np.issubdtype(a.dtype, np.floating):
                ivals.append(TOP_F)
            else:
                ivals.append(_iv(-_INF, _INF))
    else:
        ivals = seed_fn(leaves)
    ev.eval_closed(closed, ivals)
    return [e for e in ev.events if e["kind"] == "zero-denominator"]


def _cell_seeds(cell: FlowCell):
    """Post-scrub seeds for one cell's flattened args: broadcast/own are
    finite-but-arbitrary (MUR803 guarantees finiteness), the adjacency /
    edge-mask entries are [0, 1], carried state finite, round index within
    the horizon, int8 payloads within their code range."""
    import jax

    adj_pos = 3 if cell.mode == "compressed" else 2
    scale_pos = 2 if cell.mode == "compressed" else None

    def seed(leaves):
        out = []
        arg_leaf_pos: List[int] = []
        for i, a in enumerate(cell.args):
            arg_leaf_pos.extend([i] * len(jax.tree_util.tree_leaves(a)))
        for leaf, pos in zip(leaves, arg_leaf_pos):
            a = np.asarray(leaf)
            if a.dtype == bool:
                out.append(BOOL_IV)
            elif np.issubdtype(a.dtype, np.integer):
                # int8 payload codes are clipped to [-127, 127] by the
                # symmetric codec; other integers stay unbounded.
                out.append(
                    _iv(-127.0, 127.0) if a.dtype == np.int8 else TOP_F
                )
            elif pos == adj_pos:
                out.append(_iv(0.0, 1.0))  # adjacency / [k, N] edge mask
            elif pos == scale_pos:
                out.append(_iv(0.0, _INF))  # symmetric scales: max|x|/127
            else:
                out.append(_iv(-_INF, _INF))
        return out

    return seed


@_family
def check_denominators() -> List[Finding]:
    """MUR804: no reachable division/rsqrt sees a zero-capable denominator.

    Every rule cell in every supported mode, plus the compression codec
    (quantize_int8's guarded symmetric-scale division and compress_exchange
    end to end), is interval-analyzed under post-scrub seeds (finite but
    arbitrary exchange values, [0, 1] adjacency, the codec's scale
    invariants).  Guards — ``jnp.maximum(x, eps)`` floors, the codec's
    ``where(scale > 0, 1/max(scale, tiny), 0)`` — make denominators
    provably nonzero; any denominator whose interval still contains zero
    is a finding anchored at its source line."""
    from murmura_tpu.aggregation import AGGREGATORS

    findings: List[Finding] = []
    for name in sorted(AGGREGATORS):
        path, line = _rule_anchor(name)
        for mode in rule_flow_modes(name):
            try:
                cell = build_flow_cell(name, mode)
                events = denominator_events(
                    cell.fn, cell.args, seed_fn=_cell_seeds(cell),
                    closed=cell.traced(),
                )
            except Exception as e:  # noqa: BLE001 — a crash IS the finding
                findings.append(Finding(
                    "MUR804", path, line,
                    f"aggregator '{name}' ({mode}) crashed the denominator "
                    f"sweep: {type(e).__name__}: {e}",
                ))
                continue
            for e in events:
                e_path = e["path"] or path
                e_line = e["line"] or line
                findings.append(Finding(
                    "MUR804", e_path, e_line,
                    f"aggregator '{name}' ({mode}): {e['prim']} "
                    f"{e['detail']} (given post-scrub finite inputs and "
                    "[0, 1] masks) — a Byzantine-steerable zero denominator "
                    "is inf/NaN injection past the sentinel",
                    data={"rule": name, "mode": mode, **e},
                ))
    findings.extend(_codec_denominator_findings())
    return findings


def _codec_denominator_findings() -> List[Finding]:
    import jax.numpy as jnp

    from murmura_tpu.ops.compress import (
        RESIDUAL_KEY,
        CompressionSpec,
        compress_exchange,
        quantize_int8,
    )

    findings: List[Finding] = []
    pkg = Path(__file__).resolve().parent.parent
    anchor = (str(pkg / "ops" / "compress.py"), 1)
    n, p = FLOW_N, FLOW_DIM
    bcast = jnp.zeros((n, p), jnp.float32)
    resid = jnp.zeros((n, p), jnp.float32)
    spec = CompressionSpec("int8", block=FLOW_BLOCK, error_feedback=True)

    subjects = [
        ("quantize_int8", lambda b: quantize_int8(b, FLOW_BLOCK), (bcast,)),
        (
            "compress_exchange[int8+ef]",
            lambda b, r: compress_exchange(
                spec, b, {RESIDUAL_KEY: r}, True
            ),
            (bcast, resid),
        ),
    ]
    for label, fn, args in subjects:
        try:
            events = denominator_events(fn, args)
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            findings.append(Finding(
                "MUR804", anchor[0], anchor[1],
                f"codec subject '{label}' crashed the denominator sweep: "
                f"{type(e).__name__}: {e}",
            ))
            continue
        for e in events:
            findings.append(Finding(
                "MUR804", e["path"] or anchor[0], e["line"] or anchor[1],
                f"codec '{label}': {e['prim']} {e['detail']} — an all-zero "
                "block's scale is exactly 0; the symmetric codec must keep "
                "its guarded-inverse form",
                data={"subject": label, **e},
            ))
    return findings


# --------------------------------------------------------------------------
# Entry point
# --------------------------------------------------------------------------

_FLOW_MEMO: Optional[List[Finding]] = None


def check_flow(force: bool = False) -> List[Finding]:
    """Run MUR800-804 over the flow grid; returns findings (empty = every
    dataflow contract holds).  Memoized per process — the tier-1 gate, the
    CLI and the battery pre-flight share one sweep.  Trace-level only:
    nothing compiles, nothing needs a multi-device platform."""
    global _FLOW_MEMO
    if _FLOW_MEMO is not None and not force:
        return list(_FLOW_MEMO)

    from murmura_tpu.analysis.ir import _apply_suppressions

    findings: List[Finding] = []
    for fam_name, fam in FLOW_CHECK_FAMILIES.items():
        try:
            findings.extend(fam())
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            findings.append(Finding(
                "MUR800", str(Path(__file__).resolve()), 1,
                f"flow check family '{fam_name}' crashed: "
                f"{type(e).__name__}: {e}",
            ))
    findings = _apply_suppressions(list(dict.fromkeys(findings)))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    _FLOW_MEMO = list(findings)
    return findings

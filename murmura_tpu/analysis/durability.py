"""MUR901/902: the resume-determinism contract (`murmura check
--durability`; docs/ROBUSTNESS.md "Run durability").

MUR900 (analysis/contracts.py) proves the snapshot *payload* is complete
— every reserved carried-state key survives the save→restore roundtrip.
This module proves the payload is *sufficient*: restoring a snapshot into
the warm compiled round program and re-running the interrupted rounds
must reproduce the uninterrupted run exactly, for every registered
aggregation rule in every exchange mode.  Executable, per cell:

- **MUR901 — crash-equivalence**: train 2 rounds, snapshot, train 2 more
  (the uninterrupted tail), restore the snapshot into the SAME network,
  replay the tail.  History, params and the full ``agg_state`` (EF
  residual, topk reference, trust state — whatever the cell carries) must
  match byte-for-byte.  Anything less means a resumed run silently
  diverges from the run it claims to continue.
- **MUR902 — zero-recompile restore**: the replay runs under
  :class:`~murmura_tpu.analysis.sanitizers.CompileTracker`; a restore
  that triggers even one compile would stall a real resume behind a full
  program rebuild and break the donation story (the restored arrays must
  land with the shapes/dtypes/layouts the warm program specialized on).

Both hold *by construction* — every random stream is a pure function of
``(seed, round)`` and the snapshot carries all round-crossing state — so
a finding here is a real regression: a new piece of carried state that
missed the snapshot, or a restore path that perturbs placement.

The grid is ``AGGREGATORS x (dense, circulant, sparse, compressed,
adaptive, stale)`` — the same rule inventory the IR/flow/budget sweeps use
(``AGG_CASES`` keeps the bijection under MUR205).  Cells are tiny (5-8 nodes, an
83-param MLP, 4 rounds) but compile-dominated (~3-4 s each), so the full
sweep is memoized per process and runs by default only for the package
check, like ``check_ir``/``check_flow``.  Tests gate a representative
subset per tier-1 run (tests/test_durability.py) and the full grid under
``-m slow``.

Findings anchor to the rule's factory ``def`` (the ir.py convention), so
``# murmura: ignore[MUR901]`` suppression applies there.
"""

import tempfile
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from murmura_tpu.analysis.lint import Finding

# The exchange formulations a rule's math can take (ISSUE 7/8
# vocabulary): dense allgather, circulant ppermute shifts, the sparse
# [k, N] edge-mask engine, and the int8+error-feedback codec (the mode
# with round-crossing COMPRESS_STATE_KEYS state — the one a shallow
# checkpoint would silently corrupt).  ``adaptive`` (ISSUE 11) runs the
# dense exchange under a closed-loop bisection attack: the mode with
# round-crossing ATTACK_STATE_KEYS state — a snapshot that dropped the
# attacker's bracket would resume a silently-cold adversary.  ``stale``
# (ISSUE 13) runs the dense exchange under a straggler/link-drop fault
# schedule with bounded staleness armed: the mode with round-crossing
# STALE_STATE_KEYS state — a snapshot that dropped the payload cache
# would resume serving zeros as "cached" neighbor models.  ``pipeline``
# (ISSUE 14) runs the dense exchange with pipelined rounds armed: the
# mode with round-crossing PIPELINE_STATE_KEYS state — a snapshot that
# dropped the double buffer would resume with the in-flight round's
# exchange silently discarded (the delayed displacement lost forever).
DURABILITY_MODES: Tuple[str, ...] = (
    "dense", "circulant", "sparse", "compressed", "adaptive", "stale",
    "pipeline",
)

# Registry of check families in this module: name -> callable, scanned by
# analysis/ir.py's check_coverage so an unwired family is a MUR205
# finding (the flow.py twin pattern).
DURABILITY_CHECK_FAMILIES: Dict[str, Callable[[], List[Finding]]] = {}


def _family(fn):
    DURABILITY_CHECK_FAMILIES[fn.__name__] = fn
    return fn


def history_equal(a: Any, b: Any) -> bool:
    """Recursive byte-equality over json-able history values, with
    ``NaN == NaN`` (a rule metric that is legitimately NaN — e.g. a
    masked mean over an empty mask — must not read as divergence just
    because the restored prefix round-tripped through JSON and came back
    as a different NaN object)."""
    if isinstance(a, dict) and isinstance(b, dict):
        return set(a) == set(b) and all(
            history_equal(a[k], b[k]) for k in a
        )
    if isinstance(a, (list, tuple)) and isinstance(b, (list, tuple)):
        return len(a) == len(b) and all(
            history_equal(x, y) for x, y in zip(a, b)
        )
    if isinstance(a, float) and isinstance(b, float):
        return a == b or (a != a and b != b)
    return a == b


def _cell_config(rule: str, mode: str):
    """The cell's tiny-but-real config: synthetic data, an 83-param MLP,
    5 nodes (8 for the sparse exponential graph), 4 total rounds.  Rule
    params come from analysis/ir.py's AGG_CASES so the durability grid
    and the IR/budget grids stay one inventory."""
    from murmura_tpu.analysis.ir import AGG_CASES
    from murmura_tpu.config import Config

    raw: Dict[str, Any] = {
        "experiment": {"name": f"durability-{rule}-{mode}", "seed": 7,
                       "rounds": 4},
        "topology": {"type": "ring", "num_nodes": 5},
        "aggregation": {"algorithm": rule,
                        "params": dict(AGG_CASES.get(rule, {}))},
        "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.05},
        "data": {"adapter": "synthetic",
                 "params": {"num_samples": 40, "input_shape": [6],
                            "num_classes": 3}},
        "model": {"factory": "mlp",
                  "params": {"input_dim": 6, "hidden_dims": [8],
                             "num_classes": 3}},
        "backend": "simulation",
    }
    if mode == "circulant":
        # ppermute requires the tpu backend + a static circulant topology;
        # num_devices pinned to 1 so the cell runs on any host.
        raw["backend"] = "tpu"
        raw["tpu"] = {"exchange": "ppermute", "num_devices": 1,
                      "compute_dtype": "float32"}
    elif mode == "sparse":
        raw["topology"] = {"type": "exponential", "num_nodes": 8}
    elif mode == "compressed":
        raw["compression"] = {"algorithm": "int8", "error_feedback": True,
                              "block": 64}
    elif mode == "adaptive":
        raw["attack"] = {"enabled": True, "type": "gaussian",
                         "percentage": 0.3, "params": {"noise_std": 5.0},
                         "adaptive": {"enabled": True}}
    elif mode == "stale":
        raw["faults"] = {"enabled": True, "straggler_prob": 0.4,
                         "link_drop_prob": 0.2, "seed": 11}
        raw["exchange"] = {"max_staleness": 2, "staleness_discount": 0.5}
    elif mode == "pipeline":
        # Snapshot at round 2 => the pipeline buffer holds round 1's
        # un-aggregated exchange; the resumed run must aggregate it on
        # its first replayed round exactly as the uninterrupted one did.
        raw["exchange"] = {"pipeline": True}
    elif mode != "dense":
        raise ValueError(f"unknown durability mode {mode!r}")
    return Config.model_validate(raw)


def resume_cell_findings(rule: str, mode: str) -> List[Finding]:
    """Run ONE (rule, mode) cell of the resume-determinism contract and
    return its MUR901/902 findings (empty = crash-equivalent).

    The probe: train 2 rounds, snapshot, train 2 more uninterrupted and
    record (history, params, agg_state); restore the snapshot into the
    now-warm network and replay the 2 tail rounds under CompileTracker.
    Exposed per-cell so tests can gate a subset without paying for the
    full grid (tests/test_durability.py)."""
    import jax

    from murmura_tpu.analysis.sanitizers import track_compiles
    from murmura_tpu.utils.factories import build_network_from_config

    path, line = _anchor(rule)
    net = build_network_from_config(_cell_config(rule, mode))
    with tempfile.TemporaryDirectory() as snap:
        net.train(rounds=2, verbose=False)
        net.save_checkpoint(snap)
        net.train(rounds=2, verbose=False)
        full_hist = {k: list(v) for k, v in net.history.items()}
        full_params = [
            np.asarray(x) for x in jax.tree_util.tree_leaves(net.params)
        ]
        full_agg = {k: np.asarray(v) for k, v in net.agg_state.items()}
        restored_round = net.restore_checkpoint(snap)
        if restored_round != 2:
            return [Finding(
                "MUR901", path, line,
                f"[{rule}/{mode}] snapshot saved at round 2 restored to "
                f"round {restored_round} — the round counter did not "
                "survive the roundtrip",
            )]
        with track_compiles() as tracker:
            net.train(rounds=2, verbose=False)
        compiles = tracker.total

    findings: List[Finding] = []
    resumed_hist = {k: list(v) for k, v in net.history.items()}
    if not history_equal(resumed_hist, full_hist):
        diverged = sorted(
            k for k in set(full_hist) | set(resumed_hist)
            if not history_equal(full_hist.get(k), resumed_hist.get(k))
        )
        findings.append(Finding(
            "MUR901", path, line,
            f"[{rule}/{mode}] resumed history diverges from the "
            f"uninterrupted run in {diverged} — save→restore→round is not "
            "byte-equal to the uninterrupted round; some round-crossing "
            "state is missing from the snapshot",
        ))
    for full_leaf, leaf in zip(
        full_params, jax.tree_util.tree_leaves(net.params)
    ):
        if not np.array_equal(full_leaf, np.asarray(leaf), equal_nan=True):
            findings.append(Finding(
                "MUR901", path, line,
                f"[{rule}/{mode}] resumed params diverge byte-wise from "
                "the uninterrupted run — the parameter/rng sections do "
                "not reproduce the interrupted trajectory",
            ))
            break
    for key in sorted(set(full_agg) | set(net.agg_state)):
        a, b = full_agg.get(key), net.agg_state.get(key)
        if a is None or b is None or not np.array_equal(
            a, np.asarray(b), equal_nan=True
        ):
            findings.append(Finding(
                "MUR901", path, line,
                f"[{rule}/{mode}] carried agg_state key '{key}' diverges "
                "after resume — the rule's round-crossing state (EF "
                "residual / reference / trust) is not crash-equivalent",
            ))
    if compiles:
        findings.append(Finding(
            "MUR902", path, line,
            f"[{rule}/{mode}] replaying {2} rounds after a warm restore "
            f"compiled {compiles} program(s) — restore must be value-only "
            "into the already-compiled round program (matching shapes/"
            "dtypes/placement), or a real resume stalls behind a rebuild",
        ))
    return findings


def _anchor(rule: str) -> Tuple[str, int]:
    from murmura_tpu.analysis.ir import _rule_anchor

    return _rule_anchor(rule)


@_family
def check_resume_determinism() -> List[Finding]:
    """MUR901/902 over the full ``AGGREGATORS x DURABILITY_MODES`` grid.
    A cell that crashes outright is itself a MUR901 finding — a rule that
    cannot even run the save→restore→replay probe has no resume story."""
    from murmura_tpu.aggregation import AGGREGATORS

    findings: List[Finding] = []
    for rule in sorted(AGGREGATORS):
        for mode in DURABILITY_MODES:
            try:
                findings.extend(resume_cell_findings(rule, mode))
            except Exception as e:  # noqa: BLE001 — a crash IS the finding
                path, line = _anchor(rule)
                findings.append(Finding(
                    "MUR901", path, line,
                    f"[{rule}/{mode}] resume-determinism probe crashed: "
                    f"{type(e).__name__}: {e}",
                ))
    return findings


# --------------------------------------------------------------------------
# Entry point
# --------------------------------------------------------------------------

_DURABILITY_MEMO: Optional[List[Finding]] = None


def check_durability(force: bool = False) -> List[Finding]:
    """Run MUR901/902 over the durability grid; returns findings (empty =
    every rule x mode resumes crash-equivalently with zero recompiles).
    Memoized per process — the CLI, the battery pre-flight and the slow
    test gate share one sweep.  Unlike check_flow this EXECUTES programs
    (compile + 6 tiny rounds per cell, ~2 min for the 36-cell grid on
    CPU), which is why it runs only for the package-level check."""
    global _DURABILITY_MEMO
    if _DURABILITY_MEMO is not None and not force:
        return list(_DURABILITY_MEMO)

    from murmura_tpu.analysis.ir import _apply_suppressions

    findings: List[Finding] = []
    for fam_name, fam in DURABILITY_CHECK_FAMILIES.items():
        try:
            findings.extend(fam())
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            findings.append(Finding(
                "MUR901", str(Path(__file__).resolve()), 1,
                f"durability check family '{fam_name}' crashed: "
                f"{type(e).__name__}: {e}",
            ))
    findings = _apply_suppressions(list(dict.fromkeys(findings)))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    _DURABILITY_MEMO = list(findings)
    return findings

"""JAX-aware AST lint engine.

Six rule classes over *traced scopes* (functions that execute under a JAX
trace — ``jit``/``vmap``/``scan`` bodies and the aggregation-rule protocol
functions) plus two whole-file checks:

====== ================== =====================================================
rule   name               what it catches
====== ================== =====================================================
MUR001 traced-branch      Python ``if``/``while``/``for`` control flow on a
                          traced value — a ConcretizationTypeError at trace
                          time at best, a silent recompile-per-value at worst.
MUR002 traced-assert      ``assert`` on a traced value — either traces away
                          silently (no check runs on device) or forces a sync.
MUR003 host-sync          ``.item()``/``.tolist()``/``float()``/``int()``/
                          ``np.asarray``/``jax.device_get``/``print`` applied
                          to traced values — a device→host round-trip that
                          serializes the round hot path.
MUR004 recompile-hazard   ``jax.jit`` called inside a Python loop (a fresh
                          cache per iteration) and traced values used as
                          ``range`` bounds (should be marked static).
MUR005 import-time-alloc  module-scope ``jnp.*``/``jax.random.*``/
                          ``jax.devices`` calls — they initialize the XLA
                          backend at import, before mesh/multihost setup
                          (parallel/mesh.py) can pin the platform.
MUR006 dtype-promotion    ``jnp.zeros/ones/full/array/...`` without an
                          explicit ``dtype`` combined directly with traced
                          state — the f32 default silently promotes bf16
                          kernels (tpu.param_dtype) and doubles their HBM
                          working set.
====== ================== =====================================================

Traced scopes are found by: ``@jax.jit``-style decorators; functions passed
by name to ``jit``/``vmap``/``grad``/``lax.scan``-family calls in the same
module; the aggregation-rule protocol names (``aggregate``,
``aggregate_circulant`` — AggregatorDef's contract); an explicit
``# murmura: traced`` marker on the ``def`` line; and anything lexically
nested inside one of those.

Inside a traced scope a lightweight forward taint pass tracks which names
hold traced values: parameters seed the set; results of calls involving
tainted values propagate it; static accessors (``.shape``, ``.dtype``,
``len()``, ``is None``/``in`` comparisons, the static AggContext fields)
break it.  This keeps ``if x.shape[0] > 4`` and ``if ctx.evidential`` legal
while ``if x.sum() > 0`` is flagged.

Suppression: append ``# murmura: ignore[MUR003]`` (comma-separated ids, or
bare ``ignore`` for all rules) to the flagged line.
"""

import ast
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

RULES: Dict[str, str] = {
    "MUR000": "syntax-error",
    "MUR001": "traced-branch",
    "MUR002": "traced-assert",
    "MUR003": "host-sync",
    "MUR004": "recompile-hazard",
    "MUR005": "import-time-alloc",
    "MUR006": "dtype-promotion",
    # 1xx = cross-layer contract checks (analysis/contracts.py)
    "MUR100": "contract-import-failure",
    "MUR101": "registry-schema-sync",
    "MUR102": "per-rule-test-coverage",
    "MUR103": "topology-zero-diagonal",
    # 2xx = jaxpr/HLO-level IR contracts (analysis/ir.py) and AOT cost
    # budgets (analysis/budgets.py)
    "MUR200": "ir-host-callback",
    "MUR201": "ir-dtype-discipline",
    "MUR202": "ir-collective-inventory",
    "MUR203": "ir-shape-polymorphism",
    "MUR204": "ir-donation",
    "MUR205": "ir-coverage",
    "MUR206": "cost-budget-drift",
    # 3xx = fault-model contracts (analysis/contracts.py + analysis/ir.py)
    "MUR300": "fault-import-failure",
    "MUR301": "fault-mask-zero-diagonal",
    "MUR302": "fault-mask-recompile",
    "MUR303": "fault-collective-inventory",
    # 4xx = telemetry contracts (analysis/contracts.py + analysis/ir.py;
    # docs/OBSERVABILITY.md)
    "MUR400": "telemetry-tap-collectives",
    "MUR401": "telemetry-schema-migration-note",
    "MUR402": "telemetry-tap-recompile",
    # 5xx = gang-batched execution contracts (analysis/ir.py;
    # docs/PERFORMANCE.md)
    "MUR500": "gang-collective-inventory",
    "MUR501": "gang-bucket-recompile",
    # 6xx = sparse exchange / population contracts (analysis/ir.py +
    # analysis/contracts.py; docs/SCALING.md)
    "MUR600": "sparse-dense-free",
    "MUR601": "sparse-collective-inventory",
    "MUR602": "sparse-population-bijections",
    # 7xx = compressed exchange contracts (analysis/ir.py;
    # docs/PERFORMANCE.md)
    "MUR700": "compressed-payload",
    "MUR701": "compression-recompile",
    "MUR702": "compression-donation",
    # 8xx = jaxpr dataflow contracts (analysis/flow.py, `check --flow`;
    # docs/ANALYSIS.md)
    "MUR800": "influence-bound",
    "MUR801": "influence-declaration",
    "MUR802": "influence-mode-parity",
    "MUR803": "flow-scrub-dominance",
    "MUR804": "flow-zero-denominator",
    # 9xx = durability contracts (analysis/contracts.py MUR900;
    # analysis/durability.py MUR901/902; docs/ROBUSTNESS.md)
    "MUR900": "snapshot-completeness",
    "MUR901": "resume-determinism",
    "MUR902": "resume-recompile",
    # 10xx = adaptive-adversary contracts (analysis/adaptive.py;
    # docs/ROBUSTNESS.md "Adaptive adversaries & the frontier")
    "MUR1000": "attack-state-registry",
    "MUR1001": "adaptive-attack-recompile",
    "MUR1002": "adaptive-collective-inventory",
    "MUR1003": "adaptive-influence-containment",
    # 11xx = bounded-staleness contracts (analysis/staleness.py;
    # docs/ROBUSTNESS.md "Bounded staleness")
    "MUR1100": "stale-state-registry",
    "MUR1101": "stale-recompile",
    "MUR1102": "stale-collective-inventory",
    "MUR1103": "stale-influence-replay-hole",
    # 12xx = pipelined-rounds contracts (analysis/pipeline.py;
    # docs/PERFORMANCE.md "Pipelined rounds")
    "MUR1200": "pipeline-state-registry",
    "MUR1201": "pipeline-recompile",
    "MUR1202": "pipeline-collective-inventory",
    "MUR1203": "pipeline-delayed-influence",
    # 13xx = param-axis sharding contracts (analysis/sharded.py;
    # docs/PERFORMANCE.md "Param-axis sharding")
    "MUR1300": "sharded-collective-inventory",
    "MUR1301": "sharded-recompile",
    "MUR1302": "sharded-bit-parity",
    "MUR1303": "sharded-execution-parity",
    # 14xx = cross-feature composition contracts (analysis/composition.py,
    # `check --compose`; docs/ANALYSIS.md "Composition grid")
    "MUR1400": "manifest-bijection",
    "MUR1401": "composition-grid",
    "MUR1402": "composition-state-stages",
    "MUR1403": "composition-influence",
    # 15xx = static memory contracts (analysis/memory.py,
    # `check --memory`; docs/ANALYSIS.md "Memory contracts")
    "MUR1500": "memory-budget",
    "MUR1501": "sharded-memory-scaling",
    "MUR1502": "donation-completeness",
    "MUR1503": "overlap-dependence",
    # 16xx = serving contracts (analysis/serve.py, `check --serve`;
    # docs/ROBUSTNESS.md "Serving")
    "MUR1600": "serve-bucket-key",
    "MUR1601": "serve-admission-recompile",
    "MUR1602": "serve-frozen-lane",
    "MUR1603": "serve-resume-completeness",
    # 17xx = observability contracts (analysis/observe.py,
    # `check --observe`; docs/OBSERVABILITY.md "The fleet observability
    # plane")
    "MUR1700": "metrics-ledger-parity",
    "MUR1701": "scrape-non-interference",
    "MUR1702": "span-well-formedness",
    "MUR1703": "observability-schema-discipline",
}


@dataclass(frozen=True)
class Finding:
    rule: str
    path: str
    line: int
    message: str
    # Optional machine-readable payload for `check --json` (budget deltas
    # etc.).  Excluded from eq/hash so findings stay dedupable.
    data: Optional[dict] = field(default=None, compare=False)

    @property
    def name(self) -> str:
        return RULES.get(self.rule, "unknown")


# Attribute reads that yield static (Python-level) values even on tracers,
# plus the static fields of AggContext (aggregation/base.py) — branching on
# these is ordinary Python control flow, not traced control flow.
STATIC_ATTRS = {
    "shape", "dtype", "ndim", "size", "itemsize", "nbytes",
    # AggContext static fields
    "evidential", "num_classes", "total_rounds", "node_axis_sharded",
    # telemetry.audit_taps: a trace-time Python bool on AggContext — the
    # tap branches are ordinary staging-time control flow (MUR400/402 pin
    # that the tapped program is collective- and recompile-clean).
    "audit",
    # CompressionSpec fields that traced code BRANCHES on (ops/compress.py,
    # core/rounds.py): the codec choice and error-feedback toggle are
    # trace-time program structure by contract (MUR701).  Deliberately
    # minimal — the whitelist is name-based with no receiver-type
    # awareness, so every name here weakens MUR001 for that attribute
    # package-wide; Int8Blocks' shape-derived fields (block/p/num_blocks
    # etc.) only appear in arithmetic/slicing, which the taint pass never
    # flags, and stay OFF the list.
    "algorithm", "error_feedback",
}

# Callables whose function-position arguments execute under a trace, mapped
# to (positional indices, keyword names) where functions actually appear.
# Only those slots mark a name as traced — data arguments (scan's init/xs,
# cond's operands) routinely reuse common names like ``init`` that also name
# unrelated host functions in the same module.
_FUN0 = ((0,), ("fun", "f", "fn"))
TRACING_CALLS: Dict[str, Tuple[Tuple[int, ...], Tuple[str, ...]]] = {
    "jax.jit": _FUN0, "jit": _FUN0,
    "jax.vmap": _FUN0, "vmap": _FUN0,
    "jax.pmap": _FUN0, "pmap": _FUN0,
    "jax.grad": _FUN0, "grad": _FUN0,
    "jax.value_and_grad": _FUN0, "value_and_grad": _FUN0,
    "jax.lax.scan": _FUN0, "lax.scan": _FUN0,
    "jax.lax.fori_loop": ((2,), ("body_fun",)),
    "lax.fori_loop": ((2,), ("body_fun",)),
    "jax.lax.while_loop": ((0, 1), ("cond_fun", "body_fun")),
    "lax.while_loop": ((0, 1), ("cond_fun", "body_fun")),
    "jax.lax.map": _FUN0, "lax.map": _FUN0,
    "jax.lax.cond": ((1, 2), ("true_fun", "false_fun")),
    "lax.cond": ((1, 2), ("true_fun", "false_fun")),
    "jax.lax.switch": ((1,), ("branches",)),
    "lax.switch": ((1,), ("branches",)),
    "jax.checkpoint": _FUN0, "jax.remat": _FUN0, "jax.eval_shape": _FUN0,
    "jax.lax.associative_scan": _FUN0, "lax.associative_scan": _FUN0,
    # Pallas kernels execute under a trace too (ops/pallas_agg.py,
    # ops/pallas_sketch.py): the kernel function handed to pallas_call —
    # or closed over via functools.partial in argument position — is a
    # traced scope, which is what pulls murmura_tpu/ops/ into the MUR0xx
    # scan.
    "pl.pallas_call": _FUN0, "pallas_call": _FUN0,
    "jax.experimental.pallas.pallas_call": _FUN0,
}

# Function names the repo's protocols guarantee are traced: AggregatorDef
# aggregate functions compile into the jitted round step (core/rounds.py).
PROTOCOL_TRACED_NAMES = {"aggregate", "aggregate_circulant"}

JIT_NAMES = {"jax.jit", "jit"}

# Array constructors whose dtype defaults to float32 (MUR006).  Maps name to
# the positional index at which dtype may be passed (None = keyword-only).
F32_DEFAULT_CTORS = {
    "jnp.zeros": 1, "jnp.ones": 1, "jnp.empty": 1, "jnp.full": 2,
    "jnp.array": 1, "jnp.asarray": 1, "jnp.eye": None, "jnp.identity": 1,
    "jnp.linspace": None,
}

# array/asarray preserve an array operand's dtype and yield weak types for
# bare scalars (neither promotes bf16); only list/tuple literals of Python
# floats commit to the float32 default.
DTYPE_PRESERVING_CTORS = {"jnp.array", "jnp.asarray"}

HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready", "copy_to_host_async"}
HOST_SYNC_BUILTINS = {"float", "int", "bool", "complex"}

_IGNORE_RE = re.compile(r"#\s*murmura:\s*ignore(?:\[([A-Z0-9,\s]+)\])?")
_TRACED_MARK_RE = re.compile(r"#\s*murmura:\s*traced\b")


def _dotted(node: ast.AST) -> str:
    """Dotted-name repr of a Name/Attribute chain ('' if not a plain chain)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def _has_dtype(call: ast.Call, func: str) -> bool:
    if any(kw.arg == "dtype" for kw in call.keywords):
        return True
    pos = F32_DEFAULT_CTORS.get(func)
    return pos is not None and len(call.args) > pos


class _ModuleScanner:
    """Whole-file pass: traced-scope discovery, MUR004 (jit-in-loop) and
    MUR005 (import-time allocation)."""

    def __init__(self, tree: ast.Module, source_lines: List[str], path: str):
        self.tree = tree
        self.lines = source_lines
        self.path = path
        self.findings: List[Finding] = []
        self.traced_names: Set[str] = set(PROTOCOL_TRACED_NAMES)
        self.traced_lambdas: List[ast.Lambda] = []
        # Keyword names bound by functools.partial when a kernel/function
        # was handed to a tracing call (pl.pallas_call(partial(k, off=...)))
        # — those parameters hold trace-setup-time Python values, never
        # tracers, so they must not seed the taint set.
        self.partial_static: Dict[str, Set[str]] = {}

    def emit(self, rule: str, node: ast.AST, message: str) -> None:
        line = getattr(node, "lineno", 1)
        self.findings.append(Finding(rule, self.path, line, message))

    def scan(self) -> List[Finding]:
        self._collect_traced_names()
        self._scan_import_time(self.tree.body)
        self._scan_jit_in_loop(self.tree)
        for fn in self._traced_roots(self.tree):
            _TaintScanner(self, fn, inherited=set()).run()
        for lam in self.traced_lambdas:
            _TaintScanner(self, _lambda_as_fn(lam), inherited=set()).run()
        # A lambda passed to jit inside a traced function is scanned both by
        # the enclosing taint pass and via traced_lambdas — dedupe, keeping
        # first-seen order.
        return list(dict.fromkeys(self.findings))

    # -- traced-scope discovery ------------------------------------------

    def _collect_traced_names(self) -> None:
        for node in ast.walk(self.tree):
            if not isinstance(node, ast.Call):
                continue
            spec = TRACING_CALLS.get(_dotted(node.func))
            if spec is None:
                continue
            positions, kw_names = spec
            fn_args = [node.args[i] for i in positions if i < len(node.args)]
            fn_args += [kw.value for kw in node.keywords if kw.arg in kw_names]
            for arg in fn_args:
                # lax.switch takes a list/tuple of branch functions.
                elts = arg.elts if isinstance(arg, (ast.Tuple, ast.List)) else [arg]
                for el in elts:
                    # functools.partial(kernel, ...) in function position
                    # (the pallas_call idiom) traces the partial's target.
                    if (
                        isinstance(el, ast.Call)
                        and _dotted(el.func) in {"functools.partial", "partial"}
                        and el.args
                    ):
                        target = el.args[0]
                        if isinstance(target, ast.Name):
                            bound = {
                                kw.arg for kw in el.keywords
                                if kw.arg is not None
                            }
                            self.partial_static.setdefault(
                                target.id, set()
                            ).update(bound)
                        el = target
                    if isinstance(el, ast.Name):
                        self.traced_names.add(el.id)
                    elif isinstance(el, ast.Lambda):
                        self.traced_lambdas.append(el)

    def _is_traced(self, fn: ast.FunctionDef) -> bool:
        if fn.name in self.traced_names:
            return True
        for dec in fn.decorator_list:
            d = _dotted(dec)
            if d in JIT_NAMES:
                return True
            if isinstance(dec, ast.Call):
                dfun = _dotted(dec.func)
                if dfun in JIT_NAMES:
                    return True
                if dfun in {"functools.partial", "partial"} and dec.args:
                    if _dotted(dec.args[0]) in JIT_NAMES:
                        return True
        line = self.lines[fn.lineno - 1] if fn.lineno <= len(self.lines) else ""
        return bool(_TRACED_MARK_RE.search(line))

    def _traced_roots(self, node) -> Iterator[ast.FunctionDef]:
        """Outermost traced functions anywhere in the file.  Functions nested
        inside a traced root are covered by the root's taint scan (closure
        taint flows down); functions nested in untraced parents are still
        discovered here (e.g. ``train_round`` inside build_round_program)."""
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if self._is_traced(child):
                    yield child
                else:
                    yield from self._traced_roots(child)
            elif not isinstance(child, ast.Lambda):
                yield from self._traced_roots(child)

    # -- module-level checks ----------------------------------------------

    def _scan_import_time(self, body) -> None:
        """MUR005: calls executed at module import (module and class scope,
        pruning function/lambda bodies — those run later)."""

        def walk_pruned(node) -> Iterator[ast.AST]:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    # The body runs later; decorators and defaults (both
                    # positional and keyword-only) run now.
                    import_time_exprs = (
                        child.decorator_list
                        + child.args.defaults
                        + [d for d in child.args.kw_defaults if d is not None]
                    )
                    for expr in import_time_exprs:
                        yield expr
                        yield from walk_pruned(expr)
                    continue
                if isinstance(child, ast.Lambda):
                    continue
                yield child
                yield from walk_pruned(child)

        root = ast.Module(body=list(body), type_ignores=[])
        for sub in walk_pruned(root):
            if isinstance(sub, ast.Call):
                f = _dotted(sub.func)
                if (
                    f.startswith(("jnp.", "jax.numpy.", "jax.random."))
                    or f in {
                        "jax.devices", "jax.local_devices",
                        "jax.device_count", "jax.local_device_count",
                        "jax.device_put",
                    }
                ):
                    self.emit(
                        "MUR005", sub,
                        f"module-import-time call to {f}() initializes "
                        "the XLA backend before mesh/platform setup "
                        "(parallel/mesh.py) — move it inside a function",
                    )

    def _scan_jit_in_loop(self, fn) -> None:
        """MUR004(a): a jax.jit call lexically inside a for/while body gets a
        fresh compile cache per iteration."""

        def walk(node, in_loop: bool) -> None:
            for child in ast.iter_child_nodes(node):
                child_in_loop = in_loop or isinstance(child, (ast.For, ast.While))
                if isinstance(child, ast.Call) and _dotted(child.func) in JIT_NAMES:
                    if in_loop:
                        self.emit(
                            "MUR004", child,
                            "jax.jit called inside a loop body: each "
                            "iteration builds a fresh jitted callable with "
                            "an empty compile cache — hoist the jit out of "
                            "the loop",
                        )
                walk(child, child_in_loop)

        walk(fn, False)


class _TaintScanner:
    """Forward taint pass over one traced function (statements in order).

    ``tainted`` holds names bound to traced values.  Nested function defs
    recurse with the enclosing taint (closure reads) plus their own params.
    """

    def __init__(self, module: _ModuleScanner, fn, inherited: Set[str]):
        self.m = module
        self.fn = fn
        self.tainted: Set[str] = set(inherited)
        a = fn.args
        for arg in a.posonlyargs + a.args + a.kwonlyargs:
            self.tainted.add(arg.arg)
        if a.vararg is not None:
            self.tainted.add(a.vararg.arg)
        # **kwargs holds static configuration by convention — not tainted.
        # Params declared static in the jit decorator are Python values
        # under the trace — branching on them is legal specialization, as
        # are keywords bound by a functools.partial at the tracing call
        # site (the pallas kernel-config idiom).
        self.tainted -= _static_params(fn)
        self.tainted -= module.partial_static.get(
            getattr(fn, "name", ""), set()
        )

    def run(self) -> None:
        self._visit_body(self.fn.body)

    # -- statements -------------------------------------------------------

    def _visit_body(self, body) -> None:
        for stmt in body:
            self._visit_stmt(stmt)

    def _visit_stmt(self, stmt) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            _TaintScanner(self.m, stmt, inherited=set(self.tainted)).run()
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = stmt.value
            t = self._expr(value) if value is not None else False
            targets = (
                stmt.targets if isinstance(stmt, ast.Assign) else [stmt.target]
            )
            for target in targets:
                if isinstance(stmt, ast.AugAssign):
                    t = t or self._expr(target)
                self._bind(target, t)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            if self._expr(stmt.test):
                kind = "if" if isinstance(stmt, ast.If) else "while"
                self.m.emit(
                    "MUR001", stmt,
                    f"Python `{kind}` on a traced value inside a traced "
                    "scope — use jnp.where/lax.cond/lax.while_loop (or mark "
                    "the operand static)",
                )
            self._visit_body(stmt.body)
            self._visit_body(stmt.orelse)
            return
        if isinstance(stmt, ast.For):
            iter_tainted = self._expr(stmt.iter)
            if iter_tainted:
                self.m.emit(
                    "MUR001", stmt,
                    "Python `for` iterating a traced value inside a traced "
                    "scope — use lax.scan/lax.fori_loop",
                )
            # Iterating a static container (range, enumerate of offsets...)
            # yields static values; only a traced iterable taints the target.
            self._bind(stmt.target, iter_tainted)
            self._visit_body(stmt.body)
            self._visit_body(stmt.orelse)
            return
        if isinstance(stmt, ast.Assert):
            if self._expr(stmt.test):
                self.m.emit(
                    "MUR002", stmt,
                    "`assert` on a traced value inside a traced scope — "
                    "it either traces away (never checked on device) or "
                    "forces a host sync; use checkify or a masked metric",
                )
            return
        if isinstance(stmt, (ast.Return, ast.Expr)):
            if stmt.value is not None:
                self._expr(stmt.value)
            return
        if isinstance(stmt, ast.With):
            for item in stmt.items:
                t = self._expr(item.context_expr)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, t)
            self._visit_body(stmt.body)
            return
        if isinstance(stmt, ast.Try):
            self._visit_body(stmt.body)
            for handler in stmt.handlers:
                self._visit_body(handler.body)
            self._visit_body(stmt.orelse)
            self._visit_body(stmt.finalbody)
            return
        # Raise / Pass / Import / Delete / Global ... — walk embedded exprs
        for sub in ast.iter_child_nodes(stmt):
            if isinstance(sub, ast.expr):
                self._expr(sub)

    def _bind(self, target, tainted: bool) -> None:
        if isinstance(target, ast.Name):
            if tainted:
                self.tainted.add(target.id)
            else:
                self.tainted.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for el in target.elts:
                self._bind(el, tainted)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, tainted)
        # Subscript/Attribute targets mutate containers — leave taint as-is.

    # -- expressions ------------------------------------------------------

    def _expr(self, node) -> bool:
        """Evaluate taint of an expression, emitting findings on the way."""
        if node is None:
            return False
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                self._expr(node.value)
                return False
            return self._expr(node.value)
        if isinstance(node, ast.Subscript):
            self._expr(node.slice)
            return self._expr(node.value)
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, ast.BinOp):
            lt = self._expr(node.left)
            rt = self._expr(node.right)
            self._dtype_promotion(node, lt, rt)
            return lt or rt
        if isinstance(node, (ast.UnaryOp,)):
            return self._expr(node.operand)
        if isinstance(node, ast.BoolOp):
            # Materialize before any(): a short-circuiting generator would
            # skip scanning (and emitting findings in) later operands.
            return any([self._expr(v) for v in node.values])
        if isinstance(node, ast.Compare):
            ts = [self._expr(node.left)] + [self._expr(c) for c in node.comparators]
            # is/is not/in/not in are host-level identity & containment —
            # `x is None`, `"loss" in ctx.probe_cross` are static branches.
            if all(isinstance(op, (ast.Is, ast.IsNot, ast.In, ast.NotIn)) for op in node.ops):
                return False
            return any(ts)
        if isinstance(node, ast.IfExp):
            self._expr(node.test)
            return self._expr(node.body) or self._expr(node.orelse)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any([self._expr(el) for el in node.elts])
        if isinstance(node, ast.Dict):
            return any(
                [self._expr(v) for v in list(node.keys) + list(node.values) if v]
            )
        if isinstance(node, ast.Starred):
            return self._expr(node.value)
        if isinstance(node, (ast.JoinedStr, ast.FormattedValue)):
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, ast.expr):
                    self._expr(sub)
            return False
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)):
            t = False
            for gen in node.generators:
                if self._expr(gen.iter):
                    t = True
                self._bind(gen.target, t)
                for cond in gen.ifs:
                    self._expr(cond)
            if isinstance(node, ast.DictComp):
                return self._expr(node.key) or self._expr(node.value) or t
            return self._expr(node.elt) or t
        if isinstance(node, ast.Lambda):
            _TaintScanner(self.m, _lambda_as_fn(node), set(self.tainted)).run()
            return False
        if isinstance(node, ast.NamedExpr):
            t = self._expr(node.value)
            self._bind(node.target, t)
            return t
        # Anything else: conservatively walk children, propagate any taint.
        return any(
            [
                self._expr(sub)
                for sub in ast.iter_child_nodes(node)
                if isinstance(sub, ast.expr)
            ]
        )

    def _call(self, node: ast.Call) -> bool:
        func = _dotted(node.func)
        arg_taints = [self._expr(a) for a in node.args]
        kw_taints = [self._expr(kw.value) for kw in node.keywords]
        any_arg_tainted = any(arg_taints) or any(kw_taints)

        # len() of a tracer is its static leading-dim extent — a Python int
        # under the trace, same as .shape[0] (the docstring's taint-breaker
        # contract).
        if func == "len":
            return False

        # MUR003: host-sync calls on traced values
        if isinstance(node.func, ast.Attribute) and node.func.attr in HOST_SYNC_METHODS:
            if self._expr(node.func.value):
                self.m.emit(
                    "MUR003", node,
                    f".{node.func.attr}() on a traced value forces a "
                    "device->host sync inside the traced scope",
                )
                return False
        if func in HOST_SYNC_BUILTINS and any_arg_tainted:
            self.m.emit(
                "MUR003", node,
                f"{func}() of a traced value forces a device->host sync "
                "inside the traced scope (use jnp casts instead)",
            )
            return False
        if func.startswith(("np.", "numpy.")) and any_arg_tainted:
            self.m.emit(
                "MUR003", node,
                f"{func}() pulls a traced value to the host inside the "
                "traced scope — use the jnp equivalent",
            )
            return False
        if func == "jax.device_get":
            self.m.emit(
                "MUR003", node,
                "jax.device_get inside a traced scope is a host sync — "
                "fetch results outside the compiled program",
            )
            return False
        if func == "print" and any_arg_tainted:
            self.m.emit(
                "MUR003", node,
                "print() of a traced value syncs (or silently prints a "
                "tracer) inside the traced scope — use jax.debug.print",
            )
            return False

        # MUR004(b): traced value as a Python range bound
        if func == "range" and any_arg_tainted:
            self.m.emit(
                "MUR004", node,
                "traced value used as a range() bound — mark the argument "
                "static (jit static_argnums) or use lax.fori_loop",
            )
            return False

        # Taint of the call result: tainted function object (method on a
        # traced value) or any tainted argument.  Pure jnp constructions
        # from static arguments stay untainted (constants under trace).
        func_obj_tainted = (
            isinstance(node.func, ast.Attribute) and self._expr(node.func.value)
        )
        return func_obj_tainted or any_arg_tainted

    def _dtype_promotion(self, binop: ast.BinOp, lt: bool, rt: bool) -> None:
        """MUR006: dtype-less f32-default constructor as a direct arithmetic
        operand of traced state."""
        for ctor, other_tainted in ((binop.left, rt), (binop.right, lt)):
            if not other_tainted or not isinstance(ctor, ast.Call):
                continue
            func = _dotted(ctor.func)
            if func not in F32_DEFAULT_CTORS or _has_dtype(ctor, func):
                continue
            if func in DTYPE_PRESERVING_CTORS and not (
                ctor.args and isinstance(ctor.args[0], (ast.Tuple, ast.List))
            ):
                continue
            self.m.emit(
                "MUR006", ctor,
                f"{func}() without an explicit dtype defaults to float32 "
                "and promotes bf16 traced operands (tpu.param_dtype) — "
                "pass dtype= (e.g. the operand's .dtype)",
            )


def _static_params(fn) -> Set[str]:
    """Parameter names marked static in a jit decorator on ``fn``.

    Understands ``@jax.jit(..., static_argnums=/static_argnames=...)`` and
    the ``@functools.partial(jax.jit, static_arg...=...)`` spelling;
    ``static_argnums`` indices are resolved against the positional
    parameter order (posonly + args, the order jit itself uses).
    """
    if not hasattr(fn, "decorator_list"):
        return set()
    positional = [
        a.arg for a in list(fn.args.posonlyargs) + list(fn.args.args)
    ]
    static: Set[str] = set()
    for dec in fn.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        dfun = _dotted(dec.func)
        is_jit_call = dfun in JIT_NAMES
        is_partial_jit = (
            dfun in {"functools.partial", "partial"}
            and dec.args
            and _dotted(dec.args[0]) in JIT_NAMES
        )
        if not (is_jit_call or is_partial_jit):
            continue
        for kw in dec.keywords:
            if kw.arg not in ("static_argnums", "static_argnames"):
                continue
            values = (
                kw.value.elts
                if isinstance(kw.value, (ast.Tuple, ast.List))
                else [kw.value]
            )
            for v in values:
                if not isinstance(v, ast.Constant):
                    continue
                if isinstance(v.value, str):
                    static.add(v.value)
                elif isinstance(v.value, int) and -len(positional) <= v.value < len(positional):
                    static.add(positional[v.value])
    return static


def _lambda_as_fn(node: ast.Lambda):
    """Wrap a Lambda so _TaintScanner can treat it like a FunctionDef."""
    fn = ast.FunctionDef(
        name="<lambda>", args=node.args,
        body=[ast.Return(value=node.body, lineno=node.lineno, col_offset=0)],
        decorator_list=[], returns=None, type_comment=None,
        lineno=node.lineno, col_offset=node.col_offset,
    )
    return fn


def _suppressed(findings: List[Finding], lines: List[str]) -> List[Finding]:
    out = []
    for f in findings:
        line = lines[f.line - 1] if 0 < f.line <= len(lines) else ""
        m = _IGNORE_RE.search(line)
        if m:
            ids = m.group(1)
            if ids is None or f.rule in {s.strip() for s in ids.split(",")}:
                continue
        out.append(f)
    return out


def lint_file(path) -> List[Finding]:
    """Lint one Python file; returns findings after suppression filtering."""
    p = Path(path)
    try:
        source = p.read_text()
    except (OSError, UnicodeDecodeError) as e:
        return [Finding("MUR000", str(p), 1, f"unreadable file: {e}")]
    try:
        tree = ast.parse(source, filename=str(p))
    except SyntaxError as e:
        return [Finding("MUR000", str(p), e.lineno or 1, f"syntax error: {e.msg}")]
    lines = source.splitlines()
    findings = _ModuleScanner(tree, lines, str(p)).scan()
    return _suppressed(findings, lines)


def lint_paths(paths: Sequence) -> List[Finding]:
    """Lint every ``*.py`` under each path (files or directories)."""
    findings: List[Finding] = []
    for path in paths:
        p = Path(path)
        files = sorted(p.rglob("*.py")) if p.is_dir() else [p]
        for f in files:
            findings.extend(lint_file(f))
    return findings

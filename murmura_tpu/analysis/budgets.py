"""AOT cost budgets (MUR206) — committed FLOPs/bytes per aggregator cell.

Generalizes ``Network.step_cost_analysis`` (core/network.py) from a bench
diagnostic into a compile-time perf gate: every registry aggregator is
AOT-compiled (``.lower().compile().cost_analysis()`` — nothing executes) on
CPU over the canonical (n x dim x mode) grid from :mod:`analysis.ir`, and
the measured flops/bytes are compared against the committed
``analysis/BUDGETS.json`` with a ±10% tolerance.  A +20% FLOPs change to
any rule therefore fails ``murmura check --ir`` before a bench ever reaches
a chip, and ``murmura check --update-budgets`` rewrites the file so the
diff itself becomes reviewable perf history — a budget bump nobody can
explain in review is the regression, caught at the cheapest possible
moment.

Budget keys are ``<rule>/n<N>/d<DIM>/<dtype>/<mode>``; cells carry
``{"flops": f, "bytes": b}`` from XLA's own cost model.  The numbers are
deterministic for a fixed jax/XLA build; after a toolchain upgrade the
workflow is: run ``--update-budgets``, review the diff, commit.
"""

import contextlib
import json
import math
import os
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

from murmura_tpu.analysis.lint import Finding

BUDGETS_PATH = Path(__file__).resolve().parent / "BUDGETS.json"

# Canonical sweep: two network sizes x two model dims x both exchange
# modes, float32 (the budget tracks program *shape*, not precision; bf16
# discipline is MUR201's job and CPU bf16 costs would measure emulation
# artifacts).  Probe-based rules are pinned to the canonical probe model's
# own dimension, so they contribute one dim each.
BUDGET_NODE_COUNTS: Tuple[int, ...] = (8, 16)
BUDGET_MODEL_DIMS: Tuple[int, ...] = (256, 1024)
BUDGET_DTYPE = "float32"
TOLERANCE = 0.10

# Fused Pallas aggregation kernels (ops/pallas_agg.py): the circulant
# cells of these rules are additionally measured with the kernels armed
# (mode "pallas"), so the fused formulation's FLOP/bytes delta vs the lax
# circulant cells is committed, reviewable perf history.  On CPU the
# kernels run interpreted — the numbers track the interpreter's lowering,
# which is stable for a fixed jax build (same contract as every other
# cell).
PALLAS_BUDGET_RULES: Tuple[str, ...] = ("krum", "median", "trimmed_mean")


def normalize_cost_analysis(cost) -> Dict[str, float]:
    """Flatten the cross-version shapes of ``Compiled.cost_analysis()``
    (older jax returns ``[dict]``, newer a plain dict, either may be empty)
    into one dict.  Shared with ``Network.step_cost_analysis``."""
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost or {})


def budget_key(name: str, n: int, dim: int, mode: str) -> str:
    return f"{name}/n{n}/d{dim}/{BUDGET_DTYPE}/{mode}"


def _cpu_device():
    import jax

    try:
        return jax.devices("cpu")[0]
    except RuntimeError:
        return None


_COMPILED_MEMO: Dict[Tuple[str, int, bool, Optional[int], bool], Any] = {}


def compiled_cell(
    name: str, n: int, circulant: bool, dim: Optional[int] = None,
    pallas: bool = False,
):
    """The ONE memoized AOT compile of a canonical cell on CPU, shared by
    every consumer of the grid — the MUR206 cost gate reads its
    ``cost_analysis()``, memory consumers its ``memory_analysis()`` and
    HLO text — so adding a metric family never adds a compile sweep
    (the flow-memoization precedent; ``Network.step_cost_analysis`` /
    ``step_memory_analysis`` share their compile the same way)."""
    import jax

    from murmura_tpu.analysis import ir

    key = (name, n, circulant, dim, pallas)
    if key in _COMPILED_MEMO:
        return _COMPILED_MEMO[key]
    params = (
        dict(ir.AGG_CASES.get(name, {}), pallas=True) if pallas else None
    )
    prog = ir.build_canonical(
        name, n, BUDGET_DTYPE, circulant, dim=dim, params=params
    )
    dev = _cpu_device()
    cm = jax.default_device(dev) if dev is not None else contextlib.nullcontext()
    with cm:
        compiled = jax.jit(prog.fn).lower(*prog.args).compile()
    _COMPILED_MEMO[key] = compiled
    return compiled


def measure_cell(
    name: str, n: int, circulant: bool, dim: Optional[int] = None,
    pallas: bool = False,
) -> Dict[str, float]:
    """Read XLA's cost model off the shared compiled cell."""
    cost = normalize_cost_analysis(
        compiled_cell(name, n, circulant, dim=dim, pallas=pallas)
        .cost_analysis()
    )
    return {
        "flops": float(cost.get("flops", 0.0)),
        "bytes": float(cost.get("bytes accessed", 0.0)),
    }


def apply_persistent_cache() -> Optional[str]:
    """Honor JAX's persistent compilation cache for the AOT budget sweep.

    The sweep compiles every (rule x n x dim x mode) grid cell; on a repeat
    ``check --ir`` run (the battery pre-flight, CI, a `--update-budgets`
    after review) each identical XLA compile becomes a disk hit instead of
    seconds of compilation.  The cache dir comes from the
    ``MURMURA_COMPILATION_CACHE_DIR`` env var — the process-level twin of
    ``tpu.compilation_cache_dir``, exported by
    ``factories.apply_compilation_cache`` when a config sets it (so
    ``murmura run`` and the check sweep in one battery share one cache)
    and by ``run_tpu_battery.sh``.  Returns the applied dir, or None.
    """
    cache_dir = os.environ.get("MURMURA_COMPILATION_CACHE_DIR")
    if not cache_dir:
        return None
    import jax

    jax.config.update("jax_compilation_cache_dir", cache_dir)
    # The default minimum compile time gates tiny programs out of the
    # cache; the budget cells are exactly such small programs, so cache
    # them regardless of how fast they compile.
    try:
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
    except AttributeError:  # older jax without the knob
        pass
    return cache_dir


_MEASURE_MEMO: Optional[Dict[str, Dict[str, float]]] = None


def measure_all(force: bool = False) -> Dict[str, Dict[str, float]]:
    """Measured cost cells for every registry aggregator over the grid.
    Memoized per process (shared by the tier-1 gate, the CLI test and the
    battery pre-flight)."""
    global _MEASURE_MEMO
    if _MEASURE_MEMO is not None and not force:
        return dict(_MEASURE_MEMO)
    from murmura_tpu.aggregation import AGGREGATORS
    from murmura_tpu.analysis import ir

    ir._ensure_host_devices()
    apply_persistent_cache()
    out: Dict[str, Dict[str, float]] = {}
    for name in sorted(AGGREGATORS):
        if name not in ir.AGG_CASES:
            continue  # MUR205 already covers the missing case
        if name in ir._PROBE_RULES:
            dims: Tuple[int, ...] = (ir.rule_model_dim(name),)
        else:
            dims = BUDGET_MODEL_DIMS
        for n in BUDGET_NODE_COUNTS:
            for dim in dims:
                for circulant in (False, True):
                    key = budget_key(
                        name, n, dim, "circulant" if circulant else "dense"
                    )
                    try:
                        out[key] = measure_cell(name, n, circulant, dim=dim)
                    except Exception as e:  # noqa: BLE001 — cell error
                        out[key] = {"error": f"{type(e).__name__}: {e}"}
                if name in PALLAS_BUDGET_RULES:
                    # The fused-kernel circulant cell (mode "pallas"), so
                    # the kernel formulation's cost delta is committed
                    # perf history next to the lax cells.
                    key = budget_key(name, n, dim, "pallas")
                    try:
                        out[key] = measure_cell(
                            name, n, True, dim=dim, pallas=True
                        )
                    except Exception as e:  # noqa: BLE001 — cell error
                        out[key] = {"error": f"{type(e).__name__}: {e}"}
    _MEASURE_MEMO = dict(out)
    return out


def _load_doc(path: Optional[Path] = None) -> Dict[str, Any]:
    p = Path(path) if path is not None else BUDGETS_PATH
    if not p.exists():
        return {}
    return json.loads(p.read_text())


def load_budgets(path: Optional[Path] = None) -> Dict[str, Any]:
    return _load_doc(path).get("budgets", {})


def update_budgets(path: Optional[Path] = None) -> Path:
    """Measure the full grid and rewrite BUDGETS.json (sorted keys, stable
    formatting — the diff is the review artifact).

    Refuses to write when any cell failed to compile: committing an
    ``{"error": ...}`` record as a budget would later surface as a
    nonsensical infinite-drift finding instead of the real problem.
    """
    p = Path(path) if path is not None else BUDGETS_PATH
    measured = measure_all(force=True)
    broken = {k: v["error"] for k, v in measured.items() if "error" in v}
    if broken:
        raise RuntimeError(
            "refusing to rewrite budgets: "
            f"{len(broken)} grid cell(s) failed to compile — fix the rules "
            f"first: {json.dumps(broken, indent=2)}"
        )
    doc = {
        "_comment": (
            "Committed XLA cost-model budgets per aggregator grid cell "
            "(murmura check --ir, MUR206; see docs/ANALYSIS.md).  "
            "Regenerate with `python -m murmura_tpu check --update-budgets` "
            "and review the diff as perf history."
        ),
        "tolerance": TOLERANCE,
        "budgets": {k: measured[k] for k in sorted(measured)},
    }
    p.write_text(json.dumps(doc, indent=2, sort_keys=False) + "\n")
    return p


def _rel_delta(measured: float, budget: float) -> float:
    if budget == 0.0:
        return math.inf if measured else 0.0
    return (measured - budget) / budget


def check_budgets(
    path: Optional[Path] = None,
) -> Tuple[List[Finding], List[Dict[str, Any]]]:
    """Compare the measured grid against the committed budgets.

    Returns ``(findings, deltas)``: findings are MUR206 drift/missing/stale
    entries; ``deltas`` carries one record per cell (including in-tolerance
    ones) for ``check --json`` so CI can chart budget drift over time.
    """
    from murmura_tpu.analysis import ir

    budget_path = Path(path) if path is not None else BUDGETS_PATH
    anchor = str(budget_path)
    doc = _load_doc(budget_path)
    budgets = doc.get("budgets", {})
    # The committed file's tolerance governs (it is the reviewable knob the
    # file advertises); the module constant is only the default it is
    # written with.
    tolerance = float(doc.get("tolerance", TOLERANCE))
    measured = measure_all()

    findings: List[Finding] = []
    deltas: List[Dict[str, Any]] = []
    for key in sorted(measured):
        cell = measured[key]
        rule = key.split("/", 1)[0]
        rule_path, rule_line = ir._rule_anchor(rule)
        if "error" in cell:
            findings.append(Finding(
                "MUR206", rule_path, rule_line,
                f"cost sweep for {key} failed to compile: {cell['error']}",
            ))
            continue
        committed = budgets.get(key)
        if committed is None:
            findings.append(Finding(
                "MUR206", anchor, 1,
                f"no committed budget for {key} — run `python -m "
                "murmura_tpu check --update-budgets` and commit the diff",
            ))
            continue
        record = {
            "key": key,
            "flops": cell["flops"],
            "bytes": cell["bytes"],
            "budget_flops": committed.get("flops", 0.0),
            "budget_bytes": committed.get("bytes", 0.0),
        }
        record["flops_delta"] = _rel_delta(
            record["flops"], record["budget_flops"]
        )
        record["bytes_delta"] = _rel_delta(
            record["bytes"], record["budget_bytes"]
        )
        record["within_tolerance"] = (
            abs(record["flops_delta"]) <= tolerance
            and abs(record["bytes_delta"]) <= tolerance
        )
        deltas.append(record)
        for metric in ("flops", "bytes"):
            d = record[f"{metric}_delta"]
            if abs(d) > tolerance:
                findings.append(Finding(
                    "MUR206", rule_path, rule_line,
                    f"{key}: {metric} drifted {d:+.1%} from the committed "
                    f"budget ({record[metric]:.3g} vs "
                    f"{record[f'budget_{metric}']:.3g}, tolerance "
                    f"±{tolerance:.0%}) — if intended, run "
                    "--update-budgets and commit the diff as perf history",
                    data={"key": key, "metric": metric, "delta": d},
                ))
    for key in sorted(set(budgets) - set(measured)):
        findings.append(Finding(
            "MUR206", anchor, 1,
            f"stale budget entry {key} matches no measured grid cell — "
            "remove it (or run --update-budgets)",
        ))
    # Same suppression contract as the other IR findings (docs/ANALYSIS.md):
    # a factory-line `# murmura: ignore[MUR206]` exempts that rule's cells.
    return ir._apply_suppressions(findings), deltas

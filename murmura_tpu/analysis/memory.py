"""Static memory contracts (MUR1500-1503) — part of the default package
check (docs/ANALYSIS.md "Memory contracts", docs/PERFORMANCE.md "Memory
footprint").

ROADMAP items 4 and 5 stand on claims the repo could not verify off-chip:
that a big sharded model *fits* (peak HBM scales as P/shards) and that
pipelined rounds *overlap* (aggregation is dependence-independent of the
round's training).  This family makes both compile-time contract
evidence, the way MUR206 made FLOPs/bytes reviewable perf history:

- **MUR1500 — peak-HBM accounting.**  Every (rule x dense/circulant/
  sparse x plain/int8+EF/stale/pipeline) round-program cell is
  AOT-lowered and ``compile().memory_analysis()`` (temp/argument/output/
  generated, normalized across jax versions by
  :func:`normalize_memory_analysis` — the memory twin of
  ``normalize_cost_analysis``) is gated against the committed
  ``analysis/MEMORY.json`` within tolerance.  A change that silently
  doubles a round program's live footprint is a finding, not a battery
  surprise; ``murmura check --update-memory`` rewrites the file so the
  diff itself is reviewable residency history (the BUDGETS.json
  etiquette).
- **MUR1501 — sharded scaling law.**  For param-sharded cells, the
  per-device peak must shrink ~P/shards across shards in {1, 2, 4}: with
  d12 = peak(1) - peak(2) and d24 = peak(2) - peak(4), the sharded
  [N, P]-class bytes satisfy d12 ~ 2 x d24 (fixed overhead cancels in
  the differences) and the 4-shard peak drops below a declared fraction
  of the unsharded peak.  This statically verifies the PR 15 residency
  claim that previously rested on one committed CPU bench point.
- **MUR1502 — donation completeness by leaf.**  Walk the
  ``input_output_alias`` header of each compiled cell: every carried
  leaf — params plus every ``*_STATE_KEYS`` group in the MUR900
  registry (EF residual, top-k reference, stale cache + ages, pipeline
  buffers, attack/trust state) — must be aliased, and a finding names
  the unaliased leaf and its key group (an undonated [N, P] carry
  doubles peak; MUR204's alias *count* cannot say which).  A leaf jax
  prunes as unused before XLA (a dead carry with no executable buffer)
  is exempt by construction — :func:`entry_param_numbers` maps the
  surviving leaves onto XLA's post-pruning parameter order.  Extra
  donation-only cells (top-k, adaptive attack, DMTT) cover the key
  groups the MUR1500 feature grid does not arm.
- **MUR1503 — overlap-dependence.**  Build the def-use graph of the
  optimized HLO (call-site-qualified across fusions/calls/while bodies,
  collectives included) and prove the pipelined program's buffered-
  aggregation subgraph (``murmura.aggregate`` scope metadata) has no
  dependence path from the round's training subgraph
  (``murmura.train``).  The serialized program is the positive control —
  its train->aggregate path must exist, so a metadata or parser
  regression cannot silently make the contract vacuous — and the prover
  itself is negative-tested each run against a doctored combine whose
  aggregation reads a training output.

Every contract shares ONE memoized AOT compile per grid cell
(:func:`cell_artifacts`): MUR1500 reads its memory stats, MUR1502 its
alias header, MUR1503 its optimized HLO — the new family costs one
compile sweep, not three (the flow-memoization precedent from PR 8, and
the same sharing `budgets.compiled_cell` / `Network.step_memory_analysis`
apply on their grids).  The sweep honors the persistent compilation cache
(``MURMURA_COMPILATION_CACHE_DIR``), so battery re-runs are disk hits.
"""

import contextlib
import json
import math
import re
from collections import deque
from pathlib import Path
from typing import (
    Any, Callable, Dict, List, Optional, Sequence, Tuple,
)

import numpy as np

from murmura_tpu.analysis.lint import Finding

# Registry of check families in this module: name -> callable, scanned by
# analysis/ir.py's check_coverage so an unwired family is a MUR205
# finding (the flow.py/sharded.py twin pattern).
MEMORY_CHECK_FAMILIES: Dict[str, Callable[[], List[Finding]]] = {}


def _family(fn):
    MEMORY_CHECK_FAMILIES[fn.__name__] = fn
    return fn


MEMORY_PATH = Path(__file__).resolve().parent / "MEMORY.json"

_PKG = Path(__file__).resolve().parent.parent
_ROUNDS_PATH = str(_PKG / "core" / "rounds.py")

# The memory grid: every registry rule x exchange topology x feature.
# Topology is program structure at the round level too — "circulant" arms
# the rules' exchange_offsets roll path, "sparse" the [k, N] edge-mask
# engine — and each feature arms one carried-state subsystem, so the grid
# covers every *_STATE_KEYS layout the MUR1502 walk must see.
MEMORY_TOPOS: Tuple[str, ...] = ("dense", "circulant", "sparse")
MEMORY_FEATURES: Tuple[str, ...] = ("plain", "int8_ef", "stale", "pipeline")

# Donation-only extra cells (one rule suffices — the carried-state layout
# is feature structure, not rule structure): cover the *_STATE_KEYS
# groups the MEMORY_FEATURES grid does not arm (top-k reference,
# adaptive-attack state, DMTT trust state).
DONATION_EXTRA_CELLS: Tuple[Tuple[str, str, str], ...] = (
    ("fedavg", "dense", "topk_ef"),
    ("fedavg", "dense", "adaptive"),
    ("fedavg", "dense", "dmtt"),
)

TOLERANCE = 0.10
_N, _S = 8, 16

# MUR1501: the big-dim param-sharded scaling cells and the law's bounds
# (declared in the finding text).  d12 ~ 2 x d24 within _RATIO_TOL and
# peak(4) <= _MAX_RESIDUAL_FRACTION x peak(1) — the [N, P] class must
# dominate the cell for the scaling claim to be non-vacuous.
MUR1501_CELLS: Tuple[Tuple[str, str], ...] = (
    ("fedavg", "circulant"),
    ("median", "sparse"),
)
SCALING_SHARDS: Tuple[int, ...] = (1, 2, 4)
_SCALING_DIM = 8192
_RATIO_TOL = 0.35
_MAX_RESIDUAL_FRACTION = 0.45

# MUR1503: the dependence cells — one per adjacency storage layout; the
# "pipeline"/"plain" feature compiles are shared with MUR1500/MUR1502.
MUR1503_CELLS: Tuple[Tuple[str, str], ...] = (
    ("fedavg", "dense"),
    ("median", "sparse"),
)
_TRAIN_SCOPE = "murmura.train"
_AGG_SCOPE = "murmura.aggregate"


# --------------------------------------------------------------------------
# Cross-version memory_analysis normalization (the cost_analysis twin)
# --------------------------------------------------------------------------

_MEMORY_FIELDS: Tuple[Tuple[str, str], ...] = (
    ("temp_bytes", "temp_size_in_bytes"),
    ("argument_bytes", "argument_size_in_bytes"),
    ("output_bytes", "output_size_in_bytes"),
    ("alias_bytes", "alias_size_in_bytes"),
    ("generated_bytes", "generated_code_size_in_bytes"),
)


def normalize_memory_analysis(mem) -> Dict[str, float]:
    """Flatten the cross-version shapes of ``Compiled.memory_analysis()``
    (a ``CompiledMemoryStats`` object, a dict on some builds, a list on
    multi-device executables, or None) into one flat dict.  Shared with
    ``Network.step_memory_analysis`` and the bench ``memory{}`` blocks.

    ``peak_bytes`` is the derived live-footprint bound XLA does not
    expose directly: arguments + outputs - aliased (donated buffers are
    counted once) + temporaries + generated code.
    """
    if isinstance(mem, (list, tuple)):
        mem = mem[0] if mem else None
    out: Dict[str, float] = {}
    for key, attr in _MEMORY_FIELDS:
        if mem is None:
            val = 0.0
        elif isinstance(mem, dict):
            val = mem.get(key, mem.get(attr, 0.0))
        else:
            val = getattr(mem, attr, 0.0)
        out[key] = float(val or 0.0)
    out["peak_bytes"] = (
        out["argument_bytes"] + out["output_bytes"] - out["alias_bytes"]
        + out["temp_bytes"] + out["generated_bytes"]
    )
    return out


def memory_key(rule: str, topo: str, feature: str) -> str:
    return f"{rule}/{topo}/{feature}"


def _rule_anchor(rule: str) -> Tuple[str, int]:
    from murmura_tpu.analysis.ir import _rule_anchor as anchor

    return anchor(rule)


def _cpu_device():
    import jax

    try:
        return jax.devices("cpu")[0]
    except RuntimeError:
        return None


# --------------------------------------------------------------------------
# The shared grid-cell builder + one memoized AOT compile per cell
# --------------------------------------------------------------------------


def build_memory_cell(rule: str, topo: str, feature: str):
    """(round program, concrete args) for one grid cell — the canonical
    tiny round shape (n=8, s=16, MLP 6->(8,)->3) every executable family
    uses, with the cell's topology and feature armed."""
    import jax
    import jax.numpy as jnp
    from jax.flatten_util import ravel_pytree

    from murmura_tpu.aggregation import build_aggregator
    from murmura_tpu.analysis.ir import (
        AGG_CASES, _canonical_adj, canonical_offsets,
    )
    from murmura_tpu.attacks.gaussian import make_gaussian_attack
    from murmura_tpu.core.rounds import build_round_program
    from murmura_tpu.core.stale import StalenessSpec
    from murmura_tpu.data.base import FederatedArrays
    from murmura_tpu.faults.schedule import FaultSpec
    from murmura_tpu.models import make_mlp
    from murmura_tpu.ops.compress import CompressionSpec

    n, s = _N, _S
    rng = np.random.default_rng(0)
    data = FederatedArrays(
        x=rng.normal(size=(n, s, 6)).astype(np.float32),
        y=rng.integers(0, 3, size=(n, s)).astype(np.int32),
        mask=np.ones((n, s), np.float32),
        num_samples=np.full((n,), s),
        num_classes=3,
    )
    model = make_mlp(
        input_dim=6, hidden_dims=(8,), num_classes=3,
        evidential=(rule == "evidential_trust"),
    )
    flat0, _ = ravel_pytree(model.init(jax.random.PRNGKey(0)))
    case = dict(AGG_CASES.get(rule, {}))
    sparse_offsets: Optional[Tuple[int, ...]] = None
    if topo == "sparse":
        offsets = tuple(canonical_offsets(n))
        case["exchange_offsets"] = list(offsets)
        case["sparse_exchange"] = True
        sparse_offsets = offsets
    elif topo == "circulant":
        case["exchange_offsets"] = list(canonical_offsets(n))
    elif topo != "dense":
        raise ValueError(f"unknown memory topo {topo!r}")
    agg = build_aggregator(
        rule, case, model_dim=int(flat0.size), total_rounds=4
    )
    kw: Dict[str, Any] = dict(
        local_epochs=1, batch_size=8, lr=0.05, total_rounds=4, seed=7,
        attack=make_gaussian_attack(
            n, attack_percentage=0.3, noise_std=5.0, seed=7
        ),
        sparse_offsets=sparse_offsets,
    )
    if feature == "int8_ef":
        kw["compression"] = CompressionSpec(
            "int8", block=32, error_feedback=True
        )
    elif feature == "topk_ef":
        kw["compression"] = CompressionSpec(
            "topk", block=32, topk_ratio=0.1, error_feedback=True
        )
    elif feature == "stale":
        if topo == "sparse":
            base = np.ones((len(sparse_offsets), n), np.float32)
        else:
            base = np.asarray(
                _canonical_adj(n, circulant=(topo == "circulant")),
                np.float32,
            )
        kw["staleness"] = StalenessSpec(
            max_staleness=2, discount=0.5, base_mask=base
        )
        kw["faults"] = FaultSpec()
    elif feature == "pipeline":
        kw["pipeline"] = True
    elif feature == "adaptive":
        from murmura_tpu.attacks.adaptive import make_adaptive_alie_attack

        kw["attack"] = make_adaptive_alie_attack(
            n, attack_percentage=0.3, seed=7
        )
    elif feature == "dmtt":
        from murmura_tpu.dmtt.protocol import DMTTParams

        kw["dmtt"] = DMTTParams()
        kw.pop("attack")
    elif feature != "plain":
        raise ValueError(f"unknown memory feature {feature!r}")
    prog = build_round_program(model, agg, data, **kw)

    if prog.sparse:
        adj = jnp.ones((len(prog.sparse_offsets), n), jnp.float32)
    else:
        adj = jnp.asarray(
            _canonical_adj(n, circulant=(topo == "circulant")), jnp.float32
        )
    args: List[Any] = [
        prog.init_params,
        {k: jnp.asarray(v) for k, v in prog.init_agg_state.items()},
        jax.random.PRNGKey(0),
        adj,
        jnp.zeros((n,), jnp.float32),
        jnp.asarray(0.0, jnp.float32),
        {k: jnp.asarray(v) for k, v in prog.data_arrays.items()},
    ]
    if prog.faulted:
        args.insert(5, jnp.ones((n,), jnp.float32))
    return prog, args


_CELL_MEMO: Dict[Tuple[str, str, str], Tuple[Any, Any, Any]] = {}
_HLO_MEMO: Dict[Tuple[str, str, str], str] = {}


def cell_artifacts(rule: str, topo: str, feature: str):
    """(program, args, compiled executable) for one grid cell — the ONE
    AOT compile (donation armed, exactly as the tpu backend jits the
    step) every MUR1500/1502/1503 consumer shares.  Memoized per process;
    honors the persistent compilation cache."""
    import jax

    from murmura_tpu.analysis.budgets import apply_persistent_cache

    key = (rule, topo, feature)
    if key in _CELL_MEMO:
        return _CELL_MEMO[key]
    apply_persistent_cache()
    prog, args = build_memory_cell(rule, topo, feature)
    dev = _cpu_device()
    cm = (
        jax.default_device(dev) if dev is not None
        else contextlib.nullcontext()
    )
    with cm:
        compiled = (
            jax.jit(prog.train_step, donate_argnums=(0, 1))
            .lower(*args)
            .compile()
        )
    _CELL_MEMO[key] = (prog, args, compiled)
    return _CELL_MEMO[key]


def cell_hlo(rule: str, topo: str, feature: str) -> str:
    """Optimized HLO text of one grid cell (cached; the compile is the
    memoized one)."""
    key = (rule, topo, feature)
    if key not in _HLO_MEMO:
        _HLO_MEMO[key] = cell_artifacts(rule, topo, feature)[2].as_text()
    return _HLO_MEMO[key]


def measure_cell(rule: str, topo: str, feature: str) -> Dict[str, float]:
    """Normalized memory stats of one grid cell's compiled executable."""
    return normalize_memory_analysis(
        cell_artifacts(rule, topo, feature)[2].memory_analysis()
    )


_MEASURE_MEMO: Optional[Dict[str, Dict[str, float]]] = None


def measure_all(force: bool = False) -> Dict[str, Dict[str, float]]:
    """Measured memory cells for every registry rule over the full
    (topo x feature) grid.  Memoized per process (shared by the CLI, the
    battery pre-flight and the test gate)."""
    global _MEASURE_MEMO
    if _MEASURE_MEMO is not None and not force:
        return dict(_MEASURE_MEMO)
    from murmura_tpu.aggregation import AGGREGATORS
    from murmura_tpu.analysis import ir

    out: Dict[str, Dict[str, float]] = {}
    for rule in sorted(AGGREGATORS):
        if rule not in ir.AGG_CASES:
            continue  # MUR205 already covers the missing case
        for topo in MEMORY_TOPOS:
            for feature in MEMORY_FEATURES:
                try:
                    out[memory_key(rule, topo, feature)] = measure_cell(
                        rule, topo, feature
                    )
                except Exception as e:  # noqa: BLE001 — cell error
                    out[memory_key(rule, topo, feature)] = {
                        "error": f"{type(e).__name__}: {e}"
                    }
    _MEASURE_MEMO = dict(out)
    return out


# --------------------------------------------------------------------------
# MUR1500 — committed per-cell memory budgets (the BUDGETS.json etiquette)
# --------------------------------------------------------------------------

# The metrics gated against the committed file.  alias_bytes is implied
# by the others through peak_bytes and would double-report every drift.
_GATED_METRICS: Tuple[str, ...] = (
    "temp_bytes", "argument_bytes", "output_bytes", "generated_bytes",
    "peak_bytes",
)


def _load_doc(path: Optional[Path] = None) -> Dict[str, Any]:
    p = Path(path) if path is not None else MEMORY_PATH
    if not p.exists():
        return {}
    return json.loads(p.read_text())


def load_memory(path: Optional[Path] = None) -> Dict[str, Any]:
    return _load_doc(path).get("budgets", {})


def update_memory(path: Optional[Path] = None) -> Path:
    """Measure the full grid and rewrite MEMORY.json (sorted keys, stable
    formatting — the diff is the review artifact).  Refuses to write when
    any cell failed to compile, the update_budgets contract."""
    p = Path(path) if path is not None else MEMORY_PATH
    measured = measure_all(force=True)
    broken = {k: v["error"] for k, v in measured.items() if "error" in v}
    if broken:
        raise RuntimeError(
            "refusing to rewrite memory budgets: "
            f"{len(broken)} grid cell(s) failed to compile — fix the "
            f"rules first: {json.dumps(broken, indent=2)}"
        )
    doc = {
        "_comment": (
            "Committed XLA memory_analysis budgets per round-program "
            "grid cell (murmura check --memory, MUR1500; see "
            "docs/ANALYSIS.md).  Regenerate with `python -m murmura_tpu "
            "check --update-memory` and review the diff as residency "
            "history."
        ),
        "tolerance": TOLERANCE,
        "budgets": {
            k: {m: measured[k][m] for m in _GATED_METRICS}
            for k in sorted(measured)
        },
    }
    p.write_text(json.dumps(doc, indent=2, sort_keys=False) + "\n")
    return p


def _rel_delta(measured: float, budget: float) -> float:
    if budget == 0.0:
        return math.inf if measured else 0.0
    return (measured - budget) / budget


def memory_budget_findings(
    path: Optional[Path] = None,
) -> Tuple[List[Finding], List[Dict[str, Any]]]:
    """Compare the measured grid against the committed budgets.

    Returns ``(findings, summaries)``: findings are MUR1500
    drift/missing/stale entries; ``summaries`` carries one
    ``{"kind": "memory_summary", ...}`` record per cell (including
    in-tolerance ones) for ``check --json``.
    """
    memory_path = Path(path) if path is not None else MEMORY_PATH
    anchor = str(memory_path)
    doc = _load_doc(memory_path)
    budgets = doc.get("budgets", {})
    # The committed file's tolerance governs (the reviewable knob the
    # file advertises); the module constant is only the written default.
    tolerance = float(doc.get("tolerance", TOLERANCE))
    measured = measure_all()

    findings: List[Finding] = []
    summaries: List[Dict[str, Any]] = []
    for key in sorted(measured):
        cell = measured[key]
        rule = key.split("/", 1)[0]
        rule_path, rule_line = _rule_anchor(rule)
        if "error" in cell:
            findings.append(Finding(
                "MUR1500", rule_path, rule_line,
                f"memory sweep for {key} failed to compile: "
                f"{cell['error']}",
            ))
            continue
        committed = budgets.get(key)
        if committed is None:
            findings.append(Finding(
                "MUR1500", anchor, 1,
                f"no committed memory budget for {key} — run `python -m "
                "murmura_tpu check --update-memory` and commit the diff",
            ))
            continue
        record: Dict[str, Any] = {"kind": "memory_summary", "key": key}
        within = True
        for metric in _GATED_METRICS:
            record[metric] = cell[metric]
            record[f"budget_{metric}"] = committed.get(metric, 0.0)
            d = _rel_delta(record[metric], record[f"budget_{metric}"])
            record[f"{metric}_delta"] = d
            if abs(d) > tolerance:
                within = False
                findings.append(Finding(
                    "MUR1500", rule_path, rule_line,
                    f"{key}: {metric} drifted {d:+.1%} from the "
                    f"committed memory budget ({record[metric]:.3g} vs "
                    f"{record[f'budget_{metric}']:.3g}, tolerance "
                    f"±{tolerance:.0%}) — if intended, run "
                    "--update-memory and commit the diff as residency "
                    "history",
                    data={"key": key, "metric": metric, "delta": d},
                ))
        record["within_tolerance"] = within
        summaries.append(record)
    for key in sorted(set(budgets) - set(measured)):
        findings.append(Finding(
            "MUR1500", anchor, 1,
            f"stale memory budget entry {key} matches no measured grid "
            "cell — remove it (or run --update-memory)",
        ))
    return findings, summaries


@_family
def check_memory_budgets() -> List[Finding]:
    """MUR1500 over the committed MEMORY.json (the full grid compile
    sweep — every other family in this module reuses its executables)."""
    return memory_budget_findings()[0]


def memory_summaries() -> List[Dict[str, Any]]:
    """The per-cell ``memory_summary`` records for ``check --json``
    (measurement is the memoized sweep — no extra compiles)."""
    return memory_budget_findings()[1]


# --------------------------------------------------------------------------
# MUR1501 — per-device peak shrinks ~P/shards on the param mesh
# --------------------------------------------------------------------------


def sharded_cell_peak(rule: str, mode: str, shards: int) -> float:
    """Per-device normalized peak of one big-dim canonical cell compiled
    on a ("seed", "nodes", "param") = (1, 2, shards) mesh with the
    [N, P]-class operands column-sharded."""
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from murmura_tpu.analysis.ir import _ensure_host_devices, build_canonical
    from murmura_tpu.parallel.mesh import param_axis_scope

    _ensure_host_devices(8)
    devices = jax.devices()
    if len(devices) < 2 * shards:
        raise RuntimeError(
            f"needs {2 * shards} devices, have {len(devices)} (set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8)"
        )
    mesh = Mesh(
        np.array(devices[: 2 * shards]).reshape(1, 2, shards),
        ("seed", "nodes", "param"),
    )
    prog = build_canonical(
        rule, _N, circulant=(mode == "circulant"), node_axis_sharded=True,
        sparse=(mode == "sparse"), dim=_SCALING_DIM,
    )
    node_s = NamedSharding(mesh, P("nodes"))
    repl = NamedSharding(mesh, P())
    edge_s = NamedSharding(mesh, P(None, "nodes"))
    flat_s = NamedSharding(mesh, P("nodes", "param"))
    base = prog.arg_shardings(node_s, repl, edge_s)

    def flatten_spec(arg, spec):
        def leaf_spec(a, s):
            if (
                hasattr(a, "ndim") and a.ndim == 2
                and a.shape[-1] == prog.dim
            ):
                return flat_s
            return s
        if isinstance(arg, dict):
            return {
                k: leaf_spec(arg[k], spec[k] if isinstance(spec, dict) else spec)
                for k in arg
            }
        return leaf_spec(arg, spec)

    in_s = tuple(
        flatten_spec(arg, spec) for arg, spec in zip(prog.args, base)
    )

    def scoped(*args):  # murmura: traced
        with param_axis_scope(mesh, prog.dim):
            return prog.fn(*args)

    compiled = jax.jit(scoped, in_shardings=in_s).lower(*prog.args).compile()
    return normalize_memory_analysis(compiled.memory_analysis())["peak_bytes"]


def scaling_cell_findings(rule: str, mode: str) -> List[Finding]:
    """One (rule, mode) MUR1501 cell: peaks at shards {1, 2, 4} must obey
    the P/shards law (exposed per-cell so tests gate one cell per tier-1
    run)."""
    path, line = _rule_anchor(rule)
    peaks = {s: sharded_cell_peak(rule, mode, s) for s in SCALING_SHARDS}
    d12 = peaks[1] - peaks[2]
    d24 = peaks[2] - peaks[4]
    findings: List[Finding] = []
    detail = (
        f"peaks/device {{1: {peaks[1]:.0f}, 2: {peaks[2]:.0f}, "
        f"4: {peaks[4]:.0f}}} bytes"
    )
    if d12 <= 0 or d24 <= 0:
        findings.append(Finding(
            "MUR1501", path, line,
            f"[{rule}/{mode}] per-device peak does not decrease with "
            f"shards ({detail}) — the [N, P]-class buffers are not "
            "actually sharded",
            data={"peaks": peaks},
        ))
        return findings
    # The shards->2x-shards deltas isolate the sharded class (the fixed
    # overhead cancels): d12 = var/2, d24 = var/4, so d12 ~ 2 x d24.
    ratio = d12 / d24
    if abs(ratio - 2.0) > 2.0 * _RATIO_TOL:
        findings.append(Finding(
            "MUR1501", path, line,
            f"[{rule}/{mode}] sharded-class bytes violate the P/shards "
            f"law: (peak1-peak2)/(peak2-peak4) = {ratio:.2f}, expected "
            f"~2 within ±{_RATIO_TOL:.0%} ({detail}) — some [N, P] "
            "buffer stopped scaling with the shard count",
            data={"peaks": peaks, "ratio": ratio},
        ))
    if peaks[4] > _MAX_RESIDUAL_FRACTION * peaks[1]:
        findings.append(Finding(
            "MUR1501", path, line,
            f"[{rule}/{mode}] 4-shard per-device peak retains "
            f"{peaks[4] / peaks[1]:.0%} of the unsharded peak (bound "
            f"{_MAX_RESIDUAL_FRACTION:.0%}; {detail}) — the fixed "
            "overhead dominates, so the cell no longer evidences the "
            "P/shards residency claim",
            data={"peaks": peaks},
        ))
    return findings


@_family
def check_sharded_memory_scaling() -> List[Finding]:
    """MUR1501 over the big-dim scaling cells (3 compiles per cell;
    degrades with a warning when the platform cannot give 8 devices,
    the MUR202 convention)."""
    import warnings

    import jax

    from murmura_tpu.analysis.ir import _ensure_host_devices

    _ensure_host_devices(8)
    if len(jax.devices()) < 8:
        warnings.warn(
            "MUR1501 sharded memory scaling is unobservable on this "
            "platform (needs >= 8 devices; set "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8)",
            stacklevel=2,
        )
        return []
    findings: List[Finding] = []
    for rule, mode in MUR1501_CELLS:
        try:
            findings.extend(scaling_cell_findings(rule, mode))
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            path, line = _rule_anchor(rule)
            findings.append(Finding(
                "MUR1501", path, line,
                f"[{rule}/{mode}] sharded memory-scaling probe crashed: "
                f"{type(e).__name__}: {e}",
            ))
    return findings


# --------------------------------------------------------------------------
# MUR1502 — donation completeness by carried leaf
# --------------------------------------------------------------------------

# `{output_index}: (param_number, {param_index}, may/must-alias)` pairs in
# the HloModule input_output_alias header.
_ALIAS_PAIR_RE = re.compile(
    r"\{[\d,\s]*\}:\s*\((\d+),\s*\{[\d,\s]*\},\s*(?:may|must)-alias\)"
)


def aliased_param_numbers(hlo_text: str) -> frozenset:
    """Entry-parameter numbers aliased to an output in the compiled
    module's ``input_output_alias`` header (XLA's post-pruning
    parameter order)."""
    header = hlo_text.splitlines()[0] if hlo_text else ""
    return frozenset(
        int(m.group(1)) for m in _ALIAS_PAIR_RE.finditer(header)
    )


def entry_param_numbers(compiled, num_flat_args: int) -> Dict[int, int]:
    """Map flat argument index -> XLA entry parameter number.

    jax prunes arguments the traced program never reads before XLA sees
    them (e.g. the buffered adjacency of a circulant pipelined cell,
    whose exchange mask is offset structure, not values), shifting the
    parameter numbering the alias header uses.  A donated leaf absent
    from the map is such a dead carry: it has no executable buffer, so
    there is nothing to alias — exempt from MUR1502 by construction.
    Falls back to the identity map when the private ``_kept_var_idx`` is
    unavailable on a future jax."""
    kept = getattr(
        getattr(compiled, "_executable", None), "_kept_var_idx", None
    )
    if kept is None:
        kept = range(num_flat_args)
    return {flat: rank for rank, flat in enumerate(sorted(kept))}


def _leaf_key_group(
    path_root: int, leaf_path: str,
    groups: Dict[str, Tuple[str, ...]],
) -> str:
    """Classify one donated leaf into its MUR900 key group: ``params``,
    a registered ``*_STATE_KEYS`` group, or the rule's own carried
    state."""
    if path_root == 0:
        return "params"
    for group, keys in groups.items():
        if any(f"'{k}'" in leaf_path for k in keys):
            return group
    return "aggregator-state"


def donation_gap_findings(
    hlo_text: str,
    donated_leaves: Sequence[Tuple[Optional[int], str]],
    rule: str, topo: str, feature: str,
) -> List[Finding]:
    """The pure half of MUR1502 (unit-testable without a compile): given
    the optimized HLO and the ``(entry_param_number, leaf_path)`` list
    of donated carried leaves, a finding per live leaf missing from the
    alias header, naming the leaf and its MUR900 key group.  A leaf with
    param number None was pruned as unused before XLA (a dead carry —
    no buffer exists to alias) and is exempt."""
    from murmura_tpu.durability.snapshot import (
        resolve_reserved_agg_state_keys,
    )

    groups = resolve_reserved_agg_state_keys()
    aliased = aliased_param_numbers(hlo_text)
    path, line = _rule_anchor(rule)
    findings: List[Finding] = []
    for idx, leaf_path in donated_leaves:
        if idx is None or idx in aliased:
            continue
        root = 0 if leaf_path.startswith("[0]") else 1
        group = _leaf_key_group(root, leaf_path, groups)
        findings.append(Finding(
            "MUR1502", path, line,
            f"[{rule}/{topo}/{feature}] donated carried leaf "
            f"{leaf_path} (key group: {group}) is not aliased in the "
            "compiled executable — the undonated carry keeps two copies "
            "of the buffer live and silently raises peak memory",
            data={
                "leaf": leaf_path, "group": group, "param_number": idx,
            },
        ))
    return findings


def donation_cell_findings(
    rule: str, topo: str, feature: str
) -> List[Finding]:
    """One grid cell's MUR1502 walk (the compile is the shared memoized
    one — this reads only its alias header)."""
    import jax.tree_util as jtu

    _, args, compiled = cell_artifacts(rule, topo, feature)
    hlo = cell_hlo(rule, topo, feature)
    num_flat = len(jtu.tree_leaves(tuple(args)))
    param_of = entry_param_numbers(compiled, num_flat)
    flat, _ = jtu.tree_flatten_with_path((args[0], args[1]))
    donated = [
        (param_of.get(i), jtu.keystr(p)) for i, (p, _) in enumerate(flat)
    ]
    return donation_gap_findings(hlo, donated, rule, topo, feature)


@_family
def check_donation_completeness() -> List[Finding]:
    """MUR1502 over the full MUR1500 grid (shared compiles — no extra
    cost) plus the donation-only cells covering the remaining
    ``*_STATE_KEYS`` groups."""
    from murmura_tpu.aggregation import AGGREGATORS
    from murmura_tpu.analysis import ir

    cells: List[Tuple[str, str, str]] = [
        (rule, topo, feature)
        for rule in sorted(AGGREGATORS) if rule in ir.AGG_CASES
        for topo in MEMORY_TOPOS
        for feature in MEMORY_FEATURES
    ]
    cells.extend(DONATION_EXTRA_CELLS)
    findings: List[Finding] = []
    for rule, topo, feature in cells:
        try:
            findings.extend(donation_cell_findings(rule, topo, feature))
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            path, line = _rule_anchor(rule)
            findings.append(Finding(
                "MUR1502", path, line,
                f"[{rule}/{topo}/{feature}] donation-completeness probe "
                f"crashed: {type(e).__name__}: {e}",
            ))
    return findings


# --------------------------------------------------------------------------
# MUR1503 — overlap-dependence: no train -> buffered-aggregation path
# --------------------------------------------------------------------------

_COMP_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s+\(.*\)\s*->.*\{\s*$")
_INSTR_RE = re.compile(r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(.*)$")
_TOKEN_RE = re.compile(r"%?([\w.\-]+)")
_CALLEE_RE = re.compile(r"(?:calls|to_apply|body|condition)=\(?([%\w.\-, ]+)\)?")
_PARAM_OP_RE = re.compile(r"(?:^|\s)parameter\((\d+)\)")
_OPNAME_RE = re.compile(r'op_name="([^"]*)"')

# Backstop against pathological expansion of shared computations (each
# call site expands its callee); real round programs sit around 10^3
# instructions.
_MAX_GRAPH_NODES = 2_000_000


def parse_hlo_computations(hlo_text: str):
    """``{computation: [(instr, rhs, is_root), ...]}`` plus the ENTRY
    computation name, from optimized HLO text."""
    comps: Dict[str, List[Tuple[str, str, bool]]] = {}
    cur: Optional[str] = None
    entry: Optional[str] = None
    for line in hlo_text.splitlines():
        if cur is None:
            m = _COMP_RE.match(line)
            if m:
                cur = m.group(1)
                comps[cur] = []
                if line.startswith("ENTRY"):
                    entry = cur
            continue
        if line.strip() == "}":
            cur = None
            continue
        m = _INSTR_RE.match(line)
        if m:
            comps[cur].append(
                (m.group(1), m.group(2), line.lstrip().startswith("ROOT"))
            )
    if entry is None:
        raise ValueError("no ENTRY computation in HLO text")
    return comps, entry


def build_def_use_graph(hlo_text: str):
    """Call-site-qualified def-use graph of the optimized HLO.

    Returns ``(successors, op_names)``: nodes are
    ``<call path>/<instr>`` strings (each call site expands its callee,
    so a computation shared by two callers cannot conflate their
    dataflow), edges follow def -> use including call/fusion operand ->
    callee parameter (order-matched), callee root -> call site, and the
    while-loop carry.  ``op_names`` maps metadata-bearing nodes to their
    ``op_name`` scope string — the `jax.named_scope` phase brackets the
    round program plants (murmura.train / murmura.aggregate / ...).
    """
    comps, entry = parse_hlo_computations(hlo_text)
    succ: Dict[str, set] = {}
    op_names: Dict[str, str] = {}
    count = [0]

    def add_edge(a: str, b: str):
        succ.setdefault(a, set()).add(b)

    def expand(comp: str, site: str):
        instrs = comps[comp]
        count[0] += len(instrs)
        if count[0] > _MAX_GRAPH_NODES:
            raise RuntimeError(
                f"HLO def-use graph exceeded {_MAX_GRAPH_NODES} nodes"
            )
        defined = {n for n, _, _ in instrs}
        params: Dict[int, str] = {}
        root: Optional[str] = None
        for name, rhs, is_root in instrs:
            node = f"{site}/{name}"
            rhs_core = rhs.split(", metadata=")[0]
            mo = _OPNAME_RE.search(rhs)
            if mo:
                op_names[node] = mo.group(1)
            pm = _PARAM_OP_RE.search(rhs_core)
            if pm:
                params[int(pm.group(1))] = node
            if is_root:
                root = node
            callee_names: List[str] = []
            for c in _CALLEE_RE.findall(rhs_core):
                callee_names.extend(
                    part.strip().lstrip("%") for part in c.split(",")
                )
            operands = []
            for t in _TOKEN_RE.finditer(rhs_core):
                tok = t.group(1)
                if tok in defined and tok != name:
                    operands.append(tok)
            for op in operands:
                add_edge(f"{site}/{op}", node)
            for cn in callee_names:
                if cn not in comps:
                    continue
                sub = f"{site}/{name}>{cn}"
                sub_params, sub_root = expand(cn, sub)
                if len(operands) == len(sub_params):
                    # Call operands map to callee parameters in order.
                    for i, op in enumerate(operands):
                        if i in sub_params:
                            add_edge(f"{site}/{op}", sub_params[i])
                else:
                    # Conservative fallback (e.g. while bodies sharing
                    # one tuple operand): every operand may reach every
                    # parameter.
                    for op in operands:
                        for p in sub_params.values():
                            add_edge(f"{site}/{op}", p)
                if sub_root is not None:
                    add_edge(sub_root, node)
                    if "body=" in rhs_core:
                        # While carry: the body root feeds the next
                        # iteration's parameters.
                        for p in sub_params.values():
                            add_edge(sub_root, p)
        return params, root

    expand(entry, "")
    return succ, op_names


def scope_dependence_path(
    hlo_text: str, src_scope: str, dst_scope: str
) -> Optional[Tuple[int, int, bool]]:
    """(#src nodes, #dst nodes, path exists) for dataflow from any
    instruction whose ``op_name`` metadata contains ``src_scope`` to any
    containing ``dst_scope``.  None when either scope set is empty (the
    metadata did not survive — callers treat that as its own failure)."""
    succ, op_names = build_def_use_graph(hlo_text)
    srcs = [n for n, l in op_names.items() if src_scope in l]
    dsts = {n for n, l in op_names.items() if dst_scope in l}
    if not srcs or not dsts:
        return None
    seen = set(srcs)
    queue = deque(srcs)
    found = False
    while queue:
        n = queue.popleft()
        if n in dsts:
            found = True
            break
        for m in succ.get(n, ()):
            if m not in seen:
                seen.add(m)
                queue.append(m)
    return len(srcs), len(dsts), found


def doctored_combine_hlo() -> str:
    """Optimized HLO of a deliberately broken combine: the aggregation
    scope reads this round's training output.  The MUR1503 prover must
    find its train -> aggregate path — the per-run negative control that
    keeps the def-use machinery honest (and the shape tests reuse)."""
    import jax
    import jax.numpy as jnp

    w = jnp.asarray(np.random.default_rng(0).normal(size=(6, 6)), jnp.float32)

    def doctored(x, buf):  # murmura: traced
        with jax.named_scope(_TRAIN_SCOPE):
            t = jnp.tanh(x @ w)
        with jax.named_scope(_AGG_SCOPE):
            # The bug under test: aggregation consumes the fresh training
            # output t instead of only the buffered carry.
            a = jnp.sum(buf + t, axis=0)
        return t, a

    x = jnp.ones((4, 6), jnp.float32)
    buf = jnp.ones((4, 6), jnp.float32)
    return jax.jit(doctored).lower(x, buf).compile().as_text()


def overlap_cell_findings(rule: str, topo: str) -> List[Finding]:
    """One (rule, topo) MUR1503 cell: the pipelined program's buffered
    aggregation must have NO dependence path from this round's training;
    the serialized program is the positive control (its path MUST
    exist).  Both compiles are the shared MUR1500 grid executables."""
    path, line = _rule_anchor(rule)
    findings: List[Finding] = []

    piped = scope_dependence_path(
        cell_hlo(rule, topo, "pipeline"), _TRAIN_SCOPE, _AGG_SCOPE
    )
    plain = scope_dependence_path(
        cell_hlo(rule, topo, "plain"), _TRAIN_SCOPE, _AGG_SCOPE
    )
    if piped is None or plain is None:
        findings.append(Finding(
            "MUR1503", _ROUNDS_PATH, 1,
            f"[{rule}/{topo}] the murmura.train/murmura.aggregate "
            "named_scope metadata did not survive into the optimized "
            "HLO — the overlap-dependence contract is unobservable and "
            "the phase brackets in core/rounds.py need restoring",
        ))
        return findings
    if not plain[2]:
        findings.append(Finding(
            "MUR1503", _ROUNDS_PATH, 1,
            f"[{rule}/{topo}] positive control failed: the SERIALIZED "
            "program shows no train -> aggregate dependence path "
            f"({plain[0]} train / {plain[1]} aggregate nodes) — the "
            "prover or the scope metadata regressed, so the pipelined "
            "no-path result cannot be trusted",
        ))
    if piped[2]:
        findings.append(Finding(
            "MUR1503", _ROUNDS_PATH, 1,
            f"[{rule}/{topo}] the pipelined program's buffered "
            "aggregation depends on this round's training subgraph "
            f"({piped[0]} train / {piped[1]} aggregate nodes) — XLA "
            "cannot overlap the exchange/aggregation with local "
            "training, which is the entire point of the pipeline flag",
        ))
    return findings


@_family
def check_overlap_dependence() -> List[Finding]:
    """MUR1503 over the dependence cells, plus the doctored-combine
    negative control proving the prover still detects a real
    train -> aggregate path each run."""
    findings: List[Finding] = []
    for rule, topo in MUR1503_CELLS:
        try:
            findings.extend(overlap_cell_findings(rule, topo))
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            path, line = _rule_anchor(rule)
            findings.append(Finding(
                "MUR1503", path, line,
                f"[{rule}/{topo}] overlap-dependence probe crashed: "
                f"{type(e).__name__}: {e}",
            ))
    try:
        doctored = scope_dependence_path(
            doctored_combine_hlo(), _TRAIN_SCOPE, _AGG_SCOPE
        )
        if doctored is None or not doctored[2]:
            findings.append(Finding(
                "MUR1503", str(Path(__file__).resolve()), 1,
                "negative control failed: the dependence prover did not "
                "flag the doctored combine that reads a training output "
                "— MUR1503's clean results are vacuous until the "
                "def-use machinery is fixed",
            ))
    except Exception as e:  # noqa: BLE001 — a crash IS the finding
        findings.append(Finding(
            "MUR1503", str(Path(__file__).resolve()), 1,
            f"doctored-combine negative control crashed: "
            f"{type(e).__name__}: {e}",
        ))
    return findings


# --------------------------------------------------------------------------
# Entry point
# --------------------------------------------------------------------------

_MEMORY_MEMO: Optional[List[Finding]] = None


def check_memory(force: bool = False) -> List[Finding]:
    """Run MUR1500-1503; returns findings (empty = every memory contract
    holds).  Memoized per process — the CLI, the battery pre-flight and
    the test gate share one sweep, and the families themselves share one
    AOT compile per grid cell."""
    global _MEMORY_MEMO
    if _MEMORY_MEMO is not None and not force:
        return list(_MEMORY_MEMO)

    from murmura_tpu.analysis.ir import _apply_suppressions

    findings: List[Finding] = []
    for fam_name, fam in MEMORY_CHECK_FAMILIES.items():
        try:
            findings.extend(fam())
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            findings.append(Finding(
                "MUR1500", str(Path(__file__).resolve()), 1,
                f"memory check family '{fam_name}' crashed: "
                f"{type(e).__name__}: {e}",
            ))
    findings = _apply_suppressions(list(dict.fromkeys(findings)))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    _MEMORY_MEMO = list(findings)
    return findings

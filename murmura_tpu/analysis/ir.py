"""jaxpr/HLO-level IR contracts (MUR200-205) — ``murmura check --ir``.

The AST pass (analysis/lint.py) can only *approximate* what a traced scope
does; the jaxpr and the AOT-compiled executable show what it actually does.
The invariants the north star lives on — no host round-trips inside the
round program, bf16 exchange tensors that stay bf16, masked exchange that
lowers to boundary ppermutes instead of an all-gather, one compiled program
per shape family, donated round buffers that are actually donated — are
only visible at this level, so each is enforced here as a machine-checked
contract over a canonical (n_nodes x model_dim x dtype) grid:

====== ===================== ==============================================
rule   name                  contract
====== ===================== ==============================================
MUR200 ir-host-callback      no ``pure_callback``/``io_callback``/
                             ``jax.debug.*`` callback primitive anywhere in
                             an aggregation jaxpr — each is a device→host
                             round-trip serializing the round hot path.
MUR201 ir-dtype-discipline   dataflow dtype truth behind AST rule MUR006:
                             the aggregated [N, P] tensor and carried state
                             keep their input dtypes (bf16 in → bf16 out);
                             in bf16 programs no matmul takes a full-size
                             f32 operand (f32 belongs in *accumulation* —
                             ``preferred_element_type`` — not operands);
                             float64 appears nowhere.
MUR202 ir-collective-inventory
                             the communication primitives in the lowered
                             SPMD program are a subset of the rule's
                             ``declared_collectives()``
                             (aggregation/base.py); a stray all_gather on a
                             circulant path is a finding, not an ICI
                             surprise.  Undeclared rules are findings.
MUR203 ir-shape-polymorphism jaxprs traced at two different n are
                             structurally identical (same primitive tree) —
                             a rule whose *program* changes with n would
                             recompile per network size beyond the
                             unavoidable shape specialization.
MUR204 ir-donation           buffers the round step marks donated are
                             actually aliased in the compiled executable
                             (params + carried aggregation state) — a lost
                             alias is a silent extra [N, P] HBM copy per
                             round.
MUR205 ir-coverage           every registry aggregator has a canonical IR
                             case (the MUR101-style bijection that keeps
                             MUR200-203 from going vacuous for new rules).
====== ===================== ==============================================

Suppression: IR findings anchor to the rule's factory (``def make_*``)
line, so the ordinary line suppression applies there, e.g.
``def make_fedavg(...):  # murmura: ignore[MUR202]``.
"""

import dataclasses
import inspect
import os
import re
import warnings
from pathlib import Path
from typing import Any, Callable, Dict, Iterator, List, Optional, Sequence, Tuple

from murmura_tpu.analysis.lint import Finding, _suppressed

# --------------------------------------------------------------------------
# Canonical grid
# --------------------------------------------------------------------------

# Two network sizes: MUR200-202 run at the first, MUR203 compares the two.
IR_NODE_COUNTS: Tuple[int, int] = (8, 12)
# Flat parameter dimension for rules that never run the model; probe-based
# rules use the canonical probe model's own dimension instead.
IR_MODEL_DIM = 256
_PROBE_IN = 8
_PROBE_BATCH = 8
_PROBE_CLASSES = 4

# Canonical constructor params per registry rule — the IR twin of the
# contracts pass's _TOPOLOGY_CASES.  MUR205 enforces the bijection with
# aggregation.AGGREGATORS, so a new rule cannot land without an IR case
# (and therefore without MUR200-203 coverage and a cost budget).
AGG_CASES: Dict[str, Dict[str, Any]] = {
    "fedavg": {},
    "krum": {"num_compromised": 1},
    "balance": {},
    "sketchguard": {"sketch_size": 64},
    "ubar": {},
    "evidential_trust": {},
    "median": {},
    "trimmed_mean": {},
    "geometric_median": {"max_iters": 4},
}

# Rules that evaluate the model on probe batches (AggContext.apply_fn).
_PROBE_RULES = frozenset({"ubar", "evidential_trust"})

# HLO op → canonical collective name (aggregation.base.COLLECTIVE_NAMES).
# -start variants cover async collectives on backends that split them.
_HLO_COLLECTIVES = {
    "all-gather": "all_gather",
    "all-gather-start": "all_gather",
    "all-reduce": "all_reduce",
    "all-reduce-start": "all_reduce",
    "collective-permute": "ppermute",
    "collective-permute-start": "ppermute",
    "all-to-all": "all_to_all",
    "reduce-scatter": "reduce_scatter",
}
_COLL_RE = re.compile(
    r"\b(" + "|".join(sorted(_HLO_COLLECTIVES, key=len, reverse=True)) + r")\b"
)

_ALIAS_RE = re.compile(r"\b(?:may|must)-alias\b")


# Registry of round-program-level check families ``check_ir`` runs after
# the per-rule canonical sweep: name -> (callable, crash rule id, crash
# anchor file relative to the package).  Populated by the ``@_ir_family``
# decorator on each ``check_*`` function below; ``check_coverage`` scans
# this module (and analysis/flow.py's twin registry) for any module-level
# ``check_*`` function that is NOT registered — a new MUR family someone
# wrote but never wired into ``check_ir``/tier-1 becomes a finding, not a
# silent gap.
IR_CHECK_FAMILIES: Dict[str, Tuple[Callable, str, str]] = {}

# Entry points / meta-checks that are wired elsewhere by design: check_ir
# IS the runner, check_coverage runs first inside it, and analysis/flow's
# check_flow / analysis/durability's check_durability are their own
# runners composed by run_check_detailed.
_CHECK_ENTRY_POINTS = frozenset(
    {"check_ir", "check_coverage", "check_flow", "check_durability",
     "check_adaptive", "check_staleness", "check_pipeline",
     "check_sharded", "check_composition", "check_memory", "check_serve",
     "check_observe"}
)


def _ir_family(crash_rule: str, crash_anchor: str):
    def deco(fn):
        IR_CHECK_FAMILIES[fn.__name__] = (fn, crash_rule, crash_anchor)
        return fn

    return deco


def _ensure_host_devices(count: int = 8) -> None:
    """Request a multi-device host platform for the MUR202 sharded
    lowerings, when the XLA backend is not initialized yet (the CLI path;
    tests get their devices from conftest.py).  A no-op afterwards —
    backend flags cannot change post-init."""
    from murmura_tpu.parallel.mesh import backend_initialized

    if backend_initialized():
        return
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + f" --xla_force_host_platform_device_count={count}"
        )


# --------------------------------------------------------------------------
# Canonical programs
# --------------------------------------------------------------------------


_PROBE_MODEL_MEMO = None


def _probe_model():
    """(apply_fn, unravel, dim) of the canonical probe model — a tiny MLP
    shared by every probe-based rule's canonical program.  Memoized: the
    init/ravel is constant per process and every build_canonical call for
    a probe rule (plus every rule_model_dim) would otherwise re-run it."""
    global _PROBE_MODEL_MEMO
    if _PROBE_MODEL_MEMO is not None:
        return _PROBE_MODEL_MEMO
    import jax
    from jax.flatten_util import ravel_pytree

    from murmura_tpu.models import make_mlp

    model = make_mlp(
        input_dim=_PROBE_IN, hidden_dims=(16,), num_classes=_PROBE_CLASSES
    )
    flat0, unravel = ravel_pytree(model.init(jax.random.PRNGKey(0)))
    _PROBE_MODEL_MEMO = (model.apply, unravel, int(flat0.size))
    return _PROBE_MODEL_MEMO


def rule_model_dim(name: str) -> int:
    """Canonical flat dimension for one rule (probe rules carry the probe
    model's parameter count; everything else uses IR_MODEL_DIM)."""
    if name in _PROBE_RULES:
        return _probe_model()[2]
    return IR_MODEL_DIM


def canonical_offsets(n: int) -> List[int]:
    """Circulant offsets of the canonical k-regular(4) topology at size n —
    derived from the real generator so the IR pass exercises each
    topology's masked-exchange program, not a hand-typed stand-in."""
    from murmura_tpu.topology.generators import create_topology

    offsets = create_topology("k-regular", num_nodes=n, k=4).circulant_offsets()
    if not offsets:
        raise AssertionError(f"k-regular({n}) stopped being circulant")
    return offsets


def _canonical_adj(n: int, circulant: bool):
    import numpy as np

    from murmura_tpu.topology.generators import create_topology

    if circulant:
        adj = np.zeros((n, n), dtype=np.float32)
        for o in canonical_offsets(n):
            adj[np.arange(n), (np.arange(n) + o) % n] = 1.0
        return adj
    return create_topology("fully", num_nodes=n).mask()


@dataclasses.dataclass
class CanonicalProgram:
    """One traceable aggregation cell of the canonical grid.

    ``fn(*args)`` closes over the AggContext (static under trace) and takes
    only array arguments, so it can be handed directly to ``make_jaxpr``,
    ``eval_shape`` and sharded ``jit``.
    """

    name: str
    n: int
    dim: int
    circulant: bool
    fn: Callable
    args: Tuple
    # (node_sharding, replicated[, edge_sharding]) -> pytree of args; the
    # third parameter carries the sparse [k, N] edge-mask sharding and is
    # optional for legacy two-parameter callables.
    arg_shardings: Callable
    agg: Any = None  # the AggregatorDef (declared_collectives hook)
    # Sparse exchange mode: the adjacency argument is the [k, N] edge mask
    # (topology/sparse.py) instead of the [N, N] matrix.
    sparse: bool = False


def build_canonical(
    name: str,
    n: int,
    dtype: str = "float32",
    circulant: bool = False,
    node_axis_sharded: bool = False,
    params: Optional[Dict[str, Any]] = None,
    dim: Optional[int] = None,
    audit: bool = False,
    sparse: bool = False,
) -> CanonicalProgram:
    """Instantiate one rule over one grid cell.

    Probe batches are explicit *arguments* (not closed-over constants) so
    the MUR202 sharded lowering sees them node-sharded, exactly as the real
    round program's data arrays are.  ``dim`` overrides the flat parameter
    dimension for non-probe rules (the budgets sweep uses two sizes); probe
    rules are pinned to the canonical probe model's own dimension.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from murmura_tpu.aggregation import build_aggregator
    from murmura_tpu.aggregation.base import AggContext

    dt = jnp.dtype(dtype)
    if dim is None or name in _PROBE_RULES:
        dim = rule_model_dim(name)
    case = dict(AGG_CASES.get(name, {}) if params is None else params)
    if sparse:
        circulant = True  # sparse IS the circulant machinery, mask-weighted
        case["exchange_offsets"] = canonical_offsets(n)
        case["sparse_exchange"] = True
    elif circulant:
        case["exchange_offsets"] = canonical_offsets(n)
    agg = build_aggregator(name, case, model_dim=dim, total_rounds=10)

    rng = np.random.default_rng(0)
    own = jnp.asarray(rng.normal(size=(n, dim)) * 0.1, dt)
    bcast = jnp.asarray(rng.normal(size=(n, dim)) * 0.1, dt)
    if sparse:
        # The [k, N] all-active edge mask — the sparse program's adjacency
        # input; nothing [N, N] is built for the cell (MUR600's subject).
        adj = jnp.ones((len(canonical_offsets(n)), n), jnp.float32)
    else:
        adj = jnp.asarray(_canonical_adj(n, circulant))
    ridx = jnp.asarray(0.0, jnp.float32)
    state = {k: jnp.asarray(v) for k, v in agg.init_state(n).items()}

    base_ctx = AggContext(
        total_rounds=10,
        num_classes=_PROBE_CLASSES,
        node_axis_sharded=node_axis_sharded,
        audit=audit,
    )

    if name in _PROBE_RULES:
        apply_fn, unravel, _ = _probe_model()
        probe = {
            "x": jnp.asarray(
                rng.normal(size=(n, _PROBE_BATCH, _PROBE_IN)), jnp.float32
            ),
            "y": jnp.asarray(
                rng.integers(0, _PROBE_CLASSES, size=(n, _PROBE_BATCH)),
                jnp.int32,
            ),
            "mask": jnp.ones((n, _PROBE_BATCH), jnp.float32),
        }

        def fn(own, bcast, adj, ridx, state, probe):  # murmura: traced
            ctx = dataclasses.replace(
                base_ctx,
                apply_fn=apply_fn,
                unravel=unravel,
                probe_x=probe["x"],
                probe_y=probe["y"],
                probe_mask=probe["mask"],
            )
            return agg.aggregate(own, bcast, adj, ridx, state, ctx)

        args = (own, bcast, adj, ridx, state, probe)

        def arg_shardings(node_s, repl, edge_s=None):
            adj_s = edge_s if (sparse and edge_s is not None) else node_s
            return (
                node_s, node_s, adj_s, repl,
                {k: node_s for k in state},
                {k: node_s for k in probe},
            )

    else:

        def fn(own, bcast, adj, ridx, state):  # murmura: traced
            return agg.aggregate(own, bcast, adj, ridx, state, base_ctx)

        args = (own, bcast, adj, ridx, state)

        def arg_shardings(node_s, repl, edge_s=None):
            adj_s = edge_s if (sparse and edge_s is not None) else node_s
            return (node_s, node_s, adj_s, repl, {k: node_s for k in state})

    return CanonicalProgram(
        name=name, n=n, dim=dim, circulant=circulant, fn=fn, args=args,
        arg_shardings=arg_shardings, agg=agg, sparse=sparse,
    )


# --------------------------------------------------------------------------
# jaxpr utilities
# --------------------------------------------------------------------------


def trace_jaxpr(prog: CanonicalProgram):
    """The cell's ClosedJaxpr (tracing only — nothing compiles or runs)."""
    import jax

    return jax.make_jaxpr(prog.fn)(*prog.args)


def iter_eqns(jaxpr) -> Iterator:
    """All equations of a (Closed)Jaxpr, recursing into sub-jaxprs
    (pjit/scan/while/cond branches, custom_* calls)."""
    jx = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in jx.eqns:
        yield eqn
        for sub in eqn.params.values():
            subs = sub if isinstance(sub, (list, tuple)) else [sub]
            for s in subs:
                if hasattr(s, "jaxpr") or hasattr(s, "eqns"):
                    yield from iter_eqns(s)


def jaxpr_signature(jaxpr) -> Tuple[str, ...]:
    """Structural signature: the depth-annotated primitive sequence.  Two
    traces of the same rule at different n must produce identical
    signatures (MUR203) — dimension constants change, the program must
    not."""
    sig: List[str] = []

    def walk(jx, depth: int) -> None:
        jx = getattr(jx, "jaxpr", jx)
        for eqn in jx.eqns:
            sig.append(f"{depth}:{eqn.primitive.name}")
            for sub in eqn.params.values():
                subs = sub if isinstance(sub, (list, tuple)) else [sub]
                for s in subs:
                    if hasattr(s, "jaxpr") or hasattr(s, "eqns"):
                        walk(s, depth + 1)

    walk(jaxpr, 0)
    return tuple(sig)


def collective_inventory(prog: CanonicalProgram, mesh=None) -> Optional[frozenset]:
    """Canonical collective names in the cell's compiled SPMD program.

    Compiles the cell with the node axis sharded over a >= 2 device mesh
    (the tpu-backend layout, parallel/mesh.py) and scans the optimized HLO.
    Returns ``None`` when no multi-device platform is available — the
    inventory is then unobservable and MUR202 degrades with a warning.
    """
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    import numpy as np

    if mesh is None:
        devices = jax.devices()
        usable = [d for d in (2, 4, 8) if d <= len(devices) and prog.n % d == 0]
        if not usable:
            return None
        mesh = Mesh(np.array(devices[: max(usable)]), ("nodes",))
    node_s = NamedSharding(mesh, P("nodes"))
    repl = NamedSharding(mesh, P())
    edge_s = NamedSharding(mesh, P(None, "nodes"))  # sparse [k, N] mask
    try:
        in_s = prog.arg_shardings(node_s, repl, edge_s)
    except TypeError:  # legacy two-parameter callables (tests)
        in_s = prog.arg_shardings(node_s, repl)
    jitted = jax.jit(prog.fn, in_shardings=in_s)
    txt = jitted.lower(*prog.args).compile().as_text()
    return frozenset(_HLO_COLLECTIVES[m] for m in _COLL_RE.findall(txt))


# --------------------------------------------------------------------------
# Checks
# --------------------------------------------------------------------------


def _rule_anchor(name: str) -> Tuple[str, int]:
    """(path, line) of the rule's factory ``def`` — where IR findings point
    and where line suppressions apply."""
    from murmura_tpu.aggregation import AGGREGATORS

    fn = AGGREGATORS.get(name)
    try:
        path = inspect.getsourcefile(fn)
        line = inspect.getsourcelines(fn)[1]
        return str(path), int(line)
    except (OSError, TypeError):
        pkg = Path(__file__).resolve().parent.parent
        return str(pkg / "aggregation" / "__init__.py"), 1


def _mode(circulant: bool) -> str:
    return "circulant" if circulant else "dense"


def _check_callbacks(name: str, prog: CanonicalProgram, jaxpr) -> List[Finding]:
    """MUR200: host callback primitives in the aggregation jaxpr."""
    path, line = _rule_anchor(name)
    found = sorted(
        {
            eqn.primitive.name
            for eqn in iter_eqns(jaxpr)
            if "callback" in eqn.primitive.name
        }
    )
    if not found:
        return []
    return [Finding(
        "MUR200", path, line,
        f"aggregator '{name}' ({_mode(prog.circulant)}) traces host "
        f"callback primitive(s) {found} into the round program — each is a "
        "device->host round-trip serializing the hot path; remove the "
        "jax.debug/pure_callback/io_callback call",
    )]


def _check_dtypes(
    name: str, prog_f32: CanonicalProgram, prog_bf16: CanonicalProgram
) -> List[Finding]:
    """MUR201: dtype discipline through the dataflow (see module table)."""
    import jax
    import jax.numpy as jnp

    path, line = _rule_anchor(name)
    findings: List[Finding] = []
    mode = _mode(prog_f32.circulant)

    for prog, label in ((prog_f32, "float32"), (prog_bf16, "bfloat16")):
        own, state = prog.args[0], prog.args[4]
        out = jax.eval_shape(prog.fn, *prog.args)
        new_flat, new_state, _stats = out
        if new_flat.dtype != own.dtype:
            findings.append(Finding(
                "MUR201", path, line,
                f"aggregator '{name}' ({mode}, {label} params) returns the "
                f"aggregated [N, P] tensor as {new_flat.dtype} — the "
                "exchanged state must keep the resident param dtype "
                "(accumulate in f32, store in the input dtype)",
            ))
        for k, v in new_state.items():
            if k in state and v.dtype != state[k].dtype:
                findings.append(Finding(
                    "MUR201", path, line,
                    f"aggregator '{name}' ({mode}, {label} params) drifts "
                    f"carried state '{k}' from {state[k].dtype} to "
                    f"{v.dtype} — state dtypes must be round-stable",
                ))

    # f64 anywhere + full-size f32 matmul operands in the bf16 program.
    jaxpr = trace_jaxpr(prog_bf16)
    full = prog_bf16.n * prog_bf16.dim
    f64_prims = set()
    for eqn in iter_eqns(jaxpr):
        for var in list(eqn.invars) + list(eqn.outvars):
            aval = getattr(var, "aval", None)
            dt = getattr(aval, "dtype", None)
            if dt == jnp.float64:
                f64_prims.add(eqn.primitive.name)
            if (
                eqn.primitive.name == "dot_general"
                and var in eqn.invars
                and dt == jnp.float32
                and getattr(aval, "size", 0) >= full
            ):
                findings.append(Finding(
                    "MUR201", path, line,
                    f"aggregator '{name}' ({mode}, bfloat16 params) feeds a "
                    f"full-size float32 operand {tuple(aval.shape)} into a "
                    "matmul — promote via preferred_element_type (f32 "
                    "accumulation over bf16 operands), not via f32 "
                    "operands, which double the matmul's HBM reads",
                ))
    if f64_prims:
        findings.append(Finding(
            "MUR201", path, line,
            f"aggregator '{name}' ({mode}) traces float64 values (via "
            f"{sorted(f64_prims)[:4]}) — nothing in the round program may "
            "run double precision",
        ))
    return findings


def _check_structure(
    name: str, prog_a: CanonicalProgram, prog_b: CanonicalProgram
) -> List[Finding]:
    """MUR203: same primitive tree at both canonical network sizes."""
    path, line = _rule_anchor(name)
    sig_a = jaxpr_signature(trace_jaxpr(prog_a))
    sig_b = jaxpr_signature(trace_jaxpr(prog_b))
    if sig_a == sig_b:
        return []
    # First structural divergence, for a legible message.
    i = next(
        (k for k, (x, y) in enumerate(zip(sig_a, sig_b)) if x != y),
        min(len(sig_a), len(sig_b)),
    )
    at_a = sig_a[i] if i < len(sig_a) else "<end>"
    at_b = sig_b[i] if i < len(sig_b) else "<end>"
    return [Finding(
        "MUR203", path, line,
        f"aggregator '{name}' ({_mode(prog_a.circulant)}) traces to "
        f"structurally different programs at n={prog_a.n} "
        f"({len(sig_a)} eqns) vs n={prog_b.n} ({len(sig_b)} eqns); first "
        f"divergence at eqn {i}: {at_a} vs {at_b} — the program must be "
        "identical up to dimension constants or every network size "
        "recompiles a different computation",
    )]


def _check_collectives(name: str, prog: CanonicalProgram) -> List[Finding]:
    """MUR202: lowered collective inventory vs declared_collectives()."""
    path, line = _rule_anchor(name)
    declared = prog.agg.declared_collectives(prog.circulant)
    if declared is None:
        return [Finding(
            "MUR202", path, line,
            f"aggregator '{name}' declares no collective inventory — set "
            "AggregatorDef.collectives (dense/circulant sets drawn from "
            "aggregation.base.COLLECTIVE_NAMES) so stray communication "
            "becomes a check failure instead of an ICI surprise",
        )]
    found = collective_inventory(prog)
    if found is None:
        warnings.warn(
            "murmura check --ir: fewer than 2 devices available — the "
            "MUR202 collective inventory is unobservable on this platform "
            "(run under XLA_FLAGS=--xla_force_host_platform_device_count=8)",
            stacklevel=2,
        )
        return []
    stray = found - declared
    if not stray:
        return []
    return [Finding(
        "MUR202", path, line,
        f"aggregator '{name}' ({_mode(prog.circulant)}) lowers to "
        f"undeclared collective(s) {sorted(stray)} (declared: "
        f"{sorted(declared)}) — either the rule grew unintended "
        "communication or its declared_collectives() contract is stale",
    )]


@_ir_family("MUR204", "core/rounds.py")
def check_donation() -> List[Finding]:
    """MUR204: the round step's donated buffers are actually aliased.

    Compiles two canonical tiny round programs (a stateless rule and one
    with carried aggregation state) exactly as the simulation backend does
    (jit + donate_argnums=(0, 1), core/network.py) and requires one
    input/output alias per donated leaf in the optimized HLO.  A missing
    alias means XLA rejected the donation — params or state silently cost
    an extra full copy per round.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from murmura_tpu.aggregation import build_aggregator
    from murmura_tpu.core.rounds import build_round_program
    from murmura_tpu.data.base import FederatedArrays
    from murmura_tpu.models import make_mlp

    pkg = Path(__file__).resolve().parent.parent
    anchor = str(pkg / "core" / "rounds.py")
    findings: List[Finding] = []

    n, s = 4, 16
    rng = np.random.default_rng(0)
    data = FederatedArrays(
        x=rng.normal(size=(n, s, _PROBE_IN)).astype(np.float32),
        y=rng.integers(0, _PROBE_CLASSES, size=(n, s)).astype(np.int32),
        mask=np.ones((n, s), np.float32),
        num_samples=np.full((n,), s),
        num_classes=_PROBE_CLASSES,
    )
    model = make_mlp(
        input_dim=_PROBE_IN, hidden_dims=(16,), num_classes=_PROBE_CLASSES
    )

    model_dim = _probe_model()[2]
    for rule in ("fedavg", "sketchguard"):
        agg = build_aggregator(
            rule, dict(AGG_CASES[rule]), model_dim=model_dim, total_rounds=5
        )
        prog = build_round_program(
            model, agg, data, total_rounds=5, batch_size=8
        )
        args = (
            prog.init_params,
            {k: jnp.asarray(v) for k, v in prog.init_agg_state.items()},
            jax.random.PRNGKey(0),
            jnp.asarray(_canonical_adj(n, circulant=False)),
            jnp.zeros((n,), jnp.float32),
            jnp.asarray(0.0, jnp.float32),
            {k: jnp.asarray(v) for k, v in prog.data_arrays.items()},
        )
        donated = len(jax.tree_util.tree_leaves(args[0])) + len(
            jax.tree_util.tree_leaves(args[1])
        )
        # Two one-shot analysis compiles, not a hot path — the per-iteration
        # fresh jit cache is the point (each rule gets its own executable).
        step = jax.jit(prog.train_step, donate_argnums=(0, 1))  # murmura: ignore[MUR004]
        txt = step.lower(*args).compile().as_text()
        aliased = len(_ALIAS_RE.findall(txt))
        if aliased < donated:
            findings.append(Finding(
                "MUR204", anchor, 1,
                f"round step with '{rule}': only {aliased} of {donated} "
                "donated buffers (params + carried aggregation state) are "
                "aliased in the compiled executable — the rest pay a full "
                "extra copy per round despite donate_argnums=(0, 1)",
            ))
    return findings


@_ir_family("MUR302", "core/rounds.py")
def check_fault_round() -> List[Finding]:
    """MUR302/MUR303: the fault model is IR-inert.

    The faults subsystem's core promise (docs/ROBUSTNESS.md) is that churn
    composes into the compiled round as *values*, not structure.  Two
    machine-checked halves:

    MUR302 — alive-mask variation causes no recompile: the faulted round
    step compiles once and three rounds with three different alive masks
    re-use that executable (CompileTracker, analysis/sanitizers.py).

    MUR303 — faulted jaxprs stay collective-clean (the MUR202 companion):
    sharding the faulted round over a node mesh must lower to exactly the
    collective inventory of the unfaulted round — the sentinel's
    isfinite/where/rollback plumbing is elementwise over node-local rows
    and may not grow cross-device communication.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from murmura_tpu.aggregation import build_aggregator
    from murmura_tpu.analysis.sanitizers import RecompileError, track_compiles
    from murmura_tpu.core.rounds import build_round_program
    from murmura_tpu.data.base import FederatedArrays
    from murmura_tpu.faults.schedule import FaultSpec
    from murmura_tpu.models import make_mlp

    pkg = Path(__file__).resolve().parent.parent
    anchor = str(pkg / "core" / "rounds.py")
    findings: List[Finding] = []

    n, s = 4, 16
    rng = np.random.default_rng(0)
    data = FederatedArrays(
        x=rng.normal(size=(n, s, _PROBE_IN)).astype(np.float32),
        y=rng.integers(0, _PROBE_CLASSES, size=(n, s)).astype(np.int32),
        mask=np.ones((n, s), np.float32),
        num_samples=np.full((n,), s),
        num_classes=_PROBE_CLASSES,
    )
    model = make_mlp(
        input_dim=_PROBE_IN, hidden_dims=(16,), num_classes=_PROBE_CLASSES
    )
    agg = build_aggregator(
        "fedavg", {}, model_dim=_probe_model()[2], total_rounds=5
    )
    base = build_round_program(model, agg, data, total_rounds=5, batch_size=8)
    faulted = build_round_program(
        model, agg, data, total_rounds=5, batch_size=8, faults=FaultSpec()
    )
    adj = jnp.asarray(_canonical_adj(n, circulant=False))
    d = {k: jnp.asarray(v) for k, v in faulted.data_arrays.items()}

    def args_for(prog, alive, r):
        a = [
            prog.init_params,
            {k: jnp.asarray(v) for k, v in prog.init_agg_state.items()},
            jax.random.PRNGKey(r),
            adj,
            jnp.zeros((n,), jnp.float32),
            jnp.asarray(float(r), jnp.float32),
            d,
        ]
        if prog.faulted:
            a.insert(5, jnp.asarray(alive, jnp.float32))
        return a

    # -- MUR302 ------------------------------------------------------------
    # One-shot analysis compile, not a hot path (the MUR204 pattern).
    step = jax.jit(faulted.train_step)  # murmura: ignore[MUR004]
    masks = [
        np.ones(n, np.float32),
        np.array([1, 0, 1, 1], np.float32),
        np.array([0, 1, 0, 1], np.float32),
    ]
    try:
        with track_compiles() as tracker:
            tracker.begin("warmup")
            jax.block_until_ready(step(*args_for(faulted, masks[0], 0))[0])
            tracker.end(allow=True)
            for r, alive in enumerate(masks[1:], start=1):
                tracker.begin(f"round {r}")
                jax.block_until_ready(step(*args_for(faulted, alive, r))[0])
                tracker.end(allow=False)
    except RecompileError as e:
        findings.append(Finding(
            "MUR302", anchor, 1,
            f"varying the alive mask recompiled the faulted round step "
            f"({e}) — churn must reach the compiled program as input "
            "values, never as structure",
        ))

    # -- MUR303 ------------------------------------------------------------
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from murmura_tpu.parallel.mesh import _shard_round_fn

    devices = jax.devices()
    usable = [c for c in (2, 4) if c <= len(devices) and n % c == 0]
    if not usable:
        warnings.warn(
            "murmura check --ir: fewer than 2 devices available — the "
            "MUR303 faulted collective inventory is unobservable on this "
            "platform (run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8)",
            stacklevel=2,
        )
        return findings
    mesh = Mesh(np.array(devices[: max(usable)]), ("nodes",))
    node_s = NamedSharding(mesh, P("nodes"))

    def inventory(prog):
        sharded = _shard_round_fn(
            prog.train_step, prog, mesh, node_s, donate=False,
            alive_sharding=node_s,
        )
        txt = sharded.lower(*args_for(prog, masks[1], 1)).compile().as_text()
        return frozenset(_HLO_COLLECTIVES[m] for m in _COLL_RE.findall(txt))

    stray = inventory(faulted) - inventory(base)
    if stray:
        findings.append(Finding(
            "MUR303", anchor, 1,
            f"the faulted round step lowers to collective(s) "
            f"{sorted(stray)} absent from the unfaulted round — the fault "
            "plumbing (alive freeze, NaN sentinel, rollback) must stay "
            "node-local and communication-free",
        ))
    return findings


@_ir_family("MUR500", "core/gang.py")
def check_gang_round() -> List[Finding]:
    """MUR500/MUR501: gang batching (core/gang.py) is IR-inert.

    The gang subsystem's core promise (docs/PERFORMANCE.md) is that
    stacking S experiments and vmapping the round program over the seed
    axis changes neither the program's communication nor its compile
    stability.  Two machine-checked halves:

    MUR500 — vmap adds zero collectives, in two sharded lowerings: on the
    node axis the gang program's collective inventory equals the single
    run's (same exchange, batched); on the seed axis ALONE it must be
    collective-FREE — members are independent experiments, so any
    seed-axis collective means a rule accidentally reduced across
    members.

    MUR501 — growing S within a bucket causes zero recompiles: the gang
    pads to power-of-two buckets (core.gang.next_bucket), so a padded
    S=2 gang and a padded S=3 gang present identical shapes and must reuse
    one compiled executable (CompileTracker, analysis/sanitizers.py).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from murmura_tpu.aggregation import build_aggregator
    from murmura_tpu.analysis.sanitizers import RecompileError, track_compiles
    from murmura_tpu.core import gang as gang_mod
    from murmura_tpu.core.rounds import build_round_program
    from murmura_tpu.data.base import FederatedArrays
    from murmura_tpu.models import make_mlp

    pkg = Path(__file__).resolve().parent.parent
    anchor = str(pkg / "core" / "gang.py")
    findings: List[Finding] = []

    n, s = 4, 16
    rng = np.random.default_rng(0)
    data = FederatedArrays(
        x=rng.normal(size=(n, s, _PROBE_IN)).astype(np.float32),
        y=rng.integers(0, _PROBE_CLASSES, size=(n, s)).astype(np.int32),
        mask=np.ones((n, s), np.float32),
        num_samples=np.full((n,), s),
        num_classes=_PROBE_CLASSES,
    )
    model = make_mlp(
        input_dim=_PROBE_IN, hidden_dims=(16,), num_classes=_PROBE_CLASSES
    )
    agg = build_aggregator(
        "fedavg", {}, model_dim=_probe_model()[2], total_rounds=5
    )
    prog = build_round_program(model, agg, data, total_rounds=5, batch_size=8)
    adj = jnp.asarray(_canonical_adj(n, circulant=False))
    d = {k: jnp.asarray(v) for k, v in prog.data_arrays.items()}
    gang_axes = (0, 0, 0, None, 0, None, 0)
    vstep = jax.vmap(prog.train_step, in_axes=gang_axes)

    def gang_args(batch: int, live: int):
        """Stacked gang inputs for ``live`` members padded to ``batch``
        (the core.gang padding: tail slots replicate member 0)."""
        idx = list(range(live)) + [0] * (batch - live)
        stack = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda l: jnp.stack([l] * batch), t
        )
        return (
            stack(prog.init_params),
            stack({k: jnp.asarray(v) for k, v in prog.init_agg_state.items()}),
            jnp.stack([jax.random.PRNGKey(i) for i in idx]),
            adj,
            jnp.zeros((batch, n), jnp.float32),
            jnp.asarray(0.0, jnp.float32),
            stack(d),
        )

    # -- MUR501 ------------------------------------------------------------
    # One-shot analysis compile, not a hot path (the MUR204 pattern).
    # S=3 and S=4 share the power-of-two bucket (next_bucket -> 4), so the
    # padded shapes are identical and the second gang must be a cache hit
    # — the bucket mapping itself is the contract under test (resolved via
    # the gang module so a broken implementation is observable).
    step = jax.jit(vstep)  # murmura: ignore[MUR004]
    try:
        with track_compiles() as tracker:
            tracker.begin("gang warmup (S=3)")
            jax.block_until_ready(
                step(*gang_args(gang_mod.next_bucket(3), 3))[0]
            )
            tracker.end(allow=True)
            tracker.begin("gang grown to S=4 (same bucket)")
            jax.block_until_ready(
                step(*gang_args(gang_mod.next_bucket(4), 4))[0]
            )
            tracker.end(allow=False)
    except RecompileError as e:
        findings.append(Finding(
            "MUR501", anchor, 1,
            f"growing the gang within a bucket recompiled the gang round "
            f"step ({e}) — bucket padding must make member count a pure "
            "input-value change (core.gang.next_bucket)",
        ))

    # -- MUR500 ------------------------------------------------------------
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from murmura_tpu.parallel import mesh as mesh_mod

    devices = jax.devices()
    usable = [c for c in (2, 4) if c <= len(devices) and n % c == 0]
    if not usable:
        warnings.warn(
            "murmura check --ir: fewer than 2 devices available — the "
            "MUR500 gang collective inventory is unobservable on this "
            "platform (run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8)",
            stacklevel=2,
        )
        return findings
    n_shards = max(usable)
    single_mesh = Mesh(np.array(devices[:n_shards]), ("nodes",))
    node_s = NamedSharding(single_mesh, P("nodes"))

    def single_inventory():
        sharded = mesh_mod._shard_round_fn(
            prog.train_step, prog, single_mesh, node_s, donate=False,
            alive_sharding=node_s,
        )
        args = (
            prog.init_params,
            {k: jnp.asarray(v) for k, v in prog.init_agg_state.items()},
            jax.random.PRNGKey(0),
            adj,
            jnp.zeros((n,), jnp.float32),
            jnp.asarray(0.0, jnp.float32),
            d,
        )
        txt = sharded.lower(*args).compile().as_text()
        return frozenset(_HLO_COLLECTIVES[m] for m in _COLL_RE.findall(txt))

    def gang_inventory(batch: int, seed_ax: int, node_ax: int):
        gang_mesh = Mesh(
            np.array(devices[: seed_ax * node_ax]).reshape(seed_ax, node_ax),
            ("seed", "nodes"),
        )
        sharded = mesh_mod.shard_gang_step(
            vstep, prog, batch, gang_mesh, donate=False
        )
        txt = sharded.lower(*gang_args(batch, batch)).compile().as_text()
        return frozenset(_HLO_COLLECTIVES[m] for m in _COLL_RE.findall(txt))

    # Half 1 — node-axis inventory equality: vmapping over the seed axis
    # must not change which collectives the node-sharded exchange lowers
    # to (same kinds as the single run on the same node mesh).
    stray = gang_inventory(2, 1, n_shards) - single_inventory()
    if stray:
        findings.append(Finding(
            "MUR500", anchor, 1,
            f"the vmapped gang round step lowers to collective(s) "
            f"{sorted(stray)} absent from the single-run round — vmap over "
            "the experiment axis must not change the node exchange's "
            "communication",
        ))
    # Half 2 — seed-axis isolation: sharded along the seed axis ALONE
    # (node axis unsharded), the gang program must lower to ZERO
    # collectives.  The experiment axis is embarrassingly parallel by
    # construction; any collective here is cross-member communication — a
    # rule accidentally reducing across gang members.
    cross_member = gang_inventory(2, 2, 1)
    if cross_member:
        findings.append(Finding(
            "MUR500", anchor, 1,
            f"the gang round step sharded along the seed axis alone "
            f"lowers to collective(s) {sorted(cross_member)} — members are "
            "independent experiments and may never communicate; a "
            "collective on the seed axis means something reduced across "
            "gang members",
        ))
    return findings


# Rules whose sparse-exchange programs must be free of any [N, N]-sized
# value (MUR600).  evidential_trust is the documented exception: its
# carried smoothed-trust state keeps the dense [N, N] layout (indexed
# O(k·N) per round) for checkpoint/statistics parity.
SPARSE_DENSE_FREE: Tuple[str, ...] = (
    "fedavg", "krum", "ubar", "median", "trimmed_mean",
    "geometric_median", "balance", "sketchguard",
)
# Rules whose sparse collective inventory must EQUAL the circulant one
# (== ppermute-only) under MUR601 — the north-star set the 4096-node
# exponential run rides on.  The remaining SPARSE_DENSE_FREE rules are
# trace-checked by MUR600 but skip the (expensive) sharded compile.
SPARSE_INVENTORY_RULES: Tuple[str, ...] = ("fedavg", "krum", "ubar", "median")


@_ir_family("MUR600", "core/rounds.py")
def check_sparse_exchange() -> List[Finding]:
    """MUR600/MUR601: the sparse exchange engine is dense-free and
    communication-clean (docs/SCALING.md).

    MUR600 — no O(N²) value anywhere in a sparse-mode program: each
    SPARSE_DENSE_FREE rule's sparse cell, plus a full sparse *round
    program* (build_round_program(sparse_offsets=...) with faults armed),
    is traced and every equation's avals are scanned for a shape carrying
    the node extent on two axes.  A dense adjacency (or distance matrix)
    reappearing in sparse mode is exactly the O(N²) ceiling the engine
    exists to remove — at N=4096 one such f32 value is 64 MB and the Gram
    that usually follows is the real regression.

    MUR601 — sparse collective inventory == circulant inventory per rule:
    the SPARSE_INVENTORY_RULES cells are compiled with the node axis
    sharded (edge mask sharded on its node columns) and must lower to
    exactly the circulant mode's collectives — boundary ppermutes only; a
    stray all_gather means the mask plumbing gathered something global.
    """
    import jax.numpy as jnp
    import numpy as np

    findings: List[Finding] = []
    n = IR_NODE_COUNTS[1]  # 12: avoids colliding with the probe batch (8)

    def dense_offenders(jaxpr, extent: int):
        hits = set()
        for eqn in iter_eqns(jaxpr):
            for var in list(eqn.invars) + list(eqn.outvars):
                aval = getattr(var, "aval", None)
                shape = tuple(getattr(aval, "shape", ()) or ())
                if (
                    sum(1 for d in shape if d == extent) >= 2
                    and int(np.prod(shape or (0,))) >= extent * extent
                ):
                    hits.add((eqn.primitive.name, shape))
        return sorted(hits)

    # -- MUR600, rule cells --------------------------------------------------
    for name in SPARSE_DENSE_FREE:
        path, line = _rule_anchor(name)
        try:
            prog = build_canonical(name, n, "float32", sparse=True)
            hits = dense_offenders(trace_jaxpr(prog), n)
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            findings.append(Finding(
                "MUR600", path, line,
                f"aggregator '{name}' (sparse) crashed the dense-free "
                f"sweep: {type(e).__name__}: {e}",
            ))
            continue
        if hits:
            findings.append(Finding(
                "MUR600", path, line,
                f"aggregator '{name}' (sparse) traces O(N^2) value(s) "
                f"{hits[:4]} — the sparse exchange engine must never "
                "materialize a node-by-node object (use [k, N] edge-mask "
                "forms and rolls)",
            ))

    # -- MUR600, full round program -----------------------------------------
    pkg = Path(__file__).resolve().parent.parent
    anchor = str(pkg / "core" / "rounds.py")
    try:
        import jax

        from murmura_tpu.aggregation import build_aggregator
        from murmura_tpu.core.rounds import build_round_program
        from murmura_tpu.data.base import FederatedArrays
        from murmura_tpu.faults.schedule import FaultSpec
        from murmura_tpu.models import make_mlp

        s = 16
        rng = np.random.default_rng(0)
        data = FederatedArrays(
            x=rng.normal(size=(n, s, _PROBE_IN)).astype(np.float32),
            y=rng.integers(0, _PROBE_CLASSES, size=(n, s)).astype(np.int32),
            mask=np.ones((n, s), np.float32),
            num_samples=np.full((n,), s),
            num_classes=_PROBE_CLASSES,
        )
        model = make_mlp(
            input_dim=_PROBE_IN, hidden_dims=(16,), num_classes=_PROBE_CLASSES
        )
        offsets = tuple(canonical_offsets(n))
        agg = build_aggregator(
            "fedavg",
            {"exchange_offsets": list(offsets), "sparse_exchange": True},
            model_dim=_probe_model()[2], total_rounds=5,
        )
        # Faults armed: the alive/quarantine/scrub edge folds are the part
        # of the round body most tempted to rebuild [N, N].
        prog = build_round_program(
            model, agg, data, total_rounds=5, batch_size=8,
            sparse_offsets=offsets, faults=FaultSpec(),
        )
        args = (
            prog.init_params,
            {k: jnp.asarray(v) for k, v in prog.init_agg_state.items()},
            jax.random.PRNGKey(0),
            jnp.ones((len(offsets), n), jnp.float32),
            jnp.zeros((n,), jnp.float32),
            jnp.ones((n,), jnp.float32),
            jnp.asarray(0.0, jnp.float32),
            {k: jnp.asarray(v) for k, v in prog.data_arrays.items()},
        )
        hits = dense_offenders(jax.make_jaxpr(prog.train_step)(*args), n)
        if hits:
            findings.append(Finding(
                "MUR600", anchor, 1,
                f"the faulted sparse round program traces O(N^2) value(s) "
                f"{hits[:4]} — sparse-mode adjacency folds must stay in "
                "[k, N] edge-mask space (rolls of node flags)",
            ))
    except Exception as e:  # noqa: BLE001 — a crash IS the finding
        findings.append(Finding(
            "MUR600", anchor, 1,
            f"the sparse round-program dense-free sweep crashed: "
            f"{type(e).__name__}: {e}",
        ))

    # -- MUR601 --------------------------------------------------------------
    # The flagship rules compare sparse vs circulant inventories; every
    # swept rule is ALSO held to its declared_collectives("sparse") set,
    # which is how sketchguard's tighter sparse declaration ({"ppermute"}
    # — its sparse filter runs in circulant sketch space while its
    # circulant mode still gathers the dense sketches) stays enforced.
    for name in SPARSE_INVENTORY_RULES + ("sketchguard",):
        path, line = _rule_anchor(name)
        try:
            sparse_prog = build_canonical(
                name, n, "float32", sparse=True, node_axis_sharded=True
            )
            inv_sparse = collective_inventory(sparse_prog)
            if name in SPARSE_INVENTORY_RULES:
                circ_prog = build_canonical(
                    name, n, "float32", circulant=True,
                    node_axis_sharded=True,
                )
                inv_circ = collective_inventory(circ_prog)
            else:
                inv_circ = inv_sparse
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            findings.append(Finding(
                "MUR601", path, line,
                f"aggregator '{name}' crashed the sparse collective "
                f"inventory sweep: {type(e).__name__}: {e}",
            ))
            continue
        if inv_sparse is None or inv_circ is None:
            warnings.warn(
                "murmura check --ir: fewer than 2 devices available — the "
                "MUR601 sparse collective inventory is unobservable on "
                "this platform (run under "
                "XLA_FLAGS=--xla_force_host_platform_device_count=8)",
                stacklevel=2,
            )
            break
        if name in SPARSE_INVENTORY_RULES and inv_sparse != inv_circ:
            findings.append(Finding(
                "MUR601", path, line,
                f"aggregator '{name}' sparse mode lowers to "
                f"{sorted(inv_sparse)} but its circulant mode lowers to "
                f"{sorted(inv_circ)} — the [k, N] edge-mask weighting must "
                "not change the exchange's communication (rolls stay "
                "boundary ppermutes; nothing gathers)",
            ))
        declared = sparse_prog.agg.declared_collectives("sparse")
        stray = inv_sparse - (declared or frozenset())
        if stray:
            findings.append(Finding(
                "MUR601", path, line,
                f"aggregator '{name}' sparse mode lowers to undeclared "
                f"collective(s) {sorted(stray)} (declared sparse set: "
                f"{sorted(declared or ())}) — either the sparse path grew "
                "unintended communication or its collectives declaration "
                "is stale",
            ))
    return findings


# Rules whose circulant/sparse exchange accepts the int8 compressed
# payload (AggregatorDef.quantized_exchange — they touch the broadcast
# only through the shared roll kernels).  MUR700 runs over the flagship
# subset; the remaining quantized rules share the same kernels, so the
# payload contract transfers.
QUANTIZED_EXCHANGE_RULES: Tuple[str, ...] = (
    "fedavg", "krum", "balance", "median", "trimmed_mean",
    "geometric_median",
)
MUR700_RULES: Tuple[str, ...] = ("fedavg", "krum", "median")
_COMPRESS_BLOCK = 64

# Only lines whose OPCODE is a collective (`= <shape> <op>(...)`), not
# every line that references a collective's result name as a fusion
# operand; the operand shapes inside the parens are what crosses the wire.
_COLL_OP_LINE_RE = re.compile(
    r"^.*=\s*\S+\s+(?:collective-permute|all-gather|all-to-all|"
    r"reduce-scatter)(?:-start)?\((.*)$",
    re.MULTILINE,
)
_FLOAT_SHAPE_RE = re.compile(r"\b(f32|bf16|f64)\[([0-9,]*)\]")


def float_exchange_operands(hlo_text: str, width: int):
    """(offending floats, collective operand strings) of an HLO module:
    floating shapes of exchanged width (any dim >= ``width`` — boundary
    roll slices are [o, P]) appearing in collective ops.  The MUR700 scan,
    factored out so its negatives are unit-testable
    (tests/test_analysis_ir.py)."""
    coll_lines = _COLL_OP_LINE_RE.findall(hlo_text)
    offending = sorted({
        m.group(0)
        for ln in coll_lines
        for m in _FLOAT_SHAPE_RE.finditer(ln)
        if any(
            d >= width for d in (int(x) for x in m.group(2).split(",") if x)
        )
    })
    return offending, coll_lines


@_ir_family("MUR700", "core/rounds.py")
def check_compressed_exchange() -> List[Finding]:
    """MUR700/701/702: the compressed exchange moves compressed bytes and
    is IR-inert (docs/PERFORMANCE.md; ops/compress.py).

    MUR700 — the compressed payload is what crosses the collective: each
    MUR700_RULES cell is compiled with the node axis sharded and an int8
    payload standing in for the broadcast; no collective in the lowered
    SPMD program may carry a floating operand of exchanged width (a dim >=
    the flat model dimension — boundary roll slices are [o, P]), and at
    least one int8 collective must be present (the positive control that
    keeps the scan non-vacuous).  Runs in circulant and sparse modes; the
    dense path is documented as values-compressed only (the gathered
    matmul operand is the dequantized tensor).

    MUR701 — compression is recompile-free across rounds: an int8 +
    error-feedback round program compiles once and rounds with different
    adjacency values reuse the executable (CompileTracker) — scales,
    residuals and reference estimates are traced values, never structure.

    MUR702 — the error-feedback state is donation-clean: the compressed
    round step's donated buffers (params + agg_state including the [N, P]
    residual) are all aliased in the compiled executable; a lost alias
    would cost a full extra [N, P] copy per round.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from murmura_tpu.aggregation import build_aggregator
    from murmura_tpu.aggregation.base import AggContext
    from murmura_tpu.analysis.sanitizers import RecompileError, track_compiles
    from murmura_tpu.core.rounds import build_round_program
    from murmura_tpu.data.base import FederatedArrays
    from murmura_tpu.models import make_mlp
    from murmura_tpu.ops.compress import (
        CompressionSpec,
        Int8Blocks,
        quantize_int8,
    )

    findings: List[Finding] = []
    n = IR_NODE_COUNTS[1]  # 12: distinct from the probe batch and P dims
    dim = IR_MODEL_DIM

    # -- MUR700 ------------------------------------------------------------
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    devices = jax.devices()
    usable = [c for c in (2, 4) if c <= len(devices) and n % c == 0]
    if not usable:
        warnings.warn(
            "murmura check --ir: fewer than 2 devices available — the "
            "MUR700 compressed-payload inventory is unobservable on this "
            "platform (run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8)",
            stacklevel=2,
        )
    else:
        mesh = Mesh(np.array(devices[: max(usable)]), ("nodes",))
        node_s = NamedSharding(mesh, P("nodes"))
        repl = NamedSharding(mesh, P())
        edge_s = NamedSharding(mesh, P(None, "nodes"))
        for name in MUR700_RULES:
            path, line = _rule_anchor(name)
            for mode in ("circulant", "sparse"):
                try:
                    rng = np.random.default_rng(0)
                    case = dict(AGG_CASES[name])
                    offsets = canonical_offsets(n)
                    case["exchange_offsets"] = offsets
                    if mode == "sparse":
                        case["sparse_exchange"] = True
                    agg = build_aggregator(
                        name, case, model_dim=dim, total_rounds=10
                    )
                    own = jnp.asarray(
                        rng.normal(size=(n, dim)) * 0.1, jnp.float32
                    )
                    bcast = jnp.asarray(
                        rng.normal(size=(n, dim)) * 0.1, jnp.float32
                    )
                    qb = quantize_int8(bcast, _COMPRESS_BLOCK)
                    if mode == "sparse":
                        adj = jnp.ones((len(offsets), n), jnp.float32)
                        adj_s = edge_s
                    else:
                        adj = jnp.asarray(_canonical_adj(n, circulant=True))
                        adj_s = node_s
                    state = {
                        k: jnp.asarray(v)
                        for k, v in agg.init_state(n).items()
                    }
                    ctx = AggContext(
                        total_rounds=10, num_classes=_PROBE_CLASSES,
                        node_axis_sharded=True,
                    )

                    def fn(own, q, scale, adj, ridx, state):  # murmura: traced
                        qv = Int8Blocks(
                            q, scale, _COMPRESS_BLOCK, dim, jnp.float32
                        )
                        return agg.aggregate(own, qv, adj, ridx, state, ctx)

                    args = (
                        own, qb.q, qb.scale, adj,
                        jnp.asarray(0.0, jnp.float32), state,
                    )
                    in_s = (
                        node_s, node_s, node_s, adj_s, repl,
                        {k: node_s for k in state},
                    )
                    # One-shot analysis compile per cell, not a hot path
                    # (the MUR204 pattern).
                    jitted = jax.jit(fn, in_shardings=in_s)  # murmura: ignore[MUR004]
                    txt = jitted.lower(*args).compile().as_text()
                except Exception as e:  # noqa: BLE001 — a crash IS the finding
                    findings.append(Finding(
                        "MUR700", path, line,
                        f"aggregator '{name}' ({mode}) crashed the "
                        f"compressed-payload sweep: {type(e).__name__}: {e}",
                    ))
                    continue
                offending, coll_lines = float_exchange_operands(txt, dim)
                if offending:
                    findings.append(Finding(
                        "MUR700", path, line,
                        f"aggregator '{name}' ({mode}, compressed) moves "
                        f"full-width float operand(s) {offending[:4]} "
                        "through a collective — the compressed int8 "
                        "payload (plus per-block scales) is what must "
                        "cross; dequantize after the roll, not before",
                    ))
                if coll_lines and not any("s8[" in ln for ln in coll_lines):
                    findings.append(Finding(
                        "MUR700", path, line,
                        f"aggregator '{name}' ({mode}, compressed) lowers "
                        "to no int8 collective at all — the payload scan "
                        "is vacuous; the exchange no longer moves the "
                        "compressed representation",
                    ))

    # -- MUR701 / MUR702 over a full compressed round program ---------------
    pkg = Path(__file__).resolve().parent.parent
    anchor = str(pkg / "core" / "rounds.py")
    n4, s = 4, 16
    rng = np.random.default_rng(0)
    data = FederatedArrays(
        x=rng.normal(size=(n4, s, _PROBE_IN)).astype(np.float32),
        y=rng.integers(0, _PROBE_CLASSES, size=(n4, s)).astype(np.int32),
        mask=np.ones((n4, s), np.float32),
        num_samples=np.full((n4,), s),
        num_classes=_PROBE_CLASSES,
    )
    model = make_mlp(
        input_dim=_PROBE_IN, hidden_dims=(16,), num_classes=_PROBE_CLASSES
    )
    agg = build_aggregator(
        "fedavg", {}, model_dim=_probe_model()[2], total_rounds=5
    )
    spec = CompressionSpec("int8", block=_COMPRESS_BLOCK, error_feedback=True)
    prog = build_round_program(
        model, agg, data, total_rounds=5, batch_size=8, compression=spec
    )
    d = {k: jnp.asarray(v) for k, v in prog.data_arrays.items()}

    def args_for(adj_seed: int, r: int):
        rng_a = np.random.default_rng(adj_seed)
        adj = (rng_a.uniform(size=(n4, n4)) < 0.8).astype(np.float32)
        np.fill_diagonal(adj, 0.0)
        return (
            prog.init_params,
            {k: jnp.asarray(v) for k, v in prog.init_agg_state.items()},
            jax.random.PRNGKey(r),
            jnp.asarray(adj),
            jnp.zeros((n4,), jnp.float32),
            jnp.asarray(float(r), jnp.float32),
            d,
        )

    # One-shot analysis compile, not a hot path (the MUR204 pattern).
    step = jax.jit(prog.train_step)  # murmura: ignore[MUR004]
    try:
        with track_compiles() as tracker:
            tracker.begin("warmup")
            jax.block_until_ready(step(*args_for(0, 0))[0])
            tracker.end(allow=True)
            for r in (1, 2):
                tracker.begin(f"round {r}")
                jax.block_until_ready(step(*args_for(r, r))[0])
                tracker.end(allow=False)
    except RecompileError as e:
        findings.append(Finding(
            "MUR701", anchor, 1,
            f"varying round inputs recompiled the compressed round step "
            f"({e}) — scales, residuals and reference estimates must reach "
            "the program as traced values, never as structure",
        ))

    args = args_for(0, 0)
    donated = len(jax.tree_util.tree_leaves(args[0])) + len(
        jax.tree_util.tree_leaves(args[1])
    )
    # One-shot analysis compile, not a hot path (the MUR204 pattern).
    dstep = jax.jit(prog.train_step, donate_argnums=(0, 1))  # murmura: ignore[MUR004]
    txt = dstep.lower(*args).compile().as_text()
    aliased = len(_ALIAS_RE.findall(txt))
    if aliased < donated:
        findings.append(Finding(
            "MUR702", anchor, 1,
            f"compressed round step: only {aliased} of {donated} donated "
            "buffers (params + agg_state including the error-feedback "
            "residual) are aliased in the compiled executable — the rest "
            "pay a full extra copy per round despite donate_argnums=(0, 1)",
        ))
    return findings


# Rules that surface per-node audit taps under telemetry.audit_taps
# (tap_* stats).  MUR400/402 run over exactly this set; a new tapped rule
# joins the contract by being added here.
TAPPED_RULES: Tuple[str, ...] = ("krum", "balance", "ubar", "evidential_trust")


@_ir_family("MUR400", "core/rounds.py")
def check_telemetry_taps() -> List[Finding]:
    """MUR400/MUR402: the audit taps are IR-inert (docs/OBSERVABILITY.md).

    The telemetry subsystem's core promise is that observing a round does
    not change it.  Two machine-checked halves:

    MUR400 — taps add zero collectives: each tapped rule's sharded-lowered
    collective inventory with ``ctx.audit`` on equals the untapped
    inventory (circulant taps are roll-assembled so they stay
    ppermute-only; dense taps are axis reductions inside the already-
    declared all_reduce).

    MUR402 — tap recording toggles cause zero recompiles: a tapped round
    program compiles once, and rounds that fetch the tap metrics
    interleaved with rounds that ignore them reuse that executable
    (CompileTracker, analysis/sanitizers.py) — recording is a host-side
    decision, never a program change.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from murmura_tpu.aggregation import build_aggregator
    from murmura_tpu.analysis.sanitizers import RecompileError, track_compiles
    from murmura_tpu.core.rounds import build_round_program
    from murmura_tpu.data.base import FederatedArrays
    from murmura_tpu.models import make_mlp

    findings: List[Finding] = []
    n_a = IR_NODE_COUNTS[0]

    # -- MUR400 ------------------------------------------------------------
    inventory_observable = True
    for name in TAPPED_RULES:
        for circulant in (False, True):
            path, line = _rule_anchor(name)
            try:
                base = build_canonical(
                    name, n_a, "float32", circulant, node_axis_sharded=True
                )
                tapped = build_canonical(
                    name, n_a, "float32", circulant, node_axis_sharded=True,
                    audit=True,
                )
                inv_base = collective_inventory(base)
                if inv_base is None:
                    inventory_observable = False
                    break
                inv_tap = collective_inventory(tapped)
            except Exception as e:  # noqa: BLE001 — a crash IS the finding
                findings.append(Finding(
                    "MUR400", path, line,
                    f"aggregator '{name}' ({_mode(circulant)}) crashed the "
                    f"tapped inventory sweep: {type(e).__name__}: {e}",
                ))
                continue
            stray = (inv_tap or frozenset()) - inv_base
            if stray:
                findings.append(Finding(
                    "MUR400", path, line,
                    f"aggregator '{name}' ({_mode(circulant)}) audit taps "
                    f"lower to collective(s) {sorted(stray)} absent from "
                    "the untapped program — observing a round must not add "
                    "communication (assemble circulant taps from rolls, "
                    "dense taps from declared-inventory reductions)",
                ))
        if not inventory_observable:
            break
    if not inventory_observable:
        warnings.warn(
            "murmura check --ir: fewer than 2 devices available — the "
            "MUR400 tapped collective inventory is unobservable on this "
            "platform (run under "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8)",
            stacklevel=2,
        )

    # -- MUR402 ------------------------------------------------------------
    pkg = Path(__file__).resolve().parent.parent
    anchor = str(pkg / "core" / "rounds.py")
    n, s = 4, 16
    rng = np.random.default_rng(0)
    data = FederatedArrays(
        x=rng.normal(size=(n, s, _PROBE_IN)).astype(np.float32),
        y=rng.integers(0, _PROBE_CLASSES, size=(n, s)).astype(np.int32),
        mask=np.ones((n, s), np.float32),
        num_samples=np.full((n,), s),
        num_classes=_PROBE_CLASSES,
    )
    model = make_mlp(
        input_dim=_PROBE_IN, hidden_dims=(16,), num_classes=_PROBE_CLASSES
    )
    agg = build_aggregator(
        "krum", dict(AGG_CASES["krum"]), model_dim=_probe_model()[2],
        total_rounds=5,
    )
    tapped_prog = build_round_program(
        model, agg, data, total_rounds=5, batch_size=8, audit_taps=True
    )
    adj = jnp.asarray(_canonical_adj(n, circulant=False))
    d = {k: jnp.asarray(v) for k, v in tapped_prog.data_arrays.items()}
    # One-shot analysis compile, not a hot path (the MUR204 pattern).
    step = jax.jit(tapped_prog.train_step)  # murmura: ignore[MUR004]

    def run_round(r: int, fetch_taps: bool):
        out = step(
            tapped_prog.init_params,
            {k: jnp.asarray(v) for k, v in tapped_prog.init_agg_state.items()},
            jax.random.PRNGKey(r),
            adj,
            jnp.zeros((n,), jnp.float32),
            jnp.asarray(float(r), jnp.float32),
            d,
        )
        params, _state, metrics = out
        if fetch_taps:
            # A recording round: the host fetches the per-node tap arrays.
            jax.device_get(
                {k: v for k, v in metrics.items() if k.startswith("agg_tap_")}
            )
        jax.block_until_ready(jax.tree_util.tree_leaves(params)[0])

    try:
        with track_compiles() as tracker:
            tracker.begin("warmup")
            run_round(0, fetch_taps=True)
            tracker.end(allow=True)
            for r, fetch in ((1, False), (2, True), (3, False)):
                tracker.begin(f"round {r} (record={fetch})")
                run_round(r, fetch_taps=fetch)
                tracker.end(allow=False)
    except RecompileError as e:
        findings.append(Finding(
            "MUR402", anchor, 1,
            f"toggling audit-tap recording across rounds recompiled the "
            f"tapped round step ({e}) — tap recording must be a host-side "
            "decision over a single compiled executable, never a program "
            "change",
        ))
    return findings


def _unwired_family_findings(module, registry: Dict[str, Any]) -> List[Finding]:
    """Module-level ``check_*`` callables that are neither in the module's
    check-family registry nor a known entry point — a new MUR family that
    would otherwise silently never run in ``check``/tier-1."""
    findings: List[Finding] = []
    mod_path = str(Path(module.__file__).resolve())
    for attr, obj in sorted(vars(module).items()):
        if not attr.startswith("check_") or not callable(obj):
            continue
        if attr in registry or attr in _CHECK_ENTRY_POINTS:
            continue
        findings.append(Finding(
            "MUR205", mod_path, 1,
            f"{module.__name__.rsplit('.', 1)[-1]}.{attr} is a check "
            "family that is not registered in its module's check-family "
            "registry — it will never run in `check`/tier-1; register it "
            "(@_ir_family in analysis/ir.py, @_family in analysis/flow.py) "
            "or rename it",
        ))
    return findings


def check_coverage() -> List[Finding]:
    """MUR205: registry <-> canonical-case bijection (the MUR101
    counterpart that keeps every other MUR2xx rule non-vacuous), plus the
    check-family wiring audit: every module-level ``check_*`` function in
    analysis/ir.py, analysis/flow.py and analysis/durability.py must be
    enumerated by its module's check-family registry (IR_CHECK_FAMILIES /
    FLOW_CHECK_FAMILIES / DURABILITY_CHECK_FAMILIES) — enumeration comes
    from the registry, never a hand-maintained call list, so a future MUR
    family that is written but not wired into
    ``check_ir``/``check_flow``/``check_durability`` is a finding, not a
    silent gap."""
    import sys

    from murmura_tpu.aggregation import AGGREGATORS

    pkg = Path(__file__).resolve().parent.parent
    agg_path = str(pkg / "aggregation" / "__init__.py")
    here = str(Path(__file__).resolve())
    findings: List[Finding] = []
    for name in sorted(set(AGGREGATORS) - set(AGG_CASES)):
        findings.append(Finding(
            "MUR205", agg_path, 1,
            f"aggregation rule '{name}' has no AGG_CASES entry "
            "(analysis/ir.py) — the IR contracts (MUR200-203) and cost "
            "budgets never run for it; add a canonical case",
        ))
    for name in sorted(set(AGG_CASES) - set(AGGREGATORS)):
        findings.append(Finding(
            "MUR205", here, 1,
            f"AGG_CASES entry '{name}' names no registered aggregation "
            "rule — remove the stale canonical case",
        ))
    from murmura_tpu.analysis import adaptive as adaptive_mod
    from murmura_tpu.analysis import durability as durability_mod
    from murmura_tpu.analysis import flow as flow_mod

    findings.extend(
        _unwired_family_findings(sys.modules[__name__], IR_CHECK_FAMILIES)
    )
    findings.extend(
        _unwired_family_findings(flow_mod, flow_mod.FLOW_CHECK_FAMILIES)
    )
    findings.extend(
        _unwired_family_findings(
            durability_mod, durability_mod.DURABILITY_CHECK_FAMILIES
        )
    )
    findings.extend(
        _unwired_family_findings(
            adaptive_mod, adaptive_mod.ADAPTIVE_CHECK_FAMILIES
        )
    )
    from murmura_tpu.analysis import staleness as staleness_mod

    findings.extend(
        _unwired_family_findings(
            staleness_mod, staleness_mod.STALE_CHECK_FAMILIES
        )
    )
    from murmura_tpu.analysis import pipeline as pipeline_mod

    findings.extend(
        _unwired_family_findings(
            pipeline_mod, pipeline_mod.PIPELINE_CHECK_FAMILIES
        )
    )
    from murmura_tpu.analysis import sharded as sharded_mod

    findings.extend(
        _unwired_family_findings(
            sharded_mod, sharded_mod.SHARDED_CHECK_FAMILIES
        )
    )
    from murmura_tpu.analysis import composition as composition_mod

    findings.extend(
        _unwired_family_findings(
            composition_mod, composition_mod.COMPOSE_CHECK_FAMILIES
        )
    )
    from murmura_tpu.analysis import memory as memory_mod

    findings.extend(
        _unwired_family_findings(
            memory_mod, memory_mod.MEMORY_CHECK_FAMILIES
        )
    )
    from murmura_tpu.analysis import serve as serve_check_mod

    findings.extend(
        _unwired_family_findings(
            serve_check_mod, serve_check_mod.SERVE_CHECK_FAMILIES
        )
    )
    from murmura_tpu.analysis import observe as observe_mod

    findings.extend(
        _unwired_family_findings(
            observe_mod, observe_mod.OBSERVE_CHECK_FAMILIES
        )
    )
    return findings


# --------------------------------------------------------------------------
# Entry point
# --------------------------------------------------------------------------

_IR_MEMO: Optional[List[Finding]] = None


def _apply_suppressions(findings: List[Finding]) -> List[Finding]:
    """Line suppressions at each finding's anchor (the factory def line)."""
    out: List[Finding] = []
    by_path: Dict[str, List[Finding]] = {}
    for f in findings:
        by_path.setdefault(f.path, []).append(f)
    for path, fs in by_path.items():
        try:
            lines = Path(path).read_text().splitlines()
        except OSError:
            out.extend(fs)
            continue
        out.extend(_suppressed(fs, lines))
    return out


def check_ir(force: bool = False) -> List[Finding]:
    """Run MUR200-205 over the canonical grid; returns findings (empty =
    every IR contract holds).  Memoized per process — the tier-1 gate, the
    CLI test and the battery pre-flight share one sweep.

    Cost budgets (MUR206) live in :mod:`murmura_tpu.analysis.budgets` and
    are composed by ``run_check``, not here — they need AOT compiles per
    grid cell while everything here except MUR202/204 is trace-only.
    """
    global _IR_MEMO
    if _IR_MEMO is not None and not force:
        return list(_IR_MEMO)

    _ensure_host_devices()
    from murmura_tpu.aggregation import AGGREGATORS

    findings: List[Finding] = list(check_coverage())
    n_a, n_b = IR_NODE_COUNTS
    for name in sorted(AGGREGATORS):
        if name not in AGG_CASES:
            continue  # already a MUR205 finding
        for circulant in (False, True):
            # A crash anywhere — building the canonical program, tracing,
            # or the sharded lowering — IS the finding: one broken rule
            # must not take down the whole check run and hide every other
            # finding.
            try:
                prog = build_canonical(name, n_a, "float32", circulant)
                prog_b = build_canonical(name, n_b, "float32", circulant)
                prog_bf16 = build_canonical(name, n_a, "bfloat16", circulant)
                sharded = build_canonical(
                    name, n_a, "float32", circulant, node_axis_sharded=True
                )
                jaxpr = trace_jaxpr(prog)
                findings.extend(_check_callbacks(name, prog, jaxpr))
                findings.extend(_check_dtypes(name, prog, prog_bf16))
                findings.extend(_check_structure(name, prog, prog_b))
                findings.extend(_check_collectives(name, sharded))
            except Exception as e:  # noqa: BLE001 — a crash IS the finding
                path, line = _rule_anchor(name)
                findings.append(Finding(
                    "MUR205", path, line,
                    f"aggregator '{name}' ({_mode(circulant)}) crashed the "
                    f"canonical IR sweep: {type(e).__name__}: {e}",
                ))
    # Round-program-level families run off the registry — adding a family
    # is one decorator, and an unregistered ``check_*`` function is itself
    # a MUR205 finding (check_coverage's unwired-family scan).
    pkg = Path(__file__).resolve().parent.parent
    for fam_name, (fam, crash_rule, crash_anchor) in IR_CHECK_FAMILIES.items():
        try:
            findings.extend(fam())
        except Exception as e:  # noqa: BLE001 — a crash IS the finding
            findings.append(Finding(
                crash_rule, str(pkg / crash_anchor), 1,
                f"the '{fam_name}' IR contracts crashed: "
                f"{type(e).__name__}: {e}",
            ))

    findings = _apply_suppressions(list(dict.fromkeys(findings)))
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    _IR_MEMO = list(findings)
    return findings

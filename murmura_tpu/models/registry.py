"""Model factory registry: config factory strings -> Model builders.

Mirrors the reference's string-addressed factories
(murmura/utils/factories.py:45-61: ``examples.leaf.*`` / ``examples.wearables.*``
prefixes) plus native ids for the new framework's own models.
"""

from typing import Any, Dict

from murmura_tpu.models.cnn import FEMNIST_VARIANTS, make_celeba_cnn, make_femnist_cnn
from murmura_tpu.models.core import Model
from murmura_tpu.models.lstm import make_char_lstm
from murmura_tpu.models.mlp import make_mlp, make_wearable_mlp

# Wearable dataset default dims (reference: murmura/examples/wearables/models.py:195-300:
# UCI HAR 561/(256,128); PAMAP2 4000 = 100-window x 40 feats /(512,256,128);
# PPG-DaLiA 192 = 32-window x 6 feats /(256,128,64))
_WEARABLE_DEFAULTS = {
    "uci_har": {"input_dim": 561, "hidden_dims": (256, 128), "num_classes": 6},
    "pamap2": {"input_dim": 4000, "hidden_dims": (512, 256, 128), "num_classes": 12},
    "ppg_dalia": {"input_dim": 192, "hidden_dims": (256, 128, 64), "num_classes": 7},
}


def build_model(factory: str, params: Dict[str, Any]) -> Model:
    """Resolve a config ``model.factory`` string to a Model.

    Accepted ids:
    - ``mlp`` — generic softmax MLP (params: input_dim, hidden_dims,
      num_classes, dropout, evidential).
    - ``examples.leaf.LEAFFEMNISTModel`` / ``leaf.femnist[.variant]`` —
      FEMNIST CNN family (variant in tiny/small/baseline/large/xlarge).
    - ``examples.leaf.LEAFCelebAModel`` / ``leaf.celeba`` — CelebA CNN.
    - ``leaf.shakespeare`` — char-LSTM.
    - ``examples.wearables.<uci_har|pamap2|ppg_dalia>`` /
      ``wearables.<...>`` — evidential wearable MLPs.
    """
    params = dict(params or {})
    f = factory.strip()
    compute_dtype = params.pop("compute_dtype", None)

    if f == "mlp":
        evidential = bool(params.pop("evidential", False))
        return make_mlp(
            input_dim=int(params.pop("input_dim", 32)),
            hidden_dims=tuple(params.pop("hidden_dims", (64, 32))),
            num_classes=int(params.pop("num_classes", 10)),
            dropout_rate=float(params.pop("dropout", 0.0)),
            evidential=evidential,
            compute_dtype=compute_dtype,
        )

    lowered = f.lower()
    if "femnist" in lowered:
        variant = params.pop("variant", None)
        if variant is None:
            tail = lowered.rsplit(".", 1)[-1]
            variant = tail if tail in FEMNIST_VARIANTS else "baseline"
        return make_femnist_cnn(
            num_classes=int(params.pop("num_classes", 62)), variant=variant,
            compute_dtype=compute_dtype,
            conv_impl=params.pop("conv_impl", "direct"),
        )

    if "celeba" in lowered:
        return make_celeba_cnn(
            num_classes=int(params.pop("num_classes", 2)),
            compute_dtype=compute_dtype,
            conv_impl=params.pop("conv_impl", "direct"),
        )

    if "shakespeare" in lowered:
        return make_char_lstm(
            vocab_size=int(params.pop("vocab_size", 81)),
            embed_dim=int(params.pop("embed_dim", 8)),
            hidden=int(params.pop("hidden", 256)),
            num_layers=int(params.pop("num_layers", 2)),
            seq_len=int(params.pop("seq_len", 80)),
            compute_dtype=compute_dtype,
        )

    for prefix in ("examples.wearables.", "wearables."):
        if f.startswith(prefix):
            kind = f[len(prefix):]
            defaults = dict(_WEARABLE_DEFAULTS.get(kind, _WEARABLE_DEFAULTS["uci_har"]))
            defaults.update(params)
            return make_wearable_mlp(
                input_dim=int(defaults["input_dim"]),
                hidden_dims=tuple(defaults["hidden_dims"]),
                num_classes=int(defaults["num_classes"]),
                dropout=float(defaults.get("dropout", 0.3)),
                name=f"wearables.{kind}",
                compute_dtype=compute_dtype,
            )

    raise ValueError(f"Unknown model factory: {factory!r}")

"""Model zoo (reference families: murmura/examples/leaf/, murmura/examples/wearables/)."""

from murmura_tpu.models.core import Model
from murmura_tpu.models.mlp import make_mlp, make_wearable_mlp
from murmura_tpu.models.cnn import make_femnist_cnn, make_celeba_cnn, FEMNIST_VARIANTS
from murmura_tpu.models.lstm import make_char_lstm
from murmura_tpu.models.registry import build_model

__all__ = [
    "Model",
    "make_mlp",
    "make_wearable_mlp",
    "make_femnist_cnn",
    "make_celeba_cnn",
    "make_char_lstm",
    "build_model",
    "FEMNIST_VARIANTS",
]

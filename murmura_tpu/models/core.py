"""Functional model abstraction and layer primitives.

Models are (init, apply) pairs over plain dict pytrees — no module classes,
no mutable state. This is what makes the framework's core trick cheap:
stacking N nodes' parameters along a leading axis and vmap/shard_map-ing
``apply`` over it (the reference instead deep-copies nn.Modules and calls
``load_state_dict`` per neighbor per round — murmura/aggregation/
evidential_trust.py:236-260, a cost this design eliminates).

Conventions:
- ``init(key) -> params`` (nested dict of float32 arrays);
- ``apply(params, x, key, train) -> outputs`` where ``train`` is a Python
  bool (static under trace) and ``key`` drives dropout when training;
- images are NHWC; convs/matmuls stay large and batched for the MXU.

Normalization: models use LayerNorm instead of the reference's BatchNorm1d
(murmura/examples/wearables/models.py:208). BatchNorm's integer
``num_batches_tracked`` buffer forces the reference to special-case
non-float state in every aggregator (aggregation/base.py:100-113); LayerNorm
keeps the whole state float, aggregatable, and jit-friendly.
"""

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

Params = Any


@dataclass(frozen=True)
class Model:
    """A functional model: pure init/apply plus metadata.

    Attributes:
        name: registry id.
        init: key -> params pytree.
        apply: (params, x, key, train) -> [B, K] logits, or Dirichlet alphas
            when ``evidential`` is True.
        evidential: whether outputs are Dirichlet concentration parameters.
        input_shape: per-sample input shape (no batch dim).
        num_classes: output arity.
    """

    name: str
    init: Callable[[jax.Array], Params]
    apply: Callable[[Params, jnp.ndarray, Optional[jax.Array], bool], jnp.ndarray]
    evidential: bool = False
    input_shape: Tuple[int, ...] = ()
    num_classes: int = 0
    meta: Dict[str, Any] = field(default_factory=dict)


# ---------------------------------------------------------------------------
# Layer primitives
# ---------------------------------------------------------------------------


def dense_init(key: jax.Array, in_dim: int, out_dim: int) -> Params:
    """He-uniform linear layer init (matches torch.nn.Linear's default
    kaiming-uniform fan_in scaling)."""
    kw, kb = jax.random.split(key)
    bound = 1.0 / jnp.sqrt(in_dim)
    return {
        "w": jax.random.uniform(kw, (in_dim, out_dim), jnp.float32, -bound, bound),
        "b": jax.random.uniform(kb, (out_dim,), jnp.float32, -bound, bound),
    }


def resolve_dtype(compute_dtype) -> Optional[jnp.dtype]:
    """Config string -> matmul compute dtype (None = full precision).

    bfloat16 is the MXU-native input precision; params stay float32 and all
    accumulations are forced to float32 via preferred_element_type, so only
    the multiplicand precision drops (standard TPU mixed precision).
    """
    if compute_dtype in (None, "float32", jnp.float32):
        return None
    if compute_dtype in ("bfloat16", jnp.bfloat16):
        return jnp.bfloat16
    raise ValueError(f"Unknown compute_dtype: {compute_dtype!r}")


def dense(p: Params, x: jnp.ndarray, dtype=None) -> jnp.ndarray:
    if dtype is None:
        return x @ p["w"] + p["b"]
    y = jnp.dot(
        x.astype(dtype), p["w"].astype(dtype),
        preferred_element_type=jnp.float32,
    )
    return y + p["b"]


def conv_init(key: jax.Array, kh: int, kw: int, c_in: int, c_out: int) -> Params:
    """5x5/3x3 conv init, kaiming-uniform over fan_in."""
    k1, k2 = jax.random.split(key)
    fan_in = kh * kw * c_in
    bound = 1.0 / jnp.sqrt(fan_in)
    return {
        "w": jax.random.uniform(
            k1, (kh, kw, c_in, c_out), jnp.float32, -bound, bound
        ),
        "b": jax.random.uniform(k2, (c_out,), jnp.float32, -bound, bound),
    }


def conv2d(
    p: Params, x: jnp.ndarray, padding: str = "SAME", dtype=None,
    impl: str = "direct",
) -> jnp.ndarray:
    """NHWC conv with HWIO kernel.

    Mixed precision note: unlike dot, conv's VJP rejects mixed-dtype
    operands under preferred_element_type, so the low-precision path keeps
    the conv uniformly in ``dtype`` (MXU accumulates f32 internally) and
    casts the result back to float32.

    ``impl="im2col"`` expresses the conv as patch extraction + one GEMM
    ([B*H*W, kh*kw*cin] @ [kh*kw*cin, cout]) — the local-SGD lever
    candidate from bench_sgd_micro.py: under ``vmap`` over the node axis
    the conv stack becomes MXU-native batched matmuls instead of whatever
    XLA lowers a grouped convolution to.  Same math, same HWIO parameter
    layout (checkpoints are interchangeable between impls); the transpose
    matches conv_general_dilated_patches' channel-major feature order.
    """
    w = p["w"]
    if impl == "im2col":
        kh, kw, cin, cout = w.shape
        pat = jax.lax.conv_general_dilated_patches(
            x.astype(dtype) if dtype is not None else x,
            (kh, kw), (1, 1), padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )  # [B, H, W, cin*kh*kw], input-channel-major feature order
        b_, h_, w_ = pat.shape[0], pat.shape[1], pat.shape[2]
        wm = w.transpose(2, 0, 1, 3).reshape(cin * kh * kw, cout)
        if dtype is not None:
            wm = wm.astype(dtype)
        y = pat.reshape(b_ * h_ * w_, -1) @ wm
        y = y.reshape(b_, h_, w_, cout)
        if dtype is not None:
            y = y.astype(jnp.float32)
        return y + p["b"]
    if dtype is not None:
        x = x.astype(dtype)
        w = w.astype(dtype)
    y = jax.lax.conv_general_dilated(
        x,
        w,
        window_strides=(1, 1),
        padding=padding,
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )
    if dtype is not None:
        y = y.astype(jnp.float32)
    return y + p["b"]


def max_pool(x: jnp.ndarray, window: int = 2, stride: int = 2) -> jnp.ndarray:
    return jax.lax.reduce_window(
        x,
        -jnp.inf,
        jax.lax.max,
        window_dimensions=(1, window, window, 1),
        window_strides=(1, stride, stride, 1),
        padding="VALID",
    )


def layernorm_init(dim: int) -> Params:
    return {"scale": jnp.ones((dim,)), "bias": jnp.zeros((dim,))}


def layernorm(p: Params, x: jnp.ndarray, eps: float = 1e-5) -> jnp.ndarray:
    mean = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    return (x - mean) / jnp.sqrt(var + eps) * p["scale"] + p["bias"]


def dropout(
    key: Optional[jax.Array], x: jnp.ndarray, rate: float, train: bool
) -> jnp.ndarray:
    if not train or rate <= 0.0 or key is None:
        return x
    keep = 1.0 - rate
    mask = jax.random.bernoulli(key, keep, x.shape)
    return jnp.where(mask, x / keep, 0.0)


def evidential_head(p: Params, x: jnp.ndarray, dtype=None) -> jnp.ndarray:
    """Dense -> softplus evidence -> alpha = evidence + 1
    (reference: murmura/examples/wearables/models.py:18-46)."""
    return jax.nn.softplus(dense(p, x, dtype)) + 1.0


def split_keys(key: jax.Array, n: int) -> Sequence[jax.Array]:
    return jax.random.split(key, n)

"""MLP classifiers: plain softmax and evidential variants.

The evidential MLP is the wearables model family (reference:
murmura/examples/wearables/models.py:187-347): Linear -> norm -> ReLU ->
Dropout feature stacks with an evidential head producing Dirichlet alphas.
LayerNorm replaces the reference's BatchNorm1d (see models/core.py docstring).
"""

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp

from murmura_tpu.models.core import (
    Model,
    dense,
    dense_init,
    dropout,
    evidential_head,
    layernorm,
    layernorm_init,
    resolve_dtype,
)


def make_mlp(
    input_dim: int,
    hidden_dims: Sequence[int] = (64, 32),
    num_classes: int = 10,
    dropout_rate: float = 0.0,
    evidential: bool = False,
    name: str = "mlp",
    compute_dtype=None,
) -> Model:
    """Build an MLP ``Model``.

    Args:
        input_dim: flattened input feature size.
        hidden_dims: widths of hidden layers.
        num_classes: output classes.
        dropout_rate: dropout after each hidden block.
        evidential: if True, output Dirichlet alphas via softplus head.
        compute_dtype: None/"float32" or "bfloat16" matmul inputs (MXU).
    """
    dims = [int(input_dim)] + [int(h) for h in hidden_dims]
    cd = resolve_dtype(compute_dtype)

    def init(key: jax.Array):
        keys = jax.random.split(key, len(dims))
        params = {"layers": [], "head": None}
        for i, (d_in, d_out) in enumerate(zip(dims[:-1], dims[1:])):
            params["layers"].append(
                {"fc": dense_init(keys[i], d_in, d_out), "ln": layernorm_init(d_out)}
            )
        params["head"] = dense_init(keys[-1], dims[-1], num_classes)
        return params

    def apply(params, x, key=None, train=False):
        x = x.reshape((x.shape[0], -1))
        n_layers = len(dims) - 1
        drop_keys = (
            jax.random.split(key, n_layers) if (train and key is not None) else [None] * n_layers
        )
        for i, layer in enumerate(params["layers"]):
            x = dense(layer["fc"], x, cd)
            x = layernorm(layer["ln"], x)
            x = jax.nn.relu(x)
            x = dropout(drop_keys[i], x, dropout_rate, train)
        if evidential:
            return evidential_head(params["head"], x, cd)
        return dense(params["head"], x, cd)

    return Model(
        name=name,
        init=init,
        apply=apply,
        evidential=evidential,
        input_shape=(input_dim,),
        num_classes=num_classes,
        meta={"hidden_dims": tuple(hidden_dims), "dropout": dropout_rate},
    )


def make_wearable_mlp(
    input_dim: int = 561,
    hidden_dims: Tuple[int, ...] = (256, 128),
    num_classes: int = 6,
    dropout: float = 0.3,
    name: str = "wearables.mlp",
    compute_dtype=None,
) -> Model:
    """Evidential wearable classifier (reference: wearables/models.py:187-229
    — UCI HAR default: 561 -> 256 -> 128 -> Evidential(6))."""
    return make_mlp(
        input_dim=input_dim,
        hidden_dims=hidden_dims,
        num_classes=num_classes,
        dropout_rate=dropout,
        evidential=True,
        name=name,
        compute_dtype=compute_dtype,
    )

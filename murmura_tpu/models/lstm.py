"""Shakespeare character LSTM.

JAX counterpart of the LEAF Shakespeare next-char model the reference vendors
(leaf/models/shakespeare/stacked_lstm.py:19-38): embedding(8) -> 2-layer
LSTM(256) -> dense(vocab), seq_len 80.  The recurrence is a ``lax.scan`` over
time with both layers fused per step, so XLA compiles one loop with large
per-step matmuls for the MXU instead of Python-level cell calls.
"""

from typing import Tuple

import jax
import jax.numpy as jnp

from murmura_tpu.models.core import Model, dense, dense_init, resolve_dtype


def _lstm_cell_init(key: jax.Array, in_dim: int, hidden: int):
    k1, k2 = jax.random.split(key)
    bound = 1.0 / jnp.sqrt(hidden)
    return {
        "wi": jax.random.uniform(k1, (in_dim, 4 * hidden), jnp.float32, -bound, bound),
        "wh": jax.random.uniform(k2, (hidden, 4 * hidden), jnp.float32, -bound, bound),
        "b": jnp.zeros((4 * hidden,)),
    }


def _lstm_cell(p, x, h, c, dtype=None):
    """One LSTM step; gates packed [i, f, g, o] in a single matmul."""
    if dtype is not None:
        z = (
            jnp.dot(x.astype(dtype), p["wi"].astype(dtype),
                    preferred_element_type=jnp.float32)
            + jnp.dot(h.astype(dtype), p["wh"].astype(dtype),
                      preferred_element_type=jnp.float32)
            + p["b"]
        )
    else:
        z = x @ p["wi"] + h @ p["wh"] + p["b"]
    i, f, g, o = jnp.split(z, 4, axis=-1)
    c_new = jax.nn.sigmoid(f + 1.0) * c + jax.nn.sigmoid(i) * jnp.tanh(g)
    h_new = jax.nn.sigmoid(o) * jnp.tanh(c_new)
    return h_new, c_new


def make_char_lstm(
    vocab_size: int = 81,
    embed_dim: int = 8,
    hidden: int = 256,
    num_layers: int = 2,
    seq_len: int = 80,
    name: str = "leaf.shakespeare",
    compute_dtype=None,
) -> Model:
    """Stacked char-LSTM predicting the next character from seq_len tokens."""
    cd = resolve_dtype(compute_dtype)

    def init(key: jax.Array):
        keys = jax.random.split(key, num_layers + 2)
        params = {
            "embed": jax.random.normal(keys[0], (vocab_size, embed_dim)) * 0.1,
            "cells": [],
            "out": dense_init(keys[-1], hidden, vocab_size),
        }
        in_dim = embed_dim
        for l in range(num_layers):
            params["cells"].append(_lstm_cell_init(keys[1 + l], in_dim, hidden))
            in_dim = hidden
        return params

    def apply(params, x, key=None, train=False):
        # x: [B, T] int tokens
        emb = params["embed"][x]  # [B, T, E]
        batch = x.shape[0]

        def step(carry, x_t):
            hs, cs = carry
            inp = x_t
            new_hs, new_cs = [], []
            for l, cell in enumerate(params["cells"]):
                h, c = _lstm_cell(cell, inp, hs[l], cs[l], cd)
                new_hs.append(h)
                new_cs.append(c)
                inp = h
            return (tuple(new_hs), tuple(new_cs)), None

        h0 = tuple(jnp.zeros((batch, hidden)) for _ in range(num_layers))
        c0 = tuple(jnp.zeros((batch, hidden)) for _ in range(num_layers))
        (hs, _), _ = jax.lax.scan(step, (h0, c0), jnp.swapaxes(emb, 0, 1))
        return dense(params["out"], hs[-1], cd)

    return Model(
        name=name,
        init=init,
        apply=apply,
        evidential=False,
        input_shape=(seq_len,),
        num_classes=vocab_size,
        meta={"vocab_size": vocab_size, "hidden": hidden, "layers": num_layers},
    )

"""FEMNIST CNN family and CelebA CNN.

Architectural parity with the reference LEAF models
(murmura/examples/leaf/datasets.py:204-297, murmura/examples/leaf/models.py:12-192):
- femnist baseline: conv5x5x32 -> pool -> conv5x5x64 -> pool -> fc2048 -> fc62
  (~6.5M params);
- scaling variants tiny (8/16/fc256), small (16/32/fc512), large (64/128/fc4096),
  xlarge (3x3 convs 64/128/256 + fc4096 + fc2048).

All convs are NHWC with SAME padding; 28x28 grayscale in, two 2x2 max-pools
down to 7x7 before the dense stack — shapes that tile cleanly onto the MXU.
"""

from typing import Sequence

import jax
import jax.numpy as jnp

from murmura_tpu.models.core import (
    Model,
    conv2d,
    conv_init,
    dense,
    dense_init,
    max_pool,
    resolve_dtype,
)

FEMNIST_VARIANTS = {
    # variant: (conv_channels, kernel, fc_dims)
    "tiny": ((8, 16), 5, (256,)),
    "small": ((16, 32), 5, (512,)),
    "baseline": ((32, 64), 5, (2048,)),
    "large": ((64, 128), 5, (4096,)),
    "xlarge": ((64, 128, 256), 3, (4096, 2048)),
}


def make_femnist_cnn(
    num_classes: int = 62,
    variant: str = "baseline",
    image_size: int = 28,
    channels_in: int = 1,
    name: str = None,
    compute_dtype=None,
    conv_impl: str = "direct",
) -> Model:
    """Build a FEMNIST CNN ``Model`` for 28x28x1 inputs.

    ``conv_impl="im2col"`` routes the conv layers through the
    patch-GEMM formulation (models/core.py conv2d) — the local-SGD
    lever candidate measured by bench_sgd_micro.py.
    """
    if variant not in FEMNIST_VARIANTS:
        raise ValueError(
            f"Unknown FEMNIST variant '{variant}' (choose from {list(FEMNIST_VARIANTS)})"
        )
    conv_channels, kernel, fc_dims = FEMNIST_VARIANTS[variant]
    cd = resolve_dtype(compute_dtype)
    ci = conv_impl
    # xlarge applies conv1,conv2 then pool, conv3 then pool (reference:
    # examples/leaf/models.py:159-169); others pool after every conv.
    final_hw = image_size // 4
    flat_dim = final_hw * final_hw * conv_channels[-1]
    dense_dims = [flat_dim] + list(fc_dims) + [num_classes]

    def init(key: jax.Array):
        n_conv = len(conv_channels)
        n_fc = len(dense_dims) - 1
        keys = jax.random.split(key, n_conv + n_fc)
        params = {"convs": [], "fcs": []}
        c_prev = channels_in
        for i, c in enumerate(conv_channels):
            params["convs"].append(conv_init(keys[i], kernel, kernel, c_prev, c))
            c_prev = c
        for j in range(n_fc):
            params["fcs"].append(
                dense_init(keys[n_conv + j], dense_dims[j], dense_dims[j + 1])
            )
        return params

    def apply(params, x, key=None, train=False):
        if x.ndim == 3:
            x = x[..., None]
        n_conv = len(params["convs"])
        if n_conv == 2:
            for conv_p in params["convs"]:
                x = jax.nn.relu(conv2d(conv_p, x, dtype=cd, impl=ci))
                x = max_pool(x)
        else:
            x = jax.nn.relu(conv2d(params["convs"][0], x, dtype=cd, impl=ci))
            x = jax.nn.relu(conv2d(params["convs"][1], x, dtype=cd, impl=ci))
            x = max_pool(x)
            x = jax.nn.relu(conv2d(params["convs"][2], x, dtype=cd, impl=ci))
            x = max_pool(x)
        x = x.reshape((x.shape[0], -1))
        for fc in params["fcs"][:-1]:
            x = jax.nn.relu(dense(fc, x, cd))
        return dense(params["fcs"][-1], x, cd)

    return Model(
        name=name or f"leaf.femnist.{variant}",
        init=init,
        apply=apply,
        evidential=False,
        input_shape=(image_size, image_size, channels_in),
        num_classes=num_classes,
        meta={"variant": variant},
    )


def make_celeba_cnn(
    num_classes: int = 2,
    image_size: int = 84,
    channels: Sequence[int] = (32, 64, 128),
    fc_dim: int = 256,
    name: str = "leaf.celeba",
    compute_dtype=None,
    conv_impl: str = "direct",
) -> Model:
    """LeNet-style CelebA CNN for 84x84 RGB
    (reference: murmura/examples/leaf/datasets.py:235-297)."""
    cd = resolve_dtype(compute_dtype)
    ci = conv_impl
    n_conv = len(channels)
    final_hw = image_size // (2**n_conv)
    flat_dim = final_hw * final_hw * channels[-1]

    def init(key: jax.Array):
        keys = jax.random.split(key, n_conv + 2)
        params = {"convs": [], "fcs": []}
        c_prev = 3
        for i, c in enumerate(channels):
            params["convs"].append(conv_init(keys[i], 3, 3, c_prev, c))
            c_prev = c
        params["fcs"].append(dense_init(keys[n_conv], flat_dim, fc_dim))
        params["fcs"].append(dense_init(keys[n_conv + 1], fc_dim, num_classes))
        return params

    def apply(params, x, key=None, train=False):
        for conv_p in params["convs"]:
            x = jax.nn.relu(conv2d(conv_p, x, dtype=cd, impl=ci))
            x = max_pool(x)
        x = x.reshape((x.shape[0], -1))
        x = jax.nn.relu(dense(params["fcs"][0], x, cd))
        return dense(params["fcs"][1], x, cd)

    return Model(
        name=name,
        init=init,
        apply=apply,
        evidential=False,
        input_shape=(image_size, image_size, 3),
        num_classes=num_classes,
    )

"""Node-axis sharding over a device mesh — the ``backend: tpu`` engine.

This is the TPU-native replacement for the reference's entire distributed
communication backend (murmura/distributed/: ZeroMQ PUSH/PULL sockets,
torch.save serialization, wall-clock round sync — node_process.py:193-276):
the stacked network state's leading ``nodes`` axis is sharded over a 1-D
``jax.sharding.Mesh``, the round step is jitted global-view, and XLA lowers
the neighbor exchange (every ``adj @ bcast`` / gathered [N, P] read in the
aggregation rules) into all-gather/reduce collectives over ICI.  No sockets,
no serialization, no deadlines — the collective IS the synchronization.

Multi-host scale-out: the same program runs under ``jax.distributed`` with a
mesh spanning hosts; XLA routes intra-slice traffic over ICI and cross-slice
traffic over DCN.  Tested virtually via
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` (see tests/ and
__graft_entry__.dryrun_multichip).
"""

from typing import Any, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def backend_initialized() -> bool:
    """Whether an XLA backend already exists in this process — checked
    WITHOUT creating one (``jax.devices()`` would).

    This is the runtime twin of the MUR005 lint rule (analysis/lint.py):
    module-import-time ``jnp.*`` work initializes the backend before
    :func:`init_multihost` can pin the platform/topology, and the resulting
    jax.distributed failure modes are far less legible than failing here.
    """
    from jax._src import xla_bridge

    # backends_are_initialized() is the helper jax.distributed itself uses;
    # the _backends dict is the fallback for versions without it.  Both are
    # private (jax._src has no stability guarantee), so a future rename
    # fails OPEN — the guard stops firing rather than breaking every
    # init_multihost call; MUR005 remains the static line of defense.
    probe = getattr(xla_bridge, "backends_are_initialized", None)
    if probe is not None:
        return bool(probe())
    return bool(getattr(xla_bridge, "_backends", None))


def init_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join a multi-host JAX run (tpu.multihost: true).

    After this, ``jax.devices()`` spans every host of the slice and the same
    jitted round program runs SPMD with XLA routing intra-slice collectives
    over ICI and cross-slice over DCN. Arguments default to the standard
    JAX coordination env vars (JAX_COORDINATOR_ADDRESS etc. / TPU metadata).
    Must run before anything initializes the XLA backend; a duplicate call
    in the same process is ignored.
    """
    if getattr(jax.distributed, "is_initialized", lambda: False)():
        return
    if backend_initialized():
        raise RuntimeError(
            "init_multihost called after an XLA backend was already "
            "initialized in this process: jax.distributed cannot join a "
            "run once single-process devices exist.  Something executed a "
            "jax computation (often a module-import-time jnp.* call — the "
            "MUR005 lint class, `python -m murmura_tpu check`) before the "
            "mesh setup; move it inside a function"
        )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_mesh(num_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the first ``num_devices`` devices, axis name ``nodes``."""
    devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"Requested {num_devices} devices but only {len(devices)} available"
            )
        devices = devices[:num_devices]
    return Mesh(np.array(devices), ("nodes",))


def make_shardings(mesh: Mesh):
    """(node_sharded, replicated) NamedSharding pair for the mesh."""
    return NamedSharding(mesh, P("nodes")), NamedSharding(mesh, P())


def _shard_leading_axis(tree: Any, node_sharding, replicated) -> Any:
    """Sharding pytree: leading-axis 'nodes' on every array leaf, replicating
    scalars and rank-0 leaves."""

    def spec(leaf):
        if hasattr(leaf, "ndim") and leaf.ndim >= 1:
            return node_sharding
        return replicated

    return jax.tree_util.tree_map(spec, tree)


def _shard_round_fn(
    fn, program, mesh: Mesh, adj_sharding, donate: bool, alive_sharding=None
):
    """Shared jit wrapper for round-shaped programs.

    Both the per-round step and the fused multi-round scan take
    (params, agg_state, key, <adjacency>, compromised, round, data) and
    return (params, agg_state, metrics); only the adjacency argument's
    sharding differs.  Faulted programs (``program.faulted``) take an
    extra per-round alive mask after ``compromised`` whose sharding is
    supplied as ``alive_sharding`` ([N] node-sharded for the step,
    [chunk, N] second-axis-sharded for the fused scan).  Outputs:
    params/agg_state stay node-sharded; the small per-node metrics arrays
    are replicated so the orchestrator's device_get works when the mesh
    spans multiple processes (multi-host: a node-sharded output would span
    non-addressable devices).
    """
    n_dev = mesh.devices.size
    if program.num_nodes % n_dev != 0:
        raise ValueError(
            f"num_nodes={program.num_nodes} not divisible by mesh size {n_dev}"
        )
    node_s, repl = make_shardings(mesh)

    params_s = _shard_leading_axis(program.init_params, node_s, repl)
    agg_s = _shard_leading_axis(program.init_agg_state, node_s, repl)
    data_s = _shard_leading_axis(program.data_arrays, node_s, repl)

    in_shardings = [
        params_s,  # params
        agg_s,  # agg_state
        repl,  # rng key
        adj_sharding,  # adjacency (per-round rows or stacked)
        node_s,  # compromised mask
        repl,  # round index
        data_s,  # data dict
    ]
    if program.faulted:
        in_shardings.insert(5, alive_sharding)  # alive mask / alive stack
    return jax.jit(
        fn,
        in_shardings=tuple(in_shardings),
        out_shardings=(params_s, agg_s, repl),
        donate_argnums=(0, 1) if donate else (),
    )


def shard_step(step, program, mesh: Mesh, donate: bool = True):
    """Jit a RoundProgram step with the node axis sharded over ``mesh``.

    Args:
        step: the traced round function (params, agg_state, key, adj,
            compromised, round_idx, data) -> (params, agg_state, metrics)
            — faulted programs take an [N] alive mask after compromised.
        program: RoundProgram (for example structures to derive shardings).
        mesh: 1-D ``nodes`` mesh; program.num_nodes must be divisible by its
            size.

    Returns:
        The compiled step with in/out shardings pinned.
    """
    node_s, _ = make_shardings(mesh)
    adj_s = edge_mask_sharding(mesh) if program.sparse else node_s
    return _shard_round_fn(
        step, program, mesh, adj_s, donate, alive_sharding=node_s
    )


def adj_stack_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding of the fused-dispatch adjacency stack [chunk, N, N]: sharded
    on its *second* axis (each device holds its nodes' rows for every round
    of the chunk).  Shared by :func:`shard_multi_round` and the
    orchestrator's explicit input staging (Network._stage)."""
    return NamedSharding(mesh, P(None, "nodes"))


def edge_mask_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding of the sparse [k, N] per-offset edge mask
    (topology/sparse.py): the node axis is SECOND, the small static offset
    axis replicates — each device holds its nodes' columns of every offset
    row."""
    return NamedSharding(mesh, P(None, "nodes"))


def sparse_adj_stack_sharding(mesh: Mesh) -> NamedSharding:
    """Fused-dispatch sparse edge-mask stack [chunk, k, N]: node axis third."""
    return NamedSharding(mesh, P(None, None, "nodes"))


def shard_multi_round(multi_round, program, mesh: Mesh, donate: bool = True):
    """Jit a fused multi-round scan (core.rounds.build_multi_round) over
    ``mesh`` with the same node-axis layout as :func:`shard_step`.  The
    faulted alive_stack [chunk, N] shares the adj_stack's layout: sharded
    on its second (node) axis."""
    adj_s = (
        sparse_adj_stack_sharding(mesh) if program.sparse
        else adj_stack_sharding(mesh)
    )
    return _shard_round_fn(
        multi_round, program, mesh, adj_s, donate,
        alive_sharding=adj_stack_sharding(mesh),
    )


# --------------------------------------------------------------------------
# Gang-batched execution (core/gang.py): the [B] experiment axis joins the
# mesh as a second dimension.
# --------------------------------------------------------------------------


def make_gang_mesh(
    batch: int, num_nodes: int, num_devices: Optional[int] = None
) -> Mesh:
    """2-D ("seed", "nodes") mesh for a gang of ``batch`` members.

    Layout policy (ISSUE 5): **seed-major** when the whole gang fits —
    ``batch * num_nodes <= devices`` puts every (member, node) pair on its
    own device (maximum parallelism, zero per-member serialization);
    otherwise the largest seed-axis factor that divides both the device
    count and the gang, falling back to a pure node-sharded mesh with the
    seed axis replicated (size 1) — each device then holds all B members of
    its node rows, which is the right layout when N is large and B small.
    """
    devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"Requested {num_devices} devices but only {len(devices)} available"
            )
        devices = devices[:num_devices]
    n_dev = len(devices)
    if batch * num_nodes <= n_dev:
        sel = np.array(devices[: batch * num_nodes])
        return Mesh(sel.reshape(batch, num_nodes), ("seed", "nodes"))
    for s in sorted(range(1, n_dev + 1), reverse=True):
        if n_dev % s == 0 and s <= batch and batch % s == 0:
            if num_nodes % (n_dev // s) == 0:
                return Mesh(
                    np.array(devices).reshape(s, n_dev // s),
                    ("seed", "nodes"),
                )
    raise ValueError(
        f"cannot lay a gang of {batch} members x {num_nodes} nodes onto "
        f"{n_dev} devices: no (seed, nodes) factorization divides both "
        "axes — adjust tpu.num_devices or the gang size"
    )


def _shard_gang_leading(tree: Any, mesh: Mesh) -> Any:
    """Sharding pytree for *stacked* [B, ...] gang state: [B, N, ...]
    leaves split ("seed", "nodes"), [B] per-member leaves split ("seed",),
    rank-0 leaves replicate.  Leaves whose second axis is not the node
    axis (or not divisible by it) stay seed-sharded only."""
    gang2d = NamedSharding(mesh, P("seed", "nodes"))
    member = NamedSharding(mesh, P("seed"))
    repl = NamedSharding(mesh, P())
    node_ax = mesh.shape["nodes"]

    def spec(leaf):
        if not hasattr(leaf, "ndim") or leaf.ndim == 0:
            return repl
        if leaf.ndim >= 2 and leaf.shape[1] % node_ax == 0:
            return gang2d
        return member

    return jax.tree_util.tree_map(spec, tree)


def _gang_spec_from_template(tree: Any, mesh: Mesh) -> Any:
    """Sharding pytree for stacked gang inputs derived from the UNSTACKED
    per-member template (program.init_params / init_agg_state /
    data_arrays): a member leaf of rank >= 1 gains the gang axis in front
    ([B, N, ...] -> ("seed", "nodes")); a rank-0 member leaf becomes a [B]
    per-member vector (("seed",))."""
    gang2d = NamedSharding(mesh, P("seed", "nodes"))
    member = NamedSharding(mesh, P("seed"))
    node_ax = mesh.shape["nodes"]

    def spec(leaf):
        leaf = np.asarray(leaf)
        if leaf.ndim >= 1 and leaf.shape[0] % node_ax == 0:
            return gang2d
        return member

    return jax.tree_util.tree_map(spec, tree)


def gang_node_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding of member-shared node-leading arrays (the [N, N] adjacency,
    the [N] alive mask): node rows split over the ``nodes`` axis, values
    replicated along ``seed``."""
    return NamedSharding(mesh, P("nodes"))


def gang_adj_stack_sharding(mesh: Mesh) -> NamedSharding:
    """Fused-dispatch [chunk, N, N] adjacency stack (shared across
    members): sharded on the node (second) axis, replicated along seed."""
    return NamedSharding(mesh, P(None, "nodes"))


def _shard_gang_round_fn(
    vfn, program, batch: int, mesh: Mesh, adj_sharding, donate: bool,
    alive_sharding,
):
    """Jit a vmapped round-shaped gang program with in/out shardings pinned
    — the gang twin of :func:`_shard_round_fn`.  The vmapped signature is
    the single-run one with the stacked args gaining a [B] leading axis
    (params, agg_state, keys, compromised, data) and the member-shared args
    (adjacency, alive, round index) unbatched."""
    seed_ax, node_ax = mesh.shape["seed"], mesh.shape["nodes"]
    if batch % seed_ax != 0:
        raise ValueError(
            f"gang batch={batch} not divisible by mesh seed axis {seed_ax}"
        )
    if program.num_nodes % node_ax != 0:
        raise ValueError(
            f"num_nodes={program.num_nodes} not divisible by mesh node "
            f"axis {node_ax}"
        )
    member = NamedSharding(mesh, P("seed"))
    repl = NamedSharding(mesh, P())
    gang2d = NamedSharding(mesh, P("seed", "nodes"))

    params_s = _gang_spec_from_template(program.init_params, mesh)
    agg_s = _gang_spec_from_template(program.init_agg_state, mesh)
    data_s = _gang_spec_from_template(program.data_arrays, mesh)

    in_shardings = [
        params_s,  # stacked params [B, N, ...]
        agg_s,  # stacked agg state
        member,  # per-member rng keys [B, 2]
        adj_sharding,  # shared adjacency (rows or stack)
        gang2d,  # stacked compromised masks [B, N]
        repl,  # round index
        data_s,  # stacked data dict
    ]
    if program.faulted:
        in_shardings.insert(5, alive_sharding)
    return jax.jit(
        vfn,
        in_shardings=tuple(in_shardings),
        out_shardings=(params_s, agg_s, repl),
        donate_argnums=(0, 1) if donate else (),
    )


def shard_gang_step(vstep, program, batch: int, mesh: Mesh, donate: bool = True):
    """Jit the vmapped per-round gang step over a ("seed", "nodes") mesh."""
    return _shard_gang_round_fn(
        vstep, program, batch, mesh, gang_node_sharding(mesh), donate,
        alive_sharding=gang_node_sharding(mesh),
    )


def shard_gang_multi_round(
    vmulti, program, batch: int, mesh: Mesh, donate: bool = True
):
    """Jit the vmapped fused gang scan; the shared [chunk, N, N] adjacency
    stack (and [chunk, N] alive stack) shard on their node axis."""
    return _shard_gang_round_fn(
        vmulti, program, batch, mesh, gang_adj_stack_sharding(mesh), donate,
        alive_sharding=gang_adj_stack_sharding(mesh),
    )


def shard_gang_eval_step(veval, program, batch: int, mesh: Mesh):
    """Jit the vmapped gang eval step; metrics replicate for the same
    multi-host device_get reason as :func:`shard_eval_step`."""
    params_s = _gang_spec_from_template(program.init_params, mesh)
    data_s = _gang_spec_from_template(program.data_arrays, mesh)
    repl = NamedSharding(mesh, P())
    return jax.jit(
        veval,
        in_shardings=(params_s, data_s),
        out_shardings=repl,
    )


def shard_eval_step(eval_step, program, mesh: Mesh):
    """Jit a RoundProgram eval step (params, data) -> metrics over ``mesh``.

    Compiled separately from the train step so the orchestrator only pays
    the full test-set sweep on recorded rounds (``eval_every``).  Metrics
    come out replicated for the same multi-host device_get reason as
    :func:`shard_step`.
    """
    node_s, repl = make_shardings(mesh)
    params_s = _shard_leading_axis(program.init_params, node_s, repl)
    data_s = _shard_leading_axis(program.data_arrays, node_s, repl)
    return jax.jit(
        eval_step,
        in_shardings=(params_s, data_s),
        out_shardings=repl,
    )

"""Node-axis sharding over a device mesh — the ``backend: tpu`` engine.

This is the TPU-native replacement for the reference's entire distributed
communication backend (murmura/distributed/: ZeroMQ PUSH/PULL sockets,
torch.save serialization, wall-clock round sync — node_process.py:193-276):
the stacked network state's leading ``nodes`` axis is sharded over a 1-D
``jax.sharding.Mesh``, the round step is jitted global-view, and XLA lowers
the neighbor exchange (every ``adj @ bcast`` / gathered [N, P] read in the
aggregation rules) into all-gather/reduce collectives over ICI.  No sockets,
no serialization, no deadlines — the collective IS the synchronization.

Multi-host scale-out: the same program runs under ``jax.distributed`` with a
mesh spanning hosts; XLA routes intra-slice traffic over ICI and cross-slice
traffic over DCN.  Tested virtually via
``XLA_FLAGS=--xla_force_host_platform_device_count=N`` (see tests/ and
__graft_entry__.dryrun_multichip).
"""

import contextlib
from typing import Any, List, Optional, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def backend_initialized() -> bool:
    """Whether an XLA backend already exists in this process — checked
    WITHOUT creating one (``jax.devices()`` would).

    This is the runtime twin of the MUR005 lint rule (analysis/lint.py):
    module-import-time ``jnp.*`` work initializes the backend before
    :func:`init_multihost` can pin the platform/topology, and the resulting
    jax.distributed failure modes are far less legible than failing here.
    """
    from jax._src import xla_bridge

    # backends_are_initialized() is the helper jax.distributed itself uses;
    # the _backends dict is the fallback for versions without it.  Both are
    # private (jax._src has no stability guarantee), so a future rename
    # fails OPEN — the guard stops firing rather than breaking every
    # init_multihost call; MUR005 remains the static line of defense.
    probe = getattr(xla_bridge, "backends_are_initialized", None)
    if probe is not None:
        return bool(probe())
    return bool(getattr(xla_bridge, "_backends", None))


def init_multihost(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join a multi-host JAX run (tpu.multihost: true).

    After this, ``jax.devices()`` spans every host of the slice and the same
    jitted round program runs SPMD with XLA routing intra-slice collectives
    over ICI and cross-slice over DCN. Arguments default to the standard
    JAX coordination env vars (JAX_COORDINATOR_ADDRESS etc. / TPU metadata).
    Must run before anything initializes the XLA backend; a duplicate call
    in the same process is ignored.
    """
    if getattr(jax.distributed, "is_initialized", lambda: False)():
        return
    if backend_initialized():
        raise RuntimeError(
            "init_multihost called after an XLA backend was already "
            "initialized in this process: jax.distributed cannot join a "
            "run once single-process devices exist.  Something executed a "
            "jax computation (often a module-import-time jnp.* call — the "
            "MUR005 lint class, `python -m murmura_tpu check`) before the "
            "mesh setup; move it inside a function"
        )
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )


def make_mesh(num_devices: Optional[int] = None) -> Mesh:
    """1-D mesh over the first ``num_devices`` devices, axis name ``nodes``."""
    devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"Requested {num_devices} devices but only {len(devices)} available"
            )
        devices = devices[:num_devices]
    return Mesh(np.array(devices), ("nodes",))


def make_shardings(mesh: Mesh):
    """(node_sharded, replicated) NamedSharding pair for the mesh."""
    return NamedSharding(mesh, P("nodes")), NamedSharding(mesh, P())


def _shard_leading_axis(tree: Any, node_sharding, replicated) -> Any:
    """Sharding pytree: leading-axis 'nodes' on every array leaf, replicating
    scalars and rank-0 leaves."""

    def spec(leaf):
        if hasattr(leaf, "ndim") and leaf.ndim >= 1:
            return node_sharding
        return replicated

    return jax.tree_util.tree_map(spec, tree)


# --------------------------------------------------------------------------
# Param-axis sharding (docs/PERFORMANCE.md "Param-axis sharding"): a third
# mesh axis splits the flattened parameter vector so every [N, P]-shaped
# tensor of the round (the broadcast, the stale cache and pipeline buffers,
# the EF residual / top-k reference, the aggregation output) is resident at
# N x P/shards per device — the ZeRO-style cross-replica weight-update
# sharding of arXiv:2004.13336 applied to the gossip round.  The model
# pytree itself stays node-sharded (training needs each node's full model);
# it is the flat [N, P] aggregation-side state that hits the memory wall
# first, and that is what shards here.
# --------------------------------------------------------------------------


def plan_param_layout(
    num_nodes: int, param_shards: int, n_dev: int
) -> Tuple[int, int, int]:
    """(seed, nodes, param) axis sizes for a param-sharded single-run mesh.

    Largest-dividing-factor fallback (the :func:`make_gang_mesh` policy):
    prefer the full requested ``param_shards`` on the param axis, else the
    largest divisor of it that also divides the device count while leaving
    a node axis that divides ``num_nodes``.  ``param_shards=1`` degrades to
    the plain node layout.  Raises when no factorization fits.
    """
    if param_shards < 1:
        raise ValueError(f"param_shards must be >= 1, got {param_shards}")
    for s in sorted(
        (d for d in range(1, param_shards + 1) if param_shards % d == 0),
        reverse=True,
    ):
        if n_dev % s:
            continue
        nodes_ax = n_dev // s
        if nodes_ax <= num_nodes and num_nodes % nodes_ax == 0:
            return 1, nodes_ax, s
    raise ValueError(
        f"cannot lay {num_nodes} nodes x {param_shards} param shards onto "
        f"{n_dev} devices: no (nodes, param) factorization divides both "
        "axes — adjust tpu.num_devices or tpu.param_shards"
    )


def make_param_mesh(
    num_nodes: int, param_shards: int, num_devices: Optional[int] = None
) -> Mesh:
    """3-D ("seed", "nodes", "param") mesh for a param-sharded single run.

    The seed axis is size 1 (gangs get theirs from :func:`make_gang_mesh`);
    the node and param axes factor the device count by
    :func:`plan_param_layout`.  Every P("nodes")-spec'd consumer of the
    1-D mesh works unchanged on this mesh (absent axes replicate), so the
    orchestrator's sharding helpers are layout-agnostic.
    """
    devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"Requested {num_devices} devices but only {len(devices)} available"
            )
        devices = devices[:num_devices]
    seed_ax, nodes_ax, param_ax = plan_param_layout(
        num_nodes, param_shards, len(devices)
    )
    sel = np.array(devices[: seed_ax * nodes_ax * param_ax])
    return Mesh(
        sel.reshape(seed_ax, nodes_ax, param_ax), ("seed", "nodes", "param")
    )


def mesh_param_shards(mesh: Optional[Mesh]) -> int:
    """Size of the mesh's ``param`` axis (1 when absent or no mesh)."""
    if mesh is None:
        return 1
    return int(dict(zip(mesh.axis_names, mesh.devices.shape)).get("param", 1))


def mesh_node_axis(mesh: Optional[Mesh]) -> int:
    """Size of the mesh's ``nodes`` axis (the whole mesh for legacy
    unnamed consumers passing a 1-D mesh)."""
    if mesh is None:
        return 1
    sizes = dict(zip(mesh.axis_names, mesh.devices.shape))
    return int(sizes.get("nodes", mesh.devices.size))


def flat_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding of a flat [N, P] round tensor on a param-sharded mesh:
    rows over ``nodes``, columns over ``param``."""
    return NamedSharding(mesh, P("nodes", "param"))


# Trace-time ambient scope: (mesh, flat_dim) while a param-sharded round
# program is being traced.  core/rounds.py pins its [N, P] intermediates
# through :func:`constrain_flat`, aggregation/base.py aligns its P-chunk
# loops through :func:`active_param_shards`, and ops/pallas_agg.py picks
# shard-local grids through :func:`active_param_scope` — one context, three
# consumers, zero plumbing through rule signatures.  Off-scope (simulation
# backend, gang vmap, shards=1) every hook is the identity, keeping those
# programs byte-identical (MUR1302).
_PARAM_SCOPE: List[Tuple[Mesh, int]] = []


@contextlib.contextmanager
def param_axis_scope(mesh: Mesh, flat_dim: int):
    """Activate the param-axis trace scope (see module note above)."""
    _PARAM_SCOPE.append((mesh, int(flat_dim)))
    try:
        yield
    finally:
        _PARAM_SCOPE.pop()


def active_param_scope() -> Optional[Tuple[Mesh, int]]:
    """(mesh, flat_dim) of the innermost active scope, or None."""
    return _PARAM_SCOPE[-1] if _PARAM_SCOPE else None


def active_param_shards(p: Optional[int] = None) -> int:
    """Param-shard count of the active scope (1 off-scope).  With ``p``
    given, returns 1 unless the shard count divides ``p`` — callers
    slicing a [*, p] tensor must not assume shard alignment the tensor
    does not have (e.g. the int8 codec's block-padded width)."""
    scope = active_param_scope()
    if scope is None:
        return 1
    shards = mesh_param_shards(scope[0])
    if p is not None and p % shards:
        return 1
    return shards


def constrain_flat(x):
    """Pin a flat [N, P] round tensor to ("nodes", "param") when a
    param-axis scope is active; identity otherwise (and for any value
    whose trailing width is not the scope's flat_dim).  Traced as a no-op
    off-scope, so unsharded programs are byte-identical."""
    scope = active_param_scope()
    if scope is None:
        return x
    mesh, flat_dim = scope
    if getattr(x, "ndim", 0) == 2 and x.shape[-1] == flat_dim:
        return jax.lax.with_sharding_constraint(x, flat_sharding(mesh))
    return x


def constrain_replicated(x):
    """Pin a value REPLICATED across the active param-sharded mesh;
    identity off-scope.

    The one consumer is the round program's RNG draws (core/rounds.py
    ``local_training``): the legacy (non-partitionable) threefry lowering
    is sharding-DEPENDENT — the same key produces different uniforms when
    GSPMD partitions the output over a ("nodes", "param") mesh than on one
    device — so an unpinned draw would give every mesh layout its own
    batch permutations, breaking cross-layout comparability (and the
    shards=1-vs-sharded parity MUR1303 measures).  Replicating the draw
    keeps the bits byte-identical to the unsharded program; the arrays are
    [N, S]-scale (batch schedule), so the cost is noise next to the [N, P]
    state the param axis exists to shard."""
    scope = active_param_scope()
    if scope is None:
        return x
    mesh = scope[0]
    return jax.lax.with_sharding_constraint(x, NamedSharding(mesh, P()))


def state_sharding_specs(tree: Any, mesh: Mesh, flat_dim: int) -> Any:
    """Sharding pytree for param-sharded resident state: [N, flat_dim]
    leaves split ("nodes", "param") — the stale cache, pipeline buffers,
    EF residual and top-k reference — everything else keeps the
    leading-axis ``nodes`` layout of :func:`_shard_leading_axis`."""
    node_s, repl = make_shardings(mesh)
    flat_s = flat_sharding(mesh)

    def spec(leaf):
        if not hasattr(leaf, "ndim") or leaf.ndim == 0:
            return repl
        if leaf.ndim == 2 and leaf.shape[-1] == flat_dim:
            return flat_s
        return node_s

    return jax.tree_util.tree_map(spec, tree)


def _shard_round_fn(
    fn, program, mesh: Mesh, adj_sharding, donate: bool, alive_sharding=None
):
    """Shared jit wrapper for round-shaped programs.

    Both the per-round step and the fused multi-round scan take
    (params, agg_state, key, <adjacency>, compromised, round, data) and
    return (params, agg_state, metrics); only the adjacency argument's
    sharding differs.  Faulted programs (``program.faulted``) take an
    extra per-round alive mask after ``compromised`` whose sharding is
    supplied as ``alive_sharding`` ([N] node-sharded for the step,
    [chunk, N] second-axis-sharded for the fused scan).  Outputs:
    params/agg_state stay node-sharded; the small per-node metrics arrays
    are replicated so the orchestrator's device_get works when the mesh
    spans multiple processes (multi-host: a node-sharded output would span
    non-addressable devices).
    """
    node_ax = mesh_node_axis(mesh)
    if program.num_nodes % node_ax != 0:
        raise ValueError(
            f"num_nodes={program.num_nodes} not divisible by mesh node "
            f"axis {node_ax}"
        )
    node_s, repl = make_shardings(mesh)

    param_ax = mesh_param_shards(mesh)
    if param_ax > 1:
        # Param-sharded layout: the program must have been built with a
        # matching shard count — its flat width is padded to a multiple of
        # program.param_shards, and the mesh axis must divide that pad.
        shards = getattr(program, "param_shards", 1)
        flat_dim = getattr(program, "flat_dim", program.model_dim)
        if shards % param_ax or flat_dim % param_ax:
            raise ValueError(
                f"mesh param axis {param_ax} does not divide the round "
                f"program's param_shards={shards} (flat width {flat_dim}) "
                "— build the program with "
                f"build_round_program(param_shards={param_ax}) (config: "
                "tpu.param_shards) so the flat pad matches the mesh"
            )
        params_s = state_sharding_specs(program.init_params, mesh, flat_dim)
        agg_s = state_sharding_specs(program.init_agg_state, mesh, flat_dim)
        # The [N, P] intermediates inside the round body (own_flat, the
        # broadcast, the aggregation output) are pinned by constrain_flat
        # at trace time — activate the ambient scope around the traced
        # body so rounds.py / aggregation kernels see the layout.
        inner = fn

        def fn(*args):  # murmura: traced
            with param_axis_scope(mesh, flat_dim):
                return inner(*args)

    else:
        params_s = _shard_leading_axis(program.init_params, node_s, repl)
        agg_s = _shard_leading_axis(program.init_agg_state, node_s, repl)
    data_s = _shard_leading_axis(program.data_arrays, node_s, repl)

    in_shardings = [
        params_s,  # params
        agg_s,  # agg_state
        repl,  # rng key
        adj_sharding,  # adjacency (per-round rows or stacked)
        node_s,  # compromised mask
        repl,  # round index
        data_s,  # data dict
    ]
    if program.faulted:
        in_shardings.insert(5, alive_sharding)  # alive mask / alive stack
    return jax.jit(
        fn,
        in_shardings=tuple(in_shardings),
        out_shardings=(params_s, agg_s, repl),
        donate_argnums=(0, 1) if donate else (),
    )


def shard_step(step, program, mesh: Mesh, donate: bool = True):
    """Jit a RoundProgram step with the node axis sharded over ``mesh``.

    Args:
        step: the traced round function (params, agg_state, key, adj,
            compromised, round_idx, data) -> (params, agg_state, metrics)
            — faulted programs take an [N] alive mask after compromised.
        program: RoundProgram (for example structures to derive shardings).
        mesh: 1-D ``nodes`` mesh; program.num_nodes must be divisible by its
            size.

    Returns:
        The compiled step with in/out shardings pinned.
    """
    node_s, _ = make_shardings(mesh)
    adj_s = edge_mask_sharding(mesh) if program.sparse else node_s
    return _shard_round_fn(
        step, program, mesh, adj_s, donate, alive_sharding=node_s
    )


def adj_stack_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding of the fused-dispatch adjacency stack [chunk, N, N]: sharded
    on its *second* axis (each device holds its nodes' rows for every round
    of the chunk).  Shared by :func:`shard_multi_round` and the
    orchestrator's explicit input staging (Network._stage)."""
    return NamedSharding(mesh, P(None, "nodes"))


def edge_mask_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding of the sparse [k, N] per-offset edge mask
    (topology/sparse.py): the node axis is SECOND, the small static offset
    axis replicates — each device holds its nodes' columns of every offset
    row."""
    return NamedSharding(mesh, P(None, "nodes"))


def sparse_adj_stack_sharding(mesh: Mesh) -> NamedSharding:
    """Fused-dispatch sparse edge-mask stack [chunk, k, N]: node axis third."""
    return NamedSharding(mesh, P(None, None, "nodes"))


def shard_multi_round(multi_round, program, mesh: Mesh, donate: bool = True):
    """Jit a fused multi-round scan (core.rounds.build_multi_round) over
    ``mesh`` with the same node-axis layout as :func:`shard_step`.  The
    faulted alive_stack [chunk, N] shares the adj_stack's layout: sharded
    on its second (node) axis."""
    adj_s = (
        sparse_adj_stack_sharding(mesh) if program.sparse
        else adj_stack_sharding(mesh)
    )
    return _shard_round_fn(
        multi_round, program, mesh, adj_s, donate,
        alive_sharding=adj_stack_sharding(mesh),
    )


# --------------------------------------------------------------------------
# Gang-batched execution (core/gang.py): the [B] experiment axis joins the
# mesh as a second dimension.
# --------------------------------------------------------------------------


def make_gang_mesh(
    batch: int, num_nodes: int, num_devices: Optional[int] = None
) -> Mesh:
    """2-D ("seed", "nodes") mesh for a gang of ``batch`` members.

    Layout policy (ISSUE 5): **seed-major** when the whole gang fits —
    ``batch * num_nodes <= devices`` puts every (member, node) pair on its
    own device (maximum parallelism, zero per-member serialization);
    otherwise the largest seed-axis factor that divides both the device
    count and the gang, falling back to a pure node-sharded mesh with the
    seed axis replicated (size 1) — each device then holds all B members of
    its node rows, which is the right layout when N is large and B small.
    """
    devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"Requested {num_devices} devices but only {len(devices)} available"
            )
        devices = devices[:num_devices]
    n_dev = len(devices)
    if batch * num_nodes <= n_dev:
        sel = np.array(devices[: batch * num_nodes])
        return Mesh(sel.reshape(batch, num_nodes), ("seed", "nodes"))
    for s in sorted(range(1, n_dev + 1), reverse=True):
        if n_dev % s == 0 and s <= batch and batch % s == 0:
            if num_nodes % (n_dev // s) == 0:
                return Mesh(
                    np.array(devices).reshape(s, n_dev // s),
                    ("seed", "nodes"),
                )
    raise ValueError(
        f"cannot lay a gang of {batch} members x {num_nodes} nodes onto "
        f"{n_dev} devices: no (seed, nodes) factorization divides both "
        "axes — adjust tpu.num_devices or the gang size"
    )


def plan_gang_param_layout(
    batch: int, num_nodes: int, param_shards: int, n_dev: int
) -> Tuple[int, int, int]:
    """(seed, nodes, param) axis sizes for a param-sharded GANG mesh —
    the sharding x sweep lift (ISSUE 16).

    Same largest-dividing-factor policy as :func:`plan_param_layout`:
    prefer the full requested ``param_shards`` on the param axis (else
    its largest divisor that divides the device count), then lay the
    remaining devices as a ("seed", "nodes") gang plane under the
    :func:`make_gang_mesh` policy — seed-major when the whole gang fits,
    otherwise the largest seed factor whose node remainder divides N.
    Raises when no factorization fits.
    """
    if param_shards < 1:
        raise ValueError(f"param_shards must be >= 1, got {param_shards}")
    for s in sorted(
        (d for d in range(1, param_shards + 1) if param_shards % d == 0),
        reverse=True,
    ):
        if n_dev % s:
            continue
        rem = n_dev // s
        if batch * num_nodes <= rem:
            return batch, num_nodes, s
        for g in sorted(range(1, rem + 1), reverse=True):
            if rem % g == 0 and g <= batch and batch % g == 0:
                if num_nodes % (rem // g) == 0:
                    return g, rem // g, s
    raise ValueError(
        f"cannot lay a gang of {batch} members x {num_nodes} nodes x "
        f"{param_shards} param shards onto {n_dev} devices: no "
        "(seed, nodes, param) factorization divides all three axes — "
        "adjust tpu.num_devices, tpu.param_shards or the gang size"
    )


def make_gang_param_mesh(
    batch: int,
    num_nodes: int,
    param_shards: int,
    num_devices: Optional[int] = None,
) -> Mesh:
    """3-D ("seed", "nodes", "param") mesh for a param-sharded gang —
    :func:`make_gang_mesh` composed with :func:`make_param_mesh`'s param
    role, so the gang's [S, N, P] stacked state shards its trailing flat
    axis too.  ``param_shards=1`` still yields the 3-D mesh (param axis
    size 1), keeping one code path; every P("seed", "nodes")-spec'd
    consumer works unchanged (absent/size-1 axes replicate)."""
    devices = jax.devices()
    if num_devices is not None:
        if num_devices > len(devices):
            raise ValueError(
                f"Requested {num_devices} devices but only {len(devices)} available"
            )
        devices = devices[:num_devices]
    seed_ax, node_ax, param_ax = plan_gang_param_layout(
        batch, num_nodes, param_shards, len(devices)
    )
    sel = np.array(devices[: seed_ax * node_ax * param_ax])
    return Mesh(
        sel.reshape(seed_ax, node_ax, param_ax), ("seed", "nodes", "param")
    )


def _shard_gang_leading(
    tree: Any, mesh: Mesh, flat_dim: Optional[int] = None
) -> Any:
    """Sharding pytree for *stacked* [B, ...] gang state: [B, N, ...]
    leaves split ("seed", "nodes"), [B] per-member leaves split ("seed",),
    rank-0 leaves replicate.  Leaves whose second axis is not the node
    axis (or not divisible by it) stay seed-sharded only.  On a
    param-sharded gang mesh (``flat_dim`` given), [B, N, flat_dim] leaves
    additionally split their trailing flat axis over ("param",)."""
    gang2d = NamedSharding(mesh, P("seed", "nodes"))
    member = NamedSharding(mesh, P("seed"))
    repl = NamedSharding(mesh, P())
    node_ax = mesh.shape["nodes"]
    param_ax = mesh_param_shards(mesh)
    gang3d = (
        NamedSharding(mesh, P("seed", "nodes", "param"))
        if param_ax > 1 else gang2d
    )

    def spec(leaf):
        if not hasattr(leaf, "ndim") or leaf.ndim == 0:
            return repl
        if leaf.ndim >= 2 and leaf.shape[1] % node_ax == 0:
            if (
                flat_dim is not None
                and leaf.ndim == 3
                and leaf.shape[2] == flat_dim
            ):
                return gang3d
            return gang2d
        return member

    return jax.tree_util.tree_map(spec, tree)


def _gang_spec_from_template(
    tree: Any, mesh: Mesh, flat_dim: Optional[int] = None
) -> Any:
    """Sharding pytree for stacked gang inputs derived from the UNSTACKED
    per-member template (program.init_params / init_agg_state /
    data_arrays): a member leaf of rank >= 1 gains the gang axis in front
    ([B, N, ...] -> ("seed", "nodes")); a rank-0 member leaf becomes a [B]
    per-member vector (("seed",)).  On a param-sharded gang mesh
    (``flat_dim`` given), [N, flat_dim] member leaves stack to
    [B, N, flat_dim] split ("seed", "nodes", "param")."""
    gang2d = NamedSharding(mesh, P("seed", "nodes"))
    member = NamedSharding(mesh, P("seed"))
    node_ax = mesh.shape["nodes"]
    param_ax = mesh_param_shards(mesh)
    gang3d = (
        NamedSharding(mesh, P("seed", "nodes", "param"))
        if param_ax > 1 else gang2d
    )

    def spec(leaf):
        leaf = np.asarray(leaf)
        if leaf.ndim >= 1 and leaf.shape[0] % node_ax == 0:
            if (
                flat_dim is not None
                and leaf.ndim == 2
                and leaf.shape[-1] == flat_dim
            ):
                return gang3d
            return gang2d
        return member

    return jax.tree_util.tree_map(spec, tree)


def gang_node_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding of member-shared node-leading arrays (the [N, N] adjacency,
    the [N] alive mask): node rows split over the ``nodes`` axis, values
    replicated along ``seed``."""
    return NamedSharding(mesh, P("nodes"))


def gang_adj_stack_sharding(mesh: Mesh) -> NamedSharding:
    """Fused-dispatch [chunk, N, N] adjacency stack (shared across
    members): sharded on the node (second) axis, replicated along seed."""
    return NamedSharding(mesh, P(None, "nodes"))


def _shard_gang_round_fn(
    vfn, program, batch: int, mesh: Mesh, adj_sharding, donate: bool,
    alive_sharding,
):
    """Jit a vmapped round-shaped gang program with in/out shardings pinned
    — the gang twin of :func:`_shard_round_fn`.  The vmapped signature is
    the single-run one with the stacked args gaining a [B] leading axis
    (params, agg_state, keys, compromised, data) and the member-shared args
    (adjacency, alive, round index) unbatched."""
    seed_ax, node_ax = mesh.shape["seed"], mesh.shape["nodes"]
    if batch % seed_ax != 0:
        raise ValueError(
            f"gang batch={batch} not divisible by mesh seed axis {seed_ax}"
        )
    if program.num_nodes % node_ax != 0:
        raise ValueError(
            f"num_nodes={program.num_nodes} not divisible by mesh node "
            f"axis {node_ax}"
        )
    member = NamedSharding(mesh, P("seed"))
    repl = NamedSharding(mesh, P())
    gang2d = NamedSharding(mesh, P("seed", "nodes"))

    param_ax = mesh_param_shards(mesh)
    flat_dim = None
    if param_ax > 1:
        # Param-sharded gang layout (the sharding x sweep lift): the
        # member program must have been built with a matching shard
        # count, exactly as in :func:`_shard_round_fn`.  Unlike the
        # single-run path there is NO param_axis_scope here — under the
        # gang vmap the [N, P] intermediates carry a leading member axis
        # the scope's rank-2 constraints do not expect; the jit-boundary
        # shardings pin the [B, N, P] layout and GSPMD propagates it
        # through the vmapped body.
        shards = getattr(program, "param_shards", 1)
        flat_dim = getattr(program, "flat_dim", program.model_dim)
        if shards % param_ax or flat_dim % param_ax:
            raise ValueError(
                f"gang mesh param axis {param_ax} does not divide the "
                f"round program's param_shards={shards} (flat width "
                f"{flat_dim}) — build the program with "
                f"build_round_program(param_shards={param_ax}) (config: "
                "tpu.param_shards) so the flat pad matches the mesh"
            )

    params_s = _gang_spec_from_template(program.init_params, mesh, flat_dim)
    agg_s = _gang_spec_from_template(program.init_agg_state, mesh, flat_dim)
    data_s = _gang_spec_from_template(program.data_arrays, mesh)

    in_shardings = [
        params_s,  # stacked params [B, N, ...]
        agg_s,  # stacked agg state
        member,  # per-member rng keys [B, 2]
        adj_sharding,  # shared adjacency (rows or stack)
        gang2d,  # stacked compromised masks [B, N]
        repl,  # round index
        data_s,  # stacked data dict
    ]
    if program.faulted:
        in_shardings.insert(5, alive_sharding)
    return jax.jit(
        vfn,
        in_shardings=tuple(in_shardings),
        out_shardings=(params_s, agg_s, repl),
        donate_argnums=(0, 1) if donate else (),
    )


def shard_gang_step(vstep, program, batch: int, mesh: Mesh, donate: bool = True):
    """Jit the vmapped per-round gang step over a ("seed", "nodes") mesh."""
    return _shard_gang_round_fn(
        vstep, program, batch, mesh, gang_node_sharding(mesh), donate,
        alive_sharding=gang_node_sharding(mesh),
    )


def shard_gang_multi_round(
    vmulti, program, batch: int, mesh: Mesh, donate: bool = True
):
    """Jit the vmapped fused gang scan; the shared [chunk, N, N] adjacency
    stack (and [chunk, N] alive stack) shard on their node axis."""
    return _shard_gang_round_fn(
        vmulti, program, batch, mesh, gang_adj_stack_sharding(mesh), donate,
        alive_sharding=gang_adj_stack_sharding(mesh),
    )


def shard_gang_eval_step(veval, program, batch: int, mesh: Mesh):
    """Jit the vmapped gang eval step; metrics replicate for the same
    multi-host device_get reason as :func:`shard_eval_step`."""
    params_s = _gang_spec_from_template(program.init_params, mesh)
    data_s = _gang_spec_from_template(program.data_arrays, mesh)
    repl = NamedSharding(mesh, P())
    return jax.jit(
        veval,
        in_shardings=(params_s, data_s),
        out_shardings=repl,
    )


def shard_eval_step(eval_step, program, mesh: Mesh):
    """Jit a RoundProgram eval step (params, data) -> metrics over ``mesh``.

    Compiled separately from the train step so the orchestrator only pays
    the full test-set sweep on recorded rounds (``eval_every``).  Metrics
    come out replicated for the same multi-host device_get reason as
    :func:`shard_step`.
    """
    node_s, repl = make_shardings(mesh)
    params_s = _shard_leading_axis(program.init_params, node_s, repl)
    data_s = _shard_leading_axis(program.data_arrays, node_s, repl)
    return jax.jit(
        eval_step,
        in_shardings=(params_s, data_s),
        out_shardings=repl,
    )


# ---------------------------------------------------------------------------
# Composition manifest (murmura_tpu/levers.py; `murmura check --compose`).
# The single source of truth for this lever's cross-feature verdicts —
# guard sites in config/schema.py and utils/factories.py cite
# refusal_reason() so user-facing messages and the analyzer's grid can
# never drift apart (MUR1400).
# ---------------------------------------------------------------------------
from murmura_tpu.levers import LeverManifest, composes, refuses

LEVER_MANIFEST = LeverManifest(
    name="sharding",
    module="murmura_tpu.parallel.mesh",
    mesh_axes=("param",),
    verdicts={
        "adaptive": composes(),
        "compression": composes(
            topk=(
                "tpu.param_shards does not compose with compression."
                "algorithm: topk (the per-row global top-k needs the "
                "full [P] row resident on one device, defeating the "
                "shard); use the int8 codec — its per-block scales "
                "shard with P"
            ),
            int8_block=(
                "a quant block straddling a shard boundary would "
                "compute its scale across shards; pick a block that "
                "divides the shard-local width"
            ),
        ),
        "dmtt": refuses(
            "tpu.param_shards does not compose with dmtt (the N x N "
            "claim cross-evaluation unravels every broadcast row into "
            "a full model per pair — there is no sharded formulation "
            "of that sweep)"
        ),
        "faults": composes(),
        "mobility": composes(),
        "pipeline": composes(),
        "population": refuses(
            "tpu.param_shards does not compose with population yet "
            "(the memmapped user bank stages full [P] rows per cohort "
            "swap; a sharded bank is ROADMAP item 5's sharded-bank "
            "leg)"
        ),
    },
)

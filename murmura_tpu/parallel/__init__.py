"""Device-mesh parallelism for the ``tpu`` backend."""

from murmura_tpu.parallel.mesh import make_mesh, make_shardings, shard_step

__all__ = ["make_mesh", "make_shardings", "shard_step"]

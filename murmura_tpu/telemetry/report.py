"""``murmura report <run_dir>``: render a run manifest + event stream.

Reads only the telemetry schema (schema.py) — any producer's run directory
works: a CLI run, a Monitor-folded distributed run, or a bench artifact.
Sections render only when their data exists, so a minimal manifest still
produces a useful summary instead of a wall of empty tables.
"""

import math
from typing import Any, Dict, List, Optional

from murmura_tpu.telemetry.schema import KIND_BENCH
from murmura_tpu.telemetry.writer import iter_events, read_manifest


def _fmt(v: Any, nd: int = 4) -> str:
    if isinstance(v, float):
        if math.isnan(v):
            return "nan"
        return f"{v:.{nd}f}"
    return str(v)


def _mean(xs: List[float]) -> float:
    finite = [x for x in xs if isinstance(x, (int, float)) and math.isfinite(x)]
    return sum(finite) / len(finite) if finite else float("nan")


def build_report(run_dir) -> Dict[str, Any]:
    """Machine-readable report dict (the renderer's single source; tests
    assert on this instead of scraping table text)."""
    manifest = read_manifest(run_dir)
    if manifest is None:
        raise FileNotFoundError(
            f"no readable manifest.json under {run_dir} — not a telemetry "
            "run directory (docs/OBSERVABILITY.md)"
        )
    events = list(iter_events(run_dir))
    report: Dict[str, Any] = {"manifest": manifest, "run_dir": str(run_dir)}

    history = manifest.get("history") or {}
    if history.get("round"):
        finite_acc = [
            a for a in history["mean_accuracy"]
            if isinstance(a, (int, float)) and math.isfinite(a)
        ]
        acc: Dict[str, Any] = {
            "rounds_recorded": len(history["round"]),
            "final_round": history["round"][-1],
            "final_mean_accuracy": history["mean_accuracy"][-1],
            # max over finite entries only: a partial-flush NaN row (an
            # all-skipped distributed round) must not poison the best.
            "best_mean_accuracy": max(finite_acc, default=float("nan")),
            "final_mean_loss": history["mean_loss"][-1],
        }
        if history.get("honest_accuracy"):
            acc["final_honest_accuracy"] = history["honest_accuracy"][-1]
        if history.get("compromised_accuracy"):
            acc["final_compromised_accuracy"] = history["compromised_accuracy"][-1]
        report["accuracy"] = acc

        robustness = {
            k: {"mean": _mean(v), "last": v[-1] if v else None}
            for k, v in history.items()
            if k.startswith("agg_") and not k.startswith("agg_tap_")
        }
        for k in ("skipped_nodes", "reporting_nodes"):
            if history.get(k):
                robustness[k] = {"mean": _mean(history[k]), "last": history[k][-1]}
        if robustness:
            report["robustness"] = robustness

    # ---- time breakdown -------------------------------------------------
    phase = [e for e in events if e.get("type") == "phase_times"]
    if phase:
        by_mode: Dict[str, List[float]] = {}
        for e in phase:
            by_mode.setdefault(e.get("mode", "?"), []).append(e.get("wall_s", 0.0))
        report["time"] = {
            "rounds_timed": len(phase),
            "total_s": sum(sum(v) for v in by_mode.values()),
            "by_mode": {
                m: {
                    "rounds": len(v),
                    "mean_s": _mean(v),
                    "max_s": max(v),
                }
                for m, v in by_mode.items()
            },
        }
        # Critical-path decomposition under overlap (exchange.pipeline;
        # docs/PERFORMANCE.md "Pipelined rounds"): pipelined rounds run
        # train and the delayed exchange+aggregate CONCURRENTLY inside
        # one dispatch, so each wall_s above is the round's critical
        # path and the per-phase named_scope brackets (murmura.train /
        # murmura.aggregate) overlap in profiler-trace time — a
        # per-phase sum would double-count the hidden exchange.  This
        # section makes the overlap explicit instead of letting readers
        # add brackets; serialized runs (no ``overlap`` marker) emit no
        # section and their time report is byte-identical to previous
        # releases (pinned by tests/test_pipeline.py).
        overlapped = [e for e in phase if e.get("overlap")]
        if overlapped:
            walls = [e.get("wall_s", 0.0) for e in overlapped]
            report["time"]["critical_path"] = {
                "overlap": overlapped[0].get("overlap"),
                "rounds": len(overlapped),
                "mean_s": _mean(walls),
                "total_s": sum(walls),
                "concurrent_phases": [
                    "murmura.train",
                    "murmura.aggregate (delayed, round r-1)",
                ],
                "note": (
                    "wall_s is the per-round critical path; the "
                    "exchange+aggregate bracket runs concurrently with "
                    "training and must not be added to it (see "
                    "bench_breakdown's pipeline hidden-fraction cells "
                    "for the overlapped segment's size)"
                ),
            }
    ckpt = [e for e in events if e.get("type") == "checkpoint"]
    if ckpt:
        saves = [e for e in ckpt if e.get("action") == "save"]
        report["checkpoints"] = {
            "saves": len(saves),
            "restores": len(ckpt) - len(saves),
            "total_save_s": sum(e.get("duration_s", 0.0) for e in saves),
        }
    mem = [
        e for e in events
        if e.get("type") == "memory" and isinstance(e.get("stats"), dict)
    ]
    if mem:
        peaks = [
            e["stats"].get("peak_bytes_in_use") or e["stats"].get("bytes_in_use")
            for e in mem
        ]
        peaks = [p for p in peaks if isinstance(p, (int, float))]
        if peaks:
            report["memory"] = {
                "samples": len(mem),
                "peak_bytes_in_use": max(peaks),
                "device_kind": mem[-1].get("device_kind"),
            }
    prof = [e for e in events if e.get("type") == "profile"]
    if prof:
        report["profile"] = prof

    # ---- faults (per-node quarantine/alive from round events) -----------
    rounds = [e for e in events if e.get("type") == "round"]
    faults: Dict[str, Any] = {}
    for key, out in (
        ("agg_tap_quarantined", "quarantined_rounds"),
        ("agg_tap_attack_scrubbed", "scrubbed_rounds"),
        ("agg_tap_alive", "alive_rounds"),
    ):
        per_node = _per_node_sum(rounds, key)
        if per_node is not None:
            faults[out] = per_node
    if faults:
        report["faults"] = faults

    # ---- audit taps: per-node acceptance/rejection ----------------------
    taps = _tap_report(rounds)
    if taps:
        report["taps"] = taps

    # ---- bounded staleness (core/stale.py; docs/ROBUSTNESS.md) ----------
    # ``agg_tap_stale_used`` counts, per round, how many of node i's
    # in-edges were served from the payload cache; ``agg_tap_stale_age``
    # is the age of each SERVED sender's cached payload (0 = fresh or
    # unserved).  The histogram answers "how stale did the exchange
    # actually run" next to the configured max_staleness bound.
    stale = _stale_report(rounds)
    if stale:
        report["staleness"] = stale

    # ---- declared influence contract ------------------------------------
    # The rule's InfluenceDecl (aggregation/base.py; verified statically by
    # `murmura check --flow` MUR800-802) doubles as runtime documentation:
    # rendered next to the observed audit-tap rejection counts so "how much
    # could a bad neighbor have moved me" sits beside "who actually got
    # rejected".
    influence = _declared_influence(manifest)
    if influence:
        report["influence"] = influence

    counters = manifest.get("counters") or {}
    if counters:
        report["counters"] = counters
    if manifest.get("kind") == KIND_BENCH:
        report["bench"] = manifest.get("summary") or {}
    return report


def _declared_influence(manifest: dict) -> Optional[Dict[str, Any]]:
    """The configured rule's declared Byzantine influence contract, built
    from the manifest's config snapshot.  Best-effort: bench manifests and
    pre-influence runs have no (usable) aggregation config."""
    cfg = manifest.get("config") or {}
    agg_cfg = cfg.get("aggregation") or {}
    algo = agg_cfg.get("algorithm")
    if not algo:
        return None
    try:
        from murmura_tpu.aggregation import build_aggregator

        agg = build_aggregator(
            algo, dict(agg_cfg.get("params") or {}), model_dim=1,
            total_rounds=1,
        )
    except Exception:  # noqa: BLE001 — stale config snapshots stay renderable
        return None
    decl = agg.influence
    if decl is None:
        return None
    return {
        "rule": algo,
        "kind": decl.kind,
        "declared": decl.describe(),
        "note": decl.note,
    }


def _per_node_sum(rounds: List[dict], key: str) -> Optional[List[float]]:
    rows = [
        e["metrics"][key] for e in rounds
        if isinstance(e.get("metrics"), dict)
        and isinstance(e["metrics"].get(key), list)
    ]
    if not rows:
        return None
    n = max(len(r) for r in rows)
    out = [0.0] * n
    for r in rows:
        for i, v in enumerate(r):
            if isinstance(v, (int, float)) and math.isfinite(v):
                out[i] += v
    return out


def _stale_report(rounds: List[dict]) -> Optional[Dict[str, Any]]:
    """Per-node stale-edge totals + the served-age histogram from the
    bounded-staleness audit taps (agg_tap_stale_used / agg_tap_stale_age
    — core/stale.py)."""
    used = _per_node_sum(rounds, "agg_tap_stale_used")
    if used is None:
        return None
    out: Dict[str, Any] = {
        "stale_in_edges": used,
        "total_stale_edges": sum(used),
    }
    hist: Dict[str, int] = {}
    for e in rounds:
        metrics = e.get("metrics")
        row = metrics.get("agg_tap_stale_age") if isinstance(metrics, dict) else None
        if not isinstance(row, list):
            continue
        for a in row:
            if isinstance(a, (int, float)) and math.isfinite(a) and a > 0:
                hist[str(int(a))] = hist.get(str(int(a)), 0) + 1
    if hist:
        out["age_histogram"] = dict(sorted(hist.items(), key=lambda kv: int(kv[0])))
    return out


def _tap_report(rounds: List[dict]) -> Optional[Dict[str, Any]]:
    """Per-node selection/rejection totals from the in-jit audit taps.

    ``agg_tap_selected_by`` counts, per round, how many peers selected or
    accepted node i's broadcast; ``agg_tap_considered_by`` how many peers
    had it as a candidate (the round's effective in-degree under faults).
    Rejections = considered - selected, summed over recorded rounds — the
    "why did the Byzantine rule reject node 3" view (docs/OBSERVABILITY.md).
    """
    selected = _per_node_sum(rounds, "agg_tap_selected_by")
    if selected is None:
        return None
    considered = _per_node_sum(rounds, "agg_tap_considered_by")
    out: Dict[str, Any] = {"selected_by": selected}
    if considered is not None:
        out["considered_by"] = considered
        out["rejections"] = [
            max(0.0, c - s) for c, s in zip(considered, selected)
        ]
    return out


# ----------------------------------------------------------------------
# rendering


def render_report(run_dir, console=None) -> Dict[str, Any]:
    """Render the report with rich; returns the report dict."""
    from rich.console import Console
    from rich.table import Table

    console = console or Console()
    report = build_report(run_dir)
    m = report["manifest"]
    cfg = m.get("config") or {}
    exp = cfg.get("experiment") or {}
    console.print(
        f"[bold cyan]murmura report[/bold cyan] — run "
        f"[bold]{exp.get('name', m.get('run_id'))}[/bold] "
        f"(kind={m.get('kind')}, schema=v{m.get('schema_version')}, "
        f"run_id={m.get('run_id')}, "
        f"{'finalized' if m.get('finalized') else 'IN PROGRESS'})"
    )

    def kv_table(title: str, mapping: Dict[str, Any]) -> None:
        t = Table(title=title)
        t.add_column("metric", style="cyan")
        t.add_column("value", justify="right")
        for k, v in mapping.items():
            t.add_row(k, _fmt(v))
        console.print(t)

    if "accuracy" in report:
        kv_table("Accuracy", report["accuracy"])
    if "robustness" in report:
        t = Table(title="Robustness / rule statistics (over recorded rounds)")
        t.add_column("stat", style="cyan")
        t.add_column("mean", justify="right")
        t.add_column("last", justify="right")
        for k, v in sorted(report["robustness"].items()):
            t.add_row(k, _fmt(v["mean"]), _fmt(v["last"]))
        console.print(t)
    if "time" in report:
        t = Table(title="Time breakdown")
        t.add_column("dispatch mode", style="cyan")
        t.add_column("rounds", justify="right")
        t.add_column("mean s/round", justify="right")
        t.add_column("max s", justify="right")
        for mode, v in report["time"]["by_mode"].items():
            t.add_row(mode, str(v["rounds"]), _fmt(v["mean_s"]), _fmt(v["max_s"]))
        console.print(t)
        console.print(
            f"  total timed: {_fmt(report['time']['total_s'], 2)}s over "
            f"{report['time']['rounds_timed']} round records"
        )
        cp = report["time"].get("critical_path")
        if cp:
            console.print(
                f"  [cyan]critical path[/cyan] ({cp['overlap']}): "
                f"{cp['rounds']} rounds at {_fmt(cp['mean_s'])}s/round — "
                f"{' + '.join(cp['concurrent_phases'])} run "
                "concurrently; per-phase brackets must not be summed"
            )
    if "checkpoints" in report:
        kv_table("Checkpoints", report["checkpoints"])
    if "memory" in report:
        kv_table("Device memory", report["memory"])
    if "influence" in report:
        inf = report["influence"]
        console.print(
            f"  [cyan]declared influence[/cyan] ({inf['rule']}): "
            f"{inf['declared']}"
        )
    if "staleness" in report:
        stale = report["staleness"]
        hist = stale.get("age_histogram") or {}
        hist_txt = (
            "  ages " + "  ".join(
                f"{a}r:{c}" for a, c in hist.items()
            )
            if hist else ""
        )
        console.print(
            f"  [cyan]bounded staleness[/cyan]: "
            f"{_fmt(stale['total_stale_edges'], 0)} stale edge-serves "
            f"over recorded rounds{hist_txt}"
        )
    if "taps" in report or "faults" in report or "staleness" in report:
        taps = report.get("taps") or {}
        faults = report.get("faults") or {}
        stale_cols = {
            k: v for k, v in (report.get("staleness") or {}).items()
            if k == "stale_in_edges"
        }
        n = max(
            [len(v) for v in taps.values()]
            + [len(v) for v in faults.values()]
            + [len(v) for v in stale_cols.values()]
        )
        t = Table(title="Per-node audit (totals over recorded rounds)")
        t.add_column("node", justify="right")
        cols = []
        for key, src in (
            ("selected_by", taps), ("considered_by", taps),
            ("rejections", taps), ("quarantined_rounds", faults),
            ("scrubbed_rounds", faults), ("alive_rounds", faults),
            ("stale_in_edges", stale_cols),
        ):
            if key in src:
                t.add_column(key, justify="right")
                cols.append(src[key])
        for i in range(n):
            t.add_row(
                str(i), *[_fmt(c[i], 1) if i < len(c) else "-" for c in cols]
            )
        console.print(t)
    if "counters" in report:
        kv_table("Distributed counters", report["counters"])
    if "bench" in report:
        flat = {
            k: v for k, v in report["bench"].items()
            if isinstance(v, (int, float, str)) or v is None
        }
        kv_table("Bench summary", {k: "null" if v is None else v for k, v in flat.items()})
    extra = [e for e in iter_events(run_dir) if e.get("type") == "extra"]
    if extra:
        console.print(
            f"[yellow]{len(extra)} forward-compat 'extra' event(s) — keys "
            "this version does not understand were preserved, not "
            "dropped[/yellow]"
        )
    return report


# ----------------------------------------------------------------------
# frontier rendering (`murmura report --frontier`; docs/ROBUSTNESS.md
# "The robustness frontier")


def _bar(frac: float, width: int = 16) -> str:
    """Accuracy-fraction bar for the curve rows (unicode blocks)."""
    if not math.isfinite(frac):
        return "?" * width
    filled = int(round(max(0.0, min(1.0, frac)) * width))
    return "█" * filled + "·" * (width - filled)


def render_frontier(artifact: Dict[str, Any], console=None) -> None:
    """Render a ``frontier.json`` artifact (murmura_tpu/frontier.py): one
    summary table of empirical breaking point vs MUR800 declared bound
    per (rule x attack x topology) cell, then each cell's honest-accuracy
    curve over attack strength.

    The two columns to read together: ``declared`` is what the flow
    analyzer PROVES the rule can admit per coordinate (`murmura check
    --flow`, MUR800); ``broken at`` is where a closed-loop adversary
    actually pushed the rule off its honest-accuracy cliff.  A bounded
    rule breaking at low strength is a robustness gap the static bound
    cannot see; an unbounded rule holding to high strength is averaging
    luck, not a guarantee.
    """
    from rich.console import Console
    from rich.table import Table

    from murmura_tpu.frontier import frontier_break_summary

    console = console or Console()
    grid = artifact.get("grid") or {}
    console.print(
        f"[bold cyan]murmura frontier[/bold cyan] — "
        f"[bold]{artifact.get('experiment', '?')}[/bold] "
        f"(nodes={grid.get('num_nodes', '?')}, "
        f"rounds={grid.get('rounds', '?')}, seeds={grid.get('seeds', '?')}, "
        f"break < {grid.get('break_fraction', '?')} x benign)"
    )
    t = Table(title="Breaking point vs declared influence bound (per cell)")
    t.add_column("rule", style="cyan")
    t.add_column("attack")
    t.add_column("topology")
    t.add_column("pct", justify="right")
    t.add_column("deg", justify="right")
    t.add_column("benign acc", justify="right")
    t.add_column("held ≤", justify="right")
    t.add_column("broken at", justify="right")
    t.add_column("declared (MUR800)")
    t.add_column("compiles", justify="right")
    for row in frontier_break_summary(artifact):
        held = row["last_held"]
        broken = row["first_broken"]
        kind = row["declared_kind"]
        # Compact contract cell; the full InfluenceDecl.describe() text
        # stays in the artifact's declared_influence payload.
        declared = (
            "undeclared" if kind is None
            else f"bounded ≤ {row['declared_bound']}" if kind == "bounded"
            else str(kind)
        )
        pct = row.get("percentage")
        t.add_row(
            str(row["rule"]), str(row["attack"]), str(row["topology"]),
            "-" if pct is None else f"{pct:g}",
            str(row["degree"]), _fmt(row["benign_accuracy"], 3),
            "-" if held is None else f"{held:.3g}",
            "[bold red]never[/bold red]" if broken is None
            else f"[bold]{broken:.3g}[/bold]",
            declared,
            str(row["compiles"]),
        )
    console.print(t)
    for cell in artifact.get("cells", []):
        benign = cell.get("benign_accuracy") or float("nan")
        title = (
            f"{cell['rule']} x {cell['attack']} x {cell['topology']} — "
            f"honest accuracy vs strength (benign {_fmt(benign, 3)})"
        )
        ct = Table(title=title)
        ct.add_column("strength", justify="right")
        ct.add_column("mean acc", justify="right")
        ct.add_column("std", justify="right")
        ct.add_column("vs benign")
        ct.add_column("attacker state")
        for row in cell.get("curve", []):
            frac = (
                row["mean"] / benign
                if benign and math.isfinite(benign) and benign > 0
                else float("nan")
            )
            adaptive = row.get("adaptive") or {}
            summary = ""
            if adaptive:
                # Mean converged state over seeds: the attacker's own
                # account of the margin it found (atk_lo / atk_z).
                keys = sorted({k for d in adaptive.values() for k in d})
                show = [
                    k for k in ("atk_lo", "atk_z", "atk_accept_ema")
                    if k in keys
                ]
                summary = "  ".join(
                    f"{k}={_fmt(_mean([d.get(k, float('nan')) for d in adaptive.values()]), 2)}"
                    for k in show
                )
            ct.add_row(
                f"{row['strength']:.3g}", _fmt(row["mean"], 3),
                _fmt(row.get("std", float("nan")), 3), _bar(frac), summary,
            )
        console.print(ct)


def render_grid(artifact: Dict[str, Any], console=None) -> None:
    """Render a ``grid.json`` manifest (murmura_tpu/serve/scheduler.py):
    one bucket table (cells per compile-compatible bucket, its ONE
    compile, wall time), then the per-cell accuracy grid.

    The number to read first is ``total_compiles`` vs ``total_cells``:
    the scheduler's whole job is making the first much smaller than the
    second (the README 50-cell grid runs in 5 compiles).  A bucket whose
    ``compiles`` exceeds 1 means a cell smuggled a structural difference
    past the skeleton key — exactly what `murmura check --serve`
    (MUR1600/1601) exists to refuse.
    """
    from rich.console import Console
    from rich.table import Table

    console = console or Console()
    grid = artifact.get("grid") or {}
    console.print(
        f"[bold cyan]murmura grid[/bold cyan] — "
        f"[bold]{artifact.get('experiment', '?')}[/bold] "
        f"(nodes={grid.get('num_nodes', '?')}, "
        f"rounds={grid.get('rounds', '?')}, seeds={grid.get('seeds', '?')}): "
        f"[bold]{artifact.get('total_cells', '?')}[/bold] cells in "
        f"[bold]{len(artifact.get('buckets', []))}[/bold] buckets, "
        f"[bold]{artifact.get('total_compiles', '?')}[/bold] compiles"
    )
    bt = Table(title="Compile-compatible buckets (one gang = one compile)")
    bt.add_column("bucket", style="cyan")
    bt.add_column("rule")
    bt.add_column("attack")
    bt.add_column("topology")
    bt.add_column("cells", justify="right")
    bt.add_column("lanes", justify="right")
    bt.add_column("compiles", justify="right")
    bt.add_column("wall s", justify="right")
    for b in artifact.get("buckets", []):
        compiles = b.get("compiles")
        bt.add_row(
            str(b.get("key")), str(b.get("rule")), str(b.get("attack")),
            str(b.get("topology")), str(len(b.get("cells", []))),
            f"{b.get('gang_size', '?')}/{b.get('batch', '?')}",
            f"[bold red]{compiles}[/bold red]"
            if (compiles or 0) > 1 else str(compiles),
            _fmt(b.get("wall_s", float("nan")), 2),
        )
    console.print(bt)
    ct = Table(title="Cells (accuracy by rule x attack x strength x seed)")
    ct.add_column("cell", style="cyan")
    ct.add_column("bucket")
    ct.add_column("strength", justify="right")
    ct.add_column("seed", justify="right")
    ct.add_column("final acc", justify="right")
    ct.add_column("honest acc", justify="right")
    ct.add_column("mean round s", justify="right")
    for c in artifact.get("cells", []):
        phase = c.get("phase_times") or {}
        ct.add_row(
            str(c.get("id")), str(c.get("bucket")),
            f"{c.get('strength', float('nan')):g}", str(c.get("seed")),
            _fmt(c.get("final_accuracy"), 3),
            _fmt(c.get("honest_accuracy"), 3),
            _fmt(phase.get("mean_round_s", float("nan")), 3),
        )
    console.print(ct)

"""The cross-run registry (ISSUE 19 leg 3): one index over every run
the repo has ever produced.

``murmura runs [roots...]`` walks telemetry roots (default:
``telemetry_runs/``) plus any serve state dirs it finds, and emits one
row per run directory / ledger submission: kind, run id, schema
version, config fingerprint (the serve scheduler's structural
fingerprint, so "which runs shared a compiled bucket" is answerable
offline), platform stamp, rounds, best accuracy, and terminal state —
with torn/stale event streams flagged instead of hidden.  ``murmura
report --latest`` is sugar over :func:`find_latest`.

Read-only by construction: the index opens manifests/ledgers/streams
and never writes.
"""

import json
from pathlib import Path
from typing import Any, Dict, List, Optional

from murmura_tpu.telemetry.schema import EVENTS_FILE, MANIFEST_FILE


def _torn_tail(run_dir: Path) -> bool:
    """Whether events.jsonl ends in a torn (unparseable) line."""
    path = run_dir / EVENTS_FILE
    try:
        raw = path.read_bytes()
    except OSError:
        return False
    if not raw.strip():
        return False
    last = raw.strip().rsplit(b"\n", 1)[-1]
    try:
        json.loads(last.decode("utf-8"))
        return False
    except (UnicodeDecodeError, json.JSONDecodeError):
        return True


def _best_accuracy(history: Optional[Dict[str, Any]]) -> Optional[float]:
    if not isinstance(history, dict):
        return None
    series = history.get("honest_accuracy") or history.get("mean_accuracy")
    try:
        return max(float(v) for v in series) if series else None
    except (TypeError, ValueError):
        return None


def _fingerprint(config: Optional[Dict[str, Any]]) -> Optional[str]:
    if not isinstance(config, dict):
        return None
    try:
        from murmura_tpu.config.schema import Config
        from murmura_tpu.serve.scheduler import structural_fingerprint

        return structural_fingerprint(Config.model_validate(config))
    except Exception:  # noqa: BLE001 — old/partial configs index as None
        return None


def index_run_dir(run_dir) -> Dict[str, Any]:
    """One index row for one run directory."""
    from murmura_tpu.telemetry.writer import iter_events, read_manifest

    run_dir = Path(run_dir)
    manifest = read_manifest(run_dir)
    events = list(iter_events(run_dir))
    rounds = sum(1 for e in events if e.get("type") == "round")
    if manifest is None:
        status = "no-manifest"
    elif manifest.get("finalized"):
        status = "finalized"
    else:
        status = "in-progress"
    manifest = manifest or {}
    return {
        "path": str(run_dir),
        "kind": manifest.get("kind"),
        "run_id": manifest.get("run_id"),
        "schema_version": manifest.get("schema_version"),
        "created_unix": manifest.get("created_unix"),
        "platform": (
            (manifest.get("summary") or {}).get("platform")
            or (manifest.get("config") or {}).get("backend")
        ),
        "fingerprint": _fingerprint(manifest.get("config")),
        "rounds": rounds,
        "best_accuracy": _best_accuracy(manifest.get("history")),
        "status": status,
        "torn_tail": _torn_tail(run_dir),
        "num_events": len(events),
    }


def index_submission(record_path) -> Dict[str, Any]:
    """One index row for one serve-ledger submission record."""
    record_path = Path(record_path)
    try:
        rec = json.loads(record_path.read_text(encoding="utf-8"))
    except (OSError, json.JSONDecodeError):
        return {
            "path": str(record_path), "kind": "submission",
            "status": "unreadable", "torn_tail": True,
        }
    return {
        "path": str(record_path),
        "kind": "submission",
        "run_id": rec.get("id"),
        "schema_version": None,
        "created_unix": rec.get("submitted_at"),
        "platform": (rec.get("config") or {}).get("backend"),
        "fingerprint": rec.get("fingerprint"),
        "rounds": rec.get("rounds"),
        "best_accuracy": _best_accuracy(rec.get("history")),
        "status": rec.get("state"),
        "torn_tail": False,
        "num_events": None,
    }


def index_runs(roots) -> List[Dict[str, Any]]:
    """Walk ``roots`` and index every run directory and serve ledger.

    A run directory is any directory holding a manifest or event stream;
    a serve state dir is recognized by its ``submissions/`` ledger.
    Rows sort newest-first (unknown creation time last)."""
    rows: List[Dict[str, Any]] = []
    seen: set = set()
    for root in roots:
        root = Path(root)
        if not root.exists():
            continue
        candidates = [root] + [p for p in root.rglob("*") if p.is_dir()]
        for d in candidates:
            if d in seen:
                continue
            if (d / MANIFEST_FILE).exists() or (d / EVENTS_FILE).exists():
                seen.add(d)
                rows.append(index_run_dir(d))
            if d.name == "submissions" and d.is_dir():
                for rec in sorted(d.glob("*.json")):
                    if rec in seen:
                        continue
                    seen.add(rec)
                    rows.append(index_submission(rec))
    rows.sort(
        key=lambda r: (r.get("created_unix") is None,
                       -(r.get("created_unix") or 0.0), r["path"])
    )
    return rows


def find_latest(roots) -> Optional[Dict[str, Any]]:
    """The newest indexed run DIRECTORY (submissions are ledger rows,
    not reportable dirs) — ``murmura report --latest``."""
    for row in index_runs(roots):
        if row["kind"] != "submission" and row.get("created_unix"):
            return row
    return None


def render_rows(rows: List[Dict[str, Any]]) -> str:
    """Plain-text table of index rows (the --json twin is the raw list)."""
    headers = ("run_id", "kind", "status", "rounds", "best_acc",
               "platform", "schema", "torn", "path")
    table: List[List[str]] = [list(headers)]
    for r in rows:
        acc = r.get("best_accuracy")
        table.append([
            str(r.get("run_id") or "-"),
            str(r.get("kind") or "-"),
            str(r.get("status") or "-"),
            str(r.get("rounds") if r.get("rounds") is not None else "-"),
            f"{acc:.4f}" if isinstance(acc, float) else "-",
            str(r.get("platform") or "-"),
            str(r.get("schema_version") or "-"),
            "TORN" if r.get("torn_tail") else "",
            str(r.get("path")),
        ])
    widths = [max(len(row[i]) for row in table) for i in range(len(headers))]
    lines = [
        "  ".join(cell.ljust(w) for cell, w in zip(row, widths)).rstrip()
        for row in table
    ]
    lines.insert(1, "  ".join("-" * w for w in widths))
    return "\n".join(lines)

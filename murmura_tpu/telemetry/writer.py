"""Telemetry writer/reader: the one path every producer emits through.

``TelemetryWriter`` owns a run directory holding the versioned manifest and
the append-only JSONL event stream (schema.py).  Design constraints:

- **Crash-safe**: events append line-at-a-time (a crash loses at most the
  in-flight line); the manifest is only ever replaced atomically via
  :func:`murmura_tpu.utils.checkpoint.durable_replace` — the same fsync'd
  temp-file + rename + directory-fsync path the checkpoints use, so a
  half-written manifest is impossible.
- **Resumable**: reopening an existing run directory appends to the event
  stream (the checkpoint/restore path keeps one stream per run) and marks
  the manifest ``resumed``.
- **jax-free at import**: bench scripts construct writers before deciding
  which backend they run on; only :meth:`memory_event` touches jax, lazily.
"""

import json
import os
import time
import uuid
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from murmura_tpu.telemetry.schema import (
    EVENTS_FILE,
    KIND_BENCH,
    KIND_RUN,
    MANIFEST_FILE,
    MANIFEST_SCHEMA_VERSION,
)
from murmura_tpu.utils.checkpoint import durable_replace


def _jsonable(value: Any) -> Any:
    """Recursively convert numpy/jax leaves to plain JSON types."""
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    if isinstance(value, np.ndarray):
        return _jsonable(value.tolist())
    if isinstance(value, (np.floating, np.integer, np.bool_)):
        return value.item()
    if hasattr(value, "tolist") and not isinstance(value, (str, bytes)):
        # jax arrays (and anything array-like) without importing jax here.
        return _jsonable(np.asarray(value).tolist())
    # Non-finite floats stay floats: Python's json emits/accepts NaN and
    # Infinity literals, so manifest histories round-trip with full
    # fidelity (a partial-flush NaN row must not come back as a string).
    return value


class TelemetryWriter:
    """Manifest + event-stream writer for one run directory.

    Args:
        run_dir: directory to create/append; one run per directory.
        kind: ``"run"`` or ``"bench"`` (schema.py).
        run_id: stable id across resumes; generated when omitted.
        config: optional validated Config — snapshotted (``model_dump``)
            into the manifest so a report is self-describing.
        record_taps: host-side toggle for per-node ``agg_tap_*`` arrays in
            round events.  Purely a recording decision — the compiled round
            program is identical either way (MUR402, analysis/ir.py).
        resume: the caller is CONTINUING a prior run in this directory
            (checkpoint restore, crash recovery): append to the existing
            event stream, keep its run_id/counters, mark the manifest
            ``resumed``.  False (default): a pre-existing stream is a
            STALE run — it is rotated to ``*.prev`` (one generation kept)
            so re-running an experiment into the same deterministic dir
            never double-counts events in ``murmura report``.
        memory_stats: sample per-round device memory into ``memory`` events.
        profile_dir / profile_start_round / profile_rounds: the profiler
            trace window ``murmura run --profile`` captures
            (core/network.py drives start/stop at round boundaries).
    """

    def __init__(
        self,
        run_dir,
        *,
        kind: str = KIND_RUN,
        run_id: Optional[str] = None,
        config=None,
        record_taps: bool = True,
        phase_times: bool = True,
        memory_stats: bool = False,
        profile_dir: Optional[str] = None,
        profile_start_round: int = 0,
        profile_rounds: int = 0,
        resume: bool = False,
    ):
        self.run_dir = Path(run_dir)
        self.run_dir.mkdir(parents=True, exist_ok=True)
        self.kind = kind
        self.record_taps = record_taps
        self.record_phase_times = phase_times
        self.memory_stats = memory_stats
        self.profile_dir = profile_dir
        self.profile_start_round = int(profile_start_round)
        self.profile_rounds = int(profile_rounds)

        events_path = self.run_dir / EVENTS_FILE
        has_prior = events_path.exists() and events_path.stat().st_size > 0
        if has_prior and not resume:
            # A fresh run into an existing dir: rotate the stale stream
            # (keep one generation) instead of appending — otherwise every
            # re-run of a deterministically-named experiment doubles the
            # report's event sums.
            os.replace(events_path, self.run_dir / (EVENTS_FILE + ".prev"))
            mpath = self.run_dir / MANIFEST_FILE
            if mpath.exists():
                os.replace(mpath, self.run_dir / (MANIFEST_FILE + ".prev"))
        resumed = has_prior and resume
        existing = read_manifest(self.run_dir) if resumed else None
        if run_id is None:
            run_id = (existing or {}).get("run_id") or uuid.uuid4().hex[:12]
        self.run_id = run_id
        self._counters: Dict[str, float] = dict(
            (existing or {}).get("counters", {})
        )
        self._seq = 0
        self._events = open(events_path, "a", encoding="utf-8")
        self._manifest: Dict[str, Any] = {
            "schema_version": MANIFEST_SCHEMA_VERSION,
            "kind": kind,
            "run_id": run_id,
            "created_unix": (existing or {}).get("created_unix", time.time()),
            "finalized": False,
            "resumed": bool(resumed),
        }
        if config is not None:
            try:
                self._manifest["config"] = _jsonable(config.model_dump())
            except Exception:  # noqa: BLE001 — a snapshot failure must not kill the run
                self._manifest["config"] = None
        self._write_manifest()
        self.emit("run", status="resumed" if resumed else "started")

    # ------------------------------------------------------------------
    # events

    def emit(self, etype: str, _t: Optional[float] = None, **fields) -> None:
        """Append one event line (flushed whole; crash loses at most one).

        Every line carries ``t``, the emit wall-clock timestamp (schema
        v2) — the anchor for trace spans and the offline metrics fold.
        ``_t`` backdates an event whose real time predates the writer
        (the daemon's retroactive ``submitted`` lifecycle event)."""
        rec = {
            "type": etype,
            "seq": self._seq,
            "t": float(_t) if _t is not None else time.time(),
            **_jsonable(fields),
        }
        self._seq += 1
        self._events.write(json.dumps(rec) + "\n")
        self._events.flush()

    def serve_event(self, event: str, _t: Optional[float] = None,
                    **context) -> None:
        """One serve-daemon lifecycle transition (schema v2 ``serve``
        events: submitted/admitted/generation_start/generation_done/
        evicted/frozen/resumed) — the stream-side twin of the ledger."""
        self.emit("serve", _t=_t, event=str(event), **context)

    def phase_times(self, round_idx: int, mode: str, wall_s: float, **extra) -> None:
        """One round's time record.  ``mode`` carries the dispatch
        semantics (schema.py): per_round = wall round time, fused =
        elapsed/k amortized over the chunk.  Pipelined programs
        (exchange.pipeline) additionally pass ``overlap="pipelined"``:
        the round's train and (delayed) exchange+aggregate phases run
        concurrently inside one dispatch, so ``wall_s`` is the round's
        CRITICAL PATH — per-phase profiler brackets (murmura.train /
        murmura.aggregate) overlap in trace time and must not be summed
        (`murmura report` renders a critical_path section instead)."""
        if not self.record_phase_times:
            return
        self.emit(
            "phase_times", round=int(round_idx), mode=mode,
            wall_s=float(wall_s), **extra,
        )

    def round_event(
        self,
        round_num: int,
        metrics: Dict[str, Any],
        in_degree=None,
    ) -> None:
        """Per-node metric arrays of one recorded round.

        ``agg_tap_*`` keys are the in-jit audit taps; they are dropped here
        when ``record_taps`` is off (a host-side recording decision — the
        compiled program is unchanged, MUR402)."""
        payload = {
            k: v for k, v in metrics.items()
            if self.record_taps or not k.startswith("agg_tap_")
        }
        fields: Dict[str, Any] = {"round": int(round_num), "metrics": payload}
        if in_degree is not None:
            fields["in_degree"] = in_degree
        self.emit("round", **fields)

    def memory_event(self, round_idx: int) -> None:
        """Sample device memory_stats() (no-op unless enabled; tolerates
        platforms that expose none — CPU returns None)."""
        if not self.memory_stats:
            return
        stats = None
        kind = None
        try:
            import jax

            dev = jax.local_devices()[0]
            kind = dev.device_kind
            stats = dev.memory_stats()
        except Exception:  # noqa: BLE001 — sampling must never kill the run
            pass
        self.emit("memory", round=int(round_idx), device_kind=kind, stats=stats)

    def checkpoint_event(
        self, round_idx: int, duration_s: float, action: str = "save",
        path: Optional[str] = None,
    ) -> None:
        self.emit(
            "checkpoint", round=int(round_idx), action=action,
            duration_s=float(duration_s), path=path,
        )

    def add_counters(self, counters: Dict[str, float]) -> None:
        """Accumulate distributed counters into the manifest totals."""
        for k, v in counters.items():
            try:
                self._counters[k] = self._counters.get(k, 0.0) + float(v)
            except (TypeError, ValueError):
                continue

    # ------------------------------------------------------------------
    # manifest

    def _write_manifest(self) -> None:
        blob = dict(self._manifest)
        blob["counters"] = dict(self._counters)
        durable_replace(
            self.run_dir, MANIFEST_FILE,
            json.dumps(_jsonable(blob), indent=2).encode("utf-8"),
        )

    def finalize(
        self,
        history: Optional[Dict[str, list]] = None,
        summary: Optional[Dict[str, Any]] = None,
    ) -> Path:
        """Atomically commit the manifest (durable_replace).  Callable more
        than once — each train() call re-finalizes with the latest history,
        so the manifest is always the last *complete* view."""
        if history is not None:
            self._manifest["history"] = history
        if summary is not None:
            self._manifest["summary"] = summary
        self._manifest["finalized"] = True
        self._manifest["finalized_unix"] = time.time()
        self._manifest["num_events"] = self._seq
        self._write_manifest()
        return self.run_dir / MANIFEST_FILE

    def close(self) -> None:
        try:
            self._events.close()
        except Exception:  # noqa: BLE001 — teardown best-effort
            pass


# ----------------------------------------------------------------------
# readers (murmura report, tests)


def read_manifest(run_dir) -> Optional[Dict[str, Any]]:
    """Parsed manifest.json, or None when absent/unreadable."""
    path = Path(run_dir) / MANIFEST_FILE
    try:
        return json.loads(path.read_text())
    except (OSError, json.JSONDecodeError):
        return None


def iter_events(run_dir) -> Iterator[Dict[str, Any]]:
    """Yield event dicts in append order, tolerating a torn final line."""
    path = Path(run_dir) / EVENTS_FILE
    if not path.exists():
        return
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except json.JSONDecodeError:
                # A crash mid-append leaves at most one torn line — the
                # valid prefix is the stream.
                return


def events_of_type(run_dir, etype: str) -> List[Dict[str, Any]]:
    return [e for e in iter_events(run_dir) if e.get("type") == etype]


def write_bench_manifest(
    run_dir,
    name: str,
    payload: Dict[str, Any],
    legacy_path=None,
) -> Path:
    """One-schema bench artifact (satellite of ISSUE 4).

    The bench's result blob becomes the ``summary`` of a ``kind: bench``
    manifest in ``run_dir``; ``legacy_path`` (when given) keeps the
    script's historical filename as a duplicated view of the same payload
    for one release, so downstream readers migrate on their own clock.
    """
    w = TelemetryWriter(run_dir, kind=KIND_BENCH, run_id=name)
    try:
        w.emit("bench", name=name)
        path = w.finalize(summary=payload)
    finally:
        w.close()
    # Final OpenMetrics snapshot next to the manifest (ISSUE 19): the
    # same serializer the daemon's ``metrics`` op uses, so batch and
    # serve artifacts scrape identically.
    from murmura_tpu.telemetry.metrics import (
        MetricsRegistry,
        fold_bench_payload,
        write_openmetrics_snapshot,
    )

    reg = MetricsRegistry()
    fold_bench_payload(reg, name, payload)
    write_openmetrics_snapshot(run_dir, reg)
    if legacy_path is not None:
        legacy_path = Path(legacy_path)
        legacy_path.parent.mkdir(parents=True, exist_ok=True)
        durable_replace(
            legacy_path.parent, legacy_path.name,
            (json.dumps(_jsonable(payload), indent=2) + "\n").encode("utf-8"),
        )
    return path

"""The telemetry run schema: one manifest, one event stream.

Every producer in the framework — the simulation/tpu orchestrator
(core/network.py), the ZMQ Monitor (distributed/monitor.py), and the bench
scripts (bench.py, bench_breakdown.py) — writes observability data through
this one schema instead of private JSON shapes:

    <run_dir>/manifest.json   versioned envelope: schema_version, kind,
                              run_id, config snapshot, summary, counters,
                              history — finalized ATOMICALLY via
                              utils.checkpoint.durable_replace, so a crash
                              mid-run leaves either the previous manifest
                              or the new complete one.
    <run_dir>/events.jsonl    append-only event stream, one JSON object per
                              line.  A crash leaves a valid prefix (each
                              line is flushed whole); readers must tolerate
                              a truncated final line.

Event types (the ``type`` field of each line):

=============== ==========================================================
type            meaning
=============== ==========================================================
``run``         run lifecycle marker (started / resumed / finalized)
``round``       one recorded round: per-node metric arrays (accuracy,
                agg_* rule statistics, ``agg_tap_*`` audit taps) plus the
                host-side ``in_degree`` of the round's effective adjacency
``phase_times`` where a round's wall time went.  ``mode`` records the
                dispatch semantics: ``per_round`` entries are wall round
                times; ``fused`` entries are ``elapsed/k`` amortized over
                the chunk (per-round wall times inside a single device
                dispatch are not observable — core/network.py round_times)
``memory``      per-round device ``memory_stats()`` sample
``checkpoint``  checkpoint write (``duration_s``) or restore
``profile``     profiler trace window started/stopped (``trace_dir``)
``run_resumed`` a durability restore continued this run from a snapshot
                (``round``, ``path``, ``run_id``) — the event stream it
                appends to is the SAME stream the interrupted run wrote
                (durability/snapshot.py; a resumed run never rotates its
                own events to ``*.prev``)
``backend_degraded``
                the dispatch envelope observed a degradation: a
                transient device/tunnel failure being retried with
                backoff (``reason``, ``retry``, ``delay_s``), a bench
                CPU fallback, or a frozen gang member lane
                (``member``, ``reason`` — core/gang.py freeze_member)
``counter``     distributed-backend node counters folded by the Monitor
                (reconnects, send retries/failures, skipped frames,
                checkpoint durations)
``serve``       (v2) one serve-daemon lifecycle transition of this
                tenant: ``event`` in submitted / admitted /
                generation_start / generation_done / evicted / frozen /
                resumed, with ``bucket``/``gen``/``lane`` context — the
                stream-side twin of the durable ledger record, so
                ``murmura report`` and the trace export see the
                lifecycle without reading daemon internals
``extra``       forward-compat: metric keys this version does not know,
                preserved verbatim under ``extra.*`` instead of dropped
=============== ==========================================================

Since v2 every event line also carries ``t``, the host wall-clock unix
timestamp at emit — the anchor the trace-span builder
(telemetry/spans.py) and the offline metrics fold need.  v1 streams
(no ``t``) still render everywhere: readers synthesize a timeline from
the manifest's ``created_unix`` plus cumulative wall time (MUR1703).

Versioning: ``MANIFEST_SCHEMA_VERSION`` bumps on any breaking change to the
manifest envelope or an event's required fields, and every version must
have a migration note in docs/OBSERVABILITY.md ("Schema versions") —
enforced by ``murmura check`` rule MUR401 (analysis/contracts.py).
"""

MANIFEST_SCHEMA_VERSION = 2

MANIFEST_FILE = "manifest.json"
EVENTS_FILE = "events.jsonl"

# Manifest ``kind`` values: a training run (CLI / Network / Monitor) vs a
# bench artifact (bench.py, bench_breakdown.py payloads in ``summary``).
KIND_RUN = "run"
KIND_BENCH = "bench"

# Metric keys the Monitor understands natively; anything else a node
# reports is forwarded under ``extra.*`` (never silently dropped — the
# forward-compat contract an old monitor owes new node events).
MONITOR_KNOWN_KEYS = frozenset({
    "round", "node", "skipped", "compromised",
    "accuracy", "loss", "vacuity", "entropy", "strength",
    "stats", "counters",
})

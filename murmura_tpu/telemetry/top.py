"""``murmura top`` (ISSUE 19 leg 2): a refreshing live view of a serve
daemon, built ENTIRELY on the read-only protocol ops (ping/list/
metrics).  No new daemon state: everything the dashboard shows is a
projection of responses the ops already serve, so a top session is a
polling loop a tenant cannot observe (MUR1701 — zero recompiles, byte-
identical histories under scrape).
"""

import time
from typing import Any, Dict, List, Optional

from murmura_tpu.telemetry.metrics import parse_openmetrics


def gather(socket_path: str) -> Dict[str, Any]:
    """One snapshot: ping + list + metrics over the socket."""
    from murmura_tpu.serve.protocol import send_request

    snap: Dict[str, Any] = {"t": time.time()}
    snap["ping"] = send_request(str(socket_path), {"op": "ping"})
    snap["list"] = send_request(str(socket_path), {"op": "list"})
    metrics = send_request(str(socket_path), {"op": "metrics"})
    snap["metrics"] = (
        parse_openmetrics(metrics["text"]) if metrics.get("ok") else {}
    )
    return snap


def _tenant_metric(metrics: Dict, name: str, tenant: str) -> Optional[float]:
    for (sample, labels), value in metrics.items():
        if sample == name and ("tenant", tenant) in labels:
            return value
    return None


def render_snapshot(snap: Dict[str, Any]) -> str:
    """The dashboard as plain text (one frame; the CLI loop redraws).

    Header: daemon liveness + the satellite counters (uptime, version,
    schema, cumulative admissions/evictions/resumes/compiles).  Body:
    the tenant table (state / rounds / accuracy / mean round seconds)
    and the bucket occupancy census."""
    ping = snap.get("ping") or {}
    rows: List[Dict[str, Any]] = (snap.get("list") or {}).get(
        "submissions", []
    )
    metrics = snap.get("metrics") or {}
    counters = ping.get("counters") or {}
    lines: List[str] = []
    uptime = ping.get("uptime_s")
    lines.append(
        "murmura top — pid {pid}  up {up}  v{ver} schema v{schema}  "
        "queued {queued}".format(
            pid=ping.get("pid", "?"),
            up=f"{uptime:.0f}s" if isinstance(uptime, (int, float)) else "?",
            ver=ping.get("version", "?"),
            schema=ping.get("schema_version", "?"),
            queued=ping.get("queued", "?"),
        )
    )
    lines.append(
        "admissions {a}  evictions {e}  resumes {r}  compiles {c}  "
        "generations {g}".format(
            a=counters.get("admissions", 0),
            e=counters.get("evictions", 0),
            r=counters.get("resumes", 0),
            c=counters.get("compiles", 0),
            g=counters.get("generations", 0),
        )
    )
    lines.append("")
    header = ("id", "state", "bucket", "rounds", "acc", "round_s")
    table = [list(header)]
    for row in rows:
        tenant = str(row.get("id"))
        rounds = _tenant_metric(metrics, "murmura_rounds_total", tenant)
        wall_sum = _tenant_metric(
            metrics, "murmura_round_wall_seconds_sum", tenant
        )
        wall_n = _tenant_metric(
            metrics, "murmura_round_wall_seconds_count", tenant
        )
        acc = row.get("final_accuracy")
        table.append([
            tenant,
            str(row.get("state", "-")),
            str(row.get("bucket", "-"))[:12],
            str(int(rounds)) if rounds is not None else "-",
            f"{acc:.4f}" if isinstance(acc, float) else "-",
            f"{wall_sum / wall_n:.3f}" if wall_sum and wall_n else "-",
        ])
    widths = [max(len(r[i]) for r in table) for i in range(len(header))]
    for i, row in enumerate(table):
        lines.append(
            "  ".join(c.ljust(w) for c, w in zip(row, widths)).rstrip()
        )
        if i == 0:
            lines.append("  ".join("-" * w for w in widths))
    lines.append("")
    buckets = ping.get("buckets") or {}
    if buckets:
        lines.append("buckets:")
        for fp, b in sorted(buckets.items()):
            lines.append(
                f"  {fp[:16]}  gen {b.get('gen')}  lanes "
                f"{b.get('running')}/{b.get('batch')}"
            )
    else:
        lines.append("buckets: (none warm)")
    age = snap.get("t")
    if age is not None:
        lines.append(f"snapshot age: {time.time() - age:.1f}s")
    return "\n".join(lines)


def run_top(
    socket_path: str,
    *,
    interval_s: float = 1.0,
    iterations: Optional[int] = None,
    echo=print,
    clear: bool = True,
) -> None:
    """The polling loop. ``iterations=None`` runs until interrupted;
    tests pass a bound (and ``clear=False``) to capture frames."""
    n = 0
    while iterations is None or n < iterations:
        snap = gather(socket_path)
        frame = render_snapshot(snap)
        if clear:
            echo("\033[2J\033[H" + frame)
        else:
            echo(frame)
        n += 1
        if iterations is not None and n >= iterations:
            break
        time.sleep(interval_s)

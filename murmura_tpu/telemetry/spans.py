"""Trace spans over the durable event stream (ISSUE 19 leg 4).

A *span* is one closed interval on one lane of one run's timeline:
``trace_id`` is the run/submission id, lanes (``tid``) separate the
lifecycle, round, and checkpoint tracks, and every non-root span is
parented, so a whole multi-tenant soak renders as one forest.  No new
in-jit work: spans are REBUILT from the records the framework already
emits — the serve lifecycle events, the per-round ``phase_times``
accounting (whose semantics the span durations inherit: fused rounds
are elapsed/k amortized, pipelined rounds are the critical path), and
the checkpoint/profile events.  The export target is the Chrome
trace-event JSON that Perfetto (ui.perfetto.dev) opens directly:
``murmura report <run_dir> --trace out.json``.

Timeline semantics: round spans are laid out on the *accounted*
timeline — each round occupies ``[max(cursor, t - wall_s), ... +
wall_s]`` so that (a) spans on a lane never overlap even when a fused
chunk reports k amortized rounds at one wall-clock instant, and (b) the
sum of round-span durations equals the summed ``phase_times`` exactly.
Both properties are the MUR1702 contract (analysis/observe.py
:func:`validate_spans`).  v1 streams (no per-event ``t`` timestamp)
still render: the timeline is synthesized from the manifest's
``created_unix`` plus cumulative wall time (the MUR1703 old-streams-
still-render half of the schema bump).
"""

import json
from pathlib import Path
from typing import Any, Dict, List, Optional

Span = Dict[str, Any]  # {"name","trace_id","tid","start","end","parent","args"}

# Lane (tid) names, one per track of a run's timeline.
LANE_LIFECYCLE = "lifecycle"
LANE_ROUNDS = "rounds"
LANE_CHECKPOINTS = "checkpoints"


def _span(name: str, trace_id: str, tid: str, start: float, end: float,
          parent: Optional[str] = None, **args) -> Span:
    return {
        "name": name,
        "trace_id": trace_id,
        "tid": tid,
        "start": float(start),
        "end": float(end),
        "parent": parent,
        "args": {k: v for k, v in args.items() if v is not None},
    }


def build_spans(run_dir) -> List[Span]:
    """One run directory's event stream as a parented span list.

    Taxonomy (docs/OBSERVABILITY.md "Span taxonomy"):

    - ``run`` — the root, one per trace_id.
    - ``queued`` / ``generation`` — serve lifecycle (submitted->admitted,
      generation_start->generation_done), lane ``lifecycle``.
    - ``round <n>`` — one per phase_times event, lane ``rounds``; args
      carry mode/chunk/overlap so fused amortization and pipelined
      critical-path semantics stay visible in Perfetto.
    - ``checkpoint save/restore`` — lane ``checkpoints``.
    """
    from murmura_tpu.telemetry.writer import iter_events, read_manifest

    manifest = read_manifest(run_dir) or {}
    trace_id = str(
        manifest.get("run_id") or Path(str(run_dir)).name or "run"
    )
    created = float(manifest.get("created_unix") or 0.0)
    root_id = f"{trace_id}/run"

    spans: List[Span] = []
    cursor = created      # accounted-timeline cursor for the rounds lane
    last_t = created      # latest real timestamp seen anywhere
    serve_marks: Dict[str, float] = {}

    for event in iter_events(run_dir):
        t = event.get("t")
        if t is not None:
            last_t = max(last_t, float(t))
        etype = event.get("type")
        if etype == "phase_times":
            wall = float(event.get("wall_s", 0.0))
            start = max(cursor, (float(t) - wall) if t is not None else cursor)
            end = start + wall
            cursor = end
            last_t = max(last_t, end)
            spans.append(_span(
                f"round {event.get('round')}", trace_id, LANE_ROUNDS,
                start, end, parent=root_id,
                round=event.get("round"), mode=event.get("mode"),
                chunk=event.get("chunk"), overlap=event.get("overlap"),
            ))
        elif etype == "checkpoint":
            dur = float(event.get("duration_s", 0.0))
            end = float(t) if t is not None else cursor
            spans.append(_span(
                f"checkpoint {event.get('action', 'save')}", trace_id,
                LANE_CHECKPOINTS, end - dur, end, parent=root_id,
                round=event.get("round"), path=event.get("path"),
            ))
        elif etype == "serve":
            name = str(event.get("event"))
            at = float(t) if t is not None else last_t
            serve_marks[name] = at
            if name == "admitted" and "submitted" in serve_marks:
                spans.append(_span(
                    "queued", trace_id, LANE_LIFECYCLE,
                    serve_marks["submitted"], at, parent=root_id,
                    bucket=event.get("bucket"),
                ))
            elif (name in ("generation_done", "evicted", "frozen")
                  and "generation_start" in serve_marks):
                spans.append(_span(
                    "generation", trace_id, LANE_LIFECYCLE,
                    serve_marks.pop("generation_start"), at, parent=root_id,
                    gen=event.get("gen"), lane=event.get("lane"),
                    outcome=name,
                ))

    end = float(manifest.get("finalized_unix") or 0.0) or last_t
    end = max(end, last_t, created)
    spans.insert(0, _span(
        "run", trace_id, LANE_LIFECYCLE, created, end,
        parent=None, kind=manifest.get("kind"),
        schema_version=manifest.get("schema_version"),
    ))
    spans[0]["id"] = root_id
    return spans


def validate_spans(
    spans: List[Span], phase_total: Optional[float] = None,
    tolerance: float = 1e-6,
) -> List[str]:
    """The MUR1702 well-formedness predicate; returns problem strings.

    Checks: every span closed (finite start <= end), every non-root span
    parented at an existing root id, per-lane non-overlap (sorted by
    start, each span must not start before its predecessor ends), and —
    when ``phase_total`` is given — the round-lane durations summing to
    the phase_times total within tolerance."""
    problems: List[str] = []
    roots = {s.get("id") for s in spans if s.get("id")}
    by_lane: Dict[tuple, List[Span]] = {}
    for s in spans:
        if not (s["start"] <= s["end"]):
            problems.append(
                f"span {s['name']!r} is not closed: start {s['start']} > "
                f"end {s['end']}"
            )
        if s.get("parent") is None and not s.get("id"):
            problems.append(f"span {s['name']!r} has neither parent nor id")
        if s.get("parent") is not None and s["parent"] not in roots:
            problems.append(
                f"span {s['name']!r} parented at unknown id {s['parent']!r}"
            )
        if not s.get("id"):
            # Root spans enclose their whole trace by design; only
            # non-root spans owe their lane non-overlap.
            by_lane.setdefault((s["trace_id"], s["tid"]), []).append(s)
    for (trace_id, tid), lane in by_lane.items():
        lane.sort(key=lambda s: (s["start"], s["end"]))
        for prev, cur in zip(lane, lane[1:]):
            if cur["start"] < prev["end"] - tolerance:
                problems.append(
                    f"lane {trace_id}/{tid}: span {cur['name']!r} starts "
                    f"at {cur['start']} before {prev['name']!r} ends at "
                    f"{prev['end']}"
                )
    if phase_total is not None:
        round_total = sum(
            s["end"] - s["start"] for s in spans if s["tid"] == LANE_ROUNDS
        )
        if abs(round_total - phase_total) > max(tolerance,
                                                1e-3 * abs(phase_total)):
            problems.append(
                f"round spans sum to {round_total:.6f}s but phase_times "
                f"total {phase_total:.6f}s — the trace is inventing or "
                "losing accounted time"
            )
    return problems


# ----------------------------------------------------------------------
# Chrome trace-event export (Perfetto)


def to_chrome_trace(span_lists: List[List[Span]]) -> Dict[str, Any]:
    """Merge per-run span lists into one Chrome trace-event JSON object.

    Each run becomes one ``pid`` (named by trace_id via metadata events),
    each lane one ``tid``; spans are complete events (``ph: "X"``) with
    microsecond timestamps relative to the earliest span."""
    events: List[Dict[str, Any]] = []
    starts = [
        s["start"] for spans in span_lists for s in spans
    ]
    epoch = min(starts) if starts else 0.0
    for pid, spans in enumerate(span_lists, start=1):
        if not spans:
            continue
        tids: Dict[str, int] = {}
        events.append({
            "name": "process_name", "ph": "M", "pid": pid, "tid": 0,
            "args": {"name": spans[0]["trace_id"]},
        })
        for s in spans:
            tid = tids.setdefault(s["tid"], len(tids) + 1)
            events.append({
                "name": s["name"],
                "cat": s["tid"],
                "ph": "X",
                "ts": (s["start"] - epoch) * 1e6,
                "dur": max(0.0, s["end"] - s["start"]) * 1e6,
                "pid": pid,
                "tid": tid,
                "args": {**s["args"], "trace_id": s["trace_id"]},
            })
        for lane_name, tid in tids.items():
            events.append({
                "name": "thread_name", "ph": "M", "pid": pid, "tid": tid,
                "args": {"name": lane_name},
            })
    return {"traceEvents": events, "displayTimeUnit": "ms"}


def write_chrome_trace(out_path, run_dirs) -> int:
    """Build spans for every run dir and write one Chrome trace JSON;
    returns the number of spans exported."""
    span_lists = [build_spans(d) for d in run_dirs]
    out_path = Path(out_path)
    out_path.parent.mkdir(parents=True, exist_ok=True)
    out_path.write_text(
        json.dumps(to_chrome_trace(span_lists)) + "\n", encoding="utf-8"
    )
    return sum(len(spans) for spans in span_lists)

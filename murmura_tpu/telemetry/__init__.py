"""Unified telemetry subsystem (ISSUE 4; docs/OBSERVABILITY.md).

One versioned run manifest + JSONL event stream (schema.py, writer.py)
that all three backends and the bench scripts emit through, plus the
``murmura report`` renderer (report.py).  Default off: with no
``telemetry:`` config block the compiled programs, histories, and random
streams are byte-identical to a build without this package.
"""

from murmura_tpu.telemetry.schema import (
    EVENTS_FILE,
    MANIFEST_FILE,
    MANIFEST_SCHEMA_VERSION,
    MONITOR_KNOWN_KEYS,
)
from murmura_tpu.telemetry.writer import (
    TelemetryWriter,
    events_of_type,
    iter_events,
    read_manifest,
    write_bench_manifest,
)

__all__ = [
    "EVENTS_FILE",
    "MANIFEST_FILE",
    "MANIFEST_SCHEMA_VERSION",
    "MONITOR_KNOWN_KEYS",
    "TelemetryWriter",
    "events_of_type",
    "iter_events",
    "read_manifest",
    "write_bench_manifest",
]

"""The one metrics registry (ISSUE 19 leg 1): counters/gauges/histograms
with labels, rendered as OpenMetrics text.

Batch runs and the serve daemon share this registry: everything a scrape
can see is a *fold* of durable state — the event stream (events.jsonl),
the manifest counters, and the daemon's submission ledger — so a metrics
snapshot never invents numbers the artifacts cannot reproduce.  That is
the MUR1700 contract (analysis/observe.py): a scraped counter that a
full replay of the stream + ledger cannot reconstruct is a finding.

Three consumers:

- the daemon's ``{"op": "metrics"}`` protocol op
  (:meth:`serve.daemon.ServeDaemon.metrics_registry` -> :func:`render_openmetrics`);
- ``murmura metrics <socket|run_dir>`` (cli.py) — the offline twin folds
  a run directory's stream through :func:`fold_run_events`;
- the bench scripts, which drop a ``metrics.prom`` snapshot next to each
  manifest (:func:`write_openmetrics_snapshot`) so BENCH trajectories
  are scrapeable by stock Prometheus tooling.

Read path only: rendering takes the registry lock, touches no jax state,
and therefore cannot recompile anything (MUR1701's half of the story;
the other half is the daemon's handler never mutating gang state).
"""

import math
import threading
from pathlib import Path
from typing import Any, Dict, Iterable, List, Mapping, Optional, Tuple

# Default histogram buckets: wall-time seconds spanning a 2ms fused CPU
# round to a multi-minute TPU generation.
DEFAULT_BUCKETS = (
    0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 60.0, 300.0,
)

_TYPES = ("counter", "gauge", "histogram")

LabelDict = Optional[Mapping[str, Any]]
_LabelKey = Tuple[Tuple[str, str], ...]


def _label_key(labels: LabelDict) -> _LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render_labels(key: _LabelKey, extra: Tuple[Tuple[str, str], ...] = ()) -> str:
    pairs = key + extra
    if not pairs:
        return ""
    body = ",".join(
        f'{k}="{v}"'.replace("\n", " ")
        for k, v in pairs
    )
    return "{" + body + "}"


def _fmt_value(v: float) -> str:
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


class MetricsRegistry:
    """A minimal, dependency-free metric registry.

    Families are created lazily on first touch; each family is one
    OpenMetrics ``# TYPE`` block holding one sample (or one
    bucket/sum/count triple) per distinct label set.  Thread-safe: the
    daemon's listener thread scrapes while the main thread trains.
    """

    def __init__(self):
        self._lock = threading.Lock()
        # name -> {"type", "help", "samples": {label_key: value|hist}}
        self._families: Dict[str, Dict[str, Any]] = {}

    def _family(self, name: str, mtype: str, help_text: str) -> Dict[str, Any]:
        fam = self._families.get(name)
        if fam is None:
            fam = {"type": mtype, "help": help_text, "samples": {}}
            self._families[name] = fam
        elif fam["type"] != mtype:
            raise ValueError(
                f"metric {name!r} already registered as {fam['type']}, "
                f"not {mtype}"
            )
        return fam

    def inc(self, name: str, value: float = 1.0, labels: LabelDict = None,
            help: str = "") -> None:
        """Add ``value`` to counter ``name`` (created at 0 on first inc)."""
        if value < 0:
            raise ValueError(f"counter {name!r} cannot decrease ({value})")
        with self._lock:
            samples = self._family(name, "counter", help)["samples"]
            key = _label_key(labels)
            samples[key] = samples.get(key, 0.0) + float(value)

    def set_gauge(self, name: str, value: float, labels: LabelDict = None,
                  help: str = "") -> None:
        with self._lock:
            self._family(name, "gauge", help)["samples"][_label_key(labels)] = (
                float(value)
            )

    def max_gauge(self, name: str, value: float, labels: LabelDict = None,
                  help: str = "") -> None:
        """Gauge that keeps the maximum seen (peak-memory folds)."""
        with self._lock:
            samples = self._family(name, "gauge", help)["samples"]
            key = _label_key(labels)
            samples[key] = max(float(value), samples.get(key, float("-inf")))

    def observe(self, name: str, value: float, labels: LabelDict = None,
                help: str = "",
                buckets: Tuple[float, ...] = DEFAULT_BUCKETS) -> None:
        with self._lock:
            samples = self._family(name, "histogram", help)["samples"]
            key = _label_key(labels)
            hist = samples.get(key)
            if hist is None:
                hist = {"buckets": dict.fromkeys(buckets, 0), "sum": 0.0,
                        "count": 0}
                samples[key] = hist
            for le in hist["buckets"]:
                if value <= le:
                    hist["buckets"][le] += 1
            hist["sum"] += float(value)
            hist["count"] += 1

    def value(self, name: str, labels: LabelDict = None) -> Optional[float]:
        """A counter/gauge sample's current value (None when absent)."""
        with self._lock:
            fam = self._families.get(name)
            if fam is None or fam["type"] == "histogram":
                return None
            return fam["samples"].get(_label_key(labels))

    def families(self) -> List[str]:
        with self._lock:
            return sorted(self._families)


def render_openmetrics(registry: MetricsRegistry) -> str:
    """The registry as OpenMetrics text (terminated by ``# EOF``).

    Counter samples carry the ``_total`` suffix; histogram samples
    expand to ``_bucket{le=...}`` / ``_sum`` / ``_count``."""
    lines: List[str] = []
    with registry._lock:
        for name in sorted(registry._families):
            fam = registry._families[name]
            lines.append(f"# TYPE {name} {fam['type']}")
            if fam["help"]:
                lines.append(f"# HELP {name} {fam['help']}")
            samples = fam["samples"]
            if fam["type"] == "counter":
                for key in sorted(samples):
                    lines.append(
                        f"{name}_total{_render_labels(key)} "
                        f"{_fmt_value(samples[key])}"
                    )
            elif fam["type"] == "gauge":
                for key in sorted(samples):
                    lines.append(
                        f"{name}{_render_labels(key)} "
                        f"{_fmt_value(samples[key])}"
                    )
            else:  # histogram
                for key in sorted(samples):
                    hist = samples[key]
                    # ``observe`` already stores cumulative counts (every
                    # bucket >= the value is bumped) — render verbatim.
                    for le in sorted(hist["buckets"]):
                        lines.append(
                            f"{name}_bucket"
                            f"{_render_labels(key, (('le', _fmt_value(le)),))}"
                            f" {hist['buckets'][le]}"
                        )
                    lines.append(
                        f"{name}_bucket"
                        f"{_render_labels(key, (('le', '+Inf'),))} "
                        f"{hist['count']}"
                    )
                    lines.append(
                        f"{name}_sum{_render_labels(key)} "
                        f"{_fmt_value(hist['sum'])}"
                    )
                    lines.append(
                        f"{name}_count{_render_labels(key)} "
                        f"{hist['count']}"
                    )
    lines.append("# EOF")
    return "\n".join(lines) + "\n"


def parse_openmetrics(text: str) -> Dict[Tuple[str, _LabelKey], float]:
    """Parse rendered OpenMetrics text back into ``{(sample_name,
    label_key): value}`` — the MUR1700 parity checks compare a scrape
    against an independent replay through this."""
    out: Dict[Tuple[str, _LabelKey], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if "{" in line:
            name, rest = line.split("{", 1)
            body, value_part = rest.rsplit("}", 1)
            labels: List[Tuple[str, str]] = []
            for pair in _split_label_pairs(body):
                k, v = pair.split("=", 1)
                labels.append((k.strip(), v.strip().strip('"')))
            key = tuple(sorted(labels))
        else:
            name, value_part = line.split(None, 1)
            key = ()
        value = value_part.strip()
        out[(name.strip(), key)] = (
            float("inf") if value == "+Inf"
            else float("-inf") if value == "-Inf"
            else float(value)
        )
    return out


def _split_label_pairs(body: str) -> Iterable[str]:
    """Split ``k="v",k2="v2"`` on commas outside quotes."""
    depth_quote = False
    start = 0
    for i, ch in enumerate(body):
        if ch == '"':
            depth_quote = not depth_quote
        elif ch == "," and not depth_quote:
            if body[start:i]:
                yield body[start:i]
            start = i + 1
    if body[start:]:
        yield body[start:]


# ----------------------------------------------------------------------
# Folds: events.jsonl / manifest -> registry (the offline scrape)


def fold_run_events(
    registry: MetricsRegistry,
    run_dir,
    labels: LabelDict = None,
) -> MetricsRegistry:
    """Replay one run directory's durable telemetry into the registry.

    This is the whole offline scrape: every metric below is a pure
    function of the manifest + event stream, which is exactly what makes
    the MUR1700 ledger-parity contract checkable — drop an event and the
    fold visibly disagrees with a scrape that saw it."""
    from murmura_tpu.telemetry.writer import iter_events, read_manifest

    base = dict(labels or {})
    manifest = read_manifest(run_dir) or {}
    if manifest:
        registry.set_gauge(
            "murmura_run_finalized", 1.0 if manifest.get("finalized") else 0.0,
            labels=base, help="1 when the manifest is finalized",
        )
        registry.set_gauge(
            "murmura_run_schema_version",
            float(manifest.get("schema_version") or 0),
            labels=base, help="telemetry manifest schema version",
        )
        for cname, cval in (manifest.get("counters") or {}).items():
            try:
                registry.inc(
                    "murmura_run_counter", float(cval),
                    labels={**base, "counter": cname},
                    help="manifest counter totals (compiles, distributed "
                         "node counters, dispatch retries)",
                )
            except (TypeError, ValueError):
                continue
    for event in iter_events(run_dir):
        etype = event.get("type")
        if etype == "round":
            registry.inc(
                "murmura_rounds", labels=base,
                help="recorded FL rounds",
            )
        elif etype == "phase_times":
            registry.observe(
                "murmura_round_wall_seconds", float(event.get("wall_s", 0.0)),
                labels={**base, "mode": str(event.get("mode"))},
                help="per-round wall time by dispatch mode (fused entries "
                     "are elapsed/k amortized; pipelined entries are the "
                     "round's critical path)",
            )
        elif etype == "checkpoint":
            action = str(event.get("action", "save"))
            registry.inc(
                "murmura_checkpoints", labels={**base, "action": action},
                help="checkpoint saves/restores",
            )
            registry.observe(
                "murmura_checkpoint_seconds",
                float(event.get("duration_s", 0.0)),
                labels={**base, "action": action},
                help="checkpoint save/restore durations",
            )
        elif etype == "memory":
            stats = event.get("stats") or {}
            in_use = stats.get("bytes_in_use")
            if in_use is not None:
                registry.max_gauge(
                    "murmura_memory_peak_bytes", float(in_use),
                    labels={**base,
                            "device_kind": str(event.get("device_kind"))},
                    help="peak sampled device bytes_in_use",
                )
        elif etype == "backend_degraded":
            registry.inc(
                "murmura_degradations",
                labels={**base, "kind": str(event.get("kind", "retry"))},
                help="dispatch-envelope degradations (transient retries, "
                     "frozen lanes, CPU fallbacks)",
            )
            if event.get("delay_s") is not None:
                registry.inc(
                    "murmura_backoff_seconds", float(event["delay_s"]),
                    labels=base,
                    help="cumulative dispatch backoff sleep",
                )
        elif etype == "serve":
            registry.inc(
                "murmura_serve_events",
                labels={**base, "event": str(event.get("event"))},
                help="serve lifecycle events (submitted/admitted/"
                     "generation_start/generation_done/evicted/resumed)",
            )
        elif etype == "run_resumed":
            registry.inc(
                "murmura_resumes", labels=base,
                help="durability restores that continued this run",
            )
    return registry


def fold_bench_payload(
    registry: MetricsRegistry, name: str, payload: Mapping[str, Any],
) -> MetricsRegistry:
    """Flatten a bench payload's numeric leaves into labelled gauges.

    One serializer for every bench script: scalar leaves become
    ``murmura_bench{bench=..., key="a.b.c"}`` gauges; non-numeric leaves
    are skipped (the manifest keeps full fidelity — the snapshot is the
    scrapeable projection, not the artifact of record)."""

    def walk(prefix: str, node: Any) -> None:
        if isinstance(node, Mapping):
            for k, v in node.items():
                walk(f"{prefix}.{k}" if prefix else str(k), v)
        elif isinstance(node, bool):
            return
        elif isinstance(node, (int, float)) and math.isfinite(node):
            registry.set_gauge(
                "murmura_bench", float(node),
                labels={"bench": name, "key": prefix},
                help="bench payload scalar leaves (see the adjacent "
                     "manifest for full structure)",
            )

    walk("", payload)
    return registry


METRICS_SNAPSHOT_FILE = "metrics.prom"


def write_openmetrics_snapshot(run_dir, registry: MetricsRegistry) -> Path:
    """Durably write the registry next to a manifest as
    ``metrics.prom`` (atomic via the checkpoint durability path)."""
    from murmura_tpu.utils.checkpoint import durable_replace

    run_dir = Path(run_dir)
    run_dir.mkdir(parents=True, exist_ok=True)
    durable_replace(
        run_dir, METRICS_SNAPSHOT_FILE,
        render_openmetrics(registry).encode("utf-8"),
    )
    return run_dir / METRICS_SNAPSHOT_FILE


def scrape_socket(socket_path: str) -> str:
    """One ``{"op": "metrics"}`` scrape of a live daemon."""
    from murmura_tpu.serve.protocol import send_request

    response = send_request(str(socket_path), {"op": "metrics"})
    if not response.get("ok"):
        raise RuntimeError(
            f"metrics scrape failed: {response.get('error')}"
        )
    return response["text"]

#!/bin/bash
# Full TPU bench battery, run sequentially with per-step timeouts.
# Usage: ./run_tpu_battery.sh [outdir]  (default: tpu_battery_results/ in
# the repo, so results survive into the driver's end-of-round commit even
# if the tunnel recovers after the working window; bench_breakdown.json
# and bench_scaling.json are additionally rewritten at the repo root by
# their own scripts)
# Each bench probes the backend itself and self-describes in its JSON;
# bench_breakdown/bench_scaling write their committed artifacts only when
# they actually ran (breakdown always writes; check "backend" in the JSON).
set -u
CHAOS=0
PROFILE=0
while :; do
  case "${1:-}" in
    --chaos) CHAOS=1; shift;;
    --profile) PROFILE=1; shift;;
    *) break;;
  esac
done
OUT="${1:-/root/repo/tpu_battery_results}"
mkdir -p "$OUT"
cd "$(dirname "$0")"
run() {
  local name=$1 tmo=$2; shift 2
  echo "=== $name ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
  timeout "$tmo" "$@" > "$OUT/$name.out" 2>&1
  local rc=$?
  echo "$name rc=$rc" | tee -a "$OUT/battery.log"
  tail -1 "$OUT/$name.out" >> "$OUT/battery.log"
}
# Pre-flight gate: the static analyzer (docs/ANALYSIS.md) must be clean
# before any bench touches the chip — a traced-branch/host-sync/recompile
# hazard in the round path invalidates every number the battery produces.
# --ir adds the jaxpr/HLO contracts and the committed AOT cost budgets
# (MUR200-206): an undeclared collective or a >10% FLOPs drift in any
# aggregator aborts the battery before a single chip-second is spent.
# CPU-pinned so the gate itself cannot wedge the single-tenant TPU.
echo "=== preflight: murmura check --ir ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
if ! timeout 600 env JAX_PLATFORMS=cpu python -m murmura_tpu check --ir murmura_tpu/ \
    > "$OUT/preflight_check.out" 2>&1; then
  echo "preflight murmura check FAILED — aborting battery" | tee -a "$OUT/battery.log"
  cat "$OUT/preflight_check.out" | tee -a "$OUT/battery.log"
  exit 1
fi
echo "preflight check clean" | tee -a "$OUT/battery.log"
# Optional chaos pre-flight (./run_tpu_battery.sh --chaos [outdir]): the
# full operational-fault gauntlet — 20% Markov churn, link drops,
# stragglers, one NaN-injecting node, gaussian Byzantine noise — must
# complete end-to-end (docs/ROBUSTNESS.md) before the battery spends chip
# time: a regression in the fault masks or the NaN sentinel invalidates
# the robustness story every bench number rides on.  CPU-pinned like the
# static gate.
if [ "$CHAOS" = 1 ]; then
  echo "=== preflight: chaos smoke ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
  if ! timeout 900 env JAX_PLATFORMS=cpu python -m murmura_tpu run \
      examples/configs/chaos_churn.yaml --quiet \
      -o "$OUT/chaos_history.json" > "$OUT/preflight_chaos.out" 2>&1; then
    echo "preflight chaos smoke FAILED — aborting battery" | tee -a "$OUT/battery.log"
    tail -20 "$OUT/preflight_chaos.out" | tee -a "$OUT/battery.log"
    exit 1
  fi
  echo "preflight chaos smoke clean" | tee -a "$OUT/battery.log"
fi
# Optional profiling pre-flight (./run_tpu_battery.sh --profile [outdir]):
# a tiny CPU-pinned run with the telemetry profiler window armed must
# produce a non-empty trace capture (docs/OBSERVABILITY.md) — if trace
# plumbing is broken, find out before a chip session depends on it.
if [ "$PROFILE" = 1 ]; then
  echo "=== preflight: telemetry profile capture ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
  PROF_RUN="$OUT/profile_preflight"
  rm -rf "$PROF_RUN"
  if ! timeout 600 env JAX_PLATFORMS=cpu MURMURA_TELEMETRY_DIR="$PROF_RUN" python - > "$OUT/preflight_profile.out" 2>&1 <<'PYEOF'
import os, sys
from pathlib import Path
import yaml
cfg = yaml.safe_load(Path("examples/configs/telemetry_audit_report.yaml").read_text())
cfg["experiment"]["rounds"] = 3
cfg["telemetry"]["dir"] = os.environ["MURMURA_TELEMETRY_DIR"]
cfg["telemetry"]["profile_rounds"] = 2
cfg["telemetry"]["profile_start_round"] = 1
tmp = Path(os.environ["MURMURA_TELEMETRY_DIR"] + ".yaml")
tmp.parent.mkdir(parents=True, exist_ok=True)
tmp.write_text(yaml.safe_dump(cfg))
from click.testing import CliRunner
from murmura_tpu.cli import app
r = CliRunner().invoke(app, ["run", str(tmp), "--quiet"])
print(r.output)
if r.exit_code:
    sys.exit(r.exit_code)
run_dir = Path(os.environ["MURMURA_TELEMETRY_DIR"])
trace = run_dir / "trace"
captured = list(trace.rglob("*")) if trace.is_dir() else []
if not any(p.is_file() and p.stat().st_size > 0 for p in captured):
    print(f"no non-empty trace files under {trace}")
    sys.exit(1)
import json
events = [json.loads(l) for l in (run_dir / "events.jsonl").read_text().splitlines()]
prof = [e for e in events if e.get("type") == "profile"]
if not any(e.get("status") == "started" for e in prof) or not any(
    e.get("status") == "stopped" for e in prof
):
    print(f"profile window events incomplete: {prof}")
    sys.exit(1)
print(f"trace capture ok: {sum(1 for p in captured if p.is_file())} file(s)")
PYEOF
  then
    echo "preflight profile capture FAILED — aborting battery" | tee -a "$OUT/battery.log"
    tail -20 "$OUT/preflight_profile.out" | tee -a "$OUT/battery.log"
    exit 1
  fi
  echo "preflight profile capture clean" | tee -a "$OUT/battery.log"
fi
run bench          2400 python bench.py
run breakdown      2400 python bench_breakdown.py
run breakdown256   2400 python bench_breakdown.py --nodes 256
run sgd_micro      1800 python bench_sgd_micro.py
run rules256       3600 python bench_rules_256.py
run scaling        14400 python bench_scaling.py
echo "battery done $(date)" | tee -a "$OUT/battery.log"

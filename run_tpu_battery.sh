#!/bin/bash
# Full TPU bench battery, run sequentially with per-step timeouts.
# Usage: ./run_tpu_battery.sh [outdir]  (default: tpu_battery_results/ in
# the repo, so results survive into the driver's end-of-round commit even
# if the tunnel recovers after the working window; bench_breakdown.json
# and bench_scaling.json are additionally rewritten at the repo root by
# their own scripts)
# Each bench probes the backend itself and self-describes in its JSON;
# bench_breakdown/bench_scaling write their committed artifacts only when
# they actually ran (breakdown always writes; check "backend" in the JSON).
set -u
CHAOS=0
PROFILE=0
GANG=0
POPULATION=0
COMPRESS=0
RESUME=0
FRONTIER=0
STALE=0
PIPELINE=0
SHARDED=0
COMPOSE=0
MEMORY=0
SERVE=0
OBS=0
while :; do
  case "${1:-}" in
    --chaos) CHAOS=1; shift;;
    --profile) PROFILE=1; shift;;
    --gang) GANG=1; shift;;
    --population) POPULATION=1; shift;;
    --compress) COMPRESS=1; shift;;
    --resume) RESUME=1; shift;;
    --frontier) FRONTIER=1; shift;;
    --stale) STALE=1; shift;;
    --pipeline) PIPELINE=1; shift;;
    --sharded) SHARDED=1; shift;;
    --compose) COMPOSE=1; shift;;
    --memory) MEMORY=1; shift;;
    --serve) SERVE=1; shift;;
    --obs) OBS=1; shift;;
    *) break;;
  esac
done
OUT="${1:-/root/repo/tpu_battery_results}"
mkdir -p "$OUT"
cd "$(dirname "$0")"
# One persistent XLA compile cache for the whole battery: `murmura run`,
# the benches (tpu.compilation_cache_dir) and the check --ir budget sweep
# (analysis/budgets.apply_persistent_cache) all read this, so repeat
# invocations skip identical compiles.
export MURMURA_COMPILATION_CACHE_DIR="${MURMURA_COMPILATION_CACHE_DIR:-/tmp/murmura_jax_cache}"
run() {
  local name=$1 tmo=$2; shift 2
  echo "=== $name ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
  timeout "$tmo" "$@" > "$OUT/$name.out" 2>&1
  local rc=$?
  echo "$name rc=$rc" | tee -a "$OUT/battery.log"
  tail -1 "$OUT/$name.out" >> "$OUT/battery.log"
}
# Pre-flight gate: the static analyzer (docs/ANALYSIS.md) must be clean
# before any bench touches the chip — a traced-branch/host-sync/recompile
# hazard in the round path invalidates every number the battery produces.
# --ir adds the jaxpr/HLO contracts and the committed AOT cost budgets
# (MUR200-206): an undeclared collective or a >10% FLOPs drift in any
# aggregator aborts the battery before a single chip-second is spent.
# --flow adds the jaxpr dataflow contracts (MUR800-804): a leaked
# influence bound, a scrub-dominance break, or a zero-capable denominator
# in any rule/codec likewise aborts before the chip is touched.
# CPU-pinned so the gate itself cannot wedge the single-tenant TPU.
echo "=== preflight: murmura check --ir --flow ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
if ! timeout 600 env JAX_PLATFORMS=cpu python -m murmura_tpu check --ir --flow murmura_tpu/ \
    > "$OUT/preflight_check.out" 2>&1; then
  echo "preflight murmura check FAILED — aborting battery" | tee -a "$OUT/battery.log"
  cat "$OUT/preflight_check.out" | tee -a "$OUT/battery.log"
  exit 1
fi
echo "preflight check clean" | tee -a "$OUT/battery.log"
# Optional chaos pre-flight (./run_tpu_battery.sh --chaos [outdir]): the
# full operational-fault gauntlet — 20% Markov churn, link drops,
# stragglers, one NaN-injecting node, gaussian Byzantine noise — must
# complete end-to-end (docs/ROBUSTNESS.md) before the battery spends chip
# time: a regression in the fault masks or the NaN sentinel invalidates
# the robustness story every bench number rides on.  CPU-pinned like the
# static gate.
if [ "$CHAOS" = 1 ]; then
  echo "=== preflight: chaos smoke ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
  if ! timeout 900 env JAX_PLATFORMS=cpu python -m murmura_tpu run \
      examples/configs/chaos_churn.yaml --quiet \
      -o "$OUT/chaos_history.json" > "$OUT/preflight_chaos.out" 2>&1; then
    echo "preflight chaos smoke FAILED — aborting battery" | tee -a "$OUT/battery.log"
    tail -20 "$OUT/preflight_chaos.out" | tee -a "$OUT/battery.log"
    exit 1
  fi
  echo "preflight chaos smoke clean" | tee -a "$OUT/battery.log"
fi
# Optional profiling pre-flight (./run_tpu_battery.sh --profile [outdir]):
# a tiny CPU-pinned run with the telemetry profiler window armed must
# produce a non-empty trace capture (docs/OBSERVABILITY.md) — if trace
# plumbing is broken, find out before a chip session depends on it.
if [ "$PROFILE" = 1 ]; then
  echo "=== preflight: telemetry profile capture ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
  PROF_RUN="$OUT/profile_preflight"
  rm -rf "$PROF_RUN"
  if ! timeout 600 env JAX_PLATFORMS=cpu MURMURA_TELEMETRY_DIR="$PROF_RUN" python - > "$OUT/preflight_profile.out" 2>&1 <<'PYEOF'
import os, sys
from pathlib import Path
import yaml
cfg = yaml.safe_load(Path("examples/configs/telemetry_audit_report.yaml").read_text())
cfg["experiment"]["rounds"] = 3
cfg["telemetry"]["dir"] = os.environ["MURMURA_TELEMETRY_DIR"]
cfg["telemetry"]["profile_rounds"] = 2
cfg["telemetry"]["profile_start_round"] = 1
tmp = Path(os.environ["MURMURA_TELEMETRY_DIR"] + ".yaml")
tmp.parent.mkdir(parents=True, exist_ok=True)
tmp.write_text(yaml.safe_dump(cfg))
from click.testing import CliRunner
from murmura_tpu.cli import app
r = CliRunner().invoke(app, ["run", str(tmp), "--quiet"])
print(r.output)
if r.exit_code:
    sys.exit(r.exit_code)
run_dir = Path(os.environ["MURMURA_TELEMETRY_DIR"])
trace = run_dir / "trace"
captured = list(trace.rglob("*")) if trace.is_dir() else []
if not any(p.is_file() and p.stat().st_size > 0 for p in captured):
    print(f"no non-empty trace files under {trace}")
    sys.exit(1)
import json
events = [json.loads(l) for l in (run_dir / "events.jsonl").read_text().splitlines()]
prof = [e for e in events if e.get("type") == "profile"]
if not any(e.get("status") == "started" for e in prof) or not any(
    e.get("status") == "stopped" for e in prof
):
    print(f"profile window events incomplete: {prof}")
    sys.exit(1)
print(f"trace capture ok: {sum(1 for p in captured if p.is_file())} file(s)")
PYEOF
  then
    echo "preflight profile capture FAILED — aborting battery" | tee -a "$OUT/battery.log"
    tail -20 "$OUT/preflight_profile.out" | tee -a "$OUT/battery.log"
    exit 1
  fi
  echo "preflight profile capture clean" | tee -a "$OUT/battery.log"
fi
# Optional gang pre-flight (./run_tpu_battery.sh --gang [outdir]): a
# CPU-pinned 2-seed gang (docs/PERFORMANCE.md) must (a) byte-match both
# members' single-run histories and (b) compile exactly one program for
# the whole gang — if gang batching breaks parity or the compile
# amortization, the gang bench numbers below are meaningless.
if [ "$GANG" = 1 ]; then
  echo "=== preflight: gang parity + single compile ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
  if ! timeout 600 env JAX_PLATFORMS=cpu python - > "$OUT/preflight_gang.out" 2>&1 <<'PYEOF'
import sys
import yaml
from pathlib import Path
from murmura_tpu.config import Config
from murmura_tpu.utils.factories import build_gang_from_config, build_network_from_config
from murmura_tpu.analysis.sanitizers import track_compiles

raw = yaml.safe_load(Path("examples/configs/sweep_seeds.yaml").read_text())
raw["experiment"]["rounds"] = 4
base_seed = raw["experiment"]["seed"]
seeds = [base_seed, base_seed + 1]

gang = build_gang_from_config(Config.model_validate(raw), seeds=seeds)
with track_compiles() as tracker:
    histories = gang.train(rounds=4, eval_every=2, rounds_per_dispatch=4)
    gang_compiles = tracker.total
# The fused gang program (train + in-scan eval) must be the gang's ONE
# compile — S members share it.
if gang_compiles != 1:
    print(f"gang train compiled {gang_compiles} program(s), expected exactly 1")
    sys.exit(1)
for i, seed in enumerate(seeds):
    sraw = yaml.safe_load(Path("examples/configs/sweep_seeds.yaml").read_text())
    sraw["experiment"]["rounds"] = 4
    sraw["experiment"]["seed"] = seed
    sraw.pop("sweep", None)
    single = build_network_from_config(Config.model_validate(sraw)).train(
        rounds=4, eval_every=2, rounds_per_dispatch=4
    )
    mismatched = [
        k for k in single
        if single[k] and histories[i].get(k) != single[k]
    ]
    if mismatched:
        print(f"gang member seed={seed} diverged from its single run in {mismatched}")
        print("gang:", {k: histories[i].get(k) for k in mismatched})
        print("single:", {k: single[k] for k in mismatched})
        sys.exit(1)
print(f"gang parity ok for seeds {seeds}; whole gang compiled once")
PYEOF
  then
    echo "preflight gang FAILED — aborting battery" | tee -a "$OUT/battery.log"
    tail -20 "$OUT/preflight_gang.out" | tee -a "$OUT/battery.log"
    exit 1
  fi
  echo "preflight gang clean" | tee -a "$OUT/battery.log"
fi
# Optional frontier pre-flight (./run_tpu_battery.sh --frontier [outdir]):
# a CPU-pinned 2-strength x 2-seed mini-frontier on krum
# (docs/ROBUSTNESS.md "The robustness frontier") must (a) cost exactly
# ONE compile for the whole bucket across both successive-halving stages
# under tpu.recompile_guard — the reset_run re-aim is value-only over the
# warm executables — and (b) produce a monotone (non-increasing)
# accuracy-vs-strength curve; if either breaks, a full frontier sweep
# would burn its budget recompiling or chart noise.
if [ "${FRONTIER:-0}" = 1 ]; then
  echo "=== preflight: frontier mini-sweep (1 compile/bucket + monotone curve) ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
  if ! timeout 900 env JAX_PLATFORMS=cpu python - > "$OUT/preflight_frontier.out" 2>&1 <<'PYEOF'
import sys

from murmura_tpu.config import Config
from murmura_tpu.frontier import run_frontier

raw = {
    "experiment": {"name": "frontier-preflight", "seed": 7, "rounds": 2,
                   "verbose": False},
    "topology": {"type": "ring", "num_nodes": 5},
    "aggregation": {"algorithm": "krum", "params": {"num_compromised": 1}},
    "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.05},
    "data": {"adapter": "synthetic",
             "params": {"num_samples": 40, "input_shape": [6],
                        "num_classes": 3}},
    "model": {"factory": "mlp",
              "params": {"input_dim": 6, "hidden_dims": [8],
                         "num_classes": 3}},
    "backend": "simulation",
    # recompile_guard arms CompileTracker inside the gang: any compile
    # after the bucket's warmup raises instead of silently re-lowering.
    "tpu": {"recompile_guard": True, "num_devices": 1,
            "compute_dtype": "float32"},
    "frontier": {"rules": ["krum"], "attacks": ["gaussian"],
                 "topologies": ["dense"], "points": 2, "stages": 2,
                 "seeds": [7, 11], "rounds": 2,
                 "strength_lo": 0.5, "strength_hi": 4.0},
}
artifact = run_frontier(Config.model_validate(raw))
(cell,) = artifact["cells"]
print(f"compiles={cell['compiles']} stages={cell['stages']}")
if cell["compiles"] != 1:
    print(f"FAIL: bucket cost {cell['compiles']} compiles, expected "
          "exactly 1 (the successive-halving stages must reuse the warm "
          "gang executables)")
    sys.exit(1)
curve = cell["curve"]
for row in curve:
    print(f"  strength {row['strength']:.3g}: mean {row['mean']:.4f}")
benign = curve[0]["mean"]
for row in curve[1:]:
    if row["mean"] > benign + 0.05:
        print(f"FAIL: accuracy at strength {row['strength']:.3g} "
              f"({row['mean']:.4f}) exceeds benign ({benign:.4f}) — the "
              "curve is not monotone non-increasing")
        sys.exit(1)
means = [row["mean"] for row in curve]
for a, b in zip(means, means[1:]):
    if b > a + 0.05:
        print("FAIL: accuracy-vs-strength curve is not monotone "
              f"non-increasing: {means}")
        sys.exit(1)
print("frontier preflight ok")
PYEOF
  then
    echo "preflight frontier FAILED — aborting battery" | tee -a "$OUT/battery.log"
    tail -20 "$OUT/preflight_frontier.out" | tee -a "$OUT/battery.log"
    exit 1
  fi
  echo "preflight frontier clean" | tee -a "$OUT/battery.log"
fi
# Optional staleness pre-flight (./run_tpu_battery.sh --stale [outdir]):
# the ISSUE-13 gates — a krum run under a 30% straggler + 30% link-drop
# schedule on non-IID shards with bounded staleness armed must (a) run
# with ZERO post-warmup recompiles under tpu.recompile_guard (the cache
# and ages are carried state, the fault masks input values — MUR1101),
# (b) actually serve stale edges (a dead stale layer would pass every
# accuracy bar vacuously), and (c) recover at least HALF the accuracy
# gap between the fault-free and drop-sync-faulted baselines — the
# acceptance bar of docs/ROBUSTNESS.md "Bounded staleness".  CPU-pinned
# like the static gate.
if [ "${STALE:-0}" = 1 ]; then
  echo "=== preflight: bounded-staleness recovery (stale-on vs stale-off vs fault-free) ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
  if ! timeout 900 env JAX_PLATFORMS=cpu python - > "$OUT/preflight_stale.out" 2>&1 <<'PYEOF'
import sys

import numpy as np

from murmura_tpu.config import Config
from murmura_tpu.utils.factories import build_network_from_config

ROUNDS = 12


def run(faults=None, exchange=None):
    raw = {
        "experiment": {"name": "stale-preflight", "seed": 3,
                       "rounds": ROUNDS},
        "topology": {"type": "k-regular", "num_nodes": 8, "k": 4},
        "aggregation": {"algorithm": "krum"},
        "training": {"local_epochs": 1, "batch_size": 16, "lr": 0.05},
        "data": {"adapter": "synthetic",
                 "params": {"num_samples": 240, "input_dim": 16,
                            "num_classes": 8,
                            "partition_method": "dirichlet",
                            "alpha": 0.3}},
        "model": {"factory": "mlp",
                  "params": {"input_dim": 16, "hidden_dims": [16],
                             "num_classes": 8}},
        "backend": "simulation",
        # recompile_guard arms CompileTracker inside the round loop: any
        # compile after warmup raises instead of silently re-lowering.
        "tpu": {"recompile_guard": True, "num_devices": 1,
                "compute_dtype": "float32"},
    }
    if faults:
        raw["faults"] = faults
    if exchange:
        raw["exchange"] = exchange
    h = build_network_from_config(Config.model_validate(raw)).train(
        rounds=ROUNDS
    )
    return h, float(np.mean(h["mean_accuracy"][-2:]))


FAULTS = {"enabled": True, "straggler_prob": 0.3, "link_drop_prob": 0.3,
          "seed": 11}
_, acc_clean = run()
_, acc_drop = run(faults=FAULTS)
h_stale, acc_stale = run(faults=FAULTS, exchange={"max_staleness": 2})
gap = acc_clean - acc_drop
recovered = acc_stale - acc_drop
print(f"clean={acc_clean:.4f} drop-sync={acc_drop:.4f} "
      f"stale={acc_stale:.4f} gap={gap:.4f} recovered={recovered:.4f}")
served = sum(h_stale.get("agg_stale_used", []))
print(f"stale edge-serves: {served}")
if served <= 0:
    print("FAIL: the stale layer served zero edges under a 30% "
          "straggler/link-drop schedule — the accuracy comparison is "
          "vacuous")
    sys.exit(1)
if gap > 0.01 and recovered < 0.5 * gap:
    print(f"FAIL: staleness recovered {recovered:.4f} of a {gap:.4f} "
          "accuracy gap — the acceptance bar is >= half")
    sys.exit(1)
print("stale preflight ok (zero post-warmup recompiles by guard)")
PYEOF
  then
    echo "preflight stale FAILED — aborting battery" | tee -a "$OUT/battery.log"
    tail -20 "$OUT/preflight_stale.out" | tee -a "$OUT/battery.log"
    exit 1
  fi
  echo "preflight stale clean" | tee -a "$OUT/battery.log"
fi
# Optional pipelined-rounds pre-flight (./run_tpu_battery.sh --pipeline
# [outdir]): the ISSUE-14 gates — (a) a pipelined krum run under a
# straggler/link-drop schedule must be BIT-IDENTICAL to the explicit
# one-round-delayed averaging reference (core/pipeline.
# run_delayed_reference drives the serialized program through the
# delayed recursion) on CPU, with ZERO post-warmup recompiles under
# tpu.recompile_guard (the double buffer is carried state — MUR1201) and
# a buffer that actually reports valid (a dead pipeline would pass the
# parity vacuously); then (b) when a TPU is attached, the
# bench_breakdown pipeline cell must show the exchange+aggregate segment
# >= 80% hidden behind local training — the docs/PERFORMANCE.md
# acceptance bar (skipped with a loud note on CPU-only hosts: XLA CPU
# schedules the concurrent stages sequentially).
if [ "${PIPELINE:-0}" = 1 ]; then
  echo "=== preflight: pipelined rounds (delayed-averaging bit-parity, CPU) ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
  if ! timeout 900 env JAX_PLATFORMS=cpu python - > "$OUT/preflight_pipeline.out" 2>&1 <<'PYEOF'
import sys

import jax
import numpy as np

from murmura_tpu.config import Config
from murmura_tpu.core.pipeline import run_delayed_reference
from murmura_tpu.utils.factories import build_network_from_config

ROUNDS = 12


def raw(pipeline):
    r = {
        "experiment": {"name": "pipe-preflight", "seed": 3,
                       "rounds": ROUNDS},
        "topology": {"type": "k-regular", "num_nodes": 8, "k": 4},
        "aggregation": {"algorithm": "krum"},
        "training": {"local_epochs": 1, "batch_size": 16, "lr": 0.05},
        "data": {"adapter": "synthetic",
                 "params": {"num_samples": 240, "input_dim": 16,
                            "num_classes": 8}},
        "model": {"factory": "mlp",
                  "params": {"input_dim": 16, "hidden_dims": [16],
                             "num_classes": 8}},
        "backend": "simulation",
        "faults": {"enabled": True, "straggler_prob": 0.3,
                   "link_drop_prob": 0.2, "seed": 11},
        # recompile_guard arms CompileTracker inside the round loop: any
        # compile after warmup raises instead of silently re-lowering.
        "tpu": {"recompile_guard": True, "num_devices": 1,
                "compute_dtype": "float32"},
    }
    if pipeline:
        r["exchange"] = {"pipeline": True}
    return Config.model_validate(r)


net = build_network_from_config(raw(pipeline=True))
h = net.train(rounds=ROUNDS)
valid = sum(h.get("agg_pipe_valid", []))
print(f"pipelined run: final acc {h['mean_accuracy'][-1]:.4f}, "
      f"valid-buffer rounds {valid:.0f}")
if valid <= 0:
    print("FAIL: agg_pipe_valid never reported a valid buffer — the "
          "pipeline stage is dead and the parity below is vacuous")
    sys.exit(1)
ref_net = build_network_from_config(raw(pipeline=False))
ref_params, ref_hist = run_delayed_reference(ref_net, rounds=ROUNDS)
pl = [np.asarray(x) for x in jax.tree_util.tree_leaves(net.params)]
rl = [np.asarray(x) for x in jax.tree_util.tree_leaves(ref_params)]
if not all(np.array_equal(a, b, equal_nan=True) for a, b in zip(pl, rl)):
    print("FAIL: pipelined params diverge byte-wise from the "
          "one-round-delayed averaging reference")
    sys.exit(1)
if h["mean_accuracy"] != ref_hist["mean_accuracy"]:
    print("FAIL: pipelined accuracy history diverges from the reference")
    sys.exit(1)
print("pipeline preflight ok: bit-identical to the delayed-averaging "
      "reference, zero post-warmup recompiles by guard")
PYEOF
  then
    echo "preflight pipeline FAILED — aborting battery" | tee -a "$OUT/battery.log"
    tail -20 "$OUT/preflight_pipeline.out" | tee -a "$OUT/battery.log"
    exit 1
  fi
  echo "preflight pipeline (CPU bit-parity) clean" | tee -a "$OUT/battery.log"
  echo "=== preflight: pipelined rounds (TPU hidden-fraction) ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
  if ! timeout 1800 python - > "$OUT/preflight_pipeline_tpu.out" 2>&1 <<'PYEOF'
import sys

import jax

if jax.default_backend() != "tpu":
    # The overlap measurement needs the chip: XLA CPU schedules the two
    # independent stages sequentially, so hidden_fraction ~ 0 there by
    # construction.  Not a failure — the CPU half above carried the
    # correctness gate — but say so loudly in the log.
    print(f"SKIP: default backend is {jax.default_backend()}, not tpu — "
          "the >= 80%-hidden acceptance bar only measures on the chip")
    sys.exit(0)

import bench_breakdown

cells = bench_breakdown._pipeline_cells(20)["cells"]
cell = cells["dense/codec_none"]
hf = cell.get("hidden_fraction")
print(f"dense/codec_none: serialized {cell['serialized_ms']} ms, "
      f"pipelined {cell['pipelined_ms']} ms, hidden_fraction {hf}")
if hf is None or hf < 0.8:
    print("FAIL: the exchange+aggregate segment is not >= 80% hidden "
          "behind local training on the chip (docs/PERFORMANCE.md "
          "acceptance bar); inspect the profiler trace — the delayed "
          "aggregation's collectives should overlap murmura.train")
    sys.exit(1)
print("pipeline preflight ok: exchange+aggregate >= 80% hidden on TPU")
PYEOF
  then
    echo "preflight pipeline (TPU hidden-fraction) FAILED — aborting battery" | tee -a "$OUT/battery.log"
    tail -20 "$OUT/preflight_pipeline_tpu.out" | tee -a "$OUT/battery.log"
    exit 1
  fi
  tail -1 "$OUT/preflight_pipeline_tpu.out" | tee -a "$OUT/battery.log"
fi
# Optional param-axis sharding pre-flight (./run_tpu_battery.sh --sharded
# [outdir]): the ISSUE-15 gates on a forced 8-virtual-device CPU mesh —
# (a) the MUR1300-1303 family must be clean (sharded-P collective
# inventory ppermute-only on "nodes" plus one small psum over "param";
# zero recompiles across sharded rounds; shards=1 BIT-parity with the
# unsharded program; sharded execution parity to reassociation
# tolerance), and (b) an end-to-end param-sharded run must hold under
# tpu.recompile_guard with a stale cache + int8 EF residual riding the
# sharded state.  After the gate, the bench_scaling --sharded cells
# (including the >= 50M-param-per-node acceptance point) record the
# per-device resident-params numbers into bench_scaling_sharded.json.
if [ "$SHARDED" = 1 ]; then
  echo "=== preflight: param-axis sharding (MUR1300-1303 + guarded run, CPU) ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
  if ! timeout 1200 env JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
      python - > "$OUT/preflight_sharded.out" 2>&1 <<'PYEOF'
import sys

from murmura_tpu.analysis.sharded import check_sharded

findings = check_sharded()
for f in findings:
    print(f"{f.path}:{f.line}: {f.rule} {f.message}")
if findings:
    print(f"FAIL: {len(findings)} MUR130x finding(s)")
    sys.exit(1)
print("MUR1300-1303 clean")

from murmura_tpu.config import Config
from murmura_tpu.utils.factories import build_network_from_config

cfg = Config.model_validate({
    "experiment": {"name": "sharded-preflight", "seed": 3, "rounds": 6},
    "topology": {"type": "ring", "num_nodes": 8},
    "aggregation": {"algorithm": "krum",
                    "params": {"num_compromised": 1}},
    "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.05},
    "data": {"adapter": "synthetic",
             "params": {"num_samples": 64, "input_shape": [16],
                        "num_classes": 4}},
    "model": {"factory": "mlp",
              "params": {"input_dim": 16, "hidden_dims": [36],
                         "num_classes": 4}},
    "backend": "tpu",
    "faults": {"enabled": True, "straggler_prob": 0.3,
               "link_drop_prob": 0.2, "seed": 11},
    "exchange": {"max_staleness": 2, "staleness_discount": 0.5},
    "compression": {"algorithm": "int8", "block": 8,
                    "error_feedback": True},
    "tpu": {"param_shards": 4, "param_dtype": "float32",
            "compute_dtype": "float32", "recompile_guard": True},
})
net = build_network_from_config(cfg)
h = net.train(rounds=6)
print(f"guarded sharded run ok: mesh {dict(net.mesh.shape)}, "
      f"flat_dim {net.program.flat_dim}, "
      f"final acc {h['mean_accuracy'][-1]:.4f}")
PYEOF
  then
    echo "preflight sharded FAILED — aborting battery" | tee -a "$OUT/battery.log"
    tail -20 "$OUT/preflight_sharded.out" | tee -a "$OUT/battery.log"
    exit 1
  fi
  echo "preflight sharded clean" | tee -a "$OUT/battery.log"
  run bench_scaling_sharded 7200 python bench_scaling.py --sharded --force
fi
# Optional composition-grid pre-flight (./run_tpu_battery.sh --compose
# [outdir]): the ISSUE-16 gates on a forced 8-virtual-device CPU mesh —
# (a) the MUR1400-1403 family must be clean (lever-manifest/guard
# bijection with the executable refusal census; every
# declared-compatible pair's composed round program recompile-free with
# collective-inventory parity; composed-state/stage-order parity;
# flow-taint preservation through the composed compress+stale and
# sparse+stale cells), and (b) the lifted sharding x sweep cell — a
# gang sweep on the 3-axis ("seed", "nodes", "param") mesh — must hold
# end-to-end under tpu.recompile_guard.
if [ "$COMPOSE" = 1 ]; then
  echo "=== preflight: composition grid (MUR1400-1403 + lifted sharded sweep, CPU) ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
  if ! timeout 1200 env JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
      python - > "$OUT/preflight_compose.out" 2>&1 <<'PYEOF'
import sys

from murmura_tpu.analysis.composition import check_composition

findings = check_composition()
for f in findings:
    print(f"{f.path}:{f.line}: {f.rule} {f.message}")
if findings:
    print(f"FAIL: {len(findings)} MUR140x finding(s)")
    sys.exit(1)
print("MUR1400-1403 clean")

from murmura_tpu.config import Config
from murmura_tpu.utils.factories import build_gang_from_config

cfg = Config.model_validate({
    "experiment": {"name": "compose-preflight", "seed": 3, "rounds": 6},
    "topology": {"type": "ring", "num_nodes": 8},
    "aggregation": {"algorithm": "krum",
                    "params": {"num_compromised": 1}},
    "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.05},
    "data": {"adapter": "synthetic",
             "params": {"num_samples": 64, "input_shape": [16],
                        "num_classes": 4}},
    "model": {"factory": "mlp",
              "params": {"input_dim": 16, "hidden_dims": [36],
                         "num_classes": 4}},
    "backend": "tpu",
    "sweep": {"num_seeds": 2},
    "tpu": {"param_shards": 2, "param_dtype": "float32",
            "compute_dtype": "float32", "recompile_guard": True},
})
gang = build_gang_from_config(cfg)
assert tuple(gang.mesh.axis_names) == ("seed", "nodes", "param"), \
    gang.mesh.axis_names
gang.train(rounds=6, verbose=False)
finals = [h["mean_loss"][-1] for h in gang.histories]
assert all(l == l for l in finals), finals  # finite
print(f"guarded lifted sweep ok: mesh {dict(gang.mesh.shape)}, "
      f"final losses {[round(float(l), 4) for l in finals]}")
PYEOF
  then
    echo "preflight compose FAILED — aborting battery" | tee -a "$OUT/battery.log"
    tail -20 "$OUT/preflight_compose.out" | tee -a "$OUT/battery.log"
    exit 1
  fi
  echo "preflight compose clean" | tee -a "$OUT/battery.log"
fi
# Optional memory-contract pre-flight (./run_tpu_battery.sh --memory
# [outdir]): the ISSUE-17 gates on a forced 8-virtual-device CPU mesh —
# the MUR1500-1503 family must be clean end to end: the committed
# memory_analysis() budget grid (analysis/MEMORY.json) over every
# (rule x topology x feature) cell, the sharded per-device-peak scaling
# law across shards {1, 2, 4} (needs the 8-device mesh, hence the forced
# host platform count), donation completeness per carried leaf, and the
# pipelined overlap-dependence proof (buffered aggregation independent
# of the round's training subgraph, with its serialized positive
# control).  A budget drift, an unaliased carry, or a dependence edge
# from train into the pipelined combine aborts the battery before a
# chip-second is spent — the residency numbers the battery records would
# be measuring a different program than the one the budgets describe.
if [ "$MEMORY" = 1 ]; then
  echo "=== preflight: memory contracts (MUR1500-1503, CPU) ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
  if ! timeout 1800 env JAX_PLATFORMS=cpu XLA_FLAGS="--xla_force_host_platform_device_count=8" \
      python - > "$OUT/preflight_memory.out" 2>&1 <<'PYEOF'
import sys

from murmura_tpu.analysis.memory import (
    check_memory,
    overlap_cell_findings,
    scaling_cell_findings,
)

# The full family: MUR1500 budget grid, MUR1501 scaling law (live on the
# forced 8-device mesh), MUR1502 donation walk, MUR1503 dependence proof
# incl. the doctored-combine negative control.
findings = check_memory()
for f in findings:
    print(f"{f.path}:{f.line}: {f.rule} {f.message}")
if findings:
    print(f"FAIL: {len(findings)} MUR150x finding(s)")
    sys.exit(1)
print("MUR1500-1503 clean")

# Belt-and-braces: re-run one sharded scaling cell and the pipelined
# dependence cell directly so the preflight log names them even if the
# family-level memoization ever changes what the default gate covers.
extra = list(scaling_cell_findings("krum", "circulant"))
extra += list(overlap_cell_findings("fedavg", "dense"))
for f in extra:
    print(f"{f.path}:{f.line}: {f.rule} {f.message}")
if extra:
    print(f"FAIL: {len(extra)} finding(s) in the named cells")
    sys.exit(1)
print("scaling cell (krum/circulant, shards 1-2-4) + "
      "overlap cell (fedavg/dense) clean")
PYEOF
  then
    echo "preflight memory FAILED — aborting battery" | tee -a "$OUT/battery.log"
    tail -20 "$OUT/preflight_memory.out" | tee -a "$OUT/battery.log"
    exit 1
  fi
  echo "preflight memory clean" | tee -a "$OUT/battery.log"
fi
# Optional serving pre-flight (./run_tpu_battery.sh --serve [outdir]):
# the ISSUE-18 gates, CPU-pinned — (a) the committed serve_grid.yaml grid
# through the compile-compatible scheduler under tpu.recompile_guard must
# cover >= 40 cells in <= 5 compiles, asserted from the grid.json
# manifest's CompileTracker counts (docs/ROBUSTNESS.md "Serving"), and
# (b) a daemon mini-soak with a REAL process death: a subprocess daemon
# takes concurrent socket submissions, is SIGKILLed mid-generation (no
# atexit, no finalization), a second subprocess rebinds over the stale
# socket file (the EADDRINUSE transient path), recovers from the durable
# ledger + cadence snapshots, and every submission must finish with a
# history byte-identical to an uninterrupted in-process reference daemon
# (MUR1603 end-to-end, with the kill landing wherever the scheduler
# happened to be).
if [ "$SERVE" = 1 ]; then
  echo "=== preflight: serving (grid <=5 compiles + daemon kill-mid-soak) ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
  SERVE_DIR="$OUT/serve_preflight"
  rm -rf "$SERVE_DIR"
  if ! timeout 2400 env JAX_PLATFORMS=cpu MURMURA_SERVE_DIR="$SERVE_DIR" python - > "$OUT/preflight_serve.out" 2>&1 <<'PYEOF'
import json
import os
import signal
import subprocess
import sys
import time
from pathlib import Path

import yaml

from murmura_tpu.analysis.durability import history_equal
from murmura_tpu.config import Config
from murmura_tpu.serve.daemon import TERMINAL_STATES, ServeDaemon
from murmura_tpu.serve.protocol import send_request
from murmura_tpu.serve.scheduler import run_grid, write_grid

serve_dir = Path(os.environ["MURMURA_SERVE_DIR"])
serve_dir.mkdir(parents=True, exist_ok=True)

# -- (a) the committed grid: >= 40 cells in <= 5 compiles ----------------
raw = yaml.safe_load(Path("examples/configs/serve_grid.yaml").read_text())
# recompile_guard arms CompileTracker inside each bucket's gang: a
# compile after a bucket's fused warmup raises instead of silently
# re-lowering — the manifest's per-bucket counts stay honest.
raw["tpu"] = dict(raw.get("tpu") or {}, recompile_guard=True)
art = run_grid(Config.model_validate(raw), progress=print)
write_grid(art, serve_dir / "grid.json")
print(f"grid: {art['total_cells']} cells, {art['total_compiles']} compiles")
if art["total_cells"] < 40 or art["total_compiles"] > 5:
    print(f"FAIL: grid gate is >= 40 cells in <= 5 compiles, got "
          f"{art['total_cells']} cells / {art['total_compiles']} compiles")
    sys.exit(1)

# -- (b) daemon mini-soak: SIGKILL mid-generation, byte-identical finish -
ROUNDS = 4
SEEDS = (5, 6, 7)


def tenant(seed):
    return {
        "experiment": {"name": f"soak-{seed}", "seed": seed,
                       "rounds": ROUNDS},
        "topology": {"type": "ring", "num_nodes": 5},
        "aggregation": {"algorithm": "krum",
                        "params": {"num_compromised": 1}},
        "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.05},
        "data": {"adapter": "synthetic",
                 "params": {"num_samples": 40, "input_shape": [6],
                            "num_classes": 3}},
        "model": {"factory": "mlp",
                  "params": {"input_dim": 6, "hidden_dims": [8],
                             "num_classes": 3}},
        "backend": "simulation",
        "tpu": {"recompile_guard": True, "num_devices": 1,
                "compute_dtype": "float32"},
    }


def daemon_raw(state_dir):
    r = tenant(0)
    r["serve"] = {"state_dir": str(state_dir), "capacity": 2,
                  "checkpoint_every": 1, "poll_interval_s": 0.05}
    return r


# Uninterrupted in-process reference: the byte-identity baseline.
ref = ServeDaemon(Config.model_validate(daemon_raw(serve_dir / "ref")))
for seed in SEEDS:
    ref.submit_config(tenant(seed))
ref.drain()
ref_hist = {}
for rec in ref._ledger.values():
    if rec["state"] != "done":
        print(f"FAIL: reference daemon left {rec['id']} {rec['state']}")
        sys.exit(1)
    ref_hist[rec["config"]["experiment"]["seed"]] = rec["history"]

victim_dir = serve_dir / "victim"
cfg_path = serve_dir / "victim_daemon.json"
cfg_path.write_text(json.dumps(daemon_raw(victim_dir)))
daemon_main = r"""
import json, sys
from pathlib import Path
from murmura_tpu.config import Config
from murmura_tpu.serve.daemon import ServeDaemon
ServeDaemon(
    Config.model_validate(json.loads(Path(sys.argv[1]).read_text()))
).serve_forever()
"""
env = {**os.environ, "JAX_PLATFORMS": "cpu"}


def spawn():
    return subprocess.Popen(
        [sys.executable, "-c", daemon_main, str(cfg_path)],
        env=env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT,
    )


def status(sock, sub_id):
    return send_request(sock, {"op": "status", "id": sub_id})["submission"]


def await_daemon(sock, timeout_s=180):
    # A cold subprocess pays the full jax import before binding; poll the
    # ping op (send_request's own retry envelope covers the connect races
    # once the file exists).
    deadline = time.monotonic() + timeout_s
    while time.monotonic() < deadline:
        try:
            if send_request(sock, {"op": "ping"}, retries=2)["ok"]:
                return
        except (ConnectionError, TimeoutError, OSError):
            time.sleep(0.5)
    print(f"FAIL: daemon never answered ping at {sock}")
    sys.exit(1)


proc = spawn()
sock = str(victim_dir / "daemon.sock")
await_daemon(sock)
ids = [
    send_request(sock, {"op": "submit", "config": tenant(seed)})["id"]
    for seed in SEEDS
]
print(f"submitted {ids} to daemon pid {proc.pid}")
deadline = time.monotonic() + 300
while time.monotonic() < deadline:
    if any(status(sock, i)["state"] == "running" for i in ids):
        break
    time.sleep(0.05)
else:
    print("FAIL: no submission reached 'running' before the kill window")
    sys.exit(1)
os.kill(proc.pid, signal.SIGKILL)
proc.wait()
if proc.returncode != -signal.SIGKILL:
    print(f"FAIL: daemon did not die by SIGKILL (rc={proc.returncode})")
    sys.exit(1)
print("daemon SIGKILLed mid-generation; restarting over the same "
      "state_dir (stale socket file still on disk)")

proc2 = spawn()
await_daemon(sock)
deadline = time.monotonic() + 600
states = {}
while time.monotonic() < deadline:
    states = {i: status(sock, i)["state"] for i in ids}
    if all(s in TERMINAL_STATES for s in states.values()):
        break
    time.sleep(0.2)
send_request(sock, {"op": "shutdown"})
proc2.wait(timeout=60)
if not all(s == "done" for s in states.values()):
    print(f"FAIL: not every submission finished 'done' after recovery: "
          f"{states}")
    sys.exit(1)

for sub_id in ids:
    rec = json.loads(
        (victim_dir / "submissions" / f"{sub_id}.json").read_text()
    )
    seed = rec["config"]["experiment"]["seed"]
    if not history_equal(rec["history"], ref_hist[seed]):
        print(f"FAIL: {sub_id} (seed {seed}) resumed history diverges "
              "from the uninterrupted reference daemon's")
        sys.exit(1)
print(f"serve preflight ok: {art['total_cells']} cells / "
      f"{art['total_compiles']} compiles; kill-mid-soak recovered "
      f"{len(ids)} submissions byte-identical")
PYEOF
  then
    echo "preflight serve FAILED — aborting battery" | tee -a "$OUT/battery.log"
    tail -20 "$OUT/preflight_serve.out" | tee -a "$OUT/battery.log"
    exit 1
  fi
  echo "preflight serve clean" | tee -a "$OUT/battery.log"
fi
# Optional observability pre-flight (./run_tpu_battery.sh --obs [outdir]):
# the ISSUE-19 gates, CPU-pinned — a live mini-daemon runs a warm second
# generation while a polling thread hammers the read-only metrics/ping/
# list ops mid-soak; the scrape must cause ZERO recompiles
# (CompileTracker), every tenant history must stay byte-identical to an
# unscraped reference daemon (MUR1701), and the final scrape must agree
# with an independent replay of the durable ledger + event streams
# (MUR1700 parity).  Spans built from a drained tenant must validate and
# reconcile with phase_times (MUR1702).
if [ "$OBS" = 1 ]; then
  echo "=== preflight: observability (mid-soak scrape: zero recompiles + ledger parity) ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
  if ! timeout 600 env JAX_PLATFORMS=cpu python - > "$OUT/preflight_obs.out" 2>&1 <<'PYEOF'
import sys
import tempfile
import threading
from pathlib import Path

from murmura_tpu.analysis.observe import (
    interference_problems,
    metrics_ledger_parity,
)
from murmura_tpu.analysis.sanitizers import track_compiles
from murmura_tpu.analysis.serve import _tenant_raw
from murmura_tpu.config import Config
from murmura_tpu.serve.daemon import ServeDaemon
from murmura_tpu.telemetry.spans import build_spans, validate_spans
from murmura_tpu.telemetry.writer import events_of_type

tmp = Path(tempfile.mkdtemp(prefix="murmura-obs-preflight-"))

def daemon(state):
    cfg = Config.model_validate({
        **_tenant_raw(seed=0, rounds=3),
        "serve": {"state_dir": str(state), "capacity": 2,
                  "checkpoint_every": 1},
    })
    return ServeDaemon(cfg)

def soak(state, scrape):
    d = daemon(state)
    d.submit_config(_tenant_raw(seed=5))
    d.submit_config(_tenant_raw(seed=6))
    d.drain()  # generation 1 warms the bucket
    gen2 = [d.submit_config(_tenant_raw(seed=7))["id"],
            d.submit_config(_tenant_raw(seed=8))["id"]]
    stop = threading.Event()
    def poll():
        while not stop.is_set():
            d.handle_request({"op": "metrics"})
            d.handle_request({"op": "ping"})
            d.handle_request({"op": "list"})
    poller = threading.Thread(target=poll, daemon=True)
    if scrape:
        poller.start()
    try:
        with track_compiles() as tracker:
            d.drain()  # generation 2: the mid-soak scrape target
    finally:
        stop.set()
        if scrape:
            poller.join(timeout=10.0)
    return d, gen2, tracker.total

ref, ref_ids, _ = soak(tmp / "ref", scrape=False)
scr, scr_ids, compiles = soak(tmp / "scraped", scrape=True)

pairs = [
    (i, scr._ledger[i].get("history"), ref._ledger[j].get("history"))
    for i, j in zip(scr_ids, ref_ids)
]
problems = interference_problems(compiles, pairs)
problems += metrics_ledger_parity(scr)
for sub_id in scr_ids:
    run_dir = scr.state_dir / "telemetry" / sub_id
    total = sum(float(e.get("wall_s", 0.0))
                for e in events_of_type(run_dir, "phase_times"))
    problems += [
        f"{sub_id}: {p}"
        for p in validate_spans(build_spans(run_dir), phase_total=total)
    ]
if problems:
    print("preflight obs FAILED:")
    for p in problems:
        print(" -", p)
    sys.exit(1)
print(f"preflight obs ok: 0 compiles under scrape, parity clean, "
      f"{len(scr_ids)} tenants span-validated")
PYEOF
  then
    echo "preflight obs FAILED — aborting battery" | tee -a "$OUT/battery.log"
    tail -20 "$OUT/preflight_obs.out" | tee -a "$OUT/battery.log"
    exit 1
  fi
  echo "preflight obs clean" | tee -a "$OUT/battery.log"
fi
# Optional population pre-flight (./run_tpu_battery.sh --population
# [outdir]): the ISSUE-6 engine gates — (a) a 4096-node exponential-graph
# round program must run AND lower with no O(N^2) value (the MUR600
# contract at full acceptance scale), and (b) a virtual_size=100k
# cohort-streaming run must swap cohorts 3 times with ZERO post-warmup
# recompiles (CompileTracker via tpu.recompile_guard) and seed-
# deterministic draws.  CPU-pinned like the other gates.
if [ "$POPULATION" = 1 ]; then
  echo "=== preflight: population (4096-node sparse + 100k cohort swap) ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
  if ! timeout 1200 env JAX_PLATFORMS=cpu python - > "$OUT/preflight_population.out" 2>&1 <<'PYEOF'
import sys
import numpy as np
import jax
from murmura_tpu.config import Config
from murmura_tpu.utils.factories import build_network_from_config

def raw(**over):
    r = {
        "experiment": {"name": "pop-preflight", "seed": 11, "rounds": 3},
        "topology": {"type": "exponential", "num_nodes": 4096},
        "aggregation": {"algorithm": "fedavg", "params": {}},
        "training": {"local_epochs": 1, "batch_size": 2, "lr": 0.05},
        "data": {"adapter": "synthetic",
                 "params": {"num_samples": 4096 * 2, "input_dim": 10,
                            "num_classes": 3}},
        "model": {"factory": "mlp",
                  "params": {"input_dim": 10, "hidden_dims": [8],
                             "num_classes": 3}},
        "backend": "simulation",
    }
    r.update(over)
    return r

# -- (a) 4096-node exponential smoke + no-[N,N] lowering proof ----------
net = build_network_from_config(Config.model_validate(raw()))
n = net.program.num_nodes
adj = net._adjacency_for_round(0)
assert adj.shape == (len(net.topology.offsets), n), adj.shape
import jax.numpy as jnp
args = [net.params, net.agg_state, jax.random.PRNGKey(0),
        jnp.asarray(adj), jnp.asarray(net.compromised),
        jnp.asarray(0.0, jnp.float32), net._data]
jaxpr = jax.make_jaxpr(net.program.train_step)(*args)
def eqns(jx):
    jx = getattr(jx, "jaxpr", jx)
    for e in jx.eqns:
        yield e
        for sub in e.params.values():
            for s in (sub if isinstance(sub, (list, tuple)) else [sub]):
                if hasattr(s, "jaxpr") or hasattr(s, "eqns"):
                    yield from eqns(s)
dense = set()
for e in eqns(jaxpr):
    for v in list(e.invars) + list(e.outvars):
        shape = tuple(getattr(getattr(v, "aval", None), "shape", ()) or ())
        if sum(1 for d in shape if d == n) >= 2:
            dense.add((e.primitive.name, shape))
if dense:
    print(f"4096-node sparse program traces O(N^2) values: {sorted(dense)[:5]}")
    sys.exit(1)
hist = net.train(rounds=2, eval_every=2)
if not np.isfinite(hist["mean_loss"]).all():
    print("4096-node sparse run produced non-finite loss")
    sys.exit(1)
print(f"4096-node exponential smoke ok: degree={len(net.topology.offsets)}, "
      f"acc={hist['mean_accuracy'][-1]:.3f}, no O(N^2) values in the jaxpr")

# -- (b) 100k-user cohort streaming: zero recompiles across 3 swaps -----
r = raw(topology={"type": "exponential", "num_nodes": 16},
        population={"enabled": True, "virtual_size": 100_000,
                    "sampler": "uniform", "seed": 5},
        tpu={"recompile_guard": True})
r["data"]["params"]["num_samples"] = 16 * 8
r["training"]["batch_size"] = 8
net = build_network_from_config(Config.model_validate(r))
# tpu.recompile_guard raises RecompileError on ANY post-warmup compile —
# 3 cohort swaps under the guard ARE the zero-recompile assertion.
net.train(rounds=3, eval_every=1)
if net.cohorts_seen != 3:
    print(f"expected 3 cohort swaps, saw {net.cohorts_seen}")
    sys.exit(1)
from murmura_tpu.population import draw_cohort
a = draw_cohort("uniform", 100_000, 16, 2, 5)
b = draw_cohort("uniform", 100_000, 16, 2, 5)
if not np.array_equal(a, b):
    print("cohort draws are not seed-deterministic")
    sys.exit(1)
print(f"100k cohort streaming ok: 3 swaps, zero post-warmup recompiles, "
      f"{net.bank.activated} users activated, draws deterministic")
PYEOF
  then
    echo "preflight population FAILED — aborting battery" | tee -a "$OUT/battery.log"
    tail -20 "$OUT/preflight_population.out" | tee -a "$OUT/battery.log"
    exit 1
  fi
  echo "preflight population clean" | tee -a "$OUT/battery.log"
fi
# Optional compressed-exchange pre-flight (./run_tpu_battery.sh --compress
# [outdir]): the ISSUE-7 gates — an int8 + error-feedback krum smoke on
# the attack scenario must (a) land honest accuracy within tolerance of
# the uncompressed run, (b) finish with ZERO post-warmup recompiles
# (CompileTracker via tpu.recompile_guard — scales/residuals are traced
# values, never structure), and (c) show the >= 3x analytic exchange-bytes
# reduction the bench variants report.  CPU-pinned like the other gates.
if [ "$COMPRESS" = 1 ]; then
  echo "=== preflight: compressed exchange (int8+EF krum) ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
  if ! timeout 900 env JAX_PLATFORMS=cpu python - > "$OUT/preflight_compress.out" 2>&1 <<'PYEOF'
import sys
import numpy as np
from murmura_tpu.config import Config
from murmura_tpu.utils.factories import build_network_from_config

def raw(**over):
    r = {
        "experiment": {"name": "compress-preflight", "seed": 11, "rounds": 6},
        "topology": {"type": "k-regular", "num_nodes": 16, "k": 4},
        "aggregation": {"algorithm": "krum",
                        "params": {"num_compromised": 1}},
        "attack": {"enabled": True, "type": "gaussian", "percentage": 0.2,
                   "params": {"noise_std": 10.0}},
        "training": {"local_epochs": 1, "batch_size": 16, "lr": 0.05},
        "data": {"adapter": "synthetic",
                 "params": {"num_samples": 16 * 32, "input_dim": 10,
                            "num_classes": 3}},
        "model": {"factory": "mlp",
                  "params": {"input_dim": 10, "hidden_dims": [16],
                             "num_classes": 3}},
        "backend": "simulation",
    }
    r.update(over)
    return r

def honest_acc(net, hist):
    comp = net.compromised > 0
    return hist.get("honest_accuracy", hist["mean_accuracy"])[-1]

base = build_network_from_config(Config.model_validate(raw()))
h0 = base.train(rounds=6, eval_every=6)
# tpu.recompile_guard raises RecompileError on ANY post-warmup compile —
# the 6 rounds under the guard ARE the zero-recompile assertion.
comp_net = build_network_from_config(Config.model_validate(raw(
    compression={"algorithm": "int8", "error_feedback": True, "block": 256},
    tpu={"recompile_guard": True},
)))
h1 = comp_net.train(rounds=6, eval_every=6)
a0, a1 = honest_acc(base, h0), honest_acc(comp_net, h1)
# One-sided: the codec must not LOSE accuracy (beating the uncompressed
# run — quantization noise sometimes regularizes — is not a failure).
if a1 < a0 - 0.02:
    print(f"int8+EF honest accuracy {a1:.4f} more than 2% below "
          f"uncompressed {a0:.4f}")
    sys.exit(1)
cost = comp_net.exchange_cost_analysis()
if cost["exchange_bytes_reduction"] < 3.0:
    print(f"analytic exchange-bytes reduction "
          f"{cost['exchange_bytes_reduction']:.2f}x < 3x")
    sys.exit(1)
print(f"compressed exchange ok: honest acc {a1:.4f} vs {a0:.4f} "
      f"(uncompressed), zero post-warmup recompiles, "
      f"{cost['exchange_bytes_reduction']:.2f}x fewer exchange bytes "
      f"({cost['payload_bytes_per_edge']:.0f} vs "
      f"{cost['uncompressed_bytes_per_edge']:.0f} per edge)")
PYEOF
  then
    echo "preflight compress FAILED — aborting battery" | tee -a "$OUT/battery.log"
    tail -20 "$OUT/preflight_compress.out" | tee -a "$OUT/battery.log"
    exit 1
  fi
  echo "preflight compress clean" | tee -a "$OUT/battery.log"
fi
# Optional durability pre-flight (./run_tpu_battery.sh --resume [outdir]):
# the ISSUE-10 crash-equivalence gate, with a REAL process death — a
# subprocess trains the resumable example config 3 rounds, snapshots, and
# SIGKILLs itself (no atexit, no finalization; everything past the
# snapshot is genuinely lost).  A fresh process then resumes under
# tpu.recompile_guard and must (a) restore exactly round 3, (b) finish
# with a history byte-identical to an uninterrupted run (MUR901), and
# (c) compile nothing after its warmup round (MUR902) — if kill-and-
# resume drifts by one bit or one compile, every long battery run below
# is unrecoverable and the whole durability story is fiction.  CPU-pinned
# like the other gates.
if [ "$RESUME" = 1 ]; then
  echo "=== preflight: durability kill/resume (crash-equivalence) ($(date +%H:%M:%S)) ===" | tee -a "$OUT/battery.log"
  DUR_DIR="$OUT/resume_preflight"
  rm -rf "$DUR_DIR"
  if ! timeout 900 env JAX_PLATFORMS=cpu MURMURA_DUR_DIR="$DUR_DIR" python - > "$OUT/preflight_resume.out" 2>&1 <<'PYEOF'
import json
import os
import signal
import subprocess
import sys
from pathlib import Path

import yaml

from murmura_tpu.analysis.durability import history_equal
from murmura_tpu.config import Config
from murmura_tpu.utils.checkpoint import has_checkpoint
from murmura_tpu.utils.factories import build_network_from_config

dur_dir = Path(os.environ["MURMURA_DUR_DIR"])
ckpt = dur_dir / "ckpt"
raw = yaml.safe_load(Path("examples/configs/resumable_run.yaml").read_text())
raw["experiment"]["rounds"] = 6
raw["experiment"]["verbose"] = False
raw["telemetry"]["enabled"] = False
raw["durability"]["checkpoint_dir"] = str(ckpt)
raw["durability"]["checkpoint_every"] = 3
(dur_dir / "config.json").parent.mkdir(parents=True, exist_ok=True)
(dur_dir / "config.json").write_text(json.dumps(raw))

# -- uninterrupted reference (same build path the victim/resumer use) ----
ref = build_network_from_config(Config.model_validate(raw))
ref.train(rounds=6)
ref_hist = {k: list(v) for k, v in ref.history.items()}

# -- victim: train 3 rounds, snapshot, then die by SIGKILL ---------------
victim = r"""
import json, os, signal, sys
from pathlib import Path
from murmura_tpu.config import Config
from murmura_tpu.utils.factories import build_network_from_config
raw = json.loads(Path(sys.argv[1]).read_text())
net = build_network_from_config(Config.model_validate(raw))
net.train(rounds=3)
net.save_checkpoint(raw["durability"]["checkpoint_dir"])
print("victim: snapshot written, dying", flush=True)
os.kill(os.getpid(), signal.SIGKILL)
"""
proc = subprocess.run(
    [sys.executable, "-c", victim, str(dur_dir / "config.json")],
    capture_output=True, text=True,
    env={**os.environ, "JAX_PLATFORMS": "cpu"},
)
if proc.returncode != -signal.SIGKILL:
    print(f"victim did not die by SIGKILL (rc={proc.returncode}):\n"
          f"{proc.stdout}\n{proc.stderr}")
    sys.exit(1)
if not has_checkpoint(ckpt):
    print(f"victim died without a snapshot in {ckpt}")
    sys.exit(1)
meta = json.loads((ckpt / "meta.json").read_text())
if meta["round"] != 3:
    print(f"snapshot round {meta['round']} != 3")
    sys.exit(1)

# -- resume: fresh process state, recompile-guarded continuation ---------
raw["tpu"] = dict(raw.get("tpu") or {}, recompile_guard=True)
resumed = build_network_from_config(
    Config.model_validate(raw), checkpoint_dir=str(ckpt)
)
done = resumed.restore_checkpoint(str(ckpt))
if done != 3:
    print(f"restore returned round {done}, expected 3")
    sys.exit(1)
# tpu.recompile_guard raises RecompileError on ANY post-warmup compile —
# the 3 resumed rounds under the guard ARE the zero-recompile assertion.
resumed.train(rounds=3)
res_hist = {k: list(v) for k, v in resumed.history.items()}
if not history_equal(ref_hist, res_hist):
    diverged = sorted(
        k for k in ref_hist
        if not history_equal(ref_hist[k], res_hist.get(k, []))
    )
    print(f"resumed history diverged from uninterrupted run in {diverged}")
    sys.exit(1)
print("kill/resume ok: victim SIGKILLed after round 3, resumed history "
      "byte-identical over 6 rounds, zero post-warmup recompiles")
PYEOF
  then
    echo "preflight resume FAILED — aborting battery" | tee -a "$OUT/battery.log"
    tail -20 "$OUT/preflight_resume.out" | tee -a "$OUT/battery.log"
    exit 1
  fi
  echo "preflight resume clean" | tee -a "$OUT/battery.log"
fi
run bench          2400 python bench.py
run breakdown      2400 python bench_breakdown.py
run breakdown256   2400 python bench_breakdown.py --nodes 256
run sgd_micro      1800 python bench_sgd_micro.py
run rules256       3600 python bench_rules_256.py
run scaling        14400 python bench_scaling.py
run scaling_sparse 7200 python bench_scaling.py --sparse
echo "battery done $(date)" | tee -a "$OUT/battery.log"

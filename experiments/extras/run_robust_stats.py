#!/usr/bin/env python3
"""Beyond-parity rule evidence: median / trimmed_mean / geometric_median
on the UCI-HAR synthetic fallback, clean vs 20% gaussian, against the
fedavg contrast.

The committed paper matrix (experiments/paper/) covers the six reference
rules; this compact companion anchors the three robust additions the same
way: each robust rule under attack must stay within 0.25 of its clean
baseline AND beat attacked fedavg by >= 0.15.

Usage: python experiments/extras/run_robust_stats.py
Writes results.json next to this file (committed).
"""

import json
import os
import subprocess
import sys
import tempfile
from pathlib import Path

import yaml

HERE = Path(__file__).parent

BASE = {
    "experiment": {"name": "extras", "seed": 42, "rounds": 50},
    "topology": {"type": "fully", "num_nodes": 10},
    "training": {"local_epochs": 2, "batch_size": 32, "lr": 0.01},
    "data": {"adapter": "wearables.uci_har",
             "params": {"partition_method": "dirichlet", "alpha": 0.5}},
    "model": {"factory": "wearables.uci_har", "params": {}},
    "backend": "simulation",
}

ATTACK = {"enabled": True, "type": "gaussian", "percentage": 0.2,
          "params": {"noise_std": 10.0}}

# The stealth scenario: ALIE hides inside the honest variance envelope
# (alie.py).  z is explicit because the paper's z_max rule degenerates to
# 0 at n=10/m=2 (the quantile construction targets larger coalitions).
ALIE_ATTACK = {"enabled": True, "type": "alie", "percentage": 0.2,
               "params": {"z": 1.5}}

RULES = {
    "fedavg": {},
    "median": {},
    # trim must cover the Byzantine fraction per neighborhood: 20% of 10
    # nodes = 2 Byzantine; candidates = 10 -> trim_ratio 0.3 drops 3/side.
    "trimmed_mean": {"trim_ratio": 0.3},
    "geometric_median": {"max_iters": 8},
}


def run_cfg(cfg: dict, tag: str) -> dict:
    with tempfile.TemporaryDirectory() as td:
        cfg_path = Path(td) / f"{tag}.yaml"
        out_path = Path(td) / f"{tag}.json"
        cfg_path.write_text(yaml.safe_dump(cfg))
        env = dict(os.environ)
        # Same persistent compile cache as the paper runner: runs sharing
        # a program shape compile once (one shape per rule x scenario).
        env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/murmura_jax_cache")
        proc = subprocess.run(
            [sys.executable, "-m", "murmura_tpu", "run", str(cfg_path),
             "-o", str(out_path)],
            capture_output=True, text=True, timeout=1800,
            cwd=HERE.parent.parent, env=env,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"{tag} failed:\n{(proc.stderr or proc.stdout)[-2000:]}"
            )
        hist = json.loads(out_path.read_text())
        key = "honest_accuracy" if hist.get("honest_accuracy") else "mean_accuracy"
        return {"final_accuracy": hist[key][-1], "metric": key}


def main():
    results = {}
    for rule, params in RULES.items():
        for scenario in ("clean", "attacked"):
            tag = f"{rule}_{scenario}"
            cfg = json.loads(json.dumps(BASE))  # deep copy
            cfg["aggregation"] = {"algorithm": rule, "params": params}
            if scenario == "attacked":
                cfg["attack"] = ATTACK
            print(f"[{tag}] ...", file=sys.stderr, flush=True)
            results[tag] = run_cfg(cfg, tag)

    # ALIE evidence: the colluding stealth attack vs plain averaging and
    # the strongest beyond-parity rule.  (The coordinate-wise rules are
    # omitted: ALIE is designed to sit inside the per-coordinate envelope
    # they filter on, and their clean accuracy on this non-IID task is
    # already the limiting factor.)
    for rule in ("fedavg", "geometric_median"):
        tag = f"{rule}_alie"
        cfg = json.loads(json.dumps(BASE))
        cfg["aggregation"] = {"algorithm": rule,
                               "params": RULES.get(rule, {})}
        cfg["attack"] = ALIE_ATTACK
        print(f"[{tag}] ...", file=sys.stderr, flush=True)
        results[tag] = run_cfg(cfg, tag)

    checks = {
        "fedavg_collapses": (
            results["fedavg_attacked"]["final_accuracy"]
            < results["fedavg_clean"]["final_accuracy"] - 0.15
        ),
    }
    for rule in (r for r in RULES if r != "fedavg"):
        att = results[f"{rule}_attacked"]["final_accuracy"]
        clean = results[f"{rule}_clean"]["final_accuracy"]
        # Absolute floor: robust rules may trade clean accuracy for
        # robustness on non-IID shards (the coordinate-wise rules do;
        # geometric_median largely doesn't), but a broken rule
        # (near-constant output ~= chance = 1/6) must not pass on
        # relative checks alone.
        checks[f"{rule}_clean_above_floor"] = clean >= 0.30
        checks[f"{rule}_holds_under_attack"] = att >= clean - 0.25
        checks[f"{rule}_beats_attacked_fedavg"] = (
            att >= results["fedavg_attacked"]["final_accuracy"] + 0.15
        )

    checks["alie_degrades_fedavg"] = (
        results["fedavg_alie"]["final_accuracy"]
        < results["fedavg_clean"]["final_accuracy"] - 0.15
    )
    checks["geometric_median_holds_under_alie"] = (
        results["geometric_median_alie"]["final_accuracy"]
        >= results["geometric_median_clean"]["final_accuracy"] - 0.25
    )
    checks["geometric_median_beats_fedavg_under_alie"] = (
        results["geometric_median_alie"]["final_accuracy"]
        >= results["fedavg_alie"]["final_accuracy"] + 0.03
    )

    blob = {
        # ALIE caveat carried with the numbers, not just the module
        # docstring (round-4 advisor): on the simulation/tpu backends the
        # colluding vector uses the TRUE honest-population mu/sigma — the
        # omniscient variant, strictly STRONGER than Baruch et al.'s
        # coalition-estimated construction (which the ZMQ backend
        # implements).  '*_alie' rows are an upper bound on the paper
        # attack's effect.
        "alie_note": (
            "ALIE rows use omniscient honest-population statistics "
            "(stronger than the paper's coalition estimator; see "
            "murmura_tpu/attacks/alie.py)"
        ),
        "results": results,
        "checks": checks,
        "all_pass": all(checks.values()),
    }
    (HERE / "results.json").write_text(json.dumps(blob, indent=2) + "\n")
    print(json.dumps(blob, indent=2))
    return 0 if blob["all_pass"] else 1


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python3
"""Data-poisoning evidence: label_flip vs state-filtering defenses.

The scenario this threat model exists to demonstrate (label_flip.py,
Tolpegin et al. 2020): poisoned nodes train on rotated labels and
broadcast honest-looking states, so Byzantine rules that filter outlier
STATES (krum, trimmed mean) have nothing to reject — unlike the gaussian
/ ALIE scenarios in run_robust_stats.py where they visibly defend.

Expected orderings (asserted, committed to results_label_flip.json):
  1. the poison bites: fedavg poisoned < fedavg clean by a wide margin;
  2. state filters do NOT restore clean accuracy: krum and trimmed_mean
     under label_flip stay well below the clean baseline (the honest
     negative result — a robust-aggregation story that omitted it would
     overclaim);
  3. sanity: every run learns something (> chance).

Usage: python experiments/extras/run_label_flip.py
Writes results_label_flip.json next to this file (committed).
"""

import json
import os
import subprocess
import sys
import tempfile
from pathlib import Path

import yaml

HERE = Path(__file__).parent

BASE = {
    "experiment": {"name": "label-flip-extras", "seed": 42, "rounds": 40},
    "topology": {"type": "fully", "num_nodes": 10},
    "training": {"local_epochs": 2, "batch_size": 32, "lr": 0.01},
    "data": {"adapter": "wearables.uci_har",
             "params": {"partition_method": "dirichlet", "alpha": 0.5}},
    "model": {"factory": "wearables.uci_har", "params": {}},
    "backend": "simulation",
}

ATTACK = {"enabled": True, "type": "label_flip", "percentage": 0.3,
          "params": {"flip_fraction": 1.0}}

# Distance-based rules (expected to FAIL against data poisoning) and
# performance-probe rules (expected to DEFEND: the probe evaluates
# neighbor models on the node's own CLEAN data, and a poisoned model
# scores badly regardless of how honest its parameters look).
RULES = {
    "fedavg": {},
    "krum": {"num_compromised": 3},
    "trimmed_mean": {"trim_ratio": 0.3},
    "ubar": {"rho": 0.7},
    "evidential_trust": {},
}

CHANCE = 1.0 / 6.0  # UCI HAR: 6 classes


def run_cfg(cfg: dict, tag: str) -> dict:
    with tempfile.TemporaryDirectory() as td:
        cfg_path = Path(td) / f"{tag}.yaml"
        out_path = Path(td) / f"{tag}.json"
        cfg_path.write_text(yaml.safe_dump(cfg))
        env = dict(os.environ)
        env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/murmura_jax_cache")
        proc = subprocess.run(
            [sys.executable, "-m", "murmura_tpu", "run", str(cfg_path),
             "-o", str(out_path)],
            capture_output=True, text=True, timeout=1800,
            cwd=HERE.parent.parent, env=env,
        )
        if proc.returncode != 0:
            raise RuntimeError(
                f"{tag} failed:\n{(proc.stderr or proc.stdout)[-2000:]}"
            )
        hist = json.loads(out_path.read_text())
        key = "honest_accuracy" if hist.get("honest_accuracy") else "mean_accuracy"
        return {"final_accuracy": hist[key][-1], "metric": key}


def main():
    results = {}

    clean = dict(BASE)
    clean["aggregation"] = {"algorithm": "fedavg", "params": {}}
    results["fedavg_clean"] = run_cfg(clean, "fedavg_clean")
    print("fedavg_clean", results["fedavg_clean"], flush=True)

    for rule, params in RULES.items():
        cfg = dict(BASE)
        cfg["aggregation"] = {"algorithm": rule, "params": dict(params)}
        cfg["attack"] = dict(ATTACK)
        if rule == "evidential_trust":
            cfg["model"] = {"factory": "wearables.uci_har",
                            "params": {"evidential": True}}
        tag = f"{rule}_label_flip"
        results[tag] = run_cfg(cfg, tag)
        print(tag, results[tag], flush=True)

    clean_acc = results["fedavg_clean"]["final_accuracy"]
    checks = {
        "poison_bites_fedavg":
            results["fedavg_label_flip"]["final_accuracy"] < clean_acc - 0.1,
        # The honest negative result: state filters do not restore the
        # clean baseline against data poisoning (within 5% of it would
        # mean they effectively defended).
        "krum_does_not_restore_clean":
            results["krum_label_flip"]["final_accuracy"] < clean_acc - 0.05,
        "trimmed_does_not_restore_clean":
            results["trimmed_mean_label_flip"]["final_accuracy"]
            < clean_acc - 0.05,
        # The other half of the taxonomy: performance-probe rules DO
        # defend — the probe scores poisoned models on clean local data.
        "ubar_defends":
            results["ubar_label_flip"]["final_accuracy"] > clean_acc - 0.05,
        "evidential_trust_defends":
            results["evidential_trust_label_flip"]["final_accuracy"]
            > clean_acc - 0.08,
        "probes_beat_distance_filters":
            min(results["ubar_label_flip"]["final_accuracy"],
                results["evidential_trust_label_flip"]["final_accuracy"])
            > max(results["krum_label_flip"]["final_accuracy"],
                  results["trimmed_mean_label_flip"]["final_accuracy"]) + 0.1,
        "all_learn_above_chance": all(
            r["final_accuracy"] > CHANCE + 0.05 for r in results.values()
        ),
    }
    blob = {
        "note": (
            "label_flip poisons TRAINING DATA of 30% of nodes "
            "(flip_fraction 1.0); broadcast states are untouched, so "
            "state-distance filters have nothing to reject (krum and "
            "trimmed_mean land BELOW plain fedavg: they filter honest "
            "heterogeneity while the poison rides through) — while the "
            "performance-probe rules defend: UBAR's loss probe and "
            "evidential trust's uncertainty probe score poisoned models "
            "on clean local data (ubar even beats the clean fedavg "
            "baseline).  The full defense taxonomy in one scenario."
        ),
        "scenarios": results,
        "checks": checks,
        "all_pass": all(checks.values()),
    }
    (HERE / "results_label_flip.json").write_text(
        json.dumps(blob, indent=2) + "\n"
    )
    print(json.dumps(blob["checks"]))
    if not blob["all_pass"]:
        sys.exit(1)


if __name__ == "__main__":
    main()

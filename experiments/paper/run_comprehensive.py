#!/usr/bin/env python3
"""Run the paper experiment matrix and summarize results
(reference: experiments/paper/run_comprehensive.py:1-40).

Improvement over the reference: the CLI writes history JSON directly
(`murmura run cfg -o out.json`), so results are read structurally instead of
regex-scraping stdout (reference: run_comprehensive.py:58-69).

Usage:
    python experiments/paper/run_comprehensive.py                  # everything
    python experiments/paper/run_comprehensive.py --category attacks
    python experiments/paper/run_comprehensive.py --dataset uci_har
    python experiments/paper/run_comprehensive.py --summary-only
"""

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

PAPER_DIR = Path(__file__).parent
CONFIG_DIR = PAPER_DIR / "configs"
RESULTS_DIR = PAPER_DIR / "results"
CATEGORIES = ["baseline", "heterogeneity", "attacks", "topologies",
              "ablation", "ablation_attacked"]


def run_one(cfg_path: Path, out_json: Path, timeout: float,
            device: str = None) -> dict:
    """Run one experiment through the CLI; returns a result record."""
    t0 = time.time()
    record = {"config": str(cfg_path.relative_to(CONFIG_DIR))}
    # Persistent XLA compilation cache: the matrix reuses a handful of
    # program shapes across hundreds of subprocesses, so all but the first
    # few runs skip compilation entirely.
    env = dict(os.environ)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/murmura_jax_cache")
    cmd = [sys.executable, "-m", "murmura_tpu", "run", str(cfg_path),
           "-o", str(out_json), "--quiet"]
    if device:
        cmd += ["--device", device]
    try:
        proc = subprocess.run(
            cmd,
            capture_output=True,
            text=True,
            timeout=timeout,
            cwd=PAPER_DIR.parent.parent,
            env=env,
        )
    except subprocess.TimeoutExpired:
        record.update(ok=False, error=f"timeout after {timeout}s",
                      wall_s=round(time.time() - t0, 1))
        return record
    record.update(ok=proc.returncode == 0, wall_s=round(time.time() - t0, 1))
    if proc.returncode != 0:
        record["error"] = proc.stderr[-2000:]
        return record

    hist = json.loads(out_json.read_text())
    acc = hist.get("mean_accuracy", [])
    record.update(
        final_accuracy=acc[-1] if acc else None,
        peak_accuracy=max(acc) if acc else None,
        final_std=(hist.get("std_accuracy") or [None])[-1],
        honest_accuracy=(hist.get("honest_accuracy") or [None])[-1],
        rounds=len(acc),
    )
    if hist.get("mean_vacuity"):
        record["final_vacuity"] = hist["mean_vacuity"][-1]
    return record


def summarize(records: list) -> str:
    """RESULTS_SUMMARY.md: final accuracy per dataset x algorithm per
    category (reference: experiments/paper/RESULTS_SUMMARY.md)."""
    lines = [
        "# Results summary",
        "",
        "## Reading these numbers (synthetic-regime expectations)",
        "",
        "This matrix runs on shape-identical **synthetic stand-ins** for the",
        "wearable datasets (zero-egress environment), evaluated on per-node",
        "holdouts from each node's own partition. Absolute accuracies are",
        "therefore not comparable to the published tables; the orderings are",
        "(asserted by `assert_orderings.py`, 15 families). Two places where",
        "the synthetic regime *visibly changes* the picture, and why:",
        "",
        "- **Krum's clean-run accuracies (~0.16-0.31 on `fully`) are",
        "  expected, not a defect.** Krum outputs a *single selected state*.",
        "  Under strongly non-IID per-node label distributions with",
        "  per-node evaluation, one neighbor's model cannot serve every",
        "  node's personalized holdout, so the selected state scores low",
        "  everywhere — and the more candidates there are (`fully`), the",
        "  likelier the selection lands far from any given node (see the",
        "  krum-connectivity-weakness ordering: krum/ring beats",
        "  krum/fully). The published 38.8-54.5 % figures are on real data",
        "  against a shared test distribution, which rewards any central",
        "  state. The reference reports the same qualitative collapse",
        "  (krum 46.8 vs fedavg 85.3 on UCI HAR).",
        "- **The heterogeneity (alpha) direction flips.** Published Table II",
        "  accuracy rises with alpha; here lower alpha = fewer classes per",
        "  node = an *easier personalized* task under per-node holdouts, so",
        "  robust-rule accuracy falls as alpha grows (asserted as the",
        "  alpha-direction family).",
        "",
    ]
    by_cat = {}
    for r in records:
        if not r.get("ok"):
            continue
        cat = r["config"].split("/", 1)[0]
        by_cat.setdefault(cat, []).append(r)
    for cat in CATEGORIES:
        if cat not in by_cat:
            continue
        lines += [f"## {cat}", "", "| config | final acc | peak acc | honest acc |",
                  "|---|---|---|---|"]
        for r in sorted(by_cat[cat], key=lambda r: r["config"]):
            fmt = lambda v: f"{v:.4f}" if isinstance(v, float) else "—"
            lines.append(
                f"| {Path(r['config']).stem} | {fmt(r['final_accuracy'])} "
                f"| {fmt(r['peak_accuracy'])} | {fmt(r.get('honest_accuracy'))} |"
            )
        lines.append("")
    failed = [r for r in records if not r.get("ok")]
    if failed:
        lines += ["## Failures", ""] + [f"- {r['config']}" for r in failed]
    return "\n".join(lines)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--category", choices=CATEGORIES, default=None)
    ap.add_argument("--dataset", default=None,
                    help="Substring filter on config names")
    ap.add_argument("--summary-only", action="store_true")
    ap.add_argument("--timeout", type=float, default=3600.0)
    ap.add_argument("--limit", type=int, default=None,
                    help="Run at most N configs (smoke testing)")
    ap.add_argument("--jobs", type=int, default=1,
                    help="Concurrent experiment subprocesses (use ~nproc; "
                         "each experiment is single-threaded on CPU)")
    ap.add_argument("--device", choices=["cpu", "tpu"], default=None,
                    help="Force the JAX platform for every run (a single "
                         "TPU chip runs the matrix serially: --jobs 1)")
    args = ap.parse_args()
    if args.device == "tpu" and args.jobs > 1:
        sys.exit("--device tpu requires --jobs 1 (single-tenant chip)")

    RESULTS_DIR.mkdir(parents=True, exist_ok=True)
    results_file = RESULTS_DIR / "results.json"
    records = (
        json.loads(results_file.read_text()) if results_file.exists() else []
    )

    if not args.summary_only:
        if not CONFIG_DIR.exists():
            sys.exit("No configs found — run generate_all_configs.py first")
        cfgs = sorted(CONFIG_DIR.glob("**/*.yaml"))
        if args.category:
            cfgs = [c for c in cfgs if c.parent.name == args.category]
        if args.dataset:
            cfgs = [c for c in cfgs if args.dataset in c.name]
        if args.limit:
            cfgs = cfgs[: args.limit]
        done = {r["config"] for r in records if r.get("ok")}
        todo = [c for c in cfgs if str(c.relative_to(CONFIG_DIR)) not in done]

        def out_path(rel: str) -> Path:
            out = RESULTS_DIR / "histories" / rel.replace("/", "_").replace(
                ".yaml", ".json"
            )
            out.parent.mkdir(parents=True, exist_ok=True)
            return out

        if args.jobs <= 1:
            for i, cfg in enumerate(todo):
                rel = str(cfg.relative_to(CONFIG_DIR))
                print(f"[{i + 1}/{len(todo)}] {rel}", flush=True)
                records = [r for r in records if r["config"] != rel]
                records.append(
                    run_one(cfg, out_path(rel), args.timeout, args.device)
                )
                results_file.write_text(json.dumps(records, indent=2))
        else:
            from concurrent.futures import ThreadPoolExecutor, as_completed

            with ThreadPoolExecutor(max_workers=args.jobs) as pool:
                futs = {
                    pool.submit(
                        run_one, cfg,
                        out_path(str(cfg.relative_to(CONFIG_DIR))),
                        args.timeout, args.device,
                    ): str(cfg.relative_to(CONFIG_DIR))
                    for cfg in todo
                }
                for i, fut in enumerate(as_completed(futs)):
                    rel = futs[fut]
                    print(f"[{i + 1}/{len(todo)}] {rel}", flush=True)
                    records = [r for r in records if r["config"] != rel]
                    records.append(fut.result())
                    results_file.write_text(json.dumps(records, indent=2))

    (PAPER_DIR / "RESULTS_SUMMARY.md").write_text(summarize(records))
    ok = sum(1 for r in records if r.get("ok"))
    print(f"{ok}/{len(records)} experiments ok; summary in RESULTS_SUMMARY.md")


if __name__ == "__main__":
    main()

#!/usr/bin/env python3
"""Generate the full paper experiment matrix
(reference: experiments/paper/generate_all_configs.py:1-11).

Categories (≈280 configs, mirroring the reference matrix):
1. baseline      — no attacks, fully connected, α=0.5, all 6 algorithms
2. heterogeneity — Dirichlet α ∈ {0.1, 0.5, 1.0}
3. attacks       — {gaussian, directed_deviation} × {10, 20, 30}%
4. topologies    — {ring, fully, erdos, k-regular}
5. ablation      — evidential_trust sensitivity: self_weight,
                   trust_threshold, accuracy_weight

Configs are written to experiments/paper/configs/<category>/<name>.yaml.
Without a data_path the wearable adapters emit shape-identical synthetic
data, so the whole matrix is runnable in a zero-egress environment; pass
--data-root to point at real datasets.
"""

import argparse
from pathlib import Path

import yaml

PAPER_DIR = Path(__file__).parent
ALGORITHMS = ["fedavg", "krum", "balance", "ubar", "sketchguard", "evidential_trust"]

DATASETS = {
    "uci_har": {
        "adapter": "wearables.uci_har",
        "data_dir": "UCI HAR Dataset",
        "model_factory": "examples.wearables.uci_har",
        "num_nodes": 10,
    },
    "pamap2": {
        "adapter": "wearables.pamap2",
        "data_dir": "PAMAP2_Dataset",
        "model_factory": "examples.wearables.pamap2",
        "num_nodes": 9,
    },
    "ppg_dalia": {
        "adapter": "wearables.ppg_dalia",
        "data_dir": "PPG_FieldStudy",
        "model_factory": "examples.wearables.ppg_dalia",
        "num_nodes": 15,
    },
}

# Per-rule parameters, in this framework's param names
# (reference values: experiments/paper/generate_all_configs.py AGG_PARAMS).
AGG_PARAMS = {
    "fedavg": {},
    "krum": {"num_compromised": 3},
    "balance": {"gamma": 2.0, "min_neighbors": 2},
    "ubar": {"rho": 0.5},
    "sketchguard": {"sketch_size": 1000, "gamma": 2.0},
    "evidential_trust": {
        "vacuity_threshold": 0.5,
        "accuracy_weight": 0.7,
        "trust_threshold": 0.1,
        "self_weight": 0.6,
    },
}

TOPOLOGY_PARAMS = {
    "ring": {},
    "fully": {},
    "erdos": {"p": 0.5},
    "k-regular": {"k": 4},
}


def create_config(
    dataset,
    algorithm,
    name_suffix="",
    topology_type="fully",
    alpha=0.5,
    attack_enabled=False,
    attack_type="gaussian",
    attack_percentage=0.2,
    attack_params=None,
    agg_overrides=None,
    rounds=50,
    data_root=None,
):
    ds = DATASETS[dataset]
    exp_name = f"{dataset.upper().replace('_', '')}-{algorithm}"
    if name_suffix:
        exp_name += f"-{name_suffix}"

    data_params = {"partition_method": "dirichlet", "alpha": alpha}
    if data_root:
        data_params["data_path"] = str(Path(data_root) / ds["data_dir"])

    return {
        "experiment": {"name": exp_name, "seed": 42, "rounds": rounds,
                       "verbose": True},
        "topology": {
            "type": topology_type,
            "num_nodes": ds["num_nodes"],
            "seed": 12345,
            **TOPOLOGY_PARAMS[topology_type],
        },
        "aggregation": {
            "algorithm": algorithm,
            "params": {**AGG_PARAMS.get(algorithm, {}), **(agg_overrides or {})},
        },
        "attack": {
            "enabled": attack_enabled,
            "type": attack_type if attack_enabled else None,
            "percentage": attack_percentage if attack_enabled else 0.0,
            "params": attack_params or {},
        },
        "training": {"local_epochs": 2, "batch_size": 32, "lr": 0.01,
                     "max_samples": None},
        "data": {"adapter": ds["adapter"], "params": data_params},
        "model": {"factory": ds["model_factory"], "params": {}},
        "backend": "simulation",
    }


def generate_all(data_root=None):
    """Yield (category, filename, config-dict) for the full matrix."""
    mk = lambda **kw: create_config(data_root=data_root, **kw)

    for ds in DATASETS:
        for algo in ALGORITHMS:
            yield ("baseline", f"{ds}_{algo}",
                   mk(dataset=ds, algorithm=algo))

            for alpha in (0.1, 0.5, 1.0):
                yield ("heterogeneity", f"{ds}_{algo}_alpha{alpha}",
                       mk(dataset=ds, algorithm=algo, alpha=alpha,
                          name_suffix=f"alpha{alpha}"))

            for atk, atk_params in (
                ("gaussian", {"noise_std": 10.0}),
                ("directed_deviation", {"lambda_param": -5.0}),
            ):
                for pct in (0.1, 0.2, 0.3):
                    yield ("attacks", f"{ds}_{algo}_{atk}_{int(pct*100)}",
                           mk(dataset=ds, algorithm=algo, attack_enabled=True,
                              attack_type=atk, attack_percentage=pct,
                              attack_params=atk_params,
                              name_suffix=f"{atk}{int(pct*100)}"))

            for topo in TOPOLOGY_PARAMS:
                yield ("topologies", f"{ds}_{algo}_{topo}",
                       mk(dataset=ds, algorithm=algo, topology_type=topo,
                          name_suffix=topo))

    # Ablation: evidential_trust hyperparameter sensitivity, the full
    # reference grid — 4 params x {5,4,4,4} values x 3 datasets = 51
    # configs, attack-free / fully-connected / alpha 0.5 exactly like the
    # reference's ablation category (reference:
    # experiments/paper/generate_all_configs.py:244-282, Table III).
    for ds in DATASETS:
        for param, values in (
            ("self_weight", (0.3, 0.5, 0.6, 0.7, 0.9)),
            ("trust_threshold", (0.05, 0.1, 0.2, 0.3)),
            ("accuracy_weight", (0.3, 0.5, 0.7, 0.9)),
            ("vacuity_threshold", (0.3, 0.5, 0.7, 0.9)),
        ):
            for v in values:
                yield ("ablation", f"{ds}_et_{param}_{v}",
                       mk(dataset=ds, algorithm="evidential_trust",
                          agg_overrides={param: v},
                          name_suffix=f"{param}{v}"))

    # Beyond the reference grid: the same sensitivity trio measured UNDER
    # the 20% gaussian attack (the regime the paper's robustness claims
    # live in).  These were this repo's original ablation cells; kept as
    # their own category so the reference-matching grid above stays
    # byte-comparable.
    for param, values in (
        ("self_weight", (0.3, 0.5, 0.7)),
        ("trust_threshold", (0.05, 0.1, 0.2)),
        ("accuracy_weight", (0.5, 0.7, 0.9)),
    ):
        for v in values:
            yield ("ablation_attacked", f"uci_har_et_{param}_{v}",
                   mk(dataset="uci_har", algorithm="evidential_trust",
                      attack_enabled=True, attack_type="gaussian",
                      attack_percentage=0.2,
                      attack_params={"noise_std": 10.0},
                      agg_overrides={param: v},
                      name_suffix=f"{param}{v}"))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--data-root", default=None,
                    help="Directory holding the wearable datasets; omit for "
                         "synthetic fallbacks")
    ap.add_argument("--out", default=str(PAPER_DIR / "configs"))
    args = ap.parse_args()

    out = Path(args.out)
    count = 0
    for category, name, cfg in generate_all(args.data_root):
        d = out / category
        d.mkdir(parents=True, exist_ok=True)
        (d / f"{name}.yaml").write_text(yaml.safe_dump(cfg, sort_keys=False))
        count += 1
    print(f"Wrote {count} configs under {out}")


if __name__ == "__main__":
    main()

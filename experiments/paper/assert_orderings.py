#!/usr/bin/env python3
"""Assert the qualitative Byzantine-robustness orderings the reference paper
reports (reference: experiments/paper/RESULTS_SUMMARY.md:7-38, CCGrid'26
paper Tables I-III) hold in this framework's executed matrix.

The committed matrix runs on shape-identical SYNTHETIC stand-ins for the
wearable datasets (zero-egress environment), so absolute accuracies are not
comparable to the published tables; what must carry over is every ordering
the tables imply.  Where the synthetic regime provably flips a published
direction, the check asserts the synthetic-regime direction and documents
why (see ALPHA DIRECTION below).

Ordering families (each expands into per-dataset / per-cell checks):

 1. sanity-band        — no-attack baselines in (0.35, 0.999); the round-1
                         failure mode was every config pinned at 1.0000.
 2. gaussian-degrades-fedavg   — honest acc under gaussian at EVERY
                         percentage (10/20/30) drops >= 0.2 vs clean
                         (Table I: fedavg 85.3 -> n/a under attack).
 3. directed-degrades-fedavg  — same for directed deviation.
 4. robust-beats-fedavg-gaussian  — balance/ubar/sketchguard/
                         evidential_trust beat fedavg-under-attack by
                         >= 0.10 at every percentage (Table I rows).
 5. robust-beats-fedavg-directed — same under directed deviation.
 6. krum-beats-fedavg-gaussian   — selection survives gaussian too,
                         margin >= 0.05 (Table I: krum 46.8 vs collapsed
                         fedavg under attack).
 7. robust-resilience  — robust rules lose <= 0.25 of their own clean
                         accuracy under 20% gaussian.
 8. krum-noniid-weakness — krum's clean accuracy trails fedavg at
                         alpha=0.1 (RESULTS_SUMMARY.md:10-15).
 9. krum-connectivity-weakness — krum on `fully` trails krum on `ring`:
                         more candidates = more wrong selections on
                         non-IID data (the m-grows pathology behind
                         Table I's krum collapse).
10. connectivity-helps-fedavg — fedavg on `fully` >= fedavg on `ring`
                         (averaging wants connectivity).
11. evtrust-top-tier   — evidential_trust is the best robust rule in the
                         majority of gaussian cells (Table I: best on all
                         three datasets).
12. evtrust-every-topology — evidential_trust >= fedavg - 0.05 on every
                         topology (Table I + topologies category).
13. alpha-direction    — ALPHA DIRECTION: published Table II (real data,
                         shared test distribution) shows accuracy rising
                         with alpha; this matrix evaluates per-node
                         holdouts drawn from each node's own partition, so
                         lower alpha = fewer classes per node = easier
                         personalized task, and the direction flips:
                         robust-rule accuracy at alpha=0.1 must be >= its
                         accuracy at alpha=1.0 - 0.02.  (fedavg is
                         excluded: global averaging cancels the
                         personalization advantage either way.)
14. ablation-stability — evidential_trust final accuracy moves <= 0.15
                         across each hyperparameter's grid (Table III:
                         98.3+-0.3 / 98.3+-0.5 / 98.4+-0.2), per dataset
                         and per parameter (self_weight, trust_threshold,
                         accuracy_weight, vacuity_threshold).
15. ablation-attacked-stability — same trio measured under 20% gaussian
                         (this repo's beyond-reference category) moves
                         <= 0.10 per parameter.

Checks whose records are missing are reported as SKIPPED (the matrix may
be mid-run) — they do not fail the script, but the committed-matrix test
gates on the total executed count, so a half-run matrix cannot pass CI.

Exit 0 iff every executed check passes. Usage:
    python experiments/paper/assert_orderings.py [--results PATH]
"""

import argparse
import json
import sys
from collections import Counter
from pathlib import Path

PAPER_DIR = Path(__file__).parent
DATASETS = ["uci_har", "pamap2", "ppg_dalia"]
ROBUST = ["balance", "ubar", "sketchguard", "evidential_trust"]
ATTACK_PCTS = (10, 20, 30)
TOPOLOGIES = ["ring", "fully", "erdos", "k-regular"]
ABLATION_GRID = {
    "self_weight": (0.3, 0.5, 0.6, 0.7, 0.9),
    "trust_threshold": (0.05, 0.1, 0.2, 0.3),
    "accuracy_weight": (0.3, 0.5, 0.7, 0.9),
    "vacuity_threshold": (0.3, 0.5, 0.7, 0.9),
}
ABLATION_ATTACKED_GRID = {
    "self_weight": (0.3, 0.5, 0.7),
    "trust_threshold": (0.05, 0.1, 0.2),
    "accuracy_weight": (0.5, 0.7, 0.9),
}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--results", default=str(PAPER_DIR / "results" / "results.json")
    )
    args = ap.parse_args()

    records = json.loads(Path(args.results).read_text())
    # Keyed by category-qualified path ("attacks/uci_har_fedavg_gaussian_10"):
    # bare stems collide between ablation/ and ablation_attacked/.
    by_name = {}
    for r in records:
        if r.get("ok"):
            by_name[str(Path(r["config"]).with_suffix(""))] = r

    def acc(name, key="honest_accuracy"):
        r = by_name.get(name)
        if r is None:
            return None
        v = r.get(key)
        return v if v is not None else r.get("final_accuracy")

    failures = []
    skipped = []
    families = Counter()

    def check(family, cond, msg):
        families[family] += 1
        if not cond:
            failures.append(f"[{family}] {msg}")

    def skip(family, msg):
        skipped.append(f"[{family}] {msg}")

    for ds in DATASETS:
        clean = {
            a: acc(f"baseline/{ds}_{a}", "final_accuracy")
            for a in ["fedavg", "krum"] + ROBUST
        }

        # 1. sanity band
        if clean["fedavg"] is None:
            skip("sanity-band", f"{ds}: missing fedavg baseline")
        else:
            check(
                "sanity-band",
                0.35 < clean["fedavg"] < 0.999,
                f"{ds}: fedavg clean accuracy {clean['fedavg']:.4f} outside "
                "(0.35, 0.999) — data saturated or broken",
            )

        # 2-6: attack grids
        for atk, fam_degrade, fam_robust in (
            ("gaussian", "gaussian-degrades-fedavg",
             "robust-beats-fedavg-gaussian"),
            ("directed_deviation", "directed-degrades-fedavg",
             "robust-beats-fedavg-directed"),
        ):
            for pct in ATTACK_PCTS:
                atk_fedavg = acc(f"attacks/{ds}_fedavg_{atk}_{pct}")
                if clean["fedavg"] is None or atk_fedavg is None:
                    skip(fam_degrade, f"{ds}/{atk}/{pct}: missing records")
                    continue
                check(
                    fam_degrade,
                    clean["fedavg"] - atk_fedavg >= 0.2,
                    f"{ds}: {pct}% {atk} should degrade fedavg by >=0.2 "
                    f"(clean {clean['fedavg']:.4f} -> {atk_fedavg:.4f})",
                )
                for rule in ROBUST:
                    attacked = acc(f"attacks/{ds}_{rule}_{atk}_{pct}")
                    if attacked is None:
                        skip(fam_robust, f"{ds}/{rule}/{atk}/{pct}: missing")
                        continue
                    check(
                        fam_robust,
                        attacked - atk_fedavg >= 0.10,
                        f"{ds}/{rule}: {atk} {pct}% accuracy {attacked:.4f} "
                        f"should beat fedavg-under-attack {atk_fedavg:.4f} "
                        "by >= 0.10",
                    )
                if atk == "gaussian":
                    krum_atk = acc(f"attacks/{ds}_krum_{atk}_{pct}")
                    if krum_atk is None:
                        skip("krum-beats-fedavg-gaussian",
                             f"{ds}/{pct}: missing")
                    else:
                        check(
                            "krum-beats-fedavg-gaussian",
                            krum_atk - atk_fedavg >= 0.05,
                            f"{ds}: krum gaussian {pct}% {krum_atk:.4f} "
                            f"should beat fedavg {atk_fedavg:.4f} by >= 0.05",
                        )

        # 7. robust resilience at 20% gaussian
        for rule in ROBUST:
            attacked = acc(f"attacks/{ds}_{rule}_gaussian_20")
            if clean[rule] is None or attacked is None:
                skip("robust-resilience", f"{ds}/{rule}: missing records")
                continue
            check(
                "robust-resilience",
                clean[rule] - attacked <= 0.25,
                f"{ds}/{rule}: robust rule lost "
                f"{clean[rule] - attacked:.4f} (> 0.25) under 20% gaussian",
            )

        # 8. krum non-IID weakness
        krum_noniid = acc(f"heterogeneity/{ds}_krum_alpha0.1", "final_accuracy")
        fedavg_noniid = acc(f"heterogeneity/{ds}_fedavg_alpha0.1", "final_accuracy")
        if krum_noniid is None or fedavg_noniid is None:
            skip("krum-noniid-weakness", f"{ds}: missing alpha records")
        else:
            check(
                "krum-noniid-weakness",
                krum_noniid <= fedavg_noniid + 0.02,
                f"{ds}: krum non-IID {krum_noniid:.4f} should not beat "
                f"fedavg {fedavg_noniid:.4f}",
            )

        # 9-10. topology orderings
        krum_ring = acc(f"topologies/{ds}_krum_ring", "final_accuracy")
        krum_fully = acc(f"topologies/{ds}_krum_fully", "final_accuracy")
        if krum_ring is None or krum_fully is None:
            skip("krum-connectivity-weakness", f"{ds}: missing topo records")
        else:
            check(
                "krum-connectivity-weakness",
                krum_fully <= krum_ring + 0.02,
                f"{ds}: krum fully {krum_fully:.4f} should trail krum ring "
                f"{krum_ring:.4f} (candidate-set growth pathology)",
            )
        fa_ring = acc(f"topologies/{ds}_fedavg_ring", "final_accuracy")
        fa_fully = acc(f"topologies/{ds}_fedavg_fully", "final_accuracy")
        if fa_ring is None or fa_fully is None:
            skip("connectivity-helps-fedavg", f"{ds}: missing topo records")
        else:
            check(
                "connectivity-helps-fedavg",
                fa_fully >= fa_ring - 0.02,
                f"{ds}: fedavg fully {fa_fully:.4f} should be >= ring "
                f"{fa_ring:.4f} (averaging wants connectivity)",
            )

        # 12. evidential_trust vs fedavg per topology
        for topo in TOPOLOGIES:
            et = acc(f"topologies/{ds}_evidential_trust_{topo}", "final_accuracy")
            fa = acc(f"topologies/{ds}_fedavg_{topo}", "final_accuracy")
            if et is None or fa is None:
                skip("evtrust-every-topology", f"{ds}/{topo}: missing")
                continue
            check(
                "evtrust-every-topology",
                et >= fa - 0.05,
                f"{ds}/{topo}: evidential_trust {et:.4f} should be within "
                f"0.05 of fedavg {fa:.4f}",
            )

        # 13. alpha direction (see ALPHA DIRECTION in the docstring)
        for rule in ROBUST:
            lo = acc(f"heterogeneity/{ds}_{rule}_alpha0.1", "final_accuracy")
            hi = acc(f"heterogeneity/{ds}_{rule}_alpha1.0", "final_accuracy")
            if lo is None or hi is None:
                skip("alpha-direction", f"{ds}/{rule}: missing alpha records")
                continue
            check(
                "alpha-direction",
                lo >= hi - 0.02,
                f"{ds}/{rule}: alpha=0.1 accuracy {lo:.4f} should be >= "
                f"alpha=1.0 accuracy {hi:.4f} (per-node holdout regime)",
            )

        # 14. ablation stability bands
        for param, values in ABLATION_GRID.items():
            accs = [
                acc(f"ablation/{ds}_et_{param}_{v}", "final_accuracy") for v in values
            ]
            have = [a for a in accs if a is not None]
            if len(have) < len(values):
                skip("ablation-stability",
                     f"{ds}/{param}: {len(have)}/{len(values)} records")
                continue
            band = max(have) - min(have)
            check(
                "ablation-stability",
                band <= 0.15,
                f"{ds}/{param}: evidential_trust moved {band:.4f} (> 0.15) "
                f"across {values}",
            )

    # 11. evidential_trust top-tier under gaussian (global majority vote)
    best_count, cells = 0, 0
    for ds in DATASETS:
        for pct in ATTACK_PCTS:
            scores = {
                rule: acc(f"attacks/{ds}_{rule}_gaussian_{pct}") for rule in ROBUST
            }
            if any(v is None for v in scores.values()):
                continue
            cells += 1
            if scores["evidential_trust"] >= max(scores.values()) - 1e-9:
                best_count += 1
    if cells < 9:
        skip("evtrust-top-tier", f"only {cells}/9 gaussian cells present")
    else:
        check(
            "evtrust-top-tier",
            best_count * 2 > cells,
            f"evidential_trust best in only {best_count}/{cells} gaussian "
            "cells (needs majority)",
        )

    # 15. attacked-ablation stability (uci_har only — the committed cells)
    for param, values in ABLATION_ATTACKED_GRID.items():
        accs = [
            acc(f"ablation_attacked/uci_har_et_{param}_{v}", "final_accuracy") for v in values
        ]
        have = [a for a in accs if a is not None]
        if len(have) < len(values):
            skip("ablation-attacked-stability",
                 f"uci_har/{param}: {len(have)}/{len(values)} records")
            continue
        band = max(have) - min(have)
        check(
            "ablation-attacked-stability",
            band <= 0.10,
            f"uci_har/{param}: attacked evidential_trust moved {band:.4f} "
            f"(> 0.10) across {values}",
        )

    total = sum(families.values())
    print(
        f"{total} ordering checks across {len(families)} families, "
        f"{len(failures)} failures, {len(skipped)} skipped"
    )
    for fam in sorted(families):
        print(f"  {fam}: {families[fam]} checks")
    for s in skipped:
        print(f"SKIP: {s}")
    for f in failures:
        print(f"FAIL: {f}")
    # Machine-readable tail for the test harness.
    print(json.dumps({
        "checks": total,
        "families": len(families),
        "failures": len(failures),
        "skipped": len(skipped),
    }))
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()

#!/usr/bin/env python3
"""Assert the qualitative Byzantine-robustness orderings the reference paper
reports (reference: experiments/paper/RESULTS_SUMMARY.md:7-38, CCGrid'26
paper Tables II-III) hold in this framework's executed matrix.

Checks (per dataset, on the synthetic-fallback data):
1. Attack degrades fedavg: honest accuracy under 20%+ gaussian drops by
   >= 0.2 vs the no-attack baseline.
2. Robust rules survive: balance / ubar / sketchguard / evidential_trust
   keep honest accuracy within 0.25 of their own no-attack baseline under
   20% gaussian, and beat fedavg-under-attack by >= 0.15.
3. Krum's known weakness (reference RESULTS_SUMMARY.md:10-15: krum 46.8%
   vs fedavg 85.3% on UCI HAR): under non-IID (alpha=0.1) krum's clean
   accuracy trails fedavg's.
4. Nothing saturates: no-attack baselines land in (0.35, 0.999) — the
   round-1 failure mode was every config pinned at 1.0000.

Exit 0 iff every check passes. Usage:
    python experiments/paper/assert_orderings.py [--results PATH]
"""

import argparse
import json
import sys
from pathlib import Path

PAPER_DIR = Path(__file__).parent
DATASETS = ["uci_har", "pamap2", "ppg_dalia"]
ROBUST = ["balance", "ubar", "sketchguard", "evidential_trust"]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument(
        "--results", default=str(PAPER_DIR / "results" / "results.json")
    )
    args = ap.parse_args()

    records = json.loads(Path(args.results).read_text())
    by_name = {}
    for r in records:
        if r.get("ok"):
            by_name[Path(r["config"]).stem] = r

    def acc(name, key="honest_accuracy"):
        r = by_name.get(name)
        if r is None:
            return None
        v = r.get(key)
        return v if v is not None else r.get("final_accuracy")

    failures = []
    checked = 0

    def check(cond, msg):
        nonlocal checked
        checked += 1
        if not cond:
            failures.append(msg)

    for ds in DATASETS:
        clean_fedavg = acc(f"{ds}_fedavg", "final_accuracy")
        atk_fedavg = acc(f"{ds}_fedavg_gaussian_20")
        if clean_fedavg is None or atk_fedavg is None:
            failures.append(f"{ds}: missing fedavg baseline/attack records")
            continue

        check(
            0.35 < clean_fedavg < 0.999,
            f"{ds}: fedavg clean accuracy {clean_fedavg:.4f} outside "
            "(0.35, 0.999) — data saturated or broken",
        )
        check(
            clean_fedavg - atk_fedavg >= 0.2,
            f"{ds}: 20% gaussian should degrade fedavg by >=0.2 "
            f"(clean {clean_fedavg:.4f} -> attacked {atk_fedavg:.4f})",
        )

        for rule in ROBUST:
            clean = acc(f"{ds}_{rule}", "final_accuracy")
            attacked = acc(f"{ds}_{rule}_gaussian_20")
            if clean is None or attacked is None:
                failures.append(f"{ds}/{rule}: missing records")
                continue
            check(
                clean - attacked <= 0.25,
                f"{ds}/{rule}: robust rule lost {clean - attacked:.4f} "
                f"(> 0.25) under 20% gaussian",
            )
            check(
                attacked - atk_fedavg >= 0.15,
                f"{ds}/{rule}: attacked accuracy {attacked:.4f} should beat "
                f"fedavg-under-attack {atk_fedavg:.4f} by >= 0.15",
            )

        # Krum's non-IID weakness (alpha=0.1 heterogeneity category).
        krum_noniid = acc(f"{ds}_krum_alpha0.1", "final_accuracy")
        fedavg_noniid = acc(f"{ds}_fedavg_alpha0.1", "final_accuracy")
        if krum_noniid is not None and fedavg_noniid is not None:
            check(
                krum_noniid <= fedavg_noniid + 0.02,
                f"{ds}: krum non-IID {krum_noniid:.4f} should not beat "
                f"fedavg {fedavg_noniid:.4f} (reference krum degradation)",
            )

    print(f"{checked} ordering checks, {len(failures)} failures")
    for f in failures:
        print(f"FAIL: {f}")
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()

#!/usr/bin/env python3
"""Run the 3-condition DMTT experiment and assert its headline ordering.

Conditions (reference: experiments/paper/dmtt/01..03 — the reference ships
these configs but only placeholder results, documentation/
new_murmura_extension/paper.tex:712):

    01 static baseline   — fixed fully-connected graph, 30% topology liars
                           poisoning models, plain fedavg.
    02 dynamic no trust  — mobility G^t, same liars, no protocol.
    03 full DMTT         — same G^t + claim verification, Beta-evidence
                           trust, TopB collaborator selection.

Headline claim: full DMTT keeps honest accuracy above the unprotected
dynamic condition (03 > 02 by a clear margin) because trust gating cuts the
poisoned states out of aggregation.

Writes results_dmtt.json next to this file and exits non-zero if the
ordering fails.  Usage:
    python experiments/paper/dmtt/run_dmtt.py [--device cpu|tpu]
"""

import argparse
import json
import os
import subprocess
import sys
import time
from pathlib import Path

DMTT_DIR = Path(__file__).parent
REPO = DMTT_DIR.parent.parent.parent
CONDITIONS = ["01_baseline_static", "02_dynamic_no_trust", "03_dmtt"]


def run_one(name: str, device: str, timeout: float) -> dict:
    out = DMTT_DIR / "results" / f"{name}.json"
    out.parent.mkdir(exist_ok=True)
    env = dict(os.environ)
    env.setdefault("JAX_COMPILATION_CACHE_DIR", "/tmp/murmura_jax_cache")
    cmd = [sys.executable, "-m", "murmura_tpu", "run",
           str(DMTT_DIR / f"{name}.yaml"), "-o", str(out), "--quiet"]
    if device:
        cmd += ["--device", device]
    t0 = time.time()
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=timeout, cwd=REPO, env=env)
    except subprocess.TimeoutExpired:
        return {"condition": name, "ok": False,
                "error": f"timeout after {timeout}s",
                "wall_s": round(time.time() - t0, 1)}
    rec = {"condition": name, "ok": proc.returncode == 0,
           "wall_s": round(time.time() - t0, 1)}
    if proc.returncode != 0:
        rec["error"] = proc.stderr[-1500:]
        return rec
    hist = json.loads(out.read_text())
    honest = hist.get("honest_accuracy") or hist.get("mean_accuracy")
    rec.update(
        final_honest_accuracy=honest[-1],
        peak_honest_accuracy=max(honest),
        final_mean_accuracy=hist["mean_accuracy"][-1],
        rounds=len(hist["mean_accuracy"]),
    )
    return rec


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--device", choices=["cpu", "tpu"], default=None)
    ap.add_argument("--timeout", type=float, default=1200.0)
    args = ap.parse_args()

    records = [run_one(c, args.device, args.timeout) for c in CONDITIONS]
    by = {r["condition"]: r for r in records}

    failures = []
    if all(r.get("ok") for r in records):
        dmtt = by["03_dmtt"]["final_honest_accuracy"]
        no_trust = by["02_dynamic_no_trust"]["final_honest_accuracy"]
        static = by["01_baseline_static"]["final_honest_accuracy"]
        if not dmtt >= no_trust + 0.1:
            failures.append(
                f"full DMTT ({dmtt:.4f}) should beat dynamic-no-trust "
                f"({no_trust:.4f}) by >= 0.1"
            )
        if not dmtt >= static:
            failures.append(
                f"full DMTT ({dmtt:.4f}) should not trail the poisoned "
                f"static baseline ({static:.4f})"
            )
    else:
        failures.append("not all conditions ran ok")

    blob = {"records": records, "ordering_failures": failures}
    (DMTT_DIR / "results_dmtt.json").write_text(json.dumps(blob, indent=2) + "\n")
    print(json.dumps(blob, indent=2))
    sys.exit(1 if failures else 0)


if __name__ == "__main__":
    main()

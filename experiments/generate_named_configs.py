#!/usr/bin/env python3
"""Generate the 32 named experiment configs (exp1-exp4).

The reference ships these as hand-written YAMLs under experiments/configs/
(reference: experiments/configs/exp1_baseline_*.yaml ... exp4_*): a baseline
sweep over the six aggregation rules, an attack study (gaussian 20/30/40%,
mild-noise, directed deviation), a heterogeneity study (Dirichlet alpha
0.1/1.0), and a personalization study at extreme non-IID including the
"local only" upper bound (evidential_trust with self_weight=1.0 and an
impossible trust threshold).  Here they are emitted from a delta table over
one base config so the shared structure lives in one place.

All configs target UCI HAR (10 nodes, fully connected unless noted); without
an on-disk dataset the adapter emits the calibrated synthetic fallback
(murmura_tpu/data/wearables.py), so the whole set runs in a zero-egress
environment.
"""

import argparse
from pathlib import Path

import yaml

EXP_DIR = Path(__file__).parent

BASE = {
    "experiment": {"name": "", "seed": 42, "rounds": 50, "verbose": True},
    "topology": {"type": "fully", "num_nodes": 10, "seed": 12345},
    "aggregation": {"algorithm": "fedavg", "params": {}},
    "attack": {"enabled": False},
    "training": {"local_epochs": 2, "batch_size": 32, "lr": 0.01,
                 "max_samples": None},
    "data": {
        "adapter": "wearables.uci_har",
        "params": {
            "data_path": "wearables_datasets/UCI HAR Dataset",
            "split": "train",
            "partition_method": "dirichlet",
            "alpha": 0.5,
        },
    },
    "model": {
        "factory": "examples.wearables.uci_har",
        "params": {"input_dim": 561, "hidden_dims": [256, 128],
                   "num_classes": 6, "dropout": 0.3},
    },
    "backend": "simulation",
}

# Per-rule aggregation params (reference values).
AGG = {
    "fedavg": {},
    "krum": {"f": 2},
    "balance": {"gamma": 0.5, "kappa": 1.0, "alpha": 0.5, "min_neighbors": 1},
    "ubar": {"rho": 0.5, "alpha": 0.5, "min_neighbors": 1},
    "sketchguard": {"gamma": 0.5, "kappa": 1.0, "alpha": 0.5,
                    "sketch_size": 1000},
    "evidential_trust": {
        "vacuity_threshold": 0.5, "accuracy_weight": 0.5,
        "trust_threshold": 0.1, "self_weight": 0.5,
        "use_adaptive_trust": True, "trust_momentum": 0.7,
        "use_tightening_threshold": True, "gamma": 0.5, "kappa": 1.0,
        "max_eval_samples": 100, "track_statistics": True,
    },
}

GAUSSIAN = {"enabled": True, "type": "gaussian", "percentage": 0.2,
            "params": {"noise_std": 10.0}}

# (filename, display name, algorithm, overrides)
#   overrides keys: attack, data_alpha, lr, agg (merged into AGG[algo]),
#   topology_type
EXPERIMENTS = [
    # exp1: clean baseline, all six rules
    ("exp1_baseline_fedavg", "EXP1-Baseline-FedAvg", "fedavg", {}),
    ("exp1_baseline_krum", "EXP1-Baseline-Krum", "krum", {}),
    ("exp1_baseline_balance", "EXP1-Baseline-BALANCE", "balance", {}),
    ("exp1_baseline_ubar", "EXP1-Baseline-UBAR", "ubar", {}),
    ("exp1_baseline_sketchguard", "EXP1-Baseline-Sketchguard",
     "sketchguard", {}),
    ("exp1_baseline_evidential", "EXP1-Baseline-EvidentialTrust",
     "evidential_trust", {"lr": 0.001}),
    # exp2: attack study
    ("exp2_attack20_fedavg", "EXP2-Attack20-FedAvg", "fedavg",
     {"attack": GAUSSIAN}),
    ("exp2_attack20_krum", "EXP2-Attack20-Krum", "krum",
     {"attack": GAUSSIAN}),
    ("exp2_attack20_balance", "EXP2-Attack20-BALANCE", "balance",
     {"attack": GAUSSIAN}),
    ("exp2_attack20_ubar", "EXP2-Attack20-UBAR", "ubar",
     {"attack": GAUSSIAN}),
    ("exp2_attack20_sketchguard", "EXP2-Attack20-Sketchguard", "sketchguard",
     {"attack": GAUSSIAN}),
    ("exp2_attack20_evidential", "EXP2-Attack20-EvidentialTrust",
     "evidential_trust", {"attack": GAUSSIAN, "lr": 0.001}),
    ("exp2_attack20_mild_evidential", "EXP2-Attack20-Mild-EvidentialTrust",
     "evidential_trust",
     {"attack": {**GAUSSIAN, "params": {"noise_std": 1.0}}, "lr": 0.001}),
    ("exp2_attack30_krum", "EXP2-Attack30-Krum", "krum",
     {"attack": {**GAUSSIAN, "percentage": 0.3}, "agg": {"f": 3}}),
    ("exp2_attack30_evidential", "EXP2-Attack30-EvidentialTrust",
     "evidential_trust",
     {"attack": {**GAUSSIAN, "percentage": 0.3}, "lr": 0.001}),
    ("exp2_attack40_krum", "EXP2-Attack40-Krum", "krum",
     {"attack": {**GAUSSIAN, "percentage": 0.4}, "agg": {"f": 4}}),
    ("exp2_attack40_evidential", "EXP2-Attack40-EvidentialTrust",
     "evidential_trust",
     {"attack": {**GAUSSIAN, "percentage": 0.4}, "lr": 0.001}),
    ("exp2_directed_krum", "EXP2-Directed20-Krum", "krum",
     {"attack": {"enabled": True, "type": "directed_deviation",
                 "percentage": 0.2, "params": {"lambda_param": -5.0}}}),
    ("exp2_directed_evidential", "EXP2-Directed20-EvidentialTrust",
     "evidential_trust",
     {"attack": {"enabled": True, "type": "directed_deviation",
                 "percentage": 0.2, "params": {"lambda_param": -5.0}},
      "lr": 0.001}),
    # exp3: heterogeneity study
    ("exp3_heterog_extreme_fedavg", "EXP3-Heterog-Extreme-FedAvg", "fedavg",
     {"data_alpha": 0.1}),
    ("exp3_heterog_extreme_evidential", "EXP3-Heterog-Extreme-EvidentialTrust",
     "evidential_trust", {"data_alpha": 0.1, "lr": 0.001}),
    ("exp3_heterog_extreme_attack_krum", "EXP3-Heterog-Extreme-Attack-Krum",
     "krum", {"data_alpha": 0.1, "attack": GAUSSIAN}),
    ("exp3_heterog_extreme_attack_evidential",
     "EXP3-Heterog-Extreme-Attack-EvidentialTrust", "evidential_trust",
     {"data_alpha": 0.1, "attack": GAUSSIAN, "lr": 0.001}),
    ("exp3_heterog_mild_fedavg", "EXP3-Heterog-Mild-FedAvg", "fedavg",
     {"data_alpha": 1.0}),
    ("exp3_heterog_mild_evidential", "EXP3-Heterog-Mild-EvidentialTrust",
     "evidential_trust", {"data_alpha": 1.0, "lr": 0.001}),
    # exp4: personalization study at extreme non-IID
    ("exp4_personalization_fedavg", "EXP4-Personalization-FedAvg", "fedavg",
     {"data_alpha": 0.1}),
    ("exp4_personalization_krum", "EXP4-Personalization-Krum", "krum",
     {"data_alpha": 0.1}),
    ("exp4_personalization_balance", "EXP4-Personalization-BALANCE",
     "balance", {"data_alpha": 0.1}),
    ("exp4_personalization_ubar", "EXP4-Personalization-UBAR", "ubar",
     {"data_alpha": 0.1}),
    ("exp4_personalization_sketchguard", "EXP4-Personalization-Sketchguard",
     "sketchguard", {"data_alpha": 0.1}),
    ("exp4_personalization_evidential", "EXP4-Personalization-EvidentialTrust",
     "evidential_trust",
     {"data_alpha": 0.1, "agg": {"self_weight": 0.6, "accuracy_weight": 0.7}}),
    # Local-only upper bound: reject every neighbor, 100% self weight.
    ("exp4_personalization_local_only", "EXP4-Personalization-LocalOnly",
     "evidential_trust",
     {"data_alpha": 0.1, "topology_type": "ring",
      "agg": {"self_weight": 1.0, "trust_threshold": 1.0,
              "use_adaptive_trust": False,
              "use_tightening_threshold": False}}),
]


def build(name: str, algo: str, ov: dict) -> dict:
    cfg = yaml.safe_load(yaml.safe_dump(BASE))  # deep copy
    cfg["experiment"]["name"] = name
    cfg["aggregation"]["algorithm"] = algo
    cfg["aggregation"]["params"] = {**AGG[algo], **ov.get("agg", {})}
    if "attack" in ov:
        cfg["attack"] = dict(ov["attack"])
    if "data_alpha" in ov:
        cfg["data"]["params"]["alpha"] = ov["data_alpha"]
    if "lr" in ov:
        cfg["training"]["lr"] = ov["lr"]
    if "topology_type" in ov:
        cfg["topology"]["type"] = ov["topology_type"]
    return cfg


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default=str(EXP_DIR / "configs"))
    args = ap.parse_args()
    out = Path(args.out)
    out.mkdir(parents=True, exist_ok=True)
    for fname, display, algo, ov in EXPERIMENTS:
        (out / f"{fname}.yaml").write_text(
            yaml.safe_dump(build(display, algo, ov), sort_keys=False)
        )
    print(f"Wrote {len(EXPERIMENTS)} configs under {out}")


if __name__ == "__main__":
    main()

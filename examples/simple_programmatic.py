"""Python-API walkthrough: 10-node ring, synthetic data, FedAvg
(reference: murmura/examples/simple_programmatic.py:24-100).

Instead of a YAML file, build every component directly:
topology -> federated data -> model -> aggregator -> round program -> Network.
Run it with: python examples/simple_programmatic.py
"""

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from murmura_tpu.aggregation import build_aggregator
from murmura_tpu.core.network import Network
from murmura_tpu.core.rounds import build_round_program
from murmura_tpu.data.base import stack_partitions
from murmura_tpu.data.partitioners import iid_partition
from murmura_tpu.data.synthetic import make_synthetic
from murmura_tpu.models.registry import build_model
from murmura_tpu.topology import create_topology


def main():
    num_nodes, rounds = 10, 15

    # 1. A ring topology (reference: create_topology, generators.py:11-46).
    topology = create_topology("ring", num_nodes=num_nodes)
    print(f"Topology: ring, {num_nodes} nodes, avg degree {topology.avg_degree():.1f}")

    # 2. Synthetic clustered data, IID-partitioned across the nodes, stacked
    #    into [N, max_samples, ...] arrays with validity masks.
    x, y = make_synthetic(num_samples=3000, input_shape=(32,), num_classes=4, seed=0)
    parts = iid_partition(len(y), num_nodes, seed=0)
    data = stack_partitions(x, y, parts, num_classes=4)
    print(f"Data: {data.num_samples.sum()} samples over {data.num_nodes} nodes")

    # 3. A small MLP and the FedAvg rule.
    model = build_model("mlp", {"input_dim": 32, "hidden_dims": [64, 32],
                                "num_classes": 4})
    agg = build_aggregator("fedavg", {}, total_rounds=rounds)

    # 4. The whole FL round as one jitted program over stacked pytrees.
    program = build_round_program(
        model, agg, data,
        local_epochs=2, batch_size=32, lr=0.05, total_rounds=rounds, seed=0,
    )

    # 5. Train and read the history (same schema as the YAML-driven CLI).
    network = Network(program, topology, seed=0)
    history = network.train(rounds=rounds, verbose=True)
    print(f"\nFinal mean accuracy: {history['mean_accuracy'][-1]:.4f}")


if __name__ == "__main__":
    main()

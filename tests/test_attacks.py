"""Attack subsystem tests (reference semantics: murmura/attacks/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from murmura_tpu.attacks import (
    false_claims,
    make_directed_deviation_attack,
    make_gaussian_attack,
    make_topology_liar_attack,
    select_compromised,
)


class TestSelection:
    def test_count_rule(self):
        """max(1, floor(pct*n)) when pct > 0 (gaussian.py:36-44)."""
        assert select_compromised(10, 0.2, seed=1).sum() == 2
        assert select_compromised(10, 0.05, seed=1).sum() == 1  # ceil-to-1
        assert select_compromised(10, 0.0, seed=1).sum() == 0

    def test_deterministic(self):
        a = select_compromised(20, 0.3, seed=7)
        b = select_compromised(20, 0.3, seed=7)
        assert np.array_equal(a, b)
        c = select_compromised(20, 0.3, seed=8)
        assert not np.array_equal(a, c)


class TestGaussian:
    def test_noise_only_on_compromised(self):
        atk = make_gaussian_attack(4, 0.5, noise_std=1.0, seed=0)
        flat = jnp.zeros((4, 16))
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        out = np.asarray(atk.apply(flat, comp, jax.random.PRNGKey(0), 0))
        for i in range(4):
            if atk.compromised[i]:
                assert np.abs(out[i]).max() > 0
            else:
                assert np.abs(out[i]).max() == 0

    def test_noise_scale(self):
        atk = make_gaussian_attack(2, 1.0, noise_std=10.0, seed=0)
        flat = jnp.zeros((2, 10000))
        comp = jnp.ones(2)
        out = np.asarray(atk.apply(flat, comp, jax.random.PRNGKey(1), 0))
        assert out.std() == pytest.approx(10.0, rel=0.05)


class TestDirectedDeviation:
    def test_lambda_scaling(self):
        atk = make_directed_deviation_attack(3, 0.34, lambda_param=-5.0, seed=0)
        flat = jnp.ones((3, 8))
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        out = np.asarray(atk.apply(flat, comp, None, 0))
        for i in range(3):
            expected = -5.0 if atk.compromised[i] else 1.0
            np.testing.assert_allclose(out[i], expected)

    def test_registry_name(self):
        """The config-visible name "directed_deviation" (ATTACKS registry /
        schema enum) builds the same attack the factory helper does."""
        from murmura_tpu.attacks import ATTACKS

        atk = ATTACKS["directed_deviation"](
            num_nodes=3, attack_percentage=0.34, lambda_param=-5.0, seed=0
        )
        ref = make_directed_deviation_attack(3, 0.34, lambda_param=-5.0, seed=0)
        assert np.array_equal(atk.compromised, ref.compromised)
        flat = jnp.ones((3, 8))
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(atk.apply(flat, comp, None, 0)),
            np.asarray(ref.apply(flat, comp, None, 0)),
        )


class TestTopologyLiar:
    def test_false_claims_add_coalition(self):
        """Liar's claim = true neighbors ∪ other Byzantine nodes
        (topology_liar.py:78-102)."""
        true_adj = jnp.asarray(np.array([
            [0, 1, 0, 0],
            [1, 0, 1, 0],
            [0, 1, 0, 1],
            [0, 0, 1, 0],
        ], dtype=np.float32))
        comp = jnp.asarray(np.array([1, 0, 0, 1], dtype=np.float32))
        claims = np.asarray(false_claims(true_adj, comp))
        # honest rows unchanged
        np.testing.assert_array_equal(claims[1], [1, 0, 1, 0])
        np.testing.assert_array_equal(claims[2], [0, 1, 0, 1])
        # liar 0 adds fellow-Byzantine 3; liar 3 adds 0
        np.testing.assert_array_equal(claims[0], [0, 1, 0, 1])
        np.testing.assert_array_equal(claims[3], [1, 0, 1, 0])

    def test_pure_liar_no_model_poisoning(self):
        atk = make_topology_liar_attack(4, 0.5, seed=0)
        flat = jnp.ones((4, 8))
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        out = np.asarray(atk.apply(flat, comp, jax.random.PRNGKey(0), 0))
        np.testing.assert_allclose(out, 1.0)

    def test_wrapped_model_attack_shares_compromised_set(self):
        inner = make_gaussian_attack(4, 0.5, noise_std=1.0, seed=99)
        atk = make_topology_liar_attack(4, 0.5, seed=0, model_attack=inner)
        flat = jnp.zeros((4, 8))
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        out = np.asarray(atk.apply(flat, comp, jax.random.PRNGKey(0), 0))
        for i in range(4):
            assert (np.abs(out[i]).max() > 0) == bool(atk.compromised[i])


class TestAttackProtocol:
    def test_is_compromised_and_set(self):
        atk = make_gaussian_attack(10, 0.2, seed=42)
        nodes = atk.get_compromised_nodes()
        assert len(nodes) == 2
        for i in range(10):
            assert atk.is_compromised(i) == (i in nodes)


class TestALIE:
    """Beyond-parity colluding attack (alie.py; Baruch et al. 2019)."""

    def test_compromised_rows_collude_at_mu_minus_z_sigma(self):
        from murmura_tpu.attacks.alie import make_alie_attack

        atk = make_alie_attack(10, 0.2, z=1.5, seed=42)
        rng = np.random.default_rng(0)
        flat = jnp.asarray(rng.normal(size=(10, 32)).astype(np.float32))
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        out = np.asarray(atk.apply(flat, comp, jax.random.PRNGKey(0), 0))

        honest = ~atk.compromised
        # Honest rows pass through untouched.
        np.testing.assert_array_equal(out[honest], np.asarray(flat)[honest])
        # Compromised rows all broadcast the identical colluding vector.
        comp_rows = out[atk.compromised]
        np.testing.assert_array_equal(comp_rows[0], comp_rows[1])
        # ... equal to mu - z*sigma of the HONEST population.
        mu = np.asarray(flat)[honest].mean(axis=0)
        sigma = np.asarray(flat)[honest].std(axis=0)
        np.testing.assert_allclose(comp_rows[0], mu - 1.5 * sigma, atol=1e-5)

    def test_z_max_grows_with_coalition_size(self):
        from murmura_tpu.attacks.alie import alie_z_max

        zs = [alie_z_max(20, m) for m in (2, 6, 8)]
        assert zs[0] <= zs[1] <= zs[2], zs
        assert zs[2] < 3.0  # stays a *little* deviation

    def test_alie_dmtt_distributed_rejected(self):
        # DMTTNodeProcess has no coalition branch; alie there would be a
        # silent no-op attack (round-5 review finding) — must fail loud.
        from murmura_tpu.config import Config
        from murmura_tpu.utils.factories import ConfigError, build_attack

        cfg = Config.model_validate(
            {
                "experiment": {"name": "a", "seed": 0, "rounds": 1},
                "topology": {"type": "ring", "num_nodes": 4},
                "aggregation": {"algorithm": "fedavg"},
                "attack": {"enabled": True, "type": "alie",
                            "percentage": 0.25},
                "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.1},
                "data": {"adapter": "synthetic",
                          "params": {"num_samples": 64, "input_dim": 4,
                                     "num_classes": 2}},
                "model": {"factory": "mlp",
                           "params": {"input_dim": 4, "hidden_dims": [4],
                                      "num_classes": 2}},
                "backend": "distributed",
                "distributed": {"transport": "ipc"},
                "mobility": {"area_size": 50.0, "comm_range": 30.0,
                              "max_speed": 5.0, "seed": 7},
                "dmtt": {"budget_B": 3},
            }
        )
        with pytest.raises(ConfigError, match="DMTT"):
            build_attack(cfg)

    def test_alie_distributed_single_colluder_rejected(self):
        # One colluder makes the coalition sigma 0 -> silent no-attack run;
        # must fail loud at build time (round-5 review finding).
        from murmura_tpu.config import Config
        from murmura_tpu.utils.factories import ConfigError, build_attack

        cfg = Config.model_validate(
            {
                "experiment": {"name": "a", "seed": 0, "rounds": 1},
                "topology": {"type": "ring", "num_nodes": 4},
                "aggregation": {"algorithm": "fedavg"},
                "attack": {"enabled": True, "type": "alie",
                            "percentage": 0.05},  # ceil-to-1 colluder
                "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.1},
                "data": {"adapter": "synthetic",
                          "params": {"num_samples": 64, "input_dim": 4,
                                     "num_classes": 2}},
                "model": {"factory": "mlp",
                           "params": {"input_dim": 4, "hidden_dims": [4],
                                      "num_classes": 2}},
                "backend": "distributed",
                "distributed": {"transport": "ipc"},
            }
        )
        with pytest.raises(ConfigError, match="at least 2"):
            build_attack(cfg)

    def test_colluding_vector_is_paper_estimator(self):
        # The ZMQ-backend estimator (coalition sample, f64 host stats):
        # mu - z*sigma over the colluders' own benign states.
        from murmura_tpu.attacks.alie import colluding_vector

        rng = np.random.default_rng(3)
        sample = rng.normal(size=(4, 16)).astype(np.float32)
        out = colluding_vector(sample, z=1.2)
        assert out.dtype == np.float32
        np.testing.assert_allclose(
            out, sample.mean(0) - 1.2 * sample.std(0), atol=1e-6
        )
        # Single colluder: sigma undefined-in-spirit, degenerates to the
        # benign state rather than fabricating a deviation.
        np.testing.assert_allclose(
            colluding_vector(sample[:1], z=5.0), sample[0], atol=1e-6
        )

    def test_network_runs_and_biases_fedavg(self):
        from murmura_tpu.config import Config
        from murmura_tpu.utils.factories import build_network_from_config

        base = {
            "experiment": {"name": "alie", "seed": 3, "rounds": 3},
            "topology": {"type": "fully", "num_nodes": 8},
            "aggregation": {"algorithm": "fedavg"},
            "training": {"local_epochs": 1, "batch_size": 16, "lr": 0.1},
            "data": {"adapter": "synthetic",
                      "params": {"num_samples": 640, "input_dim": 24,
                                 "num_classes": 4}},
            "model": {"factory": "mlp",
                       "params": {"input_dim": 24, "hidden_dims": [32],
                                  "num_classes": 4}},
            "backend": "simulation",
            "tpu": {"compute_dtype": "float32"},
        }
        clean = build_network_from_config(
            Config.model_validate(base)
        ).train(rounds=3)
        attacked_cfg = {**base,
                         "attack": {"enabled": True, "type": "alie",
                                    "percentage": 0.375,
                                    "params": {"z": 3.0}}}
        attacked = build_network_from_config(
            Config.model_validate(attacked_cfg)
        ).train(rounds=3)
        assert np.isfinite(attacked["honest_accuracy"]).all()
        # A strong colluding deviation (z=3, 3/8 nodes) must cost fedavg
        # accuracy while training is still in flight (round 1).  By
        # design ALIE fades as honest nodes converge — sigma_honest
        # shrinks, so the colluding vector collapses toward mu and the
        # trivially-separable synthetic task still saturates; the
        # pre-saturation round is where the bias is observable.
        assert (
            attacked["honest_accuracy"][0] < clean["mean_accuracy"][0] - 0.05
        ), (attacked["honest_accuracy"], clean["mean_accuracy"])

    def test_topology_liar_rejects_unknown_inner_attack(self):
        from murmura_tpu.config import Config
        from murmura_tpu.utils.factories import ConfigError, build_attack

        cfg = Config.model_validate(
            {
                "experiment": {"name": "t", "seed": 0, "rounds": 1},
                "topology": {"type": "ring", "num_nodes": 4},
                "aggregation": {"algorithm": "fedavg"},
                "attack": {"enabled": True, "type": "topology_liar",
                            "percentage": 0.25,
                            "params": {"model_attack_type": "alie"}},
                "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.1},
                "data": {"adapter": "synthetic",
                          "params": {"num_samples": 64, "input_dim": 4,
                                     "num_classes": 2}},
                "model": {"factory": "mlp",
                           "params": {"input_dim": 4, "hidden_dims": [4],
                                      "num_classes": 2}},
                "backend": "simulation",
            }
        )
        with pytest.raises(ConfigError, match="model_attack_type"):
            build_attack(cfg)


class TestIPM:
    """Beyond-parity colluding attack #2 (ipm.py; Xie et al. UAI 2020)."""

    def test_compromised_rows_broadcast_negated_honest_mean(self):
        from murmura_tpu.attacks.ipm import make_ipm_attack

        atk = make_ipm_attack(10, 0.2, epsilon=2.0, seed=42)
        rng = np.random.default_rng(1)
        flat = jnp.asarray(rng.normal(size=(10, 32)).astype(np.float32))
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        out = np.asarray(atk.apply(flat, comp, jax.random.PRNGKey(0), 0))

        honest = ~atk.compromised
        np.testing.assert_array_equal(out[honest], np.asarray(flat)[honest])
        comp_rows = out[atk.compromised]
        np.testing.assert_array_equal(comp_rows[0], comp_rows[1])
        mu = np.asarray(flat)[honest].mean(axis=0)
        np.testing.assert_allclose(comp_rows[0], -2.0 * mu, atol=1e-5)

    def test_ipm_vector_estimator_and_single_colluder(self):
        from murmura_tpu.attacks.ipm import ipm_vector

        rng = np.random.default_rng(2)
        sample = rng.normal(size=(3, 8)).astype(np.float32)
        np.testing.assert_allclose(
            ipm_vector(sample, 0.5), -0.5 * sample.mean(0), atol=1e-6
        )
        # Single colluder stays a REAL attack (sign-flipped own state),
        # unlike ALIE's sigma=0 degeneration — no minimum-coalition guard.
        np.testing.assert_allclose(
            ipm_vector(sample[:1], 1.5), -1.5 * sample[0], atol=1e-6
        )

    def test_network_geometric_median_resists_fedavg_degrades(self):
        from murmura_tpu.config import Config
        from murmura_tpu.utils.factories import build_network_from_config

        base = {
            "experiment": {"name": "ipm", "seed": 3, "rounds": 3},
            "topology": {"type": "fully", "num_nodes": 8},
            "aggregation": {"algorithm": "fedavg"},
            "training": {"local_epochs": 1, "batch_size": 16, "lr": 0.1},
            "data": {"adapter": "synthetic",
                      "params": {"num_samples": 640, "input_dim": 24,
                                 "num_classes": 4}},
            "model": {"factory": "mlp",
                       "params": {"input_dim": 24, "hidden_dims": [32],
                                  "num_classes": 4}},
            "backend": "simulation",
            "attack": {"enabled": True, "type": "ipm", "percentage": 0.25,
                        "params": {"epsilon": 2.0}},
        }
        fed = build_network_from_config(
            Config.model_validate(base)
        ).train(rounds=3)
        gm_cfg = {**base, "aggregation": {"algorithm": "geometric_median",
                                           "params": {"max_iters": 8}}}
        gm = build_network_from_config(
            Config.model_validate(gm_cfg)
        ).train(rounds=3)
        assert np.isfinite(fed["honest_accuracy"]).all()
        # -2x mean from 2/8 nodes drives the fedavg aggregate backwards;
        # the geometric median downweights the identical colluding pair.
        assert gm["honest_accuracy"][-1] > fed["honest_accuracy"][-1] + 0.1

    def test_ipm_dmtt_distributed_rejected(self):
        from murmura_tpu.config import Config
        from murmura_tpu.utils.factories import ConfigError, build_attack

        cfg = Config.model_validate(
            {
                "experiment": {"name": "a", "seed": 0, "rounds": 1},
                "topology": {"type": "ring", "num_nodes": 4},
                "aggregation": {"algorithm": "fedavg"},
                "attack": {"enabled": True, "type": "ipm",
                            "percentage": 0.25},
                "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.1},
                "data": {"adapter": "synthetic",
                          "params": {"num_samples": 64, "input_dim": 4,
                                     "num_classes": 2}},
                "model": {"factory": "mlp",
                           "params": {"input_dim": 4, "hidden_dims": [4],
                                      "num_classes": 2}},
                "backend": "distributed",
                "distributed": {"transport": "ipc"},
                "mobility": {"area_size": 50.0, "comm_range": 30.0,
                              "max_speed": 5.0, "seed": 7},
                "dmtt": {"budget_B": 3},
            }
        )
        with pytest.raises(ConfigError, match="DMTT"):
            build_attack(cfg)


class TestLabelFlip:
    def test_poison_only_compromised_real_samples(self):
        from murmura_tpu.attacks import poison_labels

        rng = np.random.default_rng(0)
        y = rng.integers(0, 4, size=(5, 10))
        mask = np.ones((5, 10), np.float32)
        mask[:, 8:] = 0.0  # padding
        comp = np.array([False, True, False, True, False])
        out = poison_labels(y, mask, comp, num_classes=4, flip_fraction=1.0,
                            seed=1)
        # honest rows untouched; compromised real samples rotated by 1;
        # padded positions untouched even on compromised rows.
        np.testing.assert_array_equal(out[~comp], y[~comp])
        np.testing.assert_array_equal(out[comp][:, :8], (y[comp][:, :8] + 1) % 4)
        np.testing.assert_array_equal(out[comp][:, 8:], y[comp][:, 8:])
        assert out.min() >= 0 and out.max() < 4

    def test_flip_fraction_partial(self):
        from murmura_tpu.attacks import poison_labels

        y = np.zeros((2, 20), np.int64)
        mask = np.ones((2, 20), np.float32)
        comp = np.array([True, False])
        out = poison_labels(y, mask, comp, num_classes=3, flip_fraction=0.5,
                            seed=2)
        assert (out[0] != 0).sum() == 10  # exactly half flipped (0 -> 1)
        assert (out[1] != 0).sum() == 0

    def test_states_pass_through_and_trains_locally(self):
        from murmura_tpu.attacks import ATTACKS

        atk = ATTACKS["label_flip"](num_nodes=6, attack_percentage=0.3)
        assert atk.trains_locally
        flat = jnp.asarray(np.random.default_rng(3).normal(size=(6, 7)),
                           jnp.float32)
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        out = atk.apply(flat, comp, jax.random.PRNGKey(0), 0)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(flat))

    def test_fedavg_degrades_vs_clean(self):
        """The end-to-end proof that the poison actually rides the
        compromised nodes' local SGD (frozen nodes + identity states
        would leave fedavg untouched)."""
        from murmura_tpu.config import Config
        from murmura_tpu.utils.factories import build_network_from_config

        def cfg(enabled):
            return Config.model_validate({
                "experiment": {"name": "lf", "seed": 5, "rounds": 6},
                "topology": {"type": "fully", "num_nodes": 8},
                "aggregation": {"algorithm": "fedavg", "params": {}},
                "attack": {"enabled": enabled, "type": "label_flip",
                            "percentage": 0.5,
                            "params": {"flip_fraction": 1.0}},
                "training": {"local_epochs": 1, "batch_size": 16, "lr": 0.1},
                "data": {"adapter": "synthetic",
                          "params": {"num_samples": 480, "input_dim": 16,
                                     "num_classes": 6}},
                "model": {"factory": "mlp",
                           "params": {"input_dim": 16, "hidden_dims": [32],
                                      "num_classes": 6}},
                "backend": "simulation",
            })

        clean = build_network_from_config(cfg(False)).train(rounds=6)
        poisoned = build_network_from_config(cfg(True)).train(rounds=6)
        # A clean run has no compromised set (all nodes are honest), so
        # its mean_accuracy IS the honest accuracy.
        # Measured margin at these settings: clean 1.0 vs poisoned honest
        # ~0.69 (50% of nodes fully poisoned on fully-connected gossip).
        assert (poisoned["honest_accuracy"][-1]
                < clean["mean_accuracy"][-1] - 0.1)

    def test_rejected_on_distributed_backend(self):
        from murmura_tpu.config import Config
        from murmura_tpu.utils.factories import ConfigError, build_attack

        cfg = Config.model_validate({
            "experiment": {"name": "lf-d", "seed": 5, "rounds": 2},
            "topology": {"type": "ring", "num_nodes": 4},
            "aggregation": {"algorithm": "fedavg", "params": {}},
            "attack": {"enabled": True, "type": "label_flip",
                        "percentage": 0.25},
            "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.1},
            "data": {"adapter": "synthetic",
                      "params": {"num_samples": 64, "input_dim": 8,
                                 "num_classes": 2}},
            "model": {"factory": "mlp",
                       "params": {"input_dim": 8, "hidden_dims": [8],
                                  "num_classes": 2}},
            "backend": "distributed",
        })
        with pytest.raises(ConfigError, match="label_flip"):
            build_attack(cfg)

    def test_invalid_flip_fraction_is_config_error(self):
        from murmura_tpu.config import Config
        from murmura_tpu.utils.factories import ConfigError, build_attack

        cfg = Config.model_validate({
            "experiment": {"name": "lf-v", "seed": 5, "rounds": 2},
            "topology": {"type": "ring", "num_nodes": 4},
            "aggregation": {"algorithm": "fedavg", "params": {}},
            "attack": {"enabled": True, "type": "label_flip",
                        "percentage": 0.25,
                        "params": {"flip_fraction": 1.5}},
            "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.1},
            "data": {"adapter": "synthetic",
                      "params": {"num_samples": 64, "input_dim": 8,
                                 "num_classes": 2}},
            "model": {"factory": "mlp",
                       "params": {"input_dim": 8, "hidden_dims": [8],
                                  "num_classes": 2}},
            "backend": "simulation",
        })
        with pytest.raises(ConfigError, match="flip_fraction"):
            build_attack(cfg)

    def test_no_holdout_rejected(self):
        """Evaluation falling back to the poisoned training shard would
        score compromised nodes against flipped labels — must fail loud."""
        from murmura_tpu.config import Config
        from murmura_tpu.utils.factories import (
            ConfigError, build_network_from_config,
        )

        cfg = Config.model_validate({
            "experiment": {"name": "lf-h", "seed": 5, "rounds": 2},
            "topology": {"type": "ring", "num_nodes": 4},
            "aggregation": {"algorithm": "fedavg", "params": {}},
            "attack": {"enabled": True, "type": "label_flip",
                        "percentage": 0.25},
            "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.1},
            "data": {"adapter": "synthetic",
                      "params": {"num_samples": 64, "input_dim": 8,
                                 "num_classes": 2,
                                 "holdout_fraction": 0.0}},
            "model": {"factory": "mlp",
                       "params": {"input_dim": 8, "hidden_dims": [8],
                                  "num_classes": 2}},
            "backend": "simulation",
        })
        with pytest.raises(ConfigError, match="clean eval split"):
            build_network_from_config(cfg)

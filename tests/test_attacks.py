"""Attack subsystem tests (reference semantics: murmura/attacks/)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from murmura_tpu.attacks import (
    false_claims,
    make_directed_deviation_attack,
    make_gaussian_attack,
    make_topology_liar_attack,
    select_compromised,
)


class TestSelection:
    def test_count_rule(self):
        """max(1, floor(pct*n)) when pct > 0 (gaussian.py:36-44)."""
        assert select_compromised(10, 0.2, seed=1).sum() == 2
        assert select_compromised(10, 0.05, seed=1).sum() == 1  # ceil-to-1
        assert select_compromised(10, 0.0, seed=1).sum() == 0

    def test_deterministic(self):
        a = select_compromised(20, 0.3, seed=7)
        b = select_compromised(20, 0.3, seed=7)
        assert np.array_equal(a, b)
        c = select_compromised(20, 0.3, seed=8)
        assert not np.array_equal(a, c)


class TestGaussian:
    def test_noise_only_on_compromised(self):
        atk = make_gaussian_attack(4, 0.5, noise_std=1.0, seed=0)
        flat = jnp.zeros((4, 16))
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        out = np.asarray(atk.apply(flat, comp, jax.random.PRNGKey(0), 0))
        for i in range(4):
            if atk.compromised[i]:
                assert np.abs(out[i]).max() > 0
            else:
                assert np.abs(out[i]).max() == 0

    def test_noise_scale(self):
        atk = make_gaussian_attack(2, 1.0, noise_std=10.0, seed=0)
        flat = jnp.zeros((2, 10000))
        comp = jnp.ones(2)
        out = np.asarray(atk.apply(flat, comp, jax.random.PRNGKey(1), 0))
        assert out.std() == pytest.approx(10.0, rel=0.05)


class TestDirectedDeviation:
    def test_lambda_scaling(self):
        atk = make_directed_deviation_attack(3, 0.34, lambda_param=-5.0, seed=0)
        flat = jnp.ones((3, 8))
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        out = np.asarray(atk.apply(flat, comp, None, 0))
        for i in range(3):
            expected = -5.0 if atk.compromised[i] else 1.0
            np.testing.assert_allclose(out[i], expected)


class TestTopologyLiar:
    def test_false_claims_add_coalition(self):
        """Liar's claim = true neighbors ∪ other Byzantine nodes
        (topology_liar.py:78-102)."""
        true_adj = jnp.asarray(np.array([
            [0, 1, 0, 0],
            [1, 0, 1, 0],
            [0, 1, 0, 1],
            [0, 0, 1, 0],
        ], dtype=np.float32))
        comp = jnp.asarray(np.array([1, 0, 0, 1], dtype=np.float32))
        claims = np.asarray(false_claims(true_adj, comp))
        # honest rows unchanged
        np.testing.assert_array_equal(claims[1], [1, 0, 1, 0])
        np.testing.assert_array_equal(claims[2], [0, 1, 0, 1])
        # liar 0 adds fellow-Byzantine 3; liar 3 adds 0
        np.testing.assert_array_equal(claims[0], [0, 1, 0, 1])
        np.testing.assert_array_equal(claims[3], [1, 0, 1, 0])

    def test_pure_liar_no_model_poisoning(self):
        atk = make_topology_liar_attack(4, 0.5, seed=0)
        flat = jnp.ones((4, 8))
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        out = np.asarray(atk.apply(flat, comp, jax.random.PRNGKey(0), 0))
        np.testing.assert_allclose(out, 1.0)

    def test_wrapped_model_attack_shares_compromised_set(self):
        inner = make_gaussian_attack(4, 0.5, noise_std=1.0, seed=99)
        atk = make_topology_liar_attack(4, 0.5, seed=0, model_attack=inner)
        flat = jnp.zeros((4, 8))
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        out = np.asarray(atk.apply(flat, comp, jax.random.PRNGKey(0), 0))
        for i in range(4):
            assert (np.abs(out[i]).max() > 0) == bool(atk.compromised[i])


class TestAttackProtocol:
    def test_is_compromised_and_set(self):
        atk = make_gaussian_attack(10, 0.2, seed=42)
        nodes = atk.get_compromised_nodes()
        assert len(nodes) == 2
        for i in range(10):
            assert atk.is_compromised(i) == (i in nodes)

"""Param-axis sharding (ISSUE 15): big per-node models on a
("seed", "nodes", "param") mesh with ZeRO-style sharded aggregation.

The contracts under test (docs/PERFORMANCE.md "Param-axis sharding"):

- the padded flatteners are exact (zero pad, stripped on unravel) and
  degenerate to the unpadded pair at shards=1;
- ``make_param_mesh`` honors the request, falls back by largest dividing
  factor, and refuses unfactorable layouts loudly;
- a param-sharded round program matches the single-device program to
  float-reassociation tolerance, while shards=1 is BIT-identical
  (MUR1302);
- every [N, P] carried-state tensor (stale cache, pipeline buffers, EF
  residual) adopts the padded width and lands column-sharded on the mesh;
- the int8 codec's block must divide the shard-local width (config-time
  refusal), topk/dmtt/gang/population compositions are refused;
- ``_p_chunk_len`` budgets shard-locally and never hands a chunk loop to
  a program the scaled budget can hold (chunked loops degrade to column
  gathers under GSPMD);
- the pallas entry points refuse a sharded node axis, run shard-local
  grids over a sharded param axis (parity-tested in interpret mode), and
  fall back to lax otherwise;
- a sharded run killed at a snapshot boundary resumes byte-identical,
  and a snapshot written at one shard count refuses to restore into
  another;
- the MUR1300-1303 representative cells are clean.

tests/conftest.py forces an 8-virtual-device CPU platform, so the
(1, 2, 4) check mesh is always available here.
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from murmura_tpu.config import Config
from murmura_tpu.ops.flatten import (
    make_flatteners,
    make_sharded_flatteners,
    padded_dim,
)
from murmura_tpu.parallel.mesh import (
    active_param_shards,
    make_param_mesh,
    mesh_node_axis,
    mesh_param_shards,
    param_axis_scope,
    plan_param_layout,
    shard_step,
)
from murmura_tpu.utils.factories import (
    ConfigError,
    build_network_from_config,
)


def _raw(**over):
    r = {
        "experiment": {"name": "param-shard-test", "seed": 7, "rounds": 4},
        "topology": {"type": "ring", "num_nodes": 8},
        "aggregation": {"algorithm": "balance", "params": {}},
        "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.05},
        "data": {"adapter": "synthetic",
                 "params": {"num_samples": 40, "input_shape": [6],
                            "num_classes": 3}},
        "model": {"factory": "mlp",
                  "params": {"input_dim": 6, "hidden_dims": [8],
                             "num_classes": 3}},
        "backend": "tpu",
        "tpu": {"param_shards": 4, "param_dtype": "float32"},
    }
    for k, v in over.items():
        if isinstance(v, dict) and isinstance(r.get(k), dict):
            r[k] = {**r[k], **v}
        else:
            r[k] = v
    return r


def _cfg(**over):
    return Config.model_validate(_raw(**over))


def _tiny_program(rule="krum", param_shards=1, **kw):
    from jax.flatten_util import ravel_pytree

    from murmura_tpu.aggregation import build_aggregator
    from murmura_tpu.analysis.ir import AGG_CASES
    from murmura_tpu.core.rounds import build_round_program
    from murmura_tpu.data.base import FederatedArrays
    from murmura_tpu.models import make_mlp

    n, s = 8, 16
    rng = np.random.default_rng(0)
    data = FederatedArrays(
        x=rng.normal(size=(n, s, 6)).astype(np.float32),
        y=rng.integers(0, 3, size=(n, s)).astype(np.int32),
        mask=np.ones((n, s), np.float32),
        num_samples=np.full((n,), s),
        num_classes=3,
    )
    model = make_mlp(input_dim=6, hidden_dims=(9,), num_classes=3)
    dim = int(ravel_pytree(model.init(jax.random.PRNGKey(0)))[0].size)
    agg = build_aggregator(
        rule, dict(AGG_CASES.get(rule, {})),
        model_dim=padded_dim(dim, param_shards), total_rounds=4,
    )
    return build_round_program(
        model, agg, data, local_epochs=1, batch_size=8, lr=0.05,
        total_rounds=4, seed=7, param_shards=param_shards, **kw,
    )


def _step_args(prog, adj=None):
    n = prog.num_nodes
    if adj is None:
        adj = np.ones((n, n), np.float32) - np.eye(n, dtype=np.float32)
    return (
        prog.init_params,
        {k: jnp.asarray(v) for k, v in prog.init_agg_state.items()},
        jax.random.PRNGKey(0),
        jnp.asarray(adj),
        jnp.zeros((n,), jnp.float32),
        jnp.asarray(0.0, jnp.float32),
        {k: jnp.asarray(v) for k, v in prog.data_arrays.items()},
    )


# ---------------------------------------------------------------------------
# Flatteners and mesh layout
# ---------------------------------------------------------------------------


class TestFlatteners:
    def test_padded_roundtrip_and_zero_pad(self):
        tmpl = {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
                "b": np.ones(5, np.float32)}
        ravel, unravel, dim, flat = make_sharded_flatteners(tmpl, 4)
        assert (dim, flat) == (11, 12)
        v = np.asarray(ravel(tmpl))
        assert v.shape == (12,) and v[11] == 0.0
        back = unravel(jnp.asarray(v))
        np.testing.assert_array_equal(np.asarray(back["w"]), tmpl["w"])
        np.testing.assert_array_equal(np.asarray(back["b"]), tmpl["b"])

    def test_shards1_degenerates_to_unpadded(self):
        tmpl = {"b": np.ones(5, np.float32)}
        r0, u0, d0 = make_flatteners(tmpl)
        r1, u1, d1, f1 = make_sharded_flatteners(tmpl, 1)
        assert d1 == f1 == d0 == 5
        np.testing.assert_array_equal(
            np.asarray(r1(tmpl)), np.asarray(r0(tmpl))
        )

    def test_padded_dim_validates(self):
        assert padded_dim(11, 4) == 12
        assert padded_dim(12, 4) == 12
        with pytest.raises(ValueError):
            padded_dim(3, 0)


class TestParamMesh:
    def test_primary_layout(self):
        seed, nodes, param = plan_param_layout(8, 4, 8)
        assert (seed, nodes, param) == (1, 2, 4)
        mesh = make_param_mesh(8, 4)
        assert mesh.axis_names == ("seed", "nodes", "param")
        assert mesh_param_shards(mesh) == 4
        assert mesh_node_axis(mesh) == 2

    def test_largest_dividing_factor_fallback(self):
        # 6 devices cannot give 4 param shards (4 does not divide 6):
        # fall back to the largest divisor of the request that fits.
        assert plan_param_layout(6, 4, 6) == (1, 3, 2)
        # shards=1 degrades to the plain node layout.
        assert plan_param_layout(8, 1, 8) == (1, 8, 1)

    def test_unfactorable_raises(self):
        with pytest.raises(ValueError, match="cannot lay"):
            plan_param_layout(3, 5, 7)

    def test_mesh_validates_program_shards(self):
        prog = _tiny_program(param_shards=1)
        mesh = make_param_mesh(prog.num_nodes, 4)
        with pytest.raises(ValueError, match="param_shards"):
            shard_step(prog.train_step, prog, mesh, donate=False)


# ---------------------------------------------------------------------------
# Program parity and state sharding
# ---------------------------------------------------------------------------


class TestShardedProgram:
    def test_shards1_bit_parity(self):
        # MUR1302's subject, gated per tier-1 run for one rule.
        from murmura_tpu.analysis.sharded import bit_parity_findings

        assert bit_parity_findings("krum") == []

    def test_sharded_round_matches_single_device(self):
        ref = _tiny_program(param_shards=1)
        p_ref, _, _ = jax.jit(ref.train_step)(*_step_args(ref))
        prog = _tiny_program(param_shards=4)
        mesh = make_param_mesh(prog.num_nodes, 4)
        step = shard_step(prog.train_step, prog, mesh, donate=False)
        p_sh, _, m_sh = step(*_step_args(prog))
        for a, b in zip(
            jax.tree_util.tree_leaves(p_ref),
            jax.tree_util.tree_leaves(p_sh),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=5e-5, atol=5e-6
            )

    def test_carried_state_adopts_padded_width_and_shards(self):
        from murmura_tpu.core.stale import CACHE_KEY, StalenessSpec
        from murmura_tpu.faults.schedule import FaultSpec
        from murmura_tpu.ops.compress import (
            RESIDUAL_KEY,
            CompressionSpec,
        )

        n = 8
        base = np.ones((n, n), np.float32) - np.eye(n, dtype=np.float32)
        prog = _tiny_program(
            rule="fedavg", param_shards=4,
            faults=FaultSpec(nan_quarantine=True),
            staleness=StalenessSpec(max_staleness=2, base_mask=base),
            compression=CompressionSpec(
                algorithm="int8", block=1, error_feedback=True
            ),
        )
        assert prog.flat_dim % 4 == 0 and prog.flat_dim >= prog.model_dim
        for key in (CACHE_KEY, RESIDUAL_KEY):
            assert prog.init_agg_state[key].shape == (n, prog.flat_dim)
        mesh = make_param_mesh(n, 4)
        step = shard_step(prog.train_step, prog, mesh, donate=False)
        args = list(_step_args(prog))
        args.insert(5, jnp.ones((n,), jnp.float32))  # alive mask
        _, agg_state, _ = step(*args)
        for key in (CACHE_KEY, RESIDUAL_KEY):
            spec = agg_state[key].sharding.spec
            assert "param" in str(spec), (key, spec)

    def test_fused_dispatch_matches_per_round(self):
        # The fused lax.scan path (shard_multi_round) rides the same
        # param-aware spec builder as the per-round step; round keys are
        # fold_in(base, r) on both, so histories must be byte-equal.
        per_round = build_network_from_config(_cfg())
        h1 = per_round.train(rounds=2)
        fused = build_network_from_config(_cfg())
        h2 = fused.train(rounds=2, rounds_per_dispatch=2)
        assert h1["mean_accuracy"] == h2["mean_accuracy"]
        assert h1["mean_loss"] == h2["mean_loss"]

    def test_pipeline_buffer_adopts_padded_width(self):
        from murmura_tpu.core.pipeline import BCAST_KEY, OWN_KEY

        prog = _tiny_program(rule="fedavg", param_shards=4, pipeline=True)
        n = prog.num_nodes
        assert prog.init_agg_state[OWN_KEY].shape == (n, prog.flat_dim)
        assert prog.init_agg_state[BCAST_KEY].shape == (n, prog.flat_dim)


# ---------------------------------------------------------------------------
# Mode rejections (config-time, loud)
# ---------------------------------------------------------------------------


class TestRejections:
    def test_int8_block_straddle_rejected_at_build(self):
        from murmura_tpu.ops.compress import CompressionSpec

        # flat pad of the tiny MLP at 4 shards is 4-aligned; a block of
        # 96 cannot divide the shard-local width.
        with pytest.raises(ValueError, match="shard-local"):
            _tiny_program(
                rule="fedavg", param_shards=4,
                compression=CompressionSpec(algorithm="int8", block=96),
            )

    def test_int8_block_straddle_rejected_by_factories(self):
        with pytest.raises(ConfigError, match="shard-local"):
            build_network_from_config(_cfg(
                compression={"algorithm": "int8", "block": 96},
            ))

    def test_topk_rejected(self):
        with pytest.raises(Exception, match="topk"):
            _cfg(compression={"algorithm": "topk"})

    def test_backend_simulation_rejected(self):
        with pytest.raises(Exception, match="backend"):
            _cfg(backend="simulation")

    def test_sweep_composes(self):
        # LIFTED (ISSUE 16): sharding x sweep is a declared-compatible
        # pair — the schema accepts the combination (the gang mesh grew
        # a "param" role; murmura_tpu/levers.py manifests).
        cfg = _cfg(sweep={"num_seeds": 2})
        assert cfg.sweep is not None
        assert cfg.tpu.param_shards == 4

    def test_gang_seeds_path_lifts_to_param_mesh(self):
        # The CLI `run --seeds N` path (sweep=None, explicit seed list):
        # the gang now lays a ("seed", "nodes", "param") mesh instead of
        # refusing, and trains with finite per-member metrics.
        from murmura_tpu.utils.factories import build_gang_from_config

        gang = build_gang_from_config(_cfg(), seeds=[7, 8])
        assert gang.mesh is not None
        assert gang.mesh.axis_names == ("seed", "nodes", "param")
        assert dict(gang.mesh.shape)["param"] > 1
        gang.train(rounds=2)
        for h in gang.histories:
            assert np.isfinite(np.asarray(h["mean_loss"])).all()

    def test_population_rejected(self):
        with pytest.raises(Exception, match="population"):
            _cfg(
                topology={"type": "ring", "num_nodes": 8},
                population={"enabled": True, "virtual_size": 64},
            )


# ---------------------------------------------------------------------------
# Shard-local chunk budgeting and the pallas guard
# ---------------------------------------------------------------------------


class TestChunkBudget:
    def test_scope_scales_budget_and_avoids_chunking(self):
        from murmura_tpu.aggregation.base import (
            _CIRCULANT_CHUNK_BYTES,
            _p_chunk_len,
        )

        n = 1024
        cap = _CIRCULANT_CHUNK_BYTES // (n * 4)
        p = 4 * cap  # needs chunking unsharded, fits when 4-way sharded
        assert _p_chunk_len(n, p, 4) == cap
        mesh = make_param_mesh(8, 4)
        with param_axis_scope(mesh, p):
            assert active_param_shards(p) == 4
            assert _p_chunk_len(n, p, 4) == p  # unchunked: budget x4
            # Width the shard count does not divide: unsharded accounting.
            assert active_param_shards(p + 1) == 1
        assert active_param_shards(p) == 1  # scope closed

    def test_still_chunked_case_aligns_to_shard_widths(self):
        from murmura_tpu.aggregation.base import (
            _CIRCULANT_CHUNK_BYTES,
            _p_chunk_len,
        )

        n = 1024
        cap = _CIRCULANT_CHUNK_BYTES // (n * 4)
        p = 16 * cap  # too large even for the 4-way-scaled budget
        mesh = make_param_mesh(8, 4)
        with param_axis_scope(mesh, p):
            chunk = _p_chunk_len(n, p, 4)
            assert chunk < p and chunk % (p // 4) == 0


class TestPallasGuard:
    def _operands(self, p=256):
        rng = np.random.default_rng(0)
        own = jnp.asarray(rng.normal(size=(8, p)).astype(np.float32))
        bcast = jnp.asarray(rng.normal(size=(8, p)).astype(np.float32))
        return own, bcast

    def test_sharded_nodes_refused(self):
        from murmura_tpu.ops import pallas_agg

        own, bcast = self._operands()
        mesh = make_param_mesh(8, 1)  # (1, 8, 1): node axis sharded
        assert mesh_node_axis(mesh) > 1
        with param_axis_scope(mesh, 256):
            assert pallas_agg.circulant_sq_distances(
                own, bcast, (1, 2)
            ) is None
            assert pallas_agg.pairwise_sq_distances(own, bcast) is None
            assert not pallas_agg.candidate_select_supported(
                own, bcast, (1, 2)
            )

    def test_sharded_param_shard_local_parity(self):
        from murmura_tpu.ops import pallas_agg

        own, bcast = self._operands()
        ref_circ = pallas_agg.circulant_sq_distances(own, bcast, (1, 2))
        ref_pair = pallas_agg.pairwise_sq_distances(own, bcast)
        ref_cand = pallas_agg.fused_candidate_select(
            own, bcast, (1, 2, 3), median=True
        )
        devices = jax.devices()
        from jax.sharding import Mesh

        mesh = Mesh(
            np.array(devices[:4]).reshape(1, 1, 4),
            ("seed", "nodes", "param"),
        )
        with param_axis_scope(mesh, 256):
            circ = pallas_agg.circulant_sq_distances(own, bcast, (1, 2))
            pair = pallas_agg.pairwise_sq_distances(own, bcast)
            cand = pallas_agg.fused_candidate_select(
                own, bcast, (1, 2, 3), median=True
            )
        np.testing.assert_allclose(
            np.asarray(circ), np.asarray(ref_circ), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_allclose(
            np.asarray(pair), np.asarray(ref_pair), rtol=1e-5, atol=1e-5
        )
        np.testing.assert_array_equal(
            np.asarray(cand), np.asarray(ref_cand)
        )

    def test_indivisible_width_falls_back_to_lax(self):
        from murmura_tpu.ops import pallas_agg
        from jax.sharding import Mesh

        own, bcast = self._operands(p=255)  # 4 does not divide 255
        mesh = Mesh(
            np.array(jax.devices()[:4]).reshape(1, 1, 4),
            ("seed", "nodes", "param"),
        )
        with param_axis_scope(mesh, 255):
            assert pallas_agg.circulant_sq_distances(
                own, bcast, (1, 2)
            ) is None
            assert not pallas_agg.candidate_select_supported(
                own, bcast, (1, 2)
            )


# ---------------------------------------------------------------------------
# Durability: sharded SIGKILL-resume and the shard-count refusal
# ---------------------------------------------------------------------------


class TestShardedDurability:
    def test_sigkill_at_snapshot_boundary_resumes_byte_identical(
        self, tmp_path
    ):
        from tests.test_durability import _assert_same_run

        full = build_network_from_config(_cfg())
        full.train(rounds=2)
        full.save_checkpoint(str(tmp_path / "snap"))
        full.train(rounds=2)

        resumed = build_network_from_config(_cfg())
        assert resumed.restore_checkpoint(str(tmp_path / "snap")) == 2
        resumed.train(rounds=2)
        _assert_same_run(full, resumed, "sharded@r2")

    def test_restore_refuses_shard_count_mismatch(self, tmp_path):
        writer = build_network_from_config(_cfg())  # param_shards=4
        writer.train(rounds=1)
        writer.save_checkpoint(str(tmp_path / "snap4"))
        reader = build_network_from_config(
            _cfg(tpu={"param_shards": 2, "param_dtype": "float32"})
        )
        with pytest.raises(ValueError, match="param_shards"):
            reader.restore_checkpoint(str(tmp_path / "snap4"))

    def test_unsharded_refuses_sharded_snapshot(self, tmp_path):
        writer = build_network_from_config(_cfg())
        writer.train(rounds=1)
        writer.save_checkpoint(str(tmp_path / "snap4"))
        reader = build_network_from_config(
            _cfg(tpu={"param_shards": 1, "param_dtype": "float32"})
        )
        with pytest.raises(ValueError, match="param_shards"):
            reader.restore_checkpoint(str(tmp_path / "snap4"))


# ---------------------------------------------------------------------------
# MUR1300-1303 gates
# ---------------------------------------------------------------------------


class TestShardedChecks:
    def test_mur1300_1303_representative_cell(self):
        from murmura_tpu.analysis.sharded import inventory_cell_findings

        assert inventory_cell_findings("krum", "circulant") == []

    def test_mur1301_representative_cell(self):
        from murmura_tpu.analysis.sharded import recompile_cell_findings

        assert recompile_cell_findings("fedavg", "dense") == []

    def test_oversized_all_reduce_parser_fires(self):
        from murmura_tpu.analysis.sharded import oversized_all_reduces

        hlo = (
            "%ar = f32[8,2048]{1,0} all-reduce(f32[8,2048]{1,0} %x)\n"
            "%ok = f32[8,8]{1,0} all-reduce(f32[8,8]{1,0} %y)\n"
        )
        assert oversized_all_reduces(hlo, 1024) == [8 * 2048]

    @pytest.mark.slow
    def test_full_sharded_check_clean(self):
        from murmura_tpu.analysis.sharded import check_sharded

        assert check_sharded() == []

"""Multi-process exercises (VERDICT r1 missing #4).

(a) A real 2-process ``jax.distributed`` run on CPU: init_multihost via the
    factory path, an 8-device global mesh spanning both processes, one
    sharded round executed SPMD.  This is the virtual stand-in for the
    multi-host DCN scale-out path (parallel/mesh.py docstring).
(b) An end-to-end 2-node TCP ZMQ run driven through the ``run-node`` CLI the
    way a multi-machine operator would (reference: murmura/cli.py:143-208),
    with the Monitor collecting history over TCP.

Both are wall-clock heavy (subprocess jax imports + compiles on a shared
core) and marked slow.
"""

import json
import socket
import subprocess
import sys
import time
from pathlib import Path

import pytest
import yaml

REPO = Path(__file__).parent.parent


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_jax_distributed_cpu(tmp_path):
    coordinator = f"127.0.0.1:{_free_port()}"
    outs = [tmp_path / f"proc{i}.json" for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, str(REPO / "tests" / "multihost_worker.py"),
             coordinator, "2", str(i), str(outs[i])],
            cwd=REPO,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    logs = []
    try:
        for p in procs:
            stdout, _ = p.communicate(timeout=600)
            logs.append(stdout)
    except subprocess.TimeoutExpired:
        for p in procs:
            p.kill()
        pytest.fail("multihost workers timed out")

    if any(p.returncode != 0 for p in procs):
        combined = "\n".join(logs)
        if "distributed" in combined and (
            "not supported" in combined or "Unimplemented" in combined
        ):
            pytest.skip(f"jax.distributed unsupported here: {combined[-400:]}")
        pytest.fail(f"worker failed:\n{combined[-2000:]}")

    rows = [json.loads(o.read_text()) for o in outs]
    for r in rows:
        assert r["process_count"] == 2
        assert r["global_devices"] == 8
    # Metrics outputs are replicated: both processes must record the same row.
    assert rows[0]["mean_accuracy"] == pytest.approx(rows[1]["mean_accuracy"])
    assert rows[0]["mean_loss"] == pytest.approx(rows[1]["mean_loss"])


@pytest.mark.slow
def test_two_node_tcp_run_node_cli(tmp_path):
    """Drive two `murmura_tpu run-node` workers over TCP + a Monitor, i.e.
    the multi-machine operator flow on localhost."""
    import multiprocessing as mp

    from murmura_tpu.config import Config
    from murmura_tpu.distributed.runner import _monitor_main

    base_port = _free_port()
    coordinator_pull_port = _free_port()
    cfg_dict = {
        "experiment": {"name": "tcp-e2e", "seed": 5, "rounds": 2},
        "topology": {"type": "ring", "num_nodes": 2},
        "aggregation": {"algorithm": "fedavg"},
        "training": {"local_epochs": 1, "batch_size": 16, "lr": 0.05},
        "data": {
            "adapter": "synthetic",
            "params": {"num_samples": 160, "input_dim": 10, "num_classes": 3},
        },
        "model": {
            "factory": "mlp",
            "params": {"input_dim": 10, "hidden_dims": [16], "num_classes": 3},
        },
        "backend": "distributed",
        "distributed": {
            "transport": "tcp",
            "host": "127.0.0.1",
            "base_port": base_port,
            "coordinator_pull_port": coordinator_pull_port,
            "round_duration_s": 45.0,
            "startup_grace_s": 75.0,
        },
    }
    cfg_path = tmp_path / "tcp.yaml"
    cfg_path.write_text(yaml.safe_dump(cfg_dict))
    cfg = Config.model_validate(cfg_dict)

    run_id = "tcptest"
    t_start = time.monotonic() + cfg.distributed.startup_grace_s
    queue = mp.get_context("spawn").Queue()
    monitor = mp.get_context("spawn").Process(
        target=_monitor_main, args=(cfg, run_id, t_start, [], queue)
    )
    monitor.start()

    workers = [
        subprocess.Popen(
            [sys.executable, "-m", "murmura_tpu", "run-node", str(cfg_path),
             "--node-id", str(i), "--t-start", str(t_start),
             "--run-id", run_id],
            cwd=REPO,
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    try:
        for w in workers:
            out, _ = w.communicate(timeout=400)
            assert w.returncode == 0, out[-2000:]
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()

    monitor.join(timeout=200)
    assert not monitor.is_alive()
    history = queue.get(timeout=10)
    assert history["round"], history
    assert history["mean_accuracy"][-1] > 0.3

"""DMTT trust-protocol tests (reference semantics: murmura/dmtt/).

Closed-form checks of the trust math (state.py:53-142) plus end-to-end
liar-exclusion: topology liars' falsified claims must drive their Beta trust
down until TopB stops selecting them (node_process.py:150-250).
"""

import jax.numpy as jnp
import numpy as np
import pytest

from murmura_tpu.attacks.topology_liar import false_claims
from murmura_tpu.dmtt.protocol import (
    DMTTParams,
    collab_score,
    dmtt_round_update,
    init_dmtt_state,
    model_score,
    topo_trust,
)

P = DMTTParams()


class TestTrustMath:
    def test_topo_trust_prior(self):
        """Beta(1,1) prior: R=0.5, U=sqrt(1/12)≈0.2887 < tau_U=0.3 — no
        penalty (state.py:82-94)."""
        t = float(topo_trust(jnp.ones(()), jnp.ones(()), P))
        assert t == pytest.approx(0.5, abs=1e-6)

    def test_topo_trust_monotone_in_evidence(self):
        """Positive evidence raises trust; negative evidence lowers it."""
        t_good = float(topo_trust(jnp.asarray(10.0), jnp.asarray(1.0), P))
        t_bad = float(topo_trust(jnp.asarray(1.0), jnp.asarray(10.0), P))
        t_prior = float(topo_trust(jnp.asarray(1.0), jnp.asarray(1.0), P))
        assert t_good > t_prior > t_bad

    def test_topo_trust_uncertainty_penalty(self):
        """Same mean, higher posterior std above tau_U gets the exp penalty."""
        # Beta(0.5, 0.5): R=0.5, U=sqrt(0.25/2)=0.3536 > 0.3
        t = float(topo_trust(jnp.asarray(0.5), jnp.asarray(0.5), P))
        u = np.sqrt(0.25 / (1.0 * 2.0))
        expected = 0.5 * np.exp(-P.eta * (u - P.tau_U))
        assert t == pytest.approx(expected, rel=1e-5)

    def test_model_score_formula(self):
        """s = (1-u)(w_a*a + 1-w_a), exp penalty above tau_u, floored at 0
        (state.py:100-110)."""
        s = float(model_score(jnp.asarray(0.9), jnp.asarray(0.0), P))
        assert s == pytest.approx(0.7 * 0.9 + 0.3, rel=1e-6)
        # above threshold: * exp(-(u - tau_u))
        s_pen = float(model_score(jnp.asarray(0.9), jnp.asarray(0.8), P))
        expected = (1 - 0.8) * (0.7 * 0.9 + 0.3) * np.exp(-(0.8 - 0.5))
        assert s_pen == pytest.approx(expected, rel=1e-5)

    def test_collab_score_weights(self):
        q = float(
            collab_score(jnp.asarray(1.0), jnp.asarray(1.0), jnp.asarray(1.0), P)
        )
        assert q == pytest.approx(P.lambda1 + P.lambda2 + P.lambda3, rel=1e-6)


class TestRoundUpdate:
    def _ring_adj(self, n):
        adj = np.zeros((n, n), np.float32)
        for i in range(n):
            adj[i, (i + 1) % n] = adj[i, (i - 1) % n] = 1.0
        return jnp.asarray(adj)

    def test_honest_claims_raise_alpha(self):
        """All-honest ring: every received claim fully matches G^t, so alpha
        grows by w_d * degree and beta only decays (state.py:63-76)."""
        n = 6
        adj = self._ring_adj(n)
        state = init_dmtt_state(n)
        acc = jnp.full((n, n), 0.9)
        vac = jnp.zeros((n, n))
        ack, new_state, stats = dmtt_round_update(state, adj, adj, acc, vac, P)
        alpha = np.asarray(new_state["dmtt_alpha"])
        beta = np.asarray(new_state["dmtt_beta"])
        exchanged = np.asarray(ack) > 0
        # d_j = 2 for every ring node; alpha = 0.9*1 + 1.0*2 = 2.9 on edges
        np.testing.assert_allclose(alpha[exchanged], 2.9, rtol=1e-6)
        np.testing.assert_allclose(beta[exchanged], 0.9, rtol=1e-6)
        # non-exchanged edges untouched
        np.testing.assert_allclose(alpha[~exchanged], 1.0)

    def test_round0_uses_adjacency(self):
        """Round 0 has no TopB selection yet — exchange = G^0 (symmetric ring)
        (node_process.py:111-118)."""
        n = 5
        adj = self._ring_adj(n)
        ack, _, _ = dmtt_round_update(
            init_dmtt_state(n),
            adj,
            adj,
            jnp.full((n, n), 0.5),
            jnp.zeros((n, n)),
            P,
        )
        np.testing.assert_array_equal(np.asarray(ack), np.asarray(adj))

    def test_later_rounds_use_collab_intersection(self):
        """After round 0 the exchange is C ∧ Cᵀ, not G^t."""
        n = 4
        adj = jnp.ones((n, n)) - jnp.eye(n)
        state = init_dmtt_state(n)
        # node 0 collaborates only with 1; others with everyone
        collab = np.ones((n, n), np.float32) - np.eye(n, dtype=np.float32)
        collab[0] = 0.0
        collab[0, 1] = 1.0
        state = {
            **state,
            "dmtt_collab": jnp.asarray(collab),
            "dmtt_selected": jnp.ones((), jnp.float32),
        }
        ack, _, _ = dmtt_round_update(
            state,
            adj,
            adj,
            jnp.full((n, n), 0.5),
            jnp.zeros((n, n)),
            P,
        )
        ack = np.asarray(ack)
        assert ack[0, 1] == 1.0 and ack[1, 0] == 1.0
        assert ack[0, 2] == 0.0 and ack[2, 0] == 0.0  # 2 sent, 0 didn't expect

    def test_empty_selection_not_confused_with_no_selection(self):
        """A legitimately empty TopB result (isolated node under mobility)
        must NOT fall back to the raw adjacency the following round — only
        the never-selected state does (dmtt_selected flag)."""
        n = 4
        state = init_dmtt_state(n)
        state = {
            **state,
            "dmtt_collab": jnp.zeros((n, n), jnp.float32),  # selected nothing
            "dmtt_selected": jnp.ones((), jnp.float32),
        }
        adj = jnp.ones((n, n), jnp.float32) - jnp.eye(n, dtype=jnp.float32)
        ack, _, _ = dmtt_round_update(
            state, adj, adj, jnp.full((n, n), 0.5), jnp.zeros((n, n)), P
        )
        np.testing.assert_array_equal(np.asarray(ack), 0.0)

    def test_liar_loses_trust_and_collaborators(self):
        """Falsified claims (true ∪ coalition, topology_liar.py:78-102) add
        contradictions every round.  On a ring each liar's claim is 2 true
        edges + false coalition edges, so Beta trust converges to
        d/(d+x) ≈ 2/3 under forgetting (state.py:63-94) — clearly below the
        honest steady state ≈ 1.0 — and TopB with budget 1 then prefers the
        honest neighbor over the liar (state.py:128-142)."""
        n = 8
        adj = self._ring_adj(n)
        compromised = np.zeros(n, np.float32)
        compromised[2] = compromised[5] = 1.0
        comp = jnp.asarray(compromised)
        claims = false_claims(adj, comp)
        # equal probe accuracy everywhere: trust, not accuracy, must drive
        # the exclusion
        acc = jnp.full((n, n), 0.9)
        vac = jnp.zeros((n, n))
        p = DMTTParams(budget_B=1)

        state = init_dmtt_state(n)
        for r in range(6):
            _, state, stats = dmtt_round_update(state, adj, claims, acc, vac, p)
        t = np.asarray(topo_trust(state["dmtt_alpha"], state["dmtt_beta"], p))
        honest = compromised == 0
        byz = compromised == 1
        # only adjacent pairs ever exchange claims (non-edges keep the prior)
        adj_np = np.asarray(adj) > 0
        h_b = adj_np & honest[:, None] & byz[None, :]
        h_h = adj_np & honest[:, None] & honest[None, :]
        t_in_byz = t[h_b].mean()
        t_in_honest = t[h_h].mean()
        assert t_in_byz < t_in_honest - 0.1, (t_in_byz, t_in_honest)
        # with B=1, every honest node adjacent to one liar and one honest
        # neighbor must pick the honest one
        collab = np.asarray(state["dmtt_collab"])
        for i, h in ((1, 0), (3, 4), (4, 3), (6, 7)):
            assert collab[i, h] == 1.0, f"node {i} did not pick honest {h}"
            liar = 2 if i in (1, 3) else 5
            assert collab[i, liar] == 0.0, f"node {i} still picks liar {liar}"
        assert stats["dmtt_collab_count"].shape == (n,)

    def test_topb_budget_respected(self):
        n = 6
        adj = jnp.ones((n, n), jnp.float32) - jnp.eye(n, dtype=jnp.float32)
        p = DMTTParams(budget_B=2)
        _, state, stats = dmtt_round_update(
            init_dmtt_state(n),
            adj,
            adj,
            jnp.full((n, n), 0.5),
            jnp.zeros((n, n)),
            p,
        )
        counts = np.asarray(stats["dmtt_collab_count"])
        assert (counts <= 2).all() and (counts >= 1).all()

    def test_topb_prefers_higher_model_score(self):
        """With equal trust, the candidate with better probe accuracy wins
        the budget slot (state.py:128-142)."""
        n = 4
        adj = jnp.ones((n, n), jnp.float32) - jnp.eye(n, dtype=jnp.float32)
        acc = jnp.asarray(
            np.stack([np.linspace(0.1, 0.9, n)] * n).astype(np.float32)
        )  # every observer sees subject j's accuracy grow with j
        p = DMTTParams(budget_B=1)
        _, state, _ = dmtt_round_update(
            init_dmtt_state(n),
            adj,
            adj,
            acc,
            jnp.zeros((n, n)),
            p,
        )
        collab = np.asarray(state["dmtt_collab"])
        # everyone (except node 3 itself) picks node 3, the highest-accuracy
        for i in range(3):
            assert collab[i, 3] == 1.0


class TestEndToEnd:
    def test_dmtt_simulation_distrust_of_liars(self):
        """Full config-driven run: mobility + topology_liar + DMTT.

        Asserts on the protocol's accumulated trust state rather than one
        round's TopB bitmask: the mobility graph must be sparse enough that
        liars' coalition claims are falsifiable (comm_range << area), and
        then contradiction evidence (beta) piles up on liar columns and
        their Beta-mean topology trust falls below honest peers'.  The
        per-round TopB selection itself is a binary top-k over graph-gated
        candidates — with toy probe batches it flips on noise draw, which is
        why it is not the assertion here (the exchange-mask gating is
        covered by TestRoundUpdate/TestTopB)."""
        from murmura_tpu.config import Config
        from murmura_tpu.dmtt.protocol import DMTTParams, topo_trust
        from murmura_tpu.utils.factories import build_network_from_config

        n = 8
        cfg = Config.model_validate(
            {
                "experiment": {"name": "dmtt-test", "seed": 3, "rounds": 8},
                "topology": {"type": "fully", "num_nodes": n},
                "aggregation": {"algorithm": "fedavg", "params": {}},
                "attack": {
                    "enabled": True,
                    "type": "topology_liar",
                    "percentage": 0.25,
                    "params": {"model_attack_type": "gaussian", "noise_std": 5.0},
                },
                "training": {"local_epochs": 1, "batch_size": 16, "lr": 0.1},
                "data": {
                    "adapter": "synthetic",
                    "params": {
                        "num_samples": 32 * n,
                        "input_shape": [10],
                        "num_classes": 3,
                    },
                },
                "model": {
                    "factory": "mlp",
                    "params": {
                        "input_dim": 10,
                        "hidden_dims": [16],
                        "num_classes": 3,
                    },
                },
                "mobility": {
                    "area_size": 100.0,
                    "comm_range": 35.0,
                    "max_speed": 8.0,
                    "seed": 11,
                },
                "dmtt": {"budget_B": 3},
            }
        )
        net = build_network_from_config(cfg)
        history = net.train(rounds=8)
        assert len(history["round"]) == 8
        assert np.isfinite(history["mean_accuracy"]).all()

        comp = net.attack.compromised
        honest = ~comp
        alpha = np.asarray(net.agg_state["dmtt_alpha"])
        beta = np.asarray(net.agg_state["dmtt_beta"])
        beta_byz = beta[np.ix_(honest, comp)].mean()
        beta_honest = beta[np.ix_(honest, honest)].mean()
        assert beta_byz > beta_honest + 0.5, (
            f"contradiction evidence did not accumulate on liars: "
            f"byz={beta_byz:.2f} honest={beta_honest:.2f}"
        )

        t = np.asarray(topo_trust(alpha, beta, DMTTParams()))
        t_byz = t[np.ix_(honest, comp)].mean()
        t_honest = t[np.ix_(honest, honest)].mean()
        assert t_byz < t_honest, (
            f"liars keep topology trust: byz={t_byz:.3f} honest={t_honest:.3f}"
        )

        stats = net.get_node_statistics()
        assert "dmtt_collab_count" in stats[0]


class TestProbeCrossReuse:
    """The shared cross-eval handed to probe-based rules via ctx.probe_cross
    must be interchangeable with each rule's standalone recompute."""

    def _ctx(self, evidential, n=4, b=6, dim=5, k=3, seed=0):
        import jax

        from murmura_tpu.aggregation.base import AggContext
        from murmura_tpu.models.registry import build_model
        from murmura_tpu.ops.flatten import make_flatteners

        params = {
            "input_dim": dim,
            "hidden_dims": [8],
            "num_classes": k,
            "evidential": evidential,
        }
        model = build_model("mlp", params)
        rng = np.random.default_rng(seed)
        template = model.init(jax.random.PRNGKey(0))
        ravel, unravel, p_dim = make_flatteners(template)
        flat = jnp.asarray(
            rng.normal(size=(n, p_dim)).astype(np.float32)
        )
        ctx = AggContext(
            apply_fn=model.apply,
            unravel=unravel,
            probe_x=jnp.asarray(rng.normal(size=(n, b, dim)).astype(np.float32)),
            probe_y=jnp.asarray(rng.integers(0, k, size=(n, b)).astype(np.int32)),
            probe_mask=jnp.ones((n, b), jnp.float32),
            evidential=evidential,
            num_classes=k,
            total_rounds=5,
        )
        return flat, ctx

    def test_combined_metric_matches_standalone(self):
        """combined_probe_metric emits the same loss as ce_loss_metric and
        the same accuracy/vacuity as the per-rule metrics, on both model
        families."""
        from murmura_tpu.aggregation.probe import (
            ce_loss_metric,
            combined_probe_metric,
            evidential_trust_metric,
            pairwise_probe_eval,
        )

        for evidential in (False, True):
            flat, ctx = self._ctx(evidential)
            combined = pairwise_probe_eval(
                flat, ctx, combined_probe_metric(evidential)
            )
            loss = pairwise_probe_eval(flat, ctx, ce_loss_metric)["loss"]
            np.testing.assert_allclose(
                np.asarray(combined["loss"]), np.asarray(loss), rtol=1e-6
            )
            if evidential:
                ev = pairwise_probe_eval(flat, ctx, evidential_trust_metric)
                for key in ("accuracy", "vacuity", "entropy", "strength"):
                    np.testing.assert_allclose(
                        np.asarray(combined[key]), np.asarray(ev[key]), rtol=1e-6
                    )

    def test_rules_identical_with_and_without_probe_cross(self):
        """UBAR and evidential_trust produce bit-identical outputs whether
        they recompute the cross-eval or reuse ctx.probe_cross."""
        import dataclasses

        from murmura_tpu.aggregation import build_aggregator
        from murmura_tpu.aggregation.probe import (
            combined_probe_metric,
            pairwise_probe_eval,
        )

        for name, evidential in (("ubar", False), ("evidential_trust", True)):
            flat, ctx = self._ctx(evidential)
            n = flat.shape[0]
            adj = jnp.ones((n, n), jnp.float32) - jnp.eye(n, dtype=jnp.float32)
            agg = build_aggregator(name, {}, model_dim=flat.shape[1], total_rounds=5)
            state = {k: jnp.asarray(v) for k, v in agg.init_state(n).items()}
            cross = pairwise_probe_eval(flat, ctx, combined_probe_metric(evidential))
            ctx_pre = dataclasses.replace(ctx, probe_cross=cross)

            out_a, _, _ = agg.aggregate(flat, flat, adj, jnp.asarray(1.0), state, ctx)
            out_b, _, _ = agg.aggregate(
                flat, flat, adj, jnp.asarray(1.0), state, ctx_pre
            )
            np.testing.assert_array_equal(np.asarray(out_a), np.asarray(out_b))

    def test_dmtt_with_ubar_end_to_end(self):
        """DMTT gating composes with a probe-based rule (shared cross-eval
        path live in the full round step)."""
        from murmura_tpu.config import Config
        from murmura_tpu.utils.factories import build_network_from_config

        n = 6
        cfg = Config.model_validate(
            {
                "experiment": {"name": "dmtt-ubar", "seed": 1, "rounds": 3},
                "topology": {"type": "fully", "num_nodes": n},
                "aggregation": {"algorithm": "ubar", "params": {}},
                "attack": {
                    "enabled": True,
                    "type": "topology_liar",
                    "percentage": 0.2,
                    "params": {"model_attack_type": "gaussian", "noise_std": 5.0},
                },
                "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.1},
                "data": {
                    "adapter": "synthetic",
                    "params": {
                        "num_samples": 12 * n,
                        "input_shape": [8],
                        "num_classes": 3,
                    },
                },
                "model": {
                    "factory": "mlp",
                    "params": {
                        "input_dim": 8,
                        "hidden_dims": [16],
                        "num_classes": 3,
                    },
                },
                "mobility": {"comm_range": 80.0, "seed": 2},
                "dmtt": {"budget_B": 3},
            }
        )
        net = build_network_from_config(cfg)
        history = net.train(rounds=3)
        assert np.isfinite(history["mean_accuracy"]).all()

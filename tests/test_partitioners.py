"""Partitioner tests (reference semantics: murmura/data/partitioners.py)."""

import numpy as np

from murmura_tpu.data import (
    combine_partitions_with_dirichlet,
    dirichlet_partition,
    iid_partition,
    natural_partition,
    stack_partitions,
)


def _labels(n=1000, k=10, seed=0):
    return np.random.default_rng(seed).integers(0, k, size=n)


def test_dirichlet_covers_all_samples_once():
    y = _labels()
    parts = dirichlet_partition(y, 8, alpha=0.5, seed=1)
    all_idx = sorted(i for p in parts for i in p)
    assert all_idx == list(range(1000))


def test_dirichlet_min_samples():
    y = _labels()
    parts = dirichlet_partition(y, 10, alpha=0.05, min_samples_per_client=5, seed=2)
    assert all(len(p) >= 5 for p in parts)


def test_dirichlet_deterministic():
    y = _labels()
    a = dirichlet_partition(y, 5, alpha=0.3, seed=3)
    b = dirichlet_partition(y, 5, alpha=0.3, seed=3)
    assert a == b


def test_dirichlet_heterogeneity_increases_with_small_alpha():
    """Lower alpha -> more skewed label distributions (partitioners.py:22-26)."""
    y = _labels(5000, 10)

    def mean_label_entropy(parts):
        ents = []
        for p in parts:
            counts = np.bincount(y[p], minlength=10) + 1e-9
            probs = counts / counts.sum()
            ents.append(-(probs * np.log(probs)).sum())
        return np.mean(ents)

    skewed = mean_label_entropy(dirichlet_partition(y, 10, alpha=0.05, seed=4))
    uniform = mean_label_entropy(dirichlet_partition(y, 10, alpha=100.0, seed=4))
    assert skewed < uniform


def test_iid_even_split():
    parts = iid_partition(103, 4, seed=0)
    sizes = sorted(len(p) for p in parts)
    assert sizes == [25, 26, 26, 26]
    assert sorted(i for p in parts for i in p) == list(range(103))


def test_natural_partition_groups_by_id():
    ids = np.array([3, 1, 3, 2, 1, 1])
    parts, n = natural_partition(ids)
    assert n == 3
    assert parts[0] == [1, 4, 5]  # id 1
    assert parts[1] == [3]  # id 2
    assert parts[2] == [0, 2]  # id 3


def test_natural_partition_limit():
    ids = np.array([0, 1, 2, 3, 4])
    parts, n = natural_partition(ids, num_clients=3)
    assert n == 3 and len(parts) == 3


def test_combine_partitions_with_dirichlet_preserves_index_pool():
    y = _labels(200, 5)
    nat = [list(range(0, 100)), list(range(100, 200))]
    parts = combine_partitions_with_dirichlet(nat, y, 4, alpha=0.5, seed=5)
    assert sorted(i for p in parts for i in p) == list(range(200))


def test_stack_partitions_padding_and_masks():
    x = np.arange(20, dtype=np.float32).reshape(10, 2)
    y = np.arange(10)
    parts = [[0, 1, 2], [3], [4, 5, 6, 7, 8, 9]]
    fed = stack_partitions(x, y, parts)
    assert fed.x.shape == (3, 6, 2)
    assert fed.num_samples.tolist() == [3, 1, 6]
    assert fed.mask[1].tolist() == [1, 0, 0, 0, 0, 0]
    fx, fy = fed.get_client_data(0)
    assert fy.tolist() == [0, 1, 2]


def test_effective_batch_rule():
    """min(B, max(2, n)) and drop_last semantics (network.py:278-287)."""
    x = np.zeros((30, 2), dtype=np.float32)
    y = np.zeros(30, dtype=np.int64)
    fed = stack_partitions(x, y, [[0], list(range(1, 6)), list(range(6, 30))])
    assert fed.effective_batch(8).tolist() == [2, 5, 8]
    assert fed.steps_per_epoch(8).tolist() == [1, 1, 3]


def test_stack_partitions_max_samples_truncation():
    x = np.zeros((30, 2), dtype=np.float32)
    y = np.zeros(30, dtype=np.int64)
    fed = stack_partitions(x, y, [list(range(30))], max_samples=7)
    assert fed.num_samples.tolist() == [7]

"""The executed paper matrix must satisfy the reference paper's qualitative
robustness orderings (SURVEY.md §6; reference
experiments/paper/RESULTS_SUMMARY.md:7-38).

Runs assert_orderings.py against the committed results.json — regenerate
with experiments/paper/run_comprehensive.py after changing anything that
moves accuracy (difficulty calibration, aggregation rules, holdout).
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

PAPER = Path(__file__).parent.parent / "experiments" / "paper"
RESULTS = PAPER / "results" / "results.json"


@pytest.mark.slow
def test_committed_matrix_satisfies_orderings():
    if not RESULTS.exists():
        pytest.skip("no committed results.json (run run_comprehensive.py)")
    proc = subprocess.run(
        [sys.executable, str(PAPER / "assert_orderings.py"),
         "--results", str(RESULTS)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.slow
def test_committed_matrix_is_complete():
    if not RESULTS.exists():
        pytest.skip("no committed results.json (run run_comprehensive.py)")
    records = json.loads(RESULTS.read_text())
    ok = [r for r in records if r.get("ok")]
    # The generator emits 261 configs (3 datasets x 6 algorithms x
    # (1 + 3 + 6 + 4) + 9 ablation); the committed artifact must cover them.
    assert len(ok) >= 252, f"only {len(ok)} experiments ok"

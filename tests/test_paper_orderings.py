"""The executed paper matrix must satisfy the reference paper's qualitative
robustness orderings (SURVEY.md §6; reference
experiments/paper/RESULTS_SUMMARY.md:7-38).

Runs assert_orderings.py against the committed results.json — regenerate
with experiments/paper/run_comprehensive.py after changing anything that
moves accuracy (difficulty calibration, aggregation rules, holdout).
"""

import json
import subprocess
import sys
from pathlib import Path

import pytest

PAPER = Path(__file__).parent.parent / "experiments" / "paper"
RESULTS = PAPER / "results" / "results.json"


def _completed_records():
    if not RESULTS.exists():
        pytest.skip("no committed results.json (run run_comprehensive.py)")
    records = json.loads(RESULTS.read_text())
    ok = [r for r in records if r.get("ok")]
    # The generator emits 312 configs (3 datasets x 6 algorithms x
    # (1 + 3 + 6 + 4) + 51 reference-grid ablation + 9 attacked ablation);
    # don't judge a matrix mid-generation.
    if len(ok) < 300:
        pytest.skip(f"matrix incomplete ({len(ok)}/312 ok) — still generating")
    return ok


@pytest.mark.slow
def test_committed_matrix_satisfies_orderings():
    _completed_records()
    proc = subprocess.run(
        [sys.executable, str(PAPER / "assert_orderings.py"),
         "--results", str(RESULTS)],
        capture_output=True, text=True, timeout=60,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    # The script's machine-readable tail: the matrix must exercise the
    # full breadth of orderings (round-3 verdict: >= 20 distinct), with
    # nothing silently skipped on a complete matrix.
    tail = json.loads(proc.stdout.strip().splitlines()[-1])
    assert tail["failures"] == 0
    assert tail["checks"] >= 150, tail
    assert tail["families"] >= 15, tail
    assert tail["skipped"] == 0, proc.stdout


@pytest.mark.slow
def test_committed_dmtt_ordering():
    """The committed 3-condition DMTT run must show full DMTT beating the
    unprotected dynamic condition on honest accuracy (the headline claim
    the reference leaves as a placeholder — paper.tex:712)."""
    path = PAPER / "dmtt" / "results_dmtt.json"
    if not path.exists():
        pytest.skip("no committed results_dmtt.json (run run_dmtt.py)")
    blob = json.loads(path.read_text())
    assert blob["ordering_failures"] == []
    by = {r["condition"]: r for r in blob["records"]}
    assert all(r.get("ok") for r in blob["records"])
    assert (
        by["03_dmtt"]["final_honest_accuracy"]
        >= by["02_dynamic_no_trust"]["final_honest_accuracy"] + 0.1
    )


@pytest.mark.slow
def test_committed_matrix_is_complete():
    ok = _completed_records()
    assert len(ok) >= 300, f"only {len(ok)} experiments ok"


def test_extras_robust_stats_orderings():
    """The committed beyond-parity evidence run (median/trimmed_mean vs
    fedavg, experiments/extras/) must satisfy its own checks — regenerate
    with run_robust_stats.py after changing anything that moves accuracy."""
    extras = (
        Path(__file__).parent.parent / "experiments" / "extras" / "results.json"
    )
    if not extras.exists():
        pytest.skip("no committed extras results.json")
    blob = json.loads(extras.read_text())
    failing = [k for k, v in blob["checks"].items() if not v]
    assert blob["all_pass"], f"failing checks: {failing}"

"""Worker script for the 2-process jax.distributed test.

Launched as a subprocess (one per process id) by
tests/test_multiprocess.py.  Joins a multi-process CPU run via
``tpu.multihost`` config (exercising parallel.mesh.init_multihost through
the factory path), trains one round of the sharded round step with the
global mesh spanning both processes, and writes its replicated history row
to a JSON file the test compares across processes.

Usage: python multihost_worker.py <coordinator> <num_procs> <proc_id> <out>
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    coordinator, num_procs, proc_id, out_path = sys.argv[1:5]

    # 4 virtual CPU devices per process -> 8-device global mesh.
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    ).strip()
    import jax

    jax.config.update("jax_platforms", "cpu")

    from murmura_tpu.config import Config
    from murmura_tpu.utils.factories import build_network_from_config

    cfg = Config.model_validate(
        {
            "experiment": {"name": "multihost-test", "seed": 3, "rounds": 1},
            "topology": {"type": "ring", "num_nodes": 8},
            "aggregation": {"algorithm": "fedavg"},
            "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.05},
            "data": {
                "adapter": "synthetic",
                "params": {"num_samples": 320, "input_dim": 12,
                           "num_classes": 3},
            },
            "model": {
                "factory": "mlp",
                "params": {"input_dim": 12, "hidden_dims": [16],
                           "num_classes": 3},
            },
            "backend": "tpu",
            "tpu": {
                "multihost": True,
                "coordinator_address": coordinator,
                "num_processes": int(num_procs),
                "process_id": int(proc_id),
                "compute_dtype": "float32",
            },
        }
    )
    network = build_network_from_config(cfg)
    assert jax.process_count() == int(num_procs), jax.process_count()
    assert jax.device_count() == 8, jax.device_count()
    assert network.mesh.devices.size == 8

    history = network.train(rounds=1)
    with open(out_path, "w") as f:
        json.dump(
            {
                "process_id": int(proc_id),
                "process_count": jax.process_count(),
                "global_devices": jax.device_count(),
                "mean_accuracy": history["mean_accuracy"],
                "mean_loss": history["mean_loss"],
            },
            f,
        )


if __name__ == "__main__":
    main()

"""simulation vs tpu backend equivalence: the same config, seed, and round
count must learn the same way whether the node axis is vmapped on one
device or sharded over the 8-virtual-device CPU mesh
(SURVEY.md §4 test plan items (b)/(c))."""

import numpy as np
import pytest

from murmura_tpu.config import Config
from murmura_tpu.utils.factories import build_network_from_config


def _cfg(backend: str) -> Config:
    return Config.model_validate(
        {
            "experiment": {"name": f"eq-{backend}", "seed": 11, "rounds": 3},
            "topology": {"type": "ring", "num_nodes": 8},
            "aggregation": {"algorithm": "krum", "params": {"num_compromised": 1}},
            "attack": {"enabled": True, "type": "gaussian", "percentage": 0.25,
                        "params": {"noise_std": 5.0}},
            "training": {"local_epochs": 1, "batch_size": 16, "lr": 0.05},
            "data": {"adapter": "synthetic",
                     "params": {"num_samples": 800, "input_dim": 24,
                                "num_classes": 4}},
            "model": {"factory": "mlp",
                      "params": {"input_dim": 24, "hidden_dims": [32],
                                 "num_classes": 4}},
            "backend": backend,
            # Pin full precision so the two backends are numerically
            # comparable; the tpu backend defaults to bfloat16 matmuls.
            "tpu": {"compute_dtype": "float32"},
        }
    )


def test_simulation_and_tpu_backends_match():
    hist_sim = build_network_from_config(_cfg("simulation")).train(rounds=3)
    hist_tpu = build_network_from_config(_cfg("tpu")).train(rounds=3)

    assert hist_sim["round"] == hist_tpu["round"]
    np.testing.assert_allclose(
        hist_sim["mean_accuracy"], hist_tpu["mean_accuracy"], atol=1e-4
    )
    np.testing.assert_allclose(
        hist_sim["mean_loss"], hist_tpu["mean_loss"], rtol=1e-3, atol=1e-4
    )
    np.testing.assert_allclose(
        hist_sim["honest_accuracy"], hist_tpu["honest_accuracy"], atol=1e-4
    )


def test_tpu_backend_learns_under_attack():
    net = build_network_from_config(_cfg("tpu"))
    hist = net.train(rounds=3)
    assert hist["honest_accuracy"][-1] > 0.5  # Krum resists 25% gaussian


@pytest.mark.slow
def test_wearable_window_params_sync_model_input_dim():
    # Non-default window params change sample dimensionality; the model
    # input must follow without a hand-set input_dim.
    cfg = Config.model_validate(
        {
            "experiment": {"name": "win-sync", "seed": 0, "rounds": 1},
            "topology": {"type": "ring", "num_nodes": 4},
            "aggregation": {"algorithm": "fedavg", "params": {}},
            "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.05},
            "data": {"adapter": "wearables.pamap2",
                     "params": {"window_size": 50,
                                "include_heart_rate": False,
                                "num_samples": 200,
                                "partition_method": "iid"}},
            "model": {"factory": "examples.wearables.pamap2", "params": {}},
            "backend": "simulation",
        }
    )
    hist = build_network_from_config(cfg).train(rounds=1)
    assert len(hist["round"]) == 1  # forward pass shape-consistent


def test_tpu_backend_bfloat16_learns():
    cfg = _cfg("tpu")
    cfg.tpu.compute_dtype = "bfloat16"
    hist = build_network_from_config(cfg).train(rounds=3)
    assert np.isfinite(hist["mean_loss"][-1])
    assert hist["honest_accuracy"][-1] > 0.5


def test_tpu_param_dtype_bfloat16():
    # tpu.param_dtype=bfloat16 stores the stacked node state (and the
    # exchanged [N, P] tensor) in bf16; it must actually take effect and
    # stay stable across rounds (attack noise must not promote it back).
    import jax.numpy as jnp
    from jax.tree_util import tree_leaves

    cfg = _cfg("tpu")
    cfg.tpu.param_dtype = "bfloat16"
    net = build_network_from_config(cfg)
    assert all(l.dtype == jnp.bfloat16 for l in tree_leaves(net.params))
    hist = net.train(rounds=2)
    assert all(l.dtype == jnp.bfloat16 for l in tree_leaves(net.params))
    assert np.isfinite(hist["mean_loss"][-1])
    assert hist["honest_accuracy"][-1] > 0.4


def test_ppermute_exchange_matches_allgather():
    # On a circulant graph, the roll-based O(degree) exchange must produce
    # exactly the adjacency-matmul result.
    def cfg(exchange):
        c = _cfg("tpu")
        c.topology.type = "k-regular"
        c.topology.k = 4
        c.aggregation.algorithm = "fedavg"
        c.aggregation.params = {}
        c.tpu.exchange = exchange
        return c

    hist_ag = build_network_from_config(cfg("allgather")).train(rounds=3)
    hist_pp = build_network_from_config(cfg("ppermute")).train(rounds=3)
    np.testing.assert_allclose(
        hist_ag["mean_accuracy"], hist_pp["mean_accuracy"], atol=1e-5
    )
    np.testing.assert_allclose(
        hist_ag["mean_loss"], hist_pp["mean_loss"], rtol=1e-4
    )


def test_ppermute_chunked_kernels_match_sharded_and_unsharded(monkeypatch):
    """Forcing the P-chunked circulant kernels (the 256-node OOM fix,
    base.py _CIRCULANT_CHUNK_BYTES) must not change training history —
    on one device and with the node axis sharded over the 8-device mesh."""
    from murmura_tpu.aggregation import base as agg_base

    def cfg(num_devices):
        c = _cfg("tpu")
        c.topology.type = "k-regular"
        c.topology.k = 4
        c.tpu.exchange = "ppermute"
        c.tpu.num_devices = num_devices
        return c

    ref = build_network_from_config(cfg(1)).train(rounds=3)
    # MLP 24->32->4 => P = 24*32+32+32*4+4 = 964 floats; chunk len
    # 1024 // (8 nodes * 4 bytes) = 32 -> 30 full chunks + tail.
    monkeypatch.setattr(agg_base, "_CIRCULANT_CHUNK_BYTES", 1024)
    chunked = build_network_from_config(cfg(1)).train(rounds=3)
    sharded = build_network_from_config(cfg(8)).train(rounds=3)
    for hist in (chunked, sharded):
        np.testing.assert_allclose(
            ref["mean_loss"], hist["mean_loss"], rtol=1e-4
        )
        np.testing.assert_allclose(
            ref["mean_accuracy"], hist["mean_accuracy"], atol=1e-5
        )


def test_node_axis_sharded_flag_resolution():
    """AggContext.node_axis_sharded selects circulant shift lowerings
    (probe.py): an explicit mesh is authoritative, else tpu.num_devices."""
    from murmura_tpu.utils.factories import _node_axis_sharded

    c1 = _cfg("tpu")
    c1.tpu.num_devices = 1
    assert _node_axis_sharded(c1) is False
    c8 = _cfg("tpu")
    c8.tpu.num_devices = 8
    assert _node_axis_sharded(c8) is True
    assert _node_axis_sharded(_cfg("simulation")) is False

    # Explicit mesh wins over config (a subset mesh on a multi-device host
    # must not pick the sharded lowering).
    import jax
    from jax.sharding import Mesh

    cnull = _cfg("tpu")
    cnull.tpu.num_devices = None
    single = Mesh(np.array(jax.devices()[:1]), ("nodes",))
    assert _node_axis_sharded(cnull, single) is False
    full = Mesh(np.array(jax.devices()), ("nodes",))
    assert _node_axis_sharded(cnull, full) is (len(jax.devices()) > 1)


def test_ppermute_exchange_rejects_noncirculant():
    import pytest as _pytest

    c = _cfg("tpu")
    c.topology.type = "erdos"
    c.topology.p = 0.5
    c.aggregation.algorithm = "fedavg"
    c.aggregation.params = {}
    c.tpu.exchange = "ppermute"
    with _pytest.raises(ValueError, match="circulant"):
        build_network_from_config(c)


import pytest


@pytest.mark.parametrize("algo,params", [
    ("balance", {"gamma": 1.5}),
    ("sketchguard", {"sketch_size": 64}),
    ("ubar", {"rho": 0.6}),
    ("evidential_trust", {"trust_threshold": 0.1}),
    ("median", {}),
    ("trimmed_mean", {"trim_ratio": 0.2}),
])
def test_ppermute_circulant_rule_matches_allgather(algo, params):
    def cfg(exchange):
        c = _cfg("tpu")
        c.topology.type = "ring"
        c.aggregation.algorithm = algo
        c.aggregation.params = dict(params)
        c.tpu.exchange = exchange
        return c

    hist_ag = build_network_from_config(cfg("allgather")).train(rounds=3)
    hist_pp = build_network_from_config(cfg("ppermute")).train(rounds=3)
    np.testing.assert_allclose(
        hist_ag["mean_loss"], hist_pp["mean_loss"], rtol=1e-3
    )
    np.testing.assert_allclose(
        hist_ag["mean_accuracy"], hist_pp["mean_accuracy"], atol=1e-3
    )


@pytest.mark.slow
def test_conv_impl_im2col_config_path_matches_direct():
    """tpu.conv_impl: im2col through the full config path (factories ->
    make_femnist_cnn -> round program): identical history to the direct
    lowering on the same seeds — the flag only changes how XLA lowers the
    convs, never the math."""
    from murmura_tpu.config import Config

    def cfg(conv_impl):
        return Config.model_validate(
            {
                "experiment": {"name": f"ci-{conv_impl}", "seed": 5,
                               "rounds": 2},
                "topology": {"type": "ring", "num_nodes": 8},
                "aggregation": {"algorithm": "fedavg"},
                "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.05},
                "data": {
                    "adapter": "synthetic",
                    "params": {"num_samples": 128,
                                "input_shape": [28, 28, 1],
                                "num_classes": 10},
                },
                "model": {"factory": "examples.leaf.LEAFFEMNISTModel",
                           "params": {"variant": "tiny",
                                      "num_classes": 10}},
                "backend": "tpu",
                "tpu": {"compute_dtype": "float32",
                         "conv_impl": conv_impl},
            }
        )

    hist_direct = build_network_from_config(cfg("direct")).train(rounds=2)
    hist_gemm = build_network_from_config(cfg("im2col")).train(rounds=2)
    np.testing.assert_allclose(
        hist_direct["mean_accuracy"], hist_gemm["mean_accuracy"], atol=1e-3
    )
    np.testing.assert_allclose(
        hist_direct["mean_loss"], hist_gemm["mean_loss"], rtol=1e-3
    )


@pytest.mark.slow
def test_64node_rules_scale_smoke(monkeypatch):
    """Structural scale coverage on CPU: 64 nodes crosses the bf16
    auto-default boundary (factories.resolved_param_dtype) and, with the
    chunk budget forced down, exercises the P-chunked circulant/dense
    kernels inside a full round program — the code paths the 256-node
    chip runs take, minus the chip."""
    from murmura_tpu.aggregation import base as agg_base

    # Tiny model keeps this a smoke test; the forced budget still splits
    # its P into multiple chunks.
    monkeypatch.setattr(agg_base, "_CIRCULANT_CHUNK_BYTES", 64 * 1024)

    for algo, params, exchange in [
        ("krum", {"num_compromised": 1}, "ppermute"),
        ("geometric_median", {}, "allgather"),
        ("median", {}, "allgather"),
        ("trimmed_mean", {"trim_ratio": 0.2}, "ppermute"),
    ]:
        c = _cfg("tpu")
        c.topology.type = "k-regular"
        c.topology.k = 4
        c.topology.num_nodes = 64
        c.data.params["num_samples"] = 64 * 20
        c.aggregation.algorithm = algo
        c.aggregation.params = dict(params)
        c.tpu.exchange = exchange
        c.tpu.compute_dtype = "float32"  # CPU: bf16 matmuls are emulated
        hist = build_network_from_config(c).train(rounds=2)
        assert len(hist["round"]) == 2
        assert np.isfinite(hist["mean_loss"]).all(), (algo, exchange)

"""End-to-end crash/recovery on the ZMQ distributed backend (ISSUE-3
acceptance, distributed half): the FaultInjector SIGKILLs a scheduled node
mid-run, survivors re-resolve expected neighbors from the schedule (no
deadline hang on a known-dead peer), and the node rejoins from its
per-node checkpoint at the scheduled recovery round and reports metrics
again.

Wall-clock heavy (spawned jax imports + compiles on a shared CI core) —
marked slow, like the sibling kill test in test_distributed.py."""

import time

import numpy as np
import pytest

from murmura_tpu.config import Config
from murmura_tpu.faults.schedule import FaultSchedule

NODES = 4
ROUNDS = 5
CHURN = dict(crash_prob=0.12, recovery_prob=0.8, min_down_rounds=2)


def _find_seed():
    """Deterministic search for a seed whose schedule kills exactly one
    node for rounds 1-2 and recovers it for rounds 3-4, with every other
    node up the whole run.  Pure numpy — the same schedule every process
    reconstructs in the run itself."""
    for seed in range(5000):
        s = FaultSchedule(NODES, seed=seed, **CHURN)
        alive = np.stack([s.alive_at(r) for r in range(ROUNDS)]) > 0
        victims = np.flatnonzero(~alive.all(axis=0))
        if len(victims) != 1:
            continue
        v = victims[0]
        if alive[0, v] and not alive[1, v] and not alive[2, v] \
                and alive[3, v] and alive[4, v]:
            return seed, int(v)
    raise AssertionError("no seed produced the wanted churn pattern")


@pytest.mark.slow
def test_sigkill_and_checkpoint_recovery(tmp_path):
    from murmura_tpu.distributed.runner import DistributedRunner

    seed, victim = _find_seed()
    duration = 30.0
    cfg = Config.model_validate(
        {
            "experiment": {"name": "fault-recovery", "seed": 42,
                           "rounds": ROUNDS},
            "topology": {"type": "fully", "num_nodes": NODES},
            "aggregation": {"algorithm": "fedavg"},
            "training": {"local_epochs": 1, "batch_size": 16, "lr": 0.05},
            "data": {
                "adapter": "synthetic",
                "params": {"num_samples": 320, "input_dim": 16,
                            "num_classes": 4},
            },
            "model": {
                "factory": "mlp",
                "params": {"input_dim": 16, "num_classes": 4,
                            "hidden_dims": [16]},
            },
            "backend": "distributed",
            "distributed": {
                "transport": "ipc",
                "ipc_dir": str(tmp_path),
                "round_duration_s": duration,
                "startup_grace_s": 90.0,  # 5 spawns share one CI core
            },
            "faults": {"enabled": True, "seed": seed, **CHURN},
        }
    )
    runner = DistributedRunner(cfg)
    runner.start()
    assert runner.injector is not None
    history = runner.wait()

    # The injector really killed and really respawned the scheduled victim.
    kinds = {(kind, node) for _, kind, node in runner.injector.events}
    assert ("kill", victim) in kinds, runner.injector.events
    assert ("respawn", victim) in kinds, runner.injector.events

    # Completed history, partial rounds recorded, no hang past a deadline:
    # every round is present despite the mid-run SIGKILL.
    assert history["round"] == list(range(1, ROUNDS + 1)), history
    reporting = history["reporting_nodes"]
    assert reporting[0] == NODES, history            # round 1 fully reported
    assert reporting[1] == NODES - 1, history        # victim dead
    assert reporting[2] == NODES - 1, history        # still dead
    # Scheduled recovery: the node rejoined from its checkpoint and
    # reports metrics again (skipped-frame or full — it is REPORTING).
    assert reporting[3] == NODES, history
    assert reporting[4] == NODES, history

    # The per-node checkpoint the recovery restored from exists.
    run_dirs = [p for p in tmp_path.iterdir() if p.is_dir()]
    assert any(
        (d / f"node_{victim}.ckpt.npz").exists() for d in run_dirs
    ), list(tmp_path.rglob("*"))

    # Learning survived the churn: the last real accuracy beats chance.
    accs = np.asarray(history["mean_accuracy"], dtype=np.float64)
    finite = accs[np.isfinite(accs)]
    assert finite.size and finite[-1] > 0.3, history

"""The serving daemon (murmura_tpu/serve/daemon.py): admission refusals,
socket-layer error classification, zero-recompile admission into the warm
bucket (MUR1601 representative), eviction semantics, SIGKILL-resume
byte-identity (MUR1603 representative + negative), and the socket
protocol round trip.

Tier-1 keeps the representatives compact (5-node ring, 2 rounds,
synthetic data); the full MUR1600-1603 family runs in the package gate
(``murmura check --serve``), exercised here under ``-m slow``.
"""

import errno
import os
import socket as socket_mod
import threading
import time

import pytest

from murmura_tpu.analysis.durability import history_equal
from murmura_tpu.config import Config
from murmura_tpu.durability import dispatch as ddispatch
from murmura_tpu.serve.daemon import (
    TERMINAL_STATES,
    ServeDaemon,
    SubmissionError,
    normalize_submission,
)
from murmura_tpu.serve.protocol import send_request


def _tenant(seed, lr=0.05, rounds=2, rule="fedavg"):
    return {
        "experiment": {"name": f"tenant-{seed}", "seed": seed,
                       "rounds": rounds},
        "topology": {"type": "ring", "num_nodes": 5},
        "aggregation": {"algorithm": rule},
        "training": {"local_epochs": 1, "batch_size": 8, "lr": lr},
        "data": {"adapter": "synthetic",
                 "params": {"num_samples": 40, "input_shape": [6],
                            "num_classes": 3}},
        "model": {"factory": "mlp",
                  "params": {"input_dim": 6, "hidden_dims": [8],
                             "num_classes": 3}},
        "backend": "simulation",
    }


def _daemon(tmp_path, name, capacity=2, checkpoint_every=1):
    raw = _tenant(0)
    raw["serve"] = {
        "state_dir": str(tmp_path / name),
        "capacity": capacity,
        "checkpoint_every": checkpoint_every,
        "poll_interval_s": 0.05,
    }
    return ServeDaemon(Config.model_validate(raw))


class TestAdmission:
    def test_driver_sections_refused(self):
        for section, payload in (
            ("sweep", {"members": [{"seed": 1}]}),
            ("frontier", {"rules": ["fedavg"]}),
            ("grid", {"rules": ["fedavg"]}),
            ("serve", {"state_dir": "/tmp/x"}),
        ):
            with pytest.raises(SubmissionError, match=section):
                normalize_submission({**_tenant(1), section: payload})

    def test_distributed_backend_refused(self):
        with pytest.raises(SubmissionError, match="distributed"):
            normalize_submission({**_tenant(1), "backend": "distributed"})

    def test_invalid_config_refused_with_reason(self):
        raw = _tenant(1)
        raw["training"]["lr"] = "not-a-float"
        with pytest.raises(SubmissionError, match="invalid"):
            normalize_submission(raw)

    def test_member_axis_shares_the_admission_key(self):
        _, fp_a = normalize_submission(_tenant(1, lr=0.05))
        _, fp_b = normalize_submission(_tenant(99, lr=0.001))
        _, fp_c = normalize_submission(_tenant(1, rule="median"))
        assert fp_a == fp_b  # seed/name/lr are member lanes
        assert fp_a != fp_c  # the rule changes the traced program


class TestSocketErrorClassification:
    """Satellite 1: the daemon's socket layer rides the durability
    envelope — its failure modes must classify transient."""

    def test_transport_exception_types_transient(self):
        for exc in (
            ConnectionResetError("peer went away"),
            BrokenPipeError("write to dead daemon"),
            ConnectionRefusedError("daemon restarting"),
            socket_mod.timeout("recv"),
        ):
            assert ddispatch.classify_error(exc) == "transient"

    def test_eaddrinuse_errno_transient(self):
        # A SIGKILL'd daemon leaves a stale socket file; the rebind's
        # EADDRINUSE arrives as a bare OSError — errno carries the class.
        exc = OSError(errno.EADDRINUSE, "Address already in use")
        assert ddispatch.classify_error(exc) == "transient"

    def test_eaddrinuse_marker_transient(self):
        exc = RuntimeError("bind failed: Address already in use")
        assert ddispatch.classify_error(exc) == "transient"

    def test_unrelated_oserror_stays_fatal(self):
        exc = OSError(errno.ENOENT, "no such state dir")
        assert ddispatch.classify_error(exc) == "fatal"


class TestEviction:
    def test_evicted_queued_tenant_never_runs(self, tmp_path):
        d = _daemon(tmp_path, "evict")
        a = d.submit_config(_tenant(5))["id"]
        b = d.submit_config(_tenant(6))["id"]
        rec = d.evict(a, "user cancel")
        assert rec["state"] == "evicted"
        assert rec["error"] == "user cancel"
        nxt = d._next_generation()
        assert nxt is not None and nxt[1] == [b]

    def test_evict_is_idempotent_and_loud_on_unknown(self, tmp_path):
        d = _daemon(tmp_path, "evict2")
        a = d.submit_config(_tenant(5))["id"]
        d.evict(a)
        assert d.evict(a)["state"] == "evicted"
        with pytest.raises(KeyError, match="sub-99999"):
            d.evict("sub-99999")


class TestWarmBucket:
    def test_admission_after_first_generation_compiles_nothing(
        self, tmp_path,
    ):
        # MUR1601 representative: the bucket compiles once, with its
        # first generation; every later admission is a value-only
        # reset_run splice into the warm lanes.
        from murmura_tpu.analysis.sanitizers import track_compiles

        d = _daemon(tmp_path, "warm", capacity=2)
        gen1 = [d.submit_config(_tenant(5))["id"],
                d.submit_config(_tenant(6, lr=0.02))["id"]]
        d.drain()
        gen2 = [d.submit_config(_tenant(21))["id"],
                d.submit_config(_tenant(22, lr=0.01))["id"]]
        with track_compiles() as tracker:
            d.drain()
        assert tracker.total == 0
        for sub_id in gen1 + gen2:
            rec = d._ledger[sub_id]
            assert rec["state"] == "done"
            assert rec["final_accuracy"] is not None
            assert rec["phase_times"]["rounds"] == 2
        assert len(d._buckets) == 1
        (bucket,) = d._buckets.values()
        assert bucket["gen"] == 2


class _Kill(BaseException):
    """SIGKILL stand-in: not an Exception, so no handler between the
    training loop and the test can swallow it — the ledger is left with
    'running' states exactly as a real kill would leave it."""


class TestCrashResume:
    def test_sigkill_resume_byte_identical(self, tmp_path, monkeypatch):
        # MUR1603 representative: kill after round 1 of 2 (one cadence
        # snapshot on disk), restart over the same state_dir, recover.
        import murmura_tpu.core.gang as gang_mod

        ref = _daemon(tmp_path, "ref")
        for seed in (5, 6):
            ref.submit_config(_tenant(seed))
        ref.drain()
        ref_hist = {
            rec["config"]["experiment"]["seed"]: rec["history"]
            for rec in ref._ledger.values()
        }

        victim = _daemon(tmp_path, "victim")
        for seed in (5, 6):
            victim.submit_config(_tenant(seed))
        orig_train = gang_mod.GangNetwork.train

        def dying_train(self, rounds, **kwargs):
            orig_train(self, rounds=1, **kwargs)
            raise _Kill()

        monkeypatch.setattr(gang_mod.GangNetwork, "train", dying_train)
        with pytest.raises(_Kill):
            victim.drain()
        for rec in victim._ledger.values():
            assert rec["state"] == "running"
        monkeypatch.setattr(gang_mod.GangNetwork, "train", orig_train)

        revived = _daemon(tmp_path, "victim")  # same state_dir
        recovered = revived.recover()
        assert sorted(recovered) == ["sub-00001", "sub-00002"]
        for rec in revived._ledger.values():
            assert rec["state"] == "done"
            seed = rec["config"]["experiment"]["seed"]
            assert history_equal(rec["history"], ref_hist[seed])

    def test_recover_without_generation_record_fails_loud(self, tmp_path):
        # MUR1603 negative: a kill can land between the 'running' ledger
        # write and the generation.json write only if the generation
        # record itself was lost (it is written first) — recovery must
        # not invent work, it marks the tenant failed with the reason.
        d = _daemon(tmp_path, "neg")
        sub_id = d.submit_config(_tenant(5))["id"]
        d._pending.clear()
        d._update(sub_id, state="running", gen=41, lane=0)

        revived = _daemon(tmp_path, "neg")
        assert revived.recover() == []
        rec = revived._ledger[sub_id]
        assert rec["state"] == "failed"
        assert "generation record lost" in rec["error"]


class TestSocketProtocol:
    def test_submit_status_list_shutdown_round_trip(self, tmp_path):
        d = _daemon(tmp_path, "sock")
        thread = threading.Thread(target=d.serve_forever, daemon=True)
        thread.start()
        sp = d.socket_path
        try:
            ping = send_request(sp, {"op": "ping"})
            assert ping["ok"] and ping["pid"] == os.getpid()

            reply = send_request(sp, {"op": "submit", "config": _tenant(5)})
            assert reply["ok"]
            sub_id = reply["id"]

            deadline = time.monotonic() + 120
            state = None
            while time.monotonic() < deadline:
                status = send_request(sp, {"op": "status", "id": sub_id})
                state = status["submission"]["state"]
                if state in TERMINAL_STATES:
                    break
                time.sleep(0.1)
            assert state == "done"
            assert status["submission"]["final_accuracy"] is not None

            bad = send_request(sp, {
                "op": "submit",
                "config": {**_tenant(7),
                           "sweep": {"members": [{"seed": 1}]}},
            })
            assert not bad["ok"] and "sweep" in bad["error"]

            rows = send_request(sp, {"op": "list"})["submissions"]
            assert [r["id"] for r in rows] == [sub_id]

            unknown = send_request(sp, {"op": "status", "id": "sub-nope"})
            assert not unknown["ok"]
        finally:
            try:
                send_request(sp, {"op": "shutdown"}, retries=1)
            except Exception:
                pass
            thread.join(timeout=15)
        assert not thread.is_alive()
        assert not os.path.exists(sp)


@pytest.mark.slow
def test_check_serve_family_clean():
    """The full MUR1600-1603 package gate comes back clean."""
    from murmura_tpu.analysis.serve import check_serve

    findings = check_serve(force=True)
    assert findings == [], [f"{f.rule}: {f.message}" for f in findings]

"""Adaptive adversaries + the robustness frontier (ISSUE 11).

The contracts under test (docs/ROBUSTNESS.md "Adaptive adversaries & the
frontier"): the closed-loop attacks tune themselves against the audit-tap
acceptance signal inside the compiled round program (no recompiles, no
added collectives), their adaptation state rides ``agg_state`` under
``ATTACK_STATE_KEYS`` (so durability covers it — tests/test_durability.py
holds the crash-matrix cell), quarantined/scrubbed rows read as
rejections while dead rows are not observations at all, the ALIE
``estimator: coalition`` mode reproduces Baruch et al.'s construction,
and `murmura frontier` locates breaking points over one warm gang bucket.
Representative MUR1000-1003 cells run tier-1; the full grids are ``slow``
(and in `murmura check --adaptive`).
"""

import dataclasses
import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from murmura_tpu.analysis.adaptive import (
    ADAPTIVE_ATTACK_KINDS,
    _build_adaptive,
    adaptive_influence_findings,
    check_adaptive,
    check_attack_state_registry,
    collective_cell_findings,
    containment_findings,
    gang_reset_findings,
    recompile_cell_findings,
)
from murmura_tpu.attacks import (
    ADAPTIVE_ATTACKS,
    ATTACK_STATE_KEYS,
    AdaptiveAttack,
    make_adaptive_alie_attack,
    make_bisection_attack,
)
from murmura_tpu.attacks.adaptive import acceptance_feedback, coalition_stats
from murmura_tpu.attacks.alie import make_alie_attack
from murmura_tpu.attacks.gaussian import make_gaussian_attack
from murmura_tpu.attacks.label_flip import make_label_flip
from murmura_tpu.config import Config
from murmura_tpu.utils.factories import (
    ConfigError,
    build_attack,
    build_gang_from_config,
    build_network_from_config,
)


def _raw(**over):
    r = {
        "experiment": {"name": "adaptive-test", "seed": 7, "rounds": 4},
        "topology": {"type": "ring", "num_nodes": 5},
        "aggregation": {"algorithm": "krum",
                        "params": {"num_compromised": 1}},
        "training": {"local_epochs": 1, "batch_size": 8, "lr": 0.05},
        "data": {"adapter": "synthetic",
                 "params": {"num_samples": 40, "input_shape": [6],
                            "num_classes": 3}},
        "model": {"factory": "mlp",
                  "params": {"input_dim": 6, "hidden_dims": [8],
                             "num_classes": 3}},
        "backend": "simulation",
        "attack": {"enabled": True, "type": "gaussian", "percentage": 0.3,
                   "params": {"noise_std": 5.0},
                   "adaptive": {"enabled": True}},
    }
    r.update(over)
    return r


def _cfg(**over):
    return Config.model_validate(_raw(**over))


# ---------------------------------------------------------------------------
# The adaptation state machines (attacks/adaptive.py), unit level
# ---------------------------------------------------------------------------


class TestBisectionStateMachine:
    def _attack(self, **kw):
        inner = make_gaussian_attack(4, 0.5, noise_std=1.0, seed=0)
        return make_bisection_attack(inner, **kw)

    def test_growth_then_bisection(self):
        atk = self._attack(scale_init=1.0, scale_max=8.0, growth=2.0)
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        ones = jnp.ones(4)
        state = {k: jnp.asarray(v) for k, v in atk.init_attack_state(4).items()}
        # Accepted while unbracketed: the probe doubles toward the cap.
        state = atk.update_attack_state(state, ones, ones, comp)
        comp_idx = np.flatnonzero(atk.compromised)[0]
        assert np.asarray(state["atk_scale"])[comp_idx] == 2.0
        assert np.asarray(state["atk_lo"])[comp_idx] == 1.0
        # First rejection pins the bracket; the probe bisects [lo, hi].
        state = atk.update_attack_state(state, jnp.zeros(4), ones, comp)
        assert np.asarray(state["atk_hi"])[comp_idx] == 2.0
        assert np.asarray(state["atk_scale"])[comp_idx] == 1.5
        # atk_lo converges from below: it only ever holds accepted scales.
        assert np.asarray(state["atk_lo"])[comp_idx] == 1.0

    def test_rejection_at_the_cap_still_pins_the_bracket(self):
        # Regression: a margin in (scale_max/growth, scale_max] means the
        # growth phase's first rejection happens exactly AT scale_max; an
        # atk_hi init of scale_max itself could not distinguish that from
        # "never rejected", wedging the probe at the cap forever and
        # understating atk_lo (the frontier's headline number) by up to
        # the growth factor.  The sentinel init sits above the cap.
        atk = self._attack(scale_init=1.0, scale_max=8.0, growth=2.0)
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        ones = jnp.ones(4)
        idx = np.flatnonzero(atk.compromised)[0]
        state = {k: jnp.asarray(v) for k, v in atk.init_attack_state(4).items()}

        def margin_accept(state):
            # An idealized defense with true margin 6: accept iff the
            # probed scale is <= 6.
            s = np.asarray(state["atk_scale"])
            return jnp.asarray((s <= 6.0).astype(np.float32))

        for _ in range(8):
            state = atk.update_attack_state(
                state, margin_accept(state), ones, comp
            )
        lo = float(np.asarray(state["atk_lo"])[idx])
        hi = float(np.asarray(state["atk_hi"])[idx])
        # The bracket pinned below the cap and converged around 6.
        assert hi <= 8.0
        assert 4.0 <= lo <= 6.0 and hi - lo < 1.0, (lo, hi)

    def test_honest_rows_never_move(self):
        atk = self._attack()
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        state0 = {k: jnp.asarray(v) for k, v in atk.init_attack_state(4).items()}
        state = atk.update_attack_state(
            state0, jnp.zeros(4), jnp.ones(4), comp
        )
        honest = ~atk.compromised
        for k in state:
            np.testing.assert_array_equal(
                np.asarray(state[k])[honest], np.asarray(state0[k])[honest]
            )

    def test_unobserved_rows_frozen(self):
        # A dead node's taps are masked out: observed=0 freezes ALL its
        # adaptation state, whatever the accept value claims.
        atk = self._attack()
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        state0 = {k: jnp.asarray(v) for k, v in atk.init_attack_state(4).items()}
        state = atk.update_attack_state(
            state0, jnp.zeros(4), jnp.zeros(4), comp
        )
        for k in state:
            np.testing.assert_array_equal(
                np.asarray(state[k]), np.asarray(state0[k])
            )

    def test_scale_zero_recovers_honest_broadcast(self):
        atk = self._attack()
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        flat = jnp.asarray(np.random.default_rng(0).normal(size=(4, 8)),
                           jnp.float32)
        state = {k: jnp.asarray(v) for k, v in atk.init_attack_state(4).items()}
        state["atk_scale"] = jnp.zeros(4)
        out = atk.apply_adaptive(flat, comp, jax.random.PRNGKey(0), 0, state)
        np.testing.assert_array_equal(np.asarray(out), np.asarray(flat))

    def test_trains_locally_unlike_wrapped_static(self):
        # A bisection around a frozen-param broadcast is degenerate —
        # distance filters reject the staleness at any scale.
        inner = make_gaussian_attack(4, 0.5, noise_std=1.0, seed=0)
        assert not inner.trains_locally
        assert make_bisection_attack(inner).trains_locally

    def test_rejects_data_poisoning(self):
        flip = make_label_flip(4, 0.5, seed=0)
        with pytest.raises(ValueError, match="poisons data"):
            make_bisection_attack(flip)


class TestAdaptiveAlieStateMachine:
    def test_z_walks_with_acceptance(self):
        atk = make_adaptive_alie_attack(
            8, attack_percentage=0.25, z=1.0, eta=0.25, seed=0
        )
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        ones = jnp.ones(8)
        state = {k: jnp.asarray(v) for k, v in atk.init_attack_state(8).items()}
        idx = np.flatnonzero(atk.compromised)
        state = atk.update_attack_state(state, ones, ones, comp)
        np.testing.assert_allclose(np.asarray(state["atk_z"])[idx], 1.25)
        state = atk.update_attack_state(state, jnp.zeros(8), ones, comp)
        np.testing.assert_allclose(
            np.asarray(state["atk_z"])[idx], 1.25 * 0.75
        )

    def test_z_clamped(self):
        atk = make_adaptive_alie_attack(
            8, attack_percentage=0.25, z=1.0, eta=0.9, z_min=0.5, z_cap=1.2,
            seed=0,
        )
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        ones = jnp.ones(8)
        state = {k: jnp.asarray(v) for k, v in atk.init_attack_state(8).items()}
        idx = np.flatnonzero(atk.compromised)
        state = atk.update_attack_state(state, ones, ones, comp)
        np.testing.assert_allclose(np.asarray(state["atk_z"])[idx], 1.2)
        for _ in range(3):
            state = atk.update_attack_state(state, jnp.zeros(8), ones, comp)
        np.testing.assert_allclose(np.asarray(state["atk_z"])[idx], 0.5)

    def test_apply_uses_per_row_state_z(self):
        atk = make_adaptive_alie_attack(8, attack_percentage=0.25, z=1.0,
                                        seed=0)
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        rng = np.random.default_rng(0)
        flat = jnp.asarray(rng.normal(size=(8, 16)), jnp.float32)
        state = {k: jnp.asarray(v) for k, v in atk.init_attack_state(8).items()}
        out1 = np.asarray(atk.apply_adaptive(
            flat, comp, jax.random.PRNGKey(0), 0, state))
        state2 = dict(state)
        state2["atk_z"] = state["atk_z"] * 3.0
        out2 = np.asarray(atk.apply_adaptive(
            flat, comp, jax.random.PRNGKey(0), 0, state2))
        idx = np.flatnonzero(atk.compromised)
        honest = ~atk.compromised
        # z scales the crafted deviation on compromised rows only.
        assert np.abs(out2[idx] - out1[idx]).max() > 0
        np.testing.assert_array_equal(out1[honest], np.asarray(flat)[honest])
        np.testing.assert_array_equal(out2[honest], np.asarray(flat)[honest])


class TestAcceptanceFeedback:
    def test_tapped_rule_fraction(self):
        stats = {"tap_selected_by": jnp.asarray([2.0, 0.0, 1.0]),
                 "tap_considered_by": jnp.asarray([2.0, 2.0, 4.0])}
        accept, observed = acceptance_feedback(
            stats, {}, jnp.full(3, 2.0), None
        )
        np.testing.assert_allclose(np.asarray(accept), [1.0, 0.0, 0.25])
        np.testing.assert_allclose(np.asarray(observed), [1.0, 1.0, 1.0])

    def test_untapped_rule_is_blind(self):
        accept, observed = acceptance_feedback({}, {}, jnp.full(3, 2.0), None)
        np.testing.assert_allclose(np.asarray(accept), 1.0)
        np.testing.assert_allclose(np.asarray(observed), 1.0)

    def test_scrub_and_quarantine_are_rejections(self):
        # An overflow scrub/quarantine IS an observation: the attack was
        # too loud, accept forced to 0 — it must not read as "missing".
        stats = {"tap_selected_by": jnp.asarray([2.0, 2.0, 2.0]),
                 "tap_considered_by": jnp.asarray([2.0, 2.0, 2.0])}
        faults = {"tap_attack_scrubbed": jnp.asarray([0.0, 1.0, 0.0]),
                  "tap_quarantined": jnp.asarray([0.0, 0.0, 1.0])}
        accept, observed = acceptance_feedback(
            stats, faults, jnp.full(3, 2.0), None
        )
        np.testing.assert_allclose(np.asarray(accept), [1.0, 0.0, 0.0])
        np.testing.assert_allclose(np.asarray(observed), [1.0, 1.0, 1.0])

    def test_dead_rows_are_not_observations(self):
        stats = {"tap_selected_by": jnp.asarray([2.0, 0.0, 1.0]),
                 "tap_considered_by": jnp.asarray([2.0, 2.0, 2.0])}
        accept, observed = acceptance_feedback(
            stats, {}, jnp.full(3, 2.0), jnp.asarray([1.0, 0.0, 1.0])
        )
        np.testing.assert_allclose(np.asarray(observed), [1.0, 0.0, 1.0])


# ---------------------------------------------------------------------------
# ALIE estimator faithfulness (satellite: params.estimator)
# ---------------------------------------------------------------------------


class TestAlieEstimators:
    def _stats_case(self, n=10, dim=32, pct=0.4, seed=3):
        atk = make_alie_attack(n, pct, z=1.5, seed=seed)
        rng = np.random.default_rng(0)
        flat = jnp.asarray(rng.normal(size=(n, dim)), jnp.float32)
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        return atk, flat, comp

    def test_omniscient_hits_envelope_exactly(self):
        # Perfect knowledge: the crafted vector sits exactly at
        # mu_honest - z * sigma_honest per coordinate — the optimal point
        # of the paper's objective, achievable only omniscient.
        atk, flat, comp = self._stats_case()
        out = np.asarray(atk.apply(flat, comp, None, 0))
        honest = np.asarray(flat)[~atk.compromised]
        mu, sigma = honest.mean(axis=0), honest.std(axis=0)
        idx = np.flatnonzero(atk.compromised)
        np.testing.assert_allclose(
            out[idx[0]], mu - 1.5 * sigma, rtol=1e-5, atol=1e-6
        )

    def test_coalition_blind_to_honest_rows(self):
        # The paper-faithful estimator sees only the colluders' own
        # benign-trained states: perturbing every honest row must not
        # move the crafted vector (the property the omniscient default
        # cannot have — its caveat in alie.py).
        n = 10
        atk_c = make_alie_attack(n, 0.4, z=1.5, seed=3,
                                 estimator="coalition")
        rng = np.random.default_rng(0)
        flat = np.asarray(rng.normal(size=(n, 16)), np.float32)
        comp = jnp.asarray(atk_c.compromised.astype(np.float32))
        out1 = np.asarray(atk_c.apply(jnp.asarray(flat), comp, None, 0))
        flat2 = flat.copy()
        flat2[~atk_c.compromised] += 7.0
        out2 = np.asarray(atk_c.apply(jnp.asarray(flat2), comp, None, 0))
        idx = np.flatnonzero(atk_c.compromised)
        np.testing.assert_array_equal(out1[idx], out2[idx])
        atk_o = make_alie_attack(n, 0.4, z=1.5, seed=3,
                                 estimator="omniscient")
        o1 = np.asarray(atk_o.apply(jnp.asarray(flat), comp, None, 0))
        o2 = np.asarray(atk_o.apply(jnp.asarray(flat2), comp, None, 0))
        assert np.abs(o1[idx] - o2[idx]).max() > 1.0

    def test_coalition_stats_match_numpy(self):
        rng = np.random.default_rng(1)
        flat = np.asarray(rng.normal(size=(8, 12)), np.float32)
        comp = np.zeros(8, np.float32)
        comp[[2, 5, 6]] = 1.0
        mu, var = coalition_stats(
            jnp.asarray(flat), jnp.asarray(comp), "coalition"
        )
        rows = flat[comp > 0]
        np.testing.assert_allclose(np.asarray(mu)[0], rows.mean(axis=0),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(var)[0], rows.var(axis=0),
                                   rtol=1e-4, atol=1e-5)

    def test_coalition_trains_locally(self):
        # The coalition sample must be benign GRADIENTS, not frozen init
        # params — the colluders run local SGD like label_flip's.
        assert make_alie_attack(8, 0.4, estimator="coalition").trains_locally
        assert not make_alie_attack(8, 0.4).trains_locally

    def test_bad_estimator_rejected(self):
        with pytest.raises(ValueError, match="estimator"):
            make_alie_attack(8, 0.4, estimator="psychic")
        with pytest.raises(ConfigError, match="estimator"):
            build_attack(_cfg(attack={
                "enabled": True, "type": "alie", "percentage": 0.4,
                "params": {"estimator": "psychic"}}))

    def test_coalition_needs_two_colluders(self):
        # sigma over a 1-sample coalition is 0: mu - z*s degenerates to
        # the colluder's benign state — a silent no-attack run.
        with pytest.raises(ConfigError, match="at least 2"):
            build_attack(_cfg(attack={
                "enabled": True, "type": "alie", "percentage": 0.2,
                "params": {"estimator": "coalition"}}))

    def test_omniscient_at_least_as_strong_on_krum(self):
        # The filtered-rule comparison the frontier labels lean on:
        # everything is seeded, so this is a deterministic pin, not a
        # statistical claim.  Omniscient crafts from the TRUE honest
        # stats; the coalition estimate can only overshoot the envelope
        # (risking rejection) or undershoot it (wasting budget).
        def run(estimator):
            cfg = _cfg(
                experiment={"name": "est", "seed": 3, "rounds": 5},
                topology={"type": "fully", "num_nodes": 10},
                aggregation={"algorithm": "krum",
                             "params": {"num_compromised": 4}},
                attack={"enabled": True, "type": "alie", "percentage": 0.4,
                        "params": {"z": 1.5, "estimator": estimator}},
            )
            net = build_network_from_config(cfg)
            net.train(rounds=5, verbose=False)
            return net.history["honest_accuracy"][-1]

        assert run("omniscient") <= run("coalition") + 1e-9


# ---------------------------------------------------------------------------
# Config / factory wiring
# ---------------------------------------------------------------------------


class TestAdaptiveConfig:
    def test_factory_builds_adaptive_twins(self):
        atk = build_attack(_cfg())
        assert isinstance(atk, AdaptiveAttack)
        assert atk.name == "bisection_gaussian"
        alie = build_attack(_cfg(attack={
            "enabled": True, "type": "alie", "percentage": 0.3,
            "adaptive": {"enabled": True}}))
        assert isinstance(alie, AdaptiveAttack)
        assert alie.name == "adaptive_alie"

    def test_adaptive_without_attack_rejected(self):
        with pytest.raises(Exception, match="no attack to adapt"):
            _cfg(attack={"enabled": False, "adaptive": {"enabled": True}})

    def test_adaptive_rejects_unscalable_attacks(self):
        for t in ("label_flip", "topology_liar"):
            with pytest.raises(Exception, match="does not support"):
                _cfg(attack={"enabled": True, "type": t, "percentage": 0.3,
                             "adaptive": {"enabled": True}})

    def test_adaptive_rejects_distributed_and_dmtt(self):
        with pytest.raises(Exception, match="distributed"):
            _cfg(backend="distributed")
        with pytest.raises(Exception, match="dmtt"):
            _cfg(topology={"type": "fully", "num_nodes": 5},
                 dmtt={"allow_static": True})

    def test_bracket_sanity(self):
        with pytest.raises(Exception, match="scale_init"):
            _cfg(attack={"enabled": True, "type": "gaussian",
                         "percentage": 0.3,
                         "adaptive": {"enabled": True, "scale_init": 9.0,
                                      "scale_max": 4.0}})

    def test_adaptive_disabled_is_byte_identical(self):
        # The "default off" contract: an adaptive block that is present
        # but disabled builds the SAME static attack and the SAME history
        # as no adaptive block at all.
        base = _raw()
        base["attack"] = {"enabled": True, "type": "gaussian",
                          "percentage": 0.3, "params": {"noise_std": 5.0}}
        withblock = _raw()
        withblock["attack"] = dict(base["attack"],
                                   adaptive={"enabled": False})
        h1 = build_network_from_config(
            Config.model_validate(base)).train(rounds=3)
        h2 = build_network_from_config(
            Config.model_validate(withblock)).train(rounds=3)
        assert h1 == h2
        assert not isinstance(
            build_attack(Config.model_validate(withblock)), AdaptiveAttack
        )

    def test_static_program_has_no_adaptive_surface(self):
        # A static-strength run must trace the pre-PR program: no
        # ATTACK_STATE_KEYS in agg_state, no atk_* metrics.
        base = _raw()
        base["attack"] = {"enabled": True, "type": "gaussian",
                          "percentage": 0.3, "params": {"noise_std": 5.0}}
        net = build_network_from_config(Config.model_validate(base))
        assert not (set(ATTACK_STATE_KEYS) & set(net.agg_state))
        assert not net.program.adaptive_attack
        net.train(rounds=2)
        assert not any(k.startswith("agg_atk_") for k in net.history)


# ---------------------------------------------------------------------------
# End-to-end closed-loop behavior + composition
# ---------------------------------------------------------------------------


class TestClosedLoop:
    def test_bisection_converges_against_krum(self):
        net = build_network_from_config(_cfg())
        net.train(rounds=4)
        comp = np.asarray(net.compromised) > 0
        lo = np.asarray(net.agg_state["atk_lo"])[comp]
        hi = np.asarray(net.agg_state["atk_hi"])[comp]
        # The bracket tightened from its [0, scale_max] init.
        assert (hi - lo < 8.0).all()
        assert any(k.startswith("agg_atk_") for k in net.history)

    def test_untapped_rule_escalates_blind(self):
        # fedavg emits no selection taps: the attacker reads constant
        # acceptance and rides the growth phase to the cap.
        net = build_network_from_config(
            _cfg(aggregation={"algorithm": "fedavg", "params": {}}))
        net.train(rounds=4)
        comp = np.asarray(net.compromised) > 0
        assert (np.asarray(net.agg_state["atk_scale"])[comp] == 8.0).all()
        assert (np.asarray(net.agg_state["atk_lo"])[comp] > 0).all()

    def test_dead_compromised_node_freezes_adaptation(self):
        # Churn composition: a dead node's taps are masked out — the EMA
        # and bracket must FREEZE at their last value, not decay (a dead
        # node broadcasting nothing is not a rejection).  crash_prob=1
        # kills everyone from round 0, so the state must stay exactly at
        # init; without the observed gate the zeroed taps would read as
        # rejections and walk the bracket down every round.
        cfg = _cfg(faults={"enabled": True, "crash_prob": 1.0,
                           "recovery_prob": 0.0, "seed": 1})
        net = build_network_from_config(cfg)
        init = {k: np.asarray(v) for k, v in net.agg_state.items()
                if k.startswith("atk_")}
        assert init, "the adaptive cell must carry attack state"
        net.train(rounds=3)
        alive = np.asarray(net.history["agg_alive"])
        assert (alive == 0.0).all(), "the schedule must actually kill"
        for k, v in init.items():
            np.testing.assert_array_equal(
                np.asarray(net.agg_state[k]), v, err_msg=k
            )

    def test_scrubbed_attack_reads_as_rejection(self):
        # An attack amplified to non-finite gets sentinel-scrubbed; the
        # scrub must land in the attacker's loop as a rejection (bracket
        # pins) — not silently vanish.
        cfg = _cfg(
            attack={"enabled": True, "type": "gaussian", "percentage": 0.3,
                    "params": {"noise_std": 1e38},
                    "adaptive": {"enabled": True, "scale_init": 4.0,
                                 "scale_max": 8.0}},
            faults={"enabled": True, "crash_prob": 0.0, "seed": 1},
        )
        net = build_network_from_config(cfg)
        net.train(rounds=2)
        comp = np.asarray(net.compromised) > 0
        # inf * scale overflowed -> scrubbed -> observed rejection: the
        # bracket's hi pinned at (or below) the first probed scale.
        assert (np.asarray(net.agg_state["atk_hi"])[comp] <= 4.0).all()
        assert np.asarray(net.history["agg_attack_scrubbed"]).sum() > 0

    def test_adaptive_composes_with_int8_ef(self):
        from murmura_tpu.ops.compress import COMPRESS_STATE_KEYS

        cfg = _cfg(compression={"algorithm": "int8",
                                "error_feedback": True, "block": 64})
        net = build_network_from_config(cfg)
        net.train(rounds=3)
        assert set(COMPRESS_STATE_KEYS) & set(net.agg_state)
        comp = np.asarray(net.compromised) > 0
        state0 = _build_adaptive("gaussian", 5).init_attack_state(5)
        assert not np.array_equal(
            np.asarray(net.agg_state["atk_scale"])[comp],
            state0["atk_scale"][comp.nonzero()[0]],
        )

    def test_adaptive_on_sparse_topology(self):
        cfg = _cfg(topology={"type": "exponential", "num_nodes": 8},
                   aggregation={"algorithm": "median", "params": {}})
        net = build_network_from_config(cfg)
        hist = net.train(rounds=3)
        assert np.isfinite(hist["mean_loss"]).all()
        assert set(ATTACK_STATE_KEYS) & set(net.agg_state)

    def test_gang_members_adapt_independently(self):
        raw = _raw()
        raw["sweep"] = {"members": [
            {"seed": 7, "attack_scale": 0.5},
            {"seed": 7, "attack_scale": 4.0},
        ]}
        gang = build_gang_from_config(Config.model_validate(raw))
        gang.train(rounds=3)
        comp = np.asarray(gang.compromised) > 0
        scales = np.asarray(gang.agg_state["atk_scale"])  # [S, N]
        # Adaptation state is stacked per member lane and every member's
        # attacker walked its own probe away from scale_init.
        assert scales.shape == (2, 5)
        assert (scales[0][comp] != 1.0).all()
        assert (scales[1][comp] != 1.0).all()
        for hist in gang.histories:
            assert any(k.startswith("agg_atk_") for k in hist)


# ---------------------------------------------------------------------------
# MUR1000-1003 (analysis/adaptive.py): representative cells + negatives
# ---------------------------------------------------------------------------


class TestAdaptiveContracts:
    def test_mur1000_registry_clean(self):
        assert check_attack_state_registry() == []

    def test_mur1000_fires_on_unregistered_key(self, monkeypatch):
        import murmura_tpu.durability.snapshot as dsnap

        monkeypatch.setattr(
            dsnap, "RESERVED_AGG_STATE_KEY_GROUPS",
            {k: v for k, v in dsnap.RESERVED_AGG_STATE_KEY_GROUPS.items()
             if k != "ATTACK_STATE_KEYS"},
        )
        fs = check_attack_state_registry()
        assert any("not registered" in f.message for f in fs), fs

    def test_mur1000_fires_on_orphan_reservation(self, monkeypatch):
        import murmura_tpu.attacks.adaptive as adp

        monkeypatch.setattr(
            adp, "ATTACK_STATE_KEYS", adp.ATTACK_STATE_KEYS + ("atk_ghost",)
        )
        fs = check_attack_state_registry()
        assert any("atk_ghost" in f.message for f in fs), fs

    @pytest.mark.parametrize("rule,kind", [
        ("krum", "gaussian"),
        ("balance", "alie"),
    ])
    def test_mur1001_representative_cells_clean(self, rule, kind):
        assert recompile_cell_findings(rule, kind) == []

    def test_mur1001_gang_reset_clean(self):
        assert gang_reset_findings() == []

    @pytest.mark.parametrize("rule", ["krum", "median"])
    def test_mur1002_representative_cells_clean(self, rule):
        assert collective_cell_findings(rule, "gaussian") == []

    @pytest.mark.parametrize("kind", list(ADAPTIVE_ATTACK_KINDS))
    def test_mur1003_containment_clean(self, kind):
        name = {"alie": "adaptive_alie", "ipm": "adaptive_ipm"}.get(
            kind, "bisection"
        )
        assert containment_findings(name, _build_adaptive(kind, 8)) == []

    def test_mur1003_fires_on_leaky_feedback(self):
        # Negative: an update that writes the acceptance signal across
        # rows must surface, proving the taint probe can fire.
        atk = _build_adaptive("gaussian", 8)
        leaky = dataclasses.replace(
            atk,
            update_attack_state=lambda st, accept, obs, comp: {
                **st,
                "atk_accept_ema": 0.5 * st["atk_accept_ema"]
                + 0.5 * jnp.roll(accept, 1),
            },
        )
        fs = containment_findings("leaky", leaky)
        assert fs and all(f.rule == "MUR1003" for f in fs)

    @pytest.mark.parametrize("rule", ["krum", "fedavg"])
    def test_mur1003_composed_step_clean(self, rule):
        assert adaptive_influence_findings(rule, "alie") == []

    def test_adaptive_attacks_registered(self):
        assert set(ADAPTIVE_ATTACKS) == {
            "adaptive_alie", "adaptive_ipm", "bisection"
        }

    @pytest.mark.slow
    def test_full_grid_clean(self):
        # The acceptance sweep: MUR1000-1003 clean over all nine rules.
        assert check_adaptive(force=True) == []


# ---------------------------------------------------------------------------
# The frontier driver (murmura_tpu/frontier.py)
# ---------------------------------------------------------------------------


class TestFrontierUnits:
    def test_geom_grid_floor(self):
        from murmura_tpu.frontier import _MIN_STRENGTH, _geom_grid

        g = _geom_grid(0.0, 4.0, 3)
        assert g[0] == _MIN_STRENGTH and g[-1] == 4.0 and len(g) == 3

    def test_locate_break(self):
        from murmura_tpu.frontier import _locate_break

        curve = {0.0: {"mean": 0.8}, 0.5: {"mean": 0.79},
                 1.0: {"mean": 0.6}, 2.0: {"mean": 0.1}}
        held, broken, thr = _locate_break(curve, 0.8, 0.5)
        assert held == 1.0 and broken == 2.0 and thr == 0.4

    def test_locate_break_nothing_broken(self):
        from murmura_tpu.frontier import _locate_break

        curve = {0.0: {"mean": 0.8}, 1.0: {"mean": 0.7}}
        held, broken, _ = _locate_break(curve, 0.8, 0.5)
        assert held == 1.0 and broken is None

    def test_cell_config_strips_run_side_effects(self):
        from murmura_tpu.config.schema import FrontierConfig
        from murmura_tpu.frontier import _cell_config

        cfg = _cfg(frontier={"rules": ["krum"], "attacks": ["gaussian"],
                             "topologies": ["dense"]})
        cell = _cell_config(cfg, cfg.frontier, "median", "gaussian", "dense")
        assert cell.aggregation.algorithm == "median"
        assert cell.attack.adaptive.enabled
        assert not cell.telemetry.enabled
        assert cell.frontier is None and cell.sweep is None
        # durability returns to its inert default (no dir, no resume).
        assert cell.durability.checkpoint_dir is None
        assert not cell.durability.resume

    def test_cell_config_sparse_topology(self):
        from murmura_tpu.frontier import _cell_config

        cfg = _cfg(frontier={})
        cell = _cell_config(cfg, cfg.frontier, "krum", "gaussian", "sparse")
        assert cell.topology.type == "exponential"
        assert cell.topology.num_nodes == cfg.topology.num_nodes

    def test_frontier_config_validators(self):
        with pytest.raises(Exception, match="strength_lo"):
            _cfg(frontier={"strength_lo": 4.0, "strength_hi": 1.0})
        with pytest.raises(Exception, match="duplicates"):
            _cfg(frontier={"rules": ["krum", "krum"]})
        with pytest.raises(Exception, match="non-empty"):
            _cfg(frontier={"rules": []})

    def test_unknown_rule_rejected(self):
        from murmura_tpu.frontier import run_frontier

        cfg = _cfg(frontier={"rules": ["krum", "nope"]})
        with pytest.raises(ConfigError, match="nope"):
            run_frontier(cfg)

    def test_dmtt_and_distributed_base_configs_rejected_early(self):
        # Regression: these used to surface mid-run as a raw pydantic
        # ValidationError from the per-cell adaptive-attack injection,
        # escaping the CLI's ConfigError rendering.
        from murmura_tpu.frontier import run_frontier

        base = _raw(topology={"type": "fully", "num_nodes": 5},
                    frontier={"rules": ["krum"]})
        base["attack"] = {"enabled": True, "type": "gaussian",
                         "percentage": 0.3, "params": {"noise_std": 5.0}}
        base["dmtt"] = {"allow_static": True}
        with pytest.raises(ConfigError, match="dmtt"):
            run_frontier(Config.model_validate(base))

    def test_declared_influence_payload(self):
        from murmura_tpu.frontier import declared_influence

        d = declared_influence("krum", 4)
        assert d is not None and d["kind"] == "bounded"
        assert d["bound"] is not None


class TestFrontierRun:
    def _artifact(self, tmp_path, **grid):
        from murmura_tpu.frontier import run_frontier, write_frontier

        f = {"rules": ["krum"], "attacks": ["gaussian"],
             "topologies": ["dense"], "points": 2, "stages": 2,
             "rounds": 2, "strength_lo": 0.5, "strength_hi": 4.0}
        f.update(grid)
        cfg = _cfg(experiment={"name": "frontier-test", "seed": 7,
                               "rounds": 2},
                   frontier=f)
        artifact = run_frontier(cfg)
        path = write_frontier(artifact, tmp_path / "frontier.json")
        return artifact, path

    def test_tiny_frontier_end_to_end(self, tmp_path):
        from murmura_tpu.frontier import load_frontier

        artifact, path = self._artifact(tmp_path)
        assert path.is_file()
        loaded = load_frontier(path)
        assert loaded["schema_version"] == artifact["schema_version"]
        (cell,) = loaded["cells"]
        assert cell["rule"] == "krum"
        strengths = [r["strength"] for r in cell["curve"]]
        assert strengths == sorted(strengths) and 0.0 in strengths
        assert np.isfinite(cell["benign_accuracy"])
        # <= 2 compiles per bucket: train program (+ eval) — the
        # successive-halving stages reuse the warm executables.
        assert cell["compiles"] <= 2
        assert cell["stages"] == 2
        decl = cell["declared_influence"]
        assert decl["kind"] == "bounded"
        bp = cell["breaking_point"]
        assert "last_held" in bp and "first_broken" in bp
        # Per-strength adaptive summaries rode along.
        attacked = [r for r in cell["curve"] if r["strength"] > 0]
        assert all(r["adaptive"] for r in attacked)

    def test_report_frontier_renders(self, tmp_path):
        from rich.console import Console

        from murmura_tpu.telemetry.report import render_frontier

        artifact, _ = self._artifact(tmp_path, stages=1)
        console = Console(record=True, width=200)
        render_frontier(artifact, console=console)
        text = console.export_text()
        assert "krum" in text and "declared" in text.lower()

    def test_load_rejects_non_frontier_json(self, tmp_path):
        from murmura_tpu.frontier import load_frontier

        p = tmp_path / "x.json"
        p.write_text(json.dumps({"hello": 1}))
        with pytest.raises(ValueError, match="not a frontier artifact"):
            load_frontier(p)


# ---------------------------------------------------------------------------
# Adaptive IPM: epsilon as carried state (ISSUE 13 satellite — the PR 11
# follow-up named in ROADMAP item 4's remaining list)
# ---------------------------------------------------------------------------


class TestAdaptiveIpm:
    def _attack(self, n=6, pct=0.34, **kw):
        from murmura_tpu.attacks.adaptive import make_adaptive_ipm_attack

        return make_adaptive_ipm_attack(n, pct, seed=0, **kw)

    def test_epsilon_walks_with_acceptance(self):
        atk = self._attack(epsilon=1.0, eta=0.25)
        n = 6
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        ones = jnp.ones(n)
        state = {
            k: jnp.asarray(v) for k, v in atk.init_attack_state(n).items()
        }
        ci = np.flatnonzero(atk.compromised)[0]
        state = atk.update_attack_state(state, ones, ones, comp)
        assert np.asarray(state["atk_eps"])[ci] == pytest.approx(1.25)
        state = atk.update_attack_state(state, jnp.zeros(n), ones, comp)
        assert np.asarray(state["atk_eps"])[ci] == pytest.approx(0.9375)
        # Honest rows never move.
        hi = np.flatnonzero(~(atk.compromised > 0))[0]
        assert np.asarray(state["atk_eps"])[hi] == pytest.approx(1.0)

    def test_unobserved_rows_freeze(self):
        atk = self._attack(epsilon=1.0)
        n = 6
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        state = {
            k: jnp.asarray(v) for k, v in atk.init_attack_state(n).items()
        }
        before = np.asarray(state["atk_eps"]).copy()
        state = atk.update_attack_state(
            state, jnp.ones(n), jnp.zeros(n), comp
        )
        np.testing.assert_array_equal(np.asarray(state["atk_eps"]), before)

    def test_apply_negates_honest_mean_per_row(self):
        atk = self._attack(epsilon=2.0)
        n = 6
        comp = jnp.asarray(atk.compromised.astype(np.float32))
        rng = np.random.default_rng(0)
        flat = jnp.asarray(rng.normal(size=(n, 5)).astype(np.float32))
        state = {
            k: jnp.asarray(v) for k, v in atk.init_attack_state(n).items()
        }
        out = np.asarray(
            atk.apply_adaptive(flat, comp, jax.random.PRNGKey(0), 0.0, state)
        )
        honest = np.asarray(comp) == 0
        mu = np.asarray(flat)[honest].mean(axis=0)
        for i in np.flatnonzero(atk.compromised):
            np.testing.assert_allclose(out[i], -2.0 * mu, rtol=1e-5)
        np.testing.assert_array_equal(out[honest], np.asarray(flat)[honest])

    def test_factories_wire_ipm_adaptive(self):
        from murmura_tpu.attacks.adaptive import AdaptiveAttack
        from murmura_tpu.utils.factories import build_attack

        cfg = _cfg(attack={"enabled": True, "type": "ipm",
                           "percentage": 0.3,
                           "adaptive": {"enabled": True}})
        atk = build_attack(cfg)
        assert isinstance(atk, AdaptiveAttack)
        assert atk.name == "adaptive_ipm"
        assert set(atk.state_keys) == {"atk_accept_ema", "atk_eps"}

    def test_run_escalates_against_tapless_rule(self):
        # fedavg emits no selection taps: the attacker reads constant
        # acceptance and epsilon must escalate toward its cap.
        cfg = _cfg(aggregation={"algorithm": "fedavg"},
                   attack={"enabled": True, "type": "ipm",
                           "percentage": 0.3,
                           "adaptive": {"enabled": True}})
        net = build_network_from_config(cfg)
        net.train(rounds=3)
        comp = np.asarray(net.compromised) > 0
        eps = np.asarray(net.agg_state["atk_eps"])
        from murmura_tpu.attacks.ipm import DEFAULT_EPSILON

        assert (eps[comp] > DEFAULT_EPSILON).all()

    def test_run_retreats_against_krum(self):
        cfg = _cfg(attack={"enabled": True, "type": "ipm",
                           "percentage": 0.3,
                           "adaptive": {"enabled": True}})
        net = build_network_from_config(cfg)
        net.train(rounds=4)
        comp = np.asarray(net.compromised) > 0
        eps = np.asarray(net.agg_state["atk_eps"])
        from murmura_tpu.attacks.ipm import DEFAULT_EPSILON

        # Krum rejects the negated mean outright: epsilon ducks below
        # its starting strength toward the stealth regime.
        assert (eps[comp] < DEFAULT_EPSILON).all()
        assert "agg_atk_eps" in net.history


# ---------------------------------------------------------------------------
# Frontier percentage axis (ISSUE 13 satellite — the breakdown-point axis)
# ---------------------------------------------------------------------------


class TestFrontierPercentages:
    def test_percentages_validators(self):
        with pytest.raises(Exception, match="distinct"):
            _cfg(frontier={"percentages": [0.2, 0.2]})
        with pytest.raises(Exception, match="non-empty"):
            _cfg(frontier={"percentages": []})
        with pytest.raises(Exception, match=r"\(0, 1\)"):
            _cfg(frontier={"percentages": [0.2, 1.5]})

    def test_cell_config_overrides_percentage(self):
        from murmura_tpu.frontier import _cell_config

        cfg = _cfg(frontier={"percentages": [0.2, 0.45]})
        cell = _cell_config(cfg, cfg.frontier, "krum", "gaussian", "dense",
                            percentage=0.45)
        assert cell.attack.percentage == 0.45

    def test_percentage_axis_end_to_end(self, tmp_path):
        from murmura_tpu.frontier import (
            frontier_break_summary,
            run_frontier,
        )

        cfg = _cfg(
            experiment={"name": "frontier-pct", "seed": 7, "rounds": 2},
            frontier={"rules": ["krum"], "attacks": ["gaussian"],
                      "topologies": ["dense"], "points": 2, "stages": 1,
                      "rounds": 2, "strength_lo": 0.5, "strength_hi": 4.0,
                      "percentages": [0.2, 0.45]},
        )
        artifact = run_frontier(cfg)
        cells = artifact["cells"]
        assert [c["percentage"] for c in cells] == [0.2, 0.45]
        assert artifact["grid"]["percentages"] == [0.2, 0.45]
        # Each percentage is its own bucket: both charted, both with
        # curves and declared bounds.
        for c in cells:
            assert c["curve"] and c["declared_influence"]
        rows = frontier_break_summary(artifact)
        assert [r["percentage"] for r in rows] == [0.2, 0.45]

    def test_render_includes_percentage_column(self, tmp_path):
        from rich.console import Console

        from murmura_tpu.telemetry.report import render_frontier

        # A minimal synthetic artifact exercises the renderer without a
        # training run; an old-schema cell (no percentage) renders "-".
        artifact = {
            "experiment": "x", "grid": {},
            "cells": [{
                "rule": "krum", "attack": "gaussian", "topology": "dense",
                "percentage": 0.45, "degree": 4, "benign_accuracy": 0.9,
                "curve": [], "breaking_point": {}, "stages": 1,
                "compiles": 1,
                "declared_influence": {"kind": "bounded", "bound": 1,
                                       "describe": "bounded"},
            }, {
                "rule": "median", "attack": "gaussian",
                "topology": "dense", "degree": 4, "benign_accuracy": 0.9,
                "curve": [], "breaking_point": {}, "stages": 1,
                "compiles": 1, "declared_influence": None,
            }],
        }
        console = Console(record=True, width=220)
        render_frontier(artifact, console=console)
        text = console.export_text()
        assert "0.45" in text and "pct" in text

"""CLI surface via click's test runner (reference: murmura/cli.py:34-308)."""

import json
from pathlib import Path

import yaml
from click.testing import CliRunner

from murmura_tpu.cli import app


def _write_cfg(tmp_path, **overrides):
    cfg = {
        "experiment": {"name": "cli-test", "seed": 3, "rounds": 2},
        "topology": {"type": "ring", "num_nodes": 4},
        "aggregation": {"algorithm": "fedavg", "params": {}},
        "training": {"local_epochs": 1, "batch_size": 16, "lr": 0.1},
        "data": {"adapter": "synthetic",
                 "params": {"num_samples": 200, "input_dim": 8,
                            "num_classes": 3}},
        "model": {"factory": "mlp",
                  "params": {"input_dim": 8, "hidden_dims": [16],
                             "num_classes": 3}},
        "backend": "simulation",
    }
    cfg.update(overrides)
    p = tmp_path / "cfg.yaml"
    p.write_text(yaml.safe_dump(cfg))
    return p


def test_run_writes_history_json(tmp_path):
    cfg = _write_cfg(tmp_path)
    out = tmp_path / "hist.json"
    result = CliRunner().invoke(app, ["run", str(cfg), "-o", str(out)])
    assert result.exit_code == 0, result.output
    hist = json.loads(out.read_text())
    # Reference history schema (murmura/core/network.py:47-58).
    for key in ("round", "mean_accuracy", "std_accuracy", "mean_loss"):
        assert key in hist
    assert hist["round"] == [1, 2]


def test_run_fused_dispatch_from_config(tmp_path):
    cfg = _write_cfg(tmp_path, tpu={"rounds_per_dispatch": 2})
    out = tmp_path / "hist.json"
    result = CliRunner().invoke(app, ["run", str(cfg), "-o", str(out)])
    assert result.exit_code == 0, result.output
    hist = json.loads(out.read_text())
    assert hist["round"] == [1, 2]


def test_run_renders_wiring_error_cleanly(tmp_path):
    # data 8-dim vs model 16-dim: ConfigError message, no traceback.
    cfg = _write_cfg(
        tmp_path,
        model={"factory": "mlp",
                "params": {"input_dim": 16, "hidden_dims": [16],
                           "num_classes": 3}},
    )
    result = CliRunner().invoke(app, ["run", str(cfg)])
    assert result.exit_code == 1
    assert "data/model mismatch" in result.output
    assert "Traceback" not in result.output


def test_run_renders_parse_error_cleanly(tmp_path):
    p = tmp_path / "broken.yaml"
    p.write_text("experiment: {name: x\n  nope")
    result = CliRunner().invoke(app, ["run", str(p)])
    assert result.exit_code == 1
    assert "Cannot parse config" in result.output
    assert "Traceback" not in result.output


def test_run_resume_requires_checkpoint_dir(tmp_path):
    cfg = _write_cfg(tmp_path)
    result = CliRunner().invoke(app, ["run", str(cfg), "--resume"])
    assert result.exit_code != 0
    assert "--checkpoint-dir" in result.output


def test_list_components():
    result = CliRunner().invoke(app, ["list-components"])
    assert result.exit_code == 0
    for frag in ("fedavg", "krum", "evidential_trust", "gaussian",
                 "simulation", "ring"):
        assert frag in result.output


def test_check_flags_seeded_violation(tmp_path):
    """`murmura check <file>`: non-zero exit + greppable finding lines on a
    file seeding a traced-branch and a host-sync violation."""
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import jax\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x > 0:\n"
        "        return float(x)\n"
        "    return x\n"
    )
    result = CliRunner().invoke(app, ["check", str(bad), "--no-contracts"])
    assert result.exit_code == 1
    assert "MUR001" in result.output
    assert "MUR003" in result.output
    assert f"{bad}:5:" in result.output  # path:line: greppable format


def test_check_clean_file_exits_zero(tmp_path):
    good = tmp_path / "good.py"
    good.write_text(
        "import jax\n\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x * 2\n"
    )
    result = CliRunner().invoke(app, ["check", str(good), "--no-contracts"])
    assert result.exit_code == 0
    assert "clean" in result.output


def test_check_package_is_clean():
    """The committed package must pass its own analyzer (with contracts) —
    the same gate run_tpu_battery.sh uses as a pre-flight."""
    import murmura_tpu

    pkg = str(Path(murmura_tpu.__file__).resolve().parent)
    result = CliRunner().invoke(app, ["check", pkg])
    assert result.exit_code == 0, result.output


def test_run_with_telemetry_then_report_smoke(tmp_path):
    """Tier-1 `murmura report` smoke (ISSUE 4 satellite): a telemetry run
    renders end-to-end, and --json exposes the same report dict."""
    run_dir = tmp_path / "run"
    cfg = _write_cfg(
        tmp_path,
        aggregation={"algorithm": "krum", "params": {"num_compromised": 1}},
        telemetry={"enabled": True, "dir": str(run_dir), "audit_taps": True},
    )
    result = CliRunner().invoke(app, ["run", str(cfg)])
    assert result.exit_code == 0, result.output
    assert "Telemetry run written" in result.output

    rendered = CliRunner().invoke(app, ["report", str(run_dir)])
    assert rendered.exit_code == 0, rendered.output
    assert "murmura report" in rendered.output
    assert "Accuracy" in rendered.output

    as_json = CliRunner().invoke(app, ["report", str(run_dir), "--json"])
    assert as_json.exit_code == 0, as_json.output
    rep = json.loads(as_json.output)
    assert rep["accuracy"]["rounds_recorded"] == 2
    assert len(rep["taps"]["selected_by"]) == 4
    assert rep["time"]["by_mode"]["per_round"]["rounds"] == 2


def test_report_rejects_non_run_dir(tmp_path):
    result = CliRunner().invoke(app, ["report", str(tmp_path)])
    assert result.exit_code == 1
    assert "manifest" in result.output


def test_run_profile_flag_rejected_on_distributed(tmp_path):
    cfg = _write_cfg(tmp_path, backend="distributed")
    result = CliRunner().invoke(app, ["run", str(cfg), "--profile"])
    assert result.exit_code != 0
    assert "--profile" in result.output


def test_frontier_cli_writes_artifact_and_report_renders(tmp_path):
    # The `murmura frontier` -> `murmura report --frontier` round trip on
    # a single tiny cell (docs/ROBUSTNESS.md "The robustness frontier").
    cfg = _write_cfg(
        tmp_path,
        aggregation={"algorithm": "krum", "params": {"num_compromised": 1}},
        attack={"enabled": True, "type": "gaussian", "percentage": 0.3,
                "params": {"noise_std": 5.0}},
        frontier={"rules": ["krum"], "attacks": ["gaussian"],
                  "topologies": ["dense"], "points": 2, "stages": 1,
                  "rounds": 2, "strength_lo": 0.5, "strength_hi": 4.0},
    )
    out = tmp_path / "frontier.json"
    result = CliRunner().invoke(app, ["frontier", str(cfg), "-o", str(out)])
    assert result.exit_code == 0, result.output
    artifact = json.loads(out.read_text())
    (cell,) = artifact["cells"]
    assert cell["rule"] == "krum" and cell["compiles"] <= 2
    rendered = CliRunner().invoke(app, ["report", "--frontier", str(out)])
    assert rendered.exit_code == 0, rendered.output
    assert "krum" in rendered.output
    as_json = CliRunner().invoke(
        app, ["report", "--frontier", str(out), "--json"]
    )
    assert as_json.exit_code == 0
    assert json.loads(as_json.output)["summary"][0]["rule"] == "krum"


def test_report_without_run_dir_or_frontier_errors():
    result = CliRunner().invoke(app, ["report"])
    assert result.exit_code == 1
    assert "RUN_DIR" in result.output


def test_frontier_cli_renders_unknown_rule_cleanly(tmp_path):
    cfg = _write_cfg(
        tmp_path, frontier={"rules": ["krum", "nope"]},
    )
    result = CliRunner().invoke(app, ["frontier", str(cfg)])
    assert result.exit_code == 1
    assert "Config error" in result.output and "nope" in result.output

"""DMTT on the ZMQ distributed backend (reference: murmura/dmtt/node_process.py).

Unit tests drive the trust bookkeeping directly (no sockets); the slow test
spawns the full multi-process DMTT run over IPC with mobility + topology
liars, mirroring experiments/paper/dmtt/03_dmtt.yaml.
"""

import time

import numpy as np
import pytest

from murmura_tpu.config import Config


def _dmtt_cfg(tmp_path, num_nodes=4, rounds=2, mobility=True, attack=False):
    cfg = {
        "experiment": {"name": "dmtt-test", "seed": 42, "rounds": rounds},
        "topology": {"type": "ring", "num_nodes": num_nodes},
        "aggregation": {"algorithm": "fedavg"},
        "training": {"local_epochs": 1, "batch_size": 16, "lr": 0.05},
        "data": {
            "adapter": "synthetic",
            "params": {"num_samples": 80 * num_nodes, "input_dim": 16,
                        "num_classes": 4},
        },
        "model": {
            "factory": "mlp",
            "params": {"input_dim": 16, "num_classes": 4, "hidden_dims": [16]},
        },
        "backend": "distributed",
        "dmtt": {"budget_B": 2},
        "distributed": {
            "transport": "ipc",
            "ipc_dir": str(tmp_path),
            "round_duration_s": 45.0,  # generous: suite may share cores with heavy jobs
            "startup_grace_s": 60.0,
        },
    }
    if mobility:
        cfg["mobility"] = {"area_size": 50.0, "comm_range": 30.0,
                            "max_speed": 5.0, "seed": 7}
    else:
        # dmtt without mobility must be opted into explicitly (schema
        # validator); claims verify against the static topology.
        cfg["dmtt"]["allow_static"] = True
    if attack:
        cfg["attack"] = {"enabled": True, "type": "topology_liar",
                          "percentage": 0.25, "params": {}}
    return Config.model_validate(cfg)


def _make_process(tmp_path, **kw):
    from murmura_tpu.dmtt.node_process import DMTTNodeProcess

    cfg = _dmtt_cfg(tmp_path, **kw)
    return DMTTNodeProcess(
        cfg, node_id=0, run_id="t", t_start=time.monotonic(),
        compromised_ids=kw.get("compromised_ids", []),
    )


class TestTrustBookkeeping:
    def test_honest_claim_is_true_neighbors(self, tmp_path):
        proc = _make_process(tmp_path, mobility=False)
        assert proc._make_claim([1, 3]) == [1, 3]

    def test_liar_claims_coalition(self, tmp_path):
        from murmura_tpu.dmtt.node_process import DMTTNodeProcess
        from murmura_tpu.utils.factories import build_attack

        cfg = _dmtt_cfg(tmp_path, mobility=False, attack=True)
        attack = build_attack(cfg)
        comp = sorted(attack.get_compromised_nodes())
        proc = DMTTNodeProcess(
            cfg, node_id=comp[0], run_id="t", t_start=time.monotonic(),
            compromised_ids=comp,
        )
        proc.attack = attack
        claim = proc._make_claim([1])
        # claim = true neighbors UNION other Byzantine nodes
        assert set(claim) >= (set(comp) - {comp[0]}) | {1}

    def test_claim_verification_beta_update(self, tmp_path):
        proc = _make_process(tmp_path, mobility=False)
        # ring(4): node 1's true neighbors are {0, 2}
        proc._verify_claims({1: [0, 2]}, round_idx=0)
        p = proc.dmtt
        # all-confirmed claim: alpha grows, beta decays
        assert proc._alpha[1] == pytest.approx(p.lambda_forget * 1.0 + p.w_d * 2)
        assert proc._beta[1] == pytest.approx(p.lambda_forget * 1.0)

        proc._verify_claims({2: [0, 1, 3]}, round_idx=0)
        # node 2's true neighbors are {1, 3}: one contradiction (0)
        assert proc._alpha[2] == pytest.approx(p.lambda_forget * 1.0 + p.w_d * 2)
        assert proc._beta[2] == pytest.approx(p.lambda_forget * 1.0 + p.w_x * 1)

    def test_link_reliability_and_topb(self, tmp_path):
        proc = _make_process(tmp_path, mobility=False)
        # liar 3 racked up contradictions; 1 and 2 are clean
        for _ in range(5):
            proc._verify_claims({3: [0, 1, 2], 1: [0, 2], 2: [1, 3]}, 0)
        proc._c_hat = {1: 1.0, 2: 1.0, 3: 1.0}
        proc._select_collaborators([1, 2, 3], scores={})
        assert proc._collaborators is not None
        assert len(proc._collaborators) == proc.dmtt.budget_B
        assert 3 not in proc._collaborators  # the liar loses TopB

    def test_collaborators_default_to_graph(self, tmp_path):
        proc = _make_process(tmp_path, mobility=False)
        proc.static_neighbors = [1, 3]
        assert proc.current_collaborators(0) == [1, 3]
        proc._collaborators = [1]
        assert proc.current_collaborators(0) == [1]

    def test_mobility_ground_truth_matches_model(self, tmp_path):
        proc = _make_process(tmp_path, mobility=True)
        from murmura_tpu.utils.factories import build_mobility

        proc.mobility = build_mobility(proc.config)
        reference = build_mobility(proc.config)
        truth = reference.neighbors_at(3)
        claimer = 2
        proc._verify_claims({claimer: truth[claimer]}, round_idx=3)
        # perfectly honest claim against the recomputed G^3: zero contradictions
        p = proc.dmtt
        assert proc._beta[claimer] == pytest.approx(p.lambda_forget * 1.0)


@pytest.mark.slow
class TestDMTTFullStack:
    def test_dmtt_ipc_run_with_liars(self, tmp_path):
        """Full DMTT multi-process run: mobility + topology liars
        (reference: experiments/paper/dmtt/03_dmtt.yaml)."""
        from murmura_tpu.distributed.runner import DistributedRunner

        cfg = _dmtt_cfg(tmp_path, num_nodes=4, rounds=2, mobility=True,
                         attack=True)
        t0 = time.monotonic()
        history = DistributedRunner(cfg).run()
        assert history["round"] == [1, 2], history
        assert np.isfinite(history["mean_accuracy"][-1])
        assert time.monotonic() - t0 < 200

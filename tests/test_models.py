"""Model registry: forward shapes, param counts, evidential outputs
(reference models: murmura/examples/leaf/{datasets,models}.py,
murmura/examples/wearables/models.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from murmura_tpu.models.registry import build_model
from murmura_tpu.ops.flatten import model_dimension


def _param_count(model):
    return model_dimension(jax.eval_shape(model.init, jax.random.PRNGKey(0)))


def test_model_dimension_counts_only_float_leaves():
    """ISSUE 2 satellite regression: model_dimension's documented contract
    is the *float* parameter count (only float parameters are aggregated —
    the reference skips BatchNorm's integer num_batches_tracked buffers),
    so an integer leaf in an externally supplied pytree must not inflate
    the sketch sizing / model_dim plumbing."""
    tree = {
        "w": np.zeros((4, 5), np.float32),          # 20
        "b": jnp.zeros((5,), jnp.bfloat16),         # 5
        "steps": np.zeros((3,), np.int32),          # int buffer: excluded
        "flag": jnp.zeros((2, 2), jnp.bool_),       # bool buffer: excluded
    }
    assert model_dimension(tree) == 25
    # eval_shape structs carry dtypes too — same filtering applies.
    structs = jax.tree_util.tree_map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype), tree
    )
    assert model_dimension(structs) == 25


def test_make_flatteners_rejects_non_float_leaves():
    """The counterpart contract: the [N, P] aggregation pipeline is
    float-only, so a mixed tree must fail loudly at build time (where the
    message can point at the design note) instead of desynchronizing
    model_dimension consumers (sketch table sizing) from the ravelled
    vector, or 'aggregating' integer buffers by means."""
    from murmura_tpu.ops.flatten import make_flatteners

    tree = {"w": np.zeros((4, 5), np.float32), "steps": np.zeros((3,), np.int32)}
    with pytest.raises(TypeError, match="non-float leaves"):
        make_flatteners(tree)
    # Raw Python float leaves stay supported (ravel_pytree accepts them).
    ravel, _, dim = make_flatteners({"w": np.zeros((2,), np.float32), "s": 1.0})
    assert dim == 3
    assert model_dimension({"w": np.zeros((2,), np.float32), "s": 1.0}) == 3


def _forward(model, batch=3):
    params = model.init(jax.random.PRNGKey(0))
    x_shape = (batch,) + tuple(model.input_shape)
    if model.input_shape and model.meta.get("discrete_input"):
        x = jnp.zeros(x_shape, jnp.int32)
    else:
        x = jnp.zeros(x_shape, jnp.float32)
    return model.apply(params, x, jax.random.PRNGKey(1), False)


@pytest.mark.parametrize("factory,params,classes", [
    ("mlp", {"input_dim": 16, "num_classes": 5}, 5),
    ("examples.leaf.LEAFFEMNISTModel", {}, 62),
    ("leaf.femnist.tiny", {}, 62),
    ("leaf.celeba", {}, 2),
    ("examples.wearables.uci_har", {}, 6),
    ("examples.wearables.pamap2", {}, 12),
    ("examples.wearables.ppg_dalia", {}, 7),
])
def test_forward_shape(factory, params, classes):
    model = build_model(factory, params)
    out = _forward(model)
    assert out.shape == (3, classes)
    assert np.isfinite(np.asarray(out)).all()


def test_femnist_variant_scaling():
    # Reference scaling family: Tiny ~200K ... Baseline ~6.5M ... XLarge ~26M
    # (murmura/examples/leaf/models.py:12-216).
    counts = {
        v: _param_count(build_model(f"leaf.femnist.{v}", {}))
        for v in ("tiny", "small", "baseline", "large", "xlarge")
    }
    assert counts["tiny"] < counts["small"] < counts["baseline"] \
        < counts["large"] < counts["xlarge"]
    assert 3e6 < counts["baseline"] < 10e6   # ~6.5M in the reference
    assert counts["xlarge"] > 20e6           # ~26M


def test_wearable_models_are_evidential():
    # Wearable classifiers carry the evidential head: outputs are Dirichlet
    # alphas, all >= 1 (reference: wearables/models.py:18-46, alpha = e + 1).
    model = build_model("examples.wearables.uci_har", {})
    assert model.evidential
    out = _forward(model)
    assert (np.asarray(out) >= 1.0).all()


def test_shakespeare_lstm_forward():
    model = build_model("leaf.shakespeare", {})
    params = model.init(jax.random.PRNGKey(0))
    x = jnp.zeros((3, 80), jnp.int32)
    out = model.apply(params, x, None, False)
    assert out.shape == (3, 81)


def test_dropout_only_active_in_train_mode():
    model = build_model("mlp", {"input_dim": 8, "num_classes": 3,
                                "dropout": 0.5})
    params = model.init(jax.random.PRNGKey(0))
    x = jnp.ones((4, 8), jnp.float32)
    eval_a = model.apply(params, x, jax.random.PRNGKey(1), False)
    eval_b = model.apply(params, x, jax.random.PRNGKey(2), False)
    np.testing.assert_array_equal(np.asarray(eval_a), np.asarray(eval_b))
    train_a = model.apply(params, x, jax.random.PRNGKey(1), True)
    train_b = model.apply(params, x, jax.random.PRNGKey(2), True)
    assert not np.allclose(np.asarray(train_a), np.asarray(train_b))


def test_conv2d_im2col_matches_direct():
    """The im2col lowering (patch GEMM — the bench_sgd_micro local-SGD
    lever) must be numerically equivalent to lax.conv with the SAME HWIO
    parameters; this also pins conv_general_dilated_patches' channel-major
    feature order that the weight transpose in models/core.py relies on."""
    import jax
    import numpy as np

    from murmura_tpu.models.core import conv2d, conv_init

    key = jax.random.PRNGKey(0)
    p = conv_init(key, 5, 5, 3, 8)
    x = jax.random.normal(jax.random.PRNGKey(1), (4, 12, 12, 3))
    direct = conv2d(p, x)
    gemm = conv2d(p, x, impl="im2col")
    np.testing.assert_allclose(
        np.asarray(direct), np.asarray(gemm), rtol=1e-5, atol=1e-5
    )


def test_femnist_conv_impl_flag_equivalent_and_checkpoint_compatible():
    """conv_impl='im2col' on the FEMNIST CNN: identical init tree (same
    HWIO params — checkpoints interchangeable) and matching logits."""
    import jax
    import numpy as np

    from murmura_tpu.models.cnn import make_femnist_cnn

    direct = make_femnist_cnn(variant="tiny")
    gemm = make_femnist_cnn(variant="tiny", conv_impl="im2col")
    params = direct.init(jax.random.PRNGKey(3))
    x = jax.random.normal(jax.random.PRNGKey(4), (2, 28, 28, 1))
    np.testing.assert_allclose(
        np.asarray(direct.apply(params, x)),
        np.asarray(gemm.apply(params, x)),
        rtol=1e-4, atol=1e-4,
    )

"""FederatedArrays stacking, masks, and the per-node batch rule
(reference: murmura/core/network.py:275-294)."""

import numpy as np

from murmura_tpu.data.base import stack_partitions


def test_stack_partitions_pads_and_masks():
    x = np.arange(20, dtype=np.float32).reshape(10, 2)
    y = np.arange(10, dtype=np.int32) % 3
    parts = [[0, 1, 2, 3, 4], [5, 6], [7, 8, 9]]
    fa = stack_partitions(x, y, parts, num_classes=3)

    assert fa.x.shape == (3, 5, 2)
    assert fa.num_samples.tolist() == [5, 2, 3]
    np.testing.assert_array_equal(fa.mask.sum(axis=1), [5, 2, 3])
    # Padding region must be masked out and real rows preserved in order.
    np.testing.assert_array_equal(fa.x[1, :2], x[[5, 6]])
    assert fa.mask[1, 2:].sum() == 0


def test_max_samples_truncation():
    # max_samples truncation exists "for quick tests"
    # (reference: examples/leaf/adapter.py:12-16, schema.py:147-150).
    x = np.zeros((30, 4), np.float32)
    y = np.zeros(30, np.int32)
    parts = [list(range(15)), list(range(15, 30))]
    fa = stack_partitions(x, y, parts, max_samples=6, num_classes=1)
    assert fa.x.shape[1] == 6
    assert fa.num_samples.tolist() == [6, 6]


def test_effective_batch_rule():
    # Reference rule: min(batch, max(2, n_samples)) with drop_last
    # (network.py:278-287).
    x = np.zeros((10, 1), np.float32)
    y = np.zeros(10, np.int32)
    parts = [[0], [1, 2, 3], list(range(4, 10))]
    fa = stack_partitions(x, y, parts, num_classes=1)
    eff = fa.effective_batch(4)
    assert eff.tolist() == [2, 3, 4]  # node 0 clamps up to 2
    steps = fa.steps_per_epoch(4)
    # drop_last semantics: node 0 has 1 sample < batch 2 -> at least 1 step
    # is still granted only when a full batch exists; check monotonicity.
    assert (steps >= 0).all() and steps[2] >= steps[1]

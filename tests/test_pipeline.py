"""Pipelined rounds (murmura_tpu/core/pipeline.py; ISSUE 14).

Covers the acceptance surface of docs/PERFORMANCE.md "Pipelined rounds":

- default-off byte-identity: a config without an ``exchange`` block and
  one with ``pipeline: false`` produce byte-identical traced programs
  AND histories;
- schema fail-louds (the distributed/dmtt/population/adaptive
  rejections) and the rounds.py-level guards;
- the delayed-averaging reference: a pipelined run is BIT-IDENTICAL on
  CPU to core/pipeline.run_delayed_reference driving the serialized
  program through the explicit one-round-delayed recursion — plain,
  faulted, int8+EF, staleness-composed (buffer reuse) and
  sparse-exponential cells;
- chunk-boundary warm-up/drain: fused == per-round with eval_every
  mid-chunk, a dispatch split at an arbitrary round boundary, and
  SIGKILL-equivalent save/restore at a buffer-populated boundary
  resuming byte-identically;
- gang-member parity with pipeline on;
- phase_times critical-path accounting: pipelined runs emit the
  ``overlap`` marker and the report renders a critical_path section;
  serialized-mode phase_times events and report output are pinned
  UNCHANGED (no marker, no section);
- MUR1200-1203 representative cells clean + negatives proving each
  probe can fire (broken registry, a combine that leaks the
  lagging-verdict hole).
"""

import re

import numpy as np
import pytest

from murmura_tpu.config import Config
from murmura_tpu.core.pipeline import (
    ADJ_KEY,
    BCAST_KEY,
    OWN_KEY,
    PIPELINE_STATE_KEYS,
    VALID_KEY,
    init_pipeline_state,
    pipeline_state_keys,
    run_delayed_reference,
)
from murmura_tpu.utils.factories import build_network_from_config


def _raw(**over):
    raw = {
        "experiment": {"name": "pipe", "seed": 3, "rounds": 8},
        "topology": {"type": "k-regular", "num_nodes": 8, "k": 4},
        "aggregation": {"algorithm": "krum"},
        "training": {"local_epochs": 1, "batch_size": 16, "lr": 0.05},
        "data": {
            "adapter": "synthetic",
            "params": {"num_samples": 320, "input_dim": 16,
                       "num_classes": 4},
        },
        "model": {
            "factory": "mlp",
            "params": {"input_dim": 16, "hidden_dims": [16],
                       "num_classes": 4},
        },
        "backend": "simulation",
    }
    for k, v in over.items():
        raw[k] = v
    return raw


def _cfg(**over):
    return Config.model_validate(_raw(**over))


FAULTS = {"enabled": True, "straggler_prob": 0.4, "link_drop_prob": 0.2,
          "seed": 11}

# jvp_jaxpr_thunk reprs embed function addresses that differ between any
# two builds; scrub them so equality is structural (the address is not
# part of the traced program).
_ADDR = re.compile(r"0x[0-9a-f]+")


def _jaxpr_of(cfg):
    import jax
    import jax.numpy as jnp

    net = build_network_from_config(cfg)
    prog = net.program
    n = prog.num_nodes
    args = [
        prog.init_params,
        {k: jnp.asarray(v) for k, v in prog.init_agg_state.items()},
        jax.random.PRNGKey(0),
        jnp.asarray(net.topology.mask()),
        jnp.zeros((n,), jnp.float32),
    ]
    if prog.faulted:
        args.append(jnp.ones((n,), jnp.float32))
    args += [
        jnp.asarray(0.0, jnp.float32),
        {k: jnp.asarray(v) for k, v in prog.data_arrays.items()},
    ]
    return _ADDR.sub("0xX", str(jax.make_jaxpr(prog.train_step)(*args)))


def _leaves(params):
    import jax

    return [np.asarray(x) for x in jax.tree_util.tree_leaves(params)]


def _params_equal(a, b) -> bool:
    return all(
        np.array_equal(x, y, equal_nan=True)
        for x, y in zip(_leaves(a), _leaves(b))
    )


# ---------------------------------------------------------------------------
# Default-off byte-identity
# ---------------------------------------------------------------------------


class TestDefaultOffByteIdentity:
    def test_history_identical_without_and_with_default_block(self):
        h1 = build_network_from_config(_cfg()).train(rounds=4)
        h2 = build_network_from_config(
            _cfg(exchange={"pipeline": False})
        ).train(rounds=4)
        assert h1 == h2

    def test_traced_program_identical(self):
        assert _jaxpr_of(_cfg()) == _jaxpr_of(
            _cfg(exchange={"pipeline": False})
        )

    def test_pipelined_program_differs(self):
        # Sanity for the identity above: the pipeline flag must actually
        # change the traced program (warm-up gate, delayed aggregation).
        assert _jaxpr_of(_cfg()) != _jaxpr_of(
            _cfg(exchange={"pipeline": True})
        )


# ---------------------------------------------------------------------------
# Schema / build fail-louds
# ---------------------------------------------------------------------------


class TestPipelineConfig:
    def test_distributed_rejected(self):
        with pytest.raises(ValueError, match="distributed"):
            _cfg(exchange={"pipeline": True}, backend="distributed")

    def test_dmtt_rejected(self):
        with pytest.raises(ValueError, match="dmtt"):
            _cfg(
                exchange={"pipeline": True},
                mobility={"area_size": 100.0, "comm_range": 60.0,
                          "max_speed": 5.0},
                dmtt={},
            )

    def test_adaptive_rejected(self):
        with pytest.raises(ValueError, match="adaptive"):
            _cfg(
                exchange={"pipeline": True},
                attack={"enabled": True, "type": "gaussian",
                        "percentage": 0.25,
                        "adaptive": {"enabled": True}},
            )

    def test_population_rejected(self):
        with pytest.raises(ValueError, match="population"):
            _cfg(
                exchange={"pipeline": True},
                population={"enabled": True, "virtual_size": 64},
            )

    def test_composes_with_staleness(self):
        cfg = _cfg(
            exchange={"pipeline": True, "max_staleness": 2},
            faults=FAULTS,
        )
        assert cfg.exchange.pipeline and cfg.exchange.max_staleness == 2

    def test_build_rejects_dmtt_directly(self):
        # The rounds.py-level guard (direct library use bypasses pydantic).
        from murmura_tpu.core.rounds import build_round_program

        with pytest.raises(ValueError, match="DMTT"):
            from murmura_tpu.aggregation import build_aggregator
            from murmura_tpu.data.registry import build_federated_data
            from murmura_tpu.dmtt.protocol import DMTTParams
            from murmura_tpu.models import make_mlp

            data = build_federated_data(
                "synthetic",
                {"num_samples": 64, "input_dim": 8, "num_classes": 3},
                num_nodes=4, seed=0,
            )
            build_round_program(
                make_mlp(input_dim=8, hidden_dims=(8,), num_classes=3),
                build_aggregator("fedavg", {}),
                data,
                dmtt=DMTTParams(),
                pipeline=True,
            )


# ---------------------------------------------------------------------------
# Pipeline state init
# ---------------------------------------------------------------------------


class TestPipelineState:
    def test_keys_and_shapes(self):
        init = init_pipeline_state(6, 10, np.float32)
        assert set(init) == set(PIPELINE_STATE_KEYS)
        assert init[OWN_KEY].shape == (6, 10)
        assert init[BCAST_KEY].shape == (6, 10)
        assert init[ADJ_KEY].shape == (6, 6)
        assert not np.diagonal(init[ADJ_KEY]).any()
        assert init[VALID_KEY].shape == () and init[VALID_KEY] == 0.0

    def test_sparse_adj_is_node_leading(self):
        init = init_pipeline_state(
            8, 10, np.float32, sparse_offsets=(1, 2, 4)
        )
        assert init[ADJ_KEY].shape == (8, 3)

    def test_stale_reuse_drops_bcast(self):
        init = init_pipeline_state(6, 10, np.float32, stale=True)
        assert BCAST_KEY not in init
        assert set(init) == set(pipeline_state_keys(stale=True))


# ---------------------------------------------------------------------------
# Bit-parity with the explicit one-round-delayed averaging reference
# ---------------------------------------------------------------------------


def _parity(pipeline_over, serial_over, rounds=6):
    net_p = build_network_from_config(_cfg(**pipeline_over))
    h = net_p.train(rounds=rounds)
    net_s = build_network_from_config(_cfg(**serial_over))
    ref_params, ref_hist = run_delayed_reference(net_s, rounds=rounds)
    assert _params_equal(net_p.params, ref_params)
    assert h["mean_accuracy"] == ref_hist["mean_accuracy"]
    return h


class TestDelayedReferenceParity:
    def test_plain_krum(self):
        h = _parity({"exchange": {"pipeline": True}}, {})
        # The warm-up round reports an invalid buffer, every later
        # round a valid one.
        assert h["agg_pipe_valid"][0] == 0.0
        assert all(v == 1.0 for v in h["agg_pipe_valid"][1:])

    def test_faulted_fedavg(self):
        _parity(
            {"exchange": {"pipeline": True}, "faults": FAULTS,
             "aggregation": {"algorithm": "fedavg"}},
            {"faults": FAULTS, "aggregation": {"algorithm": "fedavg"}},
        )

    def test_int8_ef_median_under_attack(self):
        comp = {"algorithm": "int8", "error_feedback": True, "block": 32}
        atk = {"enabled": True, "type": "gaussian", "percentage": 0.25,
               "params": {"noise_std": 5.0}}
        _parity(
            {"exchange": {"pipeline": True}, "compression": comp,
             "attack": atk, "aggregation": {"algorithm": "median"}},
            {"compression": comp, "attack": atk,
             "aggregation": {"algorithm": "median"}},
        )

    def test_staleness_composition_buffer_reuse(self):
        ex = {"max_staleness": 2, "staleness_discount": 0.5}
        h = _parity(
            {"exchange": {**ex, "pipeline": True}, "faults": FAULTS},
            {"exchange": ex, "faults": FAULTS},
        )
        # Buffer reuse: the stale cache IS the broadcast buffer — the
        # pipelined run must not carry a duplicate.
        net = build_network_from_config(
            _cfg(exchange={**ex, "pipeline": True}, faults=FAULTS)
        )
        assert BCAST_KEY not in net.program.init_agg_state
        assert OWN_KEY in net.program.init_agg_state
        assert any(v > 0 for v in h.get("agg_stale_used", []))

    def test_sparse_exponential_ubar(self):
        topo = {"type": "exponential", "num_nodes": 8}
        agg = {"algorithm": "ubar", "params": {"rho": 0.5}}
        _parity(
            {"exchange": {"pipeline": True}, "topology": topo,
             "aggregation": agg},
            {"topology": topo, "aggregation": agg},
        )

    @pytest.mark.slow
    def test_evidential_trust_carried_state(self):
        # evidential_trust carries trust state across rounds — the
        # warm-up where-gate must keep the round-0 placeholder
        # aggregation out of it or parity breaks on round 1.
        agg = {"algorithm": "evidential_trust",
               "params": {"max_eval_samples": 32}}
        model = {"factory": "mlp",
                 "params": {"input_dim": 16, "hidden_dims": [16],
                            "num_classes": 4, "evidential": True}}
        _parity(
            {"exchange": {"pipeline": True}, "aggregation": agg,
             "model": model},
            {"aggregation": agg, "model": model},
        )


# ---------------------------------------------------------------------------
# Chunk boundaries: fused dispatch, eval_every mid-chunk, resume
# ---------------------------------------------------------------------------


class TestChunkBoundaries:
    def test_fused_matches_per_round_with_midchunk_eval(self):
        # eval_every=3 with chunk=4: eval rounds land mid-chunk and at
        # chunk edges across the run; the pipeline carry must make the
        # fused program byte-equal to per-round dispatch.
        n1 = build_network_from_config(
            _cfg(exchange={"pipeline": True}, faults=FAULTS)
        )
        h1 = n1.train(rounds=8, eval_every=3)
        n2 = build_network_from_config(
            _cfg(exchange={"pipeline": True}, faults=FAULTS)
        )
        h2 = n2.train(rounds=8, eval_every=3, rounds_per_dispatch=4)
        assert h1 == h2
        assert _params_equal(n1.params, n2.params)

    def test_dispatch_split_at_buffer_populated_boundary(self):
        # 3 + 5 rounds across two train() calls (buffer populated at the
        # split) == 8 straight.
        n1 = build_network_from_config(
            _cfg(exchange={"pipeline": True}, faults=FAULTS)
        )
        h1 = n1.train(rounds=8, eval_every=3)
        n3 = build_network_from_config(
            _cfg(exchange={"pipeline": True}, faults=FAULTS)
        )
        n3.train(rounds=3, eval_every=3, rounds_per_dispatch=2)
        n3.train(rounds=5, eval_every=3, rounds_per_dispatch=2)
        assert _params_equal(n1.params, n3.params)
        assert n3.history == h1

    def test_sigkill_equivalent_resume_byte_identical(self, tmp_path):
        # Save at a buffer-populated round boundary, continue; restore
        # into the warm program and replay — byte-identical (the crash
        # matrix discipline of tests/test_durability.py applied to the
        # pipeline buffer).
        net = build_network_from_config(
            _cfg(exchange={"pipeline": True}, faults=FAULTS)
        )
        net.train(rounds=3)
        net.save_checkpoint(str(tmp_path))
        net.train(rounds=3)
        full_hist = {k: list(v) for k, v in net.history.items()}
        full_params = _leaves(net.params)
        full_agg = {k: np.asarray(v) for k, v in net.agg_state.items()}
        assert net.restore_checkpoint(str(tmp_path)) == 3
        net.train(rounds=3)
        assert {k: list(v) for k, v in net.history.items()} == full_hist
        assert all(
            np.array_equal(a, b, equal_nan=True)
            for a, b in zip(full_params, _leaves(net.params))
        )
        for k in full_agg:
            assert np.array_equal(
                full_agg[k], np.asarray(net.agg_state[k]), equal_nan=True
            ), k

    def test_zero_recompiles_across_buffer_swaps(self):
        from murmura_tpu.analysis.sanitizers import track_compiles

        net = build_network_from_config(
            _cfg(exchange={"pipeline": True}, faults=FAULTS)
        )
        net.train(rounds=2)
        with track_compiles() as tracker:
            net.train(rounds=3)
        assert tracker.total == 0


# ---------------------------------------------------------------------------
# Gang composition
# ---------------------------------------------------------------------------


class TestGangParity:
    def test_gang_member_matches_single_pipelined_run(self):
        from murmura_tpu.utils.factories import build_gang_from_config

        gang = build_gang_from_config(
            _cfg(exchange={"pipeline": True}), seeds=[3, 5]
        )
        gh = gang.train(rounds=4)
        for i, s in enumerate((3, 5)):
            raw = _raw(exchange={"pipeline": True})
            raw["experiment"]["seed"] = s
            sh = build_network_from_config(
                Config.model_validate(raw)
            ).train(rounds=4)
            assert gh[i] == sh


# ---------------------------------------------------------------------------
# phase_times critical-path accounting
# ---------------------------------------------------------------------------


class TestPhaseTimesCriticalPath:
    def _run(self, tmp_path, pipeline: bool):
        import json

        over = {"telemetry": {"enabled": True,
                              "dir": str(tmp_path / "run")}}
        if pipeline:
            over["exchange"] = {"pipeline": True}
        net = build_network_from_config(_cfg(**over))
        net.train(rounds=3)
        events = [
            json.loads(line)
            for line in (tmp_path / "run" / "events.jsonl")
            .read_text().splitlines()
        ]
        from murmura_tpu.telemetry.report import build_report

        return (
            [e for e in events if e["type"] == "phase_times"],
            build_report(tmp_path / "run"),
        )

    def test_pipelined_marks_overlap_and_report_renders_critical_path(
        self, tmp_path
    ):
        phase, report = self._run(tmp_path, pipeline=True)
        assert phase and all(e.get("overlap") == "pipelined" for e in phase)
        cp = report["time"]["critical_path"]
        assert cp["overlap"] == "pipelined"
        assert cp["rounds"] == len(phase)
        assert cp["total_s"] == pytest.approx(
            sum(e["wall_s"] for e in phase)
        )

    def test_serialized_output_pinned_unchanged(self, tmp_path):
        # The regression pin: serialized-mode phase_times events carry NO
        # overlap field and the report has NO critical_path section —
        # byte-compatible with pre-pipeline releases.
        phase, report = self._run(tmp_path, pipeline=False)
        assert phase and all("overlap" not in e for e in phase)
        assert "critical_path" not in report["time"]
        assert set(report["time"]) == {"rounds_timed", "total_s", "by_mode"}


# ---------------------------------------------------------------------------
# Durability grid cell
# ---------------------------------------------------------------------------


class TestPipelineDurability:
    def test_pipeline_grid_cell_clean(self):
        from murmura_tpu.analysis.durability import (
            DURABILITY_MODES,
            resume_cell_findings,
        )

        assert "pipeline" in DURABILITY_MODES
        assert resume_cell_findings("krum", "pipeline") == []


# ---------------------------------------------------------------------------
# MUR1200-1203
# ---------------------------------------------------------------------------


class TestMUR120x:
    def test_registry_clean(self):
        from murmura_tpu.analysis.pipeline import (
            check_pipeline_state_registry,
        )

        assert check_pipeline_state_registry() == []

    def test_unregistered_group_is_a_finding(self, monkeypatch):
        from murmura_tpu.analysis import pipeline as mod
        from murmura_tpu.durability import snapshot as dsnap

        broken = dict(dsnap.RESERVED_AGG_STATE_KEY_GROUPS)
        broken.pop("PIPELINE_STATE_KEYS")
        monkeypatch.setattr(
            dsnap, "RESERVED_AGG_STATE_KEY_GROUPS", broken
        )
        findings = mod.check_pipeline_state_registry()
        assert any("MUR900" in f.message or "snapshot" in f.message
                   for f in findings)

    def test_recompile_cell_clean(self):
        from murmura_tpu.analysis.pipeline import recompile_cell_findings

        assert recompile_cell_findings("fedavg", "dense") == []

    def test_collective_parity_cells_clean(self):
        from murmura_tpu.analysis.pipeline import collective_cell_findings

        assert collective_cell_findings("krum", "dense") == []
        assert collective_cell_findings("fedavg", "sparse") == []

    @pytest.mark.parametrize("rule", ["krum", "median", "fedavg"])
    def test_influence_cells_clean(self, rule):
        from murmura_tpu.analysis.pipeline import (
            delayed_influence_findings,
        )

        assert delayed_influence_findings(rule) == []

    def test_lagging_verdict_hole_fires(self):
        # Negative: a combine that stores the RAW broadcast (ignoring
        # the production scrub) must trip probe B — the lagging-verdict
        # containment is real, not vacuous.
        import jax.numpy as jnp

        from murmura_tpu.analysis.pipeline import (
            delayed_influence_findings,
        )

        def leaky_combine(bcast_raw, own_now, scrub, buf_bcast):
            return bcast_raw, buf_bcast  # scrub verdict dropped

        findings = delayed_influence_findings(
            "fedavg", combine_factory=leaky_combine
        )
        assert any("scrubbed broadcast taints" in f.message
                   for f in findings)

    def test_replayed_buffer_hole_fires(self):
        # Negative: a combine that serves the buffer with the scrubbed
        # sender's edges RESTORED must trip probe C on an admitting rule.
        import jax.numpy as jnp

        import murmura_tpu.analysis.pipeline as mod

        # Route the lag-scrubbed sender's buffered row into a clean
        # sender's slot, so its taint reaches the output through the
        # clean sender's (live) buffered column.
        def leaky_combine(bcast_raw, own_now, scrub, buf_bcast):
            row0 = jnp.arange(buf_bcast.shape[0])[:, None] == 0
            leaked = jnp.where(
                row0, buf_bcast[mod._SCRUBBED_PREV][None, :], buf_bcast
            )
            next_buffer = jnp.where(
                scrub[:, None] > 0, bcast_raw, own_now
            )
            return next_buffer, leaked

        findings = mod.delayed_influence_findings(
            "fedavg", combine_factory=leaky_combine
        )
        assert any("BUFFERED payload taints" in f.message
                   for f in findings)

    def test_check_pipeline_wired_into_package_check(self):
        from murmura_tpu.analysis import pipeline as mod
        from murmura_tpu.analysis.ir import _CHECK_ENTRY_POINTS

        assert "check_pipeline" in _CHECK_ENTRY_POINTS
        assert set(mod.PIPELINE_CHECK_FAMILIES) == {
            "check_pipeline_state_registry",
            "check_pipeline_recompile",
            "check_pipeline_collectives",
            "check_pipeline_influence",
        }

    def test_rules_table_names_mur120x(self):
        from murmura_tpu.analysis.lint import RULES

        for rule in ("MUR1200", "MUR1201", "MUR1202", "MUR1203"):
            assert RULES.get(rule) and RULES[rule] != "unknown"
